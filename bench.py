"""North-star benchmark: drain-plan latency at 50k pods / 5k nodes.

Generates the BASELINE.md config-3 synthetic cluster (5k nodes, 50k pods,
Zipf sizes, taints/tolerations), packs it, and times the batched TPU
first-fit solve — every candidate on-demand node's full drain feasibility
proof in one device program (the reference's serial canDrainNode nest,
rescheduler.go:334-370, over the whole cluster).

Prints ONE JSON line:
  {"metric": ..., "value": <median solve ms>, "unit": "ms",
   "vs_baseline": <target_ms / value>}    (>1.0 = under the 200 ms target)

The reference publishes no benchmarks (BASELINE.md: "None exist"); the
baseline is BASELINE.json's 200 ms-on-v5e target for this exact scale.

Usage: python bench.py [--config N] [--repeats R] [--solver jax|sharded]
       python bench.py --quality [--sweep K]     # vs the affinity-aware ILP
       python bench.py --quality-scale --config 3|4   # LP/Hall bound at scale
       python bench.py --quality-boundary        # published repair boundary
       python bench.py --chain-depth             # chain-depth-demand table
       python bench.py --replay-device-only      # constrained-replay tick,
                                                 # device-only chain protocol
       python bench.py --config 5 [--constrained]    # interruption replay
       python bench.py --scale 8                 # past-one-chip (auto-shard)
"""

from __future__ import annotations

import argparse
import json
import math
import os
import subprocess
import sys
import threading
import time
import traceback

import numpy as np


TARGET_MS = 200.0

# --- backend acquisition + failure containment ---------------------------
#
# The TPU on this machine is reached through a tunnel whose backend can be
# slow or flat-out unavailable at process start (round 1's driver run died
# inside the first device_put with "Unable to initialize backend 'axon'",
# and a bare jax.devices() has been observed to hang for minutes). The
# bench must NEVER leave the driver with a stack dump and no JSON line, so:
#
#  - backend readiness is probed in a SUBPROCESS (killable on hang, unlike
#    an in-process jax init) with bounded retry/backoff;
#  - a watchdog hard-exits with a diagnostic JSON line if the whole bench
#    overruns its budget;
#  - main() is wrapped so any exception still emits the one-line JSON with
#    an "error" field — the driver's `parsed` is never null.

_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "d = jax.devices()[0];"
    "jnp.ones((8, 8)).sum().block_until_ready();"
    "print(d.platform + '/' + d.device_kind)"
)

_emit_once = threading.Lock()


def drop_non_finite(obj):
    """Strictly-valid JSON guard: ``json.dumps`` renders float NaN/inf as
    the non-standard ``NaN``/``Infinity`` tokens, which strict parsers
    reject. Dict entries carrying them are OMITTED (the driver sees the
    field absent, not a junk value); list elements become null."""
    if isinstance(obj, dict):
        return {
            k: drop_non_finite(v)
            for k, v in obj.items()
            if not (isinstance(v, float) and not math.isfinite(v))
        }
    if isinstance(obj, (list, tuple)):
        return [
            None
            if isinstance(v, float) and not math.isfinite(v)
            else drop_non_finite(v)
            for v in obj
        ]
    return obj


# backend attestation rides every emitted JSON line unless the watchdog
# is firing (a hung backend must not block the diagnostic line's exit)
_attest_enabled = [True]


def backend_attestation() -> dict:
    """Which backend actually solved — self-labeled in every BENCH/
    MULTICHIP JSON line so a CPU-fallback round reads as the artifact it
    is instead of tribal knowledge (the BENCH_r01/r05 confusion: two
    rounds of regressions that were really the tunneled chip's sick
    phases). Reports the live device platform plus the degradation
    counters that say whether any solve in the run fell off the device:
    the service watchdog's sick gauge and the planner fallback totals.
    Never imports jax itself — a bench that never initialized a backend
    attests exactly that."""
    out: dict = {}
    jax = sys.modules.get("jax")
    if jax is None:
        out["solve_backend"] = "jax-not-loaded"
    else:
        try:
            d = jax.devices()[0]
            out["solve_backend"] = f"{d.platform}/{d.device_kind}"
            out["n_devices"] = len(jax.devices())
        except Exception as err:  # noqa: BLE001 — attest the failure
            out["solve_backend"] = f"unavailable: {str(err)[-80:]}"
    try:
        from k8s_spot_rescheduler_tpu.metrics import registry as metrics

        svc = metrics.service_snapshot()
        rob = metrics.robustness_snapshot()
        out["device_sick"] = bool(svc.get("device_sick"))
        out["planner_fallbacks"] = int(rob.get("planner_fallback", 0))
        out["remote_planner_fallbacks"] = int(
            svc.get("remote_planner_fallback", 0)
        )
    except Exception as err:  # noqa: BLE001 — counters are best-effort
        out["counters_error"] = str(err)[-80:]
    return out


def emit(obj: dict) -> None:
    """Print THE one JSON line (at most once per process). The lock is
    acquired and never released: whichever thread (main or watchdog) wins
    the non-blocking acquire is the only one that prints. Every line
    carries ``backend_attestation`` (unless the watchdog is firing) so
    the solve backend is recorded in the result itself. The attestation
    is computed BEFORE the lock: if jax.devices() wedges here, the
    watchdog's own emit still wins the lock and exits with its
    diagnostic line."""
    if _attest_enabled[0] and "backend_attestation" not in obj:
        try:
            obj = dict(obj)
            obj["backend_attestation"] = backend_attestation()
        except Exception:  # noqa: BLE001 — the line must still print
            pass
    if not _emit_once.acquire(blocking=False):
        return
    print(json.dumps(drop_non_finite(obj)), flush=True)


def emit_error(metric: str, unit: str, error: str) -> None:
    emit(
        {
            "metric": metric,
            "value": None,
            "unit": unit,
            "vs_baseline": None,
            "error": error[-600:],
        }
    )


def load_twin_calibration(path: str) -> dict:
    """Collect per-bucket measured solve costs from a bench JSON-lines
    file (``--carry-wall`` rows carry a ``twin_calibration`` table:
    bucket key -> {"solve_s": measured seconds}). Later lines win on
    key collisions; a missing or unparsable file is an error — a
    calibrated fleet run must not silently fall back to the synthetic
    cost line."""
    table: dict = {}
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue  # bench files interleave logs with JSON rows
            cal = row.get("twin_calibration")
            if isinstance(cal, dict):
                for key, cost in cal.items():
                    if isinstance(cost, dict) and "solve_s" in cost:
                        table[str(key)] = {
                            "solve_s": float(cost["solve_s"])
                        }
    if not table:
        raise ValueError(
            f"no twin_calibration tables found in {path!r} "
            f"(expected --carry-wall JSON rows)"
        )
    return table


def start_watchdog(seconds: float, metric: str, unit: str) -> threading.Timer:
    """Hard-exit with a diagnostic JSON line if the bench overruns —
    a hung device fetch cannot be interrupted any other way."""

    def fire() -> None:
        # a wedged backend must not block the diagnostic line: skip the
        # attestation's jax.devices() on this path
        _attest_enabled[0] = False
        emit_error(metric, unit, f"watchdog: bench exceeded {seconds:.0f}s budget")
        sys.stdout.flush()
        os._exit(3)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


# Process-lifetime probe verdict: once an acquisition concludes (either
# way), later acquire_backend(cache=True) calls return it instantly —
# one bench invocation never pays for more than one full probe round
# (BENCH_r05's tail burned 4 × 90 s hung probes before every fallback).
_probe_verdict: dict = {}


def acquire_backend(
    budget_s: float = 300.0,
    probe_timeout_s: float = 30.0,
    max_attempts: int = 4,
    cache: bool = False,
) -> tuple:
    """Probe jax backend readiness in killable subprocesses with backoff.

    Returns (platform_desc or None, attempts, last_error). Success means a
    fresh process completed device discovery AND a tiny computation within
    the timeout, so the main process's own init is very likely to succeed
    promptly. Total probe spend is capped by BOTH ``budget_s`` and
    ``max_attempts``; with ``cache`` the verdict is remembered for the
    rest of the process."""
    if cache and "verdict" in _probe_verdict:
        return _probe_verdict["verdict"]

    def conclude(result):
        if cache:
            _probe_verdict["verdict"] = result
        return result

    deadline = time.monotonic() + budget_s
    attempt, last_err = 0, "no probe attempted"
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0 or attempt >= max_attempts:
            return conclude((None, attempt, last_err))
        attempt += 1
        this_timeout = min(probe_timeout_s, max(10.0, remaining))
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True,
                text=True,
                timeout=this_timeout,
            )
            if r.returncode == 0 and r.stdout.strip():
                return conclude(
                    (r.stdout.strip().splitlines()[-1], attempt, None)
                )
            last_err = (r.stderr or r.stdout).strip()[-400:] or (
                "probe rc=%d" % r.returncode
            )
        except subprocess.TimeoutExpired:
            last_err = f"backend probe hung >{this_timeout:.0f}s (killed)"
        print(
            f"backend probe attempt {attempt} failed: {last_err.splitlines()[-1] if last_err else '?'}",
            file=sys.stderr,
        )
        if time.monotonic() >= deadline or attempt >= max_attempts:
            return conclude((None, attempt, last_err))
        time.sleep(min(15.0, 2.0 * attempt))


def _scaled_spec(base, scale: float):
    """Multiply a config's node/pod counts by ``scale`` (1.0 = unchanged);
    shared by the latency and quality-scale benchmarks."""
    if scale == 1.0:
        return base
    import dataclasses

    return dataclasses.replace(
        base,
        name=f"{base.name}-x{scale:g}",
        n_on_demand=int(base.n_on_demand * scale),
        n_spot=int(base.n_spot * scale),
        n_pods=int(base.n_pods * scale),
    )


def build_problem(config_id: int, seed: int = 0, spec=None, pack_repeats=1):
    """Generate the synthetic cluster and pack it via the production
    observe path: the incrementally-maintained columnar mirror
    (models/columnar.py). The returned pack seconds are the steady-state
    per-tick observe+pack cost (the mirror is already attached, as it is
    in the control loop) — the MEDIAN over ``pack_repeats`` packs, so
    the parsed ``pack_ms`` isn't a one-shot cold-cache sample. Returns
    (packed, meta, pack_seconds, client, store, pdbs) — the live
    cluster rides along so the incremental-tick measurement can churn
    it between ticks."""
    from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS, generate_cluster
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    spec = spec or CONFIGS[config_id]
    cfg = ReschedulerConfig(resources=spec.resources)
    t0 = time.perf_counter()
    client = generate_cluster(spec, seed)
    t1 = time.perf_counter()
    store = client.columnar_store(
        cfg.resources,
        on_demand_label=cfg.on_demand_node_label,
        spot_label=cfg.spot_node_label,
    )
    pdbs = client.list_pdbs()
    t2 = time.perf_counter()
    pack_times = []
    for _ in range(max(1, pack_repeats)):
        t_p = time.perf_counter()
        packed, meta = store.pack(
            pdbs, priority_threshold=cfg.priority_threshold
        )
        pack_times.append(time.perf_counter() - t_p)
    pack_s = float(np.median(pack_times))
    print(
        f"generate {t1-t0:.1f}s  ingest(once) {t2-t1:.2f}s  "
        f"columnar observe+pack {pack_s*1e3:.1f} ms "
        f"(median of {len(pack_times)})  "
        f"shapes C={packed.slot_req.shape[0]} K={packed.slot_req.shape[1]} "
        f"S={packed.spot_free.shape[0]} R={packed.slot_req.shape[2]}",
        file=sys.stderr,
    )
    return packed, meta, pack_s, client, store, pdbs


def run_incremental_ticks(
    client,
    store,
    pdbs,
    spec,
    solver: str,
    n_ticks: int,
    churn: int = 5,
    staged_chunk_lanes=None,
):
    """The production per-tick pipeline, end to end: host pack diffed
    against the previous tick, churn-proportional delta shipped into the
    device-resident cache (donated scatter), staged early-exit solve, one
    tiny selection fetch. Returns (per-tick ms list, per-tick PlanReport
    list, per-tick mirror-sync ms list); tick 0 is the cold full pack +
    compile and is excluded from steady-state medians by callers. The
    sync list times the churn's application to the columnar mirror —
    the delta-shaped half of observe (the pack half is measured by
    ``build_problem``), so BENCH_*.json can show the observe split."""
    import dataclasses

    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    from k8s_spot_rescheduler_tpu.utils import tracing

    cfg = ReschedulerConfig(
        solver=solver if solver in ("jax", "pallas") else "jax",
        resources=spec.resources,
    )
    if staged_chunk_lanes is not None:
        cfg = dataclasses.replace(cfg, staged_chunk_lanes=staged_chunk_lanes)
    planner = SolverPlanner(cfg)
    uids = iter(list(client.pods))
    tick_ms, reports, sync_ms, traces = [], [], [], []
    for i in range(n_ticks):
        if i:
            # light churn, the steady-state regime: a few evictions'
            # worth of pod removals between ticks — applied to the
            # incrementally-maintained mirror (O(churn), not O(cluster))
            t_s = time.perf_counter()
            for _ in range(churn):
                uid = next(uids, None)
                if uid is not None:
                    client._remove_pod(uid)
            sync_ms.append((time.perf_counter() - t_s) * 1e3)
        t0 = time.perf_counter()
        # each tick under its own trace, exactly as the control loop
        # runs it — the smoke reads the span breakdown off these
        with tracing.tick_trace() as trace:
            reports.append(planner.plan(store, pdbs))
        traces.append(trace)
        tick_ms.append((time.perf_counter() - t0) * 1e3)
    return tick_ms, reports, sync_ms, traces


def run_quality(seed: int, sweep: int = 1, solver: str = "numpy") -> int:
    """Nodes-freed quality vs the ILP oracle across the quality configs
    (io/synthetic.QUALITY_CONFIGS): the balanced regime plus the
    adversarial high-utilization pool configs where one-pass greedy
    demonstrably loses drains and the local-search repair phase
    (solver/repair.py) recovers them. Per config, both the reference-
    faithful pure first-fit planner and the shipped solver (first-fit ∪
    best-fit ∪ repair) drain to exhaustion; the reported metric is the
    WORST shipped ratio across configs × seeds [seed, seed+sweep)."""
    from k8s_spot_rescheduler_tpu.bench.quality import (
        drain_to_exhaustion,
        ilp_max_drains,
        pack_quality,
    )
    from k8s_spot_rescheduler_tpu.io.synthetic import (
        QUALITY_CONFIGS,
        generate_quality_cluster,
    )
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    rows, worst = [], 1.0
    for name, spec in QUALITY_CONFIGS.items():
        for s in range(seed, seed + max(1, sweep)):
            packed = pack_quality(spec, s)
            ilp = ilp_max_drains(packed)
            achieved = {}
            for variant, cfg in (
                ("ffd", ReschedulerConfig(
                    solver=solver, fallback_best_fit=False, repair_rounds=0,
                    resources=spec.resources)),
                ("shipped", ReschedulerConfig(
                    solver=solver, resources=spec.resources)),
            ):
                client = generate_quality_cluster(
                    spec, s, reschedule_evicted=True
                )
                achieved[variant] = drain_to_exhaustion(client, cfg)
            r_ffd = achieved["ffd"] / ilp if ilp else 1.0
            r_full = achieved["shipped"] / ilp if ilp else 1.0
            worst = min(worst, r_full)
            rows.append((name, s, ilp, achieved["ffd"], r_ffd,
                         achieved["shipped"], r_full))
            print(
                f"quality {name} seed {s}: ILP {ilp}  "
                f"pure-FFD {achieved['ffd']} ({r_ffd:.3f})  "
                f"shipped {achieved['shipped']} ({r_full:.3f})",
                file=sys.stderr,
            )
    print(
        "quality table (config, seed, ilp, ffd, ffd_ratio, shipped, "
        f"shipped_ratio): {rows}",
        file=sys.stderr,
    )
    print(f"worst shipped ratio: {worst:.4f}", file=sys.stderr)
    emit(
        {
            "metric": "nodes_freed_vs_ilp_oracle_ratio",
            "value": round(worst, 4),
            "unit": "ratio",
            "vs_baseline": round(worst / 0.95, 4),
        }
    )
    return 0


def run_chain_depth(seed: int, sweep: int = 1, n_events: int = 300) -> int:
    """Chain-depth-demand table (bench/chain_depth.py): for every tick
    of every organic run — the quality configs drained to exhaustion,
    plus the constrained interruption replay — classify each drainable
    candidate lane by the MINIMUM mechanism that proves it (greedy /
    depth-1 repair / depth-2 chain / deeper-than-shipped / infeasible).
    The chain3 BOUNDARY config runs as the positive control: its lanes
    must register 'deeper', proving the instrument detects depth-3
    demand. The emitted metric is the ORGANIC 'deeper' count — zero
    means the published chain3 boundary is evidence-backed."""
    # host-side offline analysis: hundreds of tiny solves per run, each
    # fetched — on the tunneled TPU every fetch pays the ~65 ms RTT, so
    # the analyzer pins itself to CPU (same policy as the test suite)
    import jax

    jax.config.update("jax_platforms", "cpu")

    from k8s_spot_rescheduler_tpu.bench.chain_depth import (
        analyze_quality_runs,
        analyze_replay,
    )
    from k8s_spot_rescheduler_tpu.io.synthetic import BOUNDARY_CONFIGS

    seeds = range(seed, seed + max(1, sweep))
    organic = analyze_quality_runs(seeds=seeds)
    organic["constrained-replay"] = analyze_replay(
        n_events=n_events, seed=seed, constrained=True
    )
    control = analyze_quality_runs(seeds=seeds, configs=BOUNDARY_CONFIGS)
    keys = ("greedy", "depth1", "depth2", "deeper", "infeasible",
            "ilp-failed")
    print("chain-depth demand (lane-ticks by minimal proving mechanism):",
          file=sys.stderr)
    for name, counts in {**organic, **{
        f"[control] {k}": v for k, v in control.items()
    }}.items():
        row = "  ".join(f"{k}={counts.get(k, 0)}" for k in keys)
        print(f"  {name}: {row}", file=sys.stderr)
    deeper_organic = sum(c.get("deeper", 0) for c in organic.values())
    deeper_control = sum(c.get("deeper", 0) for c in control.values())
    out = {
        "metric": "chain_depth_demand_deeper_lanes_organic",
        "value": int(deeper_organic),
        "unit": "count",
        "vs_baseline": 1.0 if deeper_organic == 0 else 0.0,
        "control_deeper": int(deeper_control),
    }
    if deeper_control == 0:
        # a dead positive control voids the organic zero — say so IN
        # the metric line, not just on stderr
        print("WARNING: chain3 control registered no depth-3 demand — "
              "the instrument may be broken", file=sys.stderr)
        out["vs_baseline"] = 0.0
        out["error"] = "positive control (chain3) registered no depth-3 " \
                       "demand; instrument suspect"
    emit(out)
    return 0 if deeper_control else 1


def run_replay_device_only(args) -> int:
    """Device-only cost of a CONSTRAINED-REPLAY tick (VERDICT r4 #8).

    The constrained replay's p99 (docs/RESULTS.md) crosses the 200 ms
    target on this host, attributed to two tunnel RTTs — but the claim
    "a locally attached chip pays ~ms" was extrapolated from config-3/4
    shapes, not measured on the ticks that actually fire best-fit +
    repair. This mode measures it: replay the constrained stream with
    the HOST oracle stack (pure numpy — jax stays uninitialized so the
    real backend can still be acquired afterwards), harvest the tick
    shape with the most greedy-unproven valid lanes (the regime where
    the union program's best-fit and repair passes genuinely execute),
    then run the pinned chain protocol (bench/protocol.py) on the real
    device with the SHIPPED fused union program."""
    from k8s_spot_rescheduler_tpu.bench.replay import run_replay
    from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster

    cache = getattr(args, "harvest_cache", "")
    if cache and not cache.endswith(".npz"):
        # np.savez appends .npz to suffix-less paths; normalize so the
        # reuse check looks at the file that was actually written
        cache += ".npz"
    if cache and os.path.exists(cache):
        data = np.load(cache)
        packed = PackedCluster(**{f: data[f] for f in PackedCluster._fields})
        harvest = {
            "packed": packed,
            "unproven": int(data["unproven"]),
            "bf_only": bool(data["bf_only"]),
        }
        stats = {"replan_ms_p50": float(data["replay_p50_ms"]),
                 "replan_ms_p99": float(data["replay_p99_ms"])}
        print(f"reusing harvested tick from {cache}", file=sys.stderr)
        return _replay_device_protocol(args, harvest, stats)

    host_cfg = ReschedulerConfig(solver="numpy")
    harvest = {"packed": None, "unproven": -1, "bf_only": True,
               "last_id": None}

    def tap(packed):
        if packed is None or id(packed) == harvest["last_id"]:
            return  # skipped ticks repeat the previous object
        harvest["last_id"] = id(packed)
        ff = plan_oracle(packed)
        valid = np.asarray(packed.cand_valid)
        miss_ff = valid & ~np.asarray(ff.feasible)
        if not miss_ff.any():
            return  # greedy proves everything: neither pass fires
        bf = plan_oracle(packed, best_fit=True)
        miss_greedy = miss_ff & ~np.asarray(bf.feasible)
        n = int(miss_greedy.sum())
        bf_only = n == 0
        # prefer repair-firing ticks over bf-only ticks, then max lanes
        better = (
            (harvest["bf_only"] and not bf_only)
            or (harvest["bf_only"] == bf_only
                and n + int(miss_ff.sum()) > harvest["unproven"])
        )
        if harvest["packed"] is None or better:
            harvest.update(
                packed=packed, unproven=n + int(miss_ff.sum()),
                bf_only=bf_only,
            )

    stats = run_replay(
        host_cfg, n_events=args.events, seed=args.seed,
        constrained=True, on_packed=tap,
    )
    packed = harvest["packed"]
    if packed is None:
        emit({
            "metric": "replay_constrained_device_only_ms",
            "value": None, "unit": "ms", "vs_baseline": None,
            "error": "no replay tick left a valid lane greedy-unproven "
                     "(best-fit/repair never fired this seed)",
        })
        return 1
    if cache:
        np.savez_compressed(
            cache,
            unproven=harvest["unproven"],
            bf_only=harvest["bf_only"],
            replay_p50_ms=stats["replan_ms_p50"],
            replay_p99_ms=stats["replan_ms_p99"],
            **{f: np.asarray(getattr(packed, f))
               for f in type(packed)._fields},
        )
        print(f"harvested tick cached at {cache}", file=sys.stderr)
    return _replay_device_protocol(args, harvest, stats)


def _replay_device_protocol(args, harvest, stats) -> int:
    """The device half of --replay-device-only (split out so a cached
    harvest can jump straight here)."""
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    packed = harvest["packed"]
    C, K, R = packed.slot_req.shape
    note = (
        "best-fit fires, repair gated off (greedy union proves all)"
        if harvest["bf_only"]
        else "best-fit AND repair fire"
    )
    print(
        f"harvested constrained-replay tick: C={C} K={K} "
        f"S={packed.spot_free.shape[0]} R={R}; "
        f"{harvest['unproven']} greedy-unproven valid lanes ({note}); "
        f"replay p50 {stats['replan_ms_p50']:.1f} ms "
        f"p99 {stats['replan_ms_p99']:.1f} ms on this host",
        file=sys.stderr,
    )

    platform, attempts, backend_note = acquire_backend(
        budget_s=args.backend_budget,
        probe_timeout_s=args.probe_timeout,
        cache=True,
    )
    if backend_note:
        # a device-only metric measured on the CPU fallback would be a
        # misleading headline (and 50 chained union solves on host at
        # this shape would blow the watchdog anyway) — report the
        # failure honestly instead
        emit({
            "metric": "replay_constrained_device_only_ms",
            "value": None, "unit": "ms", "vs_baseline": None,
            "error": backend_note,
        })
        return 1
    import jax
    import jax.numpy as jnp

    from k8s_spot_rescheduler_tpu.bench import protocol as bench_protocol
    from k8s_spot_rescheduler_tpu.solver.fallback import with_repair
    from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd
    from k8s_spot_rescheduler_tpu.solver.select import (
        decode_selection,
        make_fused_planner,
    )

    shipped = ReschedulerConfig()
    fused = make_fused_planner(with_repair(plan_ffd, shipped.repair_rounds))
    device_packed = jax.tree.map(jnp.asarray, packed)
    t0 = time.perf_counter()
    sel = decode_selection(fused(device_packed))
    compile_s = time.perf_counter() - t0
    rec = bench_protocol.run_protocol(fused, device_packed)
    device_ms = rec["device_only_ms"]
    print(
        f"compile {compile_s:.1f}s  device-only "
        f"{device_ms:.2f} ms/solve on the harvested tick shape "
        f"({note}); feasible {sel.n_feasible} lanes  "
        f"device {jax.devices()[0].device_kind}",
        file=sys.stderr,
    )
    out = {
        "metric": "replay_constrained_device_only_ms",
        "value": round(device_ms, 3),
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / device_ms, 1) if device_ms else None,
        "device": jax.devices()[0].device_kind,
        "device_only": rec,
        "tick_shape": {"C": int(C), "K": int(K),
                       "S": int(packed.spot_free.shape[0]), "R": int(R)},
        "note": note,
        "replay_p50_ms_host": round(stats["replan_ms_p50"], 1),
        "replay_p99_ms_host": round(stats["replan_ms_p99"], 1),
    }
    if backend_note:
        out["backend_note"] = backend_note
    emit(out)
    return 0


def run_quality_boundary(seed: int, sweep: int = 1) -> int:
    """The PUBLISHED repair boundary (docs/RESULTS.md): configs where
    shipped < ILP by construction — the three-link chain that needs two
    chained ejections, beyond the depth-2 search (which closed the old
    two-pod interlock boundary). Kept out of the headline worst-ratio
    metric; this mode documents the number and watches it for drift."""
    from k8s_spot_rescheduler_tpu.bench.quality import (
        drain_to_exhaustion,
        ilp_max_drains,
        pack_quality,
    )
    from k8s_spot_rescheduler_tpu.io.synthetic import (
        BOUNDARY_CONFIGS,
        generate_quality_cluster,
    )
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    worst = 1.0
    for name, spec in BOUNDARY_CONFIGS.items():
        for s in range(seed, seed + max(1, sweep)):
            packed = pack_quality(spec, s)
            ilp = ilp_max_drains(packed)
            client = generate_quality_cluster(spec, s, reschedule_evicted=True)
            shipped = drain_to_exhaustion(
                client, ReschedulerConfig(solver="numpy",
                                          resources=spec.resources)
            )
            ratio = shipped / ilp if ilp else 1.0
            worst = min(worst, ratio)
            print(
                f"boundary {name} seed {s}: ILP {ilp}  shipped {shipped} "
                f"({ratio:.3f})",
                file=sys.stderr,
            )
    emit(
        {
            "metric": "repair_boundary_chain3_ratio",
            "value": round(worst, 4),
            "unit": "ratio",
            "vs_baseline": None,
            "note": "published depth-2 chained-repair boundary "
                    "(three-link chains); see docs/RESULTS.md",
        }
    )
    return 0


def run_quality_scale(args, metric: str, unit: str, backend_note) -> int:
    """Quality at north-star scale, where the ILP is intractable: the
    LP-relaxation/Hall upper bound (bench/quality.lp_upper_bound) vs the
    controller draining to exhaustion in multi-drain mode. Achieved/bound
    UNDERSTATES true quality (the bound relaxes per-node bins and
    anti-affinity), so a high ratio here is strong evidence."""
    from k8s_spot_rescheduler_tpu.bench.quality import (
        drain_to_exhaustion,
        lp_upper_bound,
    )
    from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS, generate_cluster
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    # Exhaustion costs one solve PER DRAIN (the controller re-plans
    # between drains to avoid spot overcommit) — ~1k drains at full
    # config-3 scale. On the CPU fallback that cannot fit any sane
    # budget, _dispatch scales the problem down; the bound and the
    # achieved count then describe the SAME (scaled) cluster.
    spec = _scaled_spec(CONFIGS[args.config], args.scale)
    packed = build_problem(args.config, args.seed, spec=spec)[0]
    t0 = time.perf_counter()
    bound = lp_upper_bound(packed)
    t_bound = time.perf_counter() - t0
    if bound is None:
        emit_error(metric, unit, "lp_upper_bound failed (linprog unsuccessful)")
        return 1
    print(
        f"LP/Hall upper bound ({spec.name}, seed {args.seed}): "
        f"{bound} drainable of {int(np.asarray(packed.cand_valid).sum())} "
        f"candidates ({t_bound:.1f}s)",
        file=sys.stderr,
    )
    horizon = max(0, int(args.schedule_horizon))
    cfg = ReschedulerConfig(
        solver=args.solver,
        resources=spec.resources,
        max_drains_per_tick=256,
        # device-resident drain schedules: fetches drop from O(drains)
        # to O(drains / horizon) — the sweep's wall clock was tunnel-RTT
        # x drains before this (docs/RESULTS.md consolidation table)
        plan_schedule_enabled=horizon > 0,
        schedule_horizon=horizon or 32,
    )
    client = generate_cluster(spec, args.seed, reschedule_evicted=True)
    stats: dict = {}
    t0 = time.perf_counter()
    achieved = drain_to_exhaustion(
        client, cfg, max_ticks=200, planner_stats=stats
    )
    t_drain = time.perf_counter() - t0
    ratio = achieved / bound if bound else 1.0
    fetches = int(stats.get("fetches_total", -1))
    lens = stats.get("schedule_lens", [])
    print(
        f"achieved {achieved} drains in {t_drain:.0f}s "
        f"({fetches} planner fetches"
        + (f", {len(lens)} schedule cuts" if horizon else "")
        + f"); achieved/bound {ratio:.3f} (bound relaxes bins+affinity: "
        f"true oracle ratio is >= this)",
        file=sys.stderr,
    )
    out = {
        "metric": metric,
        "value": round(ratio, 4),
        "unit": unit,
        "vs_baseline": round(ratio / 0.95, 4),
        "bound": bound,
        "achieved": achieved,
        "scale": args.scale,
        # the O(1)-fetch artifact: planner fetches for the WHOLE sweep,
        # schedule length distribution, and the sweep wall clock
        "fetches_total": fetches,
        "schedule_horizon": horizon,
        "sched_wall_s": round(t_drain, 2),
    }
    if lens:
        out["schedule_len_p50"] = float(np.percentile(lens, 50))
        out["schedule_len_p95"] = float(np.percentile(lens, 95))
    inv = metrics_schedule_invalidations()
    if inv is not None:
        out["schedule_invalidated"] = inv
    if horizon > 0 and fetches >= 0:
        fetch_bound = math.ceil(max(achieved, 1) / cfg.schedule_horizon) + 2
        # churn-free synthetic sweep: every invalidation would add a
        # fetch, so the bound holds exactly when the claim holds
        fetch_bound += int(inv or 0)
        out["fetch_bound"] = fetch_bound
        if fetches > fetch_bound:
            out["error"] = (
                f"fetches_total {fetches} > ceil(drains/horizon)+2 = "
                f"{fetch_bound}: the O(1)-fetch claim failed"
            )
            emit(out)
            return 1
    if backend_note:
        out["error"] = backend_note
    emit(out)
    return 0


def metrics_schedule_invalidations():
    """Schedule invalidations so far this process (None if metrics are
    unavailable) — quality-scale reports them beside the fetch bound."""
    try:
        from k8s_spot_rescheduler_tpu.metrics import registry as metrics

        return int(metrics.robustness_snapshot()["schedule_invalidated"])
    except Exception:  # noqa: BLE001 — bench-side best effort
        return None


def run_replay_bench(
    seed: int, n_events: int, note=None, constrained: bool = False
) -> int:
    from k8s_spot_rescheduler_tpu.bench.replay import run_replay
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    stats = run_replay(
        ReschedulerConfig(), n_events=n_events, seed=seed,
        constrained=constrained,
    )
    print(f"replay: {stats}", file=sys.stderr)
    out = {
        "metric": (
            "replay_constrained_replan_ms_p50_1k_events"
            if constrained
            else "replay_replan_ms_p50_1k_events"
        ),
        "value": round(stats["replan_ms_p50"], 3),
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / max(stats["replan_ms_p50"], 1e-9), 3),
    }
    if constrained:
        out["stranded_by_drain"] = stats["stranded_by_drain"]
        out["drained_nodes"] = stats["drained_nodes"]
        out["unplaceable_pods_gauge"] = stats["unplaceable_pods_gauge"]
    if note:
        out["error"] = note
    emit(out)
    return 0


def _span_ms_median(traces, name: str) -> float:
    """Median per-tick total duration of spans called ``name`` across
    the given traces (0.0 when the span never fired — e.g. queue/wire
    spans on the in-process path). The smoke/serve-smoke JSON lines
    report these so BENCH_r0*.json tracks where the tick's milliseconds
    actually go (queue vs solve vs wire)."""
    totals = []
    for trace in traces:
        if trace is None:
            continue
        spans = trace.find(name)
        totals.append(sum(s.dur_ms for s in spans) if spans else 0.0)
    return float(np.median(totals)) if totals else 0.0


def run_scale_smoke(args, metric: str, unit: str) -> int:
    """Shape-only 20x proof on CPU (make scale-smoke): the dispatch
    decision, the honest estimator breakdown, and a jaxpr trace at the
    1M-pod / 100k-node shapes (hot_programs.MAX_SHAPES) — NO device
    solve, no allocation beyond the trace.

    Fails unless, at the v5e default budget over an 8-device fleet:
    1. the dispatch ladder (solver/memory.pick_tier — the same decision
       the production planner runs) lands on a tier with repair LIVE
       (``repair_unavailable`` 0, ``repair_chunks`` > 0) for BOTH the
       fully-narrow carry layout and the conservative config-3 guarded
       layout (f32 ``used`` — MiB memory sums overflow narrow ints —
       int8 count, uint8 aff);
    2. the per-device estimate fits the budget and the carries
       component dominates it the way the layout promises;
    3. the carry-streamed union TRACES at the per-device lane-block
       shapes (jax.make_jaxpr over ShapeDtypeStructs — the program XLA
       would compile; shape-only, cost independent of problem size).
    """
    t0 = time.perf_counter()
    from k8s_spot_rescheduler_tpu.hot_programs import (
        MAX_SHAPES,
        ProbeShapes,
        packed_struct,
    )
    from k8s_spot_rescheduler_tpu.solver import memory as solver_memory
    from k8s_spot_rescheduler_tpu.solver.carry import (
        CarryLayout,
        NARROW_LAYOUT,
        plane_bytes,
    )

    s = MAX_SHAPES
    budget = int(
        solver_memory.DEFAULT_HBM_BYTES * solver_memory.BUDGET_FRACTION
    )
    n_devices = 8  # the v5e-8 fleet the 20x deployment targets
    guarded = CarryLayout(used="float32", count="int8", aff="uint8")
    tiers = {}
    for name, layout in (("narrow", NARROW_LAYOUT), ("guarded", guarded)):
        tier = solver_memory.pick_tier(
            s.C, s.K, s.S, s.R, s.W, s.A,
            n_devices=n_devices,
            budget_bytes=budget,
            wants_repair=True,
            carry_plane_bytes=plane_bytes(layout, s.R, s.A),
        )
        tiers[name] = tier
        print(
            f"scale-smoke dispatch [{name}]: {tier.kind} "
            f"repair_chunks={tier.repair_chunks} "
            f"carry_chunks={tier.carry_chunks} "
            f"est {tier.est_bytes / 1e9:.2f} GB/device "
            f"(carries {tier.carry_bytes / 1e9:.2f} GB) vs budget "
            f"{budget / 1e9:.2f} GB",
            file=sys.stderr,
        )
        if tier.repair_unavailable or tier.repair_chunks <= 0:
            emit_error(
                metric, unit,
                f"20x dispatch [{name}] lost the repair phase: {tier}",
            )
            return 1
        if tier.est_bytes > budget:
            emit_error(
                metric, unit,
                f"20x dispatch [{name}] exceeds the per-device budget: "
                f"{tier.est_bytes} > {budget}",
            )
            return 1
    tier = tiers["guarded"]  # what --scale 20 on config 3 dispatches
    bd = solver_memory.estimate_union_hbm_breakdown(
        tier.lane_block, s.K, s.S, s.R, s.W, s.A,
        repair_spot_chunks=tier.repair_chunks,
        carry_chunks=tier.carry_chunks,
        carry_plane_bytes=plane_bytes(guarded, s.R, s.A),
    )
    if sum(bd.values()) != tier.est_bytes or bd["carries"] != tier.carry_bytes:
        emit_error(
            metric, unit,
            f"estimator breakdown disagrees with the tier decision: "
            f"{bd} vs {tier}",
        )
        return 1
    if bd["carries"] <= max(v for k, v in bd.items() if k != "carries"):
        emit_error(
            metric, unit,
            f"carries no longer dominate the 20x estimate — the layout "
            f"model drifted: {bd}",
        )
        return 1

    # 3. shape-only traces of the per-device lane-block programs — each
    # dispatched (layout, chunk-count) pair traces AS DISPATCHED, so a
    # regression specific to either layout (e.g. an f32-`used` dtype
    # bug the narrow layout would mask) reddens the gate
    import jax

    from k8s_spot_rescheduler_tpu.solver.fallback import with_repair_streamed

    trace_ms = 0.0
    trace_eqns = {}
    for name, lay in (("narrow", NARROW_LAYOUT), ("guarded", guarded)):
        t = tiers[name]
        lane_shapes = ProbeShapes(
            C=t.lane_block, K=s.K, S=s.S, R=s.R, W=s.W, A=s.A
        )
        t_trace = time.perf_counter()
        union = with_repair_streamed(8, t.carry_chunks, lay)
        closed = jax.make_jaxpr(union)(packed_struct(lane_shapes))
        one_ms = (time.perf_counter() - t_trace) * 1e3
        trace_ms += one_ms
        n_eqns = trace_eqns[name] = len(closed.jaxpr.eqns)
        if n_eqns <= 0:
            emit_error(
                metric, unit,
                f"20x lane-block trace [{name}] produced no jaxpr",
            )
            return 1
        print(
            f"scale-smoke trace [{name}]: lane block C={t.lane_block} "
            f"S={s.S} carry_chunks={t.carry_chunks} layout "
            f"{lay.used}/{lay.count}/{lay.aff} -> {n_eqns} top-level "
            f"eqns in {one_ms:.0f} ms",
            file=sys.stderr,
        )
    emit({
        "metric": metric,
        "value": round(time.perf_counter() - t0, 3),
        "unit": unit,
        "carry_chunks": int(tier.carry_chunks),
        "carry_bytes": int(tier.carry_bytes),
        "repair_chunks": int(tier.repair_chunks),
        "repair_unavailable": 0,
        "narrow_carry_chunks": int(tiers["narrow"].carry_chunks),
        "lane_block": int(tier.lane_block),
        "est_device_gb": round(tier.est_bytes / 1e9, 3),
        "budget_gb": round(budget / 1e9, 3),
        "breakdown_mb": {
            k: round(v / 1e6, 1) for k, v in sorted(bd.items())
        },
        "trace_ms": round(trace_ms, 1),
        "trace_eqns": trace_eqns,  # per dispatched layout
    })
    return 0


def pallas_parity_smoke(seed: int = 0, chunk_counts=(2, 3, 5)) -> dict:
    """The Pallas stream-kernel parity core (``make pallas-smoke``):
    the fused elect-then-commit kernel (interpret mode on CPU — the
    same kernel compiles for TPU) must be bit-identical to the XLA
    ``_stream_bf_step`` carry-streamed scan at every chunk count AND to
    the host numpy oracle, on a real observe-path pack plus spot-axis
    permutations of it (one compile per chunk count — shapes are
    shared, so the whole run stays inside the <30 s watchdog). The
    first-fit kernel rides along against the same oracle."""
    import dataclasses

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS, generate_cluster
    from k8s_spot_rescheduler_tpu.ops.pallas_ffd import (
        plan_ffd_pallas,
        plan_stream_bf_pallas,
    )
    from k8s_spot_rescheduler_tpu.solver.ffd import (
        carry_layout,
        plan_ffd_streamed,
    )
    from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    spec = dataclasses.replace(
        CONFIGS[2], name="pallas-parity", n_on_demand=6, n_spot=10,
        n_pods=64,
    )
    cfg = ReschedulerConfig(resources=spec.resources)
    client = generate_cluster(spec, seed)
    store = client.columnar_store(
        cfg.resources,
        on_demand_label=cfg.on_demand_node_label,
        spot_label=cfg.spot_node_label,
    )
    packed, _ = store.pack(
        client.list_pdbs(), priority_threshold=cfg.priority_threshold
    )
    rng = np.random.default_rng(seed)
    cases = [packed]
    S = int(np.asarray(packed.spot_free).shape[0])
    for _ in range(2):
        # same shapes, different problem: permute the spot axis (every
        # spot_* plane together, so rows stay self-consistent) and
        # jitter the free capacity
        perm = rng.permutation(S)
        cases.append(packed._replace(
            spot_free=np.asarray(packed.spot_free)[perm]
            * rng.uniform(0.5, 1.5, (S, 1)).astype(np.float32),
            spot_count=np.asarray(packed.spot_count)[perm],
            spot_max_pods=np.asarray(packed.spot_max_pods)[perm],
            spot_taints=np.asarray(packed.spot_taints)[perm],
            spot_ok=np.asarray(packed.spot_ok)[perm],
            spot_aff=np.asarray(packed.spot_aff)[perm],
        ))

    mismatches = []

    def check(tag, case_i, got, want):
        if not (
            np.array_equal(np.asarray(got.feasible), np.asarray(want.feasible))
            and np.array_equal(
                np.asarray(got.assignment), np.asarray(want.assignment)
            )
        ):
            mismatches.append({"case": case_i, "vs": tag})

    t0 = time.perf_counter()
    for i, pk in enumerate(cases):
        lay = carry_layout(pk)
        got = plan_stream_bf_pallas(pk, layout=lay, interpret=True)
        check("oracle-bf", i, got, plan_oracle(pk, best_fit=True))
        for n in chunk_counts:
            check(
                f"xla-stream-c{n}", i, got,
                plan_ffd_streamed(pk, carry_chunks=n, layout=lay,
                                  best_fit=True),
            )
        check("oracle-ff", i, plan_ffd_pallas(pk), plan_oracle(pk))
    return {
        "ok": not mismatches,
        "cases": len(cases),
        "chunk_counts": list(chunk_counts),
        "checks": len(cases) * (len(chunk_counts) + 2),
        "mismatches": mismatches,
        "wall_s": round(time.perf_counter() - t0, 2),
    }


def run_pallas_smoke(args, metric: str, unit: str) -> int:
    """CI smoke of the fused Pallas stream kernel (``make pallas-smoke``,
    <30 s): interpret-mode kernel vs the XLA ``_stream_bf_step`` scan at
    >=3 chunk counts vs the host oracle — see :func:`pallas_parity_smoke`."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    result = pallas_parity_smoke(seed=args.seed)
    print(
        f"pallas-smoke: {result['cases']} packs x chunk counts "
        f"{result['chunk_counts']} ({result['checks']} parity checks) "
        f"in {result['wall_s']}s "
        f"-> {'OK' if result['ok'] else 'FAIL: %s' % result['mismatches']}",
        file=sys.stderr,
    )
    emit({
        "metric": metric,
        "value": result["wall_s"],
        "unit": unit,
        "cases": result["cases"],
        "checks": result["checks"],
        "chunk_counts": result["chunk_counts"],
        "mismatches": result["mismatches"],
        "ok": result["ok"],
    })
    return 0 if result["ok"] else 1


def run_carry_wall(args, metric: str, unit: str) -> int:
    """Measured wall clock of the carry-streamed union — the PR-13
    deferred bench row. Executes the EXACT union program the dispatch
    ladder keeps live past the wide carry bound
    (``solver/fallback.with_repair_streamed`` on the guarded narrow
    layout, repair intact) at the given ``--config``/``--scale`` on the
    reachable backend, and reports compile + median execute wall. The
    JSON self-labels through the backend attestation, so a CPU row can
    never masquerade as the chip number; ``--carry-chunks`` pins the
    chunk count (default: the 20x ladder verdict's count, so a scaled
    CPU run still measures the 20x program shape-for-shape per lane)."""
    import jax

    from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS
    from k8s_spot_rescheduler_tpu.solver import carry as solver_carry
    from k8s_spot_rescheduler_tpu.solver import memory as solver_memory
    from k8s_spot_rescheduler_tpu.solver.fallback import with_repair_streamed
    from k8s_spot_rescheduler_tpu.solver.repair import DEFAULT_ROUNDS

    spec = _scaled_spec(CONFIGS[args.config], args.scale)
    packed = build_problem(args.config, args.seed, spec=spec)[0]
    layout = solver_carry.carry_layout(packed)
    shapes = solver_memory.packed_shapes(packed)
    if args.carry_chunks > 0:
        chunks = args.carry_chunks
    else:
        # the chunk count the ladder dispatches at the 20x north star
        # (scale-smoke proves that decision; this run EXECUTES the
        # program at a backend-feasible scale with the same chunking).
        # C and S grow with cluster size, so project this run's shapes
        # back to 1x and out to 20x; K/R/W/A are per-lane plane widths.
        C, K, S, R, W, A = shapes
        f = 20.0 / max(args.scale, 1e-9)
        tier20 = solver_memory.pick_tier(
            int(C * f), K, int(S * f), R, W, A,
            n_devices=8,
            budget_bytes=None,
            wants_repair=True,
            carry_plane_bytes=solver_carry.plane_bytes(layout, R, A),
        )
        chunks = max(1, int(tier20.carry_chunks) or 16)
    union = jax.jit(
        with_repair_streamed(
            DEFAULT_ROUNDS, chunks, layout,
            use_pallas=(args.solver == "pallas"),
        )
    )
    t0 = time.perf_counter()
    first = union(packed)
    jax.block_until_ready(first.feasible)
    compile_s = time.perf_counter() - t0
    walls = []
    for _ in range(max(1, args.repeats)):
        t1 = time.perf_counter()
        out = union(packed)
        jax.block_until_ready(out.feasible)
        walls.append((time.perf_counter() - t1) * 1e3)
    wall_ms = float(np.median(walls))
    feas = int(np.asarray(out.feasible).sum())
    lanes = int(np.asarray(packed.cand_valid).sum())
    print(
        f"carry-wall: config {args.config} x{args.scale:g} "
        f"C={shapes[0]} S={shapes[2]} carry_chunks={chunks} layout "
        f"{layout.used}/{layout.count}/{layout.aff}  compile {compile_s:.1f}s  "
        f"union wall median {wall_ms:.1f} ms over {len(walls)} runs  "
        f"({feas}/{lanes} valid lanes feasible)",
        file=sys.stderr,
    )
    # the fleet twin's calibration hook: this measured union wall,
    # keyed by the service bucket this problem lands in, feeds
    # ``bench.py --twin-calibration <this file>`` so the modeled
    # device charges MEASURED per-batch solve seconds instead of the
    # synthetic base+per-lane line
    from k8s_spot_rescheduler_tpu.service import buckets as bucketing

    bucket = bucketing.bucket_for(packed)
    emit({
        "metric": metric,
        "value": round(wall_ms, 2),
        "unit": unit,
        "config": args.config,
        "scale": args.scale,
        "carry_chunks": int(chunks),
        "carry_plane_bytes": solver_carry.plane_bytes(
            layout, shapes[3], shapes[5]
        ),
        "compile_s": round(compile_s, 2),
        "repeats": len(walls),
        "feasible_lanes": feas,
        "valid_lanes": lanes,
        "twin_calibration": {
            bucket.key: {"solve_s": round(wall_ms / 1e3, 6)}
        },
    })
    return 0


def run_smoke(args, metric: str, unit: str) -> int:
    """CI smoke of the incremental device pipeline (``make bench-smoke``):
    a tiny CPU-only cluster (C≈64, S≈64) runs 5 full ticks through the
    production SolverPlanner and the run FAILS unless the steady-state
    delta tick ships strictly fewer bytes than the first full-pack tick
    and the staged solve reports coverage."""
    import dataclasses

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS

    spec = dataclasses.replace(
        CONFIGS[2], name="bench-smoke", n_on_demand=64, n_spot=64, n_pods=600
    )
    _, _, pack_s, client, store, pdbs = build_problem(2, args.seed, spec=spec)
    tick_ms, reports, sync_ms, traces = run_incremental_ticks(
        client, store, pdbs, spec, "jax",
        n_ticks=5, churn=3, staged_chunk_lanes=16,
    )
    report = reports[-1]
    uploads = [r.upload_bytes for r in reports]
    ok = (
        uploads[-1] < uploads[0]
        and not report.full_repack
        and report.delta_pack_lanes >= 0
        and report.chunks_solved >= 0
    )
    # jaxpr-tier audit cost (make audit-jaxpr): a fresh subprocess so
    # the measurement includes the jax import + every manifest trace —
    # the number the trajectory watches for tracing-cost regressions.
    # The audit must also pass: a red audit fails the smoke.
    t_audit = time.perf_counter()
    audit = subprocess.run(
        [sys.executable, "-m", "tools.analysis", "--tier", "jaxpr"],
        capture_output=True,
        text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    audit_jaxpr_ms = (time.perf_counter() - t_audit) * 1e3
    audit_ok = audit.returncode == 0
    if not audit_ok:
        print(
            f"bench-smoke: jaxpr audit RED (rc={audit.returncode}):\n"
            f"{audit.stdout[-2000:]}\n{audit.stderr[-2000:]}",
            file=sys.stderr,
        )
    ok = ok and audit_ok
    # proto-tier protocol verification cost (make verify-protocol):
    # same deal — fresh subprocess, full model exploration + contract
    # binding; the trajectory watches state-space growth here. A red
    # verification fails the smoke.
    t_proto = time.perf_counter()
    proto = subprocess.run(
        [sys.executable, "-m", "tools.analysis", "--tier", "proto"],
        capture_output=True,
        text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    verify_protocol_ms = (time.perf_counter() - t_proto) * 1e3
    proto_ok = proto.returncode == 0
    if not proto_ok:
        print(
            f"bench-smoke: protocol verification RED "
            f"(rc={proto.returncode}):\n"
            f"{proto.stdout[-2000:]}\n{proto.stderr[-2000:]}",
            file=sys.stderr,
        )
    ok = ok and proto_ok
    print(
        f"bench-smoke: uploads per tick {uploads} B  "
        f"tick ms {[round(t, 1) for t in tick_ms]}  "
        f"chunks {report.chunks_solved} solved / "
        f"{report.chunks_skipped} skipped  -> {'OK' if ok else 'FAIL'}",
        file=sys.stderr,
    )
    emit(
        {
            "metric": metric,
            "value": int(uploads[-1]),
            "unit": unit,
            "vs_baseline": round(uploads[0] / max(uploads[-1], 1), 2),
            "first_full_pack_bytes": int(uploads[0]),
            "delta_upload_bytes": int(uploads[-1]),
            "delta_pack_lanes": int(report.delta_pack_lanes),
            "chunks_solved": int(report.chunks_solved),
            "chunks_skipped": int(report.chunks_skipped),
            "steady_tick_ms": round(float(np.median(tick_ms[1:])), 2),
            # observe split: mirror sync (O(churn)) vs full pack
            "sync_ms": round(float(np.median(sync_ms)), 3),
            "pack_ms": round(pack_s * 1e3, 3),
            # span breakdown (steady ticks, cold tick 0 excluded):
            # in-process path, so queue/wire are structurally 0 — the
            # serve-smoke line carries the cross-process split
            "span_queue_ms": round(
                _span_ms_median(traces[1:], "service.queue-wait"), 3
            ),
            "span_solve_ms": round(
                _span_ms_median(traces[1:], "plan.solve"), 3
            ),
            "span_wire_ms": round(
                _span_ms_median(traces[1:], "wire.transfer"), 3
            ),
            # full jaxpr-tier audit wall (subprocess incl. jax import):
            # the tracing-cost trajectory for `make audit-jaxpr`
            "audit_jaxpr_ms": round(audit_jaxpr_ms, 1),
            # proto-tier model exploration + contract wall: the
            # state-space-growth trajectory for `make verify-protocol`
            "verify_protocol_ms": round(verify_protocol_ms, 1),
            "ok": ok,
        }
    )
    return 0 if ok else 1


def serve_smoke(n_tenants: int = 4, seed: int = 0) -> dict:
    """The multi-tenant planner-service acceptance core (``make
    serve-smoke``; reused by tests/test_service.py):

    - N synthetic tenant clusters plan SOLO through one in-process
      SolverPlanner (the single-tenant truth);
    - the same N tenants then plan CONCURRENTLY through a real
      ServiceServer over HTTP via RemotePlanner agents (observe/pack
      local, wire-protocol solve remote), with a batch window wide
      enough that they coalesce;
    - FAILS unless every tenant's selection (drained node + proven
      assignments) is bit-identical to its solo plan, at least one
      batch carried lanes from >=2 tenants (service_batch_lanes /
      service_batch_tenants), and no agent fell back;
    - every agent's plan must produce ONE trace tree holding both the
      agent-side spans and the server-returned spans (queue-wait /
      batch / solve grafted under wire.request) under a single trace
      ID that round-tripped the wire — the end-to-end tracing
      acceptance — and the JSON line reports the
      span_queue_ms/span_solve_ms/span_wire_ms medians off those trees.
    """
    import dataclasses
    import threading

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS, generate_cluster
    from k8s_spot_rescheduler_tpu.metrics import registry as metrics
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
    from k8s_spot_rescheduler_tpu.service.agent import RemotePlanner
    from k8s_spot_rescheduler_tpu.service.server import ServiceServer
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    spec = dataclasses.replace(
        CONFIGS[2], name="serve-smoke", n_on_demand=8, n_spot=8, n_pods=80
    )
    cfg = ReschedulerConfig(resources=spec.resources, solver="jax")
    tenants = []
    for i in range(n_tenants):
        client = generate_cluster(spec, seed + i)
        store = client.columnar_store(
            cfg.resources,
            on_demand_label=cfg.on_demand_node_label,
            spot_label=cfg.spot_node_label,
        )
        tenants.append((store, client.list_pdbs()))

    def selection(report):
        if report.plan is None:
            return (False, None, None)
        return (
            True,
            report.plan.node.node.name,
            dict(report.plan.assignments),
        )

    # solo truth: ONE planner instance (jit caches and pads persist, as
    # in production) planning each tenant in turn
    solo = SolverPlanner(cfg)
    solo_sel = [selection(solo.plan(store, pdbs)) for store, pdbs in tenants]
    solo_lanes = [
        int(np.asarray(store.pack(pdbs)[0].cand_valid.sum()))
        for store, pdbs in tenants
    ]

    before = metrics.service_snapshot()
    server = ServiceServer(
        cfg, "127.0.0.1:0", batch_window_s=0.5,
        # every tenant must be admitted (503-shedding would read as a
        # spurious fallback failure) — the smoke tests batching, not
        # the depth cap
        max_inflight=max(16, 2 * n_tenants),
    )
    server.start_background()
    agents = [
        RemotePlanner(cfg, f"http://{server.address}", tenant=f"tenant-{i}")
        for i in range(n_tenants)
    ]
    results: list = [None] * n_tenants
    times = [0.0] * n_tenants
    barrier = threading.Barrier(n_tenants)

    def run_agent(i):
        store, pdbs = tenants[i]
        barrier.wait()
        t0 = time.perf_counter()
        results[i] = agents[i].plan(store, pdbs)
        times[i] = (time.perf_counter() - t0) * 1e3

    threads = [
        threading.Thread(target=run_agent, args=(i,))
        for i in range(n_tenants)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # --- delta-wire steady state (wire v4): the O(churn) acceptance ---
    # tick 1 above was first contact (full packs). Tick 2 ships ZERO
    # churn — every agent's upload must be a fixed-size empty delta,
    # not a pack. Tick 3 ships small churn (one pod removed per
    # tenant) — bytes proportional to it. Tick 4 is a FORCED resync
    # (tenant cache invalidated server-side): exactly one resync per
    # agent, full-pack bytes again, and still the right selections.
    def ingest_bytes():
        return metrics.service_snapshot()["wire_ingest_bytes"]

    def delta_counts():
        d = metrics.service_snapshot()["delta_requests"]
        return d.get("applied", 0), d.get("resync", 0)

    def fleet_tick():
        ticked = [None] * n_tenants
        gate = threading.Barrier(n_tenants)

        def run(i):
            store, pdbs = tenants[i]
            gate.wait()
            ticked[i] = agents[i].plan(store, pdbs)

        ts = [
            threading.Thread(target=run, args=(i,))
            for i in range(n_tenants)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return ticked

    def check_tick(note, reports, bad):
        for i, report in enumerate(reports):
            store, pdbs = tenants[i]
            want = selection(solo.plan(store, pdbs))
            got = selection(report)
            if got != want or report.solver != "remote":
                bad.append(
                    {"tick": note, "tenant": i, "solo": want,
                     "served": got, "solver": report.solver}
                )

    delta_bad: list = []
    full_tick_bytes = ingest_bytes() - before.get("wire_ingest_bytes", 0)
    b0 = ingest_bytes()
    check_tick("quiet", fleet_tick(), delta_bad)
    quiet_tick_bytes = ingest_bytes() - b0
    quiet_cobatch = metrics.service_snapshot()["batch_tenants"]
    for i in range(n_tenants):  # small churn: one pod per tenant
        store = tenants[i][0]
        store.remove_pod(next(iter(store._pod_row)))
    b1 = ingest_bytes()
    check_tick("churn", fleet_tick(), delta_bad)
    churn_tick_bytes = ingest_bytes() - b1
    applied_before_resync, resyncs_before = delta_counts()
    server.service.invalidate_tenant_cache()
    b2 = ingest_bytes()
    check_tick("forced-resync", fleet_tick(), delta_bad)
    resync_tick_bytes = ingest_bytes() - b2
    applied_total, resyncs_total = delta_counts()
    forced_resyncs = resyncs_total - resyncs_before

    # --- persistent-wire reuse phase: the sub-RTT transport claim,
    # measured. ONE agent runs REUSE_TICKS sequential ticks against the
    # live server: every tick after the first must ride the SAME pooled
    # keep-alive socket (remote_wire_connection_reuse_total advances by
    # >= ticks-1, zero stale reconnects, pool size stays 1), and the
    # median wire.request round trip must come in strictly under a
    # same-run fresh-connection-per-tick baseline (the seed's urllib
    # transport, kept on the agent for exactly this A/B) — the per-tick
    # TCP handshake + connection setup is the RTT the pool deletes.
    # First-contact ticks (jit warm on the pooled side, full pack on
    # both) are excluded from both medians.
    reuse_ticks = 100
    server.service.batch_window_s = 0.0  # solo ticks: nothing to co-batch
    wire_agent = RemotePlanner(
        cfg, f"http://{server.address}", tenant="wire-reuse"
    )
    wire_store, wire_pdbs = tenants[0]
    r0 = metrics.service_snapshot()
    pooled_traces, reuse_bad = [], []
    for _ in range(reuse_ticks):
        rep = wire_agent.plan(wire_store, wire_pdbs)
        if rep.solver != "remote":
            reuse_bad.append(rep.solver)
        pooled_traces.append(wire_agent.last_trace)
    r1 = metrics.service_snapshot()
    reuse_delta = r1["wire_connection_reuse"] - r0["wire_connection_reuse"]
    reuse_reconnects = r1["wire_reconnects"] - r0["wire_reconnects"]
    pooled_conns = wire_agent._wire_pool.connection_count()
    pooled_wire_ms = _span_ms_median(pooled_traces[1:], "wire.request")
    fresh_agent = RemotePlanner(
        cfg, f"http://{server.address}", tenant="wire-reuse"
    )
    fresh_agent.transport = fresh_agent._transport_urllib
    fresh_traces = []
    for _ in range(25):
        fresh_agent.plan(wire_store, wire_pdbs)
        fresh_traces.append(fresh_agent.last_trace)
    fresh_wire_ms = _span_ms_median(fresh_traces[1:], "wire.request")
    reuse_ok = (
        not reuse_bad
        and reuse_delta >= reuse_ticks - 1
        and reuse_reconnects == 0
        and pooled_conns == 1
        and pooled_wire_ms < fresh_wire_ms
    )
    server.close()

    # the wire claim, measured: a zero-churn tick ships fixed-size
    # headers (not packs), churn ticks ship O(churn), and only first
    # contact / forced resyncs pay full-pack bytes
    wire_ok = (
        quiet_tick_bytes < n_tenants * 2048
        and 0 < churn_tick_bytes < 0.5 * full_tick_bytes
        and resync_tick_bytes > 0.9 * full_tick_bytes
        and forced_resyncs == n_tenants
        and quiet_cobatch >= 2  # delta ticks still co-batch
    )

    after = metrics.service_snapshot()
    mismatches = []
    for i, report in enumerate(results):
        got = selection(report)
        if got != solo_sel[i] or report.solver != "remote":
            mismatches.append(
                {"tenant": i, "solo": solo_sel[i], "served": got,
                 "solver": report.solver}
            )
    fallbacks = (
        after["remote_planner_fallback"] - before["remote_planner_fallback"]
    )
    cobatched = after["batch_tenants_max"] >= 2
    # lanes prove it too: one batch carried more lanes than any single
    # tenant holds
    lanes_prove = after["batch_lanes_max"] > max(solo_lanes)
    # end-to-end tracing acceptance: each agent's tick trace holds the
    # agent-side spans AND the server-returned spans under ONE trace id
    # that crossed the wire (the server keyed its span block by it)
    traces = [a.last_trace for a in agents]
    trace_bad = []
    for i, trace in enumerate(traces):
        if trace is None or not trace.trace_id:
            trace_bad.append({"tenant": i, "why": "no trace recorded"})
            continue
        have = {
            name
            for name in ("plan.pack", "wire.request", "service.queue-wait",
                         "service.solve", "wire.transfer")
            if trace.find(name)
        }
        missing = {"plan.pack", "wire.request", "service.queue-wait",
                   "service.solve", "wire.transfer"} - have
        if missing:
            trace_bad.append({"tenant": i, "missing": sorted(missing)})
    ok = (
        not mismatches and fallbacks == 0 and cobatched and lanes_prove
        and not trace_bad and wire_ok and not delta_bad and reuse_ok
    )
    applied = after["delta_requests"].get("applied", 0) - before.get(
        "delta_requests", {}
    ).get("applied", 0)
    resyncs = after["delta_requests"].get("resync", 0) - before.get(
        "delta_requests", {}
    ).get("resync", 0)
    return {
        "ok": ok,
        "n_tenants": n_tenants,
        "serve_ms": round(float(np.median(times)), 2),
        # the wire-anti-entropy accounting (delta phases, wire v4)
        "full_tick_bytes": int(full_tick_bytes),
        "quiet_tick_bytes": int(quiet_tick_bytes),
        "churn_tick_bytes": int(churn_tick_bytes),
        "resync_tick_bytes": int(resync_tick_bytes),
        "wire_bytes_per_tick": int(
            np.median([quiet_tick_bytes, churn_tick_bytes])
        ),
        "delta_applied": int(applied),
        "delta_resyncs": int(resyncs),
        "cache_hit_rate": round(
            applied / max(1.0, applied + resyncs), 3
        ),
        "delta_mismatches": delta_bad,
        "wire_ok": wire_ok,
        # the persistent-wire reuse accounting (sub-RTT transport)
        "reuse_ticks": reuse_ticks,
        "wire_reuse": int(reuse_delta),
        "wire_reconnects": int(reuse_reconnects),
        "wire_pooled_conns": int(pooled_conns),
        "span_wire_pooled_ms": round(pooled_wire_ms, 3),
        "span_wire_fresh_ms": round(fresh_wire_ms, 3),
        "reuse_ok": reuse_ok,
        "batch_tenants_max": int(after["batch_tenants_max"]),
        "batch_lanes_max": int(after["batch_lanes_max"]),
        "batch_occupancy": round(
            after["batch_tenants_max"] / max(n_tenants, 1), 3
        ),
        "solo_lanes_max": max(solo_lanes),
        "remote_fallbacks": int(fallbacks),
        "mismatches": mismatches,
        # the cross-process span split, medians over the agent traces:
        # where a wire-planned tick's milliseconds actually went
        "span_queue_ms": round(
            _span_ms_median(traces, "service.queue-wait"), 3
        ),
        "span_solve_ms": round(_span_ms_median(traces, "service.solve"), 3),
        "span_wire_ms": round(_span_ms_median(traces, "wire.transfer"), 3),
        "trace_violations": trace_bad,
    }


def run_serve_smoke(args, metric: str, unit: str) -> int:
    """CI smoke of the multi-tenant planner service (``make
    serve-smoke``): >=4 concurrent synthetic tenants batched through one
    in-process service over real HTTP must produce selections
    bit-identical to each tenant's solo in-process plan, with at least
    one batch sharing lanes across >=2 tenants."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    result = serve_smoke(n_tenants=max(4, args.tenants), seed=args.seed)
    fail_detail = (
        result["mismatches"] or result["trace_violations"]
        or result["delta_mismatches"]
        or (
            not result["reuse_ok"]
            and {
                k: result[k]
                for k in ("reuse_ticks", "wire_reuse", "wire_reconnects",
                          "wire_pooled_conns", "span_wire_pooled_ms",
                          "span_wire_fresh_ms")
            }
        )
        or {
            k: result[k]
            for k in ("full_tick_bytes", "quiet_tick_bytes",
                      "churn_tick_bytes", "resync_tick_bytes",
                      "delta_resyncs")
        }
    )
    print(
        f"serve-smoke: {result['n_tenants']} tenants  "
        f"serve_ms={result['serve_ms']}  "
        f"batch_tenants_max={result['batch_tenants_max']}  "
        f"batch_lanes_max={result['batch_lanes_max']} "
        f"(solo max {result['solo_lanes_max']})  "
        f"fallbacks={result['remote_fallbacks']}  "
        f"wire bytes full={result['full_tick_bytes']} "
        f"quiet={result['quiet_tick_bytes']} "
        f"churn={result['churn_tick_bytes']} "
        f"resync={result['resync_tick_bytes']}  "
        f"cache_hit={result['cache_hit_rate']}  "
        f"reuse={result['wire_reuse']}/{result['reuse_ticks']} ticks "
        f"(reconnects={result['wire_reconnects']}, "
        f"wire pooled={result['span_wire_pooled_ms']} "
        f"vs fresh={result['span_wire_fresh_ms']} ms)  "
        f"spans queue={result['span_queue_ms']} "
        f"solve={result['span_solve_ms']} wire={result['span_wire_ms']} ms  "
        f"-> {'OK' if result['ok'] else 'FAIL: %s' % fail_detail}",
        file=sys.stderr,
    )
    emit(
        {
            "metric": metric,
            "value": result["serve_ms"],
            "unit": unit,
            "n_tenants": result["n_tenants"],
            "serve_ms": result["serve_ms"],
            "batch_occupancy": result["batch_occupancy"],
            "batch_tenants_max": result["batch_tenants_max"],
            "batch_lanes_max": result["batch_lanes_max"],
            "remote_fallbacks": result["remote_fallbacks"],
            # the delta-wire accounting (wire v4): steady-state bytes
            # per tick are O(churn); full packs only on first contact
            # and forced resyncs
            "wire_bytes_per_tick": result["wire_bytes_per_tick"],
            "full_tick_bytes": result["full_tick_bytes"],
            "quiet_tick_bytes": result["quiet_tick_bytes"],
            "delta_resyncs": result["delta_resyncs"],
            "cache_hit_rate": result["cache_hit_rate"],
            # the cross-process span breakdown (grafted traces): where
            # the tunnel-RTT-bound milliseconds actually go
            "span_queue_ms": result["span_queue_ms"],
            "span_solve_ms": result["span_solve_ms"],
            "span_wire_ms": result["span_wire_ms"],
            # persistent-wire reuse: pooled keep-alive socket economics
            "wire_reuse": result["wire_reuse"],
            "wire_reconnects": result["wire_reconnects"],
            "span_wire_pooled_ms": result["span_wire_pooled_ms"],
            "span_wire_fresh_ms": result["span_wire_fresh_ms"],
            "ok": result["ok"],
        }
    )
    return 0 if result["ok"] else 1


def sched_smoke(seed: int = 0) -> tuple:
    """The drain-schedule acceptance core (``make sched-smoke``; reused
    by tests/test_schedule.py). Numpy-oracle parity path on a FakeClock,
    three cases:

    1. **local** — a quality cluster drained to exhaustion with
       schedules on must free exactly the nodes the per-tick planner
       frees, with planner fetches <= ceil(drains / horizon) + 2 (the
       O(1)-fetch claim, measured) and zero invalidations on the
       quiescent run; injected churn (a spot node removed under a
       pending schedule) must INVALIDATE the tail — flight-event delta
       == metric delta — and the next tick must re-plan and keep
       draining;
    2. **service** — the same schedule fetched through a real
       ServiceServer over HTTP (wire v3 KIND_PLAN_SCHEDULE) must be
       bit-identical to the local plan_schedule cut, and the agent's
       trace must hold plan.schedule + wire.request + the grafted
       service spans under one round-tripped trace id;
    3. **failover-with-schedule-in-flight** — killing the primary
       replica under a partially-executed schedule costs nothing until
       the NEXT cut, which fails over to the secondary (failover metric
       + flight event fire; zero local fallbacks).
    """
    import dataclasses

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from k8s_spot_rescheduler_tpu.bench.quality import (
        _HintingPlanner,
        drain_to_exhaustion,
    )
    from k8s_spot_rescheduler_tpu.io.synthetic import (
        QUALITY_CONFIGS,
        generate_quality_cluster,
    )
    from k8s_spot_rescheduler_tpu.loop import flight
    from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
    from k8s_spot_rescheduler_tpu.metrics import registry as metrics
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
    from k8s_spot_rescheduler_tpu.service.agent import RemotePlanner
    from k8s_spot_rescheduler_tpu.service.server import ServiceServer
    from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    violations: list = []
    name, spec = next(iter(QUALITY_CONFIGS.items()))
    horizon = 4

    # --- case 1: local parity + fetch bound -------------------------------
    base_cfg = ReschedulerConfig(
        solver="numpy", resources=spec.resources, max_drains_per_tick=64
    )
    sched_cfg = dataclasses.replace(
        base_cfg, plan_schedule_enabled=True, schedule_horizon=horizon
    )
    inv0 = metrics.robustness_snapshot()["schedule_invalidated"]
    drains_base = drain_to_exhaustion(
        generate_quality_cluster(spec, seed, reschedule_evicted=True),
        base_cfg,
    )
    stats: dict = {}
    drains_sched = drain_to_exhaustion(
        generate_quality_cluster(spec, seed, reschedule_evicted=True),
        sched_cfg,
        planner_stats=stats,
    )
    fetches = stats.get("fetches_total", -1)
    lens = stats.get("schedule_lens", [])
    bound = math.ceil(max(drains_sched, 1) / horizon) + 2
    if drains_sched != drains_base:
        violations.append(
            f"schedule mode drained {drains_sched} != per-tick "
            f"{drains_base}"
        )
    if fetches > bound:
        violations.append(
            f"fetches {fetches} > ceil({drains_sched}/{horizon})+2 = "
            f"{bound} — the O(1)-fetch claim failed"
        )
    inv_quiescent = (
        metrics.robustness_snapshot()["schedule_invalidated"] - inv0
    )
    if inv_quiescent:
        violations.append(
            f"{inv_quiescent} invalidation(s) on a quiescent run"
        )

    # --- case 1b: churn invalidates, flight == metric ---------------------
    client = generate_quality_cluster(spec, seed, reschedule_evicted=True)
    churn_cfg = dataclasses.replace(
        sched_cfg, max_drains_per_tick=1, schedule_horizon=8,
        node_drain_delay=0.0,
    )
    inner = SolverPlanner(churn_cfg)
    r = Rescheduler(
        client, _HintingPlanner(inner, client), churn_cfg,
        clock=client.clock, recorder=client,
    )
    m0 = metrics.robustness_snapshot()["schedule_invalidated"]
    f0 = flight.RECORDER.counts().get("schedule-invalidated", 0)
    client.clock.advance(1)
    first = r.tick()
    if not first.drained or first.report.schedule_len < 2:
        violations.append("churn case: first tick did not start a schedule")
    # churn under the pending schedule: a spot node vanishes
    spot = next(
        n for n in client.nodes.values()
        if "spot" in "".join(f"{k}={v}" for k, v in n.labels.items())
    )
    client.remove_node(spot.name)
    client.clock.advance(1)
    second = r.tick()
    m_delta = metrics.robustness_snapshot()["schedule_invalidated"] - m0
    f_delta = flight.RECORDER.counts().get("schedule-invalidated", 0) - f0
    if m_delta < 1:
        violations.append("churn did not invalidate the schedule")
    if m_delta != f_delta:
        violations.append(
            f"flight delta {f_delta} != metric delta {m_delta} for "
            "schedule-invalidated"
        )
    if not second.drained:
        violations.append("post-invalidation tick failed to re-plan+drain")

    # --- case 2: service bit-identity + span tree -------------------------
    svc_cfg = dataclasses.replace(
        ReschedulerConfig(
            solver="numpy", resources=spec.resources,
            plan_schedule_enabled=True, schedule_horizon=6,
        ),
    )
    client2 = generate_quality_cluster(spec, seed, reschedule_evicted=True)
    store = client2.columnar_store(
        svc_cfg.resources,
        on_demand_label=svc_cfg.on_demand_node_label,
        spot_label=svc_cfg.spot_node_label,
    )
    pdbs = client2.list_pdbs()
    srv = ServiceServer(svc_cfg, "127.0.0.1:0", batch_window_s=0.0)
    srv.start_background(scheduler=False)
    try:
        agent = RemotePlanner(
            svc_cfg, f"http://{srv.address}", tenant="sched-smoke",
            clock=FakeClock(),
        )
        handle_remote = agent.plan_schedule(store, pdbs)
        handle_local = SolverPlanner(svc_cfg).plan_schedule(store, pdbs)
        if handle_remote is None or handle_local is None:
            violations.append("service case: schedule cut failed")
        else:
            if len(handle_remote.steps) != len(handle_local.steps) or any(
                a.index != b.index
                or a.n_feasible != b.n_feasible
                or not np.array_equal(a.row, b.row)
                for a, b in zip(handle_remote.steps, handle_local.steps)
            ):
                violations.append(
                    "wire schedule differs from the local device cut"
                )
            trace = agent.last_trace
            want = {"plan.schedule", "wire.request", "service.solve"}
            have = {
                n for n in want if trace is not None and trace.find(n)
            }
            if want - have:
                violations.append(
                    f"service case: trace missing spans {sorted(want - have)}"
                )
    finally:
        srv.close()

    # --- case 3: failover with a schedule in flight -----------------------
    clock = FakeClock()
    srv_a = ServiceServer(svc_cfg, "127.0.0.1:0", batch_window_s=0.0,
                          clock=clock)
    srv_b = ServiceServer(svc_cfg, "127.0.0.1:0", batch_window_s=0.0,
                          clock=clock)
    srv_a.start_background(scheduler=False)
    srv_b.start_background(scheduler=False)
    svc_before = metrics.service_snapshot()
    fl_failover0 = flight.RECORDER.counts().get("failover", 0)
    try:
        agent = RemotePlanner(
            svc_cfg,
            f"http://{srv_a.address},http://{srv_b.address}",
            tenant="sched-failover",
            clock=clock,
        )
        handle = agent.plan_schedule(store, pdbs)
        if handle is None or agent.last_endpoint != f"http://{srv_a.address}":
            violations.append("failover case: primary did not serve the cut")

        def execute(report):
            # apply one step's drain to the fake cluster the way the
            # real actuator + scheduler would: evict the plan's pods
            # and let them land on their proven placements
            client2.placement_hints.clear()
            client2.placement_hints.update(report.plan.assignments)
            for pod in report.plan.pods:
                client2.evict_pod(pod, 0)
            client2.clock.advance(1)

        step = handle.next_plan(store, pdbs) if handle else None
        if step is None:
            violations.append("failover case: step 0 did not execute")
        else:
            execute(step)
        srv_a.close()
        # the in-flight schedule keeps executing with ZERO wire traffic
        if handle is not None and not handle.exhausted:
            nxt = handle.next_plan(store, pdbs)
            if nxt is None:
                violations.append(
                    "failover case: in-flight step failed after the "
                    "replica death (%s)" % handle.invalid_reason
                )
            else:
                execute(nxt)
        handle2 = agent.plan_schedule(store, pdbs)
        if handle2 is None:
            violations.append("failover case: secondary did not serve")
        elif agent.last_endpoint != f"http://{srv_b.address}":
            violations.append("failover case: cut not served by secondary")
        svc_after = metrics.service_snapshot()
        failovers = (
            svc_after["remote_planner_failover"]
            - svc_before["remote_planner_failover"]
        )
        fl_failover = (
            flight.RECORDER.counts().get("failover", 0) - fl_failover0
        )
        if failovers < 1:
            violations.append("failover metric did not fire")
        if failovers != fl_failover:
            violations.append(
                f"flight failover delta {fl_failover} != metric "
                f"delta {failovers}"
            )
        if (
            svc_after["remote_planner_fallback"]
            != svc_before["remote_planner_fallback"]
        ):
            violations.append(
                "failover case: an agent fell back to the local oracle"
            )
    finally:
        srv_b.close()

    stats_out = {
        "drains": int(drains_sched),
        "drains_per_tick_baseline": int(drains_base),
        "fetches_total": int(fetches),
        "fetch_bound": int(bound),
        "schedule_lens": lens,
        "invalidations": int(m_delta),
    }
    return stats_out, violations


def run_sched_smoke(args, metric: str, unit: str) -> int:
    """CI smoke of the drain-schedule path (``make sched-smoke``):
    local parity + fetch bound, churn invalidation with flight/metric
    parity, wire bit-identity, and failover with a schedule in
    flight."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    t0 = time.perf_counter()
    stats, violations = sched_smoke(args.seed)
    wall = time.perf_counter() - t0
    ok = not violations
    print(
        f"sched-smoke: {stats['drains']} drains in "
        f"{stats['fetches_total']} fetches (bound {stats['fetch_bound']}; "
        f"per-tick baseline {stats['drains_per_tick_baseline']} drains)  "
        f"schedule lens {stats['schedule_lens']}  "
        f"invalidations {stats['invalidations']}  wall={wall:.1f}s  "
        f"-> {'OK' if ok else 'FAIL: ' + '; '.join(violations)}",
        file=sys.stderr,
    )
    emit(
        {
            "metric": metric,
            "value": int(stats["fetches_total"]),
            "unit": unit,
            "vs_baseline": round(
                stats["drains"] / max(stats["fetches_total"], 1), 2
            ),
            "wall_s": round(wall, 2),
            "ok": ok,
            **stats,
            **({"violations": violations} if violations else {}),
        }
    )
    return 0 if ok else 1


def fleet_chaos_smoke(n_agents: int = 4, seed: int = 0) -> dict:
    """The fleet failure-domain acceptance core (``make
    fleet-chaos-smoke``; reused by tests/test_fleet_chaos.py):

    N agents x 2 planner-service replicas over real HTTP on a shared
    virtual clock, driven through four scripted phases —

    1. **healthy**: every agent plans through replica A; selections must
       be bit-identical to each tenant's solo in-process plan; the
       device-health watchdog calibrates its baseline;
    2. **wire chaos**: the seeded ``ServiceFaultPlan`` (connection
       resets, slow-loris uploads, truncated/corrupted replies, a
       scripted 503 storm) runs on every agent's transport — agents must
       fail over down the endpoint list and fall back to the local
       oracle only with both replicas unusable, with ZERO crashes and
       every selection still solo-identical;
    3. **sick device**: replica A's solve path gains scripted per-batch
       latency; the watchdog must flip within ``device_sick_threshold``
       consecutive slow batches (/healthz ``device:"sick"``, gauge 1,
       flight ``device-sick``), serve host-path plans meanwhile, and
       recover ONLY after the hysteresis probes pass once the phase
       ends;
    4. **replica kill/restart**: replica A drains gracefully (SIGTERM
       contract) and dies; agents fail over to B (``failover_ms``
       measured); A restarts on the same address, pre-warms from its
       persisted state (``warmed_buckets``), and serves again once its
       breaker window passes.

    Accounting acceptance: zero agent crashes, zero non-solo-identical
    selections (no eviction could ever come from a stale or unproven
    plan), and flight-recorder deltas exactly equal to metric deltas for
    remote-planner-fallback, failover and device-sick."""
    import dataclasses
    import tempfile

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS, generate_cluster
    from k8s_spot_rescheduler_tpu.loop import flight
    from k8s_spot_rescheduler_tpu.metrics import registry as metrics
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
    from k8s_spot_rescheduler_tpu.service.agent import RemotePlanner
    from k8s_spot_rescheduler_tpu.service.chaos import (
        ChaosAgentTransport,
        ServiceChaos,
        ServiceFaultPlan,
    )
    from k8s_spot_rescheduler_tpu.service.server import ServiceServer
    from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    spec = dataclasses.replace(
        CONFIGS[2], name="fleet-chaos", n_on_demand=6, n_spot=6, n_pods=48
    )
    cfg = ReschedulerConfig(
        resources=spec.resources,
        solver="numpy",  # CPU CI: the host oracle IS the proven path
        device_sick_threshold=3,
        service_drain_grace=2.0,
        planner_timeout=5.0,
    )
    tenants = []
    for i in range(n_agents):
        client = generate_cluster(spec, seed + i)
        store = client.columnar_store(
            cfg.resources,
            on_demand_label=cfg.on_demand_node_label,
            spot_label=cfg.spot_node_label,
        )
        tenants.append((store, client.list_pdbs()))

    def selection(report):
        if report.plan is None:
            return (False, None, None)
        return (
            True,
            report.plan.node.node.name,
            dict(report.plan.assignments),
        )

    solo = SolverPlanner(cfg)
    solo_sel = [selection(solo.plan(store, pdbs)) for store, pdbs in tenants]

    clock = FakeClock()
    state_dir = tempfile.mkdtemp(prefix="fleet-chaos-state-")
    cfg_srv = dataclasses.replace(cfg, service_state_dir=state_dir)

    def new_replica(addr="127.0.0.1:0"):
        srv = ServiceServer(
            cfg_srv, addr, batch_window_s=0.0,
            max_inflight=max(16, 4 * n_agents), clock=clock,
        )
        # scheduler-less: submissions drain synchronously on the handler
        # thread, so no background thread ever sleeps on the shared
        # virtual clock — the run is deterministic tick by tick
        srv.start_background(scheduler=False)
        return srv

    replica_a = new_replica()
    replica_b = new_replica()
    addr_a = replica_a.address

    agent_plan = ServiceFaultPlan(
        seed=seed + 7,
        connect_reset_rate=0.15,
        slow_loris_rate=0.05,
        reply_truncate_rate=0.10,
        reply_corrupt_rate=0.10,
        http_503_script=(3, 4),
        http_503_retry_after=0.5,
        http_5xx_rate=0.05,
    )
    agents, chaos_transports = [], []
    for i in range(n_agents):
        agent = RemotePlanner(
            cfg,
            f"http://{addr_a},http://{replica_b.address}",
            tenant=f"fleet-tenant-{i}",
            clock=clock,
        )
        chaos = ChaosAgentTransport(
            agent.transport, dataclasses.replace(agent_plan, seed=seed + i),
            clock=clock, pool=agent._wire_pool,
        )
        chaos.enabled = False
        agent.transport = chaos
        agents.append(agent)
        chaos_transports.append(chaos)

    m0 = metrics.service_snapshot()
    f0 = flight.RECORDER.counts()
    crashes, mismatches = [], []
    tick_no = [0]
    failover_ms: list = []

    def fleet_tick(note=""):
        """One synchronous fleet housekeeping tick: every agent plans
        once; wall time is measured per agent; virtual time advances a
        housekeeping interval afterwards."""
        tick_no[0] += 1
        walls = []
        for i, agent in enumerate(agents):
            store, pdbs = tenants[i]
            t0 = time.perf_counter()
            try:
                report = agent.plan(store, pdbs)
            except Exception as err:  # noqa: BLE001 — the acceptance: NEVER raises (Ctrl-C still propagates)
                crashes.append(
                    {"tick": tick_no[0], "tenant": i, "note": note,
                     "error": f"{type(err).__name__}: {err}"}
                )
                continue
            walls.append((time.perf_counter() - t0) * 1e3)
            got = selection(report)
            if got != solo_sel[i] or report.solver not in (
                "remote", "remote-fallback"
            ):
                mismatches.append(
                    {"tick": tick_no[0], "tenant": i, "note": note,
                     "solo": solo_sel[i], "got": got,
                     "solver": report.solver}
                )
        clock.advance(3.0)  # the virtual housekeeping interval
        return walls

    def delta_resyncs():
        return metrics.service_snapshot()["delta_requests"].get("resync", 0)

    # --- phase 1: healthy warmup (calibrates the watchdog baseline) ---
    for _ in range(6):
        fleet_tick("healthy")

    # --- phase 1.25: half-closed keep-alive sockets — between two
    # ticks the server side of every agent's pooled connection goes
    # away under the transport's feet (LB/NAT idle timeout, replica
    # restart: the connection LOOKS pooled, the next write meets a
    # dead peer). The pool's stale-retry contract must absorb each
    # strike with exactly ONE transparent reconnect per agent: ZERO
    # failover, ZERO local fallback, and every selection still
    # bit-identical to the solo plan (fleet_tick asserts that). Runs
    # while only replica A is pooled (healthy phase), so the counts
    # are exact: 2 strikes x n_agents sockets broken and reconnected.
    hc0 = metrics.service_snapshot()
    hc_plans = []
    for chaos in chaos_transports:
        hc_plans.append(chaos.plan)
        chaos.plan = ServiceFaultPlan(
            half_close_script=(chaos._requests + 1, chaos._requests + 2)
        )
        chaos.enabled = True
    for _ in range(2):
        fleet_tick("half-close")
    for chaos, original in zip(chaos_transports, hc_plans):
        chaos.enabled = False
        chaos.plan = original
    hc1 = metrics.service_snapshot()
    half_close_strikes = sum(
        c.stats["half_close"] for c in chaos_transports
    )
    half_close_reconnects = (
        hc1["wire_reconnects"] - hc0["wire_reconnects"]
    )
    half_close_ok = (
        half_close_strikes == 2 * n_agents
        and half_close_reconnects == 2 * n_agents
        and hc1["remote_planner_fallback"] == hc0["remote_planner_fallback"]
        and hc1["remote_planner_failover"] == hc0["remote_planner_failover"]
    )

    # --- phase 1.5: corrupted delta — replica A bit-flips every
    # request body ahead of the decode. The agents ship deltas by now
    # (tick 2 on); a corrupted delta must fail its integrity digest
    # and come back as a typed RESYNC DEMAND (flight delta == metric
    # delta, asserted at the end), the same-tick full-pack retry is
    # ALSO corrupted (rate 1.0) so the agent fails over to B — and
    # every selection stays bit-identical to the solo plan. Never a
    # wrong plan from corrupt bytes.
    svc_a = replica_a.service
    svc_a.chaos = ServiceChaos(
        ServiceFaultPlan(seed=seed, request_corrupt_rate=1.0),
        clock=clock,
    )
    resyncs_before_corrupt = delta_resyncs()
    fleet_tick("corrupt-delta")
    svc_a.chaos = None
    corrupt_resyncs = delta_resyncs() - resyncs_before_corrupt

    # --- phase 2: wire/HTTP chaos on every agent transport ---
    for chaos in chaos_transports:
        chaos.enabled = True
    for _ in range(8):
        fleet_tick("wire-chaos")
    for chaos in chaos_transports:
        chaos.enabled = False
    # let breaker windows from the chaos phase expire before phase 3
    clock.advance(60.0)

    # --- phase 3: scripted sick-device phase on replica A ---
    svc_a = replica_a.service
    svc_a.chaos = ServiceChaos(
        ServiceFaultPlan(seed=seed, sick_phase=(1, 10**9, 2.0)),
        clock=clock,
    )
    sick_detect_ticks = None
    for n in range(1, 5):
        fleet_tick("sick-phase")
        if (
            sick_detect_ticks is None
            and svc_a.healthz_snapshot()["device"] == "sick"
        ):
            sick_detect_ticks = n
    sick_snapshot = svc_a.healthz_snapshot()
    sick_gauge_during = metrics.service_snapshot()["device_sick"]
    wd = svc_a._devhealth
    sick_detect_batches = wd.detect_streak if wd is not None else -1
    # phase ends: quiesce the latency; hysteresis probes must recover it
    svc_a.chaos.enabled = False
    recovered_after = None
    for n in range(1, 6):
        fleet_tick("recovery")
        if svc_a.healthz_snapshot()["device"] == "ok":
            recovered_after = n
            break
    end_snapshot = svc_a.healthz_snapshot()

    # --- phase 4: graceful kill of replica A, failover, warm restart ---
    replica_a.graceful_shutdown()
    for _ in range(3):
        walls = fleet_tick("replica-kill")
        failover_ms.extend(walls)
    restarted = new_replica(addr_a)
    warmed = list(restarted.service.warmed_buckets)
    # breaker horizons on A expire; agents must return to the primary
    clock.advance(180.0)
    for _ in range(2):
        fleet_tick("replica-restart")
    primary_back = all(
        agent.last_endpoint == f"http://{addr_a}" for agent in agents
    )

    for srv in (replica_b, restarted):
        srv.close()

    m1 = metrics.service_snapshot()
    f1 = flight.RECORDER.counts()

    def fdelta(kind):
        return f1.get(kind, 0) - f0.get(kind, 0)

    fallback_metric = (
        m1["remote_planner_fallback"] - m0["remote_planner_fallback"]
    )
    failover_metric = (
        m1["remote_planner_failover"] - m0["remote_planner_failover"]
    )
    resync_metric = m1["delta_requests"].get("resync", 0) - m0.get(
        "delta_requests", {}
    ).get("resync", 0)
    flight_eq_metrics = (
        fdelta("remote-planner-fallback") == fallback_metric
        and fdelta("failover") == failover_metric
        and fdelta("device-sick") == 1
        and fdelta("device-recovered") == 1
        and fdelta("delta-resync") == resync_metric
    )
    ok = (
        not crashes
        and not mismatches
        and half_close_ok
        and corrupt_resyncs >= 1
        and sick_detect_ticks is not None
        and sick_snapshot.get("device") == "sick"
        and sick_gauge_during == 1.0
        and 0 < sick_detect_batches <= cfg.device_sick_threshold
        and recovered_after is not None
        and end_snapshot.get("device") == "ok"
        and m1["device_sick"] == 0.0
        and failover_metric > 0
        and flight_eq_metrics
        and bool(warmed)
        and primary_back
    )
    return {
        "ok": ok,
        "n_agents": n_agents,
        "ticks": tick_no[0],
        "crashes": crashes,
        "mismatches": mismatches,
        "sick_detect_ticks": sick_detect_ticks,
        "sick_detect_batches": sick_detect_batches,
        "recovered_after_ticks": recovered_after,
        "failover_ms": round(
            float(np.median(failover_ms)) if failover_ms else 0.0, 2
        ),
        "failovers": int(failover_metric),
        "fallbacks": int(fallback_metric),
        "flight_eq_metrics": flight_eq_metrics,
        "flight_deltas": {
            k: fdelta(k)
            for k in ("remote-planner-fallback", "failover",
                      "device-sick", "device-recovered", "service-shed",
                      "delta-resync")
        },
        "corrupt_resyncs": int(corrupt_resyncs),
        "half_close_strikes": int(half_close_strikes),
        "half_close_reconnects": int(half_close_reconnects),
        "half_close_ok": half_close_ok,
        "delta_resyncs": int(resync_metric),
        "warmed_buckets": warmed,
        "primary_back": primary_back,
        "device_end_state": end_snapshot.get("device"),
    }


def run_fleet_chaos(args, metric: str, unit: str) -> int:
    """CI smoke of the fleet failure domains (``make fleet-chaos-smoke``):
    see :func:`fleet_chaos_smoke` for the scripted phases and the
    acceptance accounting."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    result = fleet_chaos_smoke(n_agents=max(4, args.tenants), seed=args.seed)
    detail = (
        result["crashes"] or result["mismatches"]
        or {"flight_deltas": result["flight_deltas"]}
    )
    print(
        f"fleet-chaos-smoke: {result['n_agents']} agents x 2 replicas, "
        f"{result['ticks']} ticks  "
        f"sick_detect={result['sick_detect_ticks']} tick(s)/"
        f"{result['sick_detect_batches']} batch(es)  "
        f"recovered_after={result['recovered_after_ticks']}  "
        f"failovers={result['failovers']} "
        f"(median {result['failover_ms']} ms)  "
        f"fallbacks={result['fallbacks']}  "
        f"half_close={result['half_close_strikes']} strikes/"
        f"{result['half_close_reconnects']} reconnects "
        f"({'OK' if result['half_close_ok'] else 'FAIL'})  "
        f"resyncs={result['delta_resyncs']} "
        f"(corrupt phase {result['corrupt_resyncs']})  "
        f"warmed={result['warmed_buckets']}  "
        f"flight==metrics: {result['flight_eq_metrics']}  "
        f"-> {'OK' if result['ok'] else 'FAIL: %s' % detail}",
        file=sys.stderr,
    )
    emit(
        {
            "metric": metric,
            "value": result["failover_ms"],
            "unit": unit,
            "n_agents": result["n_agents"],
            "ticks": result["ticks"],
            "failover_ms": result["failover_ms"],
            "sick_detect_ticks": result["sick_detect_ticks"],
            "sick_detect_batches": result["sick_detect_batches"],
            "recovered_after_ticks": result["recovered_after_ticks"],
            "failovers": result["failovers"],
            "fallbacks": result["fallbacks"],
            "delta_resyncs": result["delta_resyncs"],
            "corrupt_resyncs": result["corrupt_resyncs"],
            "half_close_strikes": result["half_close_strikes"],
            "half_close_reconnects": result["half_close_reconnects"],
            "flight_eq_metrics": result["flight_eq_metrics"],
            "warmed_buckets": len(result["warmed_buckets"]),
            "ok": result["ok"],
        }
    )
    return 0 if result["ok"] else 1


def _fleet_twin_report(result: dict, label: str) -> None:
    curve = result.get("capacity_curve", [])
    occ = "/".join("%.2f" % r["occupancy"] for r in curve)
    p99 = "/".join("%.0f" % r["queue_wait_p99_ms"] for r in curve)
    storm = result.get("resync_storm") or {}
    storm_note = ""
    if storm:
        storm_note = (
            f"restart-storm[affected={storm.get('affected')} "
            f"resyncs={storm.get('resyncs_server')}=="
            f"{storm.get('resyncs_twins')} "
            f"sheds={storm.get('resync_sheds')} "
            f"ingest_max={storm.get('ingest_inflight_max')}/"
            f"{storm.get('ingest_cap')} "
            f"converged={storm.get('converge_ticks')}t/"
            f"{storm.get('converge_s')}s "
            f"p99={storm.get('p99_unaffected_ms')}ms]  "
        )
    print(
        f"{label}: {result['ever_active']} twins x "
        f"{result['replicas']} replicas, {result['sim_s']:.0f}s sim in "
        f"{result['wall_s']:.1f}s wall  occ={occ}  p99={p99}ms  "
        f"capacity@{result['slo_ms']:.0f}ms="
        f"{result['capacity_tenants_per_device_at_slo']} tenants/device  "
        f"jain={result['jain_fleet']}  "
        f"verified={result['verified_selections']}  "
        f"failovers={result['failovers_metric']}=="
        f"{result['failovers_flight']}  "
        f"sheds={result['shed_total_metric']}=="
        f"{result['shed_total_flight']}  "
        f"{storm_note}"
        f"-> {'OK' if result['ok'] else 'FAIL: %s' % result['failures']}",
        file=sys.stderr,
    )


def _twin_calibration_arg(args) -> dict | None:
    path = getattr(args, "twin_calibration", "")
    return load_twin_calibration(path) if path else None


def run_fleet_twin_smoke(args, metric: str, unit: str) -> int:
    """CI smoke of the fleet twin (``make fleet-twin-smoke``): 64
    heterogeneous tenant twins x 2 real-HTTP service replicas through
    ~20 simulated minutes (4 occupancy phases, one spot storm and one
    replica kill/restart per phase), plus the deterministic shed-edge
    induction that drives every labeled ``service_admission_shed_total``
    reason through a live replica. Fails unless zero twin crashes, every
    spot-checked selection is bit-identical to the solo in-process plan,
    the capacity curve is monotone and non-degenerate, and flight-
    recorder deltas equal metric deltas for both the failover and the
    per-reason shed edges."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    from k8s_spot_rescheduler_tpu.bench.fleet_twin import (
        fleet_twin, induce_shed_edges,
    )
    result = fleet_twin(
        n_twins=max(16, min(64, args.tenants if args.tenants > 4 else 64)),
        n_replicas=2, sim_s=1200.0, seed=args.seed, slo_ms=3000.0,
        cost_base_s=0.3, cost_per_lane_s=0.4, max_wall_s=45.0,
        calibration=_twin_calibration_arg(args),
    )
    edges = induce_shed_edges(seed=args.seed)
    ok = bool(result["ok"] and edges["ok"])
    _fleet_twin_report(result, "fleet-twin-smoke")
    print(
        f"fleet-twin-smoke shed edges: metric={edges['metric_delta']} "
        f"flight={edges['flight_delta']} "
        f"-> {'OK' if edges['ok'] else 'FAIL: %s' % edges['failures']}",
        file=sys.stderr,
    )
    emit(
        {
            "metric": metric,
            "value": result["capacity_tenants_per_device_at_slo"],
            "unit": unit,
            "n_twins": result["n_twins"],
            "ever_active": result["ever_active"],
            "replicas": result["replicas"],
            "sim_s": result["sim_s"],
            "wall_s": result["wall_s"],
            "slo_ms": result["slo_ms"],
            "capacity_curve": result["capacity_curve"],
            "failover_convexity": result["failover_convexity"],
            "jain_fleet": result["jain_fleet"],
            "compile": result["compile"],
            "sheds_by_reason": result["sheds_by_reason"],
            "shed_edge_metric_delta": edges["metric_delta"],
            "shed_edge_flight_delta": edges["flight_delta"],
            "failovers": result["failovers_flight"],
            "verified_selections": result["verified_selections"],
            "mismatches": result["mismatches"],
            "crashes": result["crashes"],
            "resyncs_server": result["resyncs_server"],
            "resyncs_twins": result["resyncs_twins"],
            "resync_storm": result["resync_storm"],
            "resync_storm_converge_ticks": result[
                "resync_storm_converge_ticks"
            ],
            "resync_sheds": result["resync_sheds"],
            "storm_p99_wait_ms": result["storm_p99_wait_ms"],
            "ok": ok,
            "failures": result["failures"] + edges["failures"],
        }
    )
    return 0 if ok else 1


def run_fleet_twin(args, metric: str, unit: str) -> int:
    """Full fleet twin (``python bench.py --fleet-twin``): 512
    heterogeneous tenant twins x 2 real-HTTP replicas through one
    simulated hour on the shared virtual clock — the capacity-planning
    artifact (tenants/device at the queue-wait SLO across 4 occupancy
    points, failover convexity, Jain fairness) in a few minutes of CPU
    wall. Same invariants as the smoke, at fleet scale."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    from k8s_spot_rescheduler_tpu.bench.fleet_twin import fleet_twin
    result = fleet_twin(
        n_twins=max(512, args.tenants if args.tenants > 4 else 512),
        n_replicas=2, sim_s=3600.0, seed=args.seed, slo_ms=1000.0,
        cost_base_s=0.05, cost_per_lane_s=0.05, max_wall_s=280.0,
        calibration=_twin_calibration_arg(args),
    )
    _fleet_twin_report(result, "fleet-twin")
    out = dict(result)
    out.update({"metric": metric, "value":
                result["capacity_tenants_per_device_at_slo"],
                "unit": unit})
    emit(out)
    return 0 if result["ok"] else 1


def run_storm_smoke(args, metric: str, unit: str) -> int:
    """Resync-storm CI smoke (``make storm-smoke``): >= 32 tenant
    twins x 2 real-HTTP replicas on the virtual clock, ramped briefly
    and then hit with the dedicated restart storm — one replica killed
    and warm-restarted under full load, wiping its tenant cache.
    Fails unless the fleet SHEDS instead of collapsing: concurrent
    full-pack ingests stay under the admission cap, unaffected tenants
    hold their queue-wait SLO, no tenant resyncs twice, the fleet
    converges in O(affected) full packs, every selection stays
    bit-identical, and ALL shed/resync ledgers (labeled metrics vs
    flight events, server vs twins) agree — plus the deterministic
    per-reason shed-edge induction, which guarantees the resync-storm
    edge fires at least once regardless of storm timing."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    from k8s_spot_rescheduler_tpu.bench.fleet_twin import (
        fleet_twin, induce_shed_edges,
    )
    result = fleet_twin(
        n_twins=max(32, min(64, args.tenants if args.tenants > 4 else 32)),
        n_replicas=2, sim_s=600.0, seed=args.seed, phases=2,
        slo_ms=3000.0, cost_base_s=0.3, cost_per_lane_s=0.4,
        max_wall_s=45.0, resync_storm_s=300.0,
        calibration=_twin_calibration_arg(args),
    )
    edges = induce_shed_edges(seed=args.seed)
    ok = bool(
        result["ok"] and edges["ok"] and result.get("resync_storm")
    )
    _fleet_twin_report(result, "storm-smoke")
    print(
        f"storm-smoke shed edges: metric={edges['metric_delta']} "
        f"flight={edges['flight_delta']} "
        f"-> {'OK' if edges['ok'] else 'FAIL: %s' % edges['failures']}",
        file=sys.stderr,
    )
    storm = result.get("resync_storm") or {}
    emit(
        {
            "metric": metric,
            "value": result["resync_storm_converge_ticks"],
            "unit": unit,
            "n_twins": result["n_twins"],
            "replicas": result["replicas"],
            "sim_s": result["sim_s"],
            "wall_s": result["wall_s"],
            "slo_ms": result["slo_ms"],
            "resync_storm": storm,
            "resync_storm_converge_ticks": result[
                "resync_storm_converge_ticks"
            ],
            "resync_sheds": result["resync_sheds"],
            "storm_p99_wait_ms": result["storm_p99_wait_ms"],
            "resyncs_server": result["resyncs_server"],
            "resyncs_twins": result["resyncs_twins"],
            "wire_bytes_sent": result["wire_bytes_sent"],
            "full_posts": result["full_posts"],
            "delta_posts": result["delta_posts"],
            "sheds_by_reason": result["sheds_by_reason"],
            "shed_edge_metric_delta": edges["metric_delta"],
            "shed_edge_flight_delta": edges["flight_delta"],
            "verified_selections": result["verified_selections"],
            "mismatches": result["mismatches"],
            "crashes": result["crashes"],
            "ok": ok,
            "failures": result["failures"] + edges["failures"] + (
                [] if result.get("resync_storm")
                else ["restart-storm phase did not run"]
            ),
        }
    )
    return 0 if ok else 1


def run_chaos(args, metric: str, unit: str) -> int:
    """Chaos soak (``make chaos-smoke``): N control-loop ticks over a
    fixture-scale fake cluster behind the seeded fault-injection client
    (io/chaos.py heavy profile + scripted 429s + one mid-drain
    interrupt + two scripted planner crashes). CPU-only by construction
    (numpy planner — the soak proves the CONTROL PLANE, which is
    solver-independent). Fails unless every robustness invariant holds:
    the loop never crashes, no orphaned ToBeDeleted taint survives at
    end-state, no node is drained twice without re-observation, and at
    least one drain lands after the faults clear — and unless the
    flight recorder captured every degradation: each contained planner
    crash and each breaker engagement appears in the ring with its
    cause and trace ID (counts equal to the independently-maintained
    metric deltas), every auto-dump names a degradation kind, and no
    dump fires outside one."""
    import dataclasses as _dc
    import json as _json
    import tempfile as _tempfile

    from k8s_spot_rescheduler_tpu.io.chaos import (
        ChaosClusterClient,
        ChaosInterrupt,
        FaultPlan,
    )
    from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
    from k8s_spot_rescheduler_tpu.loop import flight
    from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
    from k8s_spot_rescheduler_tpu.metrics import registry as _metrics
    from k8s_spot_rescheduler_tpu.models.cluster import (
        CPU,
        MEMORY,
        PODS,
        NodeSpec,
        OwnerRef,
        PodSpec,
        TO_BE_DELETED_TAINT,
    )
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
    from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    od_labels = {"kubernetes.io/role": "worker"}
    spot_labels = {"kubernetes.io/role": "spot-worker"}

    clock = FakeClock()
    fc = FakeCluster(clock, reschedule_evicted=True)

    def add_node(name, labels, cpu=4000):
        fc.add_node(NodeSpec(
            name=name, labels=dict(labels),
            allocatable={CPU: cpu, MEMORY: 8 * 1024**3, PODS: 110},
        ))

    def add_pod(name, node, cpu=100):
        fc.add_pod(PodSpec(
            name=name, namespace="default", node_name=node,
            requests={CPU: cpu, MEMORY: 64 * 1024**2},
            labels={"app": name},
            owner_refs=[OwnerRef("ReplicaSet", f"{name}-rs")],
        ))

    for i in range(6):
        add_node(f"od-{i}", od_labels)
        add_node(f"spot-{i}", spot_labels)
    for i in range(6):
        for j in range(3):
            add_pod(f"p{i}-{j}", f"od-{i}")

    base = FaultPlan.profile("heavy", args.seed)
    plan = _dc.replace(
        base,
        evict_429={"default/p0-0": 2, "default/churn-1": 1},
        interrupt_on_taint=3,
    )
    chaos = ChaosClusterClient(fc, plan, clock=clock)
    dump_dir = _tempfile.mkdtemp(prefix="chaos-flight-")
    config = ReschedulerConfig(
        solver="numpy",
        housekeeping_interval=10.0,
        node_drain_delay=30.0,
        pod_eviction_timeout=60.0,
        eviction_retry_time=5.0,
        flight_dump_dir=dump_dir,
        # per-tick path pinned (the documented opt-out): this soak's
        # invariants assert PLAN-path crash containment — flight ==
        # metric deltas for planner-fallback — and a schedule-path
        # crash deliberately degrades WITHOUT a fallback event
        # (PR 11: nothing lost but the fetch amortization)
        schedule_horizon=0,
    )

    class _ScriptedCrashPlanner:
        """Planner wrapper raising on scripted tick indices: the
        injected planner-crash half of the soak — containment (PR 4)
        already catches it; the flight assertions below prove the
        recorder reconstructs it. plan_async is defined explicitly (a
        __getattr__ forward would hand the loop the INNER planner's,
        bypassing the crash script — the _HintingPlanner lesson)."""

        def __init__(self, inner, crash_calls):
            self._inner = inner
            self._crash_calls = set(crash_calls)
            self._calls = 0

        def _maybe_crash(self):
            self._calls += 1
            if self._calls in self._crash_calls:
                raise RuntimeError(
                    "chaos: scripted planner crash #%d" % self._calls
                )

        def plan(self, observation, pdbs):
            self._maybe_crash()
            return self._inner.plan(observation, pdbs)

        def plan_async(self, observation, pdbs):
            self._maybe_crash()
            return self._inner.plan_async(observation, pdbs)

        def plan_schedule(self, observation, pdbs):
            # schedules are the default path now: the scripted crash
            # must land on whichever plan entry point the tick uses
            # (same lesson as plan_async above)
            self._maybe_crash()
            return self._inner.plan_schedule(observation, pdbs)

        def __getattr__(self, name):
            return getattr(self._inner, name)

    planner = _ScriptedCrashPlanner(SolverPlanner(config), {5, 40})

    def make_controller():
        return Rescheduler(chaos, planner, config, clock=clock, recorder=chaos)

    n_ticks = int(args.chaos_ticks)
    quiesce_at = (n_ticks * 7) // 8
    flight.RECORDER.reset()
    metrics_before = _metrics.robustness_snapshot()
    r = make_controller()
    t0 = time.perf_counter()
    interrupts = completed = churn = 0
    drains = []
    fallbacks = 0
    breaker_edges = 0
    breaker_was_engaged = False
    violations = []
    for i in range(n_ticks):
        clock.sleep(config.housekeeping_interval)
        if i == quiesce_at:
            # before the tick (NOT after): the tick may raise the
            # scripted ChaosInterrupt, whose handler continues the loop
            # and would skip a post-tick quiesce landing on this index
            chaos.enabled = False
        if i % 15 == 0:
            add_pod(f"churn-{churn}", f"od-{churn % 6}")
            churn += 1
        occupied = {
            name for name in fc.nodes
            if name.startswith("od-") and fc.list_pods_on_node(name)
        }
        try:
            result = r.tick()
        except ChaosInterrupt:
            interrupts += 1
            breaker_was_engaged = False  # fresh controller, fresh streak
            r = make_controller()
            continue
        except Exception as err:  # noqa: BLE001 — the invariant itself
            # Exception, not BaseException: ChaosInterrupt (the only
            # BaseException the soak expects) is handled above, and a
            # Ctrl-C/SystemExit must propagate, not print a bogus FAIL
            violations.append(f"tick {i} crashed the loop: {err!r}")
            break
        # independent count of breaker ENGAGE edges (entering the
        # engaged state), diffed against the flight events below
        engaged = r.breaker_engaged
        if engaged and not breaker_was_engaged:
            breaker_edges += 1
        breaker_was_engaged = engaged
        completed += 1
        # the no-double-drain-without-re-observation invariant: every
        # drained node was observed WITH PODS at this tick's start (a
        # node drained on a stale/duplicated view would be empty here)
        if not set(result.drained) <= occupied:
            violations.append(
                f"tick {i} drained unobserved/empty node(s): "
                f"{sorted(set(result.drained) - occupied)}"
            )
        if result.planner_fallback:
            fallbacks += 1
        drains.extend((i, n) for n in result.drained)
    orphans = [
        node.name
        for node in fc.nodes.values()
        if any(t.key == TO_BE_DELETED_TAINT for t in node.taints)
    ]
    if orphans:
        violations.append(f"orphaned ToBeDeleted taints at end: {orphans}")
    if interrupts != 1:
        violations.append(f"expected 1 mid-drain interrupt, saw {interrupts}")
    if not any(i >= quiesce_at for i, _ in drains):
        violations.append("no drain landed after faults cleared")

    # --- flight-recorder capture (docs/OBSERVABILITY.md) ---
    metric_fallbacks = int(
        _metrics.robustness_snapshot()["planner_fallback"]
        - metrics_before["planner_fallback"]
    )
    fl_counts = flight.RECORDER.counts()
    if metric_fallbacks < 2:
        violations.append(
            "scripted planner crashes never reached containment "
            f"(planner_fallback delta {metric_fallbacks} < 2)"
        )
    if fl_counts.get("planner-fallback", 0) != metric_fallbacks:
        violations.append(
            f"flight ring holds {fl_counts.get('planner-fallback', 0)} "
            f"planner-fallback events but the metric counted "
            f"{metric_fallbacks} — the recorder missed a degradation"
        )
    if fl_counts.get("breaker-engage", 0) != breaker_edges:
        violations.append(
            f"flight ring holds {fl_counts.get('breaker-engage', 0)} "
            f"breaker-engage events but the loop engaged "
            f"{breaker_edges} time(s)"
        )
    for ev in flight.RECORDER.events():
        if ev["kind"] in ("planner-fallback", "breaker-engage"):
            if not ev["cause"] or not ev["trace_id"]:
                violations.append(
                    f"flight event {ev['kind']} missing its cause/trace "
                    f"id: {ev!r}"
                )
                break
    # the scripted crash's cause chain must survive into the ring
    fb_events = flight.RECORDER.events("planner-fallback")
    if fb_events and not any(
        "scripted planner crash" in ev["cause"] for ev in fb_events
    ):
        violations.append(
            "no planner-fallback event carries the injected crash cause"
        )
    # every auto-dump names a degradation kind; clean ticks never dump
    dump_files = sorted(
        f for f in os.listdir(dump_dir) if f.endswith(".json")
    )
    degr_total = sum(
        fl_counts.get(k, 0) for k in flight.DEGRADATION_KINDS
    )
    if degr_total and not dump_files:
        violations.append(
            f"{degr_total} degradation event(s) fired but no flight "
            "dump was written"
        )
    if not degr_total and dump_files:
        violations.append(
            f"{len(dump_files)} dump(s) written on a clean soak"
        )
    for fname in dump_files:
        with open(os.path.join(dump_dir, fname)) as fh:
            payload = _json.load(fh)
        if payload.get("reason") not in flight.DEGRADATION_KINDS:
            violations.append(
                f"dump {fname} reason {payload.get('reason')!r} is not "
                "a degradation kind"
            )
            break
    wall = time.perf_counter() - t0
    ok = not violations
    print(
        f"chaos-soak: {completed} ticks ({interrupts} restart) "
        f"{len(drains)} drains ({sum(1 for i, _ in drains if i >= quiesce_at)} "
        f"after quiesce)  faults={sum(chaos.stats.values())} "
        f"wall={wall:.1f}s  -> {'OK' if ok else 'FAIL: ' + '; '.join(violations)}",
        file=sys.stderr,
    )
    emit(
        {
            "metric": metric,
            "value": int(completed),
            "unit": unit,
            "vs_baseline": None,
            "ticks": int(n_ticks),
            "drains": len(drains),
            "drains_after_quiesce": sum(
                1 for i, _ in drains if i >= quiesce_at
            ),
            "mid_drain_interrupts": int(interrupts),
            "injected_faults": int(sum(chaos.stats.values())),
            "planner_fallback_ticks": int(fallbacks),
            "flight_planner_fallbacks": int(
                fl_counts.get("planner-fallback", 0)
            ),
            "flight_breaker_engagements": int(
                fl_counts.get("breaker-engage", 0)
            ),
            "flight_dumps": len(dump_files),
            "orphaned_taints_end": len(orphans),
            "wall_s": round(wall, 2),
            "ok": ok,
            **({"violations": violations} if violations else {}),
        }
    )
    return 0 if ok else 1


def watch_soak(
    n_ticks: int = 300,
    seed: int = 0,
    *,
    stall_rate: float = 0.06,
    drop_rate: float = 0.04,
    progress_deadline: float = 120.0,
    staleness_budget: float = 60.0,
    resync_interval: float = 300.0,
):
    """Deterministic freshness-soak core (shared by ``--watch-soak`` and
    tests/test_freshness.py): N control-loop ticks against the scripted
    watch apiserver (io/fakewatch.py) behind the chaos layer, with the
    watchers driven SYNCHRONOUSLY on a virtual clock — open-but-silent
    stalls, random stream drops, two scripted 410-after-resume streams,
    and one injected mirror corruption. Returns (stats, violations):
    ``stats`` carries the metric deltas the acceptance criteria are
    asserted on, ``violations`` the invariant breaches (empty = pass).
    """
    import dataclasses as _dc
    import random as _random

    from k8s_spot_rescheduler_tpu.io.chaos import ChaosClusterClient, FaultPlan
    from k8s_spot_rescheduler_tpu.io.fakewatch import (
        ScriptedWatchSource,
        raw_node,
        raw_pod,
    )
    from k8s_spot_rescheduler_tpu.io.watch import WatchingKubeClusterClient
    from k8s_spot_rescheduler_tpu.loop import flight
    from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
    from k8s_spot_rescheduler_tpu.metrics import registry as metrics
    from k8s_spot_rescheduler_tpu.models.cluster import build_node_map
    from k8s_spot_rescheduler_tpu.models.tensors import pack_cluster
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
    from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    clock = FakeClock(start=1_000_000.0)
    src = ScriptedWatchSource()
    for i in range(4):
        src.objects["nodes"][f"uid-od-{i}"] = raw_node(f"od-{i}", "worker")
    for i in range(8):
        src.objects["nodes"][f"uid-spot-{i}"] = raw_node(
            f"spot-{i}", "spot-worker"
        )
    for i in range(4):
        for j in range(3):
            name = f"p{i}-{j}"
            src.objects["pods"][f"uid-{name}"] = raw_pod(
                name, f"od-{i}", cpu_millis=100 + 50 * j
            )

    plan = FaultPlan(
        seed=seed,
        watch_stall_rate=stall_rate,
        watch_drop_rate=drop_rate,
        watch_410_streams=(9, 57),
    )
    chaos = ChaosClusterClient(src, plan, clock=clock)
    # snapshot BEFORE the seeding relists so the delta accounting below
    # covers every LIST of the run (metrics are process-cumulative);
    # the flight ring resets so its counts diff cleanly against the
    # metric deltas (capture parity is an acceptance criterion)
    flight.RECORDER.reset()
    before = metrics.freshness_snapshot()
    wc = WatchingKubeClusterClient(
        chaos, clock=clock, progress_deadline=progress_deadline,
        wait_fn=clock.sleep,
    )
    wc.start(background=False)

    config = ReschedulerConfig(
        solver="numpy",
        housekeeping_interval=10.0,
        node_drain_delay=120.0,
        pod_eviction_timeout=60.0,
        eviction_retry_time=5.0,
        mirror_staleness_budget=staleness_budget,
        watch_progress_deadline=progress_deadline,
        resync_interval=resync_interval,
    )
    r = Rescheduler(
        wc, SolverPlanner(config), config, clock=clock, recorder=wc
    )
    rng = _random.Random(seed + 1)
    churn_uid = 0

    def churn_once():
        nonlocal churn_uid
        k = rng.random()
        pods = list(src.objects["pods"].values())
        if k < 0.45 or not pods:
            name = f"churn-{churn_uid}"
            churn_uid += 1
            src.push("pods", "ADDED", raw_pod(
                name, f"od-{rng.randrange(4)}",
                cpu_millis=rng.choice((50, 100, 150, 200)),
            ))
        elif k < 0.8:
            src.push("pods", "DELETED", rng.choice(pods))
        else:
            obj = rng.choice(pods)
            node = obj["spec"].get("nodeName", "")
            if node:
                src.push("pods", "MODIFIED", raw_pod(
                    obj["metadata"]["name"], node,
                    cpu_millis=rng.choice((75, 125, 250)),
                ))

    _CORRUPT_CPU = 3333  # impossible allocatable: unambiguous marker
    corrupt_key = "uid-spot-7"  # a spot node: never drained or deleted,
    # so only a store replace (audit heal or protocol relist) can fix it

    def corrupt_mirror() -> bool:
        # poke the mirror BEHIND the watch stream's back: the object
        # store and (via its delta listener) the columnar feed now
        # coherently disagree with the cluster — exactly the failure
        # only the anti-entropy audit can see
        node = dict(wc.nodes.snapshot_items()).get(corrupt_key)
        if node is None:
            return False
        wc.nodes.upsert(corrupt_key, _dc.replace(
            node, allocatable={**node.allocatable, "cpu": _CORRUPT_CPU}
        ))
        return True

    def mirror_corrupted() -> bool:
        node = dict(wc.nodes.snapshot_items()).get(corrupt_key)
        return (
            node is not None
            and node.allocatable.get("cpu") == _CORRUPT_CPU
        )

    corrupt_at = n_ticks // 2
    quiesce_at = (n_ticks * 7) // 8
    corrupt_wall = heal_wall = None
    completed = 0
    drains = []
    violations = []
    for i in range(n_ticks):
        clock.sleep(config.housekeeping_interval)
        if i == quiesce_at:
            chaos.enabled = False
        for _ in range(rng.randrange(0, 3)):
            churn_once()
        if i % 7 == 0:
            src.bookmark("pods")
            src.bookmark("nodes")
        if i == corrupt_at and corrupt_mirror():
            corrupt_wall = clock.wall()
        for w in wc._watchers:
            w.step()
        try:
            result = r.tick()
        except Exception as err:  # noqa: BLE001 — the invariant itself
            violations.append(f"tick {i} crashed the loop: {err!r}")
            break
        completed += 1
        drains.extend((i, n) for n in result.drained)
        if corrupt_wall is not None and heal_wall is None \
                and not mirror_corrupted():
            heal_wall = clock.wall()

    # let the streams drain fully, then check end-state invariants
    for w in wc._watchers:
        w.step()
    snap = metrics.freshness_snapshot()
    d = {k: snap[k] - before[k] for k in snap if k in before}

    if completed != n_ticks:
        violations.append(f"only {completed}/{n_ticks} ticks completed")
    if d["watch_stalls"] < 1:
        violations.append("no open-but-silent stall was ever detected")
    if chaos.stats.get("watch_410", 0) != 2:
        violations.append(
            f"expected 2 scripted 410 streams, saw "
            f"{chaos.stats.get('watch_410', 0)}"
        )
    if d["freshness_bypass"] < 1:
        violations.append(
            "the freshness gate never engaged the direct-LIST bypass"
        )
    if d["mirror_stale_planned"] != 0:
        violations.append(
            f"{d['mirror_stale_planned']} tick(s) reached the planner "
            "with an over-budget mirror"
        )
    # heal bound: one resync interval, plus one tick's worst-case wall
    # jump — the audit fires at the first TICK past its due time, and a
    # stalled-stream tick advances the virtual clock by a whole read
    # timeout (progress deadline + stall slack) in one jump
    heal_bound = (
        resync_interval + progress_deadline + 30.0
        + config.housekeeping_interval
    )
    if corrupt_wall is None:
        violations.append("mirror corruption was never injected")
    elif heal_wall is None:
        violations.append("injected mirror corruption was never healed")
    elif heal_wall - corrupt_wall > heal_bound:
        violations.append(
            f"corruption healed after {heal_wall - corrupt_wall:.0f}s "
            f"(> one resync interval of {resync_interval:.0f}s plus one "
            "tick's worst-case wall jump)"
        )
    if d["watch_drift"] < 1:
        violations.append(
            "the anti-entropy audit never counted any drift "
            "(watch_drift_total stayed 0 despite the injected corruption)"
        )
    # every full LIST is accounted for: protocol relists (seed / 410 /
    # error recovery) + exactly 3 per anti-entropy audit — a steady-state
    # tick between audits issues NONE (the delta-shaped observe path)
    total_lists = sum(src.list_count.values())
    expected_lists = int(d["watch_relists"] + 3 * d["resync_audits"])
    if total_lists != expected_lists:
        violations.append(
            f"{total_lists} full LISTs issued but only {expected_lists} "
            "accounted to relists/audits — the steady-state tick is not "
            "delta-shaped"
        )
    if d["resync_audits"] < 1:
        violations.append("no anti-entropy audit ever ran")

    # --- flight-recorder capture (docs/OBSERVABILITY.md): every
    # injected stall and every freshness bypass appears in the ring,
    # count-for-count with the independently-updated metrics, each
    # with its cause (and, for in-tick kinds, its trace ID) ---
    fl_counts = flight.RECORDER.counts()
    if fl_counts.get("watch-stall", 0) != int(d["watch_stalls"]):
        violations.append(
            f"flight ring holds {fl_counts.get('watch-stall', 0)} "
            f"watch-stall events but metrics counted "
            f"{int(d['watch_stalls'])}"
        )
    if fl_counts.get("freshness-bypass", 0) != int(d["freshness_bypass"]):
        violations.append(
            f"flight ring holds {fl_counts.get('freshness-bypass', 0)} "
            f"freshness-bypass events but metrics counted "
            f"{int(d['freshness_bypass'])}"
        )
    for ev in flight.RECORDER.events():
        if ev["kind"] in ("watch-stall", "freshness-bypass"):
            if not ev["cause"]:
                violations.append(f"flight event missing cause: {ev!r}")
                break
            if ev["kind"] == "freshness-bypass" and not ev["trace_id"]:
                # bypass fires inside a tick: its trace id must ride
                violations.append(
                    f"freshness-bypass event missing trace id: {ev!r}"
                )
                break
    # no dump dir is configured here, so clean OR degraded ticks alike
    # must write nothing — the ring is the capture surface
    if flight.RECORDER.dump_count() != 0:
        violations.append(
            f"{flight.RECORDER.dump_count()} dump(s) written without a "
            "configured dump dir"
        )

    # parity: the incremental mirror packs bit-identically to a fresh
    # LIST of the same end state (a plan is a pure function of the pack)
    wc.refresh()
    wc.list_unschedulable_pods()
    store = wc.columnar_store(
        ("cpu", "memory"),
        on_demand_label=config.on_demand_node_label,
        spot_label=config.spot_node_label,
    )
    pdbs = wc.list_pdbs()
    col, _ = store.pack(pdbs)
    nodes = src.list_ready_nodes()
    node_map = build_node_map(
        nodes,
        {n.name: src.list_pods_on_node(n.name) for n in nodes},
        on_demand_label=config.on_demand_node_label,
        spot_label=config.spot_node_label,
    )
    obj, _ = pack_cluster(node_map, src.list_pdbs(), resources=("cpu", "memory"))
    mismatch = [
        f for f in obj._fields
        if not np.array_equal(getattr(obj, f), getattr(col, f))
    ]
    if mismatch:
        violations.append(
            f"mirror pack diverges from a fresh LIST on {mismatch}"
        )

    stats = {
        "ticks": completed,
        "drains": len(drains),
        "stalls_detected": int(d["watch_stalls"]),
        "stream_errors": int(d["watch_stream_errors"]),
        "scripted_410s": int(chaos.stats.get("watch_410", 0)),
        "relists": int(d["watch_relists"]),
        "resync_audits": int(d["resync_audits"]),
        "drift_objects_healed": int(d["watch_drift"]),
        "presence_heals": int(d["watch_presence_heals"]),
        "drift_heal_seconds": (
            None if heal_wall is None or corrupt_wall is None
            else round(heal_wall - corrupt_wall, 1)
        ),
        "freshness_bypass_ticks": int(d["freshness_bypass"]),
        "flight_stalls": int(fl_counts.get("watch-stall", 0)),
        "flight_bypasses": int(fl_counts.get("freshness-bypass", 0)),
        "mirror_stale_planned": int(d["mirror_stale_planned"]),
        "full_lists": int(total_lists),
        "direct_bypass_reads": int(src.direct_reads),
        "watch_events_applied": int(d["watch_events"]),
        "mirror_parity": not mismatch,
    }
    return stats, violations


def run_watch_soak(args, metric: str, unit: str) -> int:
    """Freshness soak (``make watch-soak``): seconds of wall clock, no
    devices (numpy planner — the soak proves the OBSERVE plane). Fails
    unless every freshness invariant holds: stalls detected within one
    progress deadline, injected drift healed within one resync
    interval, zero ticks planned from an over-budget mirror, every full
    LIST accounted to a relist or an audit, and end-state mirror/LIST
    pack parity."""
    t0 = time.perf_counter()
    stats, violations = watch_soak(int(args.watch_soak_ticks), args.seed)
    wall = time.perf_counter() - t0
    ok = not violations
    print(
        f"watch-soak: {stats['ticks']} ticks  "
        f"{stats['stalls_detected']} stalls  "
        f"{stats['drift_objects_healed']} drift healed "
        f"({stats['drift_heal_seconds']}s)  "
        f"{stats['freshness_bypass_ticks']} bypassed  "
        f"{stats['full_lists']} LISTs ({stats['resync_audits']} audits)  "
        f"wall={wall:.1f}s  "
        f"-> {'OK' if ok else 'FAIL: ' + '; '.join(violations)}",
        file=sys.stderr,
    )
    emit(
        {
            "metric": metric,
            "value": int(stats["ticks"]),
            "unit": unit,
            "vs_baseline": None,
            "wall_s": round(wall, 2),
            "ok": ok,
            **stats,
            **({"violations": violations} if violations else {}),
        }
    )
    return 0 if ok else 1


def _metric_for(args) -> tuple:
    """(metric name, unit) this invocation will report — known up front so
    failure paths can emit a well-formed JSON line."""
    if args.chaos:
        return "chaos_soak_completed_ticks", "count"
    if args.watch_soak:
        return "watch_soak_completed_ticks", "count"
    if args.smoke:
        return "bench_smoke_delta_upload_bytes", "bytes"
    if args.scale_smoke:
        return "scale_smoke_20x_shape_proof_s", "s"
    if args.serve_smoke:
        return "serve_smoke_agent_plan_ms", "ms"
    if args.sched_smoke:
        return "sched_smoke_fetches_total", "count"
    if args.fleet_chaos:
        return "fleet_chaos_failover_ms", "ms"
    if args.fleet_twin_smoke:
        return "fleet_twin_smoke_capacity_tenants_per_device", "tenants"
    if args.fleet_twin:
        return "fleet_twin_capacity_tenants_per_device", "tenants"
    if args.storm_smoke:
        return "storm_smoke_resync_converge_ticks", "ticks"
    if args.pallas_smoke:
        return "pallas_parity_wall_s", "s"
    if args.carry_wall:
        return (
            "carry_union_wall_ms_config%d_x%g" % (args.config, args.scale),
            "ms",
        )
    if args.quality:
        return "nodes_freed_vs_ilp_oracle_ratio", "ratio"
    if args.quality_boundary:
        return "repair_boundary_chain3_ratio", "ratio"
    if args.chain_depth:
        return "chain_depth_demand_deeper_lanes_organic", "count"
    if args.replay_device_only:
        return "replay_constrained_device_only_ms", "ms"
    if args.quality_scale:
        return (
            "nodes_freed_vs_lp_bound_ratio_config%d" % args.config,
            "ratio",
        )
    if args.config == 5:
        if args.constrained:
            return "replay_constrained_replan_ms_p50_1k_events", "ms"
        return "replay_replan_ms_p50_1k_events", "ms"
    suffix = "_x%g" % args.scale if args.scale != 1.0 else ""
    if args.config in (3, 4):
        return (
            "drain_plan_ms_config%d_50kpods_5knodes%s" % (args.config, suffix),
            "ms",
        )
    return "drain_plan_ms_config%d%s" % (args.config, suffix), "ms"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, default=3)
    ap.add_argument("--repeats", type=int, default=10)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--solver", default=None,
                    choices=["jax", "sharded", "pallas", "numpy"],
                    help="latency benchmarks default to pallas; --quality "
                         "defaults to the numpy oracle (the quality metric "
                         "is solver-independent — the randomized parity "
                         "suites pin all backends to the oracle — and must "
                         "not depend on device availability)")
    ap.add_argument("--quality", action="store_true",
                    help="measure nodes-freed vs ILP oracle across the "
                         "quality configs (balanced + adversarial pools)")
    ap.add_argument("--quality-scale", action="store_true",
                    help="quality at full scale: controller drains to "
                         "exhaustion vs the LP/Hall upper bound (the ILP "
                         "is intractable at config 3/4 scale)")
    ap.add_argument("--replay-device-only", action="store_true",
                    help="harvest a constrained-replay tick shape where "
                         "best-fit + repair actually fire and run the "
                         "pinned device-only chain protocol on it "
                         "(VERDICT r4 #8)")
    ap.add_argument("--harvest-cache", default="",
                    help="with --replay-device-only: reuse/store the "
                         "harvested tick tensors at this .npz path, so a "
                         "sick-backend retry skips the minutes-long host "
                         "replay and goes straight to the device protocol")
    ap.add_argument("--chain-depth", action="store_true",
                    help="chain-depth DEMAND analysis: per organic run, the "
                         "minimum repair depth each drainable lane needed "
                         "(VERDICT r4 #4; chain3 rides along as the "
                         "positive control)")
    ap.add_argument("--quality-boundary", action="store_true",
                    help="document the published repair boundary (two-pod "
                         "interlock pools where shipped < ILP by "
                         "construction; excluded from the headline metric)")
    ap.add_argument("--sweep", type=int, default=1,
                    help="with --quality: run this many consecutive seeds "
                         "and report the worst ratio")
    ap.add_argument("--events", type=int, default=1000,
                    help="event count for --config 5 replay")
    ap.add_argument("--constrained", action="store_true",
                    help="with --config 5: replay the full-predicate "
                         "cluster (taints, affinity groups, PDBs, hard "
                         "spread) and report the stranding invariant")
    ap.add_argument("--scale", type=float, default=1.0,
                    help="multiply the config's node/pod counts (headroom runs)")
    ap.add_argument("--watchdog", type=float, default=1500.0,
                    help="hard wall-clock budget in seconds; 0 disables")
    ap.add_argument("--backend-budget", type=float, default=300.0,
                    help="max seconds spent acquiring a working jax backend")
    ap.add_argument("--probe-timeout", type=float, default=30.0,
                    help="per-attempt backend probe timeout in seconds; "
                         "total probe spend is capped by both this x 4 "
                         "attempts and --backend-budget, and a failed "
                         "verdict is cached for the rest of the run")
    ap.add_argument("--chaos", action="store_true",
                    help="chaos soak (make chaos-smoke): run the control "
                         "loop under the seeded fault-injection client "
                         "(io/chaos.py) and fail unless the robustness "
                         "invariants hold — no loop crash, no orphaned "
                         "ToBeDeleted taint at end-state, drains resume "
                         "once faults clear")
    ap.add_argument("--chaos-ticks", type=int, default=300,
                    help="ticks of the --chaos soak (>=300 for the "
                         "acceptance run)")
    ap.add_argument("--watch-soak", action="store_true",
                    help="freshness soak (make watch-soak): drive the "
                         "watch protocol synchronously on a virtual "
                         "clock under stalls, drops, scripted 410s and "
                         "one mirror corruption; fail unless the "
                         "freshness invariants hold (stall detected, "
                         "drift healed within one resync interval, zero "
                         "stale-planned ticks, delta-shaped steady "
                         "state, mirror/LIST pack parity)")
    ap.add_argument("--watch-soak-ticks", type=int, default=300,
                    help="ticks of the --watch-soak run (>=300 for the "
                         "acceptance run)")
    ap.add_argument("--serve-smoke", action="store_true",
                    help="CI smoke (make serve-smoke): N synthetic "
                         "tenant agents against an in-process planner "
                         "service over HTTP; fails unless every "
                         "tenant's selection is bit-identical to its "
                         "solo in-process plan and >=2 tenants shared "
                         "one batched solve")
    ap.add_argument("--tenants", type=int, default=4,
                    help="tenant count for --serve-smoke (>=4 for the "
                         "acceptance run)")
    ap.add_argument("--sched-smoke", action="store_true",
                    help="CI smoke (make sched-smoke): the drain-"
                         "schedule path on the numpy oracle parity "
                         "path — local drains + fetch bound, churn "
                         "invalidation with flight==metric parity, "
                         "wire bit-identity through a real service, "
                         "and failover with a schedule in flight")
    ap.add_argument("--schedule-horizon", type=int, default=32,
                    help="drain-schedule horizon for --quality-scale "
                         "(steps per planner fetch; 0 disables the "
                         "schedule path and re-measures the per-drain-"
                         "fetch baseline)")
    ap.add_argument("--fleet-chaos", action="store_true",
                    help="CI smoke (make fleet-chaos-smoke): 4 agents x "
                         "2 service replicas on a virtual clock under "
                         "seeded wire/HTTP faults, one scripted "
                         "sick-device phase and one graceful replica "
                         "kill + warm restart; fails unless zero agent "
                         "crashes, every selection bit-identical to the "
                         "solo in-process plan, detection/recovery "
                         "edges fire, and flight deltas == metric "
                         "deltas")
    ap.add_argument("--fleet-twin-smoke", action="store_true",
                    help="CI smoke (make fleet-twin-smoke): 64 tenant "
                         "twins x 2 real-HTTP replicas through ~20 "
                         "simulated minutes (storms, replica kills, "
                         "join/leave churn) plus deterministic shed-"
                         "edge induction; fails unless zero crashes, "
                         "bit-identical spot checks, a monotone non-"
                         "degenerate capacity curve, and flight==metric "
                         "for failover and every shed reason")
    ap.add_argument("--fleet-twin", action="store_true",
                    help="full fleet twin: 512 tenant twins x 2 real-"
                         "HTTP replicas through 1 simulated hour on the "
                         "virtual clock; emits the capacity-planning "
                         "curve (tenants/device at the queue-wait SLO), "
                         "failover convexity and Jain fairness")
    ap.add_argument("--storm-smoke", action="store_true",
                    help="CI smoke (make storm-smoke): >=32 tenant "
                         "twins x 2 real-HTTP replicas; one replica is "
                         "killed and warm-restarted under full load "
                         "(tenant cache wiped) — fails unless the "
                         "resync admission class sheds instead of "
                         "collapsing: bounded concurrent ingests, no "
                         "tenant resyncing twice, unaffected tenants "
                         "holding the SLO, O(affected) full-pack "
                         "convergence, and all ledgers in parity")
    ap.add_argument("--twin-calibration", default="",
                    help="bench JSON-lines file whose --carry-wall rows "
                         "carry twin_calibration tables (bucket key -> "
                         "measured solve_s); fleet twin runs then charge "
                         "the modeled device MEASURED per-batch seconds "
                         "for those buckets instead of the synthetic "
                         "base+per-lane cost line")
    ap.add_argument("--smoke", action="store_true",
                    help="CI smoke (make bench-smoke): tiny CPU-only "
                         "cluster, 5 ticks through the production "
                         "incremental pipeline; asserts the delta tick "
                         "ships fewer bytes than the first full pack")
    ap.add_argument("--scale-smoke", action="store_true",
                    help="shape-only 20x proof (make scale-smoke): the "
                         "dispatch ladder decision, estimator breakdown "
                         "and a jaxpr trace at the 1M-pod/100k-node "
                         "shapes — repair must stay live on the carry-"
                         "streamed tier under the v5e budget; no device "
                         "solve")
    ap.add_argument("--pallas-smoke", action="store_true",
                    help="CI smoke (make pallas-smoke): the fused elect-"
                         "then-commit Pallas stream kernel in interpret "
                         "mode vs the XLA carry-streamed step vs the host "
                         "oracle, bit-identical across >=3 chunk counts "
                         "on CPU")
    ap.add_argument("--carry-wall", action="store_true",
                    help="measured wall clock of the carry-streamed union "
                         "program (the tier the ladder keeps repair live "
                         "on past the wide carry bound) at --config x "
                         "--scale on the reachable backend; the JSON row "
                         "self-labels via the backend attestation")
    ap.add_argument("--carry-chunks", type=int, default=0,
                    help="with --carry-wall: pin the carry chunk count "
                         "(0 = the 20x ladder verdict's count)")
    ap.add_argument("--no-cpu-fallback", action="store_true",
                    help="fail (with a JSON error line) instead of running "
                         "on CPU when the TPU backend never comes up")
    args = ap.parse_args()

    metric, unit = _metric_for(args)
    if args.watchdog > 0:
        start_watchdog(args.watchdog, metric, unit)

    try:
        return _dispatch(ap, args, metric, unit)
    except SystemExit:
        raise
    except BaseException:
        emit_error(metric, unit, traceback.format_exc())
        return 1


def _dispatch(ap, args, metric: str, unit: str) -> int:
    if args.chaos:
        return run_chaos(args, metric, unit)
    if args.watch_soak:
        return run_watch_soak(args, metric, unit)
    if args.smoke:
        return run_smoke(args, metric, unit)
    if args.scale_smoke:
        return run_scale_smoke(args, metric, unit)
    if args.serve_smoke:
        return run_serve_smoke(args, metric, unit)
    if args.sched_smoke:
        return run_sched_smoke(args, metric, unit)
    if args.fleet_chaos:
        return run_fleet_chaos(args, metric, unit)
    if args.fleet_twin_smoke:
        return run_fleet_twin_smoke(args, metric, unit)
    if args.fleet_twin:
        return run_fleet_twin(args, metric, unit)
    if args.storm_smoke:
        return run_storm_smoke(args, metric, unit)
    if args.pallas_smoke:
        return run_pallas_smoke(args, metric, unit)
    if args.carry_wall:
        return run_carry_wall(args, metric, unit)
    if args.quality:
        return run_quality(
            args.seed, sweep=args.sweep, solver=args.solver or "numpy"
        )
    if args.quality_boundary:
        return run_quality_boundary(args.seed, sweep=args.sweep)
    if args.chain_depth:
        return run_chain_depth(args.seed, sweep=args.sweep,
                               n_events=args.events)
    if args.replay_device_only:
        return run_replay_device_only(args)
    if args.quality_scale:
        # host-side controller + solver at scale; the jax CPU/device solver
        # drives the multi-drain exhaustion run
        args.solver = args.solver or "jax"
        platform, attempts, err = acquire_backend(
            budget_s=args.backend_budget,
            probe_timeout_s=args.probe_timeout,
            cache=True,
        )
        note = None
        if platform is None:
            if args.no_cpu_fallback:
                emit_error(
                    metric, unit,
                    f"no usable jax backend after {attempts} probes: {err}",
                )
                return 1
            note = (
                f"tpu backend unavailable after {attempts} probes; ran on CPU"
            )
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
            if args.solver == "pallas":
                args.solver = "jax"
            if args.scale == 1.0:
                # exhaustion = one solve per drain; full config-3 scale on
                # CPU is ~1k x seconds — scale down, and say so
                args.scale = 0.2
                note += "; auto-scaled problem to 0.2x"
        return run_quality_scale(args, metric, unit, note)

    args.solver = args.solver or "pallas"
    if args.solver == "numpy":
        ap.error("--solver numpy is the host oracle; use it with --quality "
                 "(the latency benchmark measures the device solvers)")

    # Device paths (latency + replay): prove the backend is reachable from
    # a killable subprocess BEFORE this process commits to a jax init.
    platform, attempts, err = acquire_backend(
        budget_s=args.backend_budget,
        probe_timeout_s=args.probe_timeout,
        cache=True,
    )
    backend_note = None
    if platform is None:
        if args.no_cpu_fallback:
            emit_error(
                metric, unit,
                f"no usable jax backend after {attempts} probes: {err}",
            )
            return 1
        backend_note = (
            f"tpu backend unavailable after {attempts} probes "
            f"({(err or '').splitlines()[-1] if err else '?'}); ran on CPU"
        )
        # The site customization snapshots JAX_PLATFORMS at interpreter
        # start, so the env var alone is ignored by now — the config
        # update after import is what actually reroutes to CPU.
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        args.repeats = min(args.repeats, 3)
        if args.solver == "pallas":
            args.solver = "jax"  # interpret-mode pallas is unusable at scale
        print(f"FALLBACK: {backend_note}", file=sys.stderr)
    else:
        print(
            f"backend ready: {platform} (probe attempts: {attempts})",
            file=sys.stderr,
        )
        if platform.startswith("cpu") and args.solver == "pallas":
            # a healthy probe can still be CPU-only (no accelerator in
            # the environment at all): interpret-mode pallas is unusable
            # at bench scale there, same downgrade as the fallback path
            args.solver = "jax"

    if args.config == 5:
        return run_replay_bench(
            args.seed, args.events, note=backend_note,
            constrained=args.constrained,
        )
    return _run_latency(args, metric, unit, backend_note)


def _run_latency(args, metric: str, unit: str, backend_note) -> int:
    import jax

    from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS

    spec = CONFIGS[args.config]
    if args.scale != 1.0:
        spec = _scaled_spec(spec, args.scale)
    # pack_repeats: the parsed pack_ms is the observe+pack MEDIAN
    # (VERDICT item 7) — a single sample rides cold caches
    packed, _, pack_s, client, store, pdbs = build_problem(
        args.config, args.seed, spec=spec, pack_repeats=5
    )

    # single-chip HBM guard — the SAME dispatch ladder the production
    # planner runs (solver/memory.pick_tier): past the budget with a mesh
    # available, the solve reroutes down the tiers (cand-sharded →
    # chunked repair → carry-streamed narrow → 2-D); with ONE chip it
    # proceeds to the backend's honest OOM, annotated with the designed
    # answer.
    from k8s_spot_rescheduler_tpu.solver import carry as solver_carry
    from k8s_spot_rescheduler_tpu.solver import memory as solver_memory

    shapes = solver_memory.packed_shapes(packed)
    hbm_est = solver_memory.estimate_union_hbm_bytes(*shapes)
    hbm_budget = solver_memory.device_hbm_budget()
    n_devices = len(jax.devices())
    layout = solver_carry.carry_layout(packed)
    tier = solver_memory.pick_tier(
        *shapes,
        n_devices=n_devices,
        budget_bytes=None,
        wants_repair=True,
        carry_plane_bytes=solver_carry.plane_bytes(
            layout, shapes[3], shapes[5]
        ),
    )
    past_chip = tier.kind != "single" or hbm_est > hbm_budget
    scale_note = None
    # the union program the bench EXECUTES when a cand tier won the
    # ladder (repair live — possibly carry-streamed); None = the plain
    # solver path below (single-chip, explicit --solver sharded, or the
    # 2-D verdict)
    union_override = None
    # the tier the emitted carry_chunks/carry_bytes/repair_unavailable
    # keys describe — always the EXECUTED program, never a hypothetical
    executed_tier = tier
    if past_chip:
        scale_note = (
            f"problem est {hbm_est / 1e9:.1f} GB exceeds single-chip budget "
            f"{hbm_budget / 1e9:.1f} GB"
        )
        if (
            n_devices > 1
            and args.solver != "sharded"
            and tier.kind in ("cand", "cand-chunked", "cand-carry")
        ):
            # execute the ladder's own verdict — the program the
            # production planner would dispatch (repair intact)
            import functools as _ft

            from k8s_spot_rescheduler_tpu.parallel.mesh import make_cand_mesh
            from k8s_spot_rescheduler_tpu.parallel.sharded_ffd import (
                plan_union_cand_sharded,
            )
            from k8s_spot_rescheduler_tpu.solver.repair import DEFAULT_ROUNDS

            union_override = _ft.partial(
                plan_union_cand_sharded,
                make_cand_mesh(),
                rounds=DEFAULT_ROUNDS,  # the planner's repair depth
                repair_spot_chunks=(
                    tier.repair_chunks if tier.carry_chunks == 0 else 1
                ),
                carry_chunks=tier.carry_chunks,
                carry_layout=layout,
            )
            if args.solver not in ("jax", "pallas"):
                args.solver = "jax"
            scale_note += (
                f"; executing the dispatch ladder's verdict: {tier.kind} "
                f"(repair_chunks {tier.repair_chunks}, carry_chunks "
                f"{tier.carry_chunks}, est {tier.est_bytes / 1e9:.1f} "
                f"GB/device over {n_devices} devices; repair intact)"
            )
        elif n_devices > 1 and args.solver != "sharded":
            args.solver = "sharded"
            scale_note += (
                f"; dispatch ladder verdict: 2-D mesh-sharded over "
                f"{n_devices} devices (repair unavailable at this scale)"
            )
        if union_override is None:
            # what actually runs has NO repair phase: the 2-D layout
            # (the ladder's 2-D verdict, or an explicit --solver
            # sharded), or the one-chip honest path whose union is
            # first-fit ∪ best-fit only — the emitted keys must say so
            # even when the ladder would have kept a cand tier
            lane = tier.lane_block if args.solver == "sharded" else shapes[0]
            executed_tier = solver_memory.TierDecision(
                "2d" if args.solver == "sharded" else "single",
                0, 0,
                solver_memory.estimate_union_hbm_bytes(
                    lane, *shapes[1:], repair_spot_chunks=0
                ),
                solver_memory.estimate_union_hbm_breakdown(
                    lane, *shapes[1:], repair_spot_chunks=0
                )["carries"],
                lane,
                True,
            )
        print(f"HBM guard: {scale_note}", file=sys.stderr)

    from k8s_spot_rescheduler_tpu.solver.select import make_fused_planner

    if args.solver == "jax":
        from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd as solve_fn
    elif args.solver == "pallas":
        from k8s_spot_rescheduler_tpu.ops.pallas_ffd import (
            plan_ffd_pallas as solve_fn,
        )
    else:
        import functools

        from k8s_spot_rescheduler_tpu.parallel.mesh import make_mesh
        from k8s_spot_rescheduler_tpu.parallel.sharded_ffd import plan_ffd_sharded

        solve_fn = functools.partial(plan_ffd_sharded, make_mesh())

    # The production per-tick path: solve + on-device selection, host
    # fetches only (idx, found, n, row). NOTE: on this build's tunneled
    # TPU, block_until_ready returns early — the np.asarray fetch is the
    # only honest timing fence, and it is what the loop does anyway.
    from k8s_spot_rescheduler_tpu.solver.fallback import with_repair
    from k8s_spot_rescheduler_tpu.solver.select import decode_selection

    from k8s_spot_rescheduler_tpu.solver.repair import DEFAULT_ROUNDS

    # the production planner path: first-fit ∪ best-fit ∪ local-search
    # repair, one fused device program (what SolverPlanner ships).
    # ``union_override`` is the cand-tier verdict's own program (repair
    # live); only the 2-D regime drops the repair phase, exactly as the
    # planner's auto-shard reroute does.
    if union_override is not None:
        union_fn = union_override
    elif past_chip:
        from k8s_spot_rescheduler_tpu.solver.fallback import (
            with_best_fit_fallback,
        )

        union_fn = with_best_fit_fallback(solve_fn)
    else:
        union_fn = with_repair(solve_fn, DEFAULT_ROUNDS)
    fused = make_fused_planner(union_fn)
    device_packed = jax.tree.map(jax.numpy.asarray, packed)

    try:
        t0 = time.perf_counter()
        sel = decode_selection(fused(device_packed))
        compile_s = time.perf_counter() - t0
    except Exception as err:  # noqa: BLE001 — annotate the honest OOM
        if past_chip and n_devices <= 1:
            raise RuntimeError(
                f"{str(err)[-250:]} | {scale_note}; this host exposes one "
                "chip, so the mesh-sharded solver (the designed scale "
                "path, auto-dispatched when >1 device is visible — see "
                "MULTICHIP_r04 for its 8x proof) cannot engage here"
            ) from err
        raise

    times = []
    for _ in range(args.repeats):
        t0 = time.perf_counter()
        sel = decode_selection(fused(device_packed))
        times.append(time.perf_counter() - t0)

    # the full production tick path: fresh host tensors → upload → solve →
    # single fetch (what SolverPlanner.plan does after packing)
    e2e = []
    for _ in range(max(3, args.repeats // 2)):
        t0 = time.perf_counter()
        sel = decode_selection(fused(packed))
        e2e.append(time.perf_counter() - t0)

    # Amortized device-only estimate: this machine reaches its TPU through
    # a network tunnel whose round trip (~65 ms) dwarfs the actual solve.
    # The protocol (chain N dependent solves, fetch once, subtract the
    # round-trip floor) is pinned + unit-tested in bench/protocol.py; its
    # raw inputs ride along in the JSON line. (Skipped on the CPU
    # fallback: 50 chained config-3 solves on host would blow the
    # watchdog for no information.)
    from k8s_spot_rescheduler_tpu.bench import protocol as bench_protocol

    device_ms = float("nan")
    protocol_rec = None
    if not backend_note:
        protocol_rec = bench_protocol.run_protocol(fused, device_packed)
        device_ms = protocol_rec["device_only_ms"]

    # --- steady-state incremental tick: the pipeline production runs ---
    # (delta-pack into the device-resident cache + staged early-exit
    # solve). Tick 0 is the cold full upload + compiles; the steady
    # number is the median of the post-first-tick full ticks.
    tick_ms, tick_reports, sync_ms_list, _ = run_incremental_ticks(
        client, store, pdbs, spec, args.solver,
        n_ticks=max(4, min(8, args.repeats)),
    )
    tick_report = tick_reports[-1]
    steady_ms = float(np.median(tick_ms[1:]))
    # -1 sentinels mean the tick ran off the single-chip path (mesh
    # reroute / numpy) where upload and chunk accounting don't apply —
    # report n/a, never negative junk
    incremental_active = tick_report.upload_bytes >= 0
    if incremental_active:
        delta_note = (
            f"(delta {tick_report.upload_bytes} B, "
            f"{tick_report.chunks_solved}/"
            f"{tick_report.chunks_solved + tick_report.chunks_skipped} "
            f"chunks solved)"
        )
    else:
        delta_note = "(delta n/a: non-single-chip path)"

    value_ms = float(np.median(times) * 1e3)
    e2e_ms = float(np.median(e2e) * 1e3)
    device_est = (
        f"{device_ms:.2f}" if math.isfinite(device_ms) else "n/a"
    )
    print(
        f"compile {compile_s:.1f}s  solve+fetch median {value_ms:.2f} ms "
        f"(min {min(times)*1e3:.2f}, max {max(times)*1e3:.2f})  "
        f"with-upload {e2e_ms:.1f} ms  "
        f"full tick (pack+upload+solve+fetch) {pack_s*1e3 + e2e_ms:.1f} ms  "
        f"steady incremental tick {steady_ms:.1f} ms {delta_note}  "
        f"device-only est {device_est} ms/solve (tunnel RTT amortized)  "
        f"feasible {sel.n_feasible}/{int(np.asarray(packed.cand_valid).sum())} "
        f"candidates, first={sel.index}  device {jax.devices()[0].device_kind}",
        file=sys.stderr,
    )
    out = {
        "metric": metric,
        "value": round(value_ms, 3),
        "unit": unit,
        "vs_baseline": round(TARGET_MS / value_ms, 3),
        "device": jax.devices()[0].device_kind,
        "steady_tick_ms": round(steady_ms, 3),
        # the columnar observe+pack median, driver-visible (VERDICT
        # next-round item 7): the host half of every tick — split so the
        # delta-shaped steady state is visible: sync_ms is the O(churn)
        # mirror update between ticks, pack_ms the vectorized pack
        "pack_ms": round(pack_s * 1e3, 3),
        "sync_ms": round(float(np.median(sync_ms_list)), 3),
        "observe_ms": round(
            pack_s * 1e3 + float(np.median(sync_ms_list)), 3
        ),
    }
    if incremental_active:
        out["delta_upload_bytes"] = int(tick_report.upload_bytes)
        out["delta_pack_lanes"] = int(tick_report.delta_pack_lanes)
        out["chunks_solved"] = int(tick_report.chunks_solved)
        out["chunks_skipped"] = int(tick_report.chunks_skipped)
    if tick_report.repair_chunks > 1:
        # spot-chunked repair engaged (per-lane repair state exceeded
        # one device at these shapes)
        out["repair_chunks"] = int(tick_report.repair_chunks)
    # the EXECUTED program's tier (solver/memory.pick_tier's verdict —
    # or the 2-D layout when that is what actually ran): carry-stream
    # chunk count, estimated resident carry bytes, and whether the
    # repair phase was live in the measured run
    out["carry_chunks"] = int(executed_tier.carry_chunks)
    out["carry_bytes"] = int(executed_tier.carry_bytes)
    out["repair_unavailable"] = int(executed_tier.repair_unavailable)
    if scale_note is not None:
        out["scale_note"] = scale_note
        out["solver"] = args.solver
    if protocol_rec is not None:
        out["device_only"] = protocol_rec
    if backend_note:
        out["error"] = backend_note
    emit(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
