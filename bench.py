"""North-star benchmark: drain-plan latency at 50k pods / 5k nodes.

Generates the BASELINE.md config-3 synthetic cluster (5k nodes, 50k pods,
Zipf sizes, taints/tolerations), packs it, and times the batched TPU
first-fit solve — every candidate on-demand node's full drain feasibility
proof in one device program (the reference's serial canDrainNode nest,
rescheduler.go:334-370, over the whole cluster).

Prints ONE JSON line:
  {"metric": ..., "value": <median solve ms>, "unit": "ms",
   "vs_baseline": <target_ms / value>}    (>1.0 = under the 200 ms target)

The reference publishes no benchmarks (BASELINE.md: "None exist"); the
baseline is BASELINE.json's 200 ms-on-v5e target for this exact scale.

Usage: python bench.py [--config N] [--repeats R] [--solver jax|sharded]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time
import traceback

import numpy as np


TARGET_MS = 200.0

# --- backend acquisition + failure containment ---------------------------
#
# The TPU on this machine is reached through a tunnel whose backend can be
# slow or flat-out unavailable at process start (round 1's driver run died
# inside the first device_put with "Unable to initialize backend 'axon'",
# and a bare jax.devices() has been observed to hang for minutes). The
# bench must NEVER leave the driver with a stack dump and no JSON line, so:
#
#  - backend readiness is probed in a SUBPROCESS (killable on hang, unlike
#    an in-process jax init) with bounded retry/backoff;
#  - a watchdog hard-exits with a diagnostic JSON line if the whole bench
#    overruns its budget;
#  - main() is wrapped so any exception still emits the one-line JSON with
#    an "error" field — the driver's `parsed` is never null.

_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "d = jax.devices()[0];"
    "jnp.ones((8, 8)).sum().block_until_ready();"
    "print(d.platform + '/' + d.device_kind)"
)

_emit_once = threading.Lock()


def emit(obj: dict) -> None:
    """Print THE one JSON line (at most once per process). The lock is
    acquired and never released: whichever thread (main or watchdog) wins
    the non-blocking acquire is the only one that prints."""
    if not _emit_once.acquire(blocking=False):
        return
    print(json.dumps(obj), flush=True)


def emit_error(metric: str, unit: str, error: str) -> None:
    emit(
        {
            "metric": metric,
            "value": None,
            "unit": unit,
            "vs_baseline": None,
            "error": error[-600:],
        }
    )


def start_watchdog(seconds: float, metric: str, unit: str) -> threading.Timer:
    """Hard-exit with a diagnostic JSON line if the bench overruns —
    a hung device fetch cannot be interrupted any other way."""

    def fire() -> None:
        emit_error(metric, unit, f"watchdog: bench exceeded {seconds:.0f}s budget")
        sys.stdout.flush()
        os._exit(3)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def acquire_backend(
    budget_s: float = 300.0, probe_timeout_s: float = 90.0
) -> tuple:
    """Probe jax backend readiness in killable subprocesses with backoff.

    Returns (platform_desc or None, attempts, last_error). Success means a
    fresh process completed device discovery AND a tiny computation within
    the timeout, so the main process's own init is very likely to succeed
    promptly."""
    deadline = time.monotonic() + budget_s
    attempt, last_err = 0, "no probe attempted"
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return None, attempt - 1, last_err
        this_timeout = min(probe_timeout_s, max(10.0, remaining))
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True,
                text=True,
                timeout=this_timeout,
            )
            if r.returncode == 0 and r.stdout.strip():
                return r.stdout.strip().splitlines()[-1], attempt, None
            last_err = (r.stderr or r.stdout).strip()[-400:] or (
                "probe rc=%d" % r.returncode
            )
        except subprocess.TimeoutExpired:
            last_err = f"backend probe hung >{this_timeout:.0f}s (killed)"
        print(
            f"backend probe attempt {attempt} failed: {last_err.splitlines()[-1] if last_err else '?'}",
            file=sys.stderr,
        )
        if time.monotonic() >= deadline:
            return None, attempt, last_err
        time.sleep(min(15.0, 2.0 * attempt))


def build_problem(config_id: int, seed: int = 0, spec=None):
    """Generate the synthetic cluster and pack it via the production
    observe path: the incrementally-maintained columnar mirror
    (models/columnar.py). The returned pack seconds are the steady-state
    per-tick observe+pack cost (the mirror is already attached, as it is
    in the control loop)."""
    from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS, generate_cluster
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    spec = spec or CONFIGS[config_id]
    cfg = ReschedulerConfig(resources=spec.resources)
    t0 = time.perf_counter()
    client = generate_cluster(spec, seed)
    t1 = time.perf_counter()
    store = client.columnar_store(
        cfg.resources,
        on_demand_label=cfg.on_demand_node_label,
        spot_label=cfg.spot_node_label,
    )
    pdbs = client.list_pdbs()
    t2 = time.perf_counter()
    packed, meta = store.pack(
        pdbs, priority_threshold=cfg.priority_threshold
    )
    t3 = time.perf_counter()
    print(
        f"generate {t1-t0:.1f}s  ingest(once) {t2-t1:.2f}s  "
        f"columnar observe+pack {(t3-t2)*1e3:.1f} ms  "
        f"shapes C={packed.slot_req.shape[0]} K={packed.slot_req.shape[1]} "
        f"S={packed.spot_free.shape[0]} R={packed.slot_req.shape[2]}",
        file=sys.stderr,
    )
    return packed, meta, (t3 - t2)


def run_quality(seed: int, sweep: int = 1, solver: str = "numpy") -> int:
    """Greedy-vs-ILP quality ratio on down-scaled affinity-free clusters
    (the ILP oracle is only tractable at small scale). ``sweep`` runs
    seeds [seed, seed+sweep) and reports the WORST ratio — the honest
    quality number."""
    from k8s_spot_rescheduler_tpu.bench.quality import (
        drain_to_exhaustion,
        ilp_max_drains,
    )
    from k8s_spot_rescheduler_tpu.io.synthetic import SyntheticSpec, generate_cluster
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    spec = SyntheticSpec("quality-40n-300p", 20, 20, 300)
    ratios = []
    for s in range(seed, seed + max(1, sweep)):
        packed, _, _ = build_problem(0, s, spec=spec)
        ilp = ilp_max_drains(packed)
        client = generate_cluster(spec, s, reschedule_evicted=True)
        greedy = drain_to_exhaustion(client, ReschedulerConfig(solver=solver))
        ratio = greedy / ilp if ilp else 1.0
        ratios.append(ratio)
        print(
            f"quality seed {s}: greedy drained {greedy}, ILP oracle {ilp}, "
            f"ratio {ratio:.3f}",
            file=sys.stderr,
        )
    worst = min(ratios)
    print(
        f"quality over {len(ratios)} seed(s): worst {worst:.3f}, "
        f"mean {sum(ratios) / len(ratios):.3f}",
        file=sys.stderr,
    )
    emit(
        {
            "metric": "nodes_freed_vs_ilp_oracle_ratio",
            "value": round(worst, 4),
            "unit": "ratio",
            "vs_baseline": round(worst / 0.95, 4),
        }
    )
    return 0


def run_replay_bench(seed: int, n_events: int, note=None) -> int:
    from k8s_spot_rescheduler_tpu.bench.replay import run_replay
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    stats = run_replay(ReschedulerConfig(), n_events=n_events, seed=seed)
    print(f"replay: {stats}", file=sys.stderr)
    out = {
        "metric": "replay_replan_ms_p50_1k_events",
        "value": round(stats["replan_ms_p50"], 3),
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / max(stats["replan_ms_p50"], 1e-9), 3),
    }
    if note:
        out["error"] = note
    emit(out)
    return 0


def _metric_for(args) -> tuple:
    """(metric name, unit) this invocation will report — known up front so
    failure paths can emit a well-formed JSON line."""
    if args.quality:
        return "nodes_freed_vs_ilp_oracle_ratio", "ratio"
    if args.config == 5:
        return "replay_replan_ms_p50_1k_events", "ms"
    if args.config in (3, 4):
        return "drain_plan_ms_config%d_50kpods_5knodes" % args.config, "ms"
    return "drain_plan_ms_config%d" % args.config, "ms"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, default=3)
    ap.add_argument("--repeats", type=int, default=10)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--solver", default=None,
                    choices=["jax", "sharded", "pallas", "numpy"],
                    help="latency benchmarks default to pallas; --quality "
                         "defaults to the numpy oracle (the quality metric "
                         "is solver-independent — the randomized parity "
                         "suites pin all backends to the oracle — and must "
                         "not depend on device availability)")
    ap.add_argument("--quality", action="store_true",
                    help="measure nodes-freed vs ILP oracle (small scale)")
    ap.add_argument("--sweep", type=int, default=1,
                    help="with --quality: run this many consecutive seeds "
                         "and report the worst ratio")
    ap.add_argument("--events", type=int, default=1000,
                    help="event count for --config 5 replay")
    ap.add_argument("--scale", type=float, default=1.0,
                    help="multiply the config's node/pod counts (headroom runs)")
    ap.add_argument("--watchdog", type=float, default=1500.0,
                    help="hard wall-clock budget in seconds; 0 disables")
    ap.add_argument("--backend-budget", type=float, default=300.0,
                    help="max seconds spent acquiring a working jax backend")
    ap.add_argument("--no-cpu-fallback", action="store_true",
                    help="fail (with a JSON error line) instead of running "
                         "on CPU when the TPU backend never comes up")
    args = ap.parse_args()

    metric, unit = _metric_for(args)
    if args.watchdog > 0:
        start_watchdog(args.watchdog, metric, unit)

    try:
        return _dispatch(ap, args, metric, unit)
    except SystemExit:
        raise
    except BaseException:
        emit_error(metric, unit, traceback.format_exc())
        return 1


def _dispatch(ap, args, metric: str, unit: str) -> int:
    if args.quality:
        return run_quality(
            args.seed, sweep=args.sweep, solver=args.solver or "numpy"
        )
    args.solver = args.solver or "pallas"
    if args.solver == "numpy":
        ap.error("--solver numpy is the host oracle; use it with --quality "
                 "(the latency benchmark measures the device solvers)")

    # Device paths (latency + replay): prove the backend is reachable from
    # a killable subprocess BEFORE this process commits to a jax init.
    platform, attempts, err = acquire_backend(budget_s=args.backend_budget)
    backend_note = None
    if platform is None:
        if args.no_cpu_fallback:
            emit_error(
                metric, unit,
                f"no usable jax backend after {attempts} probes: {err}",
            )
            return 1
        backend_note = (
            f"tpu backend unavailable after {attempts} probes "
            f"({(err or '').splitlines()[-1] if err else '?'}); ran on CPU"
        )
        # The site customization snapshots JAX_PLATFORMS at interpreter
        # start, so the env var alone is ignored by now — the config
        # update after import is what actually reroutes to CPU.
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        args.repeats = min(args.repeats, 3)
        if args.solver == "pallas":
            args.solver = "jax"  # interpret-mode pallas is unusable at scale
        print(f"FALLBACK: {backend_note}", file=sys.stderr)
    else:
        print(
            f"backend ready: {platform} (probe attempts: {attempts})",
            file=sys.stderr,
        )

    if args.config == 5:
        return run_replay_bench(args.seed, args.events, note=backend_note)
    return _run_latency(args, metric, unit, backend_note)


def _run_latency(args, metric: str, unit: str, backend_note) -> int:
    import jax

    spec = None
    if args.scale != 1.0:
        import dataclasses

        from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS

        base = CONFIGS[args.config]
        spec = dataclasses.replace(
            base,
            name=f"{base.name}-x{args.scale:g}",
            n_on_demand=int(base.n_on_demand * args.scale),
            n_spot=int(base.n_spot * args.scale),
            n_pods=int(base.n_pods * args.scale),
        )
    packed, _, pack_s = build_problem(args.config, args.seed, spec=spec)

    from k8s_spot_rescheduler_tpu.solver.select import make_fused_planner

    if args.solver == "jax":
        from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd as solve_fn
    elif args.solver == "pallas":
        from k8s_spot_rescheduler_tpu.ops.pallas_ffd import (
            plan_ffd_pallas as solve_fn,
        )
    else:
        import functools

        from k8s_spot_rescheduler_tpu.parallel.mesh import make_mesh
        from k8s_spot_rescheduler_tpu.parallel.sharded_ffd import plan_ffd_sharded

        solve_fn = functools.partial(plan_ffd_sharded, make_mesh())

    # The production per-tick path: solve + on-device selection, host
    # fetches only (idx, found, n, row). NOTE: on this build's tunneled
    # TPU, block_until_ready returns early — the np.asarray fetch is the
    # only honest timing fence, and it is what the loop does anyway.
    from k8s_spot_rescheduler_tpu.solver.fallback import with_best_fit_fallback
    from k8s_spot_rescheduler_tpu.solver.select import decode_selection

    # the production planner path: first-fit + best-fit fallback union
    union_fn = with_best_fit_fallback(solve_fn)
    fused = make_fused_planner(union_fn)
    device_packed = jax.tree.map(jax.numpy.asarray, packed)

    t0 = time.perf_counter()
    sel = decode_selection(fused(device_packed))
    compile_s = time.perf_counter() - t0

    times = []
    for _ in range(args.repeats):
        t0 = time.perf_counter()
        sel = decode_selection(fused(device_packed))
        times.append(time.perf_counter() - t0)

    # the full production tick path: fresh host tensors → upload → solve →
    # single fetch (what SolverPlanner.plan does after packing)
    e2e = []
    for _ in range(max(3, args.repeats // 2)):
        t0 = time.perf_counter()
        sel = decode_selection(fused(packed))
        e2e.append(time.perf_counter() - t0)

    # Amortized device-only estimate: this machine reaches its TPU through
    # a network tunnel whose round trip (~65 ms) dwarfs the actual solve.
    # Chain N dependent solves in one program, fetch once, subtract the
    # round-trip floor — the per-solve quotient is what a locally attached
    # v5e would see per tick. (Skipped on the CPU fallback: 50 chained
    # config-3 solves on host would blow the watchdog for no information.)
    N_CHAIN = 50
    device_ms = float("nan")
    if not backend_note:

        def chained(p):
            def step(i, acc):
                p2 = p._replace(slot_req=p.slot_req + acc * 0.0)
                return acc + fused(p2).sum().astype(jax.numpy.float32)

            return jax.lax.fori_loop(0, N_CHAIN, step, jax.numpy.float32(0.0))

        chained_jit = jax.jit(chained)
        rtt_jit = jax.jit(lambda p: p.cand_valid.sum())
        np.asarray(chained_jit(device_packed)), np.asarray(rtt_jit(device_packed))
        chain_t, rtt_t = [], []
        for _ in range(5):
            t0 = time.perf_counter()
            np.asarray(chained_jit(device_packed))
            chain_t.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            np.asarray(rtt_jit(device_packed))
            rtt_t.append(time.perf_counter() - t0)
        device_ms = max(
            0.0, (np.median(chain_t) - np.median(rtt_t)) / N_CHAIN * 1e3
        )

    value_ms = float(np.median(times) * 1e3)
    e2e_ms = float(np.median(e2e) * 1e3)
    print(
        f"compile {compile_s:.1f}s  solve+fetch median {value_ms:.2f} ms "
        f"(min {min(times)*1e3:.2f}, max {max(times)*1e3:.2f})  "
        f"with-upload {e2e_ms:.1f} ms  "
        f"full tick (pack+upload+solve+fetch) {pack_s*1e3 + e2e_ms:.1f} ms  "
        f"device-only est {device_ms:.2f} ms/solve (tunnel RTT amortized)  "
        f"feasible {sel.n_feasible}/{int(np.asarray(packed.cand_valid).sum())} "
        f"candidates, first={sel.index}  device {jax.devices()[0].device_kind}",
        file=sys.stderr,
    )
    out = {
        "metric": metric,
        "value": round(value_ms, 3),
        "unit": unit,
        "vs_baseline": round(TARGET_MS / value_ms, 3),
        "device": jax.devices()[0].device_kind,
    }
    if backend_note:
        out["error"] = backend_note
    emit(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
