"""North-star benchmark: drain-plan latency at 50k pods / 5k nodes.

Generates the BASELINE.md config-3 synthetic cluster (5k nodes, 50k pods,
Zipf sizes, taints/tolerations), packs it, and times the batched TPU
first-fit solve — every candidate on-demand node's full drain feasibility
proof in one device program (the reference's serial canDrainNode nest,
rescheduler.go:334-370, over the whole cluster).

Prints ONE JSON line:
  {"metric": ..., "value": <median solve ms>, "unit": "ms",
   "vs_baseline": <target_ms / value>}    (>1.0 = under the 200 ms target)

The reference publishes no benchmarks (BASELINE.md: "None exist"); the
baseline is BASELINE.json's 200 ms-on-v5e target for this exact scale.

Usage: python bench.py [--config N] [--repeats R] [--solver jax|sharded]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


TARGET_MS = 200.0


def build_problem(config_id: int, seed: int = 0, spec=None):
    """Generate the synthetic cluster and pack it via the production
    observe path: the incrementally-maintained columnar mirror
    (models/columnar.py). The returned pack seconds are the steady-state
    per-tick observe+pack cost (the mirror is already attached, as it is
    in the control loop)."""
    from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS, generate_cluster
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    spec = spec or CONFIGS[config_id]
    cfg = ReschedulerConfig(resources=spec.resources)
    t0 = time.perf_counter()
    client = generate_cluster(spec, seed)
    t1 = time.perf_counter()
    store = client.columnar_store(
        cfg.resources,
        on_demand_label=cfg.on_demand_node_label,
        spot_label=cfg.spot_node_label,
    )
    pdbs = client.list_pdbs()
    t2 = time.perf_counter()
    packed, meta = store.pack(
        pdbs, priority_threshold=cfg.priority_threshold
    )
    t3 = time.perf_counter()
    print(
        f"generate {t1-t0:.1f}s  ingest(once) {t2-t1:.2f}s  "
        f"columnar observe+pack {(t3-t2)*1e3:.1f} ms  "
        f"shapes C={packed.slot_req.shape[0]} K={packed.slot_req.shape[1]} "
        f"S={packed.spot_free.shape[0]} R={packed.slot_req.shape[2]}",
        file=sys.stderr,
    )
    return packed, meta, (t3 - t2)


def run_quality(seed: int, sweep: int = 1, solver: str = "numpy") -> int:
    """Greedy-vs-ILP quality ratio on down-scaled affinity-free clusters
    (the ILP oracle is only tractable at small scale). ``sweep`` runs
    seeds [seed, seed+sweep) and reports the WORST ratio — the honest
    quality number."""
    from k8s_spot_rescheduler_tpu.bench.quality import (
        drain_to_exhaustion,
        ilp_max_drains,
    )
    from k8s_spot_rescheduler_tpu.io.synthetic import SyntheticSpec, generate_cluster
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    spec = SyntheticSpec("quality-40n-300p", 20, 20, 300)
    ratios = []
    for s in range(seed, seed + max(1, sweep)):
        packed, _, _ = build_problem(0, s, spec=spec)
        ilp = ilp_max_drains(packed)
        client = generate_cluster(spec, s, reschedule_evicted=True)
        greedy = drain_to_exhaustion(client, ReschedulerConfig(solver=solver))
        ratio = greedy / ilp if ilp else 1.0
        ratios.append(ratio)
        print(
            f"quality seed {s}: greedy drained {greedy}, ILP oracle {ilp}, "
            f"ratio {ratio:.3f}",
            file=sys.stderr,
        )
    worst = min(ratios)
    print(
        f"quality over {len(ratios)} seed(s): worst {worst:.3f}, "
        f"mean {sum(ratios) / len(ratios):.3f}",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "nodes_freed_vs_ilp_oracle_ratio",
                "value": round(worst, 4),
                "unit": "ratio",
                "vs_baseline": round(worst / 0.95, 4),
            }
        )
    )
    return 0


def run_replay_bench(seed: int, n_events: int) -> int:
    from k8s_spot_rescheduler_tpu.bench.replay import run_replay
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    stats = run_replay(ReschedulerConfig(), n_events=n_events, seed=seed)
    print(f"replay: {stats}", file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "replay_replan_ms_p50_1k_events",
                "value": round(stats["replan_ms_p50"], 3),
                "unit": "ms",
                "vs_baseline": round(TARGET_MS / max(stats["replan_ms_p50"], 1e-9), 3),
            }
        )
    )
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, default=3)
    ap.add_argument("--repeats", type=int, default=10)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--solver", default=None,
                    choices=["jax", "sharded", "pallas", "numpy"],
                    help="latency benchmarks default to pallas; --quality "
                         "defaults to the numpy oracle (the quality metric "
                         "is solver-independent — the randomized parity "
                         "suites pin all backends to the oracle — and must "
                         "not depend on device availability)")
    ap.add_argument("--quality", action="store_true",
                    help="measure nodes-freed vs ILP oracle (small scale)")
    ap.add_argument("--sweep", type=int, default=1,
                    help="with --quality: run this many consecutive seeds "
                         "and report the worst ratio")
    ap.add_argument("--events", type=int, default=1000,
                    help="event count for --config 5 replay")
    ap.add_argument("--scale", type=float, default=1.0,
                    help="multiply the config's node/pod counts (headroom runs)")
    args = ap.parse_args()

    if args.quality:
        return run_quality(
            args.seed, sweep=args.sweep, solver=args.solver or "numpy"
        )
    args.solver = args.solver or "pallas"
    if args.solver == "numpy":
        ap.error("--solver numpy is the host oracle; use it with --quality "
                 "(the latency benchmark measures the device solvers)")
    if args.config == 5:
        return run_replay_bench(args.seed, args.events)

    import jax

    spec = None
    if args.scale != 1.0:
        import dataclasses

        from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS

        base = CONFIGS[args.config]
        spec = dataclasses.replace(
            base,
            name=f"{base.name}-x{args.scale:g}",
            n_on_demand=int(base.n_on_demand * args.scale),
            n_spot=int(base.n_spot * args.scale),
            n_pods=int(base.n_pods * args.scale),
        )
    packed, _, pack_s = build_problem(args.config, args.seed, spec=spec)

    from k8s_spot_rescheduler_tpu.solver.select import make_fused_planner

    if args.solver == "jax":
        from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd as solve_fn
    elif args.solver == "pallas":
        from k8s_spot_rescheduler_tpu.ops.pallas_ffd import (
            plan_ffd_pallas as solve_fn,
        )
    else:
        import functools

        from k8s_spot_rescheduler_tpu.parallel.mesh import make_mesh
        from k8s_spot_rescheduler_tpu.parallel.sharded_ffd import plan_ffd_sharded

        solve_fn = functools.partial(plan_ffd_sharded, make_mesh())

    # The production per-tick path: solve + on-device selection, host
    # fetches only (idx, found, n, row). NOTE: on this build's tunneled
    # TPU, block_until_ready returns early — the np.asarray fetch is the
    # only honest timing fence, and it is what the loop does anyway.
    from k8s_spot_rescheduler_tpu.solver.fallback import with_best_fit_fallback
    from k8s_spot_rescheduler_tpu.solver.select import decode_selection

    # the production planner path: first-fit + best-fit fallback union
    union_fn = with_best_fit_fallback(solve_fn)
    fused = make_fused_planner(union_fn)
    device_packed = jax.tree.map(jax.numpy.asarray, packed)

    t0 = time.perf_counter()
    sel = decode_selection(fused(device_packed))
    compile_s = time.perf_counter() - t0

    times = []
    for _ in range(args.repeats):
        t0 = time.perf_counter()
        sel = decode_selection(fused(device_packed))
        times.append(time.perf_counter() - t0)

    # the full production tick path: fresh host tensors → upload → solve →
    # single fetch (what SolverPlanner.plan does after packing)
    e2e = []
    for _ in range(max(3, args.repeats // 2)):
        t0 = time.perf_counter()
        sel = decode_selection(fused(packed))
        e2e.append(time.perf_counter() - t0)

    # Amortized device-only estimate: this machine reaches its TPU through
    # a network tunnel whose round trip (~65 ms) dwarfs the actual solve.
    # Chain N dependent solves in one program, fetch once, subtract the
    # round-trip floor — the per-solve quotient is what a locally attached
    # v5e would see per tick.
    N_CHAIN = 50

    def chained(p):
        def step(i, acc):
            p2 = p._replace(slot_req=p.slot_req + acc * 0.0)
            return acc + fused(p2).sum().astype(jax.numpy.float32)

        return jax.lax.fori_loop(0, N_CHAIN, step, jax.numpy.float32(0.0))

    chained_jit = jax.jit(chained)
    rtt_jit = jax.jit(lambda p: p.cand_valid.sum())
    np.asarray(chained_jit(device_packed)), np.asarray(rtt_jit(device_packed))
    chain_t, rtt_t = [], []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(chained_jit(device_packed))
        chain_t.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        np.asarray(rtt_jit(device_packed))
        rtt_t.append(time.perf_counter() - t0)
    device_ms = max(0.0, (np.median(chain_t) - np.median(rtt_t)) / N_CHAIN * 1e3)

    value_ms = float(np.median(times) * 1e3)
    e2e_ms = float(np.median(e2e) * 1e3)
    print(
        f"compile {compile_s:.1f}s  solve+fetch median {value_ms:.2f} ms "
        f"(min {min(times)*1e3:.2f}, max {max(times)*1e3:.2f})  "
        f"with-upload {e2e_ms:.1f} ms  "
        f"full tick (pack+upload+solve+fetch) {pack_s*1e3 + e2e_ms:.1f} ms  "
        f"device-only est {device_ms:.2f} ms/solve (tunnel RTT amortized)  "
        f"feasible {sel.n_feasible}/{int(np.asarray(packed.cand_valid).sum())} "
        f"candidates, first={sel.index}  device {jax.devices()[0].device_kind}",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": (
                    "drain_plan_ms_config%d_50kpods_5knodes" % args.config
                    if args.config in (3, 4)
                    else "drain_plan_ms_config%d" % args.config
                ),
                "value": round(value_ms, 3),
                "unit": "ms",
                "vs_baseline": round(TARGET_MS / value_ms, 3),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
