"""Repo tooling: the lint gate (tools/lint.py) and the static-analysis
suite (tools/analysis/) behind ``make lint`` / ``make analyze``."""
