"""Checked-in baseline of grandfathered findings.

Format (tools/analysis/baseline.txt): one entry per line,

    <path>::<code>::<anchor>  # <one-line justification>

The key matches :attr:`Finding.key` — path + code + a stable anchor
(function/attribute/field name), so entries survive line drift. Blank
lines and ``#`` comment lines are skipped. Every entry MUST carry a
justification comment; an entry that no longer matches any finding is
reported as ``stale-baseline`` (warn) so the file shrinks as debt is
paid instead of rotting.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Tuple

from tools.analysis.common import WARN, Finding


def load(path) -> Dict[str, Tuple[int, str]]:
    """key -> (line in baseline file, justification)."""
    entries: Dict[str, Tuple[int, str]] = {}
    p = Path(path)
    if not p.exists():
        return entries
    for i, raw in enumerate(p.read_text().splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        key, _, comment = line.partition("#")
        entries[key.strip()] = (i, comment.strip())
    return entries


def apply(
    findings: List[Finding],
    baseline_path,
    *,
    analyzed_paths=None,
    exercised_codes=None,
) -> Tuple[List[Finding], List[Finding], List[Finding]]:
    """Split into (active, baselined, stale-baseline findings).

    An unmatched entry is STALE only when this run could have matched
    it: its file was among the analyzed paths and its code was among
    the exercised pass codes — a subset-roots, single-pass, or
    single-tier invocation must not call un-exercised debt 'paid'."""
    entries = load(baseline_path)
    active: List[Finding] = []
    baselined: List[Finding] = []
    used = set()
    for f in findings:
        if f.key in entries:
            used.add(f.key)
            baselined.append(f)
        else:
            active.append(f)
    stale: List[Finding] = []
    for key, (line, _) in sorted(entries.items(), key=lambda kv: kv[1][0]):
        if key in used:
            continue
        parts = key.split("::")
        entry_path = parts[0] if parts else ""
        entry_code = parts[1] if len(parts) > 2 else ""
        if analyzed_paths is not None and entry_path not in analyzed_paths:
            continue
        if exercised_codes is not None and entry_code not in exercised_codes:
            continue
        stale.append(Finding(
            str(baseline_path), line, "stale-baseline",
            f"baseline entry '{key}' matches no current finding — "
            "remove it (the debt was paid or the key drifted)",
            severity=WARN, anchor=key,
        ))
    return active, baselined, stale
