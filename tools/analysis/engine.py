"""Analysis driver: walk → parse once → passes → suppressions → baseline.

The reference gates merges on a fmt + golangci-lint + go vet chain
(reference Makefile:36-65); ``tools/lint.py`` is the fmt/lint half and
this engine is the vet half — project-wide passes over one shared parse
of the package, in two tiers:

- ``--tier ast`` — the source passes (symbol table + call graph;
  tools/analysis/passes). ``make analyze`` runs exactly this.
- ``--tier jaxpr`` — the traced-program passes (tools/analysis/jaxpr):
  the HOT_PROGRAMS manifest traced shape-only on CPU, audited for
  dtype, index-width, transfer, and memory properties. ``make
  audit-jaxpr`` runs exactly this.
- ``--tier proto`` — the protocol passes (tools/analysis/proto): the
  declared wire/breaker/admission automata exhaustively explored for
  safety + liveness, and the model<->code contract. ``make
  verify-protocol`` runs exactly this.
- ``--tier all`` (default) — all three.

Either tier's findings flow through the SAME suppression grammar and
baseline; suppression-hygiene findings (bare-noqa etc.) belong to the
ast tier so the two ``make check`` stages report each defect once.

Exit codes: 0 clean (warnings allowed unless --strict), 1 error-tier
findings, 2 watchdog exceeded (--max-seconds).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from tools.analysis import baseline as baseline_mod
from tools.analysis.common import (
    ANALYSIS_CODES,
    DEFAULT_ROOTS,
    ERROR,
    Suppressions,
    iter_py_files,
    relpath,
)
from tools.analysis.jaxpr import JAXPR_PASS_NAMES
from tools.analysis.passes import ALL_PASSES
from tools.analysis.proto import PROTO_PASS_NAMES
from tools.analysis.symbols import Project

DEFAULT_BASELINE = Path(__file__).parent / "baseline.txt"
DEFAULT_PARITY = "docs/PARITY.md"
DEFAULT_OBSERVABILITY = "docs/OBSERVABILITY.md"

AST_PASS_NAMES = tuple(name for name, _ in ALL_PASSES)


def _pass_tier(name) -> str:
    """Which tier owns a ``--pass`` name (pass names ARE finding
    codes, and each belongs to exactly one tier)."""
    if name in JAXPR_PASS_NAMES:
        return "jaxpr"
    if name in PROTO_PASS_NAMES:
        return "proto"
    return "ast"  # ast passes + the "suppressions" pseudo-pass


def _exercised_codes(tier: str, only_pass) -> set:
    """The finding codes this run could have produced — what baseline
    staleness may be judged against. Tier-qualified: a --tier proto
    run never calls ast/jaxpr debt paid, and vice versa."""
    if only_pass == "suppressions":
        return {"bare-noqa", "unknown-suppression"}
    if only_pass is not None:
        return {only_pass}
    codes = set()
    if tier in ("ast", "all"):
        codes.update(AST_PASS_NAMES)
        codes.update({"bare-noqa", "unknown-suppression"})
    if tier in ("jaxpr", "all"):
        codes.update(JAXPR_PASS_NAMES)
        codes.add("trace-failure")
    if tier in ("proto", "all"):
        codes.update(PROTO_PASS_NAMES)
    return codes & ANALYSIS_CODES


def analyze(
    roots,
    *,
    parity_path=DEFAULT_PARITY,
    observability_path=DEFAULT_OBSERVABILITY,
    baseline_path=DEFAULT_BASELINE,
    use_baseline=True,
    only_pass=None,
    tier="all",
    manifest=None,
    proto_model=None,
):
    """Run the selected tiers' passes; returns (active, baselined,
    tier_runtimes_ms) with per-file suppressions folded in. Pure — no
    printing, no exit."""
    project = Project(Path.cwd())
    files = {}
    suppressions = {}
    for path in iter_py_files(roots):
        try:
            source = path.read_text(encoding="utf-8", errors="replace")
        except OSError:
            continue
        files[str(path)] = source
        suppressions[str(path)] = Suppressions(source)
        project.add_file(path, source)

    parity = Path(parity_path)
    if parity.exists():
        files["__parity__"] = parity.read_text(
            encoding="utf-8", errors="replace"
        )
    observability = Path(observability_path)
    if observability.exists():
        files["__observability__"] = observability.read_text(
            encoding="utf-8", errors="replace"
        )

    findings = []
    tier_runtimes_ms = {}
    if tier in ("ast", "all"):
        t_tier = time.perf_counter()
        for name, run in ALL_PASSES:
            if only_pass and name != only_pass:
                continue
            findings.extend(run(project, files))

        # suppression hygiene findings (bare-noqa / unknown-suppression):
        # ast tier only, so an all-tier `make check` reports each once
        if only_pass in (None, "suppressions"):
            for path, supp in suppressions.items():
                findings.extend(supp.findings(relpath(path)))
        tier_runtimes_ms["ast"] = round(
            (time.perf_counter() - t_tier) * 1e3, 1
        )

    if tier in ("jaxpr", "all") and (
        only_pass is None or only_pass in JAXPR_PASS_NAMES
    ):
        from tools.analysis.jaxpr import run_tier

        t_tier = time.perf_counter()
        findings.extend(
            run_tier(manifest_path=manifest, only_pass=only_pass)
        )
        tier_runtimes_ms["jaxpr"] = round(
            (time.perf_counter() - t_tier) * 1e3, 1
        )

    if tier in ("proto", "all") and (
        only_pass is None or only_pass in PROTO_PASS_NAMES
    ):
        from tools.analysis.proto import run_tier as run_proto_tier

        t_tier = time.perf_counter()
        findings.extend(run_proto_tier(
            project, files, only_pass=only_pass,
            model_path=proto_model,
        ))
        tier_runtimes_ms["proto"] = round(
            (time.perf_counter() - t_tier) * 1e3, 1
        )

    # apply typed per-line suppressions
    kept = []
    for f in findings:
        supp = None
        for path, s in suppressions.items():
            if relpath(path) == f.path or path == f.path:
                supp = s
                break
        if supp is not None and supp.suppresses(f.line, f.code):
            continue
        kept.append(f)

    if use_baseline:
        active, baselined, stale = baseline_mod.apply(
            kept, baseline_path,
            # staleness is judged per entry, only against what this run
            # exercised (files analyzed, tiers/passes run) — a
            # subset-roots, --pass, or single-tier invocation must not
            # call un-exercised debt 'paid'
            analyzed_paths={
                relpath(p) for p in files if p != "__parity__"
            },
            exercised_codes=_exercised_codes(tier, only_pass),
        )
        active.extend(stale)
    else:
        active, baselined = kept, []
    active.sort(key=lambda f: (f.path, f.line, f.code))
    return active, baselined, tier_runtimes_ms


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tools.analysis",
        description="project-wide static analysis (vet analog), three "
                    "tiers: ast (source) + jaxpr (traced programs) + "
                    "proto (protocol model + contract)",
    )
    p.add_argument("roots", nargs="*", default=None,
                   help=f"files/dirs to analyze (default: {DEFAULT_ROOTS})")
    p.add_argument("--tier", choices=("ast", "jaxpr", "proto", "all"),
                   default="all",
                   help="which analysis tier(s) to run (default: all; "
                        "'make analyze' pins ast, 'make audit-jaxpr' "
                        "pins jaxpr, 'make verify-protocol' pins "
                        "proto)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable findings (schema in "
                        "docs/ANALYSIS.md)")
    p.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                   help="baseline file of grandfathered findings")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (report everything)")
    p.add_argument("--parity", default=DEFAULT_PARITY,
                   help="PARITY.md path for the config-contract doc check")
    p.add_argument("--manifest", default=None,
                   help="alternate HOT_PROGRAMS manifest module for the "
                        "jaxpr tier (default: the package's "
                        "hot_programs.collect(); fixture/test hook)")
    p.add_argument("--observability", default=DEFAULT_OBSERVABILITY,
                   help="OBSERVABILITY.md path for the flight-contract "
                        "doc check")
    p.add_argument("--proto-model", dest="proto_model", default=None,
                   help="alternate protocol model file for the proto "
                        "tier (default: the analyzed tree's "
                        "service/protocol_model.py; fixture/test hook)")
    p.add_argument("--strict", action="store_true",
                   help="warn-tier findings also fail the gate")
    p.add_argument("--pass", dest="only_pass", default=None,
                   choices=list(AST_PASS_NAMES)
                   + list(JAXPR_PASS_NAMES)
                   + list(PROTO_PASS_NAMES)
                   + ["suppressions"],
                   help="run a single pass by code name (a typo must "
                        "error, not report a vacuously clean tree)")
    p.add_argument("--max-seconds", type=float, default=0.0,
                   help="watchdog: exit 2 if the run exceeds this "
                        "(keeps 'make check' fast)")
    args = p.parse_args(argv)

    if args.only_pass is not None and args.tier != "all":
        owner = _pass_tier(args.only_pass)
        if owner != args.tier:
            article = "an" if owner == "ast" else "a"
            p.error(
                f"--pass {args.only_pass} is {article} {owner}-tier "
                f"pass; drop --tier {args.tier} (or use --tier "
                f"{owner})"
            )

    t0 = time.perf_counter()
    active, baselined, tier_runtimes_ms = analyze(
        args.roots or DEFAULT_ROOTS,
        parity_path=args.parity,
        observability_path=args.observability,
        baseline_path=args.baseline,
        use_baseline=not args.no_baseline,
        only_pass=args.only_pass,
        tier=args.tier,
        manifest=args.manifest,
        proto_model=args.proto_model,
    )
    elapsed = time.perf_counter() - t0

    errors = [f for f in active if f.severity == ERROR]
    warns = [f for f in active if f.severity != ERROR]

    if args.as_json:
        print(json.dumps({
            "version": 1,
            "tier": args.tier,
            "elapsed_seconds": round(elapsed, 3),
            # per-tier wall cost: the three tiers dominate `make
            # check` wall, so their split is part of the schema
            "tier_runtimes_ms": tier_runtimes_ms,
            "findings": [f.as_dict() for f in active],
            "counts": {
                "error": len(errors),
                "warn": len(warns),
                "baselined": len(baselined),
            },
        }, indent=2))
    else:
        for f in active:
            print(f"{f.path}:{f.line}: [{f.severity}] {f.code} {f.message}")
        if active or baselined:
            print(
                f"{len(errors)} error(s), {len(warns)} warning(s), "
                f"{len(baselined)} baselined",
                file=sys.stderr,
            )

    if args.max_seconds and elapsed > args.max_seconds:
        print(
            f"analysis watchdog: {elapsed:.1f}s exceeds the "
            f"{args.max_seconds:.0f}s budget — 'make check' must stay "
            "fast; profile or split the slow pass",
            file=sys.stderr,
        )
        return 2
    if errors or (args.strict and warns):
        return 1
    return 0
