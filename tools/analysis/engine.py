"""Analysis driver: walk → parse once → passes → suppressions → baseline.

The reference gates merges on a fmt + golangci-lint + go vet chain
(reference Makefile:36-65); ``tools/lint.py`` is the fmt/lint half and
this engine is the vet half — project-wide passes over one shared parse
of the package. ``make analyze`` runs it inside ``make check``.

Exit codes: 0 clean (warnings allowed unless --strict), 1 error-tier
findings, 2 watchdog exceeded (--max-seconds).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from tools.analysis import baseline as baseline_mod
from tools.analysis.common import (
    DEFAULT_ROOTS,
    ERROR,
    Suppressions,
    iter_py_files,
    relpath,
)
from tools.analysis.passes import ALL_PASSES
from tools.analysis.symbols import Project

DEFAULT_BASELINE = Path(__file__).parent / "baseline.txt"
DEFAULT_PARITY = "docs/PARITY.md"


def analyze(
    roots,
    *,
    parity_path=DEFAULT_PARITY,
    baseline_path=DEFAULT_BASELINE,
    use_baseline=True,
    only_pass=None,
):
    """Run all passes; returns (active, baselined, per-file suppressions
    findings folded in). Pure — no printing, no exit."""
    project = Project(Path.cwd())
    files = {}
    suppressions = {}
    for path in iter_py_files(roots):
        try:
            source = path.read_text(encoding="utf-8", errors="replace")
        except OSError:
            continue
        files[str(path)] = source
        suppressions[str(path)] = Suppressions(source)
        project.add_file(path, source)

    parity = Path(parity_path)
    if parity.exists():
        files["__parity__"] = parity.read_text(
            encoding="utf-8", errors="replace"
        )

    findings = []
    for name, run in ALL_PASSES:
        if only_pass and name != only_pass:
            continue
        findings.extend(run(project, files))

    # suppression hygiene findings (bare-noqa / unknown-suppression)
    if only_pass in (None, "suppressions"):
        for path, supp in suppressions.items():
            findings.extend(supp.findings(relpath(path)))

    # apply typed per-line suppressions
    kept = []
    for f in findings:
        supp = None
        for path, s in suppressions.items():
            if relpath(path) == f.path or path == f.path:
                supp = s
                break
        if supp is not None and supp.suppresses(f.line, f.code):
            continue
        kept.append(f)

    if use_baseline:
        active, baselined, stale = baseline_mod.apply(
            kept, baseline_path,
            # staleness is judged per entry, only against what this run
            # exercised (files analyzed, passes run) — a subset-roots or
            # --pass invocation must not call un-exercised debt 'paid'
            analyzed_paths={
                relpath(p) for p in files if p != "__parity__"
            },
            only_pass=only_pass,
        )
        active.extend(stale)
    else:
        active, baselined = kept, []
    active.sort(key=lambda f: (f.path, f.line, f.code))
    return active, baselined


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tools.analysis",
        description="project-wide static analysis (vet analog)",
    )
    p.add_argument("roots", nargs="*", default=None,
                   help=f"files/dirs to analyze (default: {DEFAULT_ROOTS})")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable findings (schema in "
                        "docs/ANALYSIS.md)")
    p.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                   help="baseline file of grandfathered findings")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (report everything)")
    p.add_argument("--parity", default=DEFAULT_PARITY,
                   help="PARITY.md path for the config-contract doc check")
    p.add_argument("--strict", action="store_true",
                   help="warn-tier findings also fail the gate")
    p.add_argument("--pass", dest="only_pass", default=None,
                   choices=[name for name, _ in ALL_PASSES]
                   + ["suppressions"],
                   help="run a single pass by code name (a typo must "
                        "error, not report a vacuously clean tree)")
    p.add_argument("--max-seconds", type=float, default=0.0,
                   help="watchdog: exit 2 if the run exceeds this "
                        "(keeps 'make check' fast)")
    args = p.parse_args(argv)

    t0 = time.perf_counter()
    active, baselined = analyze(
        args.roots or DEFAULT_ROOTS,
        parity_path=args.parity,
        baseline_path=args.baseline,
        use_baseline=not args.no_baseline,
        only_pass=args.only_pass,
    )
    elapsed = time.perf_counter() - t0

    errors = [f for f in active if f.severity == ERROR]
    warns = [f for f in active if f.severity != ERROR]

    if args.as_json:
        print(json.dumps({
            "version": 1,
            "elapsed_seconds": round(elapsed, 3),
            "findings": [f.as_dict() for f in active],
            "counts": {
                "error": len(errors),
                "warn": len(warns),
                "baselined": len(baselined),
            },
        }, indent=2))
    else:
        for f in active:
            print(f"{f.path}:{f.line}: [{f.severity}] {f.code} {f.message}")
        if active or baselined:
            print(
                f"{len(errors)} error(s), {len(warns)} warning(s), "
                f"{len(baselined)} baselined",
                file=sys.stderr,
            )

    if args.max_seconds and elapsed > args.max_seconds:
        print(
            f"analysis watchdog: {elapsed:.1f}s exceeds the "
            f"{args.max_seconds:.0f}s budget — 'make check' must stay "
            "fast; profile or split the slow pass",
            file=sys.stderr,
        )
        return 2
    if errors or (args.strict and warns):
        return 1
    return 0
