"""Project-wide static analysis suite (the go-vet analog).

``python -m tools.analysis`` from the repo root, or ``make analyze``.
Passes: JAX hot-path vets (jax-host-sync, donation-discipline,
recompile-trigger), cross-module contracts (metrics-contract,
config-contract, kube-write-retry), and the lock-discipline audit.
Catalogue + policy: docs/ANALYSIS.md.
"""

from tools.analysis.engine import analyze, main  # noqa: F401
