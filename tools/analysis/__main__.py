import sys

from tools.analysis.engine import main

if __name__ == "__main__":
    sys.exit(main())
