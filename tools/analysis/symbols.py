"""Package-wide symbol table + approximate call graph.

Every pass that reasons across function boundaries (the JAX hot-path
vets, the lock-discipline audit) starts from this model. It is an
*approximation* built purely from the AST — no imports are executed:

- every ``def`` (module-level, method, nested) becomes a
  :class:`FunctionInfo` with a dotted qualname;
- calls are resolved by name through (a) enclosing nested scopes,
  (b) the module's own functions, (c) ``from x import y`` / ``import x``
  bindings into other analyzed modules, (d) ``self.method`` within a
  class;
- a *function reference passed as an argument* (``lax.scan(step, ...)``,
  ``lax.cond(p, on_true, on_false)``) counts as a call edge from the
  caller — that is how tracing reaches those bodies, so that is how
  reachability must flow;
- a function's nested ``def``s are treated as reachable from it (the
  branches handed to ``lax.cond``/``lax.switch`` are defined inline in
  exactly this shape).

Unresolvable calls (parameters called as functions, attributes of
non-module objects) are silently dropped: the passes built on top are
tuned so that missing edges cost recall, never false findings.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass(eq=False)  # identity-hashed: graph node
class FunctionInfo:
    qual: str  # "module_id:Outer.inner" dotted within the module
    name: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    module: "ModuleInfo"
    cls: Optional[str]  # enclosing class name, if a method
    parent: Optional["FunctionInfo"]  # enclosing function, if nested
    _locals: Optional[set] = None

    @property
    def local_names(self) -> set:
        """Parameters + locally-assigned names: these SHADOW module
        functions/imports when resolving a bare name in this scope."""
        if self._locals is None:
            names = set()
            a = self.node.args
            for arg in (
                list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
            ):
                names.add(arg.arg)
            if a.vararg:
                names.add(a.vararg.arg)
            if a.kwarg:
                names.add(a.kwarg.arg)
            for sub in ast.walk(self.node):
                if isinstance(sub, ast.Name) and isinstance(
                    sub.ctx, (ast.Store, ast.Del)
                ):
                    names.add(sub.id)
            self._locals = names
        return self._locals

    @property
    def path(self) -> str:
        return self.module.path

    @property
    def line(self) -> int:
        return self.node.lineno


class ModuleInfo:
    def __init__(self, path: str, module_id: str, tree: ast.Module):
        self.path = path
        self.module_id = module_id  # dotted, derived from the file path
        self.tree = tree
        self.functions: Dict[str, FunctionInfo] = {}  # by in-module qual
        # local binding name -> ("module", dotted) | ("attr", dotted, name)
        self.imports: Dict[str, Tuple] = {}
        self.classes: Dict[str, ast.ClassDef] = {}


def _module_id(path: Path, root: Path) -> str:
    try:
        rel = path.resolve().relative_to(root.resolve())
    except ValueError:
        # out-of-tree file (fixture trees under tmp): keep the FULL
        # path-derived id, so suffix-matched quals (manifest-contract
        # covers, baseline anchors) behave the same as in-tree
        rel = Path(*(p for p in path.resolve().parts if p != "/"))
    return ".".join(rel.with_suffix("").parts)


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class Project:
    """All analyzed modules, indexed for cross-module name resolution."""

    def __init__(self, root: Path):
        self.root = Path(root)
        self.modules: Dict[str, ModuleInfo] = {}  # by module_id
        self.by_path: Dict[str, ModuleInfo] = {}

    # -- construction --

    def add_file(self, path: Path, source: str) -> Optional[ModuleInfo]:
        try:
            tree = ast.parse(source, filename=str(path))
        except SyntaxError:
            return None  # the lint gate owns syntax errors
        mod = ModuleInfo(str(path), _module_id(path, self.root), tree)
        self._index(mod)
        self.modules[mod.module_id] = mod
        self.by_path[str(path)] = mod
        return mod

    def _index(self, mod: ModuleInfo) -> None:
        project = self

        class Indexer(ast.NodeVisitor):
            def __init__(self):
                self.stack: List[Tuple[str, object]] = []  # (kind, name/fn)

            def _qual(self, name: str) -> str:
                parts = [n for _, n in self.stack] + [name]
                return ".".join(
                    p.name if isinstance(p, FunctionInfo) else p
                    for p in parts
                )

            def visit_Import(self, node):
                for alias in node.names:
                    bound = (alias.asname or alias.name).split(".")[0]
                    mod.imports[bound] = ("module", alias.name)

            def visit_ImportFrom(self, node):
                if not node.module or node.level:
                    return
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    mod.imports[alias.asname or alias.name] = (
                        "attr", node.module, alias.name
                    )

            def visit_ClassDef(self, node):
                mod.classes[node.name] = node
                self.stack.append(("class", node.name))
                self.generic_visit(node)
                self.stack.pop()

            def _def(self, node):
                qual = self._qual(node.name)
                cls = None
                parent = None
                for kind, val in reversed(self.stack):
                    if kind == "class" and cls is None:
                        cls = val
                        break
                    if kind == "func" and parent is None:
                        parent = val
                for kind, val in reversed(self.stack):
                    if kind == "func":
                        parent = val
                        break
                info = FunctionInfo(
                    qual=f"{mod.module_id}:{qual}",
                    name=node.name, node=node, module=mod,
                    cls=cls, parent=parent,
                )
                mod.functions[qual] = info
                self.stack.append(("func", info))
                self.generic_visit(node)
                self.stack.pop()

            visit_FunctionDef = _def
            visit_AsyncFunctionDef = _def

        Indexer().visit(mod.tree)

    # -- resolution --

    def _module_by_dotted(self, dotted_name: str) -> Optional[ModuleInfo]:
        if dotted_name in self.modules:
            return self.modules[dotted_name]
        # lenient suffix match: analyzed ids are path-derived, imports may
        # carry a different package prefix (fixture trees, src layouts)
        for mid, m in self.modules.items():
            if mid.endswith("." + dotted_name) or dotted_name.endswith(
                "." + mid
            ):
                return m
        return None

    def resolve_in_module(
        self, mod: ModuleInfo, name: str, scope: Optional[FunctionInfo]
    ) -> Optional[FunctionInfo]:
        """A bare name referenced inside ``scope`` (or at module level)."""
        # nested defs of enclosing functions, innermost first
        fn = scope
        while fn is not None:
            prefix = fn.qual.split(":", 1)[1]
            cand = mod.functions.get(f"{prefix}.{name}")
            if cand is not None:
                return cand
            fn = fn.parent
        # parameters/locals of any enclosing scope shadow module names
        # (a bare name never resolves to a method — that needs ``self.``)
        fn = scope
        while fn is not None:
            if name in fn.local_names:
                return None
            fn = fn.parent
        if name in mod.functions:
            return mod.functions[name]
        imp = mod.imports.get(name)
        if imp and imp[0] == "attr":
            target = self._module_by_dotted(imp[1])
            if target is not None:
                return target.functions.get(imp[2])
        return None

    def resolve_call(
        self, mod: ModuleInfo, func: ast.AST, scope: Optional[FunctionInfo]
    ) -> Optional[FunctionInfo]:
        """Resolve a Call's func expression to an analyzed function."""
        if isinstance(func, ast.Name):
            return self.resolve_in_module(mod, func.id, scope)
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name):
                if base.id == "self" and scope is not None and scope.cls:
                    return mod.functions.get(f"{scope.cls}.{func.attr}")
                imp = mod.imports.get(base.id)
                if imp and imp[0] == "module":
                    target = self._module_by_dotted(imp[1])
                    if target is not None:
                        return target.functions.get(func.attr)
                if imp and imp[0] == "attr":
                    # "from pkg import module as alias" style
                    target = self._module_by_dotted(f"{imp[1]}.{imp[2]}")
                    if target is not None:
                        return target.functions.get(func.attr)
        return None


def function_scope_of(
    mod: ModuleInfo, node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> Optional[FunctionInfo]:
    """The innermost FunctionInfo lexically containing ``node``."""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for info in mod.functions.values():
                if info.node is cur:
                    return info
        cur = parents.get(cur)
    return None


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents
