"""Shape-only tracing of the HOT_PROGRAMS manifest.

Every jaxpr pass consumes :class:`TracedProgram`s produced here: the
manifest entry's builder runs at a :class:`ProbeShapes` point, the
callable is traced with ``jax.make_jaxpr`` over ``ShapeDtypeStruct``
pytrees (no device buffers, no execution — abstract eval only, cost
independent of the probe shape), and anything the trace *itself* says
is captured:

- warnings (the "Explicitly requested dtype float64 ..." class — the
  only visible residue of a planted 64-bit literal when x64 is off) are
  recorded for the dtype-promotion pass;
- a ``TypeError`` naming a scan/while carry type mismatch is recorded
  as ``error_kind="carry-mismatch"`` (dtype-promotion owns it: the
  exact bug class a carry-dtype refactor introduces);
- any other exception is ``error_kind="trace"`` (the engine reports it
  as a ``trace-failure`` error — a broken manifest turns the gate red,
  never silently shrinks coverage).

Environment: the audit is CPU-only by policy (the ISSUE of record:
"traced shape-only on CPU — no device, no execution"), and the mesh
entries need >=8 virtual devices, so :func:`ensure_cpu_tracing_env`
must run BEFORE jax is first imported in this process.
"""

from __future__ import annotations

import dataclasses
import os
import warnings
from typing import List, Optional, Tuple

_DEVICE_FLAG = "--xla_force_host_platform_device_count"


def ensure_cpu_tracing_env() -> None:
    """Pin tracing to CPU with >=8 virtual devices. A no-op for any
    knob the caller already set explicitly; must run before the first
    ``import jax`` to take effect (harmless afterwards)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if _DEVICE_FLAG not in flags:
        os.environ["XLA_FLAGS"] = (flags + " " + _DEVICE_FLAG + "=8").strip()


@dataclasses.dataclass
class TracedProgram:
    name: str  # manifest entry name
    hp: object  # HotProgram
    shapes: object  # ProbeShapes this trace ran at
    path: str  # repo-relative file of the defining module
    line: int  # line of the manifest entry (suppression anchor)
    closed_jaxpr: Optional[object] = None
    arg_avals: Tuple = ()  # per-positional-arg flattened avals (donation)
    warnings: List[str] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    error_kind: Optional[str] = None  # "carry-mismatch" | "trace"


def _entry_lines(module_file: str) -> dict:
    """Manifest entry name -> line number of its key in the module's
    ``HOT_PROGRAMS`` dict literal (the noqa/baseline anchor line). The
    parse is the SAME one the manifest-contract pass uses
    (common.manifest_dict_literals), so findings anchor exactly to the
    lines the contract checks."""
    import ast

    from tools.analysis.common import manifest_dict_literals

    try:
        tree = ast.parse(
            open(module_file, encoding="utf-8").read(), filename=module_file
        )
    except (OSError, SyntaxError):
        return {}
    entries, _ = manifest_dict_literals(tree, "HOT_PROGRAMS")
    return {name: lineno for name, lineno, _ in entries}


def load_manifest(manifest_path: Optional[str] = None) -> dict:
    """``{name: (HotProgram, module_file, line)}`` — the package's
    collected manifest by default, or a single manifest module loaded
    from ``manifest_path`` (the fixture/test hook)."""
    if manifest_path is None:
        from k8s_spot_rescheduler_tpu.hot_programs import collect

        raw = collect()
    else:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "_audit_manifest", manifest_path
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        raw = {
            name: (hp, manifest_path)
            for name, hp in getattr(mod, "HOT_PROGRAMS", {}).items()
        }
    out = {}
    lines_by_file: dict = {}
    for name, (hp, module_file) in raw.items():
        if module_file not in lines_by_file:
            lines_by_file[module_file] = _entry_lines(module_file)
        line = lines_by_file[module_file].get(name, 1)
        out[name] = (hp, module_file, line)
    return out


def trace_entry(name, hp, module_file, line, shapes) -> TracedProgram:
    """Build and trace one manifest entry at one ProbeShapes point."""
    import jax

    from tools.analysis.common import relpath

    t = TracedProgram(
        name=name, hp=hp, shapes=shapes, path=relpath(module_file), line=line
    )
    try:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            built = hp.build(shapes)
            fn, args = built[0], built[1]
            static = tuple(built[2]) if len(built) > 2 else ()
            t.closed_jaxpr = jax.make_jaxpr(fn, static_argnums=static)(*args)
            t.arg_avals = tuple(
                tuple(jax.tree_util.tree_leaves(a))
                if i not in static
                else ()
                for i, a in enumerate(args)
            )
        t.warnings = [str(w.message) for w in caught]
    except TypeError as err:
        msg = str(err)
        t.error = msg
        t.error_kind = (
            "carry-mismatch"
            if "carry" in msg and ("differ" in msg or "equal types" in msg)
            else "trace"
        )
    except Exception as err:  # noqa: BLE001 — ANY builder/trace failure
        # must become a red finding, not an engine crash
        t.error = f"{type(err).__name__}: {err}"
        t.error_kind = "trace"
    return t


class TraceCache:
    """One trace per (entry, shapes) across all passes."""

    def __init__(self, manifest: dict):
        self.manifest = manifest
        self._cache: dict = {}

    def get(self, name, shapes) -> TracedProgram:
        key = (name, tuple(shapes))
        if key not in self._cache:
            hp, module_file, line = self.manifest[name]
            self._cache[key] = trace_entry(name, hp, module_file, line, shapes)
        return self._cache[key]
