"""Jaxpr-tier program auditor (``make audit-jaxpr``, docs/ANALYSIS.md).

The second analysis tier: where ``tools/analysis/passes`` vets the
SOURCE (AST + call graph), this package vets the PROGRAMS — each
``HOT_PROGRAMS`` manifest entry
(k8s_spot_rescheduler_tpu/hot_programs.py) is traced shape-only on CPU
(``jax.make_jaxpr`` over ``ShapeDtypeStruct``s; no device, no
execution) and four pass families run over the jaxprs:

- ``dtype-promotion`` — 64-bit upcasts, explicit 64-bit literals,
  scan/while carry dtype mismatches (tools/analysis/jaxpr/dtypes.py);
- ``index-width`` — interval analysis proving every derived index fits
  its dtype at the declared 20x max shapes (widths.py);
- ``transfer-audit`` — device_put/callback round-trips, by-value
  constant captures, donate_argnums aliasing (transfer.py);
- ``memory-reconcile`` — the traced program's buffer model vs
  solver/memory's HBM estimate at the boundary-pin shapes
  (memcheck.py).

Findings anchor to the manifest entry's line in its defining module,
so the shared ``# noqa`` grammar and baseline
(tools/analysis/common.py) apply unchanged. A failed trace is itself
an error (``trace-failure``): coverage can shrink loudly, never
silently. The AST-tier ``manifest-contract`` pass closes the loop from
the other side (every jit root must be in the manifest).
"""

from __future__ import annotations

from typing import List, Optional

JAXPR_PASS_NAMES = (
    "dtype-promotion",
    "index-width",
    "transfer-audit",
    "memory-reconcile",
)


def run_tier(
    manifest_path: Optional[str] = None, only_pass: Optional[str] = None
) -> List:
    """Trace the manifest and run the jaxpr passes; returns Findings.
    Imports jax — callers on the AST-only path never pay for this."""
    from tools.analysis.common import ERROR, Finding
    from tools.analysis.jaxpr import dtypes, memcheck, transfer, widths
    from tools.analysis.jaxpr.trace import (
        TraceCache,
        ensure_cpu_tracing_env,
        load_manifest,
    )

    ensure_cpu_tracing_env()  # must precede the first jax import
    from k8s_spot_rescheduler_tpu.hot_programs import (
        MAX_SHAPES,
        RECONCILE_SHAPES,
    )

    manifest = load_manifest(manifest_path)
    cache = TraceCache(manifest)
    findings: List[Finding] = []

    def want(name: str) -> bool:
        return only_pass is None or only_pass == name

    for name in sorted(manifest):
        hp, _, line = manifest[name]
        probe = MAX_SHAPES if hp.index_width else RECONCILE_SHAPES[0]
        t = cache.get(name, probe)
        if t.error is not None and t.error_kind != "carry-mismatch":
            findings.append(Finding(
                t.path, line, "trace-failure",
                f"hot program '{name}' failed to trace at "
                f"C={probe.C},S={probe.S}: {t.error[:300]} — a manifest "
                "entry that cannot trace is audit coverage silently "
                "lost; fix the builder or the program",
                severity=ERROR, anchor=f"{name}.trace", tier="jaxpr",
            ))
            continue
        if want("dtype-promotion"):
            findings.extend(dtypes.run(t))
        if t.closed_jaxpr is None:
            continue  # carry-mismatch: no jaxpr for the other passes
        if want("index-width") and hp.index_width:
            findings.extend(widths.run(t))
        if want("transfer-audit"):
            findings.extend(transfer.run(t))
        if want("memory-reconcile") and hp.reconcile is not None:
            traced_by_shape = [
                (s, cache.get(name, s)) for s in RECONCILE_SHAPES
            ]
            findings.extend(
                memcheck.reconcile(traced_by_shape, name, hp, t.path, line)
            )
    return findings
