"""memory-reconcile: the HBM estimator must track the traced program.

``solver/memory.estimate_union_hbm_bytes`` sizes every dispatch
decision — single-chip vs cand-sharded vs 2-D, and
``pick_repair_chunks``'s chunk count. It is hand-derived from the union
program's buffer structure, so nothing stops it rotting as kernels
change — until the drift strands a config on the wrong tier (phantom
reroute) or OOMs a chip the estimate said was fine. This pass re-derives
a buffer model FROM THE TRACED JAXPR at the measured boundary-pin
shapes (hot_programs.RECONCILE_SHAPES, the same points
tests/test_sharding.py pins against hardware reality) and fails on
drift beyond tolerance.

The jaxpr model (jaxpr_utils.live_model) tracks buffer liveness, which
over-counts XLA's fused reality by a program-dependent but
SCALE-STABLE factor — so the checks are ratio bands, calibrated at
introduction (values in docs/ANALYSIS.md):

- ``carries``: estimator carries vs 2x the largest scan carry — the
  one exact correspondence (measured ratio 1.00 across every variant
  and scale); band :data:`CARRY_BAND`. This is the check ROADMAP-5's
  narrow-int carry packing must keep green: repack the carry without
  resizing the estimator and the ratio jumps 4x.
- ``inputs``: estimator slots+spot_static vs summed invar avals
  (measured ~1.0); band :data:`INPUT_BAND`.
- ``total``: estimator total vs modeled peak (measured 0.31-0.55 by
  variant — liveness over-counts fusion); band :data:`TOTAL_BAND`. The
  upper bound also catches the reverse rot: kernels shrink, estimator
  doesn't, and configs get rerouted off chips they fit.
- ``scale``: the est/peak ratio at 4x vs 1x must agree within
  :data:`SCALE_DRIFT_MAX` — the estimator's asymptotics match the
  program's.

On any failure the finding carries the per-component table
(solver/memory.estimate_union_hbm_breakdown vs the jaxpr model), so
the report names WHICH buffer family drifted, not just the sum.
"""

from __future__ import annotations

from typing import List

from tools.analysis.common import ERROR, Finding
from tools.analysis.jaxpr.jaxpr_utils import live_model

CARRY_BAND = (0.7, 1.4)
INPUT_BAND = (0.7, 1.4)
TOTAL_BAND = (0.25, 0.9)
# Carry-streamed entries (reconcile spec carry_chunks >= 1) get a
# lower total floor: their elect-then-commit lax.map inside the slot
# scan makes the liveness model charge a third stacked-state copy
# XLA's ping-ponged loop buffers never materialize (measured ratio
# 0.23 at both reconcile scales; wide-layout programs keep the 0.25
# floor and still measure 0.31-0.55 — docs/ANALYSIS.md table).
CARRY_TOTAL_FLOOR = 0.20
SCALE_DRIFT_MAX = 0.15


def _breakdown(hp, shapes) -> dict:
    spec = hp.reconcile or {}
    if "estimator" in spec:
        return dict(spec["estimator"](shapes))
    from k8s_spot_rescheduler_tpu.solver.memory import (
        estimate_union_hbm_breakdown,
    )

    # carry_chunks >= 1 reconciles against the carry-streamed NARROW
    # layout (solver/carry.NARROW_LAYOUT plane bytes — the layout the
    # streamed hot programs trace with), the ROADMAP-5 regression gate
    return estimate_union_hbm_breakdown(
        shapes.C, shapes.K, shapes.S, shapes.R, shapes.W, shapes.A,
        repair_spot_chunks=spec.get("repair_spot_chunks", 1),
        carry_chunks=spec.get("carry_chunks", 0),
    )


def _component_table(est: dict, model: dict) -> str:
    est_lines = ", ".join(
        f"{k}={v / 1e6:.1f}MB" for k, v in sorted(est.items())
    )
    model_lines = ", ".join(
        f"{k}={v / 1e6:.1f}MB" for k, v in sorted(model.items())
    )
    return f"estimator[{est_lines}] vs traced[{model_lines}]"


def reconcile(traced_by_shape, name, hp, path, line) -> List[Finding]:
    """``traced_by_shape``: [(shapes, TracedProgram)] at the reconcile
    probe points, smallest first."""
    findings: List[Finding] = []

    def fail(check: str, message: str) -> None:
        findings.append(Finding(
            path, line, "memory-reconcile",
            f"hot program '{name}': {message}",
            severity=ERROR, anchor=f"{name}.{check}", tier="jaxpr",
        ))

    ratios = []
    for shapes, t in traced_by_shape:
        if t.closed_jaxpr is None:
            # the engine's trace-failure check covers only the max-shape
            # probe; a reconcile probe that cannot trace must be loud
            # too, or the HBM-drift gate goes silently green
            findings.append(Finding(
                path, line, "trace-failure",
                f"hot program '{name}' failed to trace at the "
                f"memory-reconcile probe C={shapes.C},S={shapes.S}: "
                f"{(t.error or 'no jaxpr')[:300]} — the HBM estimator "
                "cannot be reconciled against a program that does not "
                "trace",
                severity=ERROR, anchor=f"{name}.trace.C{shapes.C}",
                tier="jaxpr",
            ))
            continue
        model = live_model(t.closed_jaxpr.jaxpr)
        est = _breakdown(hp, shapes)
        est_total = sum(est.values())
        table = _component_table(est, model)

        carry_est = est.get("carries", 0)
        if model["carries"] and not (
            CARRY_BAND[0]
            <= carry_est / model["carries"]
            <= CARRY_BAND[1]
        ):
            fail(
                "carries",
                f"'carries' drifted: estimator {carry_est / 1e6:.1f}MB vs "
                f"2x traced scan carry {model['carries'] / 1e6:.1f}MB "
                f"(ratio {carry_est / model['carries']:.2f}, band "
                f"{CARRY_BAND}) at C={shapes.C},S={shapes.S} — the scan "
                f"state changed shape/dtype without the estimator; "
                f"{table}",
            )
        in_est = est.get("slots", 0) + est.get("spot_static", 0)
        if model["inputs"] and not (
            INPUT_BAND[0] <= in_est / model["inputs"] <= INPUT_BAND[1]
        ):
            fail(
                "inputs",
                f"'slots+spot_static' drifted: estimator "
                f"{in_est / 1e6:.1f}MB vs traced program inputs "
                f"{model['inputs'] / 1e6:.1f}MB (ratio "
                f"{in_est / model['inputs']:.2f}, band {INPUT_BAND}) at "
                f"C={shapes.C},S={shapes.S}; {table}",
            )
        if model["peak"]:
            r = est_total / model["peak"]
            ratios.append((shapes, r))
            total_band = (
                (CARRY_TOTAL_FLOOR, TOTAL_BAND[1])
                if (hp.reconcile or {}).get("carry_chunks")
                else TOTAL_BAND
            )
            if not (total_band[0] <= r <= total_band[1]):
                fail(
                    "total",
                    f"total drifted: estimator {est_total / 1e6:.1f}MB vs "
                    f"modeled peak {model['peak'] / 1e6:.1f}MB (ratio "
                    f"{r:.2f}, band {total_band}) at C={shapes.C},"
                    f"S={shapes.S}; {table}",
                )
    if len(ratios) >= 2:
        (s0, r0), (s1, r1) = ratios[0], ratios[-1]
        if r0 and abs(r1 - r0) / r0 > SCALE_DRIFT_MAX:
            fail(
                "scale",
                f"est/peak ratio is scale-dependent: {r0:.3f} at "
                f"C={s0.C} vs {r1:.3f} at C={s1.C} (max drift "
                f"{SCALE_DRIFT_MAX:.0%}) — the estimator's asymptotics "
                "no longer match the traced program",
            )
    return findings
