"""transfer-audit: no host round-trips, by-value captures, or
silently-copying donations in hot jaxprs.

Three buffer-movement properties the AST tier cannot see (they exist
only in what actually traced):

- **pinned transfers** — a ``device_put`` or host callback
  (``pure_callback``/``io_callback``/``debug_callback``) primitive
  inside a hot program re-serializes every call against the host
  (error);
- **by-value constants** — a concrete array closed over at trace time
  becomes a jaxpr const: it ships with the executable and re-uploads
  per compile instead of riding the argument path once
  (error past :data:`CONST_BYTES_LIMIT`; tiny scalars/offsets are the
  normal residue of static shape math);
- **donation aliasing** — every position named in an entry's
  ``donate_argnums`` must alias some output (shape+dtype multiset
  match). A donated-but-unaliasable buffer is silently COPIED by XLA:
  the caller loses the input (API contract) and gains no in-place
  update — for the delta scatter that would double the resident
  cluster's footprint (error).
"""

from __future__ import annotations

from typing import List

from tools.analysis.common import ERROR, Finding
from tools.analysis.jaxpr.jaxpr_utils import eqn_source, iter_eqns

# a const bigger than this cannot be shape bookkeeping — it is cluster
# state captured by value (the chunk-offset iotas of the chunked repair
# are < 4 KiB at any plausible chunk count)
CONST_BYTES_LIMIT = 64 * 1024

_TRANSFER_PRIMS = {"device_put"}
_CALLBACK_PRIMS = {
    "pure_callback", "io_callback", "debug_callback", "callback",
}


def run(traced) -> List[Finding]:
    import numpy as np

    t = traced
    if t.closed_jaxpr is None:
        return []
    findings: List[Finding] = []

    seen_prims = set()
    for eqn in iter_eqns(t.closed_jaxpr.jaxpr):
        name = eqn.primitive.name
        if name in _TRANSFER_PRIMS and name not in seen_prims:
            seen_prims.add(name)
            findings.append(Finding(
                t.path, t.line, "transfer-audit",
                f"hot program '{t.name}' embeds a {name} op"
                f"{eqn_source(eqn)} — a device placement pinned inside "
                "the traced program forces a transfer per call; move it "
                "to the call boundary",
                severity=ERROR, anchor=f"{t.name}.{name}", tier="jaxpr",
            ))
        elif name in _CALLBACK_PRIMS and name not in seen_prims:
            seen_prims.add(name)
            findings.append(Finding(
                t.path, t.line, "transfer-audit",
                f"hot program '{t.name}' embeds a host callback "
                f"({name}){eqn_source(eqn)} — the device pipeline "
                "drains on every call; hot programs must stay "
                "device-only",
                severity=ERROR, anchor=f"{t.name}.{name}", tier="jaxpr",
            ))

    for i, const in enumerate(t.closed_jaxpr.consts):
        try:
            nbytes = int(np.asarray(const).nbytes)
        except Exception:  # noqa: BLE001 — non-array const: no buffer
            continue
        if nbytes > CONST_BYTES_LIMIT:
            findings.append(Finding(
                t.path, t.line, "transfer-audit",
                f"hot program '{t.name}' captures a "
                f"{nbytes / 1024:.0f} KiB constant by value (const #{i}, "
                f"shape {np.shape(const)}) — closed-over concrete arrays "
                "ship with the executable and re-upload per compile; "
                "pass them as arguments",
                severity=ERROR, anchor=f"{t.name}.const{i}",
                tier="jaxpr",
            ))

    if t.hp.donate_argnums:
        # multiset match donated input avals against output avals — the
        # aliasing rule XLA applies (shape+dtype equality)
        out_pool: dict = {}
        for v in t.closed_jaxpr.jaxpr.outvars:
            key = (tuple(v.aval.shape), str(v.aval.dtype))
            out_pool[key] = out_pool.get(key, 0) + 1
        for pos in t.hp.donate_argnums:
            if pos >= len(t.arg_avals):
                findings.append(Finding(
                    t.path, t.line, "transfer-audit",
                    f"hot program '{t.name}' declares donate_argnums "
                    f"position {pos} but traces only "
                    f"{len(t.arg_avals)} arguments",
                    severity=ERROR, anchor=f"{t.name}.donate{pos}",
                    tier="jaxpr",
                ))
                continue
            for aval in t.arg_avals[pos]:
                key = (tuple(aval.shape), str(np.dtype(aval.dtype)))
                if out_pool.get(key, 0) > 0:
                    out_pool[key] -= 1
                else:
                    findings.append(Finding(
                        t.path, t.line, "transfer-audit",
                        f"hot program '{t.name}' donates argument {pos} "
                        f"({key[1]}{list(key[0])}) but NO output matches "
                        "its shape/dtype — XLA copies instead of "
                        "aliasing: the caller loses the buffer and gains "
                        "no in-place update",
                        severity=ERROR, anchor=f"{t.name}.donate{pos}",
                        tier="jaxpr",
                    ))
    return findings
