"""dtype-promotion: no 64-bit or silently-promoted values in hot jaxprs.

The solver's numeric contract (solver/ffd.py layout notes): capacities
are float32 integers < 2**24, masks are uint32, indices int32. A single
accidental float64/int64 doubles the HBM of every buffer it touches and
halves TPU throughput; a carry-dtype mismatch across a
``lax.scan``/``while_loop`` silently re-promotes per step — exactly the
bug class the ROADMAP-5 int8/bit-packed carry refactor will create.
Three checks per traced program:

- **explicit 64-bit requests**: with x64 off, a planted
  ``jnp.float64``/``int64`` literal leaves NO trace in the jaxpr (JAX
  downcasts it) — its only residue is the "Explicitly requested dtype
  ... float64" warning, which the tracer records and this pass turns
  into an error;
- **64-bit avals**: any f64/i64/u64/c128 var anywhere in the traced
  program (belt for configs that enable x64);
- **carry mismatches**: a scan/while whose carry-in and carry-out types
  differ fails AT TRACE TIME — the tracer classifies that TypeError as
  ``carry-mismatch`` and this pass owns the finding.

Int->float converts of non-bool integer operands are reported at warn
tier: in this codebase's programs every intended int->float move is a
bool mask widening (``onehot * req``), so an i32->f32 convert usually
means an integer count leaked into float arithmetic (precision cliff at
2**24).
"""

from __future__ import annotations

from typing import List

from tools.analysis.common import ERROR, WARN, Finding
from tools.analysis.jaxpr.jaxpr_utils import eqn_source, iter_avals, iter_eqns

_WIDE = {"float64", "int64", "uint64", "complex128"}

_REQUEST_MARKERS = ("float64", "int64", "uint64", "complex128")


def run(traced) -> List[Finding]:
    """``traced``: TracedPrograms of one entry (the engine calls per
    entry, max-shape probe)."""
    findings: List[Finding] = []
    t = traced
    if t.error_kind == "carry-mismatch":
        findings.append(Finding(
            t.path, t.line, "dtype-promotion",
            f"hot program '{t.name}' fails to trace: scan/while carry "
            f"dtype mismatch — {t.error.splitlines()[0][:200]}",
            severity=ERROR, anchor=f"{t.name}.carry", tier="jaxpr",
        ))
        return findings
    if t.closed_jaxpr is None:
        return findings  # trace-failure reported by the engine

    for w in t.warnings:
        if "Explicitly requested dtype" in w and any(
            m in w for m in _REQUEST_MARKERS
        ):
            findings.append(Finding(
                t.path, t.line, "dtype-promotion",
                f"hot program '{t.name}' explicitly requests a 64-bit "
                f"dtype while tracing (JAX downcasts it silently with "
                f"x64 off, doubles HBM with it on): {w[:160]}",
                severity=ERROR, anchor=f"{t.name}.request64",
                tier="jaxpr",
            ))
            break  # one finding per entry: the warning repeats per op

    wide_seen = set()
    for _, aval in iter_avals(t.closed_jaxpr.jaxpr):
        name = getattr(getattr(aval, "dtype", None), "name", "")
        if name in _WIDE and name not in wide_seen:
            wide_seen.add(name)
            findings.append(Finding(
                t.path, t.line, "dtype-promotion",
                f"hot program '{t.name}' traces with a {name} value — "
                "the solver contract is 32-bit (f32 capacities, u32 "
                "masks, i32 indices); a 64-bit buffer doubles HBM and "
                "halves TPU throughput",
                severity=ERROR, anchor=f"{t.name}.{name}", tier="jaxpr",
            ))

    seen_msgs = set()
    for eqn in iter_eqns(t.closed_jaxpr.jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        src = getattr(eqn.invars[0], "aval", None)
        dst = eqn.outvars[0].aval
        s_dt = getattr(getattr(src, "dtype", None), "name", "")
        d_dt = getattr(dst.dtype, "name", "")
        if (
            s_dt.startswith(("int", "uint"))
            and s_dt not in ("", "bool")
            and d_dt.startswith("float")
            and getattr(src.dtype, "itemsize", 0) >= 2
        ):
            msg = (
                f"hot program '{t.name}': {s_dt}->{d_dt} promotion"
                f"{eqn_source(eqn)} — an integer value entered float "
                "arithmetic (exact only below 2**24); widen deliberately "
                "or keep it integral"
            )
            if msg not in seen_msgs:
                seen_msgs.add(msg)
                findings.append(Finding(
                    t.path, t.line, "dtype-promotion", msg,
                    severity=WARN,
                    anchor=f"{t.name}.{s_dt}-{d_dt}", tier="jaxpr",
                ))
    return findings
