"""Shared jaxpr-walking helpers for the jaxpr-tier passes."""

from __future__ import annotations

from typing import Iterator, Tuple


def aval_bytes(aval) -> int:
    """Buffer size of a shaped aval (0 for abstract tokens etc.)."""
    try:
        n = 1
        for d in aval.shape:
            n *= int(d)
        return n * aval.dtype.itemsize
    except Exception:  # noqa: BLE001 — non-array avals carry no bytes
        return 0


def subjaxprs(eqn) -> Iterator:
    """Inner (open) jaxprs of a higher-order eqn, unwrapped."""
    import jax.core as jcore

    for v in eqn.params.values():
        for x in v if isinstance(v, (tuple, list)) else [v]:
            if isinstance(x, jcore.ClosedJaxpr):
                yield x.jaxpr
            elif isinstance(x, jcore.Jaxpr):
                yield x


def iter_jaxprs(jaxpr) -> Iterator:
    """The jaxpr and every nested jaxpr, depth-first."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for sub in subjaxprs(eqn):
            yield from iter_jaxprs(sub)


def iter_eqns(jaxpr) -> Iterator:
    for j in iter_jaxprs(jaxpr):
        yield from j.eqns


def iter_avals(jaxpr) -> Iterator[Tuple[object, object]]:
    """(var, aval) over every var of the program, nested included."""
    import jax.core as jcore

    for j in iter_jaxprs(jaxpr):
        for v in list(j.invars) + list(j.constvars):
            yield v, v.aval
        for eqn in j.eqns:
            for v in eqn.outvars:
                yield v, v.aval
            for v in eqn.invars:
                if isinstance(v, jcore.Literal):
                    yield v, v.aval


def eqn_src(eqn):
    """Best-effort (file, line) of the user code an eqn traced from,
    or None (internal jax API; degrades to no hint, never an error)."""
    try:
        import jax._src.source_info_util as siu

        frame = siu.user_frame(eqn.source_info)
        if frame is not None:
            return frame.file_name, frame.start_line
    except Exception:  # noqa: BLE001 — internal API: degrade to no hint
        pass
    return None


def eqn_source(eqn) -> str:
    """Human-readable location suffix for finding messages ('' when
    unavailable — message quality only, never correctness)."""
    src = eqn_src(eqn)
    return f" (traced at {src[0]}:{src[1]})" if src else ""


def live_model(jaxpr) -> dict:
    """Linear-scan peak-live-bytes model of a jaxpr.

    Returns ``{"peak", "carries", "inputs", "outputs"}``:

    - ``inputs``/``outputs``: summed invar(+const) / outvar aval bytes;
    - ``carries``: the largest double-buffered scan carry anywhere in
      the program (2x the carry avals — the scan's in-flight pair), the
      sharp term the HBM estimator must track;
    - ``peak``: last-use liveness scan over the eqn list. A
      higher-order eqn contributes its body's peak MINUS its body's
      input bytes (inner invars alias outer live buffers — counting
      both would double-charge), and a scan additionally keeps one
      extra carry copy live (the double buffer).

    This deliberately models buffer *liveness*, not XLA's fused
    allocation (fusion materializes fewer temporaries than liveness
    implies); memory-reconcile therefore compares RATIOS against the
    estimator, with the bands calibrated in docs/ANALYSIS.md.
    """
    import jax.core as jcore

    last_use: dict = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if isinstance(v, jcore.Var):
                last_use[id(v)] = i
    n = len(jaxpr.eqns)
    for v in jaxpr.outvars:
        if isinstance(v, jcore.Var):
            last_use[id(v)] = n

    inputs = sum(
        aval_bytes(v.aval)
        for v in list(jaxpr.invars) + list(jaxpr.constvars)
    )
    outputs = sum(aval_bytes(v.aval) for v in jaxpr.outvars)
    live = inputs
    peak = live
    max_carry = 0
    for i, eqn in enumerate(jaxpr.eqns):
        transient = 0
        for sub in subjaxprs(eqn):
            inner = live_model(sub)
            transient = max(transient, max(0, inner["peak"] - inner["inputs"]))
            max_carry = max(max_carry, inner["carries"])
        if eqn.primitive.name == "scan":
            nc = eqn.params.get("num_carry", 0)
            carry_bytes = sum(
                aval_bytes(v.aval) for v in eqn.outvars[:nc]
            )
            max_carry = max(max_carry, 2 * carry_bytes)
            transient += carry_bytes  # the second buffer of the pair
        live += sum(aval_bytes(v.aval) for v in eqn.outvars)
        peak = max(peak, live + transient)
        seen = set()
        for v in list(eqn.invars) + list(eqn.outvars):
            if isinstance(v, jcore.Var) and id(v) not in seen:
                seen.add(id(v))
                if last_use.get(id(v)) == i:
                    live -= aval_bytes(v.aval)
    return {
        "peak": peak,
        "carries": max_carry,
        "inputs": inputs,
        "outputs": outputs,
    }
