"""index-width: interval analysis over index-producing ops at the
declared max shapes.

The gate that makes ROADMAP-5's narrow-int carry packing safe to
attempt: every value range the traced program can produce must fit the
dtype that carries it AT :data:`hot_programs.MAX_SHAPES` (the 20x
target, 1M pods / 100k nodes). A flattened ``C*S`` offset is 2.6e9
there — past int32 — and XLA wraps silently.

Abstract interpretation over the jaxpr: each var maps to a closed
interval ``(lo, hi)`` in exact Python arithmetic, or ``None`` (unknown).
Sources of known ranges are the *structural* quantities — ``iota``
(``[0, n-1]``), ``argmax``/``argmin`` (``[0, axis-1]``),
``axis_index`` (``[0, mesh_axis-1]``), literals and small consts —
propagated through shape/arith/select/reduce ops, widened through scan
carries to a bounded fixpoint, and dropped to unknown anywhere the
transfer is not modeled. Program *inputs* are unknown by design:
intervals prove facts about the indices the program derives, not about
what the cluster feeds it (an input-derived sum may legitimately span
its dtype).

Checks (error tier):

- every integer (non-bool) eqn output whose interval is known must fit
  its dtype's range — this is where ``i32(C) * i32(S)`` overflow
  surfaces;
- every ``convert_element_type`` to a narrower integer must fit the
  target (the narrow-int packing check);
- structurally, an ``iota``/``argmax``/``argsort`` whose axis length
  alone exceeds its index dtype is reported even when intervals are
  unknown.

Precision beats recall (the suite's standing rule): an unmodeled
primitive yields unknown and costs coverage, never a false error.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from tools.analysis.common import ERROR, Finding
from tools.analysis.jaxpr.jaxpr_utils import eqn_source, eqn_src, subjaxprs

Interval = Optional[Tuple[float, float]]

_SCAN_FIXPOINT_ITERS = 3


def _dtype_range(dtype):
    import numpy as np

    name = dtype.name
    if name == "bool":
        return (0, 1)
    if name.startswith(("int", "uint")):
        info = np.iinfo(dtype)
        return (int(info.min), int(info.max))
    return None


def _finite(x) -> bool:
    return isinstance(x, (int, float)) and not (
        math.isnan(x) if isinstance(x, float) else False
    )


def _mk(lo, hi) -> Interval:
    if not _finite(lo) or not _finite(hi):
        return None
    if isinstance(lo, float) and math.isinf(lo) and lo > 0:
        return None
    if isinstance(hi, float) and math.isinf(hi) and hi < 0:
        return None
    return (lo, hi)


def _union(a: Interval, b: Interval) -> Interval:
    if a is None or b is None:
        return None
    return (min(a[0], b[0]), max(a[1], b[1]))


def _arith(op, a: Interval, b: Interval) -> Interval:
    if a is None or b is None:
        return None
    try:
        combos = [op(x, y) for x in a for y in b]
    except (OverflowError, ZeroDivisionError, ValueError):
        return None
    if any(isinstance(c, float) and math.isnan(c) for c in combos):
        return None
    return _mk(min(combos), max(combos))


def _const_interval(value) -> Interval:
    import numpy as np

    try:
        arr = np.asarray(value)
        if arr.size == 0 or arr.dtype.kind not in "biuf":
            return None
        lo, hi = arr.min(), arr.max()
        if arr.dtype.kind == "f" and not (
            np.isfinite(lo) and np.isfinite(hi)
        ):
            lo = float(lo) if np.isfinite(lo) else float("-inf")
            hi = float(hi) if np.isfinite(hi) else float("inf")
            return _mk(lo, hi)
        if arr.dtype.kind == "b":
            return (int(lo), int(hi))
        if arr.dtype.kind in "iu":
            return (int(lo), int(hi))
        return (float(lo), float(hi))
    except Exception:  # noqa: BLE001 — unintervalable const: unknown
        return None


class _Analyzer:
    """One program's interval walk; findings dedupe by eqn site (the
    scan-carry fixpoint revisits body eqns with widened intervals, and
    one defect must stay one finding)."""

    def __init__(self, report):
        self._report = report  # callable(check_name, eqn, message)
        self._mesh_sizes: Dict[str, int] = {}

    # -- environment helpers ------------------------------------------

    def _read(self, env, v) -> Interval:
        import jax.core as jcore

        if isinstance(v, jcore.Literal):
            return _const_interval(v.val)
        return env.get(id(v))

    def _check_fits(self, eqn, aval, interval: Interval) -> None:
        if interval is None:
            return
        rng = _dtype_range(getattr(aval, "dtype", None)) if hasattr(
            aval, "dtype"
        ) else None
        if rng is None or getattr(aval.dtype, "name", "") == "bool":
            return
        lo, hi = interval
        if lo < rng[0] or hi > rng[1]:
            self._report(
                "overflow",
                eqn,
                f"{eqn.primitive.name} produces values in "
                f"[{lo:.0f}, {hi:.0f}] carried as {aval.dtype.name} "
                f"(range [{rng[0]}, {rng[1]}]){eqn_source(eqn)} — "
                "silent wraparound at the declared max shapes",
            )

    # -- structural checks (fire even with unknown intervals) ---------

    def _structural(self, eqn) -> None:
        name = eqn.primitive.name
        if name == "iota":
            dtype = eqn.params.get("dtype")
            shape = eqn.params.get("shape") or ()
            dim = eqn.params.get("dimension", 0)
            rng = _dtype_range(dtype) if dtype is not None else None
            if rng and shape and int(shape[dim]) - 1 > rng[1]:
                self._report(
                    "iota-width",
                    eqn,
                    f"iota of length {int(shape[dim])} carried as "
                    f"{dtype.name} (max {rng[1]}){eqn_source(eqn)}",
                )
        elif name in ("argmax", "argmin"):
            axes = eqn.params.get("axes") or ()
            idx_dtype = eqn.params.get("index_dtype")
            operand = eqn.invars[0].aval
            rng = _dtype_range(idx_dtype) if idx_dtype is not None else None
            for ax in axes:
                if rng and int(operand.shape[ax]) - 1 > rng[1]:
                    self._report(
                        "arg-width",
                        eqn,
                        f"{name} over an axis of {int(operand.shape[ax])} "
                        f"indexed as {idx_dtype.name} (max {rng[1]})"
                        f"{eqn_source(eqn)}",
                    )
        elif name in ("sort", "argsort"):
            # argsort indices ride the output dtype of the index operand
            operand = eqn.invars[0].aval
            dim = eqn.params.get("dimension", -1)
            n = int(operand.shape[dim])
            for out in eqn.outvars:
                rng = _dtype_range(getattr(out.aval, "dtype", None))
                if (
                    rng
                    and getattr(out.aval.dtype, "kind", "") in "iu"
                    and n - 1 > rng[1]
                ):
                    self._report(
                        "sort-width",
                        eqn,
                        f"{name} over an axis of {n} with "
                        f"{out.aval.dtype.name} indices (max {rng[1]})"
                        f"{eqn_source(eqn)}",
                    )

    # -- transfer functions -------------------------------------------

    def _apply(self, eqn, ins: List[Interval]) -> List[Interval]:
        name = eqn.primitive.name
        p = eqn.params
        one = [None] * len(eqn.outvars)

        passthrough = {
            "broadcast_in_dim", "reshape", "transpose", "squeeze",
            "slice", "rev", "copy", "reduce_max", "reduce_min",
            "dynamic_slice", "gather", "expand_dims", "real",
            "stop_gradient", "reduce_precision",
        }
        if name in passthrough:
            return [ins[0]]
        if name == "convert_element_type":
            return [ins[0]]
        if name == "iota":
            shape = p.get("shape") or (0,)
            dim = p.get("dimension", 0)
            return [(0, max(0, int(shape[dim]) - 1))]
        if name == "axis_index":
            size = self._mesh_sizes.get(p.get("axis_name"))
            return [(0, size - 1)] if size else one
        if name in ("argmax", "argmin"):
            axes = p.get("axes") or (0,)
            n = int(eqn.invars[0].aval.shape[axes[0]])
            return [(0, max(0, n - 1))]
        if name == "add":
            return [_arith(lambda x, y: x + y, ins[0], ins[1])]
        if name == "sub":
            return [_arith(lambda x, y: x - y, ins[0], ins[1])]
        if name == "mul":
            return [_arith(lambda x, y: x * y, ins[0], ins[1])]
        if name == "div":
            return [_arith(lambda x, y: x / y if y else float("nan"),
                           ins[0], ins[1])]
        if name == "rem":
            b = ins[1]
            if b is not None:
                k = max(abs(b[0]), abs(b[1]))
                return [(-k, k)] if k else one
            return one
        if name == "max":
            return [_arith(max, ins[0], ins[1])]
        if name == "min":
            return [_arith(min, ins[0], ins[1])]
        if name == "neg":
            return [None if ins[0] is None else (-ins[0][1], -ins[0][0])]
        if name == "abs":
            if ins[0] is None:
                return one
            lo, hi = ins[0]
            alo = 0 if lo <= 0 <= hi else min(abs(lo), abs(hi))
            return [(alo, max(abs(lo), abs(hi)))]
        if name == "sign":
            return [(-1, 1)]
        if name in ("floor", "ceil", "round", "clamp"):
            if name == "clamp":
                lo = ins[0][0] if ins[0] else None
                hi = ins[2][1] if ins[2] else None
                mid = ins[1]
                if lo is not None and hi is not None:
                    return [(lo, hi)]
                return [mid]
            return [ins[0]]
        if name in ("eq", "ne", "lt", "le", "gt", "ge", "is_finite"):
            return [(0, 1)]
        if name in ("and", "or", "xor", "not"):
            if all(
                getattr(v.aval.dtype, "name", "") == "bool"
                for v in eqn.outvars
            ):
                return [(0, 1)]
            return one
        if name == "select_n":
            out = ins[1] if len(ins) > 1 else None
            for case in ins[2:]:
                out = _union(out, case)
            return [out]
        if name == "reduce_sum":
            if ins[0] is None:
                return one
            axes = p.get("axes") or ()
            shape = eqn.invars[0].aval.shape
            n = 1
            for ax in axes:
                n *= int(shape[ax])
            lo, hi = ins[0]
            return [_mk(min(n * lo, 0 if n == 0 else n * lo),
                        max(n * hi, 0 if n == 0 else n * hi))
                    if n else (0, 0)]
        if name in ("cumsum", "cumlogsumexp", "cummax", "cummin",
                    "cumprod"):
            if name in ("cummax", "cummin"):
                return [ins[0]]
            if name != "cumsum" or ins[0] is None:
                return one
            axis = p.get("axis", 0)
            n = int(eqn.invars[0].aval.shape[axis])
            lo, hi = ins[0]
            return [_mk(min(lo, n * lo), max(hi, n * hi))]
        if name in ("reduce_and", "reduce_or"):
            return [(0, 1)]
        if name == "concatenate":
            out = ins[0]
            for nxt in ins[1:]:
                out = _union(out, nxt)
            return [out]
        if name == "pad":
            return [_union(ins[0], ins[1] if len(ins) > 1 else None)]
        if name in ("dynamic_update_slice", "scatter", "scatter-add"):
            return [_union(ins[0], ins[-1] if len(ins) > 1 else None)]
        if name == "scan":
            return self._scan(eqn, ins)
        if name == "while":
            return self._while(eqn, ins)
        if name == "cond":
            return self._cond(eqn, ins)
        if name == "shard_map":
            return self._shard_map(eqn, ins)
        if name == "pmin":
            return [ins[0]]
        if name == "pmax":
            return [ins[0]]
        # generic call-like wrappers (pjit, remat, custom_*, closed_call):
        # recurse when exactly one inner jaxpr matches the invars arity
        subs = [
            s for s in subjaxprs(eqn) if len(s.invars) == len(eqn.invars)
        ]
        if len(subs) >= 1 and name not in ("pallas_call",):
            outs = self._eval(subs[0], ins)
            if len(outs) == len(eqn.outvars):
                return outs
        # unmodeled: still walk inner jaxprs for structural checks
        for s in subjaxprs(eqn):
            self._eval(s, [None] * len(s.invars))
        return one

    # -- higher-order primitives --------------------------------------

    def _scan(self, eqn, ins: List[Interval]) -> List[Interval]:
        p = eqn.params
        body = p["jaxpr"].jaxpr
        n_const = p.get("num_consts", 0)
        n_carry = p.get("num_carry", 0)
        consts = ins[:n_const]
        carries = list(ins[n_const:n_const + n_carry])
        xs = ins[n_const + n_carry:]  # leading axis sliced: same interval
        ys: List[Interval] = []
        for _ in range(_SCAN_FIXPOINT_ITERS):
            outs = self._eval(body, consts + carries + xs)
            new_carries = outs[:n_carry]
            ys = outs[n_carry:]
            widened = [
                _union(c, nc) for c, nc in zip(carries, new_carries)
            ]
            if widened == carries:
                break
            carries = widened
        else:
            # not converged: carries unknown, re-eval once for ys/checks
            carries = [None] * n_carry
            outs = self._eval(body, consts + carries + xs)
            ys = outs[n_carry:]
        return carries + ys

    def _while(self, eqn, ins: List[Interval]) -> List[Interval]:
        p = eqn.params
        body = p["body_jaxpr"].jaxpr
        n_body_const = p.get("body_nconsts", 0)
        n_cond_const = p.get("cond_nconsts", 0)
        consts = ins[n_cond_const:n_cond_const + n_body_const]
        n_carry = len(eqn.invars) - n_cond_const - n_body_const
        carries: List[Interval] = [None] * n_carry
        self._eval(body, consts + carries)  # structural checks only
        return [None] * len(eqn.outvars)

    def _cond(self, eqn, ins: List[Interval]) -> List[Interval]:
        branches = eqn.params.get("branches") or ()
        operands = ins[1:]
        out: Optional[List[Interval]] = None
        for br in branches:
            body = br.jaxpr if hasattr(br, "jaxpr") else br
            outs = self._eval(body, list(operands))
            if out is None:
                out = outs
            else:
                out = [_union(a, b) for a, b in zip(out, outs)]
        return out if out is not None else [None] * len(eqn.outvars)

    def _shard_map(self, eqn, ins: List[Interval]) -> List[Interval]:
        body = eqn.params.get("jaxpr")
        if body is None:
            return [None] * len(eqn.outvars)
        if hasattr(body, "jaxpr"):
            body = body.jaxpr
        mesh = eqn.params.get("mesh")
        saved = dict(self._mesh_sizes)
        try:
            shape = getattr(mesh, "shape", None)
            if shape:
                self._mesh_sizes.update(
                    {k: int(v) for k, v in dict(shape).items()}
                )
        except Exception:  # noqa: BLE001 — mesh introspection best-effort
            pass
        try:
            # sharding slices values, never transforms them: intervals
            # pass through both directions
            outs = self._eval(body, list(ins))
        finally:
            self._mesh_sizes = saved
        if len(outs) == len(eqn.outvars):
            return outs
        return [None] * len(eqn.outvars)

    # -- driver --------------------------------------------------------

    def _eval(
        self, jaxpr, in_intervals: List[Interval], const_ivs=None
    ) -> List[Interval]:
        env: Dict[int, Interval] = {}
        for v, iv in zip(jaxpr.invars, in_intervals):
            env[id(v)] = iv
        for i, v in enumerate(jaxpr.constvars):
            # top level: traced-in consts carry real intervals; nested
            # jaxprs' constvars are caller-bound and unknown here
            env[id(v)] = const_ivs[i] if const_ivs else None
        for eqn in jaxpr.eqns:
            self._structural(eqn)
            ins = [self._read(env, v) for v in eqn.invars]
            try:
                outs = self._apply(eqn, ins)
            except Exception:  # noqa: BLE001 — a transfer bug must cost
                # recall (unknown), never crash the audit
                outs = [None] * len(eqn.outvars)
            if len(outs) != len(eqn.outvars):
                outs = [None] * len(eqn.outvars)
            for v, iv in zip(eqn.outvars, outs):
                env[id(v)] = iv
                self._check_fits(eqn, v.aval, iv)
        return [self._read(env, v) for v in jaxpr.outvars]


def run(traced) -> List[Finding]:
    t = traced
    if t.closed_jaxpr is None:
        return []

    # one finding per (check, primitive, source site): the scan-carry
    # fixpoint revisits body eqns with progressively wider intervals —
    # re-fires OVERWRITE the message, so the final (widest) bound is
    # what gets reported, once
    sites: dict = {}

    def report(check: str, eqn, message: str) -> None:
        src = eqn_src(eqn)
        site = src if src is not None else id(eqn)
        sites[(check, eqn.primitive.name, site)] = message

    analyzer = _Analyzer(report)
    closed = t.closed_jaxpr
    analyzer._eval(
        closed.jaxpr,
        [None] * len(closed.jaxpr.invars),  # program inputs: unknown
        const_ivs=[_const_interval(c) for c in closed.consts],
    )

    findings: List[Finding] = []
    ordinals: dict = {}
    for (check, prim, site), message in sites.items():
        # anchor on the traced source line when jax exposes it (stable
        # across unrelated edits); fall back to an insertion ordinal
        # per (check, primitive) — never a global counter, which would
        # renumber every later anchor when an earlier finding appears
        if isinstance(site, tuple):
            suffix = f"L{site[1]}"
        else:
            ordinals[(check, prim)] = ordinals.get((check, prim), 0) + 1
            suffix = str(ordinals[(check, prim)])
        findings.append(Finding(
            t.path, t.line, "index-width",
            f"hot program '{t.name}' at max shapes "
            f"(C={t.shapes.C}, K={t.shapes.K}, S={t.shapes.S}): {message}",
            severity=ERROR,
            anchor=f"{t.name}.{check}.{prim}.{suffix}",
            tier="jaxpr",
        ))
    return findings
