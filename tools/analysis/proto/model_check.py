"""protocol-model: exhaustive exploration of the declared automata.

Loads the analyzed tree's ``service/protocol_model.py`` (or the
``--proto-model`` override) and, for every bounded product automaton
its ``build_systems()`` declares, BFS-explores the FULL reachable
state graph, checking:

- **safety** — ``system.check(state, label, info, next)`` on every
  explored transition (the four wire/breaker/admission invariants for
  the real model); any violation is an error finding carrying the
  event trail from the initial state to the violating transition;
- **deadlock** — a reachable non-goal state with no successors is an
  error (the product automaton must never wedge);
- **liveness under weak fairness** — every reachable state must be
  able to reach a goal state (``system.is_goal``: storm drained, all
  tenants cached+acked, no breaker open), computed by backward
  reachability from the goal set over the explored graph. A state
  from which the drained state is unreachable is an error with the
  trail to it. This is EF-goal: since some path always drains, weak
  fairness on the drain-enabling events (admission releases, reply
  deliveries, breaker-backoff expiry) guarantees the storm drains and
  no breaker livelocks; only an adversarial scheduler that starves
  those events forever could avoid it.

Exploration is exact, not sampled: exceeding ``max_states`` is itself
an error finding (silent truncation would read as "proved"), and
tests/test_protocol_model.py pins the explored sizes so a model edit
that quietly shrinks coverage is loud.
"""

from __future__ import annotations

import collections
import dataclasses
import importlib.util
import sys
from pathlib import Path
from typing import List, Optional

from tools.analysis.common import ERROR, Finding, relpath
from tools.analysis.passes.contracts import _find_module

MODEL_SUFFIX = "service/protocol_model.py"

# Generous headroom over the real model's ~95k combined states; a
# bounds bump that crosses this should raise it CONSCIOUSLY, with the
# runtime cost measured against the make-check watchdog.
MAX_STATES = 400_000

# event-trail prefix kept on findings: long enough to replay by hand,
# short enough to read in a terminal
_TRAIL_LIMIT = 24


@dataclasses.dataclass
class Exploration:
    """Everything one ``explore()`` run proved (or found)."""

    name: str
    n_states: int = 0
    n_edges: int = 0
    n_goal: int = 0
    truncated: bool = False
    # (message, trail-of-event-labels) per defect, bounded
    violations: List[tuple] = dataclasses.field(default_factory=list)
    deadlocks: List[tuple] = dataclasses.field(default_factory=list)
    undrainable: List[tuple] = dataclasses.field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not (
            self.truncated
            or self.violations
            or self.deadlocks
            or self.undrainable
        )


def _trail(seen, state) -> List[str]:
    """Event labels from the initial state to ``state``."""
    labels = []
    while seen[state] is not None:
        state, label = seen[state]
        labels.append(label)
    return list(reversed(labels))


def _fmt_trail(labels: List[str]) -> str:
    if len(labels) > _TRAIL_LIMIT:
        labels = labels[:_TRAIL_LIMIT] + [
            f"... (+{len(labels) - _TRAIL_LIMIT} more)"
        ]
    return " -> ".join(labels) if labels else "<initial>"


def explore(system, max_states: int = MAX_STATES,
            max_defects: int = 3) -> Exploration:
    """Exhaustively explore one system; never raises on model defects —
    they land in the returned :class:`Exploration`."""
    out = Exploration(name=getattr(system, "name", "system"))
    init = system.initial()
    seen = {init: None}  # state -> (predecessor, label) | None
    preds = collections.defaultdict(list)
    goal = []
    frontier = collections.deque([init])
    while frontier:
        state = frontier.popleft()
        if system.is_goal(state):
            goal.append(state)
        succs = list(system.successors(state))
        if not succs and not system.is_goal(state):
            if len(out.deadlocks) < max_defects:
                out.deadlocks.append((
                    "terminal non-goal state (protocol wedged)",
                    _fmt_trail(_trail(seen, state)),
                ))
        for label, info, nxt in succs:
            out.n_edges += 1
            for msg in system.check(state, label, info, nxt):
                if len(out.violations) < max_defects:
                    out.violations.append((
                        msg,
                        _fmt_trail(_trail(seen, state) + [label]),
                    ))
            if nxt not in seen:
                if len(seen) >= max_states:
                    out.truncated = True
                    out.n_states = len(seen)
                    out.n_goal = len(goal)
                    return out
                seen[nxt] = (state, label)
                frontier.append(nxt)
            preds[nxt].append(state)
    out.n_states = len(seen)
    out.n_goal = len(goal)

    # liveness: backward reachability from the goal set
    can_reach = set(goal)
    bq = collections.deque(goal)
    while bq:
        state = bq.popleft()
        for p in preds[state]:
            if p not in can_reach:
                can_reach.add(p)
                bq.append(p)
    if len(can_reach) != len(seen):
        for state in seen:
            if state in can_reach:
                continue
            if len(out.undrainable) >= max_defects:
                break
            out.undrainable.append((
                "state cannot drain: no path to the goal "
                "(all-tenants-cached, breakers closed) exists",
                _fmt_trail(_trail(seen, state)),
            ))
    return out


def _load_model(project, model_path: Optional[str]):
    """(module, display_path, error) — the model module to check."""
    if model_path is not None:
        path = Path(model_path)
        display = relpath(path)
    else:
        mod = _find_module(project, MODEL_SUFFIX)
        if mod is None:
            return None, None, None  # inert: tree declares no model
        path = Path(mod.path)
        display = relpath(path)
    try:
        spec = importlib.util.spec_from_file_location(
            "_protocol_model_under_check", path
        )
        module = importlib.util.module_from_spec(spec)
        # dataclass field resolution looks the module up by name
        sys.modules[spec.name] = module
        spec.loader.exec_module(module)
    except Exception as exc:  # noqa: BLE001 — any import failure is the finding
        sys.modules.pop("_protocol_model_under_check", None)
        return None, display, f"{type(exc).__name__}: {exc}"
    return module, display, None


def run(project, model_path=None) -> List[Finding]:
    """The protocol-model pass: explore every declared system."""
    module, display, err = _load_model(project, model_path)
    if module is None and display is None:
        return []
    findings: List[Finding] = []
    if module is None:
        return [Finding(
            display, 1, "protocol-model",
            f"protocol model failed to load: {err}",
            severity=ERROR, anchor="load", tier="proto",
        )]
    build = getattr(module, "build_systems", None)
    if build is None:
        return [Finding(
            display, 1, "protocol-model",
            "protocol model declares no build_systems(); nothing to "
            "explore — the exhaustive proof the tier promises cannot "
            "run",
            severity=ERROR, anchor="build_systems", tier="proto",
        )]
    try:
        systems = list(build())
    except Exception as exc:  # noqa: BLE001 — surfaced as a finding
        return [Finding(
            display, 1, "protocol-model",
            f"build_systems() raised {type(exc).__name__}: {exc}",
            severity=ERROR, anchor="build_systems", tier="proto",
        )]
    if not systems:
        return [Finding(
            display, 1, "protocol-model",
            "build_systems() returned no systems; the tier would pass "
            "vacuously",
            severity=ERROR, anchor="build_systems", tier="proto",
        )]
    for system in systems:
        try:
            result = explore(system)
        except Exception as exc:  # noqa: BLE001 — surfaced as a finding
            findings.append(Finding(
                display, 1, "protocol-model",
                f"exploration of '{getattr(system, 'name', '?')}' "
                f"raised {type(exc).__name__}: {exc}",
                severity=ERROR,
                anchor=f"{getattr(system, 'name', '?')}.explore",
                tier="proto",
            ))
            continue
        name = result.name
        if result.truncated:
            findings.append(Finding(
                display, 1, "protocol-model",
                f"'{name}' exceeded the {MAX_STATES} explored-state "
                "bound — the proof is INCOMPLETE; shrink the declared "
                "bounds or raise MAX_STATES consciously",
                severity=ERROR, anchor=f"{name}.bound", tier="proto",
            ))
            continue
        if result.n_goal == 0:
            findings.append(Finding(
                display, 1, "protocol-model",
                f"'{name}' has no reachable goal state: the drained "
                "fleet is not in the state space at all",
                severity=ERROR, anchor=f"{name}.goal", tier="proto",
            ))
        for msg, trail in result.violations:
            findings.append(Finding(
                display, 1, "protocol-model",
                f"'{name}' safety violation: {msg}; trail: {trail}",
                severity=ERROR,
                anchor=f"{name}.safety", tier="proto",
            ))
        for msg, trail in result.deadlocks:
            findings.append(Finding(
                display, 1, "protocol-model",
                f"'{name}' deadlock: {msg}; trail: {trail}",
                severity=ERROR,
                anchor=f"{name}.deadlock", tier="proto",
            ))
        for msg, trail in result.undrainable:
            findings.append(Finding(
                display, 1, "protocol-model",
                f"'{name}' liveness violation: {msg}; trail: {trail}",
                severity=ERROR,
                anchor=f"{name}.liveness", tier="proto",
            ))
    return findings
