"""protocol-contract: the model <-> implementation binding pass.

``service/protocol_model.py`` deliberately imports nothing from the
live wire/agent/server modules — its mirrored constants are CLAIMS.
This pass makes them falsifiable in both directions:

- every live surface element must appear in the model: ``KIND_*``
  constants, ``WIRE_VERSION``/``SUPPORTED_VERSIONS`` (service/wire.py);
  literal ``_note_shed`` reasons with their flight kinds, every
  ``self._resync_*`` admission attribute and the ingest-cap attribute
  (service/server.py); every numeric UPPERCASE ``RemotePlanner`` class
  constant and the exact ``_Endpoint.__slots__`` (service/agent.py);
- every model element must map back to live code: table entries whose
  constants vanished are errors anchored at the model line, and every
  ``site`` string (``"service/agent.py::RemotePlanner._note_failure"``)
  must resolve to an existing function through the project symbol
  table, so a model event can never describe code that no longer
  exists;
- the breaker table must be structurally sound: edges only between
  declared ``BREAKER_STATES``, and no declared state unreachable by
  the table.

Literal-only scanning, like every contract pass here: precision over
recall — a constant built at runtime simply isn't bound, it never
produces a false finding. Inert on trees without a protocol model.
"""

from __future__ import annotations

import ast
import importlib.util
import sys
from pathlib import Path
from typing import List, Optional

from tools.analysis.common import (
    ERROR,
    Finding,
    manifest_dict_literals,
    relpath,
)
from tools.analysis.passes.contracts import _find_module

MODEL_SUFFIX = "service/protocol_model.py"
WIRE_SUFFIX = "service/wire.py"
AGENT_SUFFIX = "service/agent.py"
SERVER_SUFFIX = "service/server.py"

# tables the model must declare for the contract to hold at all
REQUIRED_TABLES = (
    "VERSIONS", "WIRE_VERSION", "KINDS", "SHED_REASONS",
    "BREAKER_STATES", "BREAKER_TABLE", "BREAKER_CONSTANTS",
    "ENDPOINT_FIELDS", "ADMISSION_COUNTERS", "ADMISSION_LOCK_ATTR",
    "ADMISSION_CAP_ATTR", "ADMISSION_SITES", "LADDER_TABLE",
)


def _assign_lineno(tree: ast.Module, name: str) -> int:
    """Line of the top-level assignment binding ``name`` (1 if none)."""
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name
            for t in node.targets
        ):
            return node.lineno
        if (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.target, ast.Name)
            and node.target.id == name
        ):
            return node.lineno
    return 1


def _site_of(entry):
    """The ``site`` string of a model table entry (dataclass or dict)."""
    if isinstance(entry, dict):
        return entry.get("site")
    return getattr(entry, "site", None)


def _load_model_values(path: Path):
    """Execute the model file in isolation for its table VALUES (the
    AST supplies line anchors). Load failures are owned by the
    protocol-model pass — returning None keeps the two passes from
    double-reporting one broken import."""
    try:
        spec = importlib.util.spec_from_file_location(
            "_protocol_model_under_contract", path
        )
        module = importlib.util.module_from_spec(spec)
        # dataclass field resolution looks the module up by name
        sys.modules[spec.name] = module
        spec.loader.exec_module(module)
        return module
    except Exception:  # noqa: BLE001 — reported by protocol-model instead
        sys.modules.pop("_protocol_model_under_contract", None)
        return None


def _wire_constants(tree: ast.Module):
    """Top-level literal ints: {name: (value, lineno)} for KIND_* /
    WIRE_VERSION, plus the SUPPORTED_VERSIONS tuple."""
    kinds = {}
    wire_version = None
    supported = None
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if not isinstance(t, ast.Name):
                continue
            v = node.value
            if (
                t.id.startswith("KIND_")
                and isinstance(v, ast.Constant)
                and isinstance(v.value, int)
                and not isinstance(v.value, bool)
            ):
                kinds[t.id] = (v.value, node.lineno)
            elif t.id == "WIRE_VERSION" and isinstance(v, ast.Constant):
                wire_version = (v.value, node.lineno)
            elif t.id == "SUPPORTED_VERSIONS" and isinstance(
                v, ast.Tuple
            ):
                vals = tuple(
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant)
                )
                supported = (vals, node.lineno)
    return kinds, wire_version, supported


def _class_def(tree: ast.Module, name: str) -> Optional[ast.ClassDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == name:
            return node
    return None


def _numeric_class_constants(cls: ast.ClassDef):
    """UPPERCASE numeric class attributes: {name: (value, lineno)}."""
    out = {}
    for node in cls.body:
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if (
                isinstance(t, ast.Name)
                and t.id.isupper()
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, (int, float))
                and not isinstance(node.value.value, bool)
            ):
                out[t.id] = (node.value.value, node.lineno)
    return out


def _slots_tuple(cls: ast.ClassDef):
    """(fields, lineno) of the class's literal __slots__ tuple."""
    for node in cls.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "__slots__"
            for t in node.targets
        ):
            if isinstance(node.value, (ast.Tuple, ast.List)):
                return (
                    tuple(
                        e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)
                    ),
                    node.lineno,
                )
    return None


def _shed_calls(tree: ast.Module, funnel_default: str):
    """Literal ``*._note_shed("reason", ..., kind=...)`` call sites:
    [(reason, kind, lineno)]."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = (
            fn.attr if isinstance(fn, ast.Attribute)
            else fn.id if isinstance(fn, ast.Name) else None
        )
        if name != "_note_shed":
            continue
        if not (
            node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            continue
        kind = funnel_default
        for kw in node.keywords:
            if (
                kw.arg == "kind"
                and isinstance(kw.value, ast.Constant)
                and isinstance(kw.value.value, str)
            ):
                kind = kw.value.value
        out.append((node.args[0].value, kind, node.lineno))
    return out


def _shed_funnel_default(tree: ast.Module) -> str:
    """The literal default of ``_note_shed``'s ``kind`` parameter."""
    for node in ast.walk(tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name == "_note_shed"
        ):
            args = node.args
            params = list(args.posonlyargs) + list(args.args)
            defaults = list(args.defaults)
            # align defaults to the trailing params
            for param, default in zip(
                params[len(params) - len(defaults):], defaults
            ):
                if param.arg == "kind" and isinstance(
                    default, ast.Constant
                ):
                    return default.value
            for param, default in zip(args.kwonlyargs, args.kw_defaults):
                if (
                    param.arg == "kind"
                    and isinstance(default, ast.Constant)
                ):
                    return default.value
    return "service-shed"


def _self_attr_stores(tree: ast.Module, prefix: str):
    """{attr: first_lineno} for every ``self.<prefix>*`` assignment."""
    out = {}
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
                and t.attr.startswith(prefix)
            ):
                out.setdefault(t.attr, node.lineno)
    return out


def _self_attr_assigned(tree: ast.Module, attr: str) -> bool:
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
                and t.attr == attr
            ):
                return True
    return False


def run(project, files) -> List[Finding]:
    model_mod = _find_module(project, MODEL_SUFFIX)
    if model_mod is None:
        return []  # tree declares no protocol model: inert
    model_path = relpath(model_mod.path)
    model = _load_model_values(Path(model_mod.path))
    if model is None:
        return []  # protocol-model owns the load failure
    findings: List[Finding] = []

    def model_finding(line, message, anchor):
        findings.append(Finding(
            model_path, line, "protocol-contract", message,
            severity=ERROR, anchor=anchor, tier="proto",
        ))

    missing_tables = [
        t for t in REQUIRED_TABLES if not hasattr(model, t)
    ]
    for t in missing_tables:
        model_finding(
            1,
            f"protocol model is missing the required table {t}; the "
            "contract cannot bind the live surface without it",
            f"table.{t}",
        )
    if missing_tables:
        return findings

    kind_lines = {
        k: ln
        for k, ln, _ in manifest_dict_literals(
            model_mod.tree, "KINDS"
        )[0]
    }
    shed_lines = {
        k: ln
        for k, ln, _ in manifest_dict_literals(
            model_mod.tree, "SHED_REASONS"
        )[0]
    }
    breaker_const_lines = {
        k: ln
        for k, ln, _ in manifest_dict_literals(
            model_mod.tree, "BREAKER_CONSTANTS"
        )[0]
    }

    # ---- service/wire.py: frame kinds + versions ---------------------
    wire_mod = _find_module(project, WIRE_SUFFIX)
    if wire_mod is not None:
        wire_path = relpath(wire_mod.path)
        kinds, wire_version, supported = _wire_constants(wire_mod.tree)
        for name, (value, lineno) in sorted(kinds.items()):
            entry = model.KINDS.get(name)
            if entry is None:
                findings.append(Finding(
                    wire_path, lineno, "protocol-contract",
                    f"live wire frame kind {name}={value} is absent "
                    "from the protocol model's KINDS table "
                    f"({model_path}) — the model checker is blind to "
                    "it",
                    severity=ERROR, anchor=name, tier="proto",
                ))
            else:
                declared = (
                    entry.get("value") if isinstance(entry, dict)
                    else getattr(entry, "value", None)
                )
                if declared != value:
                    findings.append(Finding(
                        wire_path, lineno, "protocol-contract",
                        f"{name} is {value} on the wire but "
                        f"{declared} in the protocol model",
                        severity=ERROR, anchor=name, tier="proto",
                    ))
        for name in sorted(set(model.KINDS) - set(kinds)):
            model_finding(
                kind_lines.get(
                    name, _assign_lineno(model_mod.tree, "KINDS")
                ),
                f"model frame kind {name} has no live KIND_* constant "
                f"in {wire_path}; the model describes a frame that "
                "does not exist",
                name,
            )
        if wire_version is not None and (
            wire_version[0] != model.WIRE_VERSION
        ):
            findings.append(Finding(
                wire_path, wire_version[1], "protocol-contract",
                f"WIRE_VERSION is {wire_version[0]} live but "
                f"{model.WIRE_VERSION} in the protocol model",
                severity=ERROR, anchor="WIRE_VERSION", tier="proto",
            ))
        if supported is not None and (
            supported[0] != tuple(model.VERSIONS)
        ):
            findings.append(Finding(
                wire_path, supported[1], "protocol-contract",
                f"SUPPORTED_VERSIONS is {supported[0]} live but "
                f"{tuple(model.VERSIONS)} in the protocol model",
                severity=ERROR, anchor="SUPPORTED_VERSIONS",
                tier="proto",
            ))

    # ---- service/server.py: shed reasons + admission surface ---------
    server_mod = _find_module(project, SERVER_SUFFIX)
    if server_mod is not None:
        server_path = relpath(server_mod.path)
        funnel_default = _shed_funnel_default(server_mod.tree)
        live_sheds = _shed_calls(server_mod.tree, funnel_default)
        live_reasons = {}
        for reason, kind, lineno in live_sheds:
            live_reasons.setdefault(reason, (kind, lineno))
        for reason, (kind, lineno) in sorted(live_reasons.items()):
            entry = model.SHED_REASONS.get(reason)
            if entry is None:
                findings.append(Finding(
                    server_path, lineno, "protocol-contract",
                    f"live _note_shed reason '{reason}' is absent "
                    "from the protocol model's SHED_REASONS table",
                    severity=ERROR, anchor=f"shed.{reason}",
                    tier="proto",
                ))
                continue
            declared_kind = (
                entry.get("flight_kind") if isinstance(entry, dict)
                else getattr(entry, "flight_kind", None)
            )
            if declared_kind != kind:
                findings.append(Finding(
                    server_path, lineno, "protocol-contract",
                    f"shed reason '{reason}' pairs with flight kind "
                    f"'{kind}' live but '{declared_kind}' in the "
                    "protocol model",
                    severity=ERROR, anchor=f"shed.{reason}",
                    tier="proto",
                ))
        for reason in sorted(set(model.SHED_REASONS) - set(live_reasons)):
            model_finding(
                shed_lines.get(
                    reason,
                    _assign_lineno(model_mod.tree, "SHED_REASONS"),
                ),
                f"model shed reason '{reason}' has no live "
                f"_note_shed site in {server_path}",
                f"shed.{reason}",
            )

        live_admission = _self_attr_stores(server_mod.tree, "_resync_")
        declared_admission = set(model.ADMISSION_COUNTERS) | {
            model.ADMISSION_LOCK_ATTR
        }
        for attr, lineno in sorted(live_admission.items()):
            if attr not in declared_admission:
                findings.append(Finding(
                    server_path, lineno, "protocol-contract",
                    f"live admission attribute self.{attr} is absent "
                    "from the protocol model (ADMISSION_COUNTERS / "
                    "ADMISSION_LOCK_ATTR) — new admission state means "
                    "a new model dimension",
                    severity=ERROR, anchor=f"admission.{attr}",
                    tier="proto",
                ))
        for attr in sorted(declared_admission - set(live_admission)):
            model_finding(
                _assign_lineno(model_mod.tree, "ADMISSION_COUNTERS"),
                f"model admission attribute '{attr}' is never "
                f"assigned in {server_path}",
                f"admission.{attr}",
            )
        if not _self_attr_assigned(
            server_mod.tree, model.ADMISSION_CAP_ATTR
        ):
            model_finding(
                _assign_lineno(model_mod.tree, "ADMISSION_CAP_ATTR"),
                f"model admission cap attribute "
                f"'{model.ADMISSION_CAP_ATTR}' is never assigned in "
                f"{server_path}",
                "admission.cap",
            )

    # ---- service/agent.py: breaker constants + endpoint fields -------
    agent_mod = _find_module(project, AGENT_SUFFIX)
    if agent_mod is not None:
        agent_path = relpath(agent_mod.path)
        planner_cls = _class_def(agent_mod.tree, "RemotePlanner")
        if planner_cls is not None:
            live_consts = _numeric_class_constants(planner_cls)
            for name, (value, lineno) in sorted(live_consts.items()):
                if name not in model.BREAKER_CONSTANTS:
                    findings.append(Finding(
                        agent_path, lineno, "protocol-contract",
                        f"live RemotePlanner constant {name}={value} "
                        "is absent from the protocol model's "
                        "BREAKER_CONSTANTS",
                        severity=ERROR, anchor=name, tier="proto",
                    ))
                elif model.BREAKER_CONSTANTS[name] != value:
                    findings.append(Finding(
                        agent_path, lineno, "protocol-contract",
                        f"RemotePlanner.{name} is {value} live but "
                        f"{model.BREAKER_CONSTANTS[name]} in the "
                        "protocol model",
                        severity=ERROR, anchor=name, tier="proto",
                    ))
            for name in sorted(
                set(model.BREAKER_CONSTANTS) - set(live_consts)
            ):
                model_finding(
                    breaker_const_lines.get(
                        name,
                        _assign_lineno(
                            model_mod.tree, "BREAKER_CONSTANTS"
                        ),
                    ),
                    f"model breaker constant {name} does not exist "
                    "on RemotePlanner",
                    name,
                )
        endpoint_cls = _class_def(agent_mod.tree, "_Endpoint")
        if endpoint_cls is not None:
            slots = _slots_tuple(endpoint_cls)
            if slots is not None and (
                slots[0] != tuple(model.ENDPOINT_FIELDS)
            ):
                findings.append(Finding(
                    agent_path, slots[1], "protocol-contract",
                    f"_Endpoint.__slots__ is {slots[0]} live but the "
                    "protocol model's ENDPOINT_FIELDS is "
                    f"{tuple(model.ENDPOINT_FIELDS)} — per-endpoint "
                    "state and the model automaton have drifted",
                    severity=ERROR, anchor="__slots__", tier="proto",
                ))

    # ---- breaker table structure -------------------------------------
    table_line = _assign_lineno(model_mod.tree, "BREAKER_TABLE")
    states = set(model.BREAKER_STATES)
    touched = set()
    for edge in model.BREAKER_TABLE:
        src = (
            edge.get("src") if isinstance(edge, dict)
            else getattr(edge, "src", None)
        )
        dst = (
            edge.get("dst") if isinstance(edge, dict)
            else getattr(edge, "dst", None)
        )
        touched.update((src, dst))
        for s in (src, dst):
            if s not in states:
                model_finding(
                    table_line,
                    f"BREAKER_TABLE edge touches undeclared state "
                    f"'{s}' (BREAKER_STATES: "
                    f"{tuple(model.BREAKER_STATES)})",
                    f"breaker.{s}",
                )
    for s in sorted(states - touched):
        model_finding(
            _assign_lineno(model_mod.tree, "BREAKER_STATES"),
            f"breaker state '{s}' is declared but no BREAKER_TABLE "
            "edge touches it",
            f"breaker.{s}",
        )

    # ---- every model site must be live code --------------------------
    sites = []
    for name, entry in model.KINDS.items():
        sites.append((_site_of(entry), f"site.{name}",
                      _assign_lineno(model_mod.tree, "KINDS")))
    for reason, entry in model.SHED_REASONS.items():
        sites.append((_site_of(entry), f"site.shed.{reason}",
                      shed_lines.get(reason, 1)))
    for edge in model.BREAKER_TABLE:
        event = (
            edge.get("event") if isinstance(edge, dict)
            else getattr(edge, "event", "?")
        )
        sites.append((_site_of(edge), f"site.breaker.{event}",
                      table_line))
    for entry in model.LADDER_TABLE:
        event = (
            entry.get("event") if isinstance(entry, dict)
            else getattr(entry, "event", "?")
        )
        sites.append((_site_of(entry), f"site.ladder.{event}",
                      _assign_lineno(model_mod.tree, "LADDER_TABLE")))
    for key, site in model.ADMISSION_SITES.items():
        sites.append((site, f"site.admission.{key}",
                      _assign_lineno(model_mod.tree,
                                     "ADMISSION_SITES")))
    seen_sites = set()
    for site, anchor, line in sites:
        if site is None or site in seen_sites:
            continue
        seen_sites.add(site)
        if "::" not in site:
            model_finding(
                line,
                f"model site '{site}' is not of the form "
                "'<path-suffix>::<qualname>'",
                anchor,
            )
            continue
        suffix, qual = site.split("::", 1)
        target_mod = _find_module(project, suffix)
        if target_mod is None:
            model_finding(
                line,
                f"model site '{site}' names a module not present in "
                "the analyzed tree",
                anchor,
            )
            continue
        if qual not in target_mod.functions:
            model_finding(
                line,
                f"model site '{site}' maps to no live function — the "
                "code the model event describes no longer exists",
                anchor,
            )
    return findings
