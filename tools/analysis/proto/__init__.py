"""Proto tier: protocol model checking + model<->code contract.

The third analysis tier (``--tier proto``, ``make verify-protocol``),
beside the ast tier (source passes) and the jaxpr tier (traced-program
passes). Two passes:

- ``protocol-model`` (tools/analysis/proto/model_check.py): load the
  tree's ``service/protocol_model.py``, exhaustively explore every
  bounded product automaton it declares (``build_systems()``), and
  verify the four safety invariants plus drain/livelock liveness over
  the FULL reachable state space. Violations come with a concrete
  counterexample event trail.
- ``protocol-contract`` (tools/analysis/proto/contract.py): the AST
  pass that keeps the model honest — every live ``KIND_*`` constant,
  ``_note_shed`` reason, breaker constant and admission counter must
  appear in the model with the live value, and every model table entry
  must map back to an existing code site. Either side drifting turns
  ``make check`` red.

Like the jaxpr tier, findings flow through the shared suppression
grammar and baseline; ``_exercised_codes`` in the engine keeps a
``--tier proto`` run from calling ast/jaxpr debt paid.
"""

from __future__ import annotations

PROTO_PASS_NAMES = ("protocol-model", "protocol-contract")


def run_tier(project, files, only_pass=None, model_path=None):
    """All proto-tier findings for one engine run. Inert (returns [])
    on trees that declare no protocol model — same convention as the
    contract passes — so fixture trees stay green by default."""
    from tools.analysis.proto import contract, model_check

    findings = []
    if only_pass in (None, "protocol-contract"):
        findings.extend(contract.run(project, files))
    if only_pass in (None, "protocol-model"):
        findings.extend(
            model_check.run(project, model_path=model_path)
        )
    return findings
