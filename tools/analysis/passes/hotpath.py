"""JAX hot-path vet passes (the go-vet analog for the solver).

Scope: files under solver/, ops/, parallel/, planner/ — the modules whose
code runs (or builds code that runs) inside ``jax.jit`` / ``pjit`` /
``shard_map`` programs. Three passes share the jit-reachability analysis:

``jax-host-sync``
    A host synchronization inside traced code re-serializes the whole
    tick (the device pipeline drains, the host blocks on the transfer).
    Flags ``.item()``, ``.block_until_ready()``, ``np.asarray``/
    ``np.array``, and ``print`` in any function reachable from a jitted
    root (error), plus ``float()``/``int()`` on non-literals (warn — the
    AST cannot prove the operand is a traced array, but on the hot path
    they usually are).

``donation-discipline``
    An argument donated via ``donate_argnums`` is dead after the call —
    its buffer was aliased into the output. Reading it afterwards in the
    caller returns garbage (or raises, backend-dependent). Flags reads of
    a donated name/attribute after the donating call, before any rebind.

``recompile-trigger``
    Work that silently retraces per call: ``jax.jit(...)(...)`` built and
    invoked in one expression, jit/shard_map construction inside a loop,
    and per-call-varying scalars (``time.time()`` etc.) flowing into a
    jitted call's arguments.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tools.analysis.common import ERROR, WARN, Finding, relpath
from tools.analysis.symbols import (
    FunctionInfo,
    Project,
    dotted,
    parent_map,
)

SCOPE_DIRS = ("solver", "ops", "parallel", "planner")

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
_SHARD_NAMES = {"shard_map", "jax.shard_map"}
_PARTIAL_NAMES = {"functools.partial", "partial"}
_VARYING_CALLS = {
    "time.time", "time.monotonic", "time.perf_counter",
    "random.random", "random.randint", "random.uniform",
    "uuid.uuid4", "datetime.datetime.now", "datetime.now",
}


def in_scope(path: str) -> bool:
    parts = relpath(path).split("/")
    return any(d in parts for d in SCOPE_DIRS)


def _is_jit_call(node: ast.AST) -> bool:
    """``jax.jit(...)`` / ``pjit(...)`` / ``shard_map(...)`` call node."""
    if not isinstance(node, ast.Call):
        return False
    name = dotted(node.func)
    return name in _JIT_NAMES or name in _SHARD_NAMES


def _partial_jit_decorator(dec: ast.AST) -> bool:
    """``@functools.partial(jax.jit, ...)`` shape."""
    if not isinstance(dec, ast.Call):
        return False
    if dotted(dec.func) not in _PARTIAL_NAMES:
        return False
    return bool(dec.args) and dotted(dec.args[0]) in _JIT_NAMES


def _jit_decorated(fn: ast.AST) -> bool:
    for dec in fn.decorator_list:
        if dotted(dec) in _JIT_NAMES | _SHARD_NAMES:
            return True
        if isinstance(dec, ast.Call) and dotted(dec.func) in (
            _JIT_NAMES | _SHARD_NAMES
        ):
            return True
        if _partial_jit_decorator(dec):
            return True
    return False


def _first_function_ref(project: Project, mod, arg, scope):
    """The analyzed function an argument expression refers to, unwrapping
    ``functools.partial(f, ...)``."""
    if isinstance(arg, ast.Call) and dotted(arg.func) in _PARTIAL_NAMES:
        if arg.args:
            return _first_function_ref(project, mod, arg.args[0], scope)
        return None
    if isinstance(arg, (ast.Name, ast.Attribute)):
        return project.resolve_call(mod, arg, scope)
    return None


# ---------------------------------------------------------------------------
# reachability


def jit_roots(project: Project) -> List[FunctionInfo]:
    """Every function the call graph can see as a jit/pjit/shard_map
    root: decorated defs plus resolvable ``jax.jit(f, ...)`` /
    ``shard_map(f, ...)`` first-argument references. Shared by the
    reachability walk below and the manifest-contract pass
    (tools/analysis/passes/contracts.py) — ONE definition of "root" so
    the jaxpr tier's coverage contract matches what these vets vet."""
    roots: List[FunctionInfo] = []
    for mod in project.modules.values():
        parents = parent_map(mod.tree)
        # decorated roots
        for info in mod.functions.values():
            if _jit_decorated(info.node):
                roots.append(info)
        # jax.jit(f, ...) / shard_map(f, ...) reference roots
        for node in ast.walk(mod.tree):
            if _is_jit_call(node):
                from tools.analysis.symbols import function_scope_of

                scope = function_scope_of(mod, node, parents)
                for arg in node.args[:1]:
                    target = _first_function_ref(project, mod, arg, scope)
                    if target is not None:
                        roots.append(target)
    return roots


def jit_reachable(project: Project) -> Set[FunctionInfo]:
    """Functions reachable from any jit/pjit/shard_map root."""
    roots = jit_roots(project)
    edges: Dict[FunctionInfo, Set[FunctionInfo]] = {}

    for mod in project.modules.values():
        # call edges + function-reference-argument edges + nesting edges
        for info in mod.functions.values():
            out = edges.setdefault(info, set())
            if info.parent is not None:
                edges.setdefault(info.parent, set()).add(info)
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = project.resolve_call(mod, node.func, info)
                if callee is not None:
                    out.add(callee)
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    ref = _first_function_ref(project, mod, arg, info)
                    if ref is not None:
                        out.add(ref)

    seen: Set[FunctionInfo] = set()
    stack = list(roots)
    while stack:
        fn = stack.pop()
        if fn in seen:
            continue
        seen.add(fn)
        stack.extend(edges.get(fn, ()))
    return seen


def _static_param_names(project: Project) -> Dict[FunctionInfo, Set[str]]:
    """Param names marked static at a function's jit site
    (static_argnames / static_argnums): plain Python values at trace
    time, so host conversions on them are legal."""

    def names_from(call: ast.Call, target: FunctionInfo) -> Set[str]:
        out: Set[str] = set()
        a = target.node.args
        params = [p.arg for p in list(a.posonlyargs) + list(a.args)]
        for kw in call.keywords:
            vals = []
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                vals = [
                    e.value for e in kw.value.elts
                    if isinstance(e, ast.Constant)
                ]
            elif isinstance(kw.value, ast.Constant):
                vals = [kw.value.value]
            if kw.arg == "static_argnames":
                out.update(v for v in vals if isinstance(v, str))
            elif kw.arg == "static_argnums":
                for v in vals:
                    if isinstance(v, int) and v < len(params):
                        out.add(params[v])
        return out

    statics: Dict[FunctionInfo, Set[str]] = {}
    for mod in project.modules.values():
        parents = parent_map(mod.tree)
        for node in ast.walk(mod.tree):
            if _is_jit_call(node) and node.args:
                from tools.analysis.symbols import function_scope_of

                scope = function_scope_of(mod, node, parents)
                target = _first_function_ref(
                    project, mod, node.args[0], scope
                )
                if target is not None:
                    statics.setdefault(target, set()).update(
                        names_from(node, target)
                    )
        for info in mod.functions.values():
            for dec in info.node.decorator_list:
                # @functools.partial(jax.jit, static_argnames=...) and
                # the direct @jax.jit(static_argnames=...) form alike
                if _partial_jit_decorator(dec) or (
                    isinstance(dec, ast.Call)
                    and dotted(dec.func) in _JIT_NAMES | _SHARD_NAMES
                ):
                    statics.setdefault(info, set()).update(
                        names_from(dec, info)
                    )
    return statics


def _numpy_aliases(mod) -> Set[str]:
    out = set()
    for bound, imp in mod.imports.items():
        if imp[0] == "module" and imp[1] == "numpy":
            out.add(bound)
    return out


# ---------------------------------------------------------------------------
# pass: jax-host-sync


def _walk_own(fn_node):
    """Walk a function's body WITHOUT descending into nested defs — each
    reachable nested def is its own host-sync entry, so visiting it here
    would double-report (and pruning must not mutate the shared AST)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def run_host_sync(project: Project, files) -> List[Finding]:
    findings: List[Finding] = []
    reachable = jit_reachable(project)
    statics = _static_param_names(project)
    for info in reachable:
        if not in_scope(info.path):
            continue
        mod = info.module
        np_names = _numpy_aliases(mod)
        path = relpath(info.path)
        for node in _walk_own(info.node):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if isinstance(node.func, ast.Attribute):
                if node.func.attr == "item" and not node.args:
                    findings.append(Finding(
                        path, node.lineno, "jax-host-sync",
                        f".item() inside jit-reachable '{info.name}' "
                        "blocks on a device->host transfer; keep the "
                        "value traced (or fetch once, outside jit)",
                        severity=ERROR, anchor=f"{info.name}.item.L{node.lineno}",
                    ))
                elif node.func.attr == "block_until_ready":
                    findings.append(Finding(
                        path, node.lineno, "jax-host-sync",
                        f".block_until_ready() inside jit-reachable "
                        f"'{info.name}' serializes the device pipeline",
                        severity=ERROR, anchor=f"{info.name}.block.L{node.lineno}",
                    ))
                elif name and name.split(".", 1)[0] in np_names and (
                    name.endswith(".asarray") or name.endswith(".array")
                ):
                    findings.append(Finding(
                        path, node.lineno, "jax-host-sync",
                        f"numpy {name}() inside jit-reachable "
                        f"'{info.name}' forces a host round trip; use "
                        "jnp equivalents in traced code",
                        severity=ERROR, anchor=f"{info.name}.np.L{node.lineno}",
                    ))
            elif isinstance(node.func, ast.Name):
                if node.func.id == "print":
                    findings.append(Finding(
                        path, node.lineno, "jax-host-sync",
                        f"print() inside jit-reachable '{info.name}' "
                        "syncs its operands to host per call; use "
                        "jax.debug.print for traced values",
                        severity=ERROR, anchor=f"{info.name}.print.L{node.lineno}",
                    ))
                elif node.func.id in ("float", "int") and node.args:
                    arg = node.args[0]
                    is_static = isinstance(arg, ast.Name) and arg.id in (
                        statics.get(info, ())
                    )
                    if not isinstance(arg, ast.Constant) and not is_static:
                        findings.append(Finding(
                            path, node.lineno, "jax-host-sync",
                            f"{node.func.id}() on a non-literal inside "
                            f"jit-reachable '{info.name}' concretizes "
                            "(host sync) if the operand is traced",
                            severity=WARN,
                            anchor=f"{info.name}.{node.func.id}.L{node.lineno}",
                        ))
    return findings


# ---------------------------------------------------------------------------
# pass: donation-discipline


def _donate_positions(call: ast.Call) -> Optional[Set[int]]:
    """The donated positional indices of a jax.jit call, or None if the
    call has no donate_argnums. An unresolvable spec donates everything
    (empty set sentinel is avoided; None means 'not donating')."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, (ast.Tuple, ast.List)):
            out = set()
            for elt in v.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, int
                ):
                    out.add(elt.value)
            return out
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return {v.value}
        # tuple(range(N)) — the donated-scatter pattern in
        # planner/solver_planner.py
        if (
            isinstance(v, ast.Call)
            and dotted(v.func) == "tuple"
            and len(v.args) == 1
            and isinstance(v.args[0], ast.Call)
            and dotted(v.args[0].func) == "range"
            and len(v.args[0].args) == 1
            and isinstance(v.args[0].args[0], ast.Constant)
            and isinstance(v.args[0].args[0].value, int)
        ):
            return set(range(v.args[0].args[0].value))
        # any other spec is unresolvable statically: skip the call site
        # (costs recall, never a false error-tier finding)
        return None
    return None


class _DonatedDef:
    def __init__(self, name: str, positions: Set[int]):
        self.name = name
        self.positions = positions


def _collect_donating(mod) -> Dict[str, _DonatedDef]:
    """name -> donated positions, for names bound to donating jits in this
    module (module-level or self attributes), plus factory methods whose
    return value is a donating jit."""
    out: Dict[str, _DonatedDef] = {}
    for node in ast.walk(mod.tree):
        # name = jax.jit(f, donate_argnums=...)
        if isinstance(node, ast.Assign) and _is_jit_call(node.value):
            pos = _donate_positions(node.value)
            if pos is None:
                continue
            for tgt in node.targets:
                name = dotted(tgt)
                if name:
                    out[name] = _DonatedDef(name, pos)
        # @functools.partial(jax.jit, donate_argnums=...) def f(...)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _partial_jit_decorator(dec):
                    pos = _donate_positions(dec)
                    if pos is not None:
                        out[node.name] = _DonatedDef(node.name, pos)
    # factories: def m(self): ... return <donated local>
    for info in mod.functions.values():
        for node in ast.walk(info.node):
            if isinstance(node, ast.Return) and node.value is not None:
                rname = dotted(node.value)
                if rname in out and info.cls:
                    out[f"self.{info.name}()"] = out[rname]
    return out


def _donated_exprs(call: ast.Call, positions: Set[int]) -> List[str]:
    """Dotted names of the donated argument expressions at a call site."""
    names = []
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            # *xs covers this position onward: donated if any donated
            # position is >= i
            if any(p >= i for p in positions):
                n = dotted(arg.value)
                if n:
                    names.append(n)
            continue
        if i in positions:
            n = dotted(arg)
            if n:
                names.append(n)
    return names


def run_donation(project: Project, files) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.modules.values():
        if not in_scope(mod.path):
            continue
        donating = _collect_donating(mod)
        if not donating:
            continue
        path = relpath(mod.path)
        for info in mod.functions.values():
            # own body only: a nested def is its own entry, and walking
            # it under the parent would misscope _read_after to the
            # parent's (possibly shadowed) bindings and double-report
            for node in _walk_own(info.node):
                if not isinstance(node, ast.Call):
                    continue
                fname = dotted(node.func)
                ddef = donating.get(fname) if fname else None
                if ddef is None and isinstance(node.func, ast.Call):
                    inner = dotted(node.func.func)
                    if inner and f"{inner}()" in donating:
                        ddef = donating[f"{inner}()"]
                if ddef is None:
                    continue
                for donated in _donated_exprs(node, ddef.positions):
                    viol = _read_after(
                        info.node, donated, node.lineno,
                        node.end_lineno or node.lineno,
                    )
                    if viol is not None:
                        findings.append(Finding(
                            path, viol, "donation-discipline",
                            f"'{donated}' was donated to the jit call at "
                            f"line {node.lineno} (donate_argnums) and is "
                            "read afterwards in "
                            f"'{info.name}' — the buffer was consumed; "
                            "rebind before reuse",
                            severity=ERROR,
                            anchor=f"{info.name}.{donated}.L{viol}",
                        ))
    return findings


def _read_after(
    fn_node, name: str, call_start: int, call_end: int
) -> Optional[int]:
    """First line past the (possibly multi-line) donating call where
    ``name`` is read before any rebind. The donated argument's own Load
    sits inside [call_start, call_end] and must not count as a read."""
    stores: List[int] = []
    loads: List[int] = []
    for node in ast.walk(fn_node):
        n = dotted(node) if isinstance(node, (ast.Name, ast.Attribute)) else None
        if n != name:
            continue
        ctx = getattr(node, "ctx", None)
        if isinstance(ctx, (ast.Store, ast.Del)):
            stores.append(node.lineno)
        elif isinstance(ctx, ast.Load) and node.lineno > call_end:
            loads.append(node.lineno)
    for load in sorted(loads):
        # a store anywhere in the call statement is the result
        # assignment (``a = g(a)``): it rebinds the name after donation
        if not any(call_start <= s <= load for s in stores):
            return load
    return None


# ---------------------------------------------------------------------------
# pass: recompile-trigger


def run_recompile(project: Project, files) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.modules.values():
        if not in_scope(mod.path):
            continue
        path = relpath(mod.path)
        donating = _collect_donating(mod)
        jitted_names = set(donating)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and _is_jit_call(node.value):
                for tgt in node.targets:
                    n = dotted(tgt)
                    if n:
                        jitted_names.add(n)
        # jit calls that are immediately invoked: reported once by the
        # per-call check, so the in-loop check must not re-report them
        invoked_jits = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and _is_jit_call(node.func):
                invoked_jits.add(node.func)
        for node in ast.walk(mod.tree):
            # jax.jit(f)(x): traced, compiled, and thrown away per call
            if isinstance(node, ast.Call) and _is_jit_call(node.func):
                findings.append(Finding(
                    path, node.lineno, "recompile-trigger",
                    "jit program built and invoked in one expression — "
                    "it recompiles (or at best re-hashes) every call; "
                    "bind the jitted callable once and reuse it",
                    severity=ERROR, anchor=f"L{node.lineno}.per-call",
                ))
            # jit/shard_map constructed inside a loop
            if isinstance(node, (ast.For, ast.While)):
                for sub in ast.walk(node):
                    if sub is node or sub in invoked_jits:
                        continue
                    if _is_jit_call(sub):
                        findings.append(Finding(
                            path, sub.lineno, "recompile-trigger",
                            "jit/shard_map constructed inside a loop — "
                            "each iteration builds a fresh program and "
                            "its own compile-cache entry",
                            severity=ERROR, anchor=f"L{sub.lineno}.in-loop",
                        ))
            # per-call-varying scalars into a jitted call
            if isinstance(node, ast.Call):
                fname = dotted(node.func)
                if fname in jitted_names:
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        for sub in ast.walk(arg):
                            if (
                                isinstance(sub, ast.Call)
                                and dotted(sub.func) in _VARYING_CALLS
                            ):
                                findings.append(Finding(
                                    path, node.lineno, "recompile-trigger",
                                    f"per-call-varying scalar "
                                    f"({dotted(sub.func)}()) flows into "
                                    f"jitted '{fname}' — every distinct "
                                    "value retraces; pass it as a traced "
                                    "array or hoist it out",
                                    severity=ERROR,
                                    anchor=f"{fname}.varying.L{node.lineno}",
                                ))
    # dedupe in-loop findings that also matched per-call
    seen = set()
    out = []
    for f in findings:
        k = (f.path, f.line, f.message)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out
