"""exception-discipline: blind excepts on the control/service planes
must be accounted for.

PR 4's containment contract is that the loop survives every exception —
which makes ``except Exception`` the house idiom in ``service/``,
``io/`` and ``loop/``, and every such handler a place where a failure
can silently vanish. A swallowed exception on these planes is precisely
the degradation the flight recorder and the metrics surfaces exist to
expose, so the rule is:

    every ``except:`` / ``except Exception`` / ``except BaseException``
    handler in a service/ io/ loop/ module must do at least one of

    - re-raise (any ``raise`` in the handler body),
    - record the degradation: call ``flight.*`` (note_event /
      record_tick / dump), a ``metrics.update_*`` / ``metrics.observe_*``
      updater, or a ``health.*`` note, or
    - carry an explicit ``# noqa: exception-discipline`` justification
      on the ``except`` line (the standard suppression grammar).

Specific exception classes (``except ValueError``) are out of scope —
the discipline targets the catch-alls, where "handled" and "lost" look
identical to a reader. Solver/model/bench code is out of scope too: the
rule is about the planes whose degradations have contractual
metric/flight surfaces (docs/ROBUSTNESS.md, docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import ast
from typing import List

from tools.analysis.common import ERROR, Finding, relpath
from tools.analysis.symbols import Project, dotted

# path segments that put a module on a monitored plane (matches both
# the real tree, k8s_spot_rescheduler_tpu/service/..., and fixture
# trees, service/...)
_SCOPED_SEGMENTS = {"service", "io", "loop"}

# broad catches the discipline applies to
_BROAD = {"Exception", "BaseException"}

# call prefixes that count as recording the degradation
_RECORDER_PREFIXES = (
    "flight.",           # loop/flight.py note_event / record_tick / dump
    "metrics.update_",   # metrics/registry.py counters + gauges
    "metrics.observe_",  # metrics/registry.py histograms
    "health.",           # loop/health.py STATE notes
)


def _in_scope(path: str) -> bool:
    return any(seg in _SCOPED_SEGMENTS for seg in path.split("/")[:-1])


def _catches_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True  # bare except
    types = (
        handler.type.elts
        if isinstance(handler.type, ast.Tuple)
        else [handler.type]
    )
    for t in types:
        name = dotted(t) or ""
        if name.split(".")[-1] in _BROAD:
            return True
    return False


def _discharges(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = dotted(node.func) or ""
            if name.startswith(_RECORDER_PREFIXES):
                return True
    return False


def run(project: Project, files) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.modules.values():
        path = relpath(mod.path)
        if not _in_scope(path):
            continue

        def walk(node: ast.AST, func: str) -> None:
            for child in ast.iter_child_nodes(node):
                name = func
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    name = (
                        f"{func}.{child.name}" if func else child.name
                    )
                if isinstance(
                    child, ast.ExceptHandler
                ) and _catches_broad(child) and not _discharges(child):
                    caught = (
                        "bare except"
                        if child.type is None
                        else f"except {ast.unparse(child.type)}"
                    )
                    findings.append(Finding(
                        path, child.lineno, "exception-discipline",
                        f"{caught} in {func or '<module>'} neither "
                        "re-raises nor records the failure (flight.*, "
                        "metrics.update_*/observe_*, health.*) — on the "
                        "service/io/loop planes a swallowed exception "
                        "is an invisible degradation; record it, "
                        "re-raise, or justify with "
                        "'# noqa: exception-discipline'",
                        severity=ERROR,
                        anchor=f"{func or '<module>'}.L{child.lineno}",
                    ))
                walk(child, name)

        walk(mod.tree, "")
    return findings
