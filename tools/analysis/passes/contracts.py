"""Cross-module contract passes.

These encode invariants that no per-file linter can see — the quartets
and pairs of modules that must stay in lockstep:

``metrics-contract``
    Every Prometheus series mutated anywhere in the package is declared
    in metrics/registry.py, and every declared series is mutated
    somewhere (a declared-but-dead gauge is a dashboard lying in wait).

``config-contract``
    Every ``ReschedulerConfig`` field has a matching ``--kebab-case``
    flag in cli/main.py, that flag is actually wired through
    ``config_from_args`` into the dataclass, and the field is mentioned
    in docs/PARITY.md. Flags with no config field must be declared
    runtime-only (RUNTIME_ONLY_FLAGS below) or they warn.

``kube-write-retry``
    Write verbs in io/kube.py stay single-attempt: only the designated
    wrappers may call the retrying ``_read_retrying`` path, and always
    with a literal "GET" (the actuator owns eviction/taint cadence;
    a retried write would double-fire side effects).

``trace-contract``
    Every span name emitted anywhere (a literal first argument to
    ``tracing.span`` / ``tracing.phase`` / ``tracing.make_span``) is
    declared in the ``SPAN_NAMES`` registry in utils/tracing.py, and
    every declared name is emitted somewhere — so dashboards and the
    flight-recorder dump schema cannot silently drift from the code
    (the same lockstep metrics-contract enforces for Prometheus
    series). Names passed through variables are unscannable by design
    (precision over recall, like the rest of the suite); the project
    emits spans with literal names only.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analysis.common import ERROR, WARN, Finding, relpath
from tools.analysis.symbols import Project, dotted

# ---------------------------------------------------------------------------
# metrics-contract

_METRIC_TYPES = {"Counter", "Gauge", "Histogram", "Summary"}
_MUTATORS = {"inc", "dec", "set", "observe"}


def _find_module(project: Project, suffix: str):
    for mod in project.modules.values():
        if relpath(mod.path).endswith(suffix):
            return mod
    return None


def _registry_aliases(mod) -> Set[str]:
    """Local names this module binds the metrics registry module to."""
    out = set()
    for bound, imp in mod.imports.items():
        target = imp[1] if imp[0] == "module" else f"{imp[1]}.{imp[2]}"
        if target.endswith("metrics.registry") or target.endswith(
            ".registry"
        ):
            out.add(bound)
    return out


def _mutation_base(node: ast.Call) -> Optional[ast.AST]:
    """For ``X[.labels(...)].inc/.set/.observe(...)`` return X, else None."""
    if not isinstance(node.func, ast.Attribute):
        return None
    if node.func.attr not in _MUTATORS:
        return None
    base = node.func.value
    if (
        isinstance(base, ast.Call)
        and isinstance(base.func, ast.Attribute)
        and base.func.attr == "labels"
    ):
        base = base.func.value
    return base


def run_metrics(project: Project, files) -> List[Finding]:
    registry = _find_module(project, "metrics/registry.py")
    if registry is None:
        return []
    findings: List[Finding] = []
    reg_path = relpath(registry.path)

    declared: Dict[str, int] = {}  # metric var -> decl line
    locals_in_reg: Set[str] = set()
    for node in registry.tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            ctor = dotted(node.value.func)
            if ctor and ctor.split(".")[-1] in _METRIC_TYPES:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        declared[tgt.id] = node.lineno
    # names bound locally inside registry functions (params, locals) are
    # not metrics even if .set() is called on them
    for info in registry.functions.values():
        for n in ast.walk(info.node):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                locals_in_reg.add(n.id)
            if isinstance(n, ast.arg):
                locals_in_reg.add(n.arg)

    mutated: Set[str] = set()

    # inside registry.py: bare-name mutations
    for node in ast.walk(registry.tree):
        if not isinstance(node, ast.Call):
            continue
        base = _mutation_base(node)
        if isinstance(base, ast.Name):
            if base.id in declared:
                mutated.add(base.id)
            elif base.id not in locals_in_reg:
                findings.append(Finding(
                    reg_path, node.lineno, "metrics-contract",
                    f"'{base.id}' is mutated like a metric but never "
                    "declared in metrics/registry.py",
                    severity=ERROR, anchor=base.id,
                ))

    # everywhere else: alias.X mutations
    for mod in project.modules.values():
        if mod is registry:
            continue
        aliases = _registry_aliases(mod)
        if not aliases:
            continue
        path = relpath(mod.path)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            base = _mutation_base(node)
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id in aliases
            ):
                if base.attr in declared:
                    mutated.add(base.attr)
                else:
                    findings.append(Finding(
                        path, node.lineno, "metrics-contract",
                        f"'{base.attr}' is mutated through the metrics "
                        "registry but not declared in "
                        "metrics/registry.py",
                        severity=ERROR, anchor=base.attr,
                    ))

    for name, line in sorted(declared.items()):
        if name not in mutated:
            findings.append(Finding(
                reg_path, line, "metrics-contract",
                f"metric '{name}' is declared but never mutated anywhere "
                "in the package — dead series (or the updater was lost "
                "in a refactor)",
                severity=ERROR, anchor=name,
            ))
    return findings


# ---------------------------------------------------------------------------
# config-contract

# Flags that deliberately have no ReschedulerConfig field: process-level
# runtime selectors, not rescheduler policy (each justified in
# docs/ANALYSIS.md).
RUNTIME_ONLY_FLAGS = {
    "--version",
    "--verbosity",
    "--cluster",
    "--ticks",
    "--no-metrics-server",
    "--trace-dir",
    "--leader-elect",
    "--leader-elect-namespace",
    "--leader-elect-identity",
    "--leader-elect-lease-duration",
    "--watch-cache",
    "--serve",
}


def _config_fields(mod) -> Dict[str, int]:
    for cls in mod.classes.values():
        if cls.name != "ReschedulerConfig":
            continue
        out = {}
        for node in cls.body:
            if isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name
            ):
                if not node.target.id.startswith("_"):
                    out[node.target.id] = node.lineno
        return out
    return {}


def _cli_surface(mod):
    """(flags {'--x': line}, wired field kwargs in config_from_args)."""
    flags: Dict[str, int] = {}
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
        ):
            for arg in node.args:
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    if arg.value.startswith("--"):
                        flags[arg.value] = node.lineno
    wired: Set[str] = set()
    fn = mod.functions.get("config_from_args")
    if fn is not None:
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call) and dotted(node.func) in (
                "ReschedulerConfig",
            ):
                wired = {kw.arg for kw in node.keywords if kw.arg}
    return flags, wired


def run_config(project: Project, files) -> List[Finding]:
    cfg_mod = _find_module(project, "utils/config.py")
    cli_mod = _find_module(project, "cli/main.py")
    if cfg_mod is None or cli_mod is None:
        return []
    findings: List[Finding] = []
    fields = _config_fields(cfg_mod)
    if not fields:
        return []
    flags, wired = _cli_surface(cli_mod)
    cfg_path, cli_path = relpath(cfg_mod.path), relpath(cli_mod.path)

    parity_text = ""
    parity = files.get("__parity__")
    if parity is not None:
        parity_text = parity

    for field, line in sorted(fields.items()):
        flag = "--" + field.replace("_", "-")
        if flag not in flags:
            findings.append(Finding(
                cfg_path, line, "config-contract",
                f"ReschedulerConfig.{field} has no matching '{flag}' "
                "flag in cli/main.py — the knob exists but an operator "
                "cannot turn it",
                severity=ERROR, anchor=field,
            ))
        elif field not in wired:
            findings.append(Finding(
                cli_path, flags[flag], "config-contract",
                f"flag '{flag}' is parsed but config_from_args never "
                f"passes '{field}' into ReschedulerConfig — the flag "
                "silently does nothing",
                severity=ERROR, anchor=field,
            ))
        if parity is not None and (
            field not in parity_text and flag not in parity_text
        ):
            findings.append(Finding(
                cfg_path, line, "config-contract",
                f"ReschedulerConfig.{field} is not mentioned in "
                "docs/PARITY.md (config-surface section)",
                severity=ERROR, anchor=f"doc.{field}",
            ))

    field_flags = {
        "--" + f.replace("_", "-") for f in fields
    }
    for flag, line in sorted(flags.items()):
        if flag in field_flags or flag in RUNTIME_ONLY_FLAGS:
            continue
        findings.append(Finding(
            cli_path, line, "config-contract",
            f"flag '{flag}' maps to no ReschedulerConfig field and is "
            "not declared runtime-only (RUNTIME_ONLY_FLAGS)",
            severity=WARN, anchor=flag,
        ))
    return findings


# ---------------------------------------------------------------------------
# kube-write-retry

# functions in io/kube.py allowed to call the retrying read path
_RETRY_WRAPPERS = {"_request", "_request_raw"}


def run_kube_writes(project: Project, files) -> List[Finding]:
    kube = _find_module(project, "io/kube.py")
    if kube is None:
        return []
    findings: List[Finding] = []
    path = relpath(kube.path)
    for info in kube.functions.values():
        fname = info.name
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted(node.func)
            if callee and callee.endswith("._read_retrying"):
                if fname not in _RETRY_WRAPPERS:
                    findings.append(Finding(
                        path, node.lineno, "kube-write-retry",
                        f"'{fname}' calls _read_retrying directly; only "
                        f"{sorted(_RETRY_WRAPPERS)} may route through "
                        "the retry loop (write verbs are single-attempt "
                        "by design)",
                        severity=ERROR, anchor=fname,
                    ))
                if node.args:
                    m = node.args[0]
                    if not (
                        isinstance(m, ast.Constant) and m.value == "GET"
                    ):
                        findings.append(Finding(
                            path, node.lineno, "kube-write-retry",
                            "_read_retrying called with a non-'GET' "
                            "method — a retried write double-fires its "
                            "side effect (evict/taint) on a timeout "
                            "whose request actually landed",
                            severity=ERROR, anchor=f"{fname}.method",
                        ))
            # explicit retries=True on a write verb through _request
            if callee and callee.endswith("._request") and node.args:
                m = node.args[0]
                if (
                    isinstance(m, ast.Constant)
                    and isinstance(m.value, str)
                    and m.value != "GET"
                ):
                    for kw in node.keywords:
                        if (
                            kw.arg == "retries"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value is True
                        ):
                            findings.append(Finding(
                                path, node.lineno, "kube-write-retry",
                                f"_request('{m.value}', ...) asks for "
                                "retries on a write verb — writes are "
                                "single-attempt (the actuator owns "
                                "their cadence)",
                                severity=ERROR, anchor=f"{fname}.retries",
                            ))
    return findings


# ---------------------------------------------------------------------------
# trace-contract

# the emitting helpers in utils/tracing.py; a literal first argument to
# any of them through a tracing-module alias is a span-name emission
_TRACE_EMITTERS = {"span", "phase", "make_span"}


def _tracing_aliases(mod) -> Set[str]:
    """Local names this module binds the tracing module to."""
    out = set()
    for bound, imp in mod.imports.items():
        target = imp[1] if imp[0] == "module" else f"{imp[1]}.{imp[2]}"
        if target.endswith("utils.tracing") or target == "tracing":
            out.add(bound)
    return out


def _span_registry(mod) -> Dict[str, int]:
    """{name: line} from the SPAN_NAMES dict literal in utils/tracing.py."""
    out: Dict[str, int] = {}
    for node in ast.walk(mod.tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        for tgt in targets:
            if (
                isinstance(tgt, ast.Name)
                and tgt.id == "SPAN_NAMES"
                and isinstance(node.value, ast.Dict)
            ):
                for key in node.value.keys:
                    if isinstance(key, ast.Constant) and isinstance(
                        key.value, str
                    ):
                        out[key.value] = key.lineno
    return out


def run_trace(project: Project, files) -> List[Finding]:
    tracing_mod = _find_module(project, "utils/tracing.py")
    if tracing_mod is None:
        return []
    declared = _span_registry(tracing_mod)
    if not declared:
        # a tracing module without a registry: nothing to enforce
        # (fixture trees exercising other passes stay inert)
        return []
    findings: List[Finding] = []
    emitted: Set[str] = set()

    for mod in project.modules.values():
        if mod is tracing_mod:
            # the module's own internals pass names through variables
            # (phase -> Trace.span); only alias-based emission counts
            continue
        aliases = _tracing_aliases(mod)
        if not aliases:
            continue
        path = relpath(mod.path)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (
                isinstance(f, ast.Attribute)
                and f.attr in _TRACE_EMITTERS
                and isinstance(f.value, ast.Name)
                and f.value.id in aliases
            ):
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if not (
                isinstance(arg, ast.Constant) and isinstance(arg.value, str)
            ):
                continue  # variable name: unscannable (precision > recall)
            if arg.value in declared:
                emitted.add(arg.value)
            else:
                findings.append(Finding(
                    path, node.lineno, "trace-contract",
                    f"span name '{arg.value}' is emitted but not "
                    "declared in utils/tracing.py SPAN_NAMES — the "
                    "dashboards and the flight-recorder schema key on "
                    "the registry; declare it (with a description) or "
                    "fix the name",
                    severity=ERROR, anchor=arg.value,
                ))

    reg_path = relpath(tracing_mod.path)
    for name, line in sorted(declared.items()):
        if name not in emitted:
            findings.append(Finding(
                reg_path, line, "trace-contract",
                f"span name '{name}' is declared in SPAN_NAMES but "
                "never emitted anywhere in the package — dead registry "
                "entry (or the emitting call site was lost in a "
                "refactor)",
                severity=ERROR, anchor=name,
            ))
    return findings


# ---------------------------------------------------------------------------
# manifest-contract

# The jit-root <-> HOT_PROGRAMS lockstep (docs/ANALYSIS.md "Jaxpr
# tier"): every jit/pjit/shard_map root the call graph can see inside
# the hot-path scope must be exercised by some jaxpr-tier manifest
# entry (its qualname matched by an entry's ``covers``) or listed in an
# ``EXEMPT_JIT_ROOTS`` dict with a justification — and, symmetrically,
# every ``covers`` string must still name a live root. Deleting a
# manifest entry or adding an unregistered jit root turns the gate red:
# the jaxpr tier's coverage can never silently shrink.
#
# The pass is inert on trees with no manifest infrastructure at all (no
# ``HOT_PROGRAMS`` / ``EXEMPT_JIT_ROOTS`` assignment anywhere in the
# analyzed files): fixture trees exercising other passes must not be
# forced to carry manifests, and the real package always walks
# hot_programs.py, so the gate is always live where it matters.


def _covers_matches(cover: str, root_qual: str) -> bool:
    """``cover`` matches ``root_qual`` as a dot/colon-bounded suffix."""
    if not cover:
        return False
    if root_qual == cover:
        return True
    if root_qual.endswith(cover):
        boundary = root_qual[-len(cover) - 1]
        return boundary in ".:"
    return False


def _manifest_surface(project: Project):
    """((entry_name, path, line, covers)..., exempts {pattern: (path,
    line)}, any_infra) parsed from HOT_PROGRAMS / EXEMPT_JIT_ROOTS
    dict literals (plain or annotated assignments — the shared parser
    in common.manifest_dict_literals, also used by the jaxpr tracer's
    line anchoring) in the analyzed files."""
    from tools.analysis.common import manifest_dict_literals

    entries = []
    exempts: Dict[str, Tuple[str, int]] = {}
    any_infra = False
    for mod in project.modules.values():
        path = relpath(mod.path)
        programs, has_programs = manifest_dict_literals(
            mod.tree, "HOT_PROGRAMS"
        )
        exempt_keys, has_exempts = manifest_dict_literals(
            mod.tree, "EXEMPT_JIT_ROOTS"
        )
        any_infra = any_infra or has_programs or has_exempts
        for name, lineno, val in programs:
            covers: List[str] = []
            if isinstance(val, ast.Call):
                for kw in val.keywords:
                    if kw.arg != "covers":
                        continue
                    if isinstance(kw.value, (ast.Tuple, ast.List)):
                        covers = [
                            e.value
                            for e in kw.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)
                        ]
            entries.append((name, path, lineno, covers))
        for pattern, lineno, _ in exempt_keys:
            exempts[pattern] = (path, lineno)
    return entries, exempts, any_infra


def run_manifest(project: Project, files) -> List[Finding]:
    from tools.analysis.passes.hotpath import in_scope, jit_roots

    entries, exempts, any_infra = _manifest_surface(project)
    if not any_infra:
        return []
    findings: List[Finding] = []

    roots = []
    seen_roots = set()
    for info in jit_roots(project):
        if not in_scope(info.path):
            continue
        if info.qual in seen_roots:
            continue
        seen_roots.add(info.qual)
        roots.append(info)

    all_covers = [c for _, _, _, covers in entries for c in covers]

    for info in roots:
        covered = any(_covers_matches(c, info.qual) for c in all_covers)
        exempted = any(
            _covers_matches(pat, info.qual) for pat in exempts
        )
        if not covered and not exempted:
            findings.append(Finding(
                relpath(info.path), info.line, "manifest-contract",
                f"jit root '{info.qual}' is not covered by any "
                "HOT_PROGRAMS entry (jaxpr-tier audit) and not listed "
                "in EXEMPT_JIT_ROOTS — register a traced probe for it "
                "or exempt it with a justification",
                severity=ERROR, anchor=info.qual.split(":", 1)[-1],
            ))

    root_quals = [info.qual for info in roots]
    for name, path, line, covers in entries:
        for c in covers:
            if not any(_covers_matches(c, q) for q in root_quals):
                findings.append(Finding(
                    path, line, "manifest-contract",
                    f"HOT_PROGRAMS entry '{name}' covers "
                    f"'{c}' but no such jit root exists — the root was "
                    "removed or renamed; fix or delete the manifest "
                    "entry (coverage bookkeeping must not rot)",
                    severity=ERROR, anchor=f"{name}.{c}",
                ))

    for pat, (path, line) in sorted(exempts.items()):
        if not any(_covers_matches(pat, q) for q in root_quals):
            findings.append(Finding(
                path, line, "manifest-contract",
                f"EXEMPT_JIT_ROOTS pattern '{pat}' matches no jit "
                "root — stale exemption; delete it",
                severity=WARN, anchor=f"exempt.{pat}",
            ))
    return findings
