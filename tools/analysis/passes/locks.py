"""Lock-discipline + lock-graph audits.

Two passes live here. ``lock-discipline`` (``run``) is the per-class
write-protection audit described below. ``lock-graph`` (``run_graph``)
is the interprocedural deadlock audit: it builds a lock-acquisition-
order graph over the WHOLE package — which locks can be held when
every other acquire is reachable, flowing holds through the symbols
call graph — and reports

- any cycle in the acquisition order as an error naming the full
  cycle path (two threads walking the cycle from different entry
  points deadlock),
- a non-reentrant ``Lock`` acquired while already held (including
  transitively, through calls) as an error,
- ``Condition.wait`` while holding a DIFFERENT lock, and any blocking
  operation (HTTP round-trip, socket send/recv, queue wait, device
  solve, ``time.sleep``) reached with a lock held, as warnings —
  latency bombs rather than certain deadlocks.

Lock identity is (module, class, attribute) for ``self.<x>`` locks and
(module, name) for module-level locks; ``threading.Condition()``'s
default internal RLock makes nested re-entry on the same condition
benign, so self-edges on RLock/Condition nodes are dropped. The graph
is an AST approximation with the usual contract-pass bias: unresolved
receivers (locks reached through non-self objects, calls the symbol
table cannot see) cost recall, never false findings.

The ``lock-discipline`` contract:
for every class that owns a ``threading.Lock``/``RLock``/``Condition``
(assigned to ``self.<x>`` anywhere in the class), the attributes that
class protects must only be MUTATED under that protection. "Protected"
is inferred, not annotated: any attribute written inside a
``with self.<lock>:`` block (outside ``__init__``) is treated as
lock-guarded, and every other write to it must then also hold a lock.

A write counts as lock-held when it is

- lexically inside a ``with self.<lock>:`` body,
- in a function that called ``self.<lock>.acquire(...)`` earlier
  (the try/finally acquire-release idiom), or
- in a method whose *every* intra-class call site is lock-held
  (computed to fixpoint), or whose name ends in ``_locked``/
  ``_unlocked`` — the caller-holds-the-lock convention.

``__init__`` is exempt: construction happens-before publication.
Reads are deliberately out of scope (the codebase's stores use
copy-on-read snapshots; racing reads are a different, weaker contract).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analysis.common import ERROR, WARN, Finding, relpath
from tools.analysis.symbols import Project, dotted

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}
_HELD_SUFFIXES = ("_locked", "_unlocked")


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _MethodFacts:
    def __init__(self, name: str, node: ast.AST):
        self.name = name
        self.node = node
        # (attr, line) writes partitioned by lock context
        self.locked_writes: List[Tuple[str, int]] = []
        self.unlocked_writes: List[Tuple[str, int]] = []
        # intra-class calls: (callee method name, in_lock_context)
        self.calls: List[Tuple[str, bool]] = []
        self.acquires_lock = False


def _with_holds_lock(item: ast.withitem, lock_attrs: Set[str]) -> bool:
    expr = item.context_expr
    attr = _self_attr(expr)
    if attr in lock_attrs:
        return True
    # with self._lock.acquire_timeout(...) style / cond variables
    if isinstance(expr, ast.Call):
        base = _self_attr(expr.func.value) if isinstance(
            expr.func, ast.Attribute
        ) else None
        if base in lock_attrs:
            return True
    return False


def _collect_method(
    method: ast.AST, lock_attrs: Set[str]
) -> _MethodFacts:
    facts = _MethodFacts(method.name, method)

    def walk(node: ast.AST, in_lock: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs audited separately if methods
            child_lock = in_lock
            if isinstance(child, ast.With):
                if any(
                    _with_holds_lock(i, lock_attrs) for i in child.items
                ):
                    child_lock = True
            if isinstance(child, ast.Call):
                cal = dotted(child.func)
                if cal and cal.startswith("self."):
                    parts = cal.split(".")
                    if len(parts) == 3 and parts[1] in lock_attrs:
                        if parts[2] == "acquire":
                            facts.acquires_lock = True
                    elif len(parts) == 2:
                        facts.calls.append((parts[1], in_lock))
            if isinstance(child, (ast.Assign, ast.AugAssign)):
                targets = (
                    child.targets
                    if isinstance(child, ast.Assign)
                    else [child.target]
                )
                for tgt in targets:
                    for sub in ast.walk(tgt):
                        attr = _self_attr(sub)
                        if attr is None or attr in lock_attrs:
                            continue
                        if not isinstance(
                            getattr(sub, "ctx", None), ast.Store
                        ):
                            continue
                        bucket = (
                            facts.locked_writes
                            if child_lock or facts.acquires_lock
                            else facts.unlocked_writes
                        )
                        bucket.append((attr, sub.lineno))
            walk(child, child_lock)

    walk(method, False)
    return facts


def run(project: Project, files) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.modules.values():
        path = relpath(mod.path)
        for cls in mod.classes.values():
            # lock attributes of this class
            lock_attrs: Set[str] = set()
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    if dotted(node.value.func) in _LOCK_CTORS:
                        for tgt in node.targets:
                            attr = _self_attr(tgt)
                            if attr:
                                lock_attrs.add(attr)
            if not lock_attrs:
                continue

            methods: Dict[str, _MethodFacts] = {}
            for node in cls.body:
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    methods[node.name] = _collect_method(node, lock_attrs)

            # guarded attributes: written under a lock anywhere (not
            # __init__)
            guarded: Set[str] = set()
            for m in methods.values():
                if m.name == "__init__":
                    continue
                guarded.update(a for a, _ in m.locked_writes)
            if not guarded:
                continue

            # lock-held methods, to fixpoint: every intra-class call
            # site is inside a lock context or a lock-held method
            held: Set[str] = {
                m for m in methods if m.endswith(_HELD_SUFFIXES)
            }
            callers: Dict[str, List[Tuple[str, bool]]] = {}
            for m in methods.values():
                for callee, in_lock in m.calls:
                    callers.setdefault(callee, []).append(
                        (m.name, in_lock)
                    )
            changed = True
            while changed:
                changed = False
                for name, m in methods.items():
                    if name in held or not name.startswith("_"):
                        continue
                    sites = callers.get(name)
                    if not sites:
                        continue
                    if all(
                        in_lock
                        or caller in held
                        or methods[caller].acquires_lock
                        for caller, in_lock in sites
                        if caller in methods
                    ):
                        held.add(name)
                        changed = True

            for name, m in methods.items():
                if name == "__init__" or name in held:
                    continue
                for attr, line in m.unlocked_writes:
                    if attr in guarded:
                        lock_list = "/".join(sorted(lock_attrs))
                        findings.append(Finding(
                            path, line, "lock-discipline",
                            f"{cls.name}.{name} writes 'self.{attr}' "
                            f"without holding {cls.name}'s lock "
                            f"({lock_list}); the same attribute is "
                            "written under the lock elsewhere — this "
                            "write races with those",
                            severity=ERROR,
                            anchor=f"{cls.name}.{name}.{attr}",
                        ))
    return findings


# ---------------------------------------------------------------------
# lock-graph: interprocedural acquisition-order audit (run_graph)
# ---------------------------------------------------------------------

_QUEUE_CTORS = {
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "Queue", "SimpleQueue",
}

# attribute-call names that park the calling thread; the value is the
# label used in findings. Deliberately tight: a generic name here
# ("read", "get") would spray warnings over non-blocking code.
_BLOCKING_ATTRS = {
    "sleep": "time.sleep",
    "urlopen": "HTTP round-trip (urlopen)",
    "getresponse": "HTTP response wait",
    "sendall": "socket send",
    "recv": "socket recv",
    "accept": "socket accept",
    "block_until_ready": "device solve wait",
}

# queue methods that can block the caller
_QUEUE_WAIT_ATTRS = {"get", "put", "join"}


class _LockMeta:
    __slots__ = ("kind", "path", "line", "display")

    def __init__(self, kind, path, line, display):
        self.kind = kind  # "Lock" | "RLock" | "Condition"
        self.path = path
        self.line = line
        self.display = display

    @property
    def reentrant(self) -> bool:
        # Condition() wraps an RLock by default
        return self.kind in ("RLock", "Condition")


def _class_own_assigns(cls: ast.ClassDef):
    """Assign nodes in ``cls`` excluding nested ClassDef bodies, so a
    nested handler class's locks are not attributed to the outer."""
    stack = list(cls.body)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.ClassDef):
            continue
        if isinstance(node, ast.Assign):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _lock_registry(project: Project):
    """(locks, queues): identity -> meta for every literal lock/queue
    construction. Identity is (module_id, class_or_empty, name)."""
    locks: Dict[tuple, _LockMeta] = {}
    queues: Dict[tuple, _LockMeta] = {}
    for mod in project.modules.values():
        path = relpath(mod.path)
        stem = path.rsplit("/", 1)[-1].removesuffix(".py")
        for node in mod.tree.body:
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
            ):
                continue
            ctor = dotted(node.value.func)
            if ctor not in _LOCK_CTORS:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    locks[(mod.module_id, "", tgt.id)] = _LockMeta(
                        ctor.split(".")[-1], path, node.lineno,
                        f"{stem}.{tgt.id}",
                    )
        for cls in mod.classes.values():
            for node in _class_own_assigns(cls):
                if not isinstance(node.value, ast.Call):
                    continue
                ctor = dotted(node.value.func)
                if ctor is None:
                    continue
                reg = (
                    locks if ctor in _LOCK_CTORS
                    else queues if ctor in _QUEUE_CTORS
                    else None
                )
                if reg is None:
                    continue
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr:
                        reg[(mod.module_id, cls.name, attr)] = _LockMeta(
                            ctor.split(".")[-1], path, node.lineno,
                            f"{cls.name}.{attr}",
                        )
    return locks, queues


class _GraphFacts:
    """Lock-relevant events of one function, in lexical order."""

    def __init__(self):
        # (lock_key, line, held_frozenset) — every acquisition point
        self.acquires: List[tuple] = []
        # (call_node, line, held_frozenset) — every call expression
        self.calls: List[tuple] = []
        # (label, line, held_frozenset) — direct blocking operations
        self.blocking: List[tuple] = []
        # (cond_key, line, held_frozenset) — Condition.wait sites
        self.waits: List[tuple] = []


def _resolve_lock(expr: ast.AST, fn, registry) -> Optional[tuple]:
    """The registry key a lock-ish expression denotes, if resolvable:
    ``self.<attr>`` against the function's class, a bare name against
    the module's top-level locks."""
    attr = _self_attr(expr)
    if attr is not None and fn.cls:
        key = (fn.module.module_id, fn.cls, attr)
        return key if key in registry else None
    if isinstance(expr, ast.Name):
        key = (fn.module.module_id, "", expr.id)
        return key if key in registry else None
    return None


def _graph_facts(fn, locks, queues) -> _GraphFacts:
    facts = _GraphFacts()
    sticky: Set[tuple] = set()  # .acquire() without with, until .release()

    def visit(node: ast.AST, held: frozenset) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                 ast.Lambda),
            ):
                continue  # separate scopes get their own facts
            child_held = held
            if isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    expr = item.context_expr
                    lk = _resolve_lock(expr, fn, locks)
                    if lk is None and isinstance(expr, ast.Call) and (
                        isinstance(expr.func, ast.Attribute)
                    ):
                        lk = _resolve_lock(expr.func.value, fn, locks)
                    if lk is not None:
                        facts.acquires.append(
                            (lk, child.lineno,
                             child_held | frozenset(sticky))
                        )
                        child_held = child_held | {lk}
            if isinstance(child, ast.Call):
                eff = held | frozenset(sticky)
                handled = False
                if isinstance(child.func, ast.Attribute):
                    base_lock = _resolve_lock(
                        child.func.value, fn, locks
                    )
                    if base_lock is not None:
                        meth = child.func.attr
                        if meth == "acquire":
                            facts.acquires.append(
                                (base_lock, child.lineno, eff)
                            )
                            sticky.add(base_lock)
                            handled = True
                        elif meth == "release":
                            sticky.discard(base_lock)
                            handled = True
                        elif meth in ("wait", "wait_for"):
                            facts.waits.append(
                                (base_lock, child.lineno, eff)
                            )
                            handled = True
                        elif meth in ("notify", "notify_all", "locked"):
                            handled = True
                    elif (
                        _resolve_lock(child.func.value, fn, queues)
                        is not None
                        and child.func.attr in _QUEUE_WAIT_ATTRS
                    ):
                        facts.blocking.append((
                            f"queue {child.func.attr}()",
                            child.lineno, eff,
                        ))
                        handled = True
                    elif child.func.attr in _BLOCKING_ATTRS:
                        facts.blocking.append((
                            _BLOCKING_ATTRS[child.func.attr],
                            child.lineno, eff,
                        ))
                        handled = True
                if not handled:
                    facts.calls.append((child, child.lineno, eff))
            visit(child, child_held)

    visit(fn.node, frozenset())
    return facts


def _fq(fn) -> str:
    """Human-readable function identity: path::qual."""
    return f"{relpath(fn.path)}::{fn.qual.split(':', 1)[1]}"


def _transitive(project, fn_facts, direct_of, combine_key):
    """Generic transitive may-X summary with witness chains.

    ``direct_of(facts)`` yields (key, line) pairs; the result maps each
    function to {key: ("qual:line", ...) witness chain}. Call cycles
    are cut (the on-stack callee contributes nothing on that path)."""
    memo: Dict[object, dict] = {}
    on_stack: Set[object] = set()

    def summary(fn):
        if fn in memo:
            return memo[fn]
        if fn in on_stack:
            return {}
        on_stack.add(fn)
        out: dict = {}
        facts = fn_facts[fn]
        for key, line in direct_of(facts):
            out.setdefault(key, (f"{_fq(fn)}:{line}",))
        for call_node, line, _held in facts.calls:
            callee = project.resolve_call(
                fn.module, call_node.func, fn
            )
            if callee is None or callee not in fn_facts:
                continue
            for key, chain in summary(callee).items():
                out.setdefault(
                    combine_key(key),
                    (f"{_fq(fn)}:{line}",) + chain,
                )
        on_stack.discard(fn)
        memo[fn] = out
        return out

    for fn in fn_facts:
        summary(fn)
    return memo


def run_graph(project: Project, files) -> List[Finding]:
    """The lock-graph pass (see module docstring)."""
    findings: List[Finding] = []
    locks, queues = _lock_registry(project)
    if not locks:
        return []

    fn_facts = {}
    for mod in project.modules.values():
        for fn in mod.functions.values():
            fn_facts[fn] = _graph_facts(fn, locks, queues)

    may_acquire = _transitive(
        project, fn_facts,
        direct_of=lambda f: [(lk, ln) for lk, ln, _ in f.acquires],
        combine_key=lambda k: k,
    )
    may_block = _transitive(
        project, fn_facts,
        direct_of=lambda f: (
            [(label, ln) for label, ln, _ in f.blocking]
            + [("Condition.wait", ln) for _, ln, _ in f.waits]
        ),
        combine_key=lambda k: k,
    )

    # ---- build the acquisition-order graph ---------------------------
    # edge (held -> acquired) -> (path, line, witness chain or None)
    edges: Dict[tuple, tuple] = {}

    def add_edge(src, dst, path, line, chain):
        edges.setdefault((src, dst), (path, line, chain))

    warn_seen: Set[tuple] = set()
    for fn, facts in fn_facts.items():
        path = relpath(fn.path)
        for lk, line, held in facts.acquires:
            for h in held:
                add_edge(h, lk, path, line, None)
        for call_node, line, held in facts.calls:
            if not held:
                continue
            callee = project.resolve_call(
                fn.module, call_node.func, fn
            )
            if callee is None or callee not in fn_facts:
                continue
            for lk, chain in may_acquire[callee].items():
                for h in held:
                    add_edge(h, lk, path, line, chain)
            for label, chain in may_block[callee].items():
                key = (fn, label)
                if key in warn_seen:
                    continue
                warn_seen.add(key)
                held_names = ", ".join(
                    sorted(locks[h].display for h in held)
                )
                findings.append(Finding(
                    path, line, "lock-graph",
                    f"{fn.name} holds {held_names} across a blocking "
                    f"operation: {label} via "
                    f"{' -> '.join(chain)} — lock hold time is bounded "
                    "by I/O, not compute",
                    severity=WARN,
                    anchor=f"block.{fn.qual.split(':', 1)[1]}.{label}",
                ))
        for label, line, held in facts.blocking:
            if not held:
                continue
            key = (fn, label, line)
            if key in warn_seen:
                continue
            warn_seen.add(key)
            held_names = ", ".join(
                sorted(locks[h].display for h in held)
            )
            findings.append(Finding(
                path, line, "lock-graph",
                f"{fn.name} holds {held_names} across a blocking "
                f"operation: {label} — lock hold time is bounded by "
                "I/O, not compute",
                severity=WARN,
                anchor=f"block.{fn.qual.split(':', 1)[1]}.{label}",
            ))
        for cond, line, held in facts.waits:
            others = [h for h in held if h != cond]
            if not others:
                continue
            held_names = ", ".join(
                sorted(locks[h].display for h in others)
            )
            findings.append(Finding(
                path, line, "lock-graph",
                f"{fn.name} waits on {locks[cond].display} while "
                f"holding {held_names}; the wakeup needs another "
                "thread to get past those locks — a classic "
                "lost-wakeup deadlock shape",
                severity=ERROR,
                anchor=f"wait.{fn.qual.split(':', 1)[1]}",
            ))
            # waiting re-acquires the condition on wake: ordering edge
            for h in others:
                add_edge(h, cond, relpath(fn.path), line, None)

    # ---- self-acquisition of a non-reentrant lock --------------------
    for (src, dst), (path, line, chain) in sorted(
        edges.items(),
        key=lambda kv: (kv[1][0], kv[1][1]),
    ):
        if src == dst and not locks[src].reentrant:
            via = f" via {' -> '.join(chain)}" if chain else ""
            findings.append(Finding(
                path, line, "lock-graph",
                f"non-reentrant Lock {locks[src].display} is "
                f"acquired while already held{via} — this "
                "self-deadlocks",
                severity=ERROR,
                anchor=f"self.{locks[src].display}",
            ))

    # ---- cycles in the acquisition order -----------------------------
    adj: Dict[tuple, List[tuple]] = {}
    for (src, dst) in edges:
        if src != dst:
            adj.setdefault(src, []).append(dst)

    # iterative DFS cycle detection with path reconstruction; each
    # cycle is canonicalized (rotated to its smallest node) so one
    # cycle yields one finding regardless of entry point
    reported: Set[tuple] = set()
    for start in sorted(adj, key=lambda k: locks[k].display):
        stack = [(start, iter(adj.get(start, ())))]
        on_path = [start]
        on_path_set = {start}
        visited_from_start: Set[tuple] = set()
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                if nxt in on_path_set:
                    cycle = on_path[on_path.index(nxt):] + [nxt]
                    nodes = tuple(cycle[:-1])
                    pivot = min(
                        range(len(nodes)),
                        key=lambda i: locks[nodes[i]].display,
                    )
                    canon = nodes[pivot:] + nodes[:pivot]
                    if canon in reported:
                        continue
                    reported.add(canon)
                    path_names = " -> ".join(
                        locks[n].display
                        for n in canon + (canon[0],)
                    )
                    first_edge = edges[(canon[0], canon[1 % len(canon)])]
                    epath, eline, chain = first_edge
                    via = (
                        f"; first edge via {' -> '.join(chain)}"
                        if chain else ""
                    )
                    findings.append(Finding(
                        epath, eline, "lock-graph",
                        "lock acquisition cycle: "
                        f"{path_names} — two threads entering the "
                        "cycle at different locks deadlock"
                        f"{via}",
                        severity=ERROR,
                        anchor="cycle." + "->".join(
                            locks[n].display for n in canon
                        ),
                    ))
                elif nxt not in visited_from_start:
                    visited_from_start.add(nxt)
                    stack.append((nxt, iter(adj.get(nxt, ()))))
                    on_path.append(nxt)
                    on_path_set.add(nxt)
                    advanced = True
                    break
            if not advanced:
                stack.pop()
                on_path.pop()
                on_path_set.discard(node)
    return findings
