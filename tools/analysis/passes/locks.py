"""Lock-discipline audit.

For every class that owns a ``threading.Lock``/``RLock``/``Condition``
(assigned to ``self.<x>`` anywhere in the class), the attributes that
class protects must only be MUTATED under that protection. "Protected"
is inferred, not annotated: any attribute written inside a
``with self.<lock>:`` block (outside ``__init__``) is treated as
lock-guarded, and every other write to it must then also hold a lock.

A write counts as lock-held when it is

- lexically inside a ``with self.<lock>:`` body,
- in a function that called ``self.<lock>.acquire(...)`` earlier
  (the try/finally acquire-release idiom), or
- in a method whose *every* intra-class call site is lock-held
  (computed to fixpoint), or whose name ends in ``_locked``/
  ``_unlocked`` — the caller-holds-the-lock convention.

``__init__`` is exempt: construction happens-before publication.
Reads are deliberately out of scope (the codebase's stores use
copy-on-read snapshots; racing reads are a different, weaker contract).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analysis.common import ERROR, Finding, relpath
from tools.analysis.symbols import Project, dotted

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}
_HELD_SUFFIXES = ("_locked", "_unlocked")


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _MethodFacts:
    def __init__(self, name: str, node: ast.AST):
        self.name = name
        self.node = node
        # (attr, line) writes partitioned by lock context
        self.locked_writes: List[Tuple[str, int]] = []
        self.unlocked_writes: List[Tuple[str, int]] = []
        # intra-class calls: (callee method name, in_lock_context)
        self.calls: List[Tuple[str, bool]] = []
        self.acquires_lock = False


def _with_holds_lock(item: ast.withitem, lock_attrs: Set[str]) -> bool:
    expr = item.context_expr
    attr = _self_attr(expr)
    if attr in lock_attrs:
        return True
    # with self._lock.acquire_timeout(...) style / cond variables
    if isinstance(expr, ast.Call):
        base = _self_attr(expr.func.value) if isinstance(
            expr.func, ast.Attribute
        ) else None
        if base in lock_attrs:
            return True
    return False


def _collect_method(
    method: ast.AST, lock_attrs: Set[str]
) -> _MethodFacts:
    facts = _MethodFacts(method.name, method)

    def walk(node: ast.AST, in_lock: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs audited separately if methods
            child_lock = in_lock
            if isinstance(child, ast.With):
                if any(
                    _with_holds_lock(i, lock_attrs) for i in child.items
                ):
                    child_lock = True
            if isinstance(child, ast.Call):
                cal = dotted(child.func)
                if cal and cal.startswith("self."):
                    parts = cal.split(".")
                    if len(parts) == 3 and parts[1] in lock_attrs:
                        if parts[2] == "acquire":
                            facts.acquires_lock = True
                    elif len(parts) == 2:
                        facts.calls.append((parts[1], in_lock))
            if isinstance(child, (ast.Assign, ast.AugAssign)):
                targets = (
                    child.targets
                    if isinstance(child, ast.Assign)
                    else [child.target]
                )
                for tgt in targets:
                    for sub in ast.walk(tgt):
                        attr = _self_attr(sub)
                        if attr is None or attr in lock_attrs:
                            continue
                        if not isinstance(
                            getattr(sub, "ctx", None), ast.Store
                        ):
                            continue
                        bucket = (
                            facts.locked_writes
                            if child_lock or facts.acquires_lock
                            else facts.unlocked_writes
                        )
                        bucket.append((attr, sub.lineno))
            walk(child, child_lock)

    walk(method, False)
    return facts


def run(project: Project, files) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.modules.values():
        path = relpath(mod.path)
        for cls in mod.classes.values():
            # lock attributes of this class
            lock_attrs: Set[str] = set()
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    if dotted(node.value.func) in _LOCK_CTORS:
                        for tgt in node.targets:
                            attr = _self_attr(tgt)
                            if attr:
                                lock_attrs.add(attr)
            if not lock_attrs:
                continue

            methods: Dict[str, _MethodFacts] = {}
            for node in cls.body:
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    methods[node.name] = _collect_method(node, lock_attrs)

            # guarded attributes: written under a lock anywhere (not
            # __init__)
            guarded: Set[str] = set()
            for m in methods.values():
                if m.name == "__init__":
                    continue
                guarded.update(a for a, _ in m.locked_writes)
            if not guarded:
                continue

            # lock-held methods, to fixpoint: every intra-class call
            # site is inside a lock context or a lock-held method
            held: Set[str] = {
                m for m in methods if m.endswith(_HELD_SUFFIXES)
            }
            callers: Dict[str, List[Tuple[str, bool]]] = {}
            for m in methods.values():
                for callee, in_lock in m.calls:
                    callers.setdefault(callee, []).append(
                        (m.name, in_lock)
                    )
            changed = True
            while changed:
                changed = False
                for name, m in methods.items():
                    if name in held or not name.startswith("_"):
                        continue
                    sites = callers.get(name)
                    if not sites:
                        continue
                    if all(
                        in_lock
                        or caller in held
                        or methods[caller].acquires_lock
                        for caller, in_lock in sites
                        if caller in methods
                    ):
                        held.add(name)
                        changed = True

            for name, m in methods.items():
                if name == "__init__" or name in held:
                    continue
                for attr, line in m.unlocked_writes:
                    if attr in guarded:
                        lock_list = "/".join(sorted(lock_attrs))
                        findings.append(Finding(
                            path, line, "lock-discipline",
                            f"{cls.name}.{name} writes 'self.{attr}' "
                            f"without holding {cls.name}'s lock "
                            f"({lock_list}); the same attribute is "
                            "written under the lock elsewhere — this "
                            "write races with those",
                            severity=ERROR,
                            anchor=f"{cls.name}.{name}.{attr}",
                        ))
    return findings
