"""Analysis passes. Each exposes ``run(project, files) -> [Finding]``.

``project`` is the package-wide symbol table / call graph
(tools/analysis/symbols.Project); ``files`` maps path -> source text for
every analyzed file. Pass registration lives in tools/analysis/engine.py.
"""

from tools.analysis.passes import (  # noqa: F401
    contracts,
    exceptions,
    flightkinds,
    hotpath,
    locks,
)

ALL_PASSES = (
    ("jax-host-sync", hotpath.run_host_sync),
    ("donation-discipline", hotpath.run_donation),
    ("recompile-trigger", hotpath.run_recompile),
    ("metrics-contract", contracts.run_metrics),
    ("config-contract", contracts.run_config),
    ("kube-write-retry", contracts.run_kube_writes),
    ("trace-contract", contracts.run_trace),
    ("manifest-contract", contracts.run_manifest),
    ("flight-contract", flightkinds.run),
    ("lock-discipline", locks.run),
    ("lock-graph", locks.run_graph),
    ("exception-discipline", exceptions.run),
)
