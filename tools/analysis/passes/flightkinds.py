"""flight-contract: the flight-recorder kind registry vs reality.

``loop/flight.py`` declares the closed vocabularies
``DEGRADATION_KINDS`` / ``CONTEXT_KINDS``; every degradation path in
the tree narrates itself through ``flight.note_event(<kind>, ...)``
(or a funnel like the server's ``_note_shed``, whose ``kind=`` kwarg
and literal default both count as emissions). The same shape as
metrics-contract, in BOTH directions plus the doc:

- a kind emitted anywhere but missing from the declared sets is an
  error at the emission site (the recorder would raise at runtime —
  this catches it at vet time, on paths no test drives);
- a declared kind that no call site ever emits is an error at the
  declaration (dead vocabulary reads as coverage that isn't there);
- every declared kind must appear in docs/OBSERVABILITY.md (loaded by
  the engine as ``files["__observability__"]``) as a literal
  `` `kind` `` mention — the kind table is operator-facing API.

Funnels are found structurally: any function with a ``kind`` parameter
whose body calls ``note_event`` forwards its callers' literal ``kind=``
arguments (and its own literal default) into the recorder. Literal
strings only, as ever: a kind computed at runtime is simply not bound.
Inert on trees without a flight module or declared kind sets.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from tools.analysis.common import ERROR, Finding, relpath
from tools.analysis.passes.contracts import _find_module
from tools.analysis.symbols import Project, dotted

FLIGHT_SUFFIX = "loop/flight.py"
DECLARED_SETS = ("DEGRADATION_KINDS", "CONTEXT_KINDS")


def _frozenset_literal(
    tree: ast.Module, name: str
) -> Optional[Tuple[Dict[str, int], int]]:
    """({kind: lineno}, assign_lineno) of a literal
    ``name = frozenset({...})`` / ``name = {...}`` declaration."""
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == name
            for t in node.targets
        ):
            continue
        value = node.value
        if (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id == "frozenset"
            and value.args
        ):
            value = value.args[0]
        if not isinstance(value, (ast.Set, ast.List, ast.Tuple)):
            continue
        kinds = {
            e.value: e.lineno
            for e in value.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        }
        return kinds, node.lineno
    return None


def _funnels(project: Project) -> Dict[str, Optional[str]]:
    """{function_name: literal_kind_default_or_None} for every
    function that takes a ``kind`` parameter and forwards it into
    ``note_event`` — callers' literal ``kind=`` kwargs (and the
    default itself) are emissions."""
    out: Dict[str, Optional[str]] = {}
    for mod in project.modules.values():
        for fn in mod.functions.values():
            args = fn.node.args
            params = list(args.posonlyargs) + list(args.args)
            names = [p.arg for p in params] + [
                p.arg for p in args.kwonlyargs
            ]
            if "kind" not in names:
                continue
            forwards = False
            for sub in ast.walk(fn.node):
                if isinstance(sub, ast.Call):
                    d = dotted(sub.func)
                    if d and d.split(".")[-1] == "note_event":
                        forwards = True
                        break
            if not forwards:
                continue
            default = None
            defaults = list(args.defaults)
            for param, dflt in zip(
                params[len(params) - len(defaults):], defaults
            ):
                if param.arg == "kind" and isinstance(
                    dflt, ast.Constant
                ) and isinstance(dflt.value, str):
                    default = dflt.value
            for param, dflt in zip(args.kwonlyargs, args.kw_defaults):
                if param.arg == "kind" and isinstance(
                    dflt, ast.Constant
                ) and isinstance(dflt.value, str):
                    default = dflt.value
            out[fn.name] = default
    return out


def run(project: Project, files) -> List[Finding]:
    flight_mod = _find_module(project, FLIGHT_SUFFIX)
    if flight_mod is None:
        return []
    flight_path = relpath(flight_mod.path)
    declared: Dict[str, Tuple[str, int]] = {}  # kind -> (set, lineno)
    found_any = False
    for set_name in DECLARED_SETS:
        parsed = _frozenset_literal(flight_mod.tree, set_name)
        if parsed is None:
            continue
        found_any = True
        kinds, _ = parsed
        for kind, lineno in kinds.items():
            declared.setdefault(kind, (set_name, lineno))
    if not found_any:
        return []  # tree has a flight module but no kind vocabulary

    funnels = _funnels(project)

    # every literal emission outside flight.py: kind -> [(path, line)]
    emitted: Dict[str, List[Tuple[str, int]]] = {}
    for mod in project.modules.values():
        if mod is flight_mod:
            continue
        path = relpath(mod.path)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            leaf = d.split(".")[-1] if d else None
            if leaf == "note_event":
                if (
                    node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    emitted.setdefault(
                        node.args[0].value, []
                    ).append((path, node.lineno))
            elif leaf in funnels:
                explicit = False
                for kw in node.keywords:
                    if (
                        kw.arg == "kind"
                        and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)
                    ):
                        emitted.setdefault(
                            kw.value.value, []
                        ).append((path, node.lineno))
                        explicit = True
                if not explicit and funnels[leaf] is not None:
                    emitted.setdefault(
                        funnels[leaf], []
                    ).append((path, node.lineno))

    findings: List[Finding] = []

    # direction 1: emitted but undeclared — the recorder would reject
    # it at runtime on a path no test may drive
    for kind in sorted(set(emitted) - set(declared)):
        path, line = emitted[kind][0]
        findings.append(Finding(
            path, line, "flight-contract",
            f"flight kind '{kind}' is emitted here but absent from "
            f"{flight_path}'s DEGRADATION_KINDS/CONTEXT_KINDS — "
            "note_event would drop or reject it",
            severity=ERROR, anchor=f"kind.{kind}",
        ))

    # direction 2: declared but never emitted — dead vocabulary
    for kind in sorted(set(declared) - set(emitted)):
        set_name, lineno = declared[kind]
        findings.append(Finding(
            flight_path, lineno, "flight-contract",
            f"flight kind '{kind}' is declared in {set_name} but no "
            "call site ever emits it (literal scan over "
            "note_event and its funnels)",
            severity=ERROR, anchor=f"kind.{kind}",
        ))

    # direction 3: declared but undocumented — the kind table in
    # docs/OBSERVABILITY.md is the operator-facing API
    doc = files.get("__observability__")
    if doc is not None:
        for kind in sorted(declared):
            if f"`{kind}`" not in doc:
                set_name, lineno = declared[kind]
                findings.append(Finding(
                    flight_path, lineno, "flight-contract",
                    f"flight kind '{kind}' ({set_name}) is not "
                    "documented in docs/OBSERVABILITY.md — add it to "
                    "the kind table",
                    severity=ERROR, anchor=f"doc.{kind}",
                ))
    return findings
