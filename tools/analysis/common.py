"""Shared machinery of the lint gate and the analysis suite.

One file walker and ONE suppression grammar for both tools, so a
``# noqa`` comment means the same thing to ``tools/lint.py`` (the
per-file style gate) and ``tools/analysis`` (the cross-module vet):

- ``# noqa: <code>[, <code>...]`` suppresses exactly the named codes on
  that line. Codes must be known (a registered lint/analysis code, one
  of the conventional external aliases below, or an ``F401``-style alias
  that maps onto a local code) — an unrecognized code is itself a
  ``unknown-suppression`` finding, because it suppresses nothing and
  reads as if it did.
- a bare ``# noqa`` suppresses NOTHING and is an error finding
  (``bare-noqa``): the bare form would silence every current and future
  check on the line, which is how grandfathered lines rot.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path

SKIP_DIRS = {".git", "__pycache__", ".claude", "node_modules"}

# The ONE root list both gates walk (tools/lint.py and tools/analysis).
# Load-bearing: analysis owns the suppression-hygiene findings
# (bare-noqa / unknown-suppression) for every file lint walks — a root
# added to one tool and not the other would break that one-defect-
# one-finding split.
DEFAULT_ROOTS = [
    "k8s_spot_rescheduler_tpu", "tests", "tools",
    "bench.py", "__graft_entry__.py",
]

# --- severity tiers -------------------------------------------------------

ERROR = "error"  # fails the gate
WARN = "warn"  # reported; fails only under --strict (or when un-baselined
#                entries should be triaged — see docs/ANALYSIS.md)

# --- code registry --------------------------------------------------------

LINT_CODES = {
    "unused-import",
    "redefinition",
    "bare-except",
    "none-compare",
    "empty-fstring",
    "mutable-default",
    "syntax-error",
    "trailing-space",
    "tab-indent",
    "no-final-newline",
    "crlf",
}

ANALYSIS_CODES = {
    "jax-host-sync",
    "donation-discipline",
    "recompile-trigger",
    "metrics-contract",
    "config-contract",
    "kube-write-retry",
    "lock-discipline",
    "lock-graph",
    "flight-contract",
    "manifest-contract",
    "exception-discipline",
    "bare-noqa",
    "unknown-suppression",
    "stale-baseline",
    # jaxpr tier (tools/analysis/jaxpr — traced-program passes)
    "dtype-promotion",
    "index-width",
    "transfer-audit",
    "memory-reconcile",
    "trace-failure",
    # proto tier (tools/analysis/proto — protocol model + contract)
    "protocol-model",
    "protocol-contract",
}

# Conventional flake8-family codes used as machine-readable annotations in
# this tree (e.g. ``except Exception:  # noqa: BLE001`` documents a
# deliberate blind except). They are inert for our own passes unless
# aliased below, but recognized so they don't read as typos.
EXTERNAL_CODES = {"BLE001", "E402", "E731"}

# External codes that map onto one of OUR codes: suppressing the alias
# suppresses the local code too (``# noqa: F401`` keeps working on
# re-export imports).
ALIASES = {"F401": "unused-import"}

KNOWN_CODES = LINT_CODES | ANALYSIS_CODES | EXTERNAL_CODES | set(ALIASES)


@dataclasses.dataclass
class Finding:
    path: str
    line: int
    code: str
    message: str
    severity: str = ERROR
    # stable identity for the baseline file: function/attr/field name the
    # finding anchors to, so entries survive line drift
    anchor: str = ""
    # which analysis tier produced it: "ast" (source passes) or "jaxpr"
    # (traced-program passes); baseline keys are tier-agnostic
    tier: str = "ast"

    @property
    def key(self) -> str:
        return f"{self.path}::{self.code}::{self.anchor or self.line}"

    def as_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "code": self.code,
            "severity": self.severity,
            "message": self.message,
            "anchor": self.anchor,
            "tier": self.tier,
        }


# --- file walking ---------------------------------------------------------


def iter_py_files(roots):
    for root in roots:
        p = Path(root)
        if p.is_file() and p.suffix == ".py":
            yield p
            continue
        for f in sorted(p.rglob("*.py")):
            if not any(part in SKIP_DIRS for part in f.parts):
                yield f


# --- suppressions ---------------------------------------------------------

# codes are comma-separated tokens; a space inside a token ends the
# list, so trailing prose ("# noqa: BLE001 — classified below") cannot
# merge into a code and silently kill the suppression
_NOQA_RE = re.compile(
    r"#\s*noqa"
    r"(?::\s*(?P<codes>[A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*))?",
    re.I,
)


class Suppressions:
    """Typed per-line suppressions for one source file.

    Tokenized, not regex-over-lines: only real COMMENT tokens count, so
    a docstring *talking about* noqa is not a suppression (and not a
    bare-noqa finding)."""

    def __init__(self, source: str):
        self.codes_by_line: dict[int, set] = {}
        self.bare_lines: list[int] = []
        try:
            tokens = list(
                tokenize.generate_tokens(io.StringIO(source).readline)
            )
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return  # the lint gate owns syntax errors
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _NOQA_RE.search(tok.string)
            if not m:
                continue
            i = tok.start[0]
            raw = m.group("codes")
            if raw is None or not raw.strip():
                self.bare_lines.append(i)
                continue
            codes = {c.strip() for c in raw.split(",") if c.strip()}
            self.codes_by_line.setdefault(i, set()).update(codes)

    def suppresses(self, line: int, code: str) -> bool:
        codes = self.codes_by_line.get(line)
        if not codes:
            return False
        if code in codes:
            return True
        return any(ALIASES.get(c) == code for c in codes)

    def findings(self, path: str):
        """bare-noqa / unknown-suppression findings for this file."""
        out = []
        for line in self.bare_lines:
            out.append(Finding(
                path, line, "bare-noqa",
                "bare '# noqa' suppresses every current and future check "
                "on this line; name the code: '# noqa: <code>'",
                severity=ERROR,
                anchor=f"L{line}",
            ))
        for line, codes in sorted(self.codes_by_line.items()):
            for code in sorted(codes):
                if code not in KNOWN_CODES:
                    out.append(Finding(
                        path, line, "unknown-suppression",
                        f"'# noqa: {code}' names no known check "
                        "(see tools/analysis/common.py KNOWN_CODES); it "
                        "suppresses nothing",
                        severity=WARN,
                        anchor=code,
                    ))
        return out


def manifest_dict_literals(tree, target: str):
    """``(entries, assigned)`` for every literal dict bound to ``target``
    in a module AST — plain ``X = {...}`` and annotated ``X: dict =
    {...}`` alike. ``entries`` is ``[(key, key_lineno, value_node)]``
    for the string keys; ``assigned`` is True when any (possibly empty)
    dict literal was bound at all.

    The ONE parser of the HOT_PROGRAMS / EXEMPT_JIT_ROOTS surface: the
    manifest-contract pass (tools/analysis/passes/contracts.py) and the
    jaxpr tracer's line anchoring (tools/analysis/jaxpr/trace.py) must
    see the same dicts, or findings anchor to lines the contract never
    checked."""
    entries = []
    assigned = False
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            names = {
                t.id for t in node.targets if isinstance(t, ast.Name)
            }
            value = node.value
        elif isinstance(node, ast.AnnAssign):
            names = (
                {node.target.id}
                if isinstance(node.target, ast.Name)
                else set()
            )
            value = node.value
        else:
            continue
        if target not in names or not isinstance(value, ast.Dict):
            continue
        assigned = True
        for key, val in zip(value.keys, value.values):
            if isinstance(key, ast.Constant) and isinstance(
                key.value, str
            ):
                entries.append((key.value, key.lineno, val))
    return entries, assigned


def relpath(path, root=None) -> str:
    """Repo-relative string path for stable report/baseline keys."""
    p = Path(path)
    base = Path(root) if root else Path.cwd()
    try:
        return p.resolve().relative_to(base.resolve()).as_posix()
    except ValueError:
        return p.as_posix()
