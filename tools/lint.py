#!/usr/bin/env python
"""Zero-dependency lint + format gate.

The reference's ``make check`` chains gofmt + golangci-lint + go vet +
tests (reference Makefile:36-65, configure:1-115). This environment ships
no Python linter and forbids installing one, so this is the stdlib
equivalent: an AST/token pass enforcing the high-signal subset —

  lint (golangci-lint analog; the vet analog is ``tools/analysis``)
    unused-import      import never referenced (skipped in __init__.py
                       re-export shims; ``as _x`` aliases exempt)
    redefinition       same top-level def/class bound twice
    bare-except        ``except:`` swallowing SystemExit/KeyboardInterrupt
    none-compare       ``== None`` / ``!= None`` instead of ``is``
    empty-fstring      f-string with no placeholders
    mutable-default    list/dict/set literal as a parameter default

  format (gofmt analog)
    trailing-space     whitespace at end of line
    tab-indent         hard tabs in indentation
    no-final-newline   file does not end with exactly one newline
    crlf               carriage returns

Suppressions are TYPED and shared with ``tools/analysis``
(tools/analysis/common.py): ``# noqa: <code>`` suppresses exactly that
code on that line; a bare ``# noqa`` suppresses nothing (and is reported
as a ``bare-noqa`` finding by ``make analyze``, which walks the same
roots). Exit status 0 = clean, 1 = findings (printed as
path:line: code message).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.analysis.common import (  # noqa: E402
    DEFAULT_ROOTS,
    Suppressions,
    iter_py_files,
)


class _Lint(ast.NodeVisitor):
    def __init__(self, path: Path, source: str):
        self.path = path
        self.is_init = path.name == "__init__.py"
        self.noqa = Suppressions(source)
        self.findings = []
        self.imports = []  # (lineno, alias bound name)
        self.used = set()

    def add(self, lineno: int, code: str, msg: str) -> None:
        if not self.noqa.suppresses(lineno, code):
            self.findings.append((self.path, lineno, code, msg))

    # --- usage collection ---

    def visit_Name(self, node):
        self.used.add(node.id)
        self.generic_visit(node)

    # --- checks ---

    def _collect_import(self, node, name: str) -> None:
        # "import a.b" binds only "a"; usage via "a.b.c" is caught by the
        # Name visitor on the attribute chain's base
        bound = name.split(".")[0]
        if not bound.startswith("_"):
            self.imports.append((node.lineno, bound))

    def visit_Import(self, node):
        for alias in node.names:
            self._collect_import(node, alias.asname or alias.name)

    def visit_ImportFrom(self, node):
        if node.module == "__future__":  # compiler directive, not a binding
            return
        for alias in node.names:
            if alias.name == "*":
                continue
            self._collect_import(node, alias.asname or alias.name)

    def visit_ExceptHandler(self, node):
        if node.type is None:
            self.add(node.lineno, "bare-except",
                     "bare 'except:' also catches SystemExit; name the "
                     "exception (or use 'except Exception')")
        self.generic_visit(node)

    def visit_Compare(self, node):
        for op, comp in zip(node.ops, node.comparators):
            if (
                isinstance(op, (ast.Eq, ast.NotEq))
                and isinstance(comp, ast.Constant)
                and comp.value is None
            ):
                self.add(node.lineno, "none-compare",
                         "comparison to None should be 'is [not] None'")
        self.generic_visit(node)

    def visit_JoinedStr(self, node):
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self.add(node.lineno, "empty-fstring",
                     "f-string without placeholders")
        self.generic_visit(node)

    def visit_FormattedValue(self, node):
        # a format spec (":.0f") is itself a JoinedStr — recursing into it
        # would flag every formatted placeholder as an empty f-string
        self.visit(node.value)
        if node.format_spec is not None:
            for part in node.format_spec.values:
                if isinstance(part, ast.FormattedValue):
                    self.visit(part)

    def _check_defaults(self, node):
        for default in list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                self.add(default.lineno, "mutable-default",
                         "mutable literal as parameter default")

    def visit_FunctionDef(self, node):
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node):
        self._check_defaults(node)
        self.generic_visit(node)

    def finish(self, tree) -> None:
        # top-level redefinitions (second def/class under the same name)
        seen = {}
        for stmt in tree.body:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                if stmt.name in seen:
                    self.add(stmt.lineno, "redefinition",
                             f"'{stmt.name}' already defined at line "
                             f"{seen[stmt.name]}")
                seen[stmt.name] = stmt.lineno
        if not self.is_init:  # __init__.py imports are the re-export API
            for lineno, bound in self.imports:
                if bound not in self.used:
                    self.add(lineno, "unused-import",
                             f"'{bound}' imported but unused")
        # suppression hygiene (bare-noqa / unknown-suppression) is
        # reported by tools/analysis over the same roots — one finding
        # per defect, not one per gate


def check_format(path: Path, raw: bytes, text: str):
    findings = []
    if b"\r" in raw:
        findings.append((path, 1, "crlf", "carriage returns present"))
    for i, line in enumerate(text.splitlines(), 1):
        if line != line.rstrip():
            findings.append((path, i, "trailing-space",
                             "trailing whitespace"))
        stripped = line.lstrip(" ")
        if stripped.startswith("\t"):
            findings.append((path, i, "tab-indent", "tab in indentation"))
    if raw and not raw.endswith(b"\n"):
        findings.append((path, text.count("\n") + 1, "no-final-newline",
                         "file does not end with a newline"))
    return findings


def run(roots) -> int:
    findings = []
    for path in iter_py_files(roots):
        raw = path.read_bytes()
        source = raw.decode("utf-8", errors="replace")
        findings.extend(check_format(path, raw, source))
        try:
            tree = ast.parse(source, filename=str(path))
        except SyntaxError as err:
            findings.append((path, err.lineno or 1, "syntax-error", err.msg))
            continue
        lint = _Lint(path, source)
        lint.visit(tree)
        lint.finish(tree)
        findings.extend(lint.findings)

    for path, lineno, code, msg in sorted(
        findings, key=lambda f: (str(f[0]), f[1])
    ):
        print(f"{path}:{lineno}: {code} {msg}")
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    # shared with tools/analysis: both gates walk the same roots
    sys.exit(run(sys.argv[1:] or DEFAULT_ROOTS))
