# Two-stage image in the spirit of the reference's Dockerfile
# (golang:1.16 builder -> alpine runtime; here: wheel build -> slim
# runtime with the TPU-enabled jax stack).
FROM python:3.12-slim AS build
RUN apt-get update && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*
WORKDIR /src
COPY Makefile ./
COPY k8s_spot_rescheduler_tpu ./k8s_spot_rescheduler_tpu
COPY bench.py README.md ./
# native ingest engine (apiserver JSON -> columnar batches)
RUN make native

FROM python:3.12-slim
# jax[tpu] pulls libtpu for Cloud TPU VMs; CPU-only controllers can
# install plain jax and run with --solver numpy.
RUN pip install --no-cache-dir "jax[tpu]" numpy scipy prometheus_client pyyaml \
      -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
COPY --from=build /src /app
WORKDIR /app
ENV PYTHONPATH=/app
ENTRYPOINT ["python", "-m", "k8s_spot_rescheduler_tpu"]
