"""Chain-depth-demand analyzer (bench/chain_depth.py, VERDICT r4 #4).

The published chain3 boundary (docs/RESULTS.md) rests on the claim that
organic problems never demand relocation chains deeper than the shipped
depth-2 search. The analyzer turns that claim into a measurement; these
tests pin the instrument itself: each classification bucket is proven
on a fixture KNOWN to demand exactly that mechanism, and the chain3
config — which demands depth 3 by construction — must register
``deeper`` (the positive control), while the organic adversarial
configs must not.
"""

from __future__ import annotations

import pytest

from k8s_spot_rescheduler_tpu.bench.chain_depth import (
    analyze_quality_runs,
    classify_packed,
)
from k8s_spot_rescheduler_tpu.io.synthetic import AffinitySpec

# tests.test_repair's import chain needs hypothesis; collection must
# stay clean on images without it (skip here, run where it exists)
pytest.importorskip("hypothesis")
from tests.test_repair import (  # noqa: E402
    _rotation_coverage_case,
    _swap_case,
)


def test_classify_depth1_fixture():
    # _swap_case: greedy fails, one direct relocation proves it
    counts = classify_packed(_swap_case())
    assert dict(counts) == {"depth1": 1}


def test_classify_depth2_fixture():
    # _rotation_coverage_case: only a chained relocation works
    counts = classify_packed(_rotation_coverage_case())
    assert counts.get("depth2", 0) >= 1
    assert counts.get("deeper", 0) == 0


def test_chain3_registers_deeper_demand():
    """The positive control: chain3 pools need depth-3 chains, so the
    analyzer MUST classify their lanes as 'deeper' (ILP-feasible,
    beyond the shipped search). If this stops firing, the instrument is
    broken and the organic zero below means nothing."""
    spec = AffinitySpec("chain-depth-ctl", n_groups=6,
                        aswap_frac=0.0, chain3_frac=1 / 3)
    out = analyze_quality_runs(seeds=[0], configs={"chain3": spec})
    assert out["chain3"].get("deeper", 0) > 0
    assert out["chain3"].get("infeasible", 0) == 0


def test_organic_adversarial_configs_demand_at_most_depth2():
    """The evidence behind the published boundary: across the
    adversarial organic configs (interlock = the deepest by design,
    spread = round 5's), every ILP-drainable lane is proven by the
    shipped depth-≤2 search — zero 'deeper' demand."""
    from k8s_spot_rescheduler_tpu.io.synthetic import QUALITY_CONFIGS

    subset = {
        "interlock": QUALITY_CONFIGS["interlock"],
        "spread": QUALITY_CONFIGS["spread"],
    }
    out = analyze_quality_runs(seeds=[0], configs=subset)
    for name, counts in out.items():
        assert counts.get("deeper", 0) == 0, (name, counts)
        assert counts.get("ilp-failed", 0) == 0, (name, counts)
    # and the instrument saw real repair demand, not a trivial cluster
    assert out["interlock"].get("depth2", 0) > 0
    assert out["spread"].get("depth1", 0) > 0
