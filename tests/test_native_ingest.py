"""Differential tests: the native ingest engine (native/ingest.cc) must
decode apiserver JSON to exactly what the pure-Python reference decoders
produce (io/kube.py ``decode_pod``/``decode_node``), across the k8s
quantity grammar, escapes, and missing/null fields.

The library builds on demand (``make native``). The suite skips ONLY
when no C++ toolchain exists (the framework falls back to Python
decode); with a toolchain present, a build failure or an ABI-handshake
refusal is a shipped bug and the suite FAILS loudly.
"""

from __future__ import annotations

import json
import shutil
import subprocess

import pytest

ROOT = __file__.rsplit("/tests/", 1)[0]


@pytest.fixture(scope="session", autouse=True)
def built_lib():
    have_toolchain = shutil.which("g++") is not None
    proc = subprocess.run(
        ["make", "native"], cwd=ROOT, capture_output=True, text=True
    )
    if proc.returncode != 0:
        if not have_toolchain:
            pytest.skip(f"no C++ toolchain: {proc.stderr[-300:]}")
        pytest.fail(
            f"g++ exists but `make native` failed:\n{proc.stderr[-2000:]}"
        )
    from k8s_spot_rescheduler_tpu.io import native_ingest

    native_ingest._lib.cache_clear()
    if not native_ingest.available():
        # A freshly built .so the bindings refuse means the C++/Python
        # schema constants have split-brained (the round-2 regression);
        # skipping here hid that for a full round — fail instead.
        pytest.fail(
            "freshly built native library failed the ABI handshake — "
            "native/ingest.cc and io/native_ingest.py schema constants "
            "have diverged"
        )


def test_available_when_so_exists():
    """ABI sanity pinned explicitly (not just via the fixture): the
    built library must load and self-describe the layout the bindings
    expect."""
    import os

    from k8s_spot_rescheduler_tpu.io import native_ingest

    assert os.path.exists(native_ingest._LIB_PATH)
    assert native_ingest.available()


def _pod_obj(**over):
    obj = {
        "metadata": {
            "name": "p", "namespace": "ns1", "uid": "u-1",
            "labels": {"app": "web", "tier": "fe"},
            "ownerReferences": [
                {"kind": "ReplicaSet", "name": "rs", "controller": True}
            ],
        },
        "spec": {
            "nodeName": "n1",
            "priority": 7,
            "tolerations": [
                {"key": "a", "value": "b", "operator": "Equal",
                 "effect": "NoSchedule"},
                {"operator": "Exists"},
            ],
            "containers": [
                {"resources": {"requests": {
                    "cpu": "250m", "memory": "512Mi",
                    "ephemeral-storage": "1Gi"}}},
                {"resources": {"requests": {"cpu": "0.3", "memory": "1e6"}}},
            ],
        },
        "status": {"phase": "Running"},
    }
    for k, v in over.items():
        obj[k] = v
    return obj


def _assert_pod_parity(objs):
    from k8s_spot_rescheduler_tpu.io.kube import decode_pod
    from k8s_spot_rescheduler_tpu.io.native_ingest import parse_pod_list

    body = json.dumps(
        {"metadata": {"resourceVersion": "42"}, "items": objs}
    ).encode()
    batch = parse_pod_list(body)
    assert batch is not None and batch.count == len(objs)
    assert batch.resource_version == "42"
    for i, obj in enumerate(objs):
        want = decode_pod(obj)
        got = batch.view(i)
        assert got.name == want.name
        assert got.namespace == want.namespace
        assert got.node_name == want.node_name
        assert got.uid == want.uid
        assert got.requests == {
            k: v for k, v in want.requests.items() if v
        }, f"pod {i} requests"
        assert got.priority == want.priority
        assert got.labels == want.labels
        assert got.phase in (want.phase, "Running", "Succeeded")
        assert got.is_mirror() == want.is_mirror()
        assert got.is_daemonset() == want.is_daemonset()
        assert (got.controller_ref() is None) == (want.controller_ref() is None)
        assert tuple(got.tolerations) == tuple(want.tolerations)
        # the full scheduling-constraint surface must agree exactly —
        # any divergence here is a different drain decision
        assert got.node_selector == want.node_selector, f"pod {i} selector"
        assert got.anti_affinity_match == want.anti_affinity_match, (
            f"pod {i} anti-affinity"
        )
        assert got.pod_affinity_match == want.pod_affinity_match, (
            f"pod {i} pod-affinity"
        )
        assert got.pod_affinity_zone_match == want.pod_affinity_zone_match, (
            f"pod {i} zone-pod-affinity"
        )
        assert got.anti_affinity_zone_match == want.anti_affinity_zone_match, (
            f"pod {i} zone-anti-affinity"
        )
        assert tuple(got.pvc_names) == tuple(want.pvc_names), f"pod {i} pvcs"
        assert got.pvc_resolvable == want.pvc_resolvable, (
            f"pod {i} pvc_resolvable"
        )
        assert got.node_affinity == want.node_affinity, f"pod {i} node-aff"
        assert got.spread_constraints == want.spread_constraints, (
            f"pod {i} spread"
        )
        assert got.unmodeled_constraints == want.unmodeled_constraints, (
            f"pod {i} unmodeled"
        )
        # evictability-relevant phase semantics must agree exactly
        assert (got.phase in ("Succeeded", "Failed")) == (
            want.phase in ("Succeeded", "Failed")
        )
        assert (got.phase == "Pending") == (want.phase == "Pending")


def test_basic_pod_parity():
    _assert_pod_parity([_pod_obj()])


def test_quantity_grammar():
    cases = [
        "100m", "0.5", "1", "2", "1536Mi", "2Gi", "1e3", "1.5e2", "500n",
        "250u", "3k", "1M", "0.000001", "7Ti", "0", "123456789",
    ]
    objs = []
    for i, q in enumerate(cases):
        objs.append(_pod_obj(spec={
            "nodeName": "n1",
            "containers": [{"resources": {"requests": {
                "cpu": q, "memory": q, "ephemeral-storage": q}}}],
        }))
    _assert_pod_parity(objs)


def test_numeric_json_quantities():
    # requests can be bare JSON numbers, not strings
    objs = [_pod_obj(spec={
        "nodeName": "n1",
        "containers": [{"resources": {"requests": {"cpu": 2, "memory": 1048576}}}],
    })]
    _assert_pod_parity(objs)


def test_missing_and_null_fields():
    objs = [
        {"metadata": {"name": "bare"}, "spec": {}, "status": {}},
        {"metadata": {"name": "nulls", "labels": None,
                      "ownerReferences": None},
         "spec": {"tolerations": None, "containers": None},
         "status": {"phase": "Pending"}},
        _pod_obj(status={"phase": "Succeeded"}),
        _pod_obj(status={"phase": "Failed"}),
        _pod_obj(metadata={
            "name": "mirror", "namespace": "kube-system",
            "annotations": {"kubernetes.io/config.mirror": "abc"},
        }),
        _pod_obj(metadata={
            "name": "ds", "namespace": "kube-system",
            "ownerReferences": [
                {"kind": "DaemonSet", "name": "d", "controller": True}
            ],
        }),
        _pod_obj(metadata={
            "name": "noctl",
            "ownerReferences": [
                {"kind": "ReplicaSet", "name": "rs", "controller": False}
            ],
        }),
    ]
    _assert_pod_parity(objs)


def _affinity_pod(name, affinity):
    return _pod_obj(metadata={"name": name, "namespace": "ns1"},
                    spec={"nodeName": "n1", "affinity": affinity,
                          "containers": []})


def _naff(terms):
    return {"nodeAffinity": {
        "requiredDuringSchedulingIgnoredDuringExecution": {
            "nodeSelectorTerms": terms}}}


def test_pvc_shapes():
    def vol_pod(name, volumes):
        return _pod_obj(metadata={"name": name, "namespace": "ns1"},
                        spec={"nodeName": "n1", "containers": [],
                              "volumes": volumes})

    objs = [
        # clean claim list -> resolvable
        vol_pod("v1", [{"persistentVolumeClaim": {"claimName": "data"}},
                       {"configMap": {"name": "cm"}},
                       {"persistentVolumeClaim": {"claimName": "logs"}}]),
        # missing claimName voids the whole list
        vol_pod("v2", [{"persistentVolumeClaim": {"claimName": "ok"}},
                       {"persistentVolumeClaim": {}}]),
        # null claim value still counts as a PVC volume (key presence)
        vol_pod("v3", [{"persistentVolumeClaim": None}]),
        # empty name voids
        vol_pod("v4", [{"persistentVolumeClaim": {"claimName": ""}}]),
        # separator byte in a name voids (blob framing safety)
        vol_pod("v5", [{"persistentVolumeClaim":
                        {"claimName": "bad\u001ename"}}]),
        # no volumes at all
        vol_pod("v6", None),
        vol_pod("v7", []),
    ]
    _assert_pod_parity(objs)


def test_any_pvc_resolvable_matches_views():
    """The vectorized polling-path hint must equal the per-view scan it
    replaces (kube._all_pods skips the 50k-view Python walk on it)."""
    from k8s_spot_rescheduler_tpu.io.native_ingest import parse_pod_list

    def vol_pod(name, volumes):
        return _pod_obj(metadata={"name": name, "namespace": "ns1"},
                        spec={"nodeName": "n1", "containers": [],
                              "volumes": volumes})

    cases = [
        # no PVC anywhere -> False
        [vol_pod("a", None), vol_pod("b", [])],
        # resolvable claim -> True
        [vol_pod("a", None),
         vol_pod("b", [{"persistentVolumeClaim": {"claimName": "d"}}])],
        # PVC present but voided name list -> False (F_PVC set, empty list)
        [vol_pod("a", [{"persistentVolumeClaim": {}}])],
        # PVC + unmodeled affinity (F_REQAFF) -> False
        [_pod_obj(metadata={"name": "a", "namespace": "ns1"},
                  spec={"nodeName": "n1", "containers": [],
                        "volumes": [{"persistentVolumeClaim":
                                     {"claimName": "d"}}],
                        "affinity": {"podAffinity": {
                            "requiredDuringSchedulingIgnoredDuringExecution":
                                [{"topologyKey": "weird/key",
                                  "labelSelector":
                                      {"matchLabels": {"x": "y"}}}]}}})],
    ]
    for objs in cases:
        body = json.dumps(
            {"metadata": {"resourceVersion": "1"}, "items": objs}
        ).encode()
        batch = parse_pod_list(body)
        assert batch is not None
        want = any(v.pvc_resolvable for v in batch.views())
        assert batch.any_pvc_resolvable() == want, objs


def test_topology_spread_shapes():
    def spread_pod(name, spread):
        return _pod_obj(metadata={"name": name, "namespace": "ns1"},
                        spec={"nodeName": "n1", "containers": [],
                              "topologySpreadConstraints": spread})

    hard = {"maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "x"}}}
    soft = dict(hard, whenUnsatisfiable="ScheduleAnyway")
    objs = [
        spread_pod("hard", [hard]),  # canonical: modeled on BOTH paths
        spread_pod("soft", [soft]),
        spread_pod("default", [{k: v for k, v in hard.items()
                                if k != "whenUnsatisfiable"}]),
        spread_pod("mixed", [soft, hard]),
        spread_pod("pair", [hard, dict(hard,
                                       topologyKey="kubernetes.io/hostname")]),
        spread_pod("empty", []),
        spread_pod("null", None),
        spread_pod("malformed", "garbage"),
        spread_pod("badentry", [None]),
        # beyond-canonical hard shapes: unmodeled on both paths
        spread_pod("modifier", [dict(hard, minDomains=2)]),
        spread_pod("labelkeys", [dict(hard, matchLabelKeys=["rev"])]),
        spread_pod("floatskew", [dict(hard, maxSkew=1.0)]),
        spread_pod("zeroskew", [dict(hard, maxSkew=0)]),
        spread_pod("boolskew", [dict(hard, maxSkew=True)]),
        spread_pod("othertopo", [dict(hard, topologyKey="rack")]),
        spread_pod("noselector", [{k: v for k, v in hard.items()
                                   if k != "labelSelector"}]),
        spread_pod("exprs", [dict(hard, labelSelector={
            "matchLabels": {"app": "x"},
            "matchExpressions": [{"key": "a", "operator": "Exists"}]})]),
        spread_pod("multikv", [dict(hard, labelSelector={
            "matchLabels": {"app": "x", "tier": "db"}})]),
        # a soft entry carrying a modifier is still just soft (dropped)
        spread_pod("softmod", [dict(soft, minDomains=2)]),
    ]
    _assert_pod_parity(objs)


def test_zone_anti_affinity_shapes():
    objs = [
        # modeled zone-topology anti-affinity
        _affinity_pod("za", {"podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {"topologyKey": "topology.kubernetes.io/zone",
                 "labelSelector": {"matchLabels": {"app": "db"}}}]}}),
        # legacy zone key -> unmodeled
        _affinity_pod("zleg", {"podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {"topologyKey": "failure-domain.beta.kubernetes.io/zone",
                 "labelSelector": {"matchLabels": {"app": "db"}}}]}}),
        # zone topology on POSITIVE affinity -> unmodeled (hostname only)
        _affinity_pod("zpa", {"podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {"topologyKey": "topology.kubernetes.io/zone",
                 "labelSelector": {"matchLabels": {"app": "db"}}}]}}),
        # hostname anti + zone anti cannot coexist (two terms) -> unmodeled
        _affinity_pod("two", {"podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {"topologyKey": "kubernetes.io/hostname",
                 "labelSelector": {"matchLabels": {"a": "1"}}},
                {"topologyKey": "topology.kubernetes.io/zone",
                 "labelSelector": {"matchLabels": {"b": "2"}}}]}}),
    ]
    _assert_pod_parity(objs)


def test_pod_affinity_shapes():
    objs = [
        # the modeled positive-affinity shape
        _affinity_pod("pa", {"podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {"topologyKey": "kubernetes.io/hostname",
                 "labelSelector": {"matchLabels": {"app": "db"}}}]}}),
        # zone topology -> modeled (round 4: ZonePodAffinityBit)
        _affinity_pod("paz", {"podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {"topologyKey": "topology.kubernetes.io/zone",
                 "labelSelector": {"matchLabels": {"app": "db"}}}]}}),
        # single-value In expressions fold (round 4)
        _affinity_pod("pae", {"podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {"topologyKey": "kubernetes.io/hostname",
                 "labelSelector": {"matchExpressions": [
                     {"key": "app", "operator": "In",
                      "values": ["db"]}]}}]}}),
        # zone topology + folded expressions together
        _affinity_pod("pazx", {"podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {"topologyKey": "topology.kubernetes.io/zone",
                 "labelSelector": {
                     "matchLabels": {"tier": "be"},
                     "matchExpressions": [
                         {"key": "app", "operator": "In",
                          "values": ["db"]}]}}]}}),
        # other topology key -> unmodeled
        _affinity_pod("par", {"podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {"topologyKey": "example.com/rack",
                 "labelSelector": {"matchLabels": {"app": "db"}}}]}}),
        # preferred only -> unconstrained
        _affinity_pod("pap", {"podAffinity": {
            "preferredDuringSchedulingIgnoredDuringExecution": [
                {"weight": 1}]}}),
        # positive AND anti affinity together, both modeled
        _affinity_pod("both", {
            "podAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {"topologyKey": "kubernetes.io/hostname",
                     "labelSelector": {"matchLabels": {"app": "db"}}}]},
            "podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {"topologyKey": "kubernetes.io/hostname",
                     "labelSelector": {"matchLabels": {"app": "web"}}}]},
        }),
    ]
    _assert_pod_parity(objs)


def test_node_affinity_modeled_shapes():
    objs = [
        # single In expression
        _affinity_pod("in1", _naff([{"matchExpressions": [
            {"key": "zone", "operator": "In", "values": ["a", "b"]}]}])),
        # values unsorted + duplicated -> canonicalization must agree
        _affinity_pod("canon", _naff([{"matchExpressions": [
            {"key": "zone", "operator": "In",
             "values": ["b", "a", "b"]}]}])),
        # multiple terms (OR), multiple exprs per term (AND), every op
        _affinity_pod("ops", _naff([
            {"matchExpressions": [
                {"key": "a", "operator": "Exists"},
                {"key": "b", "operator": "DoesNotExist"},
                {"key": "n", "operator": "Gt", "values": ["5"]}]},
            {"matchExpressions": [
                {"key": "m", "operator": "Lt", "values": ["9"]},
                {"key": "z", "operator": "NotIn", "values": ["x"]}]},
        ])),
        # Exists with spurious values (both decoders drop them)
        _affinity_pod("exv", _naff([{"matchExpressions": [
            {"key": "a", "operator": "Exists", "values": ["junk"]}]}])),
        # empty term dropped, modeled term kept
        _affinity_pod("dropped", _naff([
            {}, {"matchExpressions": [
                {"key": "k", "operator": "In", "values": ["v"]}]}])),
        # matchFields on metadata.name: modeled as FieldIn/FieldNotIn
        _affinity_pod("mf", _naff([{"matchFields": [
            {"key": "metadata.name", "operator": "In",
             "values": ["n2", "n1", "n2"]}]}])),
        # mixed matchExpressions + matchFields in one term (AND)
        _affinity_pod("mixed", _naff([{
            "matchExpressions": [
                {"key": "zone", "operator": "In", "values": ["a"]}],
            "matchFields": [
                {"key": "metadata.name", "operator": "NotIn",
                 "values": ["n9"]}]}])),
        # preferred-only affinity: no requirement at all
        _affinity_pod("pref", {"nodeAffinity": {
            "preferredDuringSchedulingIgnoredDuringExecution": [
                {"weight": 1, "preference": {"matchExpressions": [
                    {"key": "k", "operator": "In", "values": ["v"]}]}}]}}),
        # no affinity at all
        _affinity_pod("none", None),
    ]
    _assert_pod_parity(objs)


def test_node_affinity_unmodeled_shapes():
    objs = [
        # matchFields on any other key is not a field k8s defines
        _affinity_pod("mfuid", _naff([{"matchFields": [
            {"key": "metadata.uid", "operator": "In", "values": ["x"]}]}])),
        # matchFields with a non-membership operator
        _affinity_pod("mfex", _naff([{"matchFields": [
            {"key": "metadata.name", "operator": "Exists"}]}])),
        # matchFields with no values
        _affinity_pod("mf0", _naff([{"matchFields": [
            {"key": "metadata.name", "operator": "In", "values": []}]}])),
        # Gt needs exactly one value
        _affinity_pod("gt2", _naff([{"matchExpressions": [
            {"key": "n", "operator": "Gt", "values": ["1", "2"]}]}])),
        # In needs at least one value
        _affinity_pod("in0", _naff([{"matchExpressions": [
            {"key": "k", "operator": "In", "values": []}]}])),
        # unknown operator
        _affinity_pod("op?", _naff([{"matchExpressions": [
            {"key": "k", "operator": "Fuzzy", "values": ["v"]}]}])),
        # empty nodeSelectorTerms list
        _affinity_pod("t0", _naff([])),
        # required block is a list, not an object
        _affinity_pod("reqlist", {"nodeAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {"matchExpressions": []}]}}),
        # every term empty -> matches nothing -> unplaceable
        _affinity_pod("allempty", _naff([{}, {"matchExpressions": []}])),
        # required podAffinity is unmodeled even with modeled nodeAffinity
        _affinity_pod("podaff", {
            **_naff([{"matchExpressions": [
                {"key": "k", "operator": "In", "values": ["v"]}]}]),
            "podAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {"topologyKey": "kubernetes.io/hostname"}]}}),
        # PVC volume alongside modeled affinity
        _pod_obj(metadata={"name": "pvc", "namespace": "ns1"},
                 spec={"nodeName": "n1", "containers": [],
                       "affinity": _naff([{"matchExpressions": [
                           {"key": "k", "operator": "In",
                            "values": ["v"]}]}]),
                       "volumes": [
                           {"persistentVolumeClaim": {"claimName": "c"}}]}),
        # separator bytes in a value (values are NOT validated as label
        # values by the apiserver): must be unmodeled, never corrupt the
        # native blob framing
        _affinity_pod("sep1", _naff([{"matchExpressions": [
            {"key": "k", "operator": "In", "values": ["a\x1cb"]}]}])),
        _affinity_pod("sep2", _naff([{"matchExpressions": [
            {"key": "k", "operator": "NotIn", "values": ["x\x1fy"]}]}])),
        _affinity_pod("sep3", _naff([{"matchExpressions": [
            {"key": "k\x1e", "operator": "Exists"}]}])),
        _affinity_pod("sep4", _naff([{"matchExpressions": [
            {"key": "k", "operator": "In", "values": ["t\x1du"]}]}])),
    ]
    _assert_pod_parity(objs)


def test_node_affinity_interning_shares_canonical_tuples():
    """Two pods whose requirements differ only in value order/dups must
    intern to the same canonical tuple, so they share one pseudo-taint
    bit downstream."""
    from k8s_spot_rescheduler_tpu.io.native_ingest import parse_pod_list

    objs = [
        _affinity_pod("p1", _naff([{"matchExpressions": [
            {"key": "z", "operator": "In", "values": ["a", "b"]}]}])),
        _affinity_pod("p2", _naff([{"matchExpressions": [
            {"key": "z", "operator": "In", "values": ["b", "a", "a"]}]}])),
    ]
    batch = parse_pod_list(json.dumps({"items": objs}).encode())
    v1, v2 = batch.views()
    assert v1.node_affinity == v2.node_affinity != ()
    assert not v1.unmodeled_constraints


def test_string_escapes_and_unicode():
    objs = [_pod_obj(metadata={
        "name": "esc", "namespace": "nsé",
        "labels": {"quote\\\"d": "tab\there", "emoji": "😀-ok"},
    })]
    # json.dumps re-escapes; both decoders see the same wire bytes
    _assert_pod_parity(objs)


def test_resource_support_gating():
    from k8s_spot_rescheduler_tpu.io import native_ingest

    assert native_ingest.supports(("cpu", "memory"))
    assert native_ingest.supports(
        ("cpu", "memory", "ephemeral-storage", "pods")
    )
    assert not native_ingest.supports(("cpu", "nvidia.com/gpu"))


def test_node_parity():
    from k8s_spot_rescheduler_tpu.io.kube import decode_node
    from k8s_spot_rescheduler_tpu.io.native_ingest import parse_node_list

    objs = [
        {
            "metadata": {"name": "n1", "uid": "u-n1",
                         "labels": {"kubernetes.io/role": "spot-worker"}},
            "spec": {"taints": [
                {"key": "k", "value": "v", "effect": "NoExecute"},
                {"key": "pref", "effect": "PreferNoSchedule"},
                {"key": "noval"},
            ], "unschedulable": True},
            "status": {
                "allocatable": {"cpu": "3900m", "memory": "15Gi",
                                "pods": "110", "ephemeral-storage": "93Gi"},
                "conditions": [{"type": "Ready", "status": "True"}],
            },
        },
        {
            "metadata": {"name": "n2"},
            "spec": {},
            "status": {"conditions": [
                {"type": "Ready", "status": "False"},
                {"type": "MemoryPressure", "status": "True"},
            ]},
        },
    ]
    body = json.dumps({"metadata": {"resourceVersion": "7"}, "items": objs}).encode()
    batch = parse_node_list(body)
    assert batch is not None and batch.count == 2
    for i, obj in enumerate(objs):
        want = decode_node(obj)
        got = batch.views()[i]
        assert got.name == want.name
        assert got.labels == want.labels
        assert got.ready == want.ready
        assert got.unschedulable == want.unschedulable
        assert list(got.taints) == list(want.taints)
        for key in ("cpu", "memory", "pods", "ephemeral-storage"):
            assert got.allocatable.get(key, 0) == want.allocatable.get(key, 0), key


def test_bulk_load_matches_per_pod_path():
    """ColumnarStore.bulk_add_pods (vectorized seed) must produce the
    same packed tensors — and the same orphan behavior — as per-pod
    add_pod over the batch's views."""
    import numpy as np

    from k8s_spot_rescheduler_tpu.io.native_ingest import parse_pod_list
    from k8s_spot_rescheduler_tpu.models.cluster import NodeSpec
    from k8s_spot_rescheduler_tpu.models.columnar import ColumnarStore

    pod_objs = [
        _pod_obj(
            metadata={
                "name": f"p{i}", "namespace": f"ns-{i % 3}", "uid": f"u{i}",
                "labels": {"app": f"a{i % 4}"},
                "ownerReferences": (
                    [] if i == 5 else
                    [{"kind": "DaemonSet" if i == 4 else "ReplicaSet",
                      "name": "o", "controller": True}]
                ),
            },
            spec={
                # i==7: node the store doesn't know -> orphan
                "nodeName": "mystery" if i == 7 else f"n{i % 4}",
                "priority": i - 3,
                "containers": [{"resources": {"requests": {
                    "cpu": f"{100 + 13 * i}m", "memory": f"{10 + i}Mi"}}}],
                "tolerations": (
                    [{"key": "t", "operator": "Exists"}] if i % 2 else []
                ),
                # i==3: modeled node-affinity; i==9: unmodeled matchFields
                "affinity": (
                    _naff([{"matchExpressions": [
                        {"key": "zone", "operator": "In",
                         "values": ["b", "a"]}]}]) if i == 3 else
                    _naff([{"matchFields": [
                        {"key": "metadata.name", "operator": "In",
                         "values": ["n1"]}]}]) if i == 9 else None
                ),
                # i==11: canonical hard spread (modeled, SpreadBit path);
                # i==13: beyond-canonical (unmodeled)
                "topologySpreadConstraints": (
                    [{"maxSkew": 1,
                      "topologyKey": "topology.kubernetes.io/zone",
                      "whenUnsatisfiable": "DoNotSchedule",
                      "labelSelector": {"matchLabels": {"app": "a3"}}}]
                    if i == 11 else
                    [{"maxSkew": 1, "topologyKey": "rack",
                      "labelSelector": {"matchLabels": {"app": "a1"}}}]
                    if i == 13 else None
                ),
            },
            status={"phase": "Succeeded" if i == 6 else "Running"},
        )
        for i in range(16)
    ]
    batch = parse_pod_list(json.dumps({"items": pod_objs}).encode())

    def nodes():
        return [
            NodeSpec(
                name=f"n{j}",
                labels={"kubernetes.io/role":
                        "worker" if j % 2 else "spot-worker",
                        "topology.kubernetes.io/zone": f"z{j % 2}"},
                allocatable={"cpu": 4000, "memory": 2**34, "pods": 50},
            )
            for j in range(4)
        ]

    bulk = ColumnarStore(("cpu", "memory"),
                         on_demand_label="kubernetes.io/role=worker",
                         spot_label="kubernetes.io/role=spot-worker")
    perpod = ColumnarStore(("cpu", "memory"),
                           on_demand_label="kubernetes.io/role=worker",
                           spot_label="kubernetes.io/role=spot-worker")
    for n in nodes():
        bulk.add_node(n)
        perpod.add_node(n)
    assert bulk.bulk_add_pods(batch)
    for v in batch.views():
        perpod.add_pod(v)
    a, _ = bulk.pack([], priority_threshold=2)
    b, _ = perpod.pack([], priority_threshold=2)
    for field in a._fields:
        np.testing.assert_array_equal(
            getattr(a, field), getattr(b, field), err_msg=field
        )
    # orphan parity: the mystery-node pod is parked in both
    assert bulk.n_pods == perpod.n_pods == 15
    bulk.add_node(NodeSpec(name="mystery",
                           labels={"kubernetes.io/role": "spot-worker"},
                           allocatable={"cpu": 4000, "memory": 2**34}))
    assert bulk.n_pods == 16
    # a second bulk load on a non-empty store must refuse
    assert not bulk.bulk_add_pods(batch)


def test_views_feed_columnar_store_identically():
    """End to end: a columnar store fed PodViews packs the same tensors
    as one fed the equivalent PodSpecs."""
    import numpy as np

    from k8s_spot_rescheduler_tpu.io.kube import decode_node, decode_pod
    from k8s_spot_rescheduler_tpu.io.native_ingest import (
        parse_node_list,
        parse_pod_list,
    )
    from k8s_spot_rescheduler_tpu.models.columnar import ColumnarStore

    node_objs = [
        {
            "metadata": {"name": f"{kind}-{i}", "uid": f"u-{kind}-{i}",
                         "labels": {"kubernetes.io/role": kind}},
            "spec": {},
            "status": {
                "allocatable": {"cpu": "4", "memory": "16Gi", "pods": "110"},
                "conditions": [{"type": "Ready", "status": "True"}],
            },
        }
        for kind in ("worker", "spot-worker")
        for i in range(3)
    ]
    pod_objs = [
        _pod_obj(metadata={
            "name": f"p{i}", "namespace": "default", "uid": f"u-p{i}",
            "labels": {"app": f"a{i % 3}"},
            "ownerReferences": [
                {"kind": "ReplicaSet", "name": "rs", "controller": True}
            ],
        }, spec={
            "nodeName": f"{'worker' if i % 2 else 'spot-worker'}-{i % 3}",
            "containers": [{"resources": {"requests": {
                "cpu": f"{100 + i * 37}m", "memory": f"{32 + i}Mi"}}}],
            "tolerations": [],
        })
        for i in range(12)
    ]

    def build(nodes, pods):
        store = ColumnarStore(
            ("cpu", "memory"),
            on_demand_label="kubernetes.io/role=worker",
            spot_label="kubernetes.io/role=spot-worker",
        )
        for n in nodes:
            store.add_node(n)
        for p in pods:
            store.add_pod(p)
        return store.pack([])

    nb = parse_node_list(json.dumps({"items": node_objs}).encode())
    pb = parse_pod_list(json.dumps({"items": pod_objs}).encode())
    native_packed, _ = build(nb.views(), pb.views())
    py_packed, _ = build(
        [decode_node(o) for o in node_objs], [decode_pod(o) for o in pod_objs]
    )
    for field in native_packed._fields:
        np.testing.assert_array_equal(
            getattr(native_packed, field), getattr(py_packed, field),
            err_msg=field,
        )


@pytest.mark.parametrize("seed", range(8))
def test_widened_affinity_differential_fuzz(seed, built_lib):
    """Randomized differential lockstep over the ROUND-5 widened
    surface: random selector operators (valid and invalid), value
    lists (empty/multi/sep-bytes), namespaces lists (own, cross, "*",
    malformed), namespaceSelector variants, topology keys, term counts,
    and spread modifier values — Python decode and the native engine
    must agree field-for-field on every generated pod."""
    import random

    from k8s_spot_rescheduler_tpu.io import native_ingest
    from k8s_spot_rescheduler_tpu.io.kube import decode_pod

    rng = random.Random(3000 + seed)
    ops = ["In", "NotIn", "Exists", "DoesNotExist", "Gt", "Weird", None]
    topos = ["kubernetes.io/hostname", "topology.kubernetes.io/zone",
             "example.com/rack", "", "bad\x1dkey"]

    def rand_values():
        roll = rng.random()
        if roll < 0.15:
            return []
        if roll < 0.25:
            return ["bad\x1dvalue"]
        return rng.sample(["a", "b", "c", "d", ""], rng.randint(1, 3))

    def rand_expr():
        e = {}
        op = rng.choice(ops)
        if op is not None:
            e["operator"] = op
        if rng.random() < 0.9:
            e["key"] = rng.choice(["app", "tier", "k\x1ey", "zone"])
        if rng.random() < 0.8:
            e["values"] = rand_values()
        return e

    def rand_selector():
        sel = {}
        if rng.random() < 0.6:
            sel["matchLabels"] = {
                rng.choice(["app", "tier"]): rng.choice(["db", "web", "x"])
                for _ in range(rng.randint(0, 2))
            }
        if rng.random() < 0.6:
            sel["matchExpressions"] = [
                rand_expr() for _ in range(rng.randint(0, 3))
            ]
        return sel

    def rand_term():
        term = {"topologyKey": rng.choice(topos),
                "labelSelector": rand_selector()}
        roll = rng.random()
        if roll < 0.2:
            term["namespaces"] = rng.sample(
                ["default", "other", "payments", "*", ""],
                rng.randint(1, 2),
            )
        if roll > 0.85:
            term["namespaceSelector"] = rng.choice(
                [{}, None, {"matchLabels": {"team": "x"}}]
            )
        return term

    def rand_spread():
        c = {"topologyKey": rng.choice(topos),
             "maxSkew": rng.choice([1, 2, 0, "1"]),
             "labelSelector": rand_selector()}
        if rng.random() < 0.3:
            c["whenUnsatisfiable"] = rng.choice(
                ["DoNotSchedule", "ScheduleAnyway"]
            )
        if rng.random() < 0.4:
            c[rng.choice(["minDomains", "matchLabelKeys",
                          "nodeAffinityPolicy", "nodeTaintsPolicy"])] = (
                rng.choice([None, 1, 2, [], ["rev"], "Honor", "Ignore"])
            )
        return c

    objs = []
    for i in range(40):
        spec = {"nodeName": "n1", "containers": []}
        aff = {}
        if rng.random() < 0.7:
            aff["podAntiAffinity"] = {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    rand_term() for _ in range(rng.randint(1, 3))
                ]
            }
        if rng.random() < 0.5:
            aff["podAffinity"] = {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    rand_term() for _ in range(rng.randint(1, 2))
                ]
            }
        if aff:
            spec["affinity"] = aff
        if rng.random() < 0.4:
            spec["topologySpreadConstraints"] = [
                rand_spread() for _ in range(rng.randint(1, 2))
            ]
        objs.append({
            "metadata": {"name": f"p{i}", "uid": f"u{i}",
                         "namespace": rng.choice(
                             ["default", "payments", None
                              ])},
            "spec": spec,
            "status": {"phase": "Running"},
        })
    batch = native_ingest.parse_pod_list(
        json.dumps({"items": objs}).encode()
    )
    assert batch is not None
    for i, obj in enumerate(objs):
        want = decode_pod(obj)
        got = batch.view(i)
        assert got.anti_affinity_match == want.anti_affinity_match, i
        assert (
            got.anti_affinity_zone_match == want.anti_affinity_zone_match
        ), i
        assert got.pod_affinity_match == want.pod_affinity_match, i
        assert (
            got.pod_affinity_zone_match == want.pod_affinity_zone_match
        ), i
        assert got.spread_constraints == want.spread_constraints, i
        assert got.unmodeled_constraints == want.unmodeled_constraints, i
