"""The lint/format gate (tools/lint.py) must actually gate.

``make check`` chains this linter before the tests, mirroring the
reference's fmt + golangci-lint + vet chain (reference Makefile:36-65).
These tests prove the gate fails on seeded errors of every class and
passes on the real tree (which `make check` then enforces forever).
Fixture machinery is shared with tests/test_analysis.py
(tests/analysis_fixtures.py) — one copy for both gates.
"""

from tests.analysis_fixtures import lint_file, run_lint


def test_tree_is_clean():
    r = run_lint()  # default roots = the whole repo
    assert r.returncode == 0, f"lint gate is red:\n{r.stdout}"


def test_seeded_unused_import_fails(tmp_path):
    r = lint_file(tmp_path, "import os\nprint('hi')\n")
    assert r.returncode == 1
    assert "unused-import" in r.stdout


def test_seeded_syntax_error_fails(tmp_path):
    r = lint_file(tmp_path, "def broken(:\n")
    assert r.returncode == 1
    assert "syntax-error" in r.stdout


def test_seeded_format_errors_fail(tmp_path):
    r = lint_file(tmp_path, "x = 1 \n\ty = 2")
    assert r.returncode == 1
    assert "trailing-space" in r.stdout
    assert "tab-indent" in r.stdout
    assert "no-final-newline" in r.stdout


def test_seeded_vet_errors_fail(tmp_path):
    src = (
        "def f(a={}):\n"
        "    try:\n"
        "        pass\n"
        "    except:\n"
        "        pass\n"
        "    return a == None\n"
    )
    r = lint_file(tmp_path, src)
    assert r.returncode == 1
    for code in ("mutable-default", "bare-except", "none-compare"):
        assert code in r.stdout


def test_noqa_suppresses(tmp_path):
    r = lint_file(tmp_path, "import os  # noqa: F401\n")
    assert r.returncode == 0
