"""Fleet failure-domain tests: service chaos layer (service/chaos.py),
device-health watchdog (service/devhealth.py), agent endpoint failover
(service/agent.py), graceful drain + warm restart (service/server.py),
and the fleet-chaos acceptance core (bench.fleet_chaos_smoke).

The queue/batch mechanics live in tests/test_service.py; this file owns
what happens when the service stack is sick, dying, or lying.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.loop import flight
from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.service import wire
from k8s_spot_rescheduler_tpu.service.agent import RemoteCallError, RemotePlanner
from k8s_spot_rescheduler_tpu.service.chaos import (
    ChaosAgentTransport,
    ServiceChaos,
    ServiceChaosError,
    ServiceFaultPlan,
)
from k8s_spot_rescheduler_tpu.service.devhealth import DeviceHealthWatchdog
from k8s_spot_rescheduler_tpu.service.server import (
    PlannerService,
    ServiceBusy,
    ServiceServer,
)
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.test_service import _stub_solve, tiny_packed


def _service(clock=None, **kwargs) -> PlannerService:
    return PlannerService(
        ReschedulerConfig(solver="numpy"),
        clock=clock or FakeClock(),
        batch_window_s=0,
        **kwargs,
    )


# ---------------------------------------------------------------------------
# chaos layer


def test_service_fault_plan_profiles_and_determinism():
    plan = ServiceFaultPlan.profile("heavy", seed=3)
    assert plan.connect_reset_rate > 0
    with pytest.raises(ValueError):
        ServiceFaultPlan.profile("bogus")
    # config validation rejects unknown profiles up front
    with pytest.raises(ValueError):
        ReschedulerConfig(service_chaos_profile="bogus")

    calls = []

    def inner(url, body, headers, timeout):
        calls.append(url)
        return b"reply-bytes-" + bytes(64)

    def run(seed):
        t = ChaosAgentTransport(
            inner, dataclasses.replace(plan, seed=seed), clock=FakeClock()
        )
        outcomes = []
        for _ in range(60):
            try:
                outcomes.append(("ok", len(t(
                    "http://x/v2/plan", b"b", {}, 5.0
                ))))
            except Exception as err:  # noqa: BLE001 — outcome recording
                outcomes.append(("err", type(err).__name__))
        return outcomes

    # same seed -> identical fault sequence; and the heavy profile
    # actually injected something
    assert run(11) == run(11)
    assert any(kind == "err" for kind, _ in run(11))


def test_agent_transport_scripted_503_and_slow_loris():
    clock = FakeClock()
    plan = ServiceFaultPlan(
        seed=0, http_503_script=(2,), http_503_retry_after=7.0,
        slow_loris_rate=0.0,
    )
    t = ChaosAgentTransport(
        lambda *a: b"ok" + bytes(32), plan, clock=clock
    )
    t("u", b"b", {}, 5.0)  # request 1 passes
    with pytest.raises(RemoteCallError) as exc:
        t("u", b"b", {}, 5.0)  # request 2 is the scripted 503
    assert exc.value.retry_after == 7.0

    loris = ChaosAgentTransport(
        lambda *a: b"ok", ServiceFaultPlan(slow_loris_rate=1.0), clock=clock
    )
    t0 = clock.now()
    with pytest.raises(TimeoutError):
        loris("u", b"b", {}, 5.0)
    assert clock.now() - t0 == pytest.approx(5.0)  # ate the whole deadline


def test_server_chaos_sick_phase_and_scripted_solve_error():
    clock = FakeClock()
    chaos = ServiceChaos(
        ServiceFaultPlan(sick_phase=(2, 3, 1.5), solve_error_script=(4,)),
        clock=clock,
    )
    chaos.on_batch()  # batch 1: healthy, no latency
    assert clock.now() == 0.0
    chaos.on_batch()  # batch 2: sick phase
    chaos.on_batch()  # batch 3: sick phase
    assert clock.now() == pytest.approx(3.0)
    with pytest.raises(ServiceChaosError):
        chaos.on_batch()  # batch 4: scripted solve crash


# ---------------------------------------------------------------------------
# device-health watchdog


def _calibrated(clock, threshold=3):
    wd = DeviceHealthWatchdog(clock, threshold)
    for _ in range(wd.CALIBRATION_BATCHES):
        assert wd.note_batch(0.001) is None
    return wd


def test_watchdog_sick_within_threshold_consecutive_slow_batches():
    clock = FakeClock()
    wd = _calibrated(clock, threshold=3)
    assert wd.note_batch(2.0) is None
    assert wd.note_batch(2.0) is None
    assert wd.note_batch(2.0) == "sick"  # exactly the threshold
    assert wd.sick and wd.detect_streak == 3
    assert wd.snapshot()["device"] == "sick"


def test_watchdog_slow_streak_resets_on_a_healthy_batch():
    wd = _calibrated(FakeClock())
    wd.note_batch(2.0)
    wd.note_batch(2.0)
    wd.note_batch(0.001)  # streak broken
    assert wd.note_batch(2.0) is None and not wd.sick


def test_watchdog_uniformly_slow_solver_is_not_a_sick_device():
    """Slowness is judged against the CALIBRATED baseline: a solver
    that is slow from boot never flips the watchdog (it cannot be
    distinguished from a slow solver)."""
    clock = FakeClock()
    wd = DeviceHealthWatchdog(clock, 3)
    for _ in range(30):
        assert wd.note_batch(2.0) is None
    assert not wd.sick


def test_watchdog_error_and_canary_edges():
    clock = FakeClock()
    wd = _calibrated(clock)
    assert wd.note_error(RuntimeError("xla fell over")) == "sick"
    assert "xla fell over" in wd.sick_reason

    wd2 = _calibrated(clock)
    assert wd2.note_canary(wd2.CANARY_TIMEOUT_S + 1, ok=True) == "sick"
    assert "canary" in wd2.sick_reason


def test_watchdog_recovery_is_hysteresis_gated():
    clock = FakeClock()
    wd = _calibrated(clock, threshold=1)
    assert wd.note_batch(5.0) == "sick"
    # probes are rate-limited on the clock: the first window is open,
    # and a granted probe closes it until PROBE_INTERVAL_S passes
    assert wd.should_probe()
    assert not wd.should_probe()
    # one healthy probe is NOT enough (hysteresis) and the window stays
    # shut until the interval passes
    assert wd.note_probe(0.001, ok=True) is None and wd.sick
    assert not wd.should_probe()
    clock.advance(wd.PROBE_INTERVAL_S)
    assert wd.should_probe()
    # a slow probe resets the healthy streak
    assert wd.note_probe(5.0, ok=True) is None
    clock.advance(wd.PROBE_INTERVAL_S)
    assert wd.should_probe()
    assert wd.note_probe(0.001, ok=True) is None and wd.sick
    clock.advance(wd.PROBE_INTERVAL_S)
    assert wd.should_probe()
    assert wd.note_probe(0.001, ok=True) == "recovered"
    assert not wd.sick and wd.snapshot()["device"] == "ok"


# ---------------------------------------------------------------------------
# service integration: sick flip routes batches to the host path


def test_service_flips_to_host_path_and_recovers():
    clock = FakeClock()
    svc = _service(clock)
    hook_calls = []

    def device_hook(stacked, reqs):
        hook_calls.append(clock.now())
        T, K = stacked.slot_req.shape[0], stacked.slot_req.shape[2]
        return np.zeros((T, 3 + K), np.int32)

    svc.solve_hook = device_hook
    svc.chaos = ServiceChaos(
        ServiceFaultPlan(sick_phase=(0, 0, 0.0)), clock=clock
    )
    f0 = flight.RECORDER.counts()

    # calibrate: healthy batches through the device hook (+1: the
    # shape's FIRST solve carries its compile and is never sampled)
    for i in range(DeviceHealthWatchdog.CALIBRATION_BATCHES + 1):
        svc.submit_nowait("t", tiny_packed(seed=i))
        assert svc.drain_once()
    # scripted sick phase: every batch now pays 2 s on the clock
    svc.chaos = ServiceChaos(
        ServiceFaultPlan(sick_phase=(1, 10**9, 2.0)), clock=clock
    )
    for i in range(svc.config.device_sick_threshold):
        svc.submit_nowait("t", tiny_packed(seed=10 + i))
        assert svc.drain_once()
    assert svc.healthz_snapshot()["device"] == "sick"
    assert metrics.service_snapshot()["device_sick"] == 1.0
    f1 = flight.RECORDER.counts()
    assert f1.get("device-sick", 0) - f0.get("device-sick", 0) == 1

    # while sick (and between probe windows) batches bypass the device
    # hook entirely: the host oracle answers
    n_hook = len(hook_calls)
    svc._devhealth._last_probe = clock.now()  # close the probe window
    req = svc.submit_nowait("t", tiny_packed())
    assert svc.drain_once()
    assert req.reply is not None
    assert len(hook_calls) == n_hook  # device path untouched

    # phase over: probes (healthy hook again) recover after hysteresis
    svc.chaos.enabled = False
    recovered = False
    for i in range(6):
        clock.advance(DeviceHealthWatchdog.PROBE_INTERVAL_S)
        svc.submit_nowait("t", tiny_packed(seed=20 + i))
        assert svc.drain_once()
        if svc.healthz_snapshot()["device"] == "ok":
            recovered = True
            break
    assert recovered
    assert metrics.service_snapshot()["device_sick"] == 0.0
    f2 = flight.RECORDER.counts()
    assert f2.get("device-recovered", 0) - f0.get("device-recovered", 0) == 1


def _spot_resized(packed, S):
    R = packed.spot_free.shape[1]
    W, A = packed.spot_taints.shape[1], packed.spot_aff.shape[1]
    return packed._replace(
        spot_free=np.full((S, R), 100.0, np.float32),
        spot_count=np.zeros(S, np.int32),
        spot_max_pods=np.full(S, 58, np.int32),
        spot_taints=np.zeros((S, W), np.uint32),
        spot_ok=np.ones(S, bool),
        spot_aff=np.zeros((S, A), np.uint32),
    )


def test_first_solve_per_shape_is_compile_not_latency():
    """A new bucket shape's first solve carries its XLA compile; a
    fleet ramp-up of fresh shapes (each 'slow' once) must never flip
    the watchdog — only repeated slowness of already-compiled shapes
    does (review finding)."""
    clock = FakeClock()
    svc = _service(clock)
    slow_once_keys = set()

    def compile_like(stacked, reqs):
        key = stacked.spot_free.shape
        if key not in slow_once_keys:
            slow_once_keys.add(key)
            clock.advance(10.0)  # the "compile" of this shape
        T, K = stacked.slot_req.shape[0], stacked.slot_req.shape[2]
        return np.zeros((T, 3 + K), np.int32)

    svc.solve_hook = compile_like
    # calibrate on one shape (its own first call is the excluded one)
    for i in range(DeviceHealthWatchdog.CALIBRATION_BATCHES + 1):
        svc.submit_nowait("t", tiny_packed(seed=i))
        assert svc.drain_once()
    # three brand-new shapes arrive back to back, each paying a 10 s
    # "compile" — device_sick_threshold consecutive slow-looking solves
    # that must NOT flip the watchdog
    for S in (10, 20, 40):
        svc.submit_nowait("t", _spot_resized(tiny_packed(), S))
        assert svc.drain_once()
    assert svc.healthz_snapshot()["device"] == "ok"
    # but genuine slowness on SEEN shapes still flips
    svc.chaos = ServiceChaos(
        ServiceFaultPlan(sick_phase=(1, 10**9, 10.0)), clock=clock
    )
    for i in range(svc.config.device_sick_threshold):
        svc.submit_nowait("t", tiny_packed(seed=50 + i))
        assert svc.drain_once()
    assert svc.healthz_snapshot()["device"] == "sick"


def test_device_solve_error_flips_sick_and_fails_batch_typed():
    clock = FakeClock()
    svc = _service(clock)

    def exploding(stacked, reqs):
        raise RuntimeError("XLA: device lost")

    svc.solve_hook = exploding
    req = svc.submit_nowait("t", tiny_packed())
    assert svc.drain_once()
    # the exposing batch fails typed (agents fall back locally for that
    # tick) and the service is sick for subsequent batches
    assert req.error is not None and "device lost" in str(req.error)
    assert svc.healthz_snapshot()["device"] == "sick"
    # next batch: served by the host path, no hook involved
    svc._devhealth._last_probe = clock.now()
    req2 = svc.submit_nowait("t", tiny_packed())
    assert svc.drain_once()
    assert req2.reply is not None


# ---------------------------------------------------------------------------
# agent failover ladder


def _observation():
    from tests.test_service import _observation as obs

    return obs()


def test_failover_to_second_endpoint_counted_and_evented():
    cfg = ReschedulerConfig(solver="numpy", planner_timeout=2.0)
    server = ServiceServer(cfg, "127.0.0.1:0", batch_window_s=0.0)
    server.start_background()
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    try:
        agent = RemotePlanner(
            cfg,
            f"http://127.0.0.1:{dead_port},http://{server.address}",
            tenant="fleet-1",
        )
        node_map, pdbs = _observation()
        m0 = metrics.service_snapshot()
        f0 = flight.RECORDER.counts()
        report = agent.plan(node_map, pdbs)
        # full-fidelity remote plan, served by the SECOND endpoint
        assert report.solver == "remote"
        assert agent.last_endpoint == f"http://{server.address}"
        m1 = metrics.service_snapshot()
        f1 = flight.RECORDER.counts()
        assert m1["remote_planner_failover"] == m0["remote_planner_failover"] + 1
        assert m1["remote_planner_fallback"] == m0["remote_planner_fallback"]
        assert f1.get("failover", 0) - f0.get("failover", 0) == 1
        # per-endpoint breakers: the dead endpoint accrued the failure,
        # the serving endpoint stayed clean
        assert agent._endpoints[0].consecutive_failures == 1
        assert agent._endpoints[1].consecutive_failures == 0
        # the failed attempt grafts a wire.failover span into the trace
        assert agent.last_trace is not None
        assert agent.last_trace.find("wire.failover")
    finally:
        server.close()


def test_local_fallback_only_when_every_endpoint_dead():
    import socket

    ports = []
    for _ in range(2):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()
    cfg = ReschedulerConfig(solver="numpy", planner_timeout=0.5)
    agent = RemotePlanner(
        cfg, ",".join(f"http://127.0.0.1:{p}" for p in ports), tenant="t"
    )
    node_map, pdbs = _observation()
    m0 = metrics.service_snapshot()
    report = agent.plan(node_map, pdbs)
    assert report.solver == "remote-fallback" and report.plan is not None
    m1 = metrics.service_snapshot()
    assert m1["remote_planner_fallback"] == m0["remote_planner_fallback"] + 1
    # a failover was never counted: nobody served
    assert m1["remote_planner_failover"] == m0["remote_planner_failover"]
    # both endpoints accrued their own failures
    assert all(ep.consecutive_failures == 1 for ep in agent._endpoints)


def test_planner_urls_config_feeds_the_ladder():
    cfg = ReschedulerConfig(
        solver="numpy",
        planner_urls="http://a:1, http://b:2",
        planner_url="http://ignored:9",
    )
    agent = RemotePlanner(cfg, tenant="t")
    assert agent.urls == ["http://a:1", "http://b:2"]
    # single-endpoint compat surface still works
    agent.url = "http://c:3"
    assert agent.urls[0] == "http://c:3"


def test_retry_after_above_breaker_threshold_capped_regression():
    """The satellite fix: at/above the breaker threshold the skip
    window honors a LONGER server Retry-After — max(backoff,
    Retry-After) — but caps the server-suggested value at 30 s, so one
    bad LB header cannot park the agent on its fallback for hours."""
    cfg = ReschedulerConfig(solver="numpy", planner_timeout=1.0)
    clock = FakeClock()
    agent = RemotePlanner(cfg, "http://x:1", tenant="t", clock=clock)
    ep = agent._endpoints[0]

    jit = 1.0 + RemotePlanner.RETRY_JITTER_FRAC

    # failure 1 (below threshold, no retry-after): warn only
    agent._note_failure(ep, "HTTP 503", 0.0)
    assert ep.skip_until == 0.0
    # failure 2 (AT threshold): base backoff 5 s, server suggests 20 s
    # -> the longer server horizon wins (stretched by at most the
    # per-agent decorrelation jitter)
    agent._note_failure(ep, "HTTP 503", 20.0)
    assert clock.now() + 20.0 <= ep.skip_until < clock.now() + 20.0 * jit
    # failure 3: server suggests an hour -> the SERVER's word is capped
    # at 30 s before the jitter stretch (the backoff schedule value
    # 10 s is smaller, so the capped suggestion is the horizon)
    agent._note_failure(ep, "HTTP 503", 3600.0)
    assert clock.now() + 30.0 <= ep.skip_until < clock.now() + 30.0 * jit
    # deep into the schedule the doubling backoff exceeds the cap and
    # rules unchallenged
    for _ in range(4):
        agent._note_failure(ep, "connection refused", 0.0)
    assert ep.skip_until > clock.now() + 30.0
    # below threshold a fresh endpoint still honors (capped) Retry-After
    agent2 = RemotePlanner(cfg, "http://y:1", tenant="t", clock=clock)
    agent2._note_failure(agent2._endpoints[0], "HTTP 503", 3600.0)
    assert (
        clock.now() + 30.0
        <= agent2._endpoints[0].skip_until
        < clock.now() + 30.0 * jit
    )


def test_no_failover_event_when_primary_serves_despite_later_breaker():
    """A breaker-open endpoint LATER in the list must not brand a
    healthy primary-served tick as a failover (review finding)."""
    cfg = ReschedulerConfig(solver="numpy", planner_timeout=2.0)
    server = ServiceServer(cfg, "127.0.0.1:0", batch_window_s=0.0)
    server.start_background()
    try:
        agent = RemotePlanner(
            cfg, f"http://{server.address},http://127.0.0.1:1",
            tenant="t",
        )
        # the SECOND endpoint's breaker is open; the primary is healthy
        agent._endpoints[1].consecutive_failures = 5
        agent._endpoints[1].skip_until = agent.clock.now() + 120.0
        node_map, pdbs = _observation()
        m0 = metrics.service_snapshot()
        f0 = flight.RECORDER.counts()
        report = agent.plan(node_map, pdbs)
        assert report.solver == "remote"
        assert agent.last_endpoint == f"http://{server.address}"
        m1 = metrics.service_snapshot()
        f1 = flight.RECORDER.counts()
        assert m1["remote_planner_failover"] == m0["remote_planner_failover"]
        assert f1.get("failover", 0) == f0.get("failover", 0)
    finally:
        server.close()


def test_failover_ladder_shares_one_deadline_budget():
    """Three blackholed endpoints must cost the tick ~planner_timeout
    total, not 3x: each attempt gets the REMAINING budget, and an
    endpoint never tried (budget gone) does not accrue breaker
    failures (review finding)."""
    import time as _time

    cfg = ReschedulerConfig(solver="numpy", planner_timeout=0.5)
    agent = RemotePlanner(
        cfg, "http://a:1,http://b:1,http://c:1", tenant="t"
    )
    seen_timeouts = []

    def blackhole(url, body, headers, timeout):
        seen_timeouts.append(timeout)
        _time.sleep(0.2)  # the transport eats real budget
        raise TimeoutError("blackhole")

    agent.transport = blackhole
    node_map, pdbs = _observation()
    t0 = _time.perf_counter()
    report = agent.plan(node_map, pdbs)
    wall = _time.perf_counter() - t0
    assert report.solver == "remote-fallback"
    # the whole ladder stayed near ONE planner_timeout (plus the local
    # oracle solve), nowhere near 3x
    assert wall < 3 * cfg.planner_timeout
    # later attempts saw a SHRUNK budget
    assert len(seen_timeouts) >= 2
    assert seen_timeouts[1] < seen_timeouts[0]
    # at most the budget's worth of endpoints were actually tried; any
    # endpoint skipped on exhaustion kept a clean breaker
    untried = [
        ep for ep in agent._endpoints if ep.consecutive_failures == 0
    ]
    assert len(seen_timeouts) + len(untried) == 3


# ---------------------------------------------------------------------------
# graceful drain + warm restart


def test_graceful_drain_refuses_finishes_and_evicts():
    clock = FakeClock()
    svc = _service(clock)
    svc.solve_hook = _stub_solve()
    queued = svc.submit_nowait("t", tiny_packed())
    svc.begin_drain()
    # new arrivals refused with the drain-grace Retry-After
    with pytest.raises(ServiceBusy) as exc:
        svc.submit_nowait("t", tiny_packed())
    assert exc.value.retry_after == max(
        1, int(np.ceil(svc.config.service_drain_grace))
    )
    # queued work still finishes within the grace
    svc.drain_pending()
    assert queued.reply is not None and queued.error is None


def test_graceful_drain_evicts_past_grace():
    clock = FakeClock()
    svc = _service(clock)

    def slow_solve(stacked, reqs):
        clock.advance(10.0)  # each batch eats far past the grace
        return _stub_solve()(stacked, reqs)

    svc.solve_hook = slow_solve
    first = svc.submit_nowait("a", tiny_packed(seed=1))
    # a different shape family: the second request can never ride the
    # first's batch (batches are per-bucket)
    base = tiny_packed(seed=2)
    S, R = 10, base.spot_free.shape[1]
    W, A = base.spot_taints.shape[1], base.spot_aff.shape[1]
    second = svc.submit_nowait("b", base._replace(
        spot_free=np.full((S, R), 100.0, np.float32),
        spot_count=np.zeros(S, np.int32),
        spot_max_pods=np.full(S, 58, np.int32),
        spot_taints=np.zeros((S, W), np.uint32),
        spot_ok=np.ones(S, bool),
        spot_aff=np.zeros((S, A), np.uint32),
    ))
    svc.begin_drain()
    svc.drain_pending()  # grace 30 s default? config default 5 s
    # the first batch solved (started inside the grace), the second was
    # evicted typed once the deadline passed
    assert first.reply is not None
    assert second.error is not None and "draining" in str(second.error)


def test_drained_server_rejects_http_with_retry_after():
    import urllib.error
    import urllib.request

    cfg = ReschedulerConfig(solver="numpy")
    server = ServiceServer(cfg, "127.0.0.1:0", batch_window_s=0.0)
    server.start_background()
    try:
        server.service.begin_drain()
        body = wire.encode_plan_request("t", tiny_packed())
        req = urllib.request.Request(
            f"http://{server.address}/v2/plan", data=body, method="POST",
            headers={"Content-Type": "application/octet-stream"},
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 503
        assert int(exc.value.headers["Retry-After"]) >= 1
        assert server.service.healthz_snapshot()["draining"] is True
    finally:
        server.close()


def test_warm_restart_persists_and_prewarms(tmp_path):
    cfg = ReschedulerConfig(
        solver="numpy", service_state_dir=str(tmp_path)
    )
    clock = FakeClock()
    svc = PlannerService(cfg, clock=clock, batch_window_s=0)
    svc.solve_hook = _stub_solve()
    svc.submit_nowait("tenant-a", tiny_packed())
    assert svc.drain_once()
    path = svc.save_state()
    assert path and os.path.exists(path)
    payload = json.loads(open(path).read())
    assert payload["tenants"]["tenant-a"].startswith("C")
    assert payload["buckets"]

    # a NEW service instance (the restarted replica) pre-warms those
    # buckets through its real solve path on boot
    svc2 = PlannerService(cfg, clock=FakeClock(), batch_window_s=0)
    warmed = svc2.warm_start()
    assert warmed == [payload["tenants"]["tenant-a"]]
    assert svc2.warmed_buckets == warmed
    # and the fingerprints carried over
    assert svc2._tenant_bucket["tenant-a"] == warmed[0]


def test_warm_start_survives_garbage_state(tmp_path):
    cfg = ReschedulerConfig(
        solver="numpy", service_state_dir=str(tmp_path)
    )
    state = tmp_path / "planner_warm_state.json"
    for garbage in (
        "{not json",
        '{"buckets": 5}',  # valid JSON, wrong shape (review finding)
        '[1, 2, 3]',  # top-level array: payload.get would AttributeError
    ):
        state.write_text(garbage)
        svc = PlannerService(cfg, clock=FakeClock(), batch_window_s=0)
        assert svc.warm_start() == []  # cold start, no crash


# ---------------------------------------------------------------------------
# the fleet acceptance core (the same function `make fleet-chaos-smoke`
# runs, at the CI scale)


def test_fleet_chaos_smoke_acceptance():
    import bench

    result = bench.fleet_chaos_smoke(n_agents=4, seed=0)
    assert result["crashes"] == []
    assert result["mismatches"] == []
    assert result["ok"], result
    assert result["sick_detect_batches"] <= 3
    assert result["flight_eq_metrics"]
    assert result["warmed_buckets"]
