"""Watch-cache tests against a streaming stub apiserver.

The reference's per-tick reads hit client-go watch caches (reference
rescheduler.go:154-156); io/watch.py is that layer here. These tests run
the real list-then-watch protocol over HTTP: LIST seeding, incremental
ADDED/MODIFIED/DELETED application, BOOKMARK version advance, 410-Gone
re-list, per-tick snapshot consistency, and a full control-loop tick
served entirely from the caches.
"""

import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from k8s_spot_rescheduler_tpu.io.kube import KubeClusterClient
from k8s_spot_rescheduler_tpu.io.watch import (
    WatchingKubeClusterClient,
)
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

# keep the watch streams short-lived so test teardown is fast
WATCH_SLICE_SECONDS = 0.25


# raw API-object builders shared with the scripted watch double — one
# source of truth for the shapes the decoders are exercised against
from k8s_spot_rescheduler_tpu.io.fakewatch import raw_node, raw_pod


def _node(name, role, ready=True):
    return raw_node(name, role, cpu_millis=2000, ready=ready)


def _pod(name, node, cpu="100m", phase="Running"):
    return raw_pod(
        name, node, cpu_millis=int(cpu.rstrip("m")), phase=phase
    )


class StreamingStub:
    """Apiserver stub with list+watch on nodes/pods/pdbs, plus the write
    path (evictions, taint patches, events) for full-tick tests."""

    RESOURCES = {
        "/api/v1/nodes": "nodes",
        "/api/v1/pods": "pods",
        "/apis/policy/v1/poddisruptionbudgets": "pdbs",
    }

    def __init__(self):
        self.objects = {"nodes": {}, "pods": {}, "pdbs": {}}
        self.pvcs = {}
        self.pvs = {}
        self.rv = {"nodes": 10, "pods": 10, "pdbs": 10}
        self.queues = {r: queue.Queue() for r in self.rv}
        # one-shot injected watch failures: resource -> status object
        self.fail_next_watch = {}
        self.watch_params = []  # (resource, resourceVersion or None)
        self.list_count = {r: 0 for r in self.rv}
        self.evictions = []
        self.patches = []
        self.events = []

        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, obj, code=200):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _stream_watch(self, resource, q):
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                fail = stub.fail_next_watch.pop(resource, None)
                if fail is not None:
                    self.wfile.write(
                        (json.dumps({"type": "ERROR", "object": fail}) + "\n")
                        .encode()
                    )
                    self.wfile.flush()
                    return
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline:
                    try:
                        event = q.get(timeout=WATCH_SLICE_SECONDS)
                    except queue.Empty:
                        return  # server-side timeout; client reconnects
                    self.wfile.write((json.dumps(event) + "\n").encode())
                    self.wfile.flush()

            def do_GET(self):
                parsed = urlparse(self.path)
                qs = parse_qs(parsed.query)
                resource = StreamingStub.RESOURCES.get(parsed.path)
                if resource is not None:
                    if qs.get("watch"):
                        stub.watch_params.append(
                            (resource, qs.get("resourceVersion", [None])[0])
                        )
                        return self._stream_watch(
                            resource, stub.queues[resource]
                        )
                    stub.list_count[resource] += 1
                    stub.rv[resource] += 1
                    return self._send({
                        "metadata": {"resourceVersion": str(stub.rv[resource])},
                        "items": list(stub.objects[resource].values()),
                    })
                if parsed.path == "/api/v1/persistentvolumeclaims":
                    return self._send({"items": list(stub.pvcs.values())})
                if parsed.path == "/api/v1/persistentvolumes":
                    return self._send({"items": list(stub.pvs.values())})
                if parsed.path.startswith("/api/v1/namespaces/") and \
                        "/pods/" in parsed.path:
                    name = parsed.path.rsplit("/", 1)[1]
                    for pod in stub.objects["pods"].values():
                        if pod["metadata"]["name"] == name:
                            return self._send(pod)
                    return self._send({"kind": "Status"}, 404)
                if parsed.path.startswith("/api/v1/nodes/"):
                    name = parsed.path.rsplit("/", 1)[1]
                    obj = stub.node_by_name(name)
                    return self._send(obj or {}, 200 if obj else 404)
                return self._send({}, 404)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                if self.path.endswith("/eviction"):
                    name = self.path.split("/pods/")[1].split("/")[0]
                    stub.evictions.append(name)
                    gone = [
                        k for k, v in stub.objects["pods"].items()
                        if v["metadata"]["name"] == name
                    ]
                    for k in gone:
                        obj = stub.objects["pods"].pop(k)
                        stub.queues["pods"].put(
                            {"type": "DELETED", "object": obj}
                        )
                    return self._send({"kind": "Status", "status": "Success"})
                if "/events" in self.path:
                    stub.events.append(body)
                    return self._send(body, 201)
                return self._send({}, 404)

            def do_PATCH(self):
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                name = self.path.rsplit("/", 1)[1]
                stub.patches.append((name, body))
                obj = stub.node_by_name(name)
                if obj is not None:
                    obj["spec"]["taints"] = body["spec"]["taints"]
                return self._send(obj or {})

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.server.daemon_threads = True
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self.thread.start()

    def node_by_name(self, name):
        for obj in self.objects["nodes"].values():
            if obj["metadata"]["name"] == name:
                return obj
        return None

    @property
    def url(self):
        host, port = self.server.server_address
        return f"http://{host}:{port}"

    def push(self, resource, etype, obj):
        self.rv[resource] += 1
        obj = dict(obj)
        obj["metadata"] = dict(obj["metadata"],
                               resourceVersion=str(self.rv[resource]))
        self.objects[resource][obj["metadata"]["uid"]] = obj
        if etype == "DELETED":
            self.objects[resource].pop(obj["metadata"]["uid"], None)
        self.queues[resource].put({"type": etype, "object": obj})

    def close(self):
        self.server.shutdown()


@pytest.fixture()
def stub():
    s = StreamingStub()
    yield s
    s.close()


@pytest.fixture()
def watching(stub):
    wc = WatchingKubeClusterClient(KubeClusterClient(stub.url))
    yield stub, wc
    wc.stop()


def _wait(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def test_seed_and_incremental_events(watching):
    stub, wc = watching
    stub.objects["nodes"]["uid-od-1"] = _node("od-1", "worker")
    stub.objects["pods"]["uid-a"] = _pod("a", "od-1")
    wc.start(timeout=10)

    assert [n.name for n in wc.list_ready_nodes()] == ["od-1"]
    assert [p.name for p in wc.list_pods_on_node("od-1")] == ["a"]

    # ADDED pod arrives over the stream, not a re-list
    stub.push("pods", "ADDED", _pod("b", "od-1"))
    assert _wait(lambda: len(wc.pods.snapshot()) == 2)
    wc.list_unschedulable_pods()  # new tick -> new frozen view
    assert sorted(p.name for p in wc.list_pods_on_node("od-1")) == ["a", "b"]
    assert stub.list_count["pods"] == 1  # never re-listed

    # DELETED removes from the cache
    stub.push("pods", "DELETED", _pod("a", "od-1"))
    assert _wait(lambda: len(wc.pods.snapshot()) == 1)
    wc.list_unschedulable_pods()
    assert [p.name for p in wc.list_pods_on_node("od-1")] == ["b"]

    # MODIFIED node flips readiness
    stub.push("nodes", "MODIFIED", _node("od-1", "worker", ready=False))
    assert _wait(
        lambda: not any(n.ready for n in wc.nodes.snapshot())
    )
    wc.list_unschedulable_pods()
    assert wc.list_ready_nodes() == []


def test_tick_snapshot_is_frozen(watching):
    """A tick must see one consistent view even as events stream in —
    only the next tick's first read (the safety gate) refreshes it."""
    stub, wc = watching
    stub.objects["nodes"]["uid-od-1"] = _node("od-1", "worker")
    stub.objects["pods"]["uid-a"] = _pod("a", "od-1")
    wc.start(timeout=10)

    wc.list_unschedulable_pods()  # tick 1 freeze
    stub.push("pods", "ADDED", _pod("late", "od-1"))
    assert _wait(lambda: len(wc.pods.snapshot()) == 2)
    # mid-tick reads still see the frozen view
    assert [p.name for p in wc.list_pods_on_node("od-1")] == ["a"]
    # next tick sees the new pod
    wc.list_unschedulable_pods()
    assert sorted(p.name for p in wc.list_pods_on_node("od-1")) == [
        "a", "late",
    ]


def test_refresh_unfreezes_for_midtick_replan(watching):
    """Multi-drain mode re-observes mid-tick; refresh() must surface
    post-drain state instead of the tick-start freeze."""
    stub, wc = watching
    stub.objects["nodes"]["uid-od-1"] = _node("od-1", "worker")
    stub.objects["pods"]["uid-a"] = _pod("a", "od-1")
    wc.start(timeout=10)
    wc.list_unschedulable_pods()  # tick freeze
    stub.push("pods", "ADDED", _pod("b", "od-1"))
    assert _wait(lambda: len(wc.pods.snapshot()) == 2)
    assert [p.name for p in wc.list_pods_on_node("od-1")] == ["a"]
    wc.refresh()  # what the controller calls before a mid-tick re-plan
    assert sorted(p.name for p in wc.list_pods_on_node("od-1")) == ["a", "b"]


def test_gone_triggers_relist(watching):
    stub, wc = watching
    stub.objects["pods"]["uid-a"] = _pod("a", "od-1")
    wc.start(timeout=10)
    assert stub.list_count["pods"] == 1

    # mutate state behind the cache's back, then expire its version
    stub.objects["pods"]["uid-b"] = _pod("b", "od-1")
    stub.fail_next_watch["pods"] = {
        "kind": "Status", "code": 410, "reason": "Expired",
        "message": "too old resource version",
    }
    assert _wait(lambda: stub.list_count["pods"] >= 2)
    assert _wait(lambda: len(wc.pods.snapshot()) == 2)
    # EXACTLY one throttled re-LIST per expiry: the watcher backs off,
    # lists once, and resumes watching — it must not LIST again while
    # the stream stays healthy
    time.sleep(3 * WATCH_SLICE_SECONDS)
    assert stub.list_count["pods"] == 2


def test_bookmark_leaves_store_untouched(watching):
    """A BOOKMARK advances the watcher's resourceVersion (proven by the
    reconnect params) without applying anything to the store."""
    stub, wc = watching
    stub.objects["nodes"]["uid-od-1"] = _node("od-1", "worker")
    wc.start(timeout=10)
    snap_before = wc.nodes.snapshot_items()
    stub.push("nodes", "BOOKMARK", _node("od-1", "worker"))
    bookmark_rv = int(
        stub.objects["nodes"]["uid-od-1"]["metadata"]["resourceVersion"]
    )
    n = len(stub.watch_params)
    assert _wait(lambda: any(
        res == "nodes" and rv and int(rv) >= bookmark_rv
        for res, rv in stub.watch_params[n:]
    ), timeout=10)
    # the bookmark applied no object: identical store, same objects
    assert wc.nodes.snapshot_items() == snap_before
    [w] = [w for w in wc._watchers if w.resource == "nodes"]
    assert w.event_count == 0
    assert stub.list_count["nodes"] == 1  # and certainly no re-LIST


def test_stop_during_reconnect_backoff_returns_promptly():
    """stop() must cut a reconnect-backoff wait short, not sit it out —
    here every connection fails (closed port), so without the prompt
    stop the thread would sleep its full backoff between attempts."""
    from k8s_spot_rescheduler_tpu.io.watch import RECONNECT_BACKOFF_MAX

    # a port with no listener: instant connection-refused failures
    probe = ThreadingHTTPServer(("127.0.0.1", 0), BaseHTTPRequestHandler)
    host, port = probe.server_address
    probe.server_close()  # free the port; nothing listens now
    # retry_max=0: the kube read-retry layer has its own (bounded)
    # sleeps — this test isolates the WATCHER's reconnect backoff
    wc = WatchingKubeClusterClient(
        KubeClusterClient(f"http://{host}:{port}", retry_max=0)
    )
    for w in wc._watchers:
        w._backoff = RECONNECT_BACKOFF_MAX  # deep in backoff territory
        w.start()
    time.sleep(0.3)  # let every watcher fail and enter its backoff wait
    t0 = time.monotonic()
    wc.stop()
    for w in wc._watchers:
        w.join(timeout=5.0)
        assert not w.is_alive()
    assert time.monotonic() - t0 < 3.0  # far below the 30 s backoff


def test_reconnect_resumes_from_last_rv(watching):
    stub, wc = watching
    stub.objects["nodes"]["uid-od-1"] = _node("od-1", "worker")
    wc.start(timeout=10)
    stub.push("nodes", "BOOKMARK", _node("od-1", "worker"))
    bookmark_rv = int(
        stub.objects["nodes"]["uid-od-1"]["metadata"]["resourceVersion"]
    )
    n = len(stub.watch_params)

    def resumed_at_bookmark():
        # a reconnect after the bookmark carries its version, not the LIST's
        return any(
            res == "nodes" and rv and int(rv) >= bookmark_rv
            for res, rv in stub.watch_params[n:]
        )

    assert _wait(resumed_at_bookmark, timeout=10), (
        "nodes watcher never reconnected from the bookmark's version: "
        f"{stub.watch_params[n:]}"
    )


def test_unschedulable_pods_from_cache(watching):
    stub, wc = watching
    pending = _pod("homeless", "", phase="Pending")
    stub.objects["pods"]["uid-homeless"] = pending
    wc.start(timeout=10)
    assert [p.name for p in wc.list_unschedulable_pods()] == ["homeless"]
    stub.push("pods", "DELETED", pending)
    assert _wait(lambda: not wc.pods.snapshot())
    assert wc.list_unschedulable_pods() == []


def _columnar(wc):
    return wc.columnar_store(
        ("cpu", "memory"),
        on_demand_label="kubernetes.io/role=worker",
        spot_label="kubernetes.io/role=spot-worker",
    )


def _object_pack(wc):
    from k8s_spot_rescheduler_tpu.models.cluster import build_node_map
    from k8s_spot_rescheduler_tpu.models.tensors import pack_cluster

    nodes = wc.list_ready_nodes()
    node_map = build_node_map(
        nodes,
        {n.name: wc.list_pods_on_node(n.name) for n in nodes},
        on_demand_label="kubernetes.io/role=worker",
        spot_label="kubernetes.io/role=spot-worker",
    )
    packed, _ = pack_cluster(
        node_map, wc.list_pdbs(), resources=("cpu", "memory")
    )
    return packed


def test_columnar_feed_tracks_watch_events(watching):
    """The columnar mirror follows the watch stream delta by delta and
    packs the same tensors as the object view frozen at the same point."""
    import numpy as np

    stub, wc = watching
    stub.objects["nodes"]["uid-od-1"] = _node("od-1", "worker")
    stub.objects["nodes"]["uid-spot-1"] = _node("spot-1", "spot-worker")
    stub.objects["pods"]["uid-a"] = _pod("a", "od-1", cpu="300m")
    wc.start(timeout=10)

    store = _columnar(wc)
    assert store.n_pods == 1 and store.n_nodes == 2

    stub.push("pods", "ADDED", _pod("b", "od-1", cpu="200m"))
    stub.push("pods", "ADDED", _pod("s", "spot-1", cpu="100m"))
    assert _wait(lambda: len(wc.pods.snapshot()) == 3)
    stub.push("pods", "DELETED", _pod("a", "od-1"))
    assert _wait(lambda: len(wc.pods.snapshot()) == 2)

    wc.list_unschedulable_pods()  # freeze the object view
    store = _columnar(wc)  # sync the columnar view to the same point
    obj = _object_pack(wc)
    col, _ = store.pack(wc.list_pdbs())
    for field in obj._fields:
        np.testing.assert_array_equal(
            getattr(obj, field), getattr(col, field), err_msg=field
        )


def test_columnar_feed_orphan_pod_before_node(watching):
    """A pod whose node hasn't been observed yet parks as an orphan and
    surfaces when the node ADDED event lands."""
    stub, wc = watching
    stub.objects["nodes"]["uid-od-1"] = _node("od-1", "worker")
    wc.start(timeout=10)
    store = _columnar(wc)

    stub.push("pods", "ADDED", _pod("early", "spot-9", cpu="100m"))
    assert _wait(lambda: len(wc.pods.snapshot()) == 1)
    wc.list_unschedulable_pods()  # next tick: freeze + columnar sync
    store = _columnar(wc)
    assert store.n_pods == 0  # parked: node unknown

    stub.push("nodes", "ADDED", _node("spot-9", "spot-worker"))
    assert _wait(lambda: len(wc.nodes.snapshot()) == 2)
    wc.list_unschedulable_pods()
    store = _columnar(wc)
    assert store.n_pods == 1
    packed, _ = store.pack([])
    assert int(packed.spot_count[0]) == 1


def test_columnar_node_readd_same_name_recovers_pods(watching):
    """Kubelet re-registration: node DELETED then ADDED under the same
    name while its pods stay bound — the mirror must get the pods back
    (they park as orphans in between)."""
    stub, wc = watching
    stub.objects["nodes"]["uid-od-1"] = _node("od-1", "worker")
    stub.objects["nodes"]["uid-spot-1"] = _node("spot-1", "spot-worker")
    stub.objects["pods"]["uid-s"] = _pod("s", "spot-1", cpu="500m")
    wc.start(timeout=10)
    store = _columnar(wc)
    assert store.n_pods == 1

    stub.push("nodes", "DELETED", _node("spot-1", "spot-worker"))
    assert _wait(lambda: len(wc.nodes.snapshot()) == 1)
    wc.refresh()
    store = _columnar(wc)
    assert store.n_pods == 0  # node gone, pod parked

    stub.push("nodes", "ADDED", _node("spot-1", "spot-worker"))
    assert _wait(lambda: len(wc.nodes.snapshot()) == 2)
    wc.refresh()
    store = _columnar(wc)
    assert store.n_pods == 1  # pod recovered with its node
    packed, _ = store.pack([])
    assert int(packed.spot_count[0]) == 1
    assert packed.spot_free[0, 0] == 2000.0 - 500.0


def test_columnar_feed_survives_relist(watching):
    """A 410-Gone re-list arrives as one replace delta; the mirror
    reconciles to exactly the re-listed state."""
    stub, wc = watching
    stub.objects["nodes"]["uid-od-1"] = _node("od-1", "worker")
    stub.objects["pods"]["uid-a"] = _pod("a", "od-1")
    wc.start(timeout=10)
    store = _columnar(wc)
    assert store.n_pods == 1

    # state changes behind the cache's back, then the version expires
    stub.objects["pods"].pop("uid-a")
    stub.objects["pods"]["uid-b"] = _pod("b", "od-1")
    stub.objects["pods"]["uid-c"] = _pod("c", "od-1")
    stub.fail_next_watch["pods"] = {
        "kind": "Status", "code": 410, "reason": "Expired",
        "message": "too old resource version",
    }
    assert _wait(lambda: stub.list_count["pods"] >= 2)
    assert _wait(lambda: len(wc.pods.snapshot()) == 2)
    wc.list_unschedulable_pods()  # next tick: freeze + columnar sync
    store = _columnar(wc)
    assert store.n_pods == 2
    assert "default/a" not in store._pod_row
    assert {"default/b", "default/c"} <= set(store._pod_row)


def test_uid_less_objects_fall_back_to_python_relist(watching):
    """A LIST item without metadata.uid can't be keyed consistently by
    the native path — the watcher must fall back to the Python decode
    and later events must still hit the same store key."""
    stub, wc = watching
    bare = _pod("bare", "od-1")
    del bare["metadata"]["uid"]
    stub.objects["nodes"]["uid-od-1"] = _node("od-1", "worker")
    stub.objects["pods"]["bare-key"] = bare
    wc.start(timeout=10)
    assert [p.name for p in wc.pods.snapshot()] == ["bare"]
    stub.objects["pods"].pop("bare-key")
    stub.queues["pods"].put({"type": "DELETED", "object": bare})
    assert _wait(lambda: not wc.pods.snapshot())


def test_full_tick_served_from_watch_cache(watching):
    """observe (watch caches) -> plan (TPU solver) -> drain (HTTP writes):
    the watch-backed twin of test_kube.test_full_tick_over_http."""
    stub, wc = watching
    stub.objects["nodes"]["uid-od-1"] = _node("od-1", "worker")
    stub.objects["nodes"]["uid-spot-1"] = _node("spot-1", "spot-worker")
    stub.objects["pods"]["uid-a"] = _pod("a", "od-1", cpu="300m")
    stub.objects["pods"]["uid-b"] = _pod("b", "od-1", cpu="200m")
    wc.start(timeout=10)

    config = ReschedulerConfig(pod_eviction_timeout=5.0,
                               eviction_retry_time=1.0)
    r = Rescheduler(wc, SolverPlanner(config), config, clock=FakeClock(),
                    recorder=wc)
    result = r.tick()
    assert result.drained == ["od-1"]
    assert sorted(stub.evictions) == ["a", "b"]
    keys_seq = [
        [t["key"] for t in body["spec"]["taints"]] for _, body in stub.patches
    ]
    assert keys_seq[0] == ["ToBeDeletedByClusterAutoscaler"]
    assert keys_seq[-1] == []
    # reads were served from the caches: exactly the seeding LISTs
    assert stub.list_count == {"nodes": 1, "pods": 1, "pdbs": 1}


def test_volume_affinity_resolves_in_watch_mode(stub):
    """PVC pods resolve against the PVC/PV snapshot seeded before the
    pod watcher starts, and a claim arriving LATE resolves on the next
    tick's refresh — never the unsafe direction in between."""
    stub.pvcs["data"] = {
        "metadata": {"name": "data", "namespace": "default"},
        "spec": {"volumeName": "pv-1"},
        "status": {"phase": "Bound"},
    }
    stub.pvs["pv-1"] = {
        "metadata": {"name": "pv-1"},
        "spec": {"nodeAffinity": {"required": {"nodeSelectorTerms": [
            {"matchExpressions": [
                {"key": "zone", "operator": "In", "values": ["a"]}]}]}}},
    }
    pod = _pod("web", "od-1")
    pod["spec"]["volumes"] = [{"persistentVolumeClaim": {"claimName": "data"}}]
    stub.objects["pods"]["web"] = pod
    stub.objects["nodes"]["od-1"] = _node("od-1", "worker")

    from k8s_spot_rescheduler_tpu.io.kube import KubeClusterClient
    from k8s_spot_rescheduler_tpu.io.watch import WatchingKubeClusterClient

    client = WatchingKubeClusterClient(KubeClusterClient(stub.url))
    client.start(timeout=10.0)
    try:
        [resolved] = [p for p in client.pods.snapshot() if p.name == "web"]
        assert not resolved.unmodeled_constraints
        assert resolved.node_affinity == ((("zone", "In", ("a",)),),)

        # a SECOND pvc pod arrives whose claim is not yet listed: it
        # stays conservatively unplaceable...
        late = _pod("late", "od-1")
        late["spec"]["volumes"] = [
            {"persistentVolumeClaim": {"claimName": "late-data"}}
        ]
        stub.push("pods", "ADDED", late)
        _wait(lambda: any(p.name == "late" for p in client.pods.snapshot()))
        [lp] = [p for p in client.pods.snapshot() if p.name == "late"]
        assert lp.unmodeled_constraints and lp.pvc_resolvable

        # ...until the claim+volume appear and the next tick refreshes
        stub.pvcs["late-data"] = {
            "metadata": {"name": "late-data", "namespace": "default"},
            "spec": {"volumeName": "pv-2"},
            "status": {"phase": "Bound"},
        }
        stub.pvs["pv-2"] = {"metadata": {"name": "pv-2"}, "spec": {}}
        # the genuine per-tick entry (the loop's first read each tick)
        client.refresh()
        client.list_unschedulable_pods()
        [lp] = [p for p in client.pods.snapshot() if p.name == "late"]
        assert not lp.unmodeled_constraints
    finally:
        client.stop()


def test_terminally_unresolvable_pvc_stops_retrying(stub):
    """A claim Bound to a PV with an unmodeled affinity shape can never
    resolve (PV affinity is immutable): the pod stays unmodeled and the
    per-tick retry stops re-LISTing the cluster's volumes for it."""
    stub.pvcs["data"] = {
        "metadata": {"name": "data", "namespace": "default"},
        "spec": {"volumeName": "pv-1"},
        "status": {"phase": "Bound"},
    }
    stub.pvs["pv-1"] = {
        "metadata": {"name": "pv-1"},
        "spec": {"nodeAffinity": {"required": {"nodeSelectorTerms": [
            {"matchFields": [{"key": "metadata.uid", "operator": "In",
                              "values": ["x"]}]}]}}},
    }
    pod = _pod("web", "od-1")
    pod["spec"]["volumes"] = [{"persistentVolumeClaim": {"claimName": "data"}}]
    stub.objects["pods"]["web"] = pod
    stub.objects["nodes"]["od-1"] = _node("od-1", "worker")

    from k8s_spot_rescheduler_tpu.io.kube import KubeClusterClient
    from k8s_spot_rescheduler_tpu.io.watch import WatchingKubeClusterClient

    client = WatchingKubeClusterClient(KubeClusterClient(stub.url))
    client.start(timeout=10.0)
    try:
        [p] = [p for p in client.pods.snapshot() if p.name == "web"]
        assert p.unmodeled_constraints and not p.pvc_resolvable
        # with nothing retryable, further ticks skip the volume LISTs
        client.refresh()
        client.list_unschedulable_pods()
        [p] = [p for p in client.pods.snapshot() if p.name == "web"]
        assert p.unmodeled_constraints and not p.pvc_resolvable
    finally:
        client.stop()
