"""Multi-tenant planner service tests: queue/batch/fairness mechanics
(service/server.py), bucket policy (service/buckets.py) and the agent's
degradation ladder (service/agent.py RemotePlanner).

The JSON sidecar boundary is covered in tests/test_sidecar.py; the
wire-format byte goldens in tests/test_wire_fixtures.py; the
bit-identical-to-solo acceptance runs as ``make serve-smoke``
(bench.serve_smoke, reused by the acceptance test at the bottom)."""

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster
from k8s_spot_rescheduler_tpu.service import buckets as bucketing
from k8s_spot_rescheduler_tpu.service import wire
from k8s_spot_rescheduler_tpu.service.server import (
    PlannerService,
    ServiceBusy,
    ServiceServer,
)
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from k8s_spot_rescheduler_tpu.utils.durations import parse_duration


def tiny_packed(n_lanes: int = 2, seed: int = 0) -> PackedCluster:
    """A minimal consistent problem: C=2 lanes, K=2 slots, S=2 spots.
    ``n_lanes`` valid lanes (DRR cost); values vary with ``seed`` so
    distinct requests are distinct tensors."""
    rng = np.random.default_rng(seed)
    C, K, S, R, W, A = 2, 2, 2, 2, 1, 2
    return PackedCluster(
        slot_req=rng.random((C, K, R), np.float32),
        slot_valid=np.ones((C, K), bool),
        slot_tol=np.zeros((C, K, W), np.uint32),
        slot_aff=np.zeros((C, K, A), np.uint32),
        cand_valid=np.arange(C) < n_lanes,
        spot_free=np.full((S, R), 100.0, np.float32),
        spot_count=np.zeros(S, np.int32),
        spot_max_pods=np.full(S, 58, np.int32),
        spot_taints=np.zeros((S, W), np.uint32),
        spot_ok=np.ones(S, bool),
        spot_aff=np.zeros((S, A), np.uint32),
    )


def _stub_solve(record=None):
    def solve(stacked, reqs):
        if record is not None:
            record.append([r.tenant for r in reqs])
        T = stacked.slot_req.shape[0]
        K = stacked.slot_req.shape[2]
        return np.zeros((T, 3 + K), np.int32)

    return solve


def _service(clock=None, **kwargs) -> PlannerService:
    return PlannerService(
        ReschedulerConfig(solver="numpy"),
        clock=clock or FakeClock(),
        batch_window_s=0,
        **kwargs,
    )


# ---------------------------------------------------------------------------
# buckets


def test_bucket_rounding_and_padding_semantics():
    packed = tiny_packed()
    b = bucketing.bucket_for(packed)
    # powers of two with the sublane floor
    assert (b.C, b.K, b.S) == (8, 8, 8)
    assert (b.R, b.W, b.A) == (2, 1, 2)
    padded = bucketing.pad_to_bucket(packed, b)
    assert padded.slot_req.shape == (8, 8, 2)
    assert padded.spot_free.shape == (8, 2)
    # pads are inert: invalid lanes, empty slots, not-ok zero-cap spots
    assert not padded.cand_valid[2:].any()
    assert not padded.slot_valid[:, 2:].any()
    assert not padded.spot_ok[2:].any()
    assert not padded.spot_free[2:].any()
    # the original problem survives verbatim in the prefix
    np.testing.assert_array_equal(padded.slot_req[:2, :2], packed.slot_req)
    # a problem from another shape family is refused, not mis-padded
    with pytest.raises(ValueError):
        bucketing.pad_to_bucket(
            packed._replace(spot_aff=np.zeros((2, 3), np.uint32)), b
        )


def test_bucket_batch_cap_tracks_hbm_estimate():
    b = bucketing.Bucket(C=256, K=32, S=256, R=4, W=2, A=2)
    per = bucketing.per_tenant_hbm_bytes(b)
    assert bucketing.max_batch_tenants(b, budget_bytes=10 * per) == 10
    # never zero: a lone over-budget tenant is the auto-shard tiers'
    # problem, not the batcher's
    assert bucketing.max_batch_tenants(b, budget_bytes=per // 2) == 1
    # and capped, so worst-case batch latency stays bounded
    assert bucketing.max_batch_tenants(b, budget_bytes=10**18) == 64


# ---------------------------------------------------------------------------
# queue + DRR fairness


def test_flooding_tenant_cannot_starve_another():
    """The fairness acceptance: tenant A floods 20 requests, tenant B
    submits one — B's request rides the VERY NEXT batch (bounded by one
    batch interval), because each DRR pass offers every tenant a slot
    before revisiting anyone."""
    clock = FakeClock()
    svc = _service(clock, max_batch_tenants=2)
    batches = []
    svc.solve_hook = _stub_solve(batches)
    for i in range(20):
        svc.submit_nowait("flooder", tiny_packed(seed=i))
    b_req = svc.submit_nowait("victim", tiny_packed(seed=99))
    assert svc.drain_once()
    # first batch: one from each tenant, NOT two from the flooder
    assert batches[0] == ["flooder", "victim"]
    assert b_req.event.is_set() and b_req.reply is not None
    assert b_req.reply.batch_tenants == 2
    # the flood then drains alone
    while svc.drain_once():
        pass
    assert all(t == ["flooder"] for t in [b[:1] for b in batches[1:]])
    assert svc.queue_depth() == 0


def test_drr_interleaves_within_batch_capacity():
    """With room for 6, three tenants' floods interleave one request per
    tenant per pass — not tenant-by-tenant fills."""
    clock = FakeClock()
    svc = _service(clock, max_batch_tenants=6)
    batches = []
    svc.solve_hook = _stub_solve(batches)
    for tenant in ("a", "b", "c"):
        for i in range(3):
            svc.submit_nowait(tenant, tiny_packed(seed=i))
    assert svc.drain_once()
    assert batches[0][:3] == ["a", "b", "c"]  # first pass: one each
    assert sorted(batches[0]) == ["a", "a", "b", "b", "c", "c"]


def test_batch_picks_oldest_request_bucket():
    """Bounded wait beats throughput: the batch solves the bucket of
    the OLDEST waiting request, even when a newer bucket has more
    tenants queued."""
    clock = FakeClock()
    svc = _service(clock, max_batch_tenants=8)
    batches = []
    svc.solve_hook = _stub_solve(batches)
    big = tiny_packed()._replace(
        slot_req=np.zeros((20, 2, 2), np.float32),
        slot_valid=np.ones((20, 2), bool),
        slot_tol=np.zeros((20, 2, 1), np.uint32),
        slot_aff=np.zeros((20, 2, 2), np.uint32),
        cand_valid=np.ones(20, bool),
    )
    old = svc.submit_nowait("elder", big)  # bucket C=32
    clock.advance(1.0)
    for i in range(3):
        svc.submit_nowait(f"t{i}", tiny_packed(seed=i))  # bucket C=8
    assert svc.drain_once()
    assert batches[0] == ["elder"]
    assert old.event.is_set()


def test_expired_request_is_evicted_with_cadence_retry_after():
    """A request nobody batches within the queue timeout is evicted —
    503 + Retry-After from the measured cadence — and counted per
    tenant in service_tenant_evictions_total."""
    clock = FakeClock()
    svc = _service(clock)
    svc.queue_timeout_s = 0.05
    svc._cadence_s = 3.2
    # a scheduler nominally exists but never drains (submit's inline
    # drain is for scheduler-LESS in-process callers; here the queued
    # request must genuinely rot)
    svc._thread = object()
    before = metrics.service_snapshot()["tenant_evictions"]
    with pytest.raises(ServiceBusy) as err:
        svc.submit("loner", tiny_packed())
    assert err.value.retry_after == 4  # ceil(3.2)
    assert metrics.service_snapshot()["tenant_evictions"] == before + 1
    assert svc.queue_depth() == 0  # really evicted, not abandoned


def test_client_deadline_bounds_server_wait():
    """A client-declared deadline (the agent's X-Planner-Deadline)
    tightens the server-side wait below service_queue_timeout: the
    service must not keep solving for a caller that already hung up."""
    import time

    clock = FakeClock()
    svc = _service(clock)  # queue_timeout stays the 30 s default
    svc._thread = object()  # scheduler "exists" but never drains
    t0 = time.monotonic()
    with pytest.raises(ServiceBusy):
        svc.submit("impatient", tiny_packed(), timeout_s=0.1)
    assert time.monotonic() - t0 < 5.0  # the 0.1 s deadline, not 30 s


def test_tenant_state_is_pruned():
    """Tenant ids are client-supplied: the last-plan-age map (serialized
    into every /healthz) drops entries past the TTL and hard-caps, and
    an emptied tenant leaves no queue residue behind."""
    from k8s_spot_rescheduler_tpu.service import server as srv

    clock = FakeClock()
    svc = _service(clock)
    svc.solve_hook = _stub_solve()
    for i in range(5):
        svc.submit_nowait(f"churner-{i}", tiny_packed(seed=i))
    while svc.drain_once():
        pass
    assert len(svc._last_plan_wall) == 5
    assert svc._queues == {}  # emptied tenants fully pruned
    # a batch far in the future prunes everything past the TTL
    clock.advance(srv.TENANT_STATE_TTL_S + 10)
    svc.submit_nowait("fresh", tiny_packed())
    assert svc.drain_once()
    assert set(svc._last_plan_wall) == {"fresh"}


def test_solve_failure_contained_per_batch():
    """A solve exception fails THAT batch's requests with a typed error;
    the service survives and the next batch solves normally."""
    clock = FakeClock()
    svc = _service(clock)

    def exploding(stacked, reqs):
        raise RuntimeError("device fell over")

    svc.solve_hook = exploding
    req = svc.submit_nowait("t", tiny_packed())
    assert svc.drain_once()
    assert req.error is not None and "device fell over" in str(req.error)
    svc.solve_hook = _stub_solve()
    req2 = svc.submit_nowait("t", tiny_packed())
    assert svc.drain_once()
    assert req2.reply is not None


def test_mesh_batch_pads_tenants_and_matches_single_device():
    """On a multi-device backend (conftest forces 8 virtual CPU
    devices) the service pads the tenant axis to a device multiple so
    the batch SHARDS over the tenant mesh — and the sharded results are
    identical to the plain single-device vmap program, row for row."""
    import jax

    if len(jax.devices()) <= 1:
        pytest.skip("needs >1 device")
    from k8s_spot_rescheduler_tpu.parallel.tenant_batch import (
        make_tenant_batch_planner,
    )

    svc = PlannerService(
        ReschedulerConfig(solver="jax"), clock=FakeClock(), batch_window_s=0
    )
    packs = [tiny_packed(seed=i) for i in range(3)]  # 3 % 8 != 0
    b = bucketing.bucket_for(packs[0])
    stacked = bucketing.stack_bucket(
        [bucketing.pad_to_bucket(p, b) for p in packs], b
    )
    out = svc._solve(stacked)
    assert svc._mesh is not None  # the mesh path really engaged
    assert out.shape[0] == 3  # pad tenants trimmed back off
    ref = np.asarray(make_tenant_batch_planner(None, rounds=8)(stacked))
    np.testing.assert_array_equal(out, ref)


# ---------------------------------------------------------------------------
# HTTP wire surface


def _wire_post(address, body, timeout=30):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        f"http://{address}/v2/plan",
        data=body,
        headers={"Content-Type": "application/octet-stream"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as err:
        return err.code, err.read()


@pytest.fixture()
def wire_server():
    s = ServiceServer(
        ReschedulerConfig(solver="numpy"), "127.0.0.1:0",
        batch_window_s=0.01,
    )
    s.start_background()
    yield s
    s.close()


def test_wire_endpoint_plans(wire_server):
    code, body = _wire_post(
        wire_server.address, wire.encode_plan_request("t1", tiny_packed())
    )
    assert code == 200
    reply = wire.decode_plan_reply(body)
    assert reply.found and reply.n_feasible == 2
    assert reply.batch_tenants >= 1 and reply.batch_lanes >= 2


def test_wire_endpoint_unknown_version_is_400_not_crash(wire_server):
    blob = bytearray(wire.encode_plan_request("t1", tiny_packed()))
    blob[4] = wire.WIRE_VERSION + 3
    code, body = _wire_post(wire_server.address, bytes(blob))
    assert code == 400
    with pytest.raises(wire.WireError) as err:
        wire.decode_plan_reply(body)
    assert "version" in str(err.value)
    # the server survives out-of-protocol bytes
    code, _ = _wire_post(
        wire_server.address, wire.encode_plan_request("t1", tiny_packed())
    )
    assert code == 200


def test_wire_endpoint_garbage_is_400(wire_server):
    code, body = _wire_post(wire_server.address, b"\x00" * 64)
    assert code == 400


# ---------------------------------------------------------------------------
# RemotePlanner degradation ladder


def _observation():
    """(node_map, pdbs) for RemotePlanner.plan — the object path."""
    from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
    from k8s_spot_rescheduler_tpu.models.cluster import build_node_map
    from k8s_spot_rescheduler_tpu.utils.clock import FakeClock as FC
    from tests.fixtures import (
        ON_DEMAND_LABEL,
        ON_DEMAND_LABELS,
        SPOT_LABEL,
        SPOT_LABELS,
        make_node,
        make_pod,
    )

    fc = FakeCluster(FC())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_node(make_node("spot-2", SPOT_LABELS))
    fc.add_pod(make_pod("a", 300, "od-1"))
    fc.add_pod(make_pod("b", 200, "od-1"))
    nodes = fc.list_ready_nodes()
    pods = {n.name: fc.list_pods_on_node(n.name) for n in nodes}
    return build_node_map(
        nodes, pods,
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    ), fc.list_pdbs()


def test_remote_planner_plans_falls_back_and_recovers():
    """The degradation acceptance: a healthy service plans remotely;
    the service dying mid-tick degrades the NEXT tick to the local
    numpy oracle (counted in remote_planner_fallback_total) with the
    same drain decision; a healthy service again -> remote planning
    resumes on the next reply and the breaker resets."""
    from k8s_spot_rescheduler_tpu.service.agent import RemotePlanner

    cfg = ReschedulerConfig(solver="numpy", planner_timeout=5.0)
    server = ServiceServer(cfg, "127.0.0.1:0", batch_window_s=0.01)
    server.start_background()
    node_map, pdbs = _observation()

    agent = RemotePlanner(cfg, f"http://{server.address}", tenant="c1")
    r1 = agent.plan(node_map, pdbs)
    assert r1.solver == "remote"
    assert r1.plan is not None and r1.plan.node.node.name == "od-1"
    want = dict(r1.plan.assignments)

    # service goes away mid-operation
    server.close()
    before = metrics.service_snapshot()["remote_planner_fallback"]
    r2 = agent.plan(node_map, pdbs)
    assert r2.solver == "remote-fallback"
    assert r2.plan is not None and r2.plan.node.node.name == "od-1"
    assert dict(r2.plan.assignments) == want  # same oracle, same answer
    assert metrics.service_snapshot()["remote_planner_fallback"] == before + 1
    assert agent._consecutive_failures == 1

    # service returns (new port — the agent is repointed, which keeps
    # the test deterministic; the breaker state is what's under test)
    server2 = ServiceServer(cfg, "127.0.0.1:0", batch_window_s=0.01)
    server2.start_background()
    try:
        agent.url = f"http://{server2.address}"
        agent._skip_until = 0.0  # backoff horizon passed
        r3 = agent.plan(node_map, pdbs)
        assert r3.solver == "remote"
        assert r3.plan is not None and dict(r3.plan.assignments) == want
        assert agent._consecutive_failures == 0  # healthy reply resets
    finally:
        server2.close()


def test_remote_planner_breaker_skips_dead_service():
    """Past FAIL_THRESHOLD consecutive failures the breaker opens: the
    agent stops paying connect timeouts and plans locally until the
    backoff horizon passes."""
    from k8s_spot_rescheduler_tpu.service.agent import RemotePlanner

    cfg = ReschedulerConfig(solver="numpy", planner_timeout=0.5)
    # nothing listens here (bound-then-closed port)
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    agent = RemotePlanner(cfg, f"http://127.0.0.1:{port}", tenant="c1")
    node_map, pdbs = _observation()
    for i in range(agent.FAIL_THRESHOLD):
        r = agent.plan(node_map, pdbs)
        assert r.solver == "remote-fallback"
    assert agent._skip_until > 0  # breaker open
    # while open, no network call is attempted: plan_async starts no
    # worker thread, and the tick still produces a plan
    finish = agent.plan_async(node_map, pdbs)
    r = finish()
    assert r.solver == "remote-fallback" and r.plan is not None


def test_remote_planner_honors_503_retry_after():
    """An overloaded service's Retry-After opens the skip window even
    below the failure threshold — one 503 must not cost the next tick
    another doomed round trip inside the named horizon."""
    from k8s_spot_rescheduler_tpu.service.agent import RemotePlanner

    cfg = ReschedulerConfig(solver="numpy", planner_timeout=5.0)
    server = ServiceServer(
        cfg, "127.0.0.1:0", batch_window_s=0.01, max_inflight=0
    )  # every request rejects 503 before the body is read
    server.service._cadence_s = 9.0
    server.start_background()
    try:
        agent = RemotePlanner(cfg, f"http://{server.address}", tenant="c1")
        node_map, pdbs = _observation()
        import time

        t0 = time.monotonic()
        r = agent.plan(node_map, pdbs)
        assert r.solver == "remote-fallback"
        assert agent._skip_until >= t0 + 8.0  # the named 9 s horizon
    finally:
        server.close()


# ---------------------------------------------------------------------------
# CLI wiring


def test_service_flags_flow_into_config():
    from k8s_spot_rescheduler_tpu.cli.main import (
        build_parser,
        config_from_args,
    )

    args = build_parser().parse_args([
        "--planner-url", "http://planner.svc:8642",
        "--planner-timeout", "3s",
        "--service-batch-window", "50ms",
        "--service-queue-timeout", "1m",
    ])
    cfg = config_from_args(args)
    assert cfg.planner_url == "http://planner.svc:8642"
    assert cfg.planner_timeout == 3.0
    assert cfg.service_batch_window == pytest.approx(0.05)
    assert cfg.service_queue_timeout == 60.0
    # defaults parse too (the flag defaults are duration strings)
    d = ReschedulerConfig()
    args = build_parser().parse_args([])
    cfg = config_from_args(args)
    assert cfg.planner_timeout == d.planner_timeout
    assert cfg.service_batch_window == pytest.approx(d.service_batch_window)
    assert cfg.service_queue_timeout == d.service_queue_timeout
    assert parse_duration(args.serve or "0") == 0  # runtime-only, default off


def test_config_validation():
    with pytest.raises(ValueError):
        ReschedulerConfig(planner_timeout=0)
    with pytest.raises(ValueError):
        ReschedulerConfig(service_batch_window=-1)
    with pytest.raises(ValueError):
        ReschedulerConfig(service_queue_timeout=0)


# ---------------------------------------------------------------------------
# acceptance: the serve-smoke core (same code `make serve-smoke` runs)


def test_serve_smoke_core():
    import bench

    result = bench.serve_smoke(n_tenants=4, seed=0)
    assert result["ok"], result


# ---------------------------------------------------------------------------
# fleet observability plane: windowed waits on /healthz, compile sharing,
# labeled admission shed (the per-reason edges are driven end-to-end over
# HTTP by bench.fleet_twin.induce_shed_edges / tests/test_twin.py)


def test_healthz_embeds_windowed_queue_waits():
    metrics.reset_service_window()
    clock = FakeClock()
    svc = _service(clock)
    svc.solve_hook = _stub_solve()
    svc.submit_nowait("probe-a", tiny_packed())
    clock.advance(0.25)
    svc.submit_nowait("probe-b", tiny_packed(seed=1))
    assert svc.drain_once()
    snap = svc.healthz_snapshot()
    qw = snap["queue_wait_ms"]
    assert qw["n"] == 2
    # probe-a waited ~250ms, probe-b ~0: the windowed percentiles see it
    assert qw["p99_ms"] >= 200.0
    assert qw["tenants"]["probe-a"]["p99_ms"] >= 200.0
    assert qw["tenants"]["probe-b"]["p99_ms"] < 200.0
    metrics.reset_service_window()


def test_bucket_compile_miss_then_hit_per_shape_family():
    from prometheus_client import REGISTRY as _REG

    def _v(name):
        return _REG.get_sample_value(name) or 0

    hits = "spot_rescheduler_service_bucket_compile_hits_total"
    misses = "spot_rescheduler_service_bucket_compile_misses_total"
    svc = _service()
    svc.solve_hook = _stub_solve()
    h0, m0 = _v(hits), _v(misses)
    svc.submit_nowait("t", tiny_packed(seed=0))
    assert svc.drain_once()  # first solve of this stacked family: miss
    assert (_v(misses), _v(hits)) == (m0 + 1, h0)
    svc.submit_nowait("t", tiny_packed(seed=1))
    assert svc.drain_once()  # same family again: shared program, hit
    assert (_v(misses), _v(hits)) == (m0 + 1, h0 + 1)


def test_queue_timeout_eviction_fires_labeled_shed():
    from prometheus_client import REGISTRY as _REG

    from k8s_spot_rescheduler_tpu.loop import flight

    name = "spot_rescheduler_service_admission_shed_total"
    before = _REG.get_sample_value(name, {"reason": "queue-timeout"}) or 0
    seq0 = max(
        (e["seq"] for e in flight.events("service-shed")), default=-1
    )
    clock = FakeClock()
    svc = _service(clock)
    svc.queue_timeout_s = 0.05
    svc._thread = object()  # scheduler "exists" but never drains: rot
    with pytest.raises(ServiceBusy):
        svc.submit("too-late", tiny_packed())
    after = _REG.get_sample_value(name, {"reason": "queue-timeout"}) or 0
    assert after == before + 1
    fresh = [
        e for e in flight.events("service-shed")
        if e["seq"] > seq0
        and e["attrs"].get("reason") == "queue-timeout"
    ]
    assert len(fresh) == 1  # one fire site, metric and ledger agree


# ---------------------------------------------------------------------------
# resync-storm ingest admission (docs/ROBUSTNESS.md "Resync storms")


def _ingest_server(**cfg_kwargs) -> ServiceServer:
    """An unstarted ServiceServer (port 0, FakeClock): the admission
    gate lives on the server object, no HTTP needed to exercise it."""
    return ServiceServer(
        ReschedulerConfig(solver="numpy", **cfg_kwargs),
        "127.0.0.1:0", batch_window_s=0, clock=FakeClock(),
    )


def test_resync_ingest_cap_refuses_excess():
    """The concurrent-ingest token bucket: cap admissions hold tokens,
    the cap+1th is refused (typed, with a horizon), and releases return
    both the token and the ledger bytes."""
    srv = _ingest_server()
    try:
        packed = tiny_packed()
        per = bucketing.per_tenant_hbm_bytes(bucketing.bucket_for(packed))
        charges = []
        for _ in range(srv.resync_ingest_cap):
            ok, retry, charge = srv.admit_resync_ingest(packed)
            assert ok and retry == 0 and charge == per
            charges.append(charge)
        assert srv._resync_inflight == srv.resync_ingest_cap
        assert srv._resync_ledger_bytes == per * srv.resync_ingest_cap
        ok, retry, charge = srv.admit_resync_ingest(packed)
        assert not ok and retry >= 1 and charge == 0
        for c in charges:
            srv.release_resync_ingest(c)
        assert srv._resync_inflight == 0
        assert srv._resync_ledger_bytes == 0
        ok, _, charge = srv.admit_resync_ingest(packed)  # tokens back
        assert ok
        srv.release_resync_ingest(charge)
    finally:
        srv.close()


def test_resync_ingest_retry_after_grows_with_load():
    """Refusal horizons are LOAD-derived, not static: each undrained
    refusal deepens the pressure term, so the k-th refused tenant in a
    storm is told a strictly later comeback than the (k-1)-th — the
    herd disperses instead of re-forming on one synchronized instant."""
    srv = _ingest_server()
    try:
        srv.service._cadence_s = 4.0  # measured batch cadence
        packed = tiny_packed()
        held = [srv.admit_resync_ingest(packed)[2]
                for _ in range(srv.resync_ingest_cap)]
        cap = srv.resync_ingest_cap
        horizons = [srv.admit_resync_ingest(packed)[1] for _ in range(3)]
        # ceil(cadence * (inflight + pressure) / cap): 5, 6, 7 at cap 4
        expect = [
            int(np.ceil(4.0 * (cap + k) / cap)) for k in (1, 2, 3)
        ]
        assert horizons == expect
        assert horizons == sorted(set(horizons))  # strictly increasing
        # a completed ingest drains one unit of pressure: the storm
        # being worked off relaxes the horizon
        srv.release_resync_ingest(held.pop())
        relaxed = srv.admit_resync_ingest(packed)[1]
        assert relaxed <= horizons[-1]
        for c in held:
            srv.release_resync_ingest(c)
    finally:
        srv.close()


def test_resync_ingest_byte_ledger_bounds_admission():
    """The byte ledger: a second concurrent ingest that would overflow
    the configured budget is refused even with cap tokens free — but a
    lone over-budget tenant is still admitted when the class is idle
    (the batch cap's never-zero floor), so one big tenant can't be
    locked out forever."""
    packed = tiny_packed()
    per = bucketing.per_tenant_hbm_bytes(bucketing.bucket_for(packed))
    srv = _ingest_server(
        service_resync_ingest_budget=int(per * 1.5)
    )
    try:
        ok, _, charge = srv.admit_resync_ingest(packed)
        assert ok  # idle-class floor: admitted though per > budget/2
        ok2, retry2, _ = srv.admit_resync_ingest(packed)
        assert not ok2 and retry2 >= 1  # ledger full, tokens free
        assert srv._resync_inflight < srv.resync_ingest_cap
        srv.release_resync_ingest(charge)
        ok3, _, charge3 = srv.admit_resync_ingest(packed)
        assert ok3  # bytes returned -> admissible again
        srv.release_resync_ingest(charge3)
    finally:
        srv.close()


def test_resync_gate_spares_delta_and_cached_traffic(wire_server):
    """The storm gate only sees cache-seeding resync ingests: cached
    tenants and unfingerprinted requests plan normally while excess
    resyncs shed typed 503 + Retry-After, and the labeled metric and
    the resync-shed flight ledger move in lockstep (one fire site)."""
    import urllib.error
    import urllib.request

    from prometheus_client import REGISTRY as _REG

    from k8s_spot_rescheduler_tpu.loop import flight
    from k8s_spot_rescheduler_tpu.models.columnar import pack_fingerprint

    def post(tenant, *, fp=False, seed=0):
        packed = tiny_packed(seed=seed)
        body = wire.encode_plan_request(
            tenant, packed,
            pack_fingerprint=pack_fingerprint(packed) if fp else "",
        )
        req = urllib.request.Request(
            f"http://{wire_server.address}/v2/plan", data=body,
            headers={"Content-Type": "application/octet-stream"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, dict(resp.headers)
        except urllib.error.HTTPError as err:
            err.read()
            return err.code, dict(err.headers)

    code, _ = post("cached-t", fp=True)  # seeds the tenant cache
    assert code == 200
    assert wire_server.service.tenant_cached("cached-t")

    name = "spot_rescheduler_service_admission_shed_total"
    before = _REG.get_sample_value(name, {"reason": "resync-storm"}) or 0
    seq0 = max(
        (e["seq"] for e in flight.events("resync-shed")), default=-1
    )
    old_cap = wire_server.resync_ingest_cap
    wire_server.resync_ingest_cap = 0  # every resync ingest refuses
    try:
        code, _ = post("cached-t", fp=True, seed=1)
        assert code == 200  # cached tenant: bypasses the gate
        code, _ = post("plain-t")
        assert code == 200  # no fingerprint: not a resync ingest
        code, headers = post("storm-t", fp=True)
        assert code == 503  # uncached full-pack resync: shed
        assert int(headers.get("Retry-After", "0")) >= 1
        assert not wire_server.service.tenant_cached("storm-t")
    finally:
        wire_server.resync_ingest_cap = old_cap
    after = _REG.get_sample_value(name, {"reason": "resync-storm"}) or 0
    assert after == before + 1
    fresh = [
        e for e in flight.events("resync-shed") if e["seq"] > seq0
    ]
    assert len(fresh) == 1  # flight delta == metric delta
    assert fresh[0]["attrs"].get("reason") == "resync-storm"
    # the shed tenant retries into an idle class and is admitted
    code, _ = post("storm-t", fp=True)
    assert code == 200


def test_retry_jitter_decorrelates_equal_horizons():
    """Two agents handed the SAME Retry-After must not come back in the
    same instant: each agent's private urandom-seeded jitter stretches
    the horizon independently, so equal 503s from one overloaded
    replica don't re-form the herd it just shed (PR-10's 30s cap still
    bounds the stretch)."""
    from k8s_spot_rescheduler_tpu.service.agent import RemotePlanner

    clock = FakeClock()
    cfg = ReschedulerConfig(solver="numpy")
    agents = [
        RemotePlanner(cfg, "http://127.0.0.1:1", tenant=f"t{i}",
                      clock=clock)
        for i in range(2)
    ]
    horizon = 9.0
    for a in agents:
        a._note_failure(a._endpoints[0], "storm 503",
                        retry_after=horizon)
    skips = [a._endpoints[0].skip_until for a in agents]
    now = clock.now()
    lo = now + horizon
    hi = now + horizon * (1.0 + RemotePlanner.RETRY_JITTER_FRAC)
    for s in skips:
        assert lo <= s <= hi
    assert skips[0] != skips[1]  # decorrelated: no shared comeback tick
    # the cap still rules: an absurd LB header can't park an endpoint
    a = agents[0]
    a._endpoints[0].consecutive_failures = 0
    a._note_failure(a._endpoints[0], "bad LB", retry_after=86400.0)
    cap = RemotePlanner.RETRY_AFTER_CAP_S
    assert a._endpoints[0].skip_until <= now + cap * (
        1.0 + RemotePlanner.RETRY_JITTER_FRAC
    )
