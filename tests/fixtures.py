"""Shared test fixtures, descended from the reference's builders
(nodes/nodes_test.go:324-369 ``createTestPod``/``createLowPriorityTestPod``/
``createTestNode``; rescheduler_test.go:40-123 node fixtures)."""

from __future__ import annotations

from k8s_spot_rescheduler_tpu.models.cluster import (
    CPU,
    MEMORY,
    PODS,
    NodeSpec,
    OwnerRef,
    PodSpec,
)

SPOT_LABELS = {"kubernetes.io/role": "spot-worker"}
ON_DEMAND_LABELS = {"kubernetes.io/role": "worker"}
ON_DEMAND_LABEL = "kubernetes.io/role=worker"
SPOT_LABEL = "kubernetes.io/role=spot-worker"


def own_terms(match: dict, ns: str = "default"):
    """The round-5 canonical term tuple for one own-namespace
    matchLabels selector — what decode emits for the classic shape."""
    from k8s_spot_rescheduler_tpu.predicates.selectors import canon_labels

    return (((ns,), canon_labels(match)),)


def make_pod(
    name: str,
    cpu_millis: int,
    node: str = "",
    *,
    namespace: str = "default",
    priority: int = 0,
    memory: int = 0,
    replicated: bool = True,
    **kwargs,
) -> PodSpec:
    """A replicated (ReplicaSet-owned) running pod, like the reference's
    createTestPod (nodes/nodes_test.go:324-346)."""
    requests = {CPU: cpu_millis}
    if memory:
        requests[MEMORY] = memory
    owner_refs = [OwnerRef("ReplicaSet", f"{name}-rs")] if replicated else []
    return PodSpec(
        name=name,
        namespace=namespace,
        node_name=node,
        requests=requests,
        priority=priority,
        owner_refs=owner_refs,
        **kwargs,
    )


def make_node(
    name: str,
    labels: dict,
    *,
    cpu_millis: int = 2000,
    memory: int = 2 * 1024**3,
    max_pods: int = 100,
    **kwargs,
) -> NodeSpec:
    """2000m CPU / 2Gi / 100-pod node, like the reference's createTestNode
    (nodes/nodes_test.go:348-369)."""
    return NodeSpec(
        name=name,
        labels=dict(labels),
        allocatable={CPU: cpu_millis, MEMORY: memory, PODS: max_pods},
        **kwargs,
    )


def pack_fake(fc, resources=("cpu", "memory"), **kw):
    """Pack a FakeCluster through the object path (build_node_map +
    pack_cluster) with the standard labels — the boilerplate every
    predicate test suite needs."""
    from k8s_spot_rescheduler_tpu.models.cluster import build_node_map
    from k8s_spot_rescheduler_tpu.models.tensors import pack_cluster

    nodes = fc.list_ready_nodes()
    unready = fc.list_unready_nodes()
    node_map = build_node_map(
        nodes,
        {
            n.name: fc.list_pods_on_node(n.name)
            for n in list(nodes) + list(unready)
        },
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
        unready_nodes=unready,
    )
    return pack_cluster(node_map, fc.pdbs, resources=resources, **kw)
