"""Persistent pipelined wire transport tests (service/agent.py
PooledWireTransport / _WireSocket): keep-alive semantics, pipelining,
the stale-socket retry-once contract, pool bounds, and the chaos
half-closed-socket fault. The strict reuse/latency acceptance runs as
``make serve-smoke`` (bench.serve_smoke)."""

import contextlib
import http.client
import socket
import threading
import time

import pytest

from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.service.agent import (
    PooledWireTransport,
    RemoteCallError,
    RemotePlanner,
)
from k8s_spot_rescheduler_tpu.service.chaos import (
    ChaosAgentTransport,
    ServiceFaultPlan,
)
from k8s_spot_rescheduler_tpu.service.server import ServiceServer
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.test_service import _observation


class EchoServer:
    """Minimal HTTP/1.1 keep-alive echo server: every accepted
    connection is served on its own thread, replies strictly in request
    order (the pipelining contract the pool relies on).
    ``first_reply_delay_s`` stalls each connection's FIRST reply so a
    pipelined second request can demonstrably queue behind it."""

    def __init__(self, first_reply_delay_s: float = 0.0):
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(16)
        self.port = self.sock.getsockname()[1]
        self.url = f"http://127.0.0.1:{self.port}/echo"
        self.first_reply_delay_s = first_reply_delay_s
        self.connections = 0
        self.requests = 0
        self._lock = threading.Lock()
        self._closing = False
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with self._lock:
                self.connections += 1
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn):
        rfile = conn.makefile("rb")
        served = 0
        try:
            while True:
                line = rfile.readline(65536)
                if not line or b"HTTP" not in line:
                    return
                headers = http.client.parse_headers(rfile)
                body = rfile.read(int(headers.get("Content-Length", 0)))
                with self._lock:
                    self.requests += 1
                if served == 0 and self.first_reply_delay_s:
                    time.sleep(self.first_reply_delay_s)
                served += 1
                conn.sendall(
                    b"HTTP/1.1 200 OK\r\nContent-Length: "
                    + str(len(body)).encode()
                    + b"\r\n\r\n"
                    + body
                )
        except (OSError, ValueError):
            return
        finally:
            with contextlib.suppress(Exception):
                rfile.close()
            with contextlib.suppress(Exception):
                conn.close()

    def close(self):
        self.sock.close()


def test_keep_alive_reuse_one_socket():
    """N sequential requests to one endpoint ride ONE socket: N-1
    reuses counted, one server-side accept, payloads intact."""
    srv = EchoServer()
    pool = PooledWireTransport()
    before = metrics.service_snapshot()["wire_connection_reuse"]
    try:
        for i in range(10):
            out = pool(srv.url, b"tick-%d" % i, {}, 5.0)
            assert out == b"tick-%d" % i
        assert pool.connection_count() == 1
        assert srv.connections == 1
        assert srv.requests == 10
        after = metrics.service_snapshot()["wire_connection_reuse"]
        assert after - before == 9
    finally:
        pool.close()
        srv.close()


def test_pipelined_second_request_queues_behind_first():
    """A second request issued while the first reply is still in
    flight goes onto the SAME socket (ticketed pipelining), not a
    second connection — and both replies come back to their callers."""
    srv = EchoServer(first_reply_delay_s=0.8)
    pool = PooledWireTransport()
    results = {}

    def call(name):
        results[name] = pool(srv.url, name.encode(), {}, 5.0)

    try:
        t1 = threading.Thread(target=call, args=("one",))
        t1.start()
        # wait until the first request is ON the wire (server saw it;
        # its reply is now stalled by first_reply_delay_s)
        deadline = time.monotonic() + 2.0
        while srv.requests < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert srv.requests == 1
        conn = pool.connection_for(srv.url)
        assert conn is not None
        t2 = threading.Thread(target=call, args=("two",))
        t2.start()
        # the second request must go out on the SAME pooled socket
        # while reply #1 is still stalled server-side — watch the
        # connection's send counter, not the server's (the server
        # reads a connection's requests sequentially)
        deadline = time.monotonic() + 2.0
        while conn.requests < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert conn.requests == 2, "second request did not pipeline"
        assert srv.connections == 1  # no second socket fanned out
        assert not conn.idle  # both replies still in flight
        t1.join(5.0)
        t2.join(5.0)
        assert results == {"one": b"one", "two": b"two"}
        assert srv.connections == 1
        assert srv.requests == 2
        assert pool.connection_count() == 1
    finally:
        pool.close()
        srv.close()


def test_pool_bounded_under_concurrent_hammering():
    """MAX_CONNS_PER_ENDPOINT (=1) holds under concurrency: 6 threads
    x 5 requests share one socket; every payload returns intact."""
    srv = EchoServer()
    pool = PooledWireTransport()
    errors = []

    def hammer(t):
        for i in range(5):
            payload = b"t%d-%d" % (t, i)
            try:
                if pool(srv.url, payload, {}, 5.0) != payload:
                    errors.append((t, i, "payload mismatch"))
            except Exception as err:  # noqa: BLE001 — collected
                errors.append((t, i, repr(err)))

    try:
        threads = [
            threading.Thread(target=hammer, args=(t,)) for t in range(6)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(10.0)
        assert errors == []
        assert pool.connection_count() == 1
        assert srv.connections == 1
        assert srv.requests == 30
    finally:
        pool.close()
        srv.close()


def test_stale_socket_retries_once_on_fresh_connection():
    """The stale-retry contract: a pooled socket half-closed while idle
    (server restart / idle timeout between ticks) is discovered on the
    next request and retried exactly ONCE on a fresh socket —
    transparently (the caller sees a normal reply), counted in
    remote_wire_reconnects_total."""
    srv = EchoServer()
    pool = PooledWireTransport()
    before = metrics.service_snapshot()["wire_reconnects"]
    try:
        assert pool(srv.url, b"warm", {}, 5.0) == b"warm"
        assert pool.break_idle() == 1  # OS half-close, left pooled
        out = pool(srv.url, b"after-break", {}, 5.0)
        assert out == b"after-break"
        after = metrics.service_snapshot()["wire_reconnects"]
        assert after - before == 1
        # the retry ran on a FRESH socket (second server-side accept)
        assert srv.connections == 2
        conn = pool.connection_for(srv.url)
        assert conn is not None and conn.requests == 1
    finally:
        pool.close()
        srv.close()


def test_fresh_connection_failure_propagates_immediately():
    """Failures on a connection that never served traffic are NOT
    retried (nothing was stale — the endpoint is down): they propagate
    to the ladder as an endpoint failure at once."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    pool = PooledWireTransport()
    before = metrics.service_snapshot()["wire_reconnects"]
    with pytest.raises(OSError):
        pool(f"http://127.0.0.1:{port}/echo", b"x", {}, 1.0)
    assert metrics.service_snapshot()["wire_reconnects"] == before
    pool.close()


def test_connection_close_honored_on_drain_refuse():
    """A drain-refuse 503 rides ``Connection: close`` (the server's
    pre-body reject discipline): the pool must NOT keep that socket —
    the next request opens fresh."""
    cfg = ReschedulerConfig(solver="numpy", planner_timeout=5.0)
    server = ServiceServer(cfg, "127.0.0.1:0", batch_window_s=0.01)
    server.start_background()
    pool = PooledWireTransport()
    url = f"http://{server.address}/v2/plan"
    try:
        server.service.begin_drain()
        with pytest.raises(RemoteCallError) as exc:
            pool(url, b"irrelevant", {}, 5.0)
        assert "503" in str(exc.value)
        assert exc.value.retry_after > 0  # Retry-After parsed
        # the socket was discarded per the server's Connection: close
        assert pool.connection_for(url) is None
        assert pool.connection_count() == 0
    finally:
        pool.close()
        server.close()


def test_failback_reuses_primary_pooled_socket():
    """Reuse across failover return: after a failover tick served by
    the secondary, the primary's pooled socket is still warm — the
    failback tick rides THAT socket, not a fresh connect."""
    cfg = ReschedulerConfig(solver="numpy", planner_timeout=5.0)
    server_a = ServiceServer(cfg, "127.0.0.1:0", batch_window_s=0.01)
    server_b = ServiceServer(cfg, "127.0.0.1:0", batch_window_s=0.01)
    server_a.start_background()
    server_b.start_background()
    try:
        agent = RemotePlanner(
            cfg,
            f"http://{server_a.address},http://{server_b.address}",
            tenant="c1",
        )
        node_map, pdbs = _observation()
        r1 = agent.plan(node_map, pdbs)
        assert r1.solver == "remote"
        s_primary = agent._wire_pool.connection_for(
            f"http://{server_a.address}"
        )
        assert s_primary is not None

        # scripted 503 for the chaos wrapper's FIRST call (it is
        # installed after tick 1, so its call counter starts here):
        # raised ABOVE the pool, so the primary's pooled socket stays
        # warm while the ladder fails over to the secondary
        chaos = ChaosAgentTransport(
            agent.transport,
            ServiceFaultPlan(http_503_script=(1,), http_503_retry_after=0.5),
            pool=agent._wire_pool,
        )
        agent.transport = chaos
        before = metrics.service_snapshot()["remote_planner_failover"]
        r2 = agent.plan(node_map, pdbs)
        assert r2.solver == "remote"
        assert (
            metrics.service_snapshot()["remote_planner_failover"]
            == before + 1
        )
        assert agent._wire_pool.connection_count() == 2

        # failback: the primary's breaker window passes; the next tick
        # walks the ladder back to the primary and reuses ITS socket
        agent._endpoints[0].skip_until = 0.0
        reuse_before = metrics.service_snapshot()["wire_connection_reuse"]
        r3 = agent.plan(node_map, pdbs)
        assert r3.solver == "remote"
        assert (
            agent._wire_pool.connection_for(f"http://{server_a.address}")
            is s_primary
        )
        assert (
            metrics.service_snapshot()["wire_connection_reuse"]
            == reuse_before + 1
        )
        # selections identical throughout
        assert dict(r3.plan.assignments) == dict(r1.plan.assignments)
    finally:
        server_a.close()
        server_b.close()


def test_chaos_half_close_fault_zero_fallback_bit_identical():
    """The chaos half-closed-keep-alive-socket fault: the agent must
    absorb it with ONE transparent reconnect per strike — zero
    fallback, zero failover, selections bit-identical to the unfaulted
    ticks."""
    cfg = ReschedulerConfig(solver="numpy", planner_timeout=5.0)
    server = ServiceServer(cfg, "127.0.0.1:0", batch_window_s=0.01)
    server.start_background()
    try:
        agent = RemotePlanner(cfg, f"http://{server.address}", tenant="c1")
        chaos = ChaosAgentTransport(
            agent.transport,
            ServiceFaultPlan(half_close_script=(2, 4)),
            pool=agent._wire_pool,
        )
        agent.transport = chaos
        node_map, pdbs = _observation()
        before = metrics.service_snapshot()
        results = [agent.plan(node_map, pdbs) for _ in range(4)]
        after = metrics.service_snapshot()
        assert [r.solver for r in results] == ["remote"] * 4
        assert chaos.stats["half_close"] == 2
        assert after["wire_reconnects"] - before["wire_reconnects"] == 2
        assert (
            after["remote_planner_fallback"]
            == before["remote_planner_fallback"]
        )
        assert (
            after["remote_planner_failover"]
            == before["remote_planner_failover"]
        )
        want = dict(results[0].plan.assignments)
        for r in results[1:]:
            assert dict(r.plan.assignments) == want
    finally:
        server.close()
