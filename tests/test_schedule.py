"""Device-resident drain-to-exhaustion schedules (ISSUE 11).

The contract under test, layer by layer:

- **solver/schedule.py** — the ``lax.while_loop`` schedule program is
  BIT-identical to the host oracle loop, and every step equals an
  INDEPENDENT single solve of the committed state (the while-loop
  really does data-dependent re-solves, not an approximation);
- **planner/schedule.py + loop/controller.py** — executing a schedule
  through the real control loop frees exactly the nodes per-tick
  planning frees on a quiescent cluster, in <= ceil(drains/horizon)+2
  planner fetches; injected churn INVALIDATES the tail (flight event
  delta == metric delta) and the next tick re-plans — the schedule can
  never produce an eviction a fresh solve would refuse (every executed
  step is re-proven from scratch against the live pack);
- **service/wire.py + service/server.py + service/agent.py** — the
  KIND_PLAN_SCHEDULE wire path returns the identical schedule, and a
  replica death under a schedule in flight costs nothing until the
  next cut fails over (bench.sched_smoke is the shared acceptance
  core, exactly as serve_smoke/fleet_chaos_smoke are for theirs);
- **bench/chain_depth.py** — the classification instrument still sees
  schedule-executed drains through the ``on_packed`` tap.
"""

import dataclasses

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.bench.quality import (
    _HintingPlanner,
    drain_to_exhaustion,
    pack_quality,
)
from k8s_spot_rescheduler_tpu.io.synthetic import (
    QUALITY_CONFIGS,
    generate_quality_cluster,
)
from k8s_spot_rescheduler_tpu.loop import flight
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.solver.fallback import with_repair
from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_union_oracle
from k8s_spot_rescheduler_tpu.solver.schedule import (
    commit_step_host,
    decode_schedule,
    make_schedule_planner,
    plan_schedule_oracle,
)
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

SPEC_NAME, SPEC = next(iter(QUALITY_CONFIGS.items()))


def _quality_cfg(**kw):
    base = dict(
        solver="numpy", resources=SPEC.resources, node_drain_delay=0.0
    )
    base.update(kw)
    return ReschedulerConfig(**base)


# ---------------------------------------------------------------------------
# solver tier: device == oracle == stepwise


@pytest.mark.parametrize("seed", [0, 1])
def test_schedule_matrix_matches_oracle(seed):
    """The jitted while-loop schedule is bit-identical to the host
    oracle loop over the same union program, terminal probe row
    included."""
    packed = pack_quality(SPEC, seed)
    horizon = 6
    device = np.asarray(
        make_schedule_planner(with_repair(plan_ffd, 8), horizon)(packed)
    )
    oracle = plan_schedule_oracle(packed, horizon, repair_rounds=8)
    np.testing.assert_array_equal(device, oracle)


def test_schedule_steps_equal_independent_solves():
    """Step i of a schedule equals an INDEPENDENT fresh union solve of
    the state steps 0..i-1 committed — the while-loop's re-solves are
    real, not a one-shot ranking of the base solve."""
    packed = pack_quality(SPEC, 0)
    horizon = 5
    mat = np.asarray(
        make_schedule_planner(with_repair(plan_ffd, 8), horizon)(packed)
    )
    steps = decode_schedule(mat)
    assert steps, "quality config must yield at least one drain"
    cur = packed
    for step in steps:
        res = plan_union_oracle(cur, repair_rounds=8)
        feasible = np.asarray(res.feasible) & np.asarray(cur.cand_valid)
        assert feasible.any()
        idx = int(np.argmax(feasible))
        assert idx == step.index
        np.testing.assert_array_equal(
            np.asarray(res.assignment[idx], np.int32), step.row
        )
        assert int(feasible.sum()) == step.n_feasible
        cur = commit_step_host(cur, idx, step.row)
    # after the last recorded drain the committed state must solve to
    # the terminal verdict the matrix recorded (if within horizon)
    if len(steps) < horizon:
        res = plan_union_oracle(cur, repair_rounds=8)
        assert not (
            np.asarray(res.feasible) & np.asarray(cur.cand_valid)
        ).any()


def test_commit_step_host_depletes_exactly():
    packed = pack_quality(SPEC, 0)
    res = plan_union_oracle(packed, repair_rounds=8)
    feasible = np.asarray(res.feasible) & np.asarray(packed.cand_valid)
    idx = int(np.argmax(feasible))
    row = np.asarray(res.assignment[idx], np.int32)
    after = commit_step_host(packed, idx, row)
    assert not bool(after.cand_valid[idx])
    placed = [
        (k, int(s)) for k, s in enumerate(row)
        if s >= 0 and packed.slot_valid[idx, k]
    ]
    assert placed
    for k, s in placed:
        assert np.all(
            after.spot_free[s] <= packed.spot_free[s]
        )
    delta_count = np.asarray(after.spot_count) - np.asarray(
        packed.spot_count
    )
    assert int(delta_count.sum()) == len(placed)


# ---------------------------------------------------------------------------
# controller tier: parity, fetch bound, invalidation


def test_exhaustion_parity_and_fetch_bound():
    """Schedule-mode exhaustion frees the same number of nodes as
    per-tick planning on the quiescent quality cluster, with planner
    fetches <= ceil(drains / horizon) + 2 and zero invalidations."""
    import math

    horizon = 4
    base_cfg = _quality_cfg(max_drains_per_tick=64)
    drains_base = drain_to_exhaustion(
        generate_quality_cluster(SPEC, 0, reschedule_evicted=True),
        base_cfg,
    )
    inv0 = metrics.robustness_snapshot()["schedule_invalidated"]
    stats = {}
    drains_sched = drain_to_exhaustion(
        generate_quality_cluster(SPEC, 0, reschedule_evicted=True),
        dataclasses.replace(
            base_cfg, plan_schedule_enabled=True, schedule_horizon=horizon
        ),
        planner_stats=stats,
    )
    assert drains_sched == drains_base
    assert stats["fetches_total"] <= math.ceil(drains_sched / horizon) + 2
    assert sum(stats["schedule_lens"]) == drains_sched
    assert (
        metrics.robustness_snapshot()["schedule_invalidated"] - inv0 == 0
    )


def test_schedule_report_fields_and_span():
    """A schedule-served tick's PlanReport carries schedule_len/
    schedule_step and the tick trace holds the plan.schedule span on
    the cutting tick only."""
    cfg = _quality_cfg(
        plan_schedule_enabled=True, schedule_horizon=8,
        max_drains_per_tick=1,
    )
    client = generate_quality_cluster(SPEC, 0, reschedule_evicted=True)
    inner = SolverPlanner(cfg)
    r = Rescheduler(
        client, _HintingPlanner(inner, client), cfg,
        clock=client.clock, recorder=client,
    )
    client.clock.advance(1)
    first = r.tick()
    assert first.drained
    assert first.report.schedule_len >= 2
    assert first.report.schedule_step == 0
    assert first.report.solver.endswith("+schedule")
    cut_tick = flight.RECORDER.last_tick()
    names = set()
    stack = list(cut_tick["trace"]["spans"])
    while stack:
        sp = stack.pop()
        names.add(sp["name"])
        stack.extend(sp.get("spans", ()))
    assert "plan.schedule" in names
    # next tick serves step 1 from the PENDING schedule: no new cut
    fetches = inner.fetches_total
    client.clock.advance(1)
    second = r.tick()
    assert second.drained
    assert second.report.schedule_step == 1
    assert inner.fetches_total == fetches  # no fetch — the O(1) claim


def test_churn_invalidates_not_diverges():
    """Injected churn under a pending schedule invalidates the tail —
    flight delta == metric delta — and the next tick re-plans and
    drains; no step ever executes against diverged state."""
    cfg = _quality_cfg(
        plan_schedule_enabled=True, schedule_horizon=8,
        max_drains_per_tick=1,
    )
    client = generate_quality_cluster(SPEC, 0, reschedule_evicted=True)
    inner = SolverPlanner(cfg)
    r = Rescheduler(
        client, _HintingPlanner(inner, client), cfg,
        clock=client.clock, recorder=client,
    )
    m0 = metrics.robustness_snapshot()["schedule_invalidated"]
    f0 = flight.RECORDER.counts().get("schedule-invalidated", 0)
    client.clock.advance(1)
    assert r.tick().drained
    # churn: a spot node vanishes under the pending schedule
    spot = next(
        n for n in client.nodes.values()
        if any("spot" in f"{k}={v}" for k, v in n.labels.items())
    )
    client.remove_node(spot.name)
    client.clock.advance(1)
    result = r.tick()
    m_delta = metrics.robustness_snapshot()["schedule_invalidated"] - m0
    f_delta = flight.RECORDER.counts().get("schedule-invalidated", 0) - f0
    assert m_delta == 1
    assert f_delta == m_delta  # the two surfaces never diverge
    events = flight.RECORDER.events("schedule-invalidated")
    assert events and events[-1]["cause"]
    # the re-plan still drained (correctness survived the churn)
    assert result.drained


def test_zero_step_schedule_reports_no_drain():
    """A cluster with nothing drainable cuts a zero-step schedule and
    the tick reports a coherent no-drain PlanReport."""
    cfg = _quality_cfg(plan_schedule_enabled=True, schedule_horizon=4)
    client = generate_quality_cluster(SPEC, 0, reschedule_evicted=True)
    # exhaust it first
    drains = drain_to_exhaustion(client, cfg)
    assert drains > 0
    inner = SolverPlanner(cfg)
    r = Rescheduler(
        client, _HintingPlanner(inner, client), cfg,
        clock=client.clock, recorder=client,
    )
    client.clock.advance(1)
    result = r.tick()
    assert result.drained == []
    assert result.report is not None
    assert result.report.plan is None
    assert result.report.schedule_len == 0


def test_schedule_enabled_by_default_with_horizon_zero_opt_out():
    """Schedules are ON by default (the PR-11 follow-up: quality-scale
    asserts the fetch bound with them live); ``--schedule-horizon 0``
    is the documented opt-out — no schedule is ever cut under it."""
    assert ReschedulerConfig().plan_schedule_enabled is True
    assert ReschedulerConfig().schedule_horizon == 32
    cfg = _quality_cfg(max_drains_per_tick=1, schedule_horizon=0)
    client = generate_quality_cluster(SPEC, 0, reschedule_evicted=True)
    inner = SolverPlanner(cfg)
    r = Rescheduler(
        client, _HintingPlanner(inner, client), cfg,
        clock=client.clock, recorder=client,
    )
    client.clock.advance(1)
    result = r.tick()
    assert result.report.schedule_len == 0
    assert result.report.schedule_step == -1
    assert inner.schedule_lens == []


def test_corrupt_step_index_invalidates_not_misdrains():
    """A schedule step whose index is outside the base pack (a
    corrupted-but-decodable wire reply) must INVALIDATE — counted and
    re-planned — never negative-index into the candidate list and
    drain a node the planner never elected."""
    from k8s_spot_rescheduler_tpu.planner.schedule import DrainSchedule
    from k8s_spot_rescheduler_tpu.solver.schedule import ScheduleStep

    cfg = _quality_cfg(plan_schedule_enabled=True, schedule_horizon=4)
    client = generate_quality_cluster(SPEC, 0, reschedule_evicted=True)
    planner = SolverPlanner(cfg)
    store = client.columnar_store(
        cfg.resources,
        on_demand_label=cfg.on_demand_node_label,
        spot_label=cfg.spot_node_label,
    )
    pdbs = client.list_pdbs()
    packed, meta = planner._pack_observation(store, pdbs)
    K = packed.slot_req.shape[1]
    bad = DrainSchedule(
        [ScheduleStep(index=-1, n_feasible=1, row=np.full(K, -1, np.int32))],
        packed, meta,
        pack_fn=planner._pack_observation,
        solver_label="numpy+schedule", horizon=4,
        base_observation=store,
    )
    assert bad.next_plan(store, pdbs) is None
    assert bad.invalidated
    assert "outside" in bad.invalid_reason


# ---------------------------------------------------------------------------
# chain-depth ride-along: the instrument still sees schedule drains


def test_chain_depth_sees_schedule_executed_drains():
    from k8s_spot_rescheduler_tpu.bench.chain_depth import _PackedTap

    tap = _PackedTap()
    # one drain per tick, exactly how bench/chain_depth.analyze_quality_
    # runs drives its taps — each tick's final pack still holds the
    # not-yet-drained lanes for classification
    cfg = _quality_cfg(
        plan_schedule_enabled=True, schedule_horizon=4,
        max_drains_per_tick=1,
    )
    drains = drain_to_exhaustion(
        generate_quality_cluster(SPEC, 0, reschedule_evicted=True),
        cfg,
        on_packed=tap,
    )
    assert drains > 0
    assert tap.ticks > 0
    total = sum(tap.counts.values())
    assert total > 0  # classified lanes from schedule-executed ticks
    # the drains the schedule executed were greedy/repair-provable
    # lanes — the instrument classifies them like any per-tick drain
    assert tap.counts.get("greedy", 0) > 0


# ---------------------------------------------------------------------------
# service + failover tier: the shared acceptance core


def test_sched_smoke_core():
    """The full acceptance core `make sched-smoke` runs: local parity +
    fetch bound, churn invalidation parity, wire bit-identity through a
    real ServiceServer, and failover with a schedule in flight."""
    import bench

    stats, violations = bench.sched_smoke(seed=0)
    assert violations == []
    assert stats["drains"] == stats["drains_per_tick_baseline"]
    assert stats["fetches_total"] <= stats["fetch_bound"]


# ---------------------------------------------------------------------------
# CLI: the new knobs flow into config


def test_schedule_flags_flow_into_config():
    from k8s_spot_rescheduler_tpu.cli.main import (
        build_parser,
        config_from_args,
    )

    args = build_parser().parse_args(
        ["--plan-schedule-enabled", "true", "--schedule-horizon", "16"]
    )
    cfg = config_from_args(args)
    assert cfg.plan_schedule_enabled is True
    assert cfg.schedule_horizon == 16
    # 0 = the documented opt-out (schedules off); negatives stay invalid
    assert ReschedulerConfig(schedule_horizon=0).schedule_horizon == 0
    with pytest.raises(ValueError):
        ReschedulerConfig(schedule_horizon=-1)


def test_schedule_churn_hysteresis_accounting():
    """Default-on follow-up: a schedule churn kills before it served 2
    steps (with a meaningful unserved tail) opens a doubling per-tick
    backoff window, capped; one that served >= 2 steps resets it; a
    short schedule (< 2 unserved steps wasted) never backs off."""

    class _S:
        def __init__(self, cursor, n):
            self.cursor = cursor
            self.steps = [None] * n

    r = Rescheduler.__new__(Rescheduler)  # accounting only, no loop
    r._sched_backoff = 0
    r._sched_backoff_next = 1
    r._note_schedule_outcome(_S(1, 32))
    assert (r._sched_backoff, r._sched_backoff_next) == (1, 2)
    r._note_schedule_outcome(_S(0, 32))
    assert (r._sched_backoff, r._sched_backoff_next) == (2, 4)
    for _ in range(10):
        r._note_schedule_outcome(_S(1, 32))
    assert r._sched_backoff_next == 64  # capped
    r._note_schedule_outcome(_S(2, 32))  # paid for its cut
    assert (r._sched_backoff, r._sched_backoff_next) == (0, 1)
    r._note_schedule_outcome(_S(1, 2))  # tiny waste: stay schedule-happy
    assert (r._sched_backoff, r._sched_backoff_next) == (0, 1)
