"""Spot-chunked repair tests (solver/repair.plan_repair_chunked).

The elect-then-commit chunked search must be BIT-identical to the
unchunked repair solver and its serial oracle — same partial pass,
rotation, chain election, exact affinity gates, validation — while its
per-round working set is O(S / chunks). That identity is what lets the
cand-only sharding tier carry repair past the unchunked per-device
ceiling (parallel/sharded_ffd.plan_union_cand_sharded
``repair_spot_chunks``; dispatch in planner/solver_planner._maybe_shard,
sized by solver/memory.pick_repair_chunks).

Fixtures are self-contained rather than imported from tests/test_repair:
that module's import chain needs hypothesis, which not every build image
ships.
"""

import dataclasses

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster
from k8s_spot_rescheduler_tpu.solver import memory
from k8s_spot_rescheduler_tpu.solver.repair import (
    plan_repair_chunked_jit,
    plan_repair_jit,
    plan_repair_oracle,
)
from tests.test_solver import _random_packed


def _swap_case() -> PackedCluster:
    """tests/test_repair._swap_case: greedy fails, one depth-1
    relocation (eject b, b -> n1, c -> n0) fixes the lane."""
    A = 2
    return PackedCluster(
        slot_req=np.array([[[6.0], [5.0], [5.0]]], np.float32),
        slot_valid=np.ones((1, 3), bool),
        slot_tol=np.array([[[1], [1], [0]]], np.uint32),
        slot_aff=np.zeros((1, 3, A), np.uint32),
        cand_valid=np.ones((1,), bool),
        spot_free=np.array([[11.0], [5.0]], np.float32),
        spot_count=np.zeros((2,), np.int32),
        spot_max_pods=np.full((2,), 10, np.int32),
        spot_taints=np.array([[0], [1]], np.uint32),
        spot_ok=np.ones((2,), bool),
        spot_aff=np.zeros((2, A), np.uint32),
    )


def _affinity_swap_case() -> PackedCluster:
    """tests/test_repair._affinity_swap_case: only the exact affinity
    ejection (clearing T's group bit from n0) unlocks the lane."""
    A = 2
    group = np.array([2, 0], np.uint32)
    return PackedCluster(
        slot_req=np.array([[[8.0], [7.0]]], np.float32),
        slot_valid=np.ones((1, 2), bool),
        slot_tol=np.array([[[1], [0]]], np.uint32),
        slot_aff=np.array([[group, group]], np.uint32),
        cand_valid=np.ones((1,), bool),
        spot_free=np.array([[9.0], [10.0]], np.float32),
        spot_count=np.zeros((2,), np.int32),
        spot_max_pods=np.full((2,), 10, np.int32),
        spot_taints=np.array([[0], [1]], np.uint32),
        spot_ok=np.ones((2,), bool),
        spot_aff=np.zeros((2, A), np.uint32),
    )


def _chain2_interlock_case() -> PackedCluster:
    """tests/test_repair._rotation_coverage_case: the two-pod interlock
    only the depth-2 CHAIN with the off-diagonal (q0, r1) pairing
    solves (p -> n0, q0 -> n3, r1 -> n4)."""
    A = 2
    TA, TB, TC = 1, 2, 4
    return PackedCluster(
        slot_req=np.array(
            [[[10.0], [10.0], [10.0], [10.0], [6.0]]], np.float32
        ),
        slot_valid=np.ones((1, 5), bool),
        slot_tol=np.array(
            [[[TA], [TC], [TA], [TA | TB], [TC]]], np.uint32
        ),
        slot_aff=np.zeros((1, 5, A), np.uint32),
        cand_valid=np.ones((1,), bool),
        spot_free=np.array(
            [[10.0], [10.0], [10.0], [10.0], [20.0]], np.float32
        ),
        spot_count=np.zeros((5,), np.int32),
        spot_max_pods=np.full((5,), 10, np.int32),
        spot_taints=np.array([[0], [TC], [TA], [TA], [TB]], np.uint32),
        spot_ok=np.ones((5,), bool),
        spot_aff=np.zeros((5, A), np.uint32),
    )


@pytest.mark.parametrize("chunks", [2, 3, 5])
@pytest.mark.parametrize(
    "case", [_swap_case, _affinity_swap_case, _chain2_interlock_case]
)
def test_chunked_fixture_parity(case, chunks):
    """Depth-1 swap, affinity-ejection and chain-2 interlock fixtures:
    chunked repair proves and places them bit-identically to the serial
    oracle at every chunking (including chunks > S: all-padding chunks
    are inert)."""
    packed = case()
    want = plan_repair_oracle(packed)
    assert bool(want.feasible[0])
    got = plan_repair_chunked_jit(packed, spot_chunks=chunks)
    np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
    np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)


@pytest.mark.parametrize("seed", range(30))
def test_chunked_oracle_parity_randomized(seed):
    """Randomized clusters at >= 3 spot chunks: bit parity with the
    serial oracle (feasibility AND placements)."""
    packed = _random_packed(np.random.default_rng(4000 + seed))
    chunks = 3 + seed % 3
    want = plan_repair_oracle(packed)
    got = plan_repair_chunked_jit(packed, spot_chunks=chunks)
    np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
    np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)


def test_chunked_matches_unchunked_at_scale_with_poisoned_lane():
    """Config-2-scale columnar pack (real shapes: selectors, taints,
    groups), with one lane POISONED infeasible (a pod no spot node can
    hold): chunked and unchunked device repair must agree bit for bit,
    and the poisoned lane proves the verdict still discriminates."""
    from k8s_spot_rescheduler_tpu.bench.quality import pack_quality
    from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS

    packed = pack_quality(CONFIGS[2], 0)
    cv = np.asarray(packed.cand_valid)
    sv = np.asarray(packed.slot_valid)
    c = int(np.flatnonzero(cv)[0])
    slot_req = np.array(packed.slot_req)
    slot_req[c, int(np.argmax(sv[c])), :] = 1e9
    packed = packed._replace(slot_req=slot_req)

    want = plan_repair_jit(packed)
    got = plan_repair_chunked_jit(packed, spot_chunks=4)
    w_f = np.asarray(want.feasible)
    assert not w_f[c]  # poisoned lane infeasible by construction
    assert w_f.any()  # ...while others remain feasible: discriminating
    np.testing.assert_array_equal(np.asarray(got.feasible), w_f)
    np.testing.assert_array_equal(
        np.asarray(got.assignment), np.asarray(want.assignment)
    )


def test_poisoned_lane_oracle_parity():
    """The poisoned-infeasible verdict also matches the serial oracle
    (small fixture, full-depth check): a monster pod's lane reports
    infeasible under every chunking while the clean lane repairs."""
    one = _swap_case()
    packed = PackedCluster(
        slot_req=np.concatenate(
            [one.slot_req, np.full((1, 3, 1), 1e9, np.float32)]
        ),
        slot_valid=np.concatenate([one.slot_valid, one.slot_valid]),
        slot_tol=np.concatenate([one.slot_tol, one.slot_tol]),
        slot_aff=np.concatenate([one.slot_aff, one.slot_aff]),
        cand_valid=np.ones((2,), bool),
        spot_free=one.spot_free,
        spot_count=one.spot_count,
        spot_max_pods=one.spot_max_pods,
        spot_taints=one.spot_taints,
        spot_ok=one.spot_ok,
        spot_aff=one.spot_aff,
    )
    want = plan_repair_oracle(packed)
    assert bool(want.feasible[0]) and not bool(want.feasible[1])
    for chunks in (2, 3):
        got = plan_repair_chunked_jit(packed, spot_chunks=chunks)
        np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
        np.testing.assert_array_equal(
            np.asarray(got.assignment), want.assignment
        )


def test_chunks_of_one_delegates_to_unchunked():
    packed = _swap_case()
    got = plan_repair_chunked_jit(packed, spot_chunks=1)
    want = plan_repair_jit(packed)
    np.testing.assert_array_equal(
        np.asarray(got.feasible), np.asarray(want.feasible)
    )
    np.testing.assert_array_equal(
        np.asarray(got.assignment), np.asarray(want.assignment)
    )


# --- cand-sharded union with chunked repair --------------------------------


def test_cand_sharded_union_chunked_repair_parity():
    """The cand-only layout with ``repair_spot_chunks`` > 1 still runs
    the COMPLETE union program per lane block: a greedy-unprovable lane
    must repair bit-identically to the host oracle."""
    from k8s_spot_rescheduler_tpu.parallel.mesh import make_cand_mesh
    from k8s_spot_rescheduler_tpu.parallel.sharded_ffd import (
        plan_union_cand_sharded,
    )
    from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle

    packed = _swap_case()
    assert not plan_oracle(packed).feasible[0]
    mesh = make_cand_mesh()
    got = plan_union_cand_sharded(
        mesh, packed, rounds=8, repair_spot_chunks=3
    )
    want = plan_repair_oracle(packed)
    assert bool(np.asarray(got.feasible)[0])
    np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
    np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)


# --- chunk sizing + dispatch ----------------------------------------------


def test_pick_repair_chunks_thresholds():
    """1 below the unchunked estimate, the smallest sufficient power of
    two between the chunked estimates, 0 when even full chunking cannot
    fit — the regime repair_unavailable alarms on."""
    shapes = (2560, 32, 2560, 4, 2, 2)
    e1 = memory.estimate_union_hbm_bytes(*shapes)
    e2 = memory.estimate_union_hbm_bytes(*shapes, repair_spot_chunks=2)
    e4 = memory.estimate_union_hbm_bytes(*shapes, repair_spot_chunks=4)
    assert e4 < e2 < e1
    assert memory.pick_repair_chunks(*shapes, budget_bytes=e1) == 1
    assert memory.pick_repair_chunks(*shapes, budget_bytes=(e1 + e2) // 2) == 2
    assert memory.pick_repair_chunks(*shapes, budget_bytes=(e2 + e4) // 2) == 4
    assert memory.pick_repair_chunks(*shapes, budget_bytes=1) == 0
    # tiny spot axes cannot chunk below the lane width: unchunked or bust
    assert memory.pick_repair_chunks(4, 4, 64, 2, 1, 2, budget_bytes=1) == 0
    # boundary: S=255 CAN chunk to 2 — Sc = ceil(255/2) = 128, exactly
    # the minimum width (a floor(S/128) cap would wrongly return 0 here
    # and drop repair)
    s255 = (2560, 32, 255, 4, 2, 2)
    b255 = (
        memory.estimate_union_hbm_bytes(*s255)
        + memory.estimate_union_hbm_bytes(*s255, repair_spot_chunks=2)
    ) // 2
    assert memory.pick_repair_chunks(*s255, budget_bytes=b255) == 2
    # repair_spot_chunks=0 models a repair-LESS program: its estimate
    # sits strictly below any chunking (the working set never allocates)
    assert memory.estimate_union_hbm_bytes(
        *shapes, repair_spot_chunks=0
    ) < memory.estimate_union_hbm_bytes(*shapes, repair_spot_chunks=1024)


def _chunk_scale_cluster():
    """A synthetic cluster whose packed spot axis is wide enough
    (>= 2 x MIN_REPAIR_CHUNK) for the picker to chunk."""
    from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS, generate_cluster
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    spec = dataclasses.replace(
        CONFIGS[2],
        name="chunk-dispatch",
        n_on_demand=48,
        n_spot=280,
        n_pods=1200,
    )
    cfg = ReschedulerConfig(resources=spec.resources)
    client = generate_cluster(spec, 0)
    store = client.columnar_store(
        cfg.resources,
        on_demand_label=cfg.on_demand_node_label,
        spot_label=cfg.spot_node_label,
    )
    return spec, store, client.list_pdbs()


def _solver_mode_samples():
    from k8s_spot_rescheduler_tpu.metrics import registry as metrics

    return {
        (s.labels["configured"], s.labels["running"]): s.value
        for s in metrics.solver_mode.collect()[0].samples
        if s.value
    }


def _gauge(g):
    return g.collect()[0].samples[0].value


def test_planner_dispatches_chunked_repair_between_ceilings():
    """Budget between the unchunked and 2-chunk lane estimates: the
    planner must land on the cand tier WITH chunked repair —
    repair_unavailable stays 0, solver_repair_chunks reads the count,
    and the drain verdicts match the host oracle stack exactly."""
    from k8s_spot_rescheduler_tpu.metrics import registry as metrics
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    spec, store, pdbs = _chunk_scale_cluster()
    cfg0 = ReschedulerConfig(resources=spec.resources)
    packed, _ = store.pack(
        pdbs,
        priority_threshold=cfg0.priority_threshold,
        pad_slots=cfg0.max_pods_per_node_hint,
    )
    C, K, S, R, W, A = memory.packed_shapes(packed)
    assert S >= 2 * memory.MIN_REPAIR_CHUNK
    lane = -(-C // 8)
    e1 = memory.estimate_union_hbm_bytes(lane, K, S, R, W, A)
    e2 = memory.estimate_union_hbm_bytes(
        lane, K, S, R, W, A, repair_spot_chunks=2
    )
    assert e2 < e1
    budget = (e1 + e2) // 2

    planner = SolverPlanner(
        ReschedulerConfig(
            solver="jax",
            resources=spec.resources,
            solver_hbm_budget=int(budget),
        )
    )
    report = planner.plan(store, pdbs)
    assert report.solver == "jax+cand-sharded"
    assert report.repair_chunks == 2
    assert _solver_mode_samples() == {("jax", "jax+cand-sharded"): 1.0}
    assert _gauge(metrics.repair_unavailable) == 0.0
    assert _gauge(metrics.solver_repair_chunks) == 2.0

    want = SolverPlanner(
        ReschedulerConfig(solver="numpy", resources=spec.resources)
    ).plan(store, pdbs)
    assert report.n_feasible == want.n_feasible
    if want.plan is not None:
        assert report.plan is not None
        assert report.plan.node.node.name == want.plan.node.node.name
        assert report.plan.assignments == want.plan.assignments


def test_planner_drops_repair_only_past_chunked_ceiling():
    """A budget below even the fully-chunked lane estimate is the ONLY
    regime that reaches the 2-D tier: repair_unavailable fires there
    (and nowhere earlier), solver_repair_chunks reads 0."""
    from k8s_spot_rescheduler_tpu.metrics import registry as metrics
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    spec, store, pdbs = _chunk_scale_cluster()
    planner = SolverPlanner(
        ReschedulerConfig(
            solver="jax", resources=spec.resources, solver_hbm_budget=1
        )
    )
    report = planner.plan(store, pdbs)
    assert report.solver == "jax+sharded"
    assert report.repair_chunks == 0
    assert _solver_mode_samples() == {("jax", "jax+sharded"): 1.0}
    assert _gauge(metrics.repair_unavailable) == 1.0
    assert _gauge(metrics.solver_repair_chunks) == 0.0
