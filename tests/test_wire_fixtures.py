"""Wire-shape fixture replay of apiserver payloads (round 4, VERDICT r3
missing #5; round 5 adds the watch path and the widened-selector pod).

tests/data/wire_cluster.json holds a small EKS-style cluster in FULL
apiserver wire shapes — hand-authored to wire fidelity (metadata noise:
uid, resourceVersion, managedFields, kubectl annotations; complete
container specs with probes/ports/env/volumeMounts; the default
tolerations the admission chain injects; kubelet-labeled nodes with
full status blocks), NOT a capture from a live cluster — the best
offline stand-in available here. It carries a control-plane node, a
mirror pod, a DaemonSet pod, a StatefulSet pod with a Bound zonal EBS
volume, a Deployment with real topologySpreadConstraints, and a
round-5 pod using the widened selector operators. The suite proves:

1. both decode paths (Python and the native C++ engine) agree on every
   pod, field for field, at wire-shape fidelity;
2. a full observe → plan → drain tick over real HTTP against these
   payloads makes the RIGHT decision: the worker drains, the DaemonSet
   pod stays, and the PV's zone affinity steers the database to the
   only same-zone spot node;
3. the DEFAULT kube-mode path — list-then-watch
   (`WatchingKubeClusterClient` + `ColumnarFeed`) — reaches the
   identical drain decision from the same payloads streamed as watch
   events (ADDED/MODIFIED/DELETED, BOOKMARK, a 410-Gone re-list), with
   object-vs-columnar tensor parity throughout.

The reference is exercised against real clusters by its users; its own
tests are unit-only (reference CONTRIBUTING.md:22-25) — this fixture is
the offline stand-in for that integration surface.
"""

import json
import os

import pytest

from k8s_spot_rescheduler_tpu.io.kube import (
    KubeClusterClient,
    decode_node,
    decode_pdb,
    decode_pod,
)
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.test_kube import StubApiserver

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "wire_cluster.json")

OD = "ip-10-0-1-17.ec2.internal"
SPOT_1B = "ip-10-0-2-41.ec2.internal"
SPOT_1A = "ip-10-0-3-99.ec2.internal"
CONTROL_PLANE = "ip-10-0-0-5.ec2.internal"


def _fixture():
    with open(FIXTURE) as f:
        return json.load(f)


def _config():
    return ReschedulerConfig(
        solver="numpy",
        resources=("cpu", "memory"),
        pod_eviction_timeout=5.0,
        eviction_retry_time=1.0,
    )


def test_wire_node_decode():
    data = _fixture()
    nodes = {n["metadata"]["name"]: decode_node(n) for n in data["nodes"]}
    od = nodes[OD]
    assert od.ready and not od.unschedulable
    assert od.allocatable["cpu"] == 3920  # "3920m"
    assert od.allocatable["pods"] == 58
    assert od.labels["topology.kubernetes.io/zone"] == "us-east-1a"
    spot = nodes[SPOT_1B]
    assert [t.key for t in spot.taints] == ["cloud.provider/spot"]
    cp = nodes[CONTROL_PLANE]
    assert cp.ready  # unclassified but visible (NodeMap.other)


def test_wire_pod_decode_surface():
    data = _fixture()
    pods = {p["metadata"]["name"]: decode_pod(p) for p in data["pods"]}

    web = pods["web-6d4b75cb6d-hx8vq"]
    # soft zone constraint dropped; hard hostname constraint modeled
    assert web.spread_constraints == (
        ("kubernetes.io/hostname", 2, (("app", "In", ("web",)),)),
    )
    assert not web.unmodeled_constraints
    assert web.requests["cpu"] == 500

    api = pods["api-7f8d9c5b44-qm2zn"]
    # matchExpressions single-value In ≡ a matchLabels pair (round-5
    # canonical terms)
    assert api.anti_affinity_match == (
        (("shop",), (("app", "In", ("api",)),)),
    )
    assert not api.unmodeled_constraints

    audit = pods["audit-7c9d0e1f2a-k8s2x"]
    # round-5 widened shapes on the wire: multi-value In, a second
    # hostname term with an Exists selector scoped cross-namespace,
    # and a hard spread whose selector uses NotIn + Exists
    assert audit.anti_affinity_match == (
        (("payments", "shop"),
         (("security.example.com/sensitive", "Exists", ()),)),
        (("shop",), (("app", "In", ("audit", "audit-canary")),)),
    )
    assert audit.spread_constraints == (
        ("kubernetes.io/hostname", 3,
         (("app", "NotIn", ("api", "web")),
          ("pod-template-hash", "Exists", ()))),
    )
    assert not audit.unmodeled_constraints

    fluent = pods["fluent-bit-x2lwp"]
    assert fluent.is_daemonset()
    # matchFields metadata.name node affinity is modeled
    assert fluent.node_affinity and not fluent.unmodeled_constraints

    pg = pods["pg-0"]
    assert pg.pvc_names == ("data-pg-0",)
    assert pg.pvc_resolvable  # decode defers to the volume resolver
    assert pg.unmodeled_constraints  # until the PV resolves

    mirror = pods["kube-apiserver-" + CONTROL_PLANE]
    assert mirror.is_mirror()

    job = pods["worker-9t5kd"]
    assert job.phase == "Succeeded"

    bare = pods["debug-shell"]
    assert bare.controller_ref() is None  # non-replicated

    pdb = decode_pdb(data["pdbs"][0])
    assert pdb.match_labels == (("app", "In", ("web",)),)
    assert pdb.disruptions_allowed == 1


def test_wire_native_decode_lockstep():
    from k8s_spot_rescheduler_tpu.io import native_ingest

    if not native_ingest.available():
        pytest.skip("native library unavailable")
    data = _fixture()
    body = json.dumps(
        {"metadata": {"resourceVersion": "8812345"}, "items": data["pods"]}
    ).encode()
    batch = native_ingest.parse_pod_list(body)
    assert batch is not None and batch.count == len(data["pods"])
    for i, obj in enumerate(data["pods"]):
        want = decode_pod(obj)
        got = batch.view(i)
        name = obj["metadata"]["name"]
        assert got.name == want.name, name
        assert got.namespace == want.namespace, name
        assert got.node_name == want.node_name, name
        assert got.requests == {
            k: v for k, v in want.requests.items() if v
        }, name
        assert got.priority == want.priority, name
        assert tuple(got.tolerations) == tuple(want.tolerations), name
        assert got.node_selector == want.node_selector, name
        assert got.anti_affinity_match == want.anti_affinity_match, name
        assert (
            got.anti_affinity_zone_match == want.anti_affinity_zone_match
        ), name
        assert got.pod_affinity_match == want.pod_affinity_match, name
        assert got.node_affinity == want.node_affinity, name
        assert got.spread_constraints == want.spread_constraints, name
        assert tuple(got.pvc_names) == tuple(want.pvc_names), name
        assert got.pvc_resolvable == want.pvc_resolvable, name
        assert got.unmodeled_constraints == want.unmodeled_constraints, name
        assert got.is_mirror() == want.is_mirror(), name
        assert got.is_daemonset() == want.is_daemonset(), name

    node_body = json.dumps(
        {"metadata": {"resourceVersion": "8812345"}, "items": data["nodes"]}
    ).encode()
    nbatch = native_ingest.parse_node_list(node_body)
    assert nbatch is not None
    for got, obj in zip(nbatch.views(), data["nodes"]):
        want = decode_node(obj)
        assert got.name == want.name
        assert got.ready == want.ready
        assert got.labels == want.labels
        assert dict(got.allocatable) == {
            k: v for k, v in want.allocatable.items() if v
        }
        assert tuple(got.taints) == tuple(want.taints)


@pytest.fixture()
def wire_stub():
    stub = StubApiserver()
    data = _fixture()
    for n in data["nodes"]:
        stub.nodes[n["metadata"]["name"]] = n
    for p in data["pods"]:
        stub.pods[p["metadata"]["name"]] = p
    for b in data["pdbs"]:
        stub.pdbs[b["metadata"]["name"]] = b
    for c in data["pvcs"]:
        stub.pvcs[c["metadata"]["name"]] = c
    for v in data["pvs"]:
        stub.pvs[v["metadata"]["name"]] = v
    yield stub
    stub.close()


def test_wire_full_tick_drains_the_worker(wire_stub):
    """observe → plan → drain over real HTTP against the wire payloads:
    the worker node drains; the DaemonSet and mirror pods stay; the
    PV's us-east-1a node affinity steers pg-0 to the same-zone spot
    node; the spread/anti-affinity movers place cleanly."""
    client = KubeClusterClient(wire_stub.url)
    r = Rescheduler(
        client,
        SolverPlanner(_config()),
        _config(),
        clock=FakeClock(),
        recorder=client,
    )
    result = r.tick()
    assert result.drained == [OD]
    assert sorted(wire_stub.evictions) == [
        "api-7f8d9c5b44-qm2zn",
        "audit-7c9d0e1f2a-k8s2x",
        "pg-0",
        "web-6d4b75cb6d-hx8vq",
    ]
    # the plan's proven placement pins pg-0 to the zone the PV allows
    plan = result.report.plan
    assert plan.assignments["shop/pg-0"] == SPOT_1A
    # every other mover went SOMEWHERE in the spot pool
    for uid, target in plan.assignments.items():
        assert target in (SPOT_1A, SPOT_1B), (uid, target)
    # taint round trip: MarkToBeDeleted then CleanToBeDeleted
    assert len(wire_stub.patches) == 2


# ---------------------------------------------------------------------------
# Binary wire protocol goldens (service/wire.py).
#
# The multi-tenant planner service's agent<->service boundary is framed
# binary tensors; these fixtures pin it BYTE-FOR-BYTE. Version bump
# policy (see the service/wire.py header): WIRE_VERSION moves only when
# an already-shipped frame changes meaning, and every bump must update
# the digests below in the same commit — that is the point of them. A
# digest mismatch without a version bump is silent protocol drift, the
# exact failure class these goldens exist to catch.

# --- version-1 goldens (the shipped PR-8 protocol) ---
# Encoding with version=1 must stay BIT-IDENTICAL to what version-1-only
# builds shipped: these digests are copied unchanged from before the v2
# bump — the strongest possible proof that the bump is purely additive
# on the wire and an un-upgraded peer sees the exact old bytes.
GOLDEN_V1_REQUEST_SHA256 = (
    "5177a98ea2b36e152282bdb8729be717c96f7ad1bd8d017ffed2dba9dbcbba4f"
)
GOLDEN_V1_DELTA_SHA256 = (
    "c963fd338eae41819ffb9b43e4442f4e1cb0264990f98955b7f6c69b389a22a9"
)
GOLDEN_V1_REPLY_SHA256 = (
    "3eaa5c27844e5ed2f355ae28c5e592c75c012159cc0053c622b83497ef93a58c"
)
# header of the v1 golden request: MAGIC "KSRW" | version=1 | kind=1
# (PLAN_REQUEST) | 12 frames, then the first frame's name tag
GOLDEN_V1_REQUEST_HEAD_HEX = "4b53525701010c00060074656e616e74"

# --- version-2 goldens (trace frames, ISSUE 9) ---
# Same layouts, version byte 2, plus the OPTIONAL trace frames: a
# trace_id frame on requests, span_names/span_t0_ms/span_dur_ms on
# replies. Both with-and-without variants are pinned. Since the v3
# bump these encode via an explicit version=2 — and must stay
# BIT-IDENTICAL to what v2 builds shipped (the additive-bump proof,
# same as the v1 goldens before them).
GOLDEN_V2_REQUEST_SHA256 = (
    "3aa861318f26e7ff990d7ce07c5b8a62ce02d859dd77778656b987f1257e1b79"
)
GOLDEN_V2_REQUEST_TRACE_SHA256 = (
    "ed121a2062d6394b34665ba34960e621626d6d36e1de71844fc9da99d7f5ca0c"
)
GOLDEN_V2_REPLY_SHA256 = (
    "f5ea1e0694cdb2b502ce5e93d8a641ee03f20c0fb0c40f7482af7b256be2ba03"
)
GOLDEN_V2_REPLY_SPANS_SHA256 = (
    "e2fa0500a3b66945f85581d6d8895cefb00f816dcadd4fc8f00b01c1aa5c4343"
)
GOLDEN_V2_DELTA_SHA256 = (
    "b01e6863b442e508d38993e5969ae1b78b8b778df0c1a2d72afe9d208cf8c713"
)
GOLDEN_V2_REQUEST_HEAD_HEX = "4b53525702010c00060074656e616e74"

# --- version-3 goldens (drain schedules, ISSUE 11) ---
# Version byte 3, plus the OPTIONAL schedule_horizon request frame and
# the NEW KIND_PLAN_SCHEDULE reply (steps matrix + batch telemetry +
# the v2 span block). Present-and-absent variants of every optional
# frame are pinned. Since the v4 bump these encode via an explicit
# version=3 — and must stay BIT-IDENTICAL to what v3 builds shipped
# (the additive-bump proof, same as the v1/v2 goldens before them).
GOLDEN_V3_REQUEST_SHA256 = (
    "b712ab3b1d2cdd1298e5ea07113e1cce2de6032e1e94c8d5bc8683b46e7d30dc"
)
GOLDEN_V3_REQUEST_FULL_SHA256 = (  # trace_id AND schedule_horizon frames
    "ddcafab75c9a084665b2bc208ae769efda438a1247e2dcac8560e00cd309768b"
)
GOLDEN_V3_SCHEDULE_SHA256 = (
    "35bfc6df71550a4bec5c431e1357a9b4dcfd7fec6a375ae1a4a547c01af1e7ed"
)
GOLDEN_V3_SCHEDULE_SPANS_SHA256 = (
    "a72e6ac3e63e88b6e480e021e60297250cdd5141371845a4da4abf01746d7588"
)
GOLDEN_V3_REPLY_SHA256 = (
    "9b57cbabad125584d2b520c50666fd24fa9f71dee412e6a2136b808e73975509"
)
GOLDEN_V3_DELTA_SHA256 = (
    "c129254a3d290488f6ddbc257bcc2d1a55461792cc2eb91134ad8abd65b59e30"
)
GOLDEN_V3_REQUEST_HEAD_HEX = "4b53525703010c00060074656e616e74"

# --- version-4 goldens (the delta wire, ISSUE 12) ---
# Version byte 4: KIND_PACKED_DELTA becomes a real plan request
# (REQUIRED base/new fingerprints + integrity digest, optional
# trace_id), PLAN_REQUEST gains the optional pack_fingerprint frame,
# and the NEW KIND_RESYNC reply demands a full-pack resync. Pinned
# with the delta's churn frames both present (the golden delta) and
# absent (the all-empty zero-churn delta — the fixed-size message a
# quiet tick ships), and every optional request frame both ways.
GOLDEN_V4_REQUEST_SHA256 = (
    "16225da38838ef5ab48394885c043e8abee4e25857223748f0b57b2e6f1ee260"
)
GOLDEN_V4_REQUEST_FULL_SHA256 = (  # trace + schedule_horizon + pack fp
    "e3c8c7de9644c53042553872acd12897ca1c3c2a3e49b44fb804a008a835aac0"
)
GOLDEN_V4_DELTA_SHA256 = (
    "145bdbdc50af0f06e7b5a8e001b03da228a97277e2838242aaf1f7b5b40e074e"
)
GOLDEN_V4_DELTA_TRACE_SHA256 = (
    "d0e4cd4302333906460e5ab60ff96785da9dd0db3b04118f5faa9f1b802493ba"
)
GOLDEN_V4_DELTA_EMPTY_SHA256 = (  # zero churn: every section length 0
    "a837091e65ee7c22bbcad1694f3027c89b080704c92ff38cadfe957da06e3085"
)
GOLDEN_V4_RESYNC_SHA256 = (
    "3f629a2be75c6f8509d11530e4aa3e72bbfd5157870ae79ff2aa50112e03adc7"
)
GOLDEN_V4_REPLY_SHA256 = (
    "3769605cf81595336e0a2df98f0b7eb348d2f90ff92b84917dc6f09bacde60f2"
)
GOLDEN_V4_SCHEDULE_SHA256 = (
    "a5b4f95ecee528e3de9a42df525395a97d9a5a361b32984566e93d3bc41b8dfa"
)
GOLDEN_V4_REQUEST_HEAD_HEX = "4b53525704010c00060074656e616e74"
GOLDEN_BASE_FP = "f0" * 32
GOLDEN_NEW_FP = "0f" * 32
GOLDEN_RESYNC_CAUSE = "cached state lost; send a full pack"

GOLDEN_TRACE_ID = "00f1e2d3c4b5a697"
GOLDEN_SPANS = (
    ("service.admit", 0.0, 0.25),
    ("service.decode", 0.25, 0.5),
    ("service.queue-wait", 0.0, 3.5),
    ("service.batch", 3.5, 0.75),
    ("service.solve", 4.25, 1.25),
    ("service.encode", 0.0, 0.125),
)


def _golden_packed():
    import numpy as np

    from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster

    C, K, S, R, W, A = 2, 3, 2, 2, 1, 2
    return PackedCluster(
        slot_req=np.arange(C * K * R, dtype=np.float32).reshape(C, K, R) / 4,
        slot_valid=np.array([[1, 1, 0], [1, 0, 0]], bool),
        slot_tol=np.arange(C * K * W, dtype=np.uint32).reshape(C, K, W),
        slot_aff=np.arange(C * K * A, dtype=np.uint32).reshape(C, K, A),
        cand_valid=np.array([1, 1], bool),
        spot_free=np.arange(S * R, dtype=np.float32).reshape(S, R) + 0.5,
        spot_count=np.array([3, 1], np.int32),
        spot_max_pods=np.array([58, 58], np.int32),
        spot_taints=np.arange(S * W, dtype=np.uint32).reshape(S, W),
        spot_ok=np.array([1, 0], bool),
        spot_aff=np.arange(S * A, dtype=np.uint32).reshape(S, A),
    )


def _golden_delta():
    import numpy as np

    from k8s_spot_rescheduler_tpu.models.columnar import PackedDelta

    L, K, R, W, A, M = 1, 3, 2, 1, 2, 2
    return PackedDelta(
        lanes=np.array([1], np.int32),
        lane_slot_req=np.arange(L * K * R, dtype=np.float32).reshape(L, K, R),
        lane_slot_valid=np.array([[1, 0, 0]], bool),
        lane_slot_tol=np.arange(L * K * W, dtype=np.uint32).reshape(L, K, W),
        lane_slot_aff=np.arange(L * K * A, dtype=np.uint32).reshape(L, K, A),
        cand_rows=np.array([0], np.int32),
        cand_valid=np.array([0], bool),
        spot_rows=np.array([0, 1], np.int32),
        spot_free=np.arange(M * R, dtype=np.float32).reshape(M, R),
        spot_count=np.array([2, 2], np.int32),
        spot_max_pods=np.array([58, 58], np.int32),
        spot_taints=np.arange(M * W, dtype=np.uint32).reshape(M, W),
        spot_ok=np.array([1, 1], bool),
        spot_aff=np.arange(M * A, dtype=np.uint32).reshape(M, A),
    )


def _golden_reply():
    import numpy as np

    from k8s_spot_rescheduler_tpu.service import wire

    return wire.PlanReply(
        found=True, index=1, n_feasible=2,
        row=np.array([0, 1, -1], np.int32),
        solve_ms=1.25, queue_wait_ms=3.5, batch_lanes=24, batch_tenants=3,
    )


def _golden_schedule_reply(spans=()):
    import numpy as np

    from k8s_spot_rescheduler_tpu.service import wire

    # 3 steps of a K=3 problem: two drains then the terminal found=0
    # probe (the self-delimiting matrix solver/schedule.py emits)
    steps = np.array(
        [
            [1, 1, 2, 0, 1, -1],
            [0, 1, 1, 1, -1, -1],
            [-1, 0, 0, -1, -1, -1],
        ],
        "<i4",
    )
    return wire.PlanScheduleReply(
        steps=steps, solve_ms=2.5, queue_wait_ms=3.5,
        batch_lanes=24, batch_tenants=3, spans=spans,
    )


def test_wire_protocol_byte_golden_v1():
    """Version-1 encodings are pinned to the digests version-1-only
    builds shipped — the v2 bump changed NOTHING about what an old
    peer receives or sends (trace frames are v2-gated)."""
    import hashlib

    from k8s_spot_rescheduler_tpu.service import wire

    assert 1 in wire.SUPPORTED_VERSIONS
    req = wire.encode_plan_request("golden-tenant", _golden_packed(),
                                   version=1)
    assert hashlib.sha256(req).hexdigest() == GOLDEN_V1_REQUEST_SHA256
    assert req[:16].hex() == GOLDEN_V1_REQUEST_HEAD_HEX
    # a trace id handed to a v1 encode is DROPPED, not smuggled: the
    # bytes stay exactly the shipped protocol
    req_t = wire.encode_plan_request(
        "golden-tenant", _golden_packed(), trace_id=GOLDEN_TRACE_ID,
        version=1,
    )
    assert hashlib.sha256(req_t).hexdigest() == GOLDEN_V1_REQUEST_SHA256
    delta = wire.encode_packed_delta("golden-tenant", _golden_delta(),
                                     version=1)
    assert hashlib.sha256(delta).hexdigest() == GOLDEN_V1_DELTA_SHA256
    reply = wire.encode_plan_reply(_golden_reply(), version=1)
    assert hashlib.sha256(reply).hexdigest() == GOLDEN_V1_REPLY_SHA256


def test_wire_protocol_byte_golden_v2():
    """Version-2 encodings stay pinned to the digests v2 builds
    shipped — like the v1 goldens, the strongest proof the v3 bump is
    purely additive on the wire for an un-upgraded peer."""
    import hashlib

    from k8s_spot_rescheduler_tpu.service import wire

    assert 2 in wire.SUPPORTED_VERSIONS
    req = wire.encode_plan_request(
        "golden-tenant", _golden_packed(), version=2
    )
    assert hashlib.sha256(req).hexdigest() == GOLDEN_V2_REQUEST_SHA256
    assert req[:16].hex() == GOLDEN_V2_REQUEST_HEAD_HEX
    req_t = wire.encode_plan_request(
        "golden-tenant", _golden_packed(), trace_id=GOLDEN_TRACE_ID,
        version=2,
    )
    assert (
        hashlib.sha256(req_t).hexdigest() == GOLDEN_V2_REQUEST_TRACE_SHA256
    )
    # a schedule horizon handed to a v2 encode is DROPPED, not
    # smuggled: the bytes stay exactly the shipped v2 protocol
    req_h = wire.encode_plan_request(
        "golden-tenant", _golden_packed(), trace_id=GOLDEN_TRACE_ID,
        version=2, schedule_horizon=3,
    )
    assert (
        hashlib.sha256(req_h).hexdigest() == GOLDEN_V2_REQUEST_TRACE_SHA256
    )
    delta = wire.encode_packed_delta(
        "golden-tenant", _golden_delta(), version=2
    )
    assert hashlib.sha256(delta).hexdigest() == GOLDEN_V2_DELTA_SHA256
    reply = wire.encode_plan_reply(_golden_reply(), version=2)
    assert hashlib.sha256(reply).hexdigest() == GOLDEN_V2_REPLY_SHA256
    reply_s = wire.encode_plan_reply(
        _golden_reply()._replace(spans=GOLDEN_SPANS), version=2
    )
    assert (
        hashlib.sha256(reply_s).hexdigest() == GOLDEN_V2_REPLY_SPANS_SHA256
    )


def test_wire_protocol_byte_golden_v3():
    """Version-3 encodings stay pinned to the digests v3 builds
    shipped — like the v1/v2 goldens, the strongest proof the v4 bump
    is purely additive on the wire for an un-upgraded peer."""
    import hashlib

    from k8s_spot_rescheduler_tpu.service import wire

    assert 3 in wire.SUPPORTED_VERSIONS
    req = wire.encode_plan_request(
        "golden-tenant", _golden_packed(), version=3
    )
    assert hashlib.sha256(req).hexdigest() == GOLDEN_V3_REQUEST_SHA256
    assert req[:16].hex() == GOLDEN_V3_REQUEST_HEAD_HEX
    req_full = wire.encode_plan_request(
        "golden-tenant", _golden_packed(), trace_id=GOLDEN_TRACE_ID,
        schedule_horizon=3, version=3,
    )
    assert (
        hashlib.sha256(req_full).hexdigest() == GOLDEN_V3_REQUEST_FULL_SHA256
    )
    # a pack fingerprint handed to a v3 encode is DROPPED, not
    # smuggled: the bytes stay exactly the shipped v3 protocol
    req_fp = wire.encode_plan_request(
        "golden-tenant", _golden_packed(), trace_id=GOLDEN_TRACE_ID,
        schedule_horizon=3, version=3, pack_fingerprint=GOLDEN_NEW_FP,
    )
    assert (
        hashlib.sha256(req_fp).hexdigest() == GOLDEN_V3_REQUEST_FULL_SHA256
    )
    # the v3-encode-drops-delta proof: fingerprints/digest/trace are
    # v4 frames — a v3 delta encode drops them all and stays the exact
    # shipped bytes (nothing ever SENT a v3 delta; the encoder still
    # must not let v4 state leak into v3 messages)
    delta = wire.encode_packed_delta(
        "golden-tenant", _golden_delta(), version=3,
        base_fingerprint=GOLDEN_BASE_FP, new_fingerprint=GOLDEN_NEW_FP,
        trace_id=GOLDEN_TRACE_ID,
    )
    assert hashlib.sha256(delta).hexdigest() == GOLDEN_V3_DELTA_SHA256
    reply = wire.encode_plan_reply(_golden_reply(), version=3)
    assert hashlib.sha256(reply).hexdigest() == GOLDEN_V3_REPLY_SHA256
    sched = wire.encode_plan_schedule_reply(
        _golden_schedule_reply(), version=3
    )
    assert hashlib.sha256(sched).hexdigest() == GOLDEN_V3_SCHEDULE_SHA256
    sched_s = wire.encode_plan_schedule_reply(
        _golden_schedule_reply(GOLDEN_SPANS), version=3
    )
    assert (
        hashlib.sha256(sched_s).hexdigest() == GOLDEN_V3_SCHEDULE_SPANS_SHA256
    )
    # a schedule reply cannot be downgraded below v3: a pre-v3 peer
    # never asked for one, so encoding one for it is a caller bug
    with pytest.raises(wire.WireError):
        wire.encode_plan_schedule_reply(_golden_schedule_reply(), version=2)


def _golden_empty_delta():
    from k8s_spot_rescheduler_tpu.models.columnar import (
        empty_packed_delta,
    )

    return empty_packed_delta(_golden_packed())


def test_wire_protocol_byte_golden_v4():
    """The current-version encodings, pinned with the delta's churn
    frames both present and absent (the all-empty delta is the
    fixed-size message a zero-churn tick ships — the O(churn) wire
    claim at churn = 0) and every optional request frame both ways:
    any layout change breaks this test and must ship with a
    WIRE_VERSION decision (bump on meaning change, golden refresh
    always)."""
    import hashlib

    from k8s_spot_rescheduler_tpu.service import wire

    assert wire.WIRE_VERSION == 4  # bumping? update every digest below
    req = wire.encode_plan_request("golden-tenant", _golden_packed())
    assert hashlib.sha256(req).hexdigest() == GOLDEN_V4_REQUEST_SHA256
    assert req[:16].hex() == GOLDEN_V4_REQUEST_HEAD_HEX
    req_full = wire.encode_plan_request(
        "golden-tenant", _golden_packed(), trace_id=GOLDEN_TRACE_ID,
        schedule_horizon=3, pack_fingerprint=GOLDEN_NEW_FP,
    )
    assert (
        hashlib.sha256(req_full).hexdigest() == GOLDEN_V4_REQUEST_FULL_SHA256
    )
    delta = wire.encode_packed_delta(
        "golden-tenant", _golden_delta(),
        base_fingerprint=GOLDEN_BASE_FP, new_fingerprint=GOLDEN_NEW_FP,
    )
    assert hashlib.sha256(delta).hexdigest() == GOLDEN_V4_DELTA_SHA256
    delta_t = wire.encode_packed_delta(
        "golden-tenant", _golden_delta(),
        base_fingerprint=GOLDEN_BASE_FP, new_fingerprint=GOLDEN_NEW_FP,
        trace_id=GOLDEN_TRACE_ID,
    )
    assert (
        hashlib.sha256(delta_t).hexdigest() == GOLDEN_V4_DELTA_TRACE_SHA256
    )
    empty = wire.encode_packed_delta(
        "golden-tenant", _golden_empty_delta(),
        base_fingerprint=GOLDEN_BASE_FP, new_fingerprint=GOLDEN_NEW_FP,
    )
    assert (
        hashlib.sha256(empty).hexdigest() == GOLDEN_V4_DELTA_EMPTY_SHA256
    )
    # the zero-churn message is small and FIXED-size: header + empty
    # sections + fingerprints, no pack-shaped payload anywhere
    assert len(empty) < 1024
    resync = wire.encode_resync(GOLDEN_RESYNC_CAUSE)
    assert hashlib.sha256(resync).hexdigest() == GOLDEN_V4_RESYNC_SHA256
    reply = wire.encode_plan_reply(_golden_reply())
    assert hashlib.sha256(reply).hexdigest() == GOLDEN_V4_REPLY_SHA256
    sched = wire.encode_plan_schedule_reply(_golden_schedule_reply())
    assert hashlib.sha256(sched).hexdigest() == GOLDEN_V4_SCHEDULE_SHA256
    # a v4 delta encode REQUIRES its fingerprints (unverifiable
    # otherwise), and a resync cannot be downgraded below v4 (a
    # pre-v4 peer never sent a delta)
    with pytest.raises(wire.WireError):
        wire.encode_packed_delta("golden-tenant", _golden_delta())
    with pytest.raises(wire.WireError):
        wire.encode_resync(GOLDEN_RESYNC_CAUSE, version=3)


def test_wire_protocol_roundtrip():
    import numpy as np

    from k8s_spot_rescheduler_tpu.service import wire

    packed = _golden_packed()
    tenant, dec = wire.decode_plan_request(
        wire.encode_plan_request("golden-tenant", packed)
    )
    assert tenant == "golden-tenant"
    for f in dec._fields:
        got, want = getattr(dec, f), getattr(packed, f)
        assert got.dtype == want.dtype and got.shape == want.shape, f
        np.testing.assert_array_equal(got, want, err_msg=f)

    delta = _golden_delta()
    dreq = wire.decode_packed_delta_ex(
        wire.encode_packed_delta(
            "golden-tenant", delta,
            base_fingerprint=GOLDEN_BASE_FP,
            new_fingerprint=GOLDEN_NEW_FP,
            trace_id=GOLDEN_TRACE_ID,
        )
    )
    assert dreq.tenant == "golden-tenant"
    assert dreq.base_fingerprint == GOLDEN_BASE_FP
    assert dreq.new_fingerprint == GOLDEN_NEW_FP
    assert dreq.trace_id == GOLDEN_TRACE_ID
    for f in dreq.delta._fields:
        np.testing.assert_array_equal(
            getattr(dreq.delta, f), getattr(delta, f), err_msg=f
        )

    # the resync demand round-trips, and the delta-answer decoder
    # returns whichever of the two reply shapes actually came back
    demand = wire.decode_resync(wire.encode_resync("restart lost state"))
    assert demand.cause == "restart lost state"
    assert wire.decode_plan_or_resync(
        wire.encode_resync("evicted")
    ) == wire.ResyncDemand("evicted")
    assert isinstance(
        wire.decode_plan_or_resync(wire.encode_plan_reply(_golden_reply())),
        wire.PlanReply,
    )

    reply = _golden_reply()
    rdec = wire.decode_plan_reply(wire.encode_plan_reply(reply))
    assert rdec.found == reply.found and rdec.index == reply.index
    assert rdec.n_feasible == reply.n_feasible
    np.testing.assert_array_equal(rdec.row, reply.row)
    assert rdec.solve_ms == reply.solve_ms
    assert rdec.queue_wait_ms == reply.queue_wait_ms
    assert rdec.batch_lanes == reply.batch_lanes
    assert rdec.batch_tenants == reply.batch_tenants
    assert rdec.spans == ()  # no span frames -> empty, never None

    # trace frames round-trip: the request's trace id and the reply's
    # server-span block (f4 timings compare within float32 precision)
    req_ex = wire.decode_plan_request_ex(
        wire.encode_plan_request(
            "golden-tenant", packed, trace_id=GOLDEN_TRACE_ID
        )
    )
    assert req_ex.version == wire.WIRE_VERSION
    assert req_ex.trace_id == GOLDEN_TRACE_ID
    sdec = wire.decode_plan_reply(
        wire.encode_plan_reply(reply._replace(spans=GOLDEN_SPANS))
    )
    assert [s[0] for s in sdec.spans] == [s[0] for s in GOLDEN_SPANS]
    for got, want in zip(sdec.spans, GOLDEN_SPANS):
        assert got[1] == pytest.approx(want[1], abs=1e-4)
        assert got[2] == pytest.approx(want[2], abs=1e-4)

    # the v3 schedule request + reply round-trip: horizon frame decoded,
    # steps matrix bit-identical, span block intact
    req_h = wire.decode_plan_request_ex(
        wire.encode_plan_request(
            "golden-tenant", packed, trace_id=GOLDEN_TRACE_ID,
            schedule_horizon=5,
        )
    )
    assert req_h.schedule_horizon == 5
    assert req_h.trace_id == GOLDEN_TRACE_ID
    sched = _golden_schedule_reply(GOLDEN_SPANS)
    sched_dec = wire.decode_plan_schedule_reply(
        wire.encode_plan_schedule_reply(sched)
    )
    np.testing.assert_array_equal(sched_dec.steps, sched.steps)
    assert sched_dec.batch_lanes == sched.batch_lanes
    assert sched_dec.batch_tenants == sched.batch_tenants
    assert [s[0] for s in sched_dec.spans] == [s[0] for s in GOLDEN_SPANS]
    # the decoded steps feed the same decoder the in-process fetch uses
    from k8s_spot_rescheduler_tpu.solver.schedule import decode_schedule

    steps = decode_schedule(sched_dec.steps)
    assert [s.index for s in steps] == [1, 0]


def test_wire_unknown_version_is_typed_error():
    """A future (or corrupt) protocol version must decode to the TYPED
    WireVersionError — the server answers 400, never crashes — and the
    version byte is exactly header offset 4."""
    from k8s_spot_rescheduler_tpu.service import wire

    blob = bytearray(wire.encode_plan_request("t", _golden_packed()))
    assert blob[4] == wire.WIRE_VERSION
    blob[4] = max(wire.SUPPORTED_VERSIONS) + 1
    with pytest.raises(wire.WireVersionError):
        wire.decode_frames(bytes(blob))
    # and the subclass relationship holds: version errors are WireErrors
    assert issubclass(wire.WireVersionError, wire.WireError)


def test_wire_v1_payload_still_plans():
    """The back-compat half of the v2 bump: a version-1 payload from an
    un-upgraded agent decodes (trace simply absent) AND plans through a
    real ServiceServer — which answers in version 1, so the old agent
    can decode its reply too."""
    import urllib.request

    from k8s_spot_rescheduler_tpu.service import wire
    from k8s_spot_rescheduler_tpu.service.server import ServiceServer

    v1_body = wire.encode_plan_request(
        "old-agent", _golden_packed(), version=1
    )
    # direct decode: version reported, trace empty, tensors intact
    req = wire.decode_plan_request_ex(v1_body)
    assert req.version == 1 and req.trace_id == ""
    assert req.tenant == "old-agent"

    srv = ServiceServer(
        ReschedulerConfig(solver="numpy", resources=("cpu", "memory")),
        "127.0.0.1:0", batch_window_s=0.0,
    )
    srv.start_background()
    try:
        post = urllib.request.Request(
            f"http://{srv.address}/v2/plan", data=v1_body, method="POST",
            headers={"Content-Type": "application/octet-stream"},
        )
        with urllib.request.urlopen(post, timeout=30) as resp:
            raw = resp.read()
        # the reply mirrors the request's version (offset 4) and omits
        # the v2 span frames — bytes an un-upgraded decoder accepts
        assert raw[4] == 1
        reply = wire.decode_plan_reply(raw)
        assert reply.spans == ()
        assert reply.n_feasible >= 0
    finally:
        srv.close()


def test_wire_malformed_inputs_are_typed_errors():
    import numpy as np

    from k8s_spot_rescheduler_tpu.service import wire

    blob = wire.encode_plan_request("t", _golden_packed())
    with pytest.raises(wire.WireError):
        wire.decode_frames(blob[: len(blob) // 2])  # truncated
    with pytest.raises(wire.WireError):
        wire.decode_frames(b"NOPE" + blob[4:])  # bad magic
    with pytest.raises(wire.WireError):
        wire.decode_frames(blob + b"\x00")  # trailing garbage
    bad_kind = bytearray(blob)
    bad_kind[5] = 200
    with pytest.raises(wire.WireError):
        wire.decode_frames(bytes(bad_kind))
    # a request whose tensor dtype breaks the pack contract is refused
    packed = _golden_packed()._replace(
        spot_count=np.array([3, 1], np.int64)
    )
    with pytest.raises(wire.WireError):
        wire.decode_plan_request(wire.encode_plan_request("t", packed))
    # cross-field shape inconsistency is refused
    packed = _golden_packed()._replace(spot_ok=np.array([1], bool))
    with pytest.raises(wire.WireError):
        wire.decode_plan_request(wire.encode_plan_request("t", packed))
    # a reply is not a request
    with pytest.raises(wire.WireError):
        wire.decode_plan_request(wire.encode_plan_reply(_golden_reply()))
    # a pre-v3 request smuggling a schedule_horizon frame is refused at
    # DECODE (clean 400) — only a v3 request may be answered with
    # KIND_PLAN_SCHEDULE, and honoring the frame would burn a whole
    # schedule batch solve only to fail at encode
    frames = [("tenant", np.frombuffer(b"t", np.uint8))]
    packed = _golden_packed()
    frames.extend((f, getattr(packed, f)) for f in packed._fields)
    frames.append(("schedule_horizon", np.array([4], "<i4")))
    smuggled = wire.encode_frames(wire.KIND_PLAN_REQUEST, frames, version=2)
    with pytest.raises(wire.WireError):
        wire.decode_plan_request_ex(smuggled)
    # the same frame on a v3 request decodes fine
    ok = wire.encode_frames(wire.KIND_PLAN_REQUEST, frames, version=3)
    assert wire.decode_plan_request_ex(ok).schedule_horizon == 4

    # delta-wire contract violations are typed errors, never crashes:
    # a pre-v4 request smuggling a pack_fingerprint frame
    fp_frames = [("tenant", np.frombuffer(b"t", np.uint8))]
    fp_frames.extend((f, getattr(packed, f)) for f in packed._fields)
    fp_frames.append(
        ("pack_fingerprint", np.frombuffer(b"ab" * 16, np.uint8))
    )
    smuggled_fp = wire.encode_frames(
        wire.KIND_PLAN_REQUEST, fp_frames, version=3
    )
    with pytest.raises(wire.WireError):
        wire.decode_plan_request_ex(smuggled_fp)
    # a pre-v4 packed delta (nothing ever sent one; unverifiable)
    d_frames = [("tenant", np.frombuffer(b"t", np.uint8))]
    delta = _golden_delta()
    d_frames.extend((f, getattr(delta, f)) for f in delta._fields)
    with pytest.raises(wire.WireError):
        wire.decode_packed_delta_ex(
            wire.encode_frames(wire.KIND_PACKED_DELTA, d_frames, version=3)
        )
    # a v4 delta without its fingerprint/digest frames
    with pytest.raises(wire.WireError):
        wire.decode_packed_delta_ex(
            wire.encode_frames(wire.KIND_PACKED_DELTA, d_frames, version=4)
        )
    # a v4 delta whose digest names different content (one payload
    # byte flipped after the digest was computed)
    good = wire.encode_packed_delta(
        "t", delta,
        base_fingerprint=GOLDEN_BASE_FP, new_fingerprint=GOLDEN_NEW_FP,
    )
    tampered = bytearray(good)
    # flip a bit inside the lane_slot_req payload (well past the header)
    tampered[200] ^= 0x40
    with pytest.raises(wire.WireError):
        wire.decode_packed_delta_ex(bytes(tampered))


def test_wire_fuzz_corpus_typed_errors_only():
    """Seeded fuzz corpus over the byte-golden messages: every
    truncation, bit flip and duplicate-frame mutation must decode to a
    typed ``WireError`` or a structurally-valid message — NEVER an
    unhandled exception. (A payload-byte flip that still satisfies the
    frame contracts is legitimately valid wire carrying wrong numbers;
    the crash surface is what this corpus pins.) The planner service is
    a write-capable network surface: a crafted byte stream that raises
    anything else is a denial-of-service primitive."""
    import random

    import numpy as np

    from k8s_spot_rescheduler_tpu.service import wire

    corpus = [
        ("request", wire.decode_plan_request_ex,
         wire.encode_plan_request(
             "golden-tenant", _golden_packed(), trace_id=GOLDEN_TRACE_ID,
             pack_fingerprint=GOLDEN_NEW_FP,
         )),
        ("delta", wire.decode_packed_delta_ex,
         wire.encode_packed_delta(
             "golden-tenant", _golden_delta(),
             base_fingerprint=GOLDEN_BASE_FP,
             new_fingerprint=GOLDEN_NEW_FP,
             trace_id=GOLDEN_TRACE_ID,
         )),
        ("empty-delta", wire.decode_packed_delta_ex,
         wire.encode_packed_delta(
             "golden-tenant", _golden_empty_delta(),
             base_fingerprint=GOLDEN_BASE_FP,
             new_fingerprint=GOLDEN_NEW_FP,
         )),
        ("reply", wire.decode_plan_reply,
         wire.encode_plan_reply(_golden_reply()._replace(
             spans=GOLDEN_SPANS
         ))),
        ("plan-or-resync", wire.decode_plan_or_resync,
         wire.encode_plan_reply(_golden_reply())),
        ("resync", wire.decode_plan_or_resync,
         wire.encode_resync(GOLDEN_RESYNC_CAUSE)),
        ("schedule", wire.decode_plan_schedule_reply,
         wire.encode_plan_schedule_reply(
             _golden_schedule_reply(GOLDEN_SPANS)
         )),
        ("error", wire.decode_plan_reply, wire.encode_error("boom")),
    ]
    rng = random.Random(0xF1EE7)

    def must_be_typed(decode, blob, what):
        try:
            decode(blob)
        except wire.WireError:
            return  # the contract: typed, catchable, clean 400
        except Exception as err:  # noqa: BLE001 — the fuzz verdict
            pytest.fail(f"{what}: untyped {type(err).__name__}: {err}")

    for name, decode, blob in corpus:
        # every strict prefix is a truncation the decoder must refuse
        for _ in range(150):
            n = rng.randrange(len(blob))
            with pytest.raises(wire.WireError):
                decode(blob[:n])
        # random single-bit flips anywhere in the message
        for i in range(300):
            mutated = bytearray(blob)
            pos = rng.randrange(len(mutated))
            mutated[pos] ^= 1 << rng.randrange(8)
            must_be_typed(
                decode, bytes(mutated), f"{name} bit-flip @{pos}"
            )
        # duplicate-frame splices: bump the header frame count and
        # append a copy of the message's own tail bytes
        for _ in range(30):
            mutated = bytearray(blob)
            count = int.from_bytes(mutated[6:8], "little")
            mutated[6:8] = (count + 1).to_bytes(2, "little")
            cut = rng.randrange(wire._HEADER.size, len(blob))
            mutated.extend(blob[cut:])
            must_be_typed(decode, bytes(mutated), f"{name} splice @{cut}")

    # encoder-level duplicate frames are refused by the decoder too
    dup = wire.encode_frames(
        wire.KIND_PLAN_REPLY,
        [("found", np.array([1], np.uint8)),
         ("found", np.array([1], np.uint8))],
    )
    with pytest.raises(wire.WireError):
        wire.decode_frames(dup)


def test_wire_sidecar_plans_the_same_drain():
    """The planner-sidecar boundary (SURVEY.md §2.3): POSTing the same
    wire payloads to /v1/plan yields the same drain decision the
    in-process loop makes — including the PV-zone steering of pg-0 via
    the optional pvcs/pvs snapshot sections — and not-ready nodes ride
    along as presence (the sidecar passes them into NodeMap.unready
    like the control loop does). Without the volume sections, the
    PVC-backed pod stays conservatively unplaceable and the drain is
    refused rather than risked."""
    import urllib.request

    from k8s_spot_rescheduler_tpu.sidecar.server import PlannerSidecar

    data = _fixture()
    sidecar = PlannerSidecar(
        ReschedulerConfig(solver="numpy", resources=("cpu", "memory")),
        "127.0.0.1:0",
    )
    sidecar.start_background()

    def post(body):
        req = urllib.request.Request(
            f"http://{sidecar.address}/v1/plan",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    try:
        # full snapshot: same decision as the in-process tick
        out = post({
            "nodes": data["nodes"],
            "pods": data["pods"],
            "pdbs": data["pdbs"],
            "pvcs": data["pvcs"],
            "pvs": data["pvs"],
        })
        assert out["found"] is True
        assert out["node"] == OD
        assert out["assignments"]["shop/pg-0"] == SPOT_1A
        for uid, target in out["assignments"].items():
            assert target in (SPOT_1A, SPOT_1B), (uid, target)

        # without the volume sections: pg-0 stays unplaceable, so the
        # worker cannot be proven drainable — conservative, not risky
        out = post({
            "nodes": data["nodes"],
            "pods": data["pods"],
            "pdbs": data["pdbs"],
        })
        assert out["found"] is False
    finally:
        sidecar.close()


def test_wire_unready_lister_both_paths(wire_stub):
    """list_unready_nodes (the presence-only view) returns the same
    not-ready node over HTTP on the Python and native decode paths."""
    import copy

    from k8s_spot_rescheduler_tpu.io import native_ingest

    dead = copy.deepcopy(wire_stub.nodes[SPOT_1A])
    dead["metadata"]["name"] = "ip-10-0-3-100.ec2.internal"
    dead["status"]["conditions"] = [
        {"type": "Ready", "status": "False",
         "lastTransitionTime": "2026-07-30T06:00:00Z",
         "reason": "KubeletStopped", "message": "node is shutting down"}
    ]
    wire_stub.nodes[dead["metadata"]["name"]] = dead

    client = KubeClusterClient(wire_stub.url)
    client.use_native_ingest = False
    py_unready = [n.name for n in client.list_unready_nodes()]
    assert py_unready == ["ip-10-0-3-100.ec2.internal"]
    if native_ingest.available():
        nclient = KubeClusterClient(wire_stub.url)
        assert nclient.use_native_ingest
        assert [
            n.name for n in nclient.list_unready_nodes()
        ] == py_unready
    # the ready lister keeps excluding it
    assert dead["metadata"]["name"] not in [
        n.name for n in client.list_ready_nodes()
    ]


def test_wire_native_full_tick_parity(wire_stub):
    """The same tick through the native-ingest client path must make
    the identical drain decision."""
    from k8s_spot_rescheduler_tpu.io import native_ingest

    if not native_ingest.available():
        pytest.skip("native library unavailable")
    client = KubeClusterClient(wire_stub.url)
    assert client.use_native_ingest  # default-on; decodes via the C++ engine
    r = Rescheduler(
        client,
        SolverPlanner(_config()),
        _config(),
        clock=FakeClock(),
        recorder=client,
    )
    result = r.tick()
    assert result.drained == [OD]
    assert result.report.plan.assignments["shop/pg-0"] == SPOT_1A


def test_wire_watch_path_reaches_same_drain_decision():
    """The DEFAULT kube-mode path (round 5, VERDICT r4 #5): the same
    wire payloads served as list-then-watch — seeding LIST, then
    ADDED/MODIFIED/DELETED events, a BOOKMARK, and a 410-Gone re-list —
    drive `WatchingKubeClusterClient` + `ColumnarFeed` to the identical
    drain decision the polling path makes, with object-vs-columnar
    tensor parity before and after the churn."""
    import numpy as np

    from k8s_spot_rescheduler_tpu.io.watch import WatchingKubeClusterClient
    from tests.test_watch import StreamingStub, _columnar, _object_pack, _wait

    stub = StreamingStub()
    data = _fixture()
    for n in data["nodes"]:
        stub.objects["nodes"][n["metadata"]["uid"]] = n
    for p in data["pods"]:
        stub.objects["pods"][p["metadata"]["uid"]] = p
    for b in data["pdbs"]:
        stub.objects["pdbs"][b["metadata"]["uid"]] = b
    for c in data["pvcs"]:
        stub.pvcs[c["metadata"]["name"]] = c
    for v in data["pvs"]:
        stub.pvs[v["metadata"]["name"]] = v

    wc = WatchingKubeClusterClient(KubeClusterClient(stub.url))
    try:
        wc.start(timeout=10)
        cfg = _config()
        r = Rescheduler(wc, SolverPlanner(cfg), cfg, clock=FakeClock(),
                        recorder=wc)
        result = r.tick()
        # identical drain decision to the polling-path test above
        assert result.drained == [OD]
        assert sorted(stub.evictions) == [
            "api-7f8d9c5b44-qm2zn",
            "audit-7c9d0e1f2a-k8s2x",
            "pg-0",
            "web-6d4b75cb6d-hx8vq",
        ]
        assert result.report.plan.assignments["shop/pg-0"] == SPOT_1A

        # object-vs-columnar tensor parity on the frozen view
        wc.refresh()
        store = _columnar(wc)
        col, _ = store.pack(wc.list_pdbs())
        obj = _object_pack(wc)
        for field in obj._fields:
            np.testing.assert_array_equal(
                getattr(obj, field), getattr(col, field), err_msg=field
            )

        # churn through the watch machinery: BOOKMARK, MODIFIED (the
        # cache pod gains a label), ADDED (a new spot pod), DELETED
        # (the finished job object goes away)
        pods_by_name = {
            p["metadata"]["name"]: p for p in stub.objects["pods"].values()
        }
        stub.queues["pods"].put({"type": "BOOKMARK", "object": {
            "metadata": {"resourceVersion": str(stub.rv["pods"] + 1)}}})
        cache = dict(pods_by_name["cache-5b6c7d8e9f-ttw4r"])
        cache["metadata"] = dict(cache["metadata"])
        cache["metadata"]["labels"] = dict(
            cache["metadata"].get("labels") or {}, tier="hot"
        )
        stub.push("pods", "MODIFIED", cache)
        newbie = json.loads(json.dumps(pods_by_name["cache-5b6c7d8e9f-ttw4r"]))
        newbie["metadata"]["name"] = "cache-5b6c7d8e9f-zz9qx"
        newbie["metadata"]["uid"] = "aaaa1111-2222-4333-8444-555566667777"
        stub.push("pods", "ADDED", newbie)
        job = pods_by_name.get("worker-9t5kd")
        if job is not None:
            stub.push("pods", "DELETED", job)
        watcher = wc._watchers[1]
        n_events_seen = watcher.event_count
        assert _wait(lambda: watcher.event_count >= n_events_seen + 3)

        # 410 Gone mid-stream: the pod watcher must re-list; an object
        # added WITHOUT an event (only visible to the re-list) proves
        # the reconciliation really replaced the store
        ghost = json.loads(json.dumps(newbie))
        ghost["metadata"]["name"] = "cache-5b6c7d8e9f-gh0st"
        ghost["metadata"]["uid"] = "bbbb1111-2222-4333-8444-555566667777"
        stub.objects["pods"][ghost["metadata"]["uid"]] = ghost
        relists = watcher.relist_count
        stub.fail_next_watch["pods"] = {
            "kind": "Status", "code": 410, "reason": "Expired",
            "message": "too old resource version",
        }
        assert _wait(lambda: watcher.relist_count > relists, timeout=10)

        # the post-churn view: parity again, and the next tick makes the
        # right (no-)decision — the drained worker holds only its
        # DaemonSet pod now
        wc.refresh()
        store = _columnar(wc)
        col, _ = store.pack(wc.list_pdbs())
        obj = _object_pack(wc)
        for field in obj._fields:
            np.testing.assert_array_equal(
                getattr(obj, field), getattr(col, field), err_msg=field
            )
        names = {p.name for p in wc.list_pods_on_node(SPOT_1B)} | {
            p.name for p in wc.list_pods_on_node(SPOT_1A)
        }
        assert "cache-5b6c7d8e9f-gh0st" in names  # re-list delivered it
        result2 = r.tick()
        assert result2.drained == [] and result2.drain_failed == []
    finally:
        wc.stop()
        stub.close()
