"""Wire-shape fixture replay of apiserver payloads (round 4, VERDICT r3
missing #5; round 5 adds the watch path and the widened-selector pod).

tests/data/wire_cluster.json holds a small EKS-style cluster in FULL
apiserver wire shapes — hand-authored to wire fidelity (metadata noise:
uid, resourceVersion, managedFields, kubectl annotations; complete
container specs with probes/ports/env/volumeMounts; the default
tolerations the admission chain injects; kubelet-labeled nodes with
full status blocks), NOT a capture from a live cluster — the best
offline stand-in available here. It carries a control-plane node, a
mirror pod, a DaemonSet pod, a StatefulSet pod with a Bound zonal EBS
volume, a Deployment with real topologySpreadConstraints, and a
round-5 pod using the widened selector operators. The suite proves:

1. both decode paths (Python and the native C++ engine) agree on every
   pod, field for field, at wire-shape fidelity;
2. a full observe → plan → drain tick over real HTTP against these
   payloads makes the RIGHT decision: the worker drains, the DaemonSet
   pod stays, and the PV's zone affinity steers the database to the
   only same-zone spot node;
3. the DEFAULT kube-mode path — list-then-watch
   (`WatchingKubeClusterClient` + `ColumnarFeed`) — reaches the
   identical drain decision from the same payloads streamed as watch
   events (ADDED/MODIFIED/DELETED, BOOKMARK, a 410-Gone re-list), with
   object-vs-columnar tensor parity throughout.

The reference is exercised against real clusters by its users; its own
tests are unit-only (reference CONTRIBUTING.md:22-25) — this fixture is
the offline stand-in for that integration surface.
"""

import json
import os

import pytest

from k8s_spot_rescheduler_tpu.io.kube import (
    KubeClusterClient,
    decode_node,
    decode_pdb,
    decode_pod,
)
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.test_kube import StubApiserver

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "wire_cluster.json")

OD = "ip-10-0-1-17.ec2.internal"
SPOT_1B = "ip-10-0-2-41.ec2.internal"
SPOT_1A = "ip-10-0-3-99.ec2.internal"
CONTROL_PLANE = "ip-10-0-0-5.ec2.internal"


def _fixture():
    with open(FIXTURE) as f:
        return json.load(f)


def _config():
    return ReschedulerConfig(
        solver="numpy",
        resources=("cpu", "memory"),
        pod_eviction_timeout=5.0,
        eviction_retry_time=1.0,
    )


def test_wire_node_decode():
    data = _fixture()
    nodes = {n["metadata"]["name"]: decode_node(n) for n in data["nodes"]}
    od = nodes[OD]
    assert od.ready and not od.unschedulable
    assert od.allocatable["cpu"] == 3920  # "3920m"
    assert od.allocatable["pods"] == 58
    assert od.labels["topology.kubernetes.io/zone"] == "us-east-1a"
    spot = nodes[SPOT_1B]
    assert [t.key for t in spot.taints] == ["cloud.provider/spot"]
    cp = nodes[CONTROL_PLANE]
    assert cp.ready  # unclassified but visible (NodeMap.other)


def test_wire_pod_decode_surface():
    data = _fixture()
    pods = {p["metadata"]["name"]: decode_pod(p) for p in data["pods"]}

    web = pods["web-6d4b75cb6d-hx8vq"]
    # soft zone constraint dropped; hard hostname constraint modeled
    assert web.spread_constraints == (
        ("kubernetes.io/hostname", 2, (("app", "In", ("web",)),)),
    )
    assert not web.unmodeled_constraints
    assert web.requests["cpu"] == 500

    api = pods["api-7f8d9c5b44-qm2zn"]
    # matchExpressions single-value In ≡ a matchLabels pair (round-5
    # canonical terms)
    assert api.anti_affinity_match == (
        (("shop",), (("app", "In", ("api",)),)),
    )
    assert not api.unmodeled_constraints

    audit = pods["audit-7c9d0e1f2a-k8s2x"]
    # round-5 widened shapes on the wire: multi-value In, a second
    # hostname term with an Exists selector scoped cross-namespace,
    # and a hard spread whose selector uses NotIn + Exists
    assert audit.anti_affinity_match == (
        (("payments", "shop"),
         (("security.example.com/sensitive", "Exists", ()),)),
        (("shop",), (("app", "In", ("audit", "audit-canary")),)),
    )
    assert audit.spread_constraints == (
        ("kubernetes.io/hostname", 3,
         (("app", "NotIn", ("api", "web")),
          ("pod-template-hash", "Exists", ()))),
    )
    assert not audit.unmodeled_constraints

    fluent = pods["fluent-bit-x2lwp"]
    assert fluent.is_daemonset()
    # matchFields metadata.name node affinity is modeled
    assert fluent.node_affinity and not fluent.unmodeled_constraints

    pg = pods["pg-0"]
    assert pg.pvc_names == ("data-pg-0",)
    assert pg.pvc_resolvable  # decode defers to the volume resolver
    assert pg.unmodeled_constraints  # until the PV resolves

    mirror = pods["kube-apiserver-" + CONTROL_PLANE]
    assert mirror.is_mirror()

    job = pods["worker-9t5kd"]
    assert job.phase == "Succeeded"

    bare = pods["debug-shell"]
    assert bare.controller_ref() is None  # non-replicated

    pdb = decode_pdb(data["pdbs"][0])
    assert pdb.match_labels == (("app", "In", ("web",)),)
    assert pdb.disruptions_allowed == 1


def test_wire_native_decode_lockstep():
    from k8s_spot_rescheduler_tpu.io import native_ingest

    if not native_ingest.available():
        pytest.skip("native library unavailable")
    data = _fixture()
    body = json.dumps(
        {"metadata": {"resourceVersion": "8812345"}, "items": data["pods"]}
    ).encode()
    batch = native_ingest.parse_pod_list(body)
    assert batch is not None and batch.count == len(data["pods"])
    for i, obj in enumerate(data["pods"]):
        want = decode_pod(obj)
        got = batch.view(i)
        name = obj["metadata"]["name"]
        assert got.name == want.name, name
        assert got.namespace == want.namespace, name
        assert got.node_name == want.node_name, name
        assert got.requests == {
            k: v for k, v in want.requests.items() if v
        }, name
        assert got.priority == want.priority, name
        assert tuple(got.tolerations) == tuple(want.tolerations), name
        assert got.node_selector == want.node_selector, name
        assert got.anti_affinity_match == want.anti_affinity_match, name
        assert (
            got.anti_affinity_zone_match == want.anti_affinity_zone_match
        ), name
        assert got.pod_affinity_match == want.pod_affinity_match, name
        assert got.node_affinity == want.node_affinity, name
        assert got.spread_constraints == want.spread_constraints, name
        assert tuple(got.pvc_names) == tuple(want.pvc_names), name
        assert got.pvc_resolvable == want.pvc_resolvable, name
        assert got.unmodeled_constraints == want.unmodeled_constraints, name
        assert got.is_mirror() == want.is_mirror(), name
        assert got.is_daemonset() == want.is_daemonset(), name

    node_body = json.dumps(
        {"metadata": {"resourceVersion": "8812345"}, "items": data["nodes"]}
    ).encode()
    nbatch = native_ingest.parse_node_list(node_body)
    assert nbatch is not None
    for got, obj in zip(nbatch.views(), data["nodes"]):
        want = decode_node(obj)
        assert got.name == want.name
        assert got.ready == want.ready
        assert got.labels == want.labels
        assert dict(got.allocatable) == {
            k: v for k, v in want.allocatable.items() if v
        }
        assert tuple(got.taints) == tuple(want.taints)


@pytest.fixture()
def wire_stub():
    stub = StubApiserver()
    data = _fixture()
    for n in data["nodes"]:
        stub.nodes[n["metadata"]["name"]] = n
    for p in data["pods"]:
        stub.pods[p["metadata"]["name"]] = p
    for b in data["pdbs"]:
        stub.pdbs[b["metadata"]["name"]] = b
    for c in data["pvcs"]:
        stub.pvcs[c["metadata"]["name"]] = c
    for v in data["pvs"]:
        stub.pvs[v["metadata"]["name"]] = v
    yield stub
    stub.close()


def test_wire_full_tick_drains_the_worker(wire_stub):
    """observe → plan → drain over real HTTP against the wire payloads:
    the worker node drains; the DaemonSet and mirror pods stay; the
    PV's us-east-1a node affinity steers pg-0 to the same-zone spot
    node; the spread/anti-affinity movers place cleanly."""
    client = KubeClusterClient(wire_stub.url)
    r = Rescheduler(
        client,
        SolverPlanner(_config()),
        _config(),
        clock=FakeClock(),
        recorder=client,
    )
    result = r.tick()
    assert result.drained == [OD]
    assert sorted(wire_stub.evictions) == [
        "api-7f8d9c5b44-qm2zn",
        "audit-7c9d0e1f2a-k8s2x",
        "pg-0",
        "web-6d4b75cb6d-hx8vq",
    ]
    # the plan's proven placement pins pg-0 to the zone the PV allows
    plan = result.report.plan
    assert plan.assignments["shop/pg-0"] == SPOT_1A
    # every other mover went SOMEWHERE in the spot pool
    for uid, target in plan.assignments.items():
        assert target in (SPOT_1A, SPOT_1B), (uid, target)
    # taint round trip: MarkToBeDeleted then CleanToBeDeleted
    assert len(wire_stub.patches) == 2


def test_wire_sidecar_plans_the_same_drain():
    """The planner-sidecar boundary (SURVEY.md §2.3): POSTing the same
    wire payloads to /v1/plan yields the same drain decision the
    in-process loop makes — including the PV-zone steering of pg-0 via
    the optional pvcs/pvs snapshot sections — and not-ready nodes ride
    along as presence (the sidecar passes them into NodeMap.unready
    like the control loop does). Without the volume sections, the
    PVC-backed pod stays conservatively unplaceable and the drain is
    refused rather than risked."""
    import urllib.request

    from k8s_spot_rescheduler_tpu.sidecar.server import PlannerSidecar

    data = _fixture()
    sidecar = PlannerSidecar(
        ReschedulerConfig(solver="numpy", resources=("cpu", "memory")),
        "127.0.0.1:0",
    )
    sidecar.start_background()

    def post(body):
        req = urllib.request.Request(
            f"http://{sidecar.address}/v1/plan",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    try:
        # full snapshot: same decision as the in-process tick
        out = post({
            "nodes": data["nodes"],
            "pods": data["pods"],
            "pdbs": data["pdbs"],
            "pvcs": data["pvcs"],
            "pvs": data["pvs"],
        })
        assert out["found"] is True
        assert out["node"] == OD
        assert out["assignments"]["shop/pg-0"] == SPOT_1A
        for uid, target in out["assignments"].items():
            assert target in (SPOT_1A, SPOT_1B), (uid, target)

        # without the volume sections: pg-0 stays unplaceable, so the
        # worker cannot be proven drainable — conservative, not risky
        out = post({
            "nodes": data["nodes"],
            "pods": data["pods"],
            "pdbs": data["pdbs"],
        })
        assert out["found"] is False
    finally:
        sidecar.close()


def test_wire_unready_lister_both_paths(wire_stub):
    """list_unready_nodes (the presence-only view) returns the same
    not-ready node over HTTP on the Python and native decode paths."""
    import copy

    from k8s_spot_rescheduler_tpu.io import native_ingest

    dead = copy.deepcopy(wire_stub.nodes[SPOT_1A])
    dead["metadata"]["name"] = "ip-10-0-3-100.ec2.internal"
    dead["status"]["conditions"] = [
        {"type": "Ready", "status": "False",
         "lastTransitionTime": "2026-07-30T06:00:00Z",
         "reason": "KubeletStopped", "message": "node is shutting down"}
    ]
    wire_stub.nodes[dead["metadata"]["name"]] = dead

    client = KubeClusterClient(wire_stub.url)
    client.use_native_ingest = False
    py_unready = [n.name for n in client.list_unready_nodes()]
    assert py_unready == ["ip-10-0-3-100.ec2.internal"]
    if native_ingest.available():
        nclient = KubeClusterClient(wire_stub.url)
        assert nclient.use_native_ingest
        assert [
            n.name for n in nclient.list_unready_nodes()
        ] == py_unready
    # the ready lister keeps excluding it
    assert dead["metadata"]["name"] not in [
        n.name for n in client.list_ready_nodes()
    ]


def test_wire_native_full_tick_parity(wire_stub):
    """The same tick through the native-ingest client path must make
    the identical drain decision."""
    from k8s_spot_rescheduler_tpu.io import native_ingest

    if not native_ingest.available():
        pytest.skip("native library unavailable")
    client = KubeClusterClient(wire_stub.url)
    assert client.use_native_ingest  # default-on; decodes via the C++ engine
    r = Rescheduler(
        client,
        SolverPlanner(_config()),
        _config(),
        clock=FakeClock(),
        recorder=client,
    )
    result = r.tick()
    assert result.drained == [OD]
    assert result.report.plan.assignments["shop/pg-0"] == SPOT_1A


def test_wire_watch_path_reaches_same_drain_decision():
    """The DEFAULT kube-mode path (round 5, VERDICT r4 #5): the same
    wire payloads served as list-then-watch — seeding LIST, then
    ADDED/MODIFIED/DELETED events, a BOOKMARK, and a 410-Gone re-list —
    drive `WatchingKubeClusterClient` + `ColumnarFeed` to the identical
    drain decision the polling path makes, with object-vs-columnar
    tensor parity before and after the churn."""
    import numpy as np

    from k8s_spot_rescheduler_tpu.io.watch import WatchingKubeClusterClient
    from tests.test_watch import StreamingStub, _columnar, _object_pack, _wait

    stub = StreamingStub()
    data = _fixture()
    for n in data["nodes"]:
        stub.objects["nodes"][n["metadata"]["uid"]] = n
    for p in data["pods"]:
        stub.objects["pods"][p["metadata"]["uid"]] = p
    for b in data["pdbs"]:
        stub.objects["pdbs"][b["metadata"]["uid"]] = b
    for c in data["pvcs"]:
        stub.pvcs[c["metadata"]["name"]] = c
    for v in data["pvs"]:
        stub.pvs[v["metadata"]["name"]] = v

    wc = WatchingKubeClusterClient(KubeClusterClient(stub.url))
    try:
        wc.start(timeout=10)
        cfg = _config()
        r = Rescheduler(wc, SolverPlanner(cfg), cfg, clock=FakeClock(),
                        recorder=wc)
        result = r.tick()
        # identical drain decision to the polling-path test above
        assert result.drained == [OD]
        assert sorted(stub.evictions) == [
            "api-7f8d9c5b44-qm2zn",
            "audit-7c9d0e1f2a-k8s2x",
            "pg-0",
            "web-6d4b75cb6d-hx8vq",
        ]
        assert result.report.plan.assignments["shop/pg-0"] == SPOT_1A

        # object-vs-columnar tensor parity on the frozen view
        wc.refresh()
        store = _columnar(wc)
        col, _ = store.pack(wc.list_pdbs())
        obj = _object_pack(wc)
        for field in obj._fields:
            np.testing.assert_array_equal(
                getattr(obj, field), getattr(col, field), err_msg=field
            )

        # churn through the watch machinery: BOOKMARK, MODIFIED (the
        # cache pod gains a label), ADDED (a new spot pod), DELETED
        # (the finished job object goes away)
        pods_by_name = {
            p["metadata"]["name"]: p for p in stub.objects["pods"].values()
        }
        stub.queues["pods"].put({"type": "BOOKMARK", "object": {
            "metadata": {"resourceVersion": str(stub.rv["pods"] + 1)}}})
        cache = dict(pods_by_name["cache-5b6c7d8e9f-ttw4r"])
        cache["metadata"] = dict(cache["metadata"])
        cache["metadata"]["labels"] = dict(
            cache["metadata"].get("labels") or {}, tier="hot"
        )
        stub.push("pods", "MODIFIED", cache)
        newbie = json.loads(json.dumps(pods_by_name["cache-5b6c7d8e9f-ttw4r"]))
        newbie["metadata"]["name"] = "cache-5b6c7d8e9f-zz9qx"
        newbie["metadata"]["uid"] = "aaaa1111-2222-4333-8444-555566667777"
        stub.push("pods", "ADDED", newbie)
        job = pods_by_name.get("worker-9t5kd")
        if job is not None:
            stub.push("pods", "DELETED", job)
        watcher = wc._watchers[1]
        n_events_seen = watcher.event_count
        assert _wait(lambda: watcher.event_count >= n_events_seen + 3)

        # 410 Gone mid-stream: the pod watcher must re-list; an object
        # added WITHOUT an event (only visible to the re-list) proves
        # the reconciliation really replaced the store
        ghost = json.loads(json.dumps(newbie))
        ghost["metadata"]["name"] = "cache-5b6c7d8e9f-gh0st"
        ghost["metadata"]["uid"] = "bbbb1111-2222-4333-8444-555566667777"
        stub.objects["pods"][ghost["metadata"]["uid"]] = ghost
        relists = watcher.relist_count
        stub.fail_next_watch["pods"] = {
            "kind": "Status", "code": 410, "reason": "Expired",
            "message": "too old resource version",
        }
        assert _wait(lambda: watcher.relist_count > relists, timeout=10)

        # the post-churn view: parity again, and the next tick makes the
        # right (no-)decision — the drained worker holds only its
        # DaemonSet pod now
        wc.refresh()
        store = _columnar(wc)
        col, _ = store.pack(wc.list_pdbs())
        obj = _object_pack(wc)
        for field in obj._fields:
            np.testing.assert_array_equal(
                getattr(obj, field), getattr(col, field), err_msg=field
            )
        names = {p.name for p in wc.list_pods_on_node(SPOT_1B)} | {
            p.name for p in wc.list_pods_on_node(SPOT_1A)
        }
        assert "cache-5b6c7d8e9f-gh0st" in names  # re-list delivered it
        result2 = r.tick()
        assert result2.drained == [] and result2.drain_failed == []
    finally:
        wc.stop()
        stub.close()
