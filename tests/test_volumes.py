"""Volume topology: PVC -> PV -> node-affinity resolution.

The reference inherits volume predicates from the scheduler
(CheckPredicates; reference README.md:103-114). Here, decode marks every
PVC pod conservatively unplaceable and models/volumes.py LIFTS that only
when every claim proves Bound to a PV whose nodeAffinity is absent or in
the canonical form — the PV terms then merge into the pod's own
requirement by distribution (masks.merge_affinity_terms) and ride the
NodeAffinityBit machinery end to end.
"""

import numpy as np

from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
from k8s_spot_rescheduler_tpu.io.kube import decode_pod, decode_pv, decode_pvc
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.models.cluster import PVCSpec, PVSpec
from k8s_spot_rescheduler_tpu.models.volumes import resolve_volume_affinity
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.predicates.masks import merge_affinity_terms
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.fixtures import (  # noqa: F401
    pack_fake,
    ON_DEMAND_LABEL,
    ON_DEMAND_LABELS,
    SPOT_LABEL,
    SPOT_LABELS,
    make_node,
    make_pod,
)

ZONE_A = ((("zone", "In", ("a",)),),)
ZONE_B = ((("zone", "In", ("b",)),),)


# --- term merging ----------------------------------------------------------

def test_merge_identity_and_single():
    assert merge_affinity_terms() == ()
    assert merge_affinity_terms((), ZONE_A, ()) == ZONE_A


def test_merge_distributes_and_of_ors():
    left = ((("a", "In", ("1",)),), (("b", "In", ("2",)),))
    right = ((("c", "Exists", ()),),)
    merged = merge_affinity_terms(left, right)
    assert merged == (
        (("a", "In", ("1",)), ("c", "Exists", ())),
        (("b", "In", ("2",)), ("c", "Exists", ())),
    )


def test_merge_dedupes_shared_exprs():
    merged = merge_affinity_terms(ZONE_A, ZONE_A)
    assert merged == ZONE_A


def test_merge_caps_blowup():
    many = tuple(((f"k{i}", "Exists", ()),) for i in range(5))
    assert merge_affinity_terms(many, many) is None  # 25 > cap 16


# --- decode ----------------------------------------------------------------

def test_decode_pvc():
    c = decode_pvc({
        "metadata": {"name": "data", "namespace": "ns1"},
        "spec": {"volumeName": "pv-7"},
        "status": {"phase": "Bound"},
    })
    assert (c.uid, c.volume_name, c.phase) == ("ns1/data", "pv-7", "Bound")


def test_decode_pv_affinity_shapes():
    pv = decode_pv({
        "metadata": {"name": "pv-7"},
        "spec": {"nodeAffinity": {"required": {"nodeSelectorTerms": [
            {"matchExpressions": [
                {"key": "zone", "operator": "In", "values": ["a"]}]}]}}},
    })
    assert pv.node_affinity == ZONE_A and not pv.unmodeled
    # no affinity at all: unconstrained
    pv = decode_pv({"metadata": {"name": "pv-8"}, "spec": {}})
    assert pv.node_affinity == () and not pv.unmodeled
    # present-but-empty required NodeSelector matches NO node in the
    # scheduler's matcher — resolving it as unconstrained would be the
    # unsafe direction (review regression)
    pv = decode_pv({"metadata": {"name": "pv-e"},
                    "spec": {"nodeAffinity": {"required": {}}}})
    assert pv.unmodeled
    pv = decode_pv({"metadata": {"name": "pv-e2"},
                    "spec": {"nodeAffinity": {"required": []}}})
    assert pv.unmodeled
    # malformed affinity: unmodeled
    pv = decode_pv({
        "metadata": {"name": "pv-9"},
        "spec": {"nodeAffinity": {"required": {"nodeSelectorTerms": [
            {"matchFields": [
                {"key": "metadata.uid", "operator": "In", "values": ["x"]}]}
        ]}}},
    })
    assert pv.unmodeled


def _pod_obj(volumes):
    return {
        "metadata": {"name": "p", "namespace": "ns1"},
        "spec": {"nodeName": "n1", "containers": [], "volumes": volumes},
        "status": {"phase": "Running"},
    }


def test_decode_pod_pvc_names():
    pod = decode_pod(_pod_obj([
        {"persistentVolumeClaim": {"claimName": "data"}},
        {"configMap": {"name": "cm"}},
        {"persistentVolumeClaim": {"claimName": "logs"}},
    ]))
    assert pod.pvc_names == ("data", "logs")
    assert pod.unmodeled_constraints  # conservative until resolved
    assert pod.pvc_resolvable


def test_decode_pod_malformed_claim_never_resolvable():
    pod = decode_pod(_pod_obj([
        {"persistentVolumeClaim": {"claimName": "ok"}},
        {"persistentVolumeClaim": {}},
    ]))
    assert pod.pvc_names == ()
    assert pod.unmodeled_constraints and not pod.pvc_resolvable


def test_decode_pod_pvc_plus_unmodeled_affinity_not_resolvable():
    obj = _pod_obj([{"persistentVolumeClaim": {"claimName": "data"}}])
    obj["spec"]["affinity"] = {"podAntiAffinity": {
        "requiredDuringSchedulingIgnoredDuringExecution": [
            {"topologyKey": "rack", "labelSelector": {"matchLabels": {"a": "1"}}}]}}
    pod = decode_pod(obj)
    assert pod.unmodeled_constraints and not pod.pvc_resolvable


# --- resolution ------------------------------------------------------------

def _pvc_pod(**kw):
    return make_pod(
        "web", 300, "od-1", namespace="ns1",
        pvc_names=("data",), pvc_resolvable=True,
        unmodeled_constraints=True, **kw,
    )


def test_resolution_folds_pv_affinity():
    pod = _pvc_pod()
    out = resolve_volume_affinity(
        pod,
        {"ns1/data": PVCSpec("data", "ns1", volume_name="pv-1")},
        {"pv-1": PVSpec("pv-1", node_affinity=ZONE_A)},
    )
    assert out.node_affinity == ZONE_A
    assert not out.unmodeled_constraints and not out.pvc_resolvable


def test_resolution_merges_with_own_affinity():
    pod = _pvc_pod(node_affinity=((("arch", "Exists", ()),),))
    out = resolve_volume_affinity(
        pod,
        {"ns1/data": PVCSpec("data", "ns1", volume_name="pv-1")},
        {"pv-1": PVSpec("pv-1", node_affinity=ZONE_A)},
    )
    assert out.node_affinity == (
        (("arch", "Exists", ()), ("zone", "In", ("a",))),
    )


def test_resolution_fail_safe_paths():
    pod = _pvc_pod()
    # unbound claim
    out = resolve_volume_affinity(
        pod, {"ns1/data": PVCSpec("data", "ns1", volume_name="")}, {}
    )
    assert out is pod
    # missing PV
    out = resolve_volume_affinity(
        pod, {"ns1/data": PVCSpec("data", "ns1", volume_name="pv-x")}, {}
    )
    assert out is pod
    # unmodeled PV affinity
    out = resolve_volume_affinity(
        pod,
        {"ns1/data": PVCSpec("data", "ns1", volume_name="pv-1")},
        {"pv-1": PVSpec("pv-1", unmodeled=True)},
    )
    assert out is pod
    # wrong namespace claim does not match
    out = resolve_volume_affinity(
        pod, {"other/data": PVCSpec("data", "other", volume_name="pv-1")},
        {"pv-1": PVSpec("pv-1")},
    )
    assert out is pod


def test_resolution_no_affinity_pv_just_lifts():
    pod = _pvc_pod()
    out = resolve_volume_affinity(
        pod,
        {"ns1/data": PVCSpec("data", "ns1", volume_name="pv-1")},
        {"pv-1": PVSpec("pv-1")},
    )
    assert out.node_affinity == ()
    assert not out.unmodeled_constraints


# --- end to end ------------------------------------------------------------

def _cluster():
    fc = FakeCluster(FakeClock(), reschedule_evicted=True)
    fc.pvs["pv-1"] = PVSpec("pv-1", node_affinity=ZONE_A)
    fc.pvcs["default/data"] = PVCSpec("data", "default", volume_name="pv-1")
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-a", dict(SPOT_LABELS, zone="a")))
    fc.add_node(make_node("spot-b", dict(SPOT_LABELS, zone="b")))
    fc.add_pod(make_pod("web", 300, "od-1", pvc_names=("data",),
                        pvc_resolvable=True, unmodeled_constraints=True))
    return fc


def test_drain_places_pvc_pod_in_volume_zone():
    fc = _cluster()
    cfg = ReschedulerConfig(solver="numpy", node_drain_delay=0.0)
    r = Rescheduler(fc, SolverPlanner(cfg), cfg, clock=fc.clock, recorder=fc)
    result = r.tick()
    assert result.drained == ["od-1"]
    fc.clock.advance(10.0)
    assert fc.pods["default/web"].node_name == "spot-a"


def test_unresolvable_pvc_pod_blocks_drain():
    fc = _cluster()
    fc.add_pod(make_pod("stuck", 100, "od-1", pvc_names=("ghost",),
                        pvc_resolvable=True, unmodeled_constraints=True))
    packed, _ = pack_fake(fc)
    assert not plan_oracle(packed).feasible[:1].any()


def test_columnar_parity_with_pvc_pods():
    fc = _cluster()
    store = fc.columnar_store(
        ("cpu", "memory"),
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    obj, _ = pack_fake(fc)
    col, _ = store.pack(fc.pdbs)
    for field in obj._fields:
        np.testing.assert_array_equal(
            getattr(obj, field), getattr(col, field), err_msg=field
        )
