"""Leader-election tests: Lease acquire / renew / skew-safe takeover /
CAS-race demotion against a stub apiserver enforcing resourceVersion
compare-and-swap, driven on a virtual clock."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from k8s_spot_rescheduler_tpu.io.kube import KubeClusterClient
from k8s_spot_rescheduler_tpu.io.lease import LeaseElector
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock

LEASES = "/apis/coordination.k8s.io/v1/namespaces/kube-system/leases"


class LeaseStub:
    def __init__(self):
        self.lease = None  # the single lease object, or None
        self.rv = 0
        self.conflict_next_put = False
        self.fail_next = 0  # respond 500 to the next N requests

        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, obj, code=200):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _gate(self):
                if stub.fail_next > 0:
                    stub.fail_next -= 1
                    self._send({"kind": "Status"}, 500)
                    return True
                return False

            def do_GET(self):
                if self._gate():
                    return
                if self.path.startswith(LEASES + "/"):
                    if stub.lease is None:
                        return self._send({"kind": "Status"}, 404)
                    return self._send(stub.lease)
                return self._send({}, 404)

            def do_POST(self):
                if self._gate():
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                if self.path == LEASES:
                    if stub.lease is not None:
                        return self._send({"kind": "Status"}, 409)
                    stub.rv += 1
                    body["metadata"]["resourceVersion"] = str(stub.rv)
                    stub.lease = body
                    return self._send(body, 201)
                return self._send({}, 404)

            def do_PUT(self):
                if self._gate():
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                if not self.path.startswith(LEASES + "/"):
                    return self._send({}, 404)
                if stub.conflict_next_put:
                    stub.conflict_next_put = False
                    return self._send({"kind": "Status"}, 409)
                current_rv = (
                    stub.lease["metadata"]["resourceVersion"]
                    if stub.lease else ""
                )
                if body["metadata"].get("resourceVersion") != current_rv:
                    return self._send({"kind": "Status"}, 409)
                stub.rv += 1
                body["metadata"]["resourceVersion"] = str(stub.rv)
                stub.lease = body
                return self._send(body)

        self.server = HTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self.thread.start()

    @property
    def url(self):
        host, port = self.server.server_address
        return f"http://{host}:{port}"

    def close(self):
        self.server.shutdown()


@pytest.fixture()
def stub():
    s = LeaseStub()
    yield s
    s.close()


def _elector(stub, clock, ident):
    return LeaseElector(
        KubeClusterClient(stub.url),
        identity=ident,
        lease_duration=15.0,
        clock=clock,
        wall=clock.now,
    )


def test_acquire_when_absent(stub):
    clock = FakeClock()
    a = _elector(stub, clock, "a")
    assert a.ensure()
    assert stub.lease["spec"]["holderIdentity"] == "a"
    assert stub.lease["spec"]["leaseTransitions"] == 0


def test_renew_keeps_leadership_and_acquire_time(stub):
    clock = FakeClock()
    a = _elector(stub, clock, "a")
    assert a.ensure()
    t0 = stub.lease["spec"]["acquireTime"]
    clock.advance(10)
    assert a.ensure()
    assert stub.lease["spec"]["acquireTime"] == t0  # renew, not re-acquire
    assert stub.lease["spec"]["renewTime"] != t0


def test_follower_while_holder_renews(stub):
    clock = FakeClock()
    a, b = _elector(stub, clock, "a"), _elector(stub, clock, "b")
    assert a.ensure()
    # b keeps observing fresh renewals: never becomes leader however long
    # wall time gets, because the observation clock resets on every change
    for _ in range(5):
        clock.advance(10)
        assert a.ensure()
        assert not b.ensure()


def test_takeover_after_holder_goes_quiet(stub):
    clock = FakeClock()
    a, b = _elector(stub, clock, "a"), _elector(stub, clock, "b")
    assert a.ensure()
    assert not b.ensure()  # first observation of a's record
    clock.advance(14.9)
    assert not b.ensure()  # not yet expired
    clock.advance(0.2)  # observed_at + 15 passed, a never renewed
    assert b.ensure()
    assert stub.lease["spec"]["holderIdentity"] == "b"
    assert stub.lease["spec"]["leaseTransitions"] == 1
    # a finds out on its next renew attempt (CAS fails -> follower)
    assert not a.ensure()


def test_cas_conflict_demotes(stub):
    clock = FakeClock()
    a = _elector(stub, clock, "a")
    assert a.ensure()
    stub.conflict_next_put = True
    assert not a.ensure()  # renew raced -> follower, no crash


def test_apiserver_error_demotes_without_raising(stub):
    clock = FakeClock()
    a = _elector(stub, clock, "a")
    assert a.ensure()
    stub.fail_next = 1
    assert not a.ensure()
    assert a.ensure()  # recovers next tick


def test_background_renewal_covers_long_tick(stub):
    """A leader blocked in a long drain must not go quiet: the renew
    thread keeps the lease fresh, so a standby never takes over until the
    leader actually stops. Real clocks, scaled-down durations."""
    import time as _t

    from k8s_spot_rescheduler_tpu.utils.clock import RealClock

    a = LeaseElector(
        KubeClusterClient(stub.url), identity="a",
        lease_duration=1.0, clock=RealClock(),
    )
    b = LeaseElector(
        KubeClusterClient(stub.url), identity="b",
        lease_duration=1.0, clock=RealClock(),
    )
    assert a.ensure()
    a.start_background(retry_period=0.1)
    try:
        # "main thread of A" is busy for longer than the lease duration;
        # B keeps probing and must stay follower throughout
        deadline = _t.monotonic() + 1.5
        while _t.monotonic() < deadline:
            assert not b.ensure(), "standby stole a live leader's lease"
            _t.sleep(0.05)
    finally:
        a.stop_background()
    # A is gone for real now; B takes over after a full quiet period
    deadline = _t.monotonic() + 5.0
    while _t.monotonic() < deadline and not b.ensure():
        _t.sleep(0.05)
    assert b.is_leader
    assert stub.lease["spec"]["holderIdentity"] == "b"


def test_observation_not_remote_timestamps(stub):
    """Skew safety: a holder whose renewTime is absurdly far in the future
    (its clock is wrong) is still taken over once *locally* quiet."""
    clock = FakeClock()
    b = _elector(stub, clock, "b")
    stub.rv += 1
    stub.lease = {
        "metadata": {"name": "x", "resourceVersion": str(stub.rv)},
        "spec": {
            "holderIdentity": "skewed",
            "leaseDurationSeconds": 15,
            "renewTime": "2999-01-01T00:00:00.000000Z",
            "leaseTransitions": 3,
        },
    }
    assert not b.ensure()  # first observation
    clock.advance(15.1)
    assert b.ensure()  # local quiet period decides, not the year-2999 stamp
    assert stub.lease["spec"]["leaseTransitions"] == 4
