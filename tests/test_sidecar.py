"""Planner-sidecar tests: the solver behind its JSON/HTTP boundary."""

import json
import urllib.request

import pytest

from k8s_spot_rescheduler_tpu.sidecar.server import PlannerSidecar
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.test_kube import _node, _pod


@pytest.fixture()
def sidecar():
    s = PlannerSidecar(ReschedulerConfig(), "127.0.0.1:0")
    s.start_background()
    yield s
    s.close()


def _post(sidecar, body):
    req = urllib.request.Request(
        f"http://{sidecar.address}/v1/plan",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def test_healthz(sidecar):
    with urllib.request.urlopen(
        f"http://{sidecar.address}/healthz", timeout=10
    ) as resp:
        assert json.loads(resp.read())["ok"] is True


def test_plan_over_http(sidecar):
    body = {
        "nodes": [_node("od-1", "worker"), _node("spot-1", "spot-worker")],
        "pods": [_pod("a", "od-1", cpu="300m"), _pod("b", "od-1", cpu="200m")],
        "pdbs": [],
    }
    out = _post(sidecar, body)
    assert out["found"] is True
    assert out["node"] == "od-1"
    assert out["assignments"] == {"default/a": "spot-1", "default/b": "spot-1"}
    assert out["nCandidates"] == 1 and out["nFeasible"] == 1


def test_plan_infeasible(sidecar):
    body = {
        "nodes": [_node("od-1", "worker"), _node("spot-1", "spot-worker", cpu="100m")],
        "pods": [_pod("a", "od-1", cpu="1900m")],
    }
    out = _post(sidecar, body)
    assert out["found"] is False
    assert out["nFeasible"] == 0


def test_bad_request(sidecar):
    req = urllib.request.Request(
        f"http://{sidecar.address}/v1/plan",
        data=b"not json",
        method="POST",
    )
    try:
        urllib.request.urlopen(req, timeout=10)
        raised = False
    except urllib.error.HTTPError as err:
        raised = True
        assert err.code == 400
    assert raised


def _post_raw(sidecar, data, headers=None):
    req = urllib.request.Request(
        f"http://{sidecar.address}/v1/plan",
        data=data,
        headers=headers or {"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read())


def test_oversized_snapshot_rejected():
    s = PlannerSidecar(
        ReschedulerConfig(solver="numpy"), "127.0.0.1:0", max_body_bytes=1024
    )
    s.start_background()
    try:
        code, body = _post_raw(s, b"x" * 2048)
        assert code == 413
        assert "limit" in body["error"]
        # the server survives and stays healthy
        with urllib.request.urlopen(
            f"http://{s.address}/healthz", timeout=10
        ) as resp:
            assert json.loads(resp.read())["ok"] is True
    finally:
        s.close()


def test_busy_timeout_yields_503():
    """A request that cannot get its turn within busy_timeout_s gets 503 +
    Retry-After instead of queueing unboundedly."""
    import threading
    import time

    s = PlannerSidecar(
        ReschedulerConfig(solver="numpy"), "127.0.0.1:0", busy_timeout_s=0.2
    )
    inner = s.planner

    class Slow:
        def plan(self, node_map, pdbs):
            time.sleep(1.5)
            return inner.plan(node_map, pdbs)

    s.planner = Slow()
    s.start_background()
    try:
        body = json.dumps({
            "nodes": [_node("od-1", "worker"), _node("spot-1", "spot-worker")],
            "pods": [_pod("a", "od-1", cpu="100m")],
        }).encode()
        results = []

        def fire():
            results.append(_post_raw(s, body))

        threads = [threading.Thread(target=fire) for _ in range(3)]
        for t in threads:
            t.start()
            time.sleep(0.05)  # ensure one holds the lock first
        for t in threads:
            t.join()
        codes = sorted(c for c, _ in results)
        assert codes[0] == 200, f"no request succeeded: {results}"
        assert 503 in codes, f"no request saw backpressure: {codes}"
    finally:
        s.close()


def test_concurrent_requests_all_served():
    """Within the busy timeout, concurrent requests serialize on the solve
    lock and all succeed."""
    import threading

    s = PlannerSidecar(
        ReschedulerConfig(solver="numpy"), "127.0.0.1:0",
        busy_timeout_s=30.0, max_inflight=8,
    )
    s.start_background()
    try:
        body = json.dumps({
            "nodes": [_node("od-1", "worker"), _node("spot-1", "spot-worker")],
            "pods": [_pod("a", "od-1", cpu="100m")],
        }).encode()
        results = []

        def fire():
            results.append(_post_raw(s, body))

        threads = [threading.Thread(target=fire) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 8
        assert all(code == 200 for code, _ in results), results
        assert all(out["found"] for _, out in results)
    finally:
        s.close()


def test_inflight_depth_cap_rejects_immediately():
    """Past max_inflight concurrent requests, /v1/plan 503s IMMEDIATELY —
    before reading the body — so a burst of oversize-adjacent requests
    holds at most max_inflight bodies in memory (the busy timeout alone
    capped queue time, not depth)."""
    import threading
    import time

    s = PlannerSidecar(
        ReschedulerConfig(solver="numpy"), "127.0.0.1:0",
        busy_timeout_s=30.0, max_inflight=2,
    )
    release = threading.Event()
    inner = s.planner

    class Gated:
        def plan(self, node_map, pdbs):
            release.wait(timeout=30)
            return inner.plan(node_map, pdbs)

    s.planner = Gated()
    s.start_background()
    try:
        body = json.dumps({
            "nodes": [_node("od-1", "worker"), _node("spot-1", "spot-worker")],
            "pods": [_pod("a", "od-1", cpu="100m")],
        }).encode()
        slow_results = []

        def fire_slow():
            slow_results.append(_post_raw(s, body))

        # fill both inflight slots: one solving (gated), one lock-waiting
        occupants = [threading.Thread(target=fire_slow) for _ in range(2)]
        for t in occupants:
            t.start()
            time.sleep(0.2)

        # burst past the cap: each must reject fast (well under the 30 s
        # busy timeout) while the gate still holds both slots
        t0 = time.monotonic()
        burst = [_post_raw(s, body) for _ in range(4)]
        burst_s = time.monotonic() - t0
        assert all(code == 503 for code, _ in burst), burst
        assert all("overloaded" in out["error"] for _, out in burst), burst
        assert burst_s < 5.0, f"depth rejection waited: {burst_s:.1f}s"

        release.set()
        for t in occupants:
            t.join()
        assert sorted(c for c, _ in slow_results) == [200, 200], slow_results
        # slots drain: a fresh request is admitted again
        code, out = _post_raw(s, body)
        assert code == 200 and out["found"]
    finally:
        release.set()
        s.close()


def test_negative_content_length_rejected():
    """A negative Content-Length must not reach rfile.read(-1) (which
    would buffer until EOF, bypassing the size cap)."""
    import http.client

    s = PlannerSidecar(
        ReschedulerConfig(solver="numpy"), "127.0.0.1:0", max_body_bytes=1024
    )
    s.start_background()
    try:
        host, _, port = s.address.rpartition(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=10)
        conn.putrequest("POST", "/v1/plan", skip_accept_encoding=True)
        conn.putheader("Content-Length", "-1")
        conn.endheaders()
        resp = conn.getresponse()
        assert resp.status == 400
        conn.close()
    finally:
        s.close()
