"""Planner-sidecar tests: the solver behind its JSON/HTTP boundary.

Since the multi-tenant promotion the sidecar IS the planner service
(service/server.py): /v1/plan decodes, packs and rides the batching
queue. These tests cover the JSON boundary's contract — the service's
own queue/batch/fairness mechanics live in tests/test_service.py."""

import json
import urllib.request

import pytest

from k8s_spot_rescheduler_tpu.sidecar.server import PlannerSidecar
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.test_kube import _node, _pod


@pytest.fixture()
def sidecar():
    s = PlannerSidecar(ReschedulerConfig(), "127.0.0.1:0")
    s.start_background()
    yield s
    s.close()


def _post(sidecar, body):
    req = urllib.request.Request(
        f"http://{sidecar.address}/v1/plan",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def test_healthz(sidecar):
    with urllib.request.urlopen(
        f"http://{sidecar.address}/healthz", timeout=10
    ) as resp:
        out = json.loads(resp.read())
    assert out["ok"] is True
    # the service half: queue depth, per-bucket occupancy, per-tenant
    # last-plan ages and the measured batch cadence ride along so a
    # probe can see a starving tenant without scraping Prometheus
    assert out["queue_depth"] == 0
    assert out["bucket_occupancy"] == {}
    assert out["tenant_last_plan_age_s"] == {}
    assert "batch_cadence_s" in out and "batch_window_s" in out


def test_plan_over_http(sidecar):
    body = {
        "nodes": [_node("od-1", "worker"), _node("spot-1", "spot-worker")],
        "pods": [_pod("a", "od-1", cpu="300m"), _pod("b", "od-1", cpu="200m")],
        "pdbs": [],
    }
    out = _post(sidecar, body)
    assert out["found"] is True
    assert out["node"] == "od-1"
    assert out["assignments"] == {"default/a": "spot-1", "default/b": "spot-1"}
    assert out["nCandidates"] == 1 and out["nFeasible"] == 1


def test_plan_infeasible(sidecar):
    body = {
        "nodes": [_node("od-1", "worker"), _node("spot-1", "spot-worker", cpu="100m")],
        "pods": [_pod("a", "od-1", cpu="1900m")],
    }
    out = _post(sidecar, body)
    assert out["found"] is False
    assert out["nFeasible"] == 0


def test_bad_request(sidecar):
    req = urllib.request.Request(
        f"http://{sidecar.address}/v1/plan",
        data=b"not json",
        method="POST",
    )
    try:
        urllib.request.urlopen(req, timeout=10)
        raised = False
    except urllib.error.HTTPError as err:
        raised = True
        assert err.code == 400
    assert raised


def _post_raw(sidecar, data, headers=None):
    req = urllib.request.Request(
        f"http://{sidecar.address}/v1/plan",
        data=data,
        headers=headers or {"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read())


def test_oversized_snapshot_rejected():
    s = PlannerSidecar(
        ReschedulerConfig(solver="numpy"), "127.0.0.1:0", max_body_bytes=1024
    )
    s.start_background()
    try:
        code, body = _post_raw(s, b"x" * 2048)
        assert code == 413
        assert "limit" in body["error"]
        # the server survives and stays healthy
        with urllib.request.urlopen(
            f"http://{s.address}/healthz", timeout=10
        ) as resp:
            assert json.loads(resp.read())["ok"] is True
    finally:
        s.close()


def test_busy_timeout_yields_503():
    """A request that cannot be batched within busy_timeout_s gets 503 +
    Retry-After instead of queueing unboundedly."""
    import threading
    import time

    s = PlannerSidecar(
        ReschedulerConfig(solver="numpy"), "127.0.0.1:0", busy_timeout_s=0.2
    )
    real_host = s.service._solve_host

    def slow_solve(stacked, reqs):
        time.sleep(1.5)
        return real_host(stacked)

    s.service.solve_hook = slow_solve
    s.start_background()
    try:
        body = json.dumps({
            "nodes": [_node("od-1", "worker"), _node("spot-1", "spot-worker")],
            "pods": [_pod("a", "od-1", cpu="100m")],
        }).encode()
        results = []

        def fire():
            results.append(_post_raw(s, body))

        # first request rides the first batch and holds the (slow) solve
        first = threading.Thread(target=fire)
        first.start()
        time.sleep(0.5)  # batch window passed; the 1.5 s solve is in flight
        # these arrive while the scheduler is busy: still QUEUED past the
        # 0.2 s bounded wait -> evicted with 503 + Retry-After
        late = [threading.Thread(target=fire) for _ in range(2)]
        for t in late:
            t.start()
        for t in [first] + late:
            t.join()
        codes = sorted(c for c, _ in results)
        assert codes[0] == 200, f"no request succeeded: {results}"
        assert 503 in codes, f"no request saw backpressure: {codes}"
        rejected = [out for code, out in results if code == 503]
        assert all("queue timeout" in out["error"] for out in rejected)
    finally:
        s.close()


def test_concurrent_requests_all_served():
    """Within the busy timeout, concurrent requests serialize on the solve
    lock and all succeed."""
    import threading

    s = PlannerSidecar(
        ReschedulerConfig(solver="numpy"), "127.0.0.1:0",
        busy_timeout_s=30.0, max_inflight=8,
    )
    s.start_background()
    try:
        body = json.dumps({
            "nodes": [_node("od-1", "worker"), _node("spot-1", "spot-worker")],
            "pods": [_pod("a", "od-1", cpu="100m")],
        }).encode()
        results = []

        def fire():
            results.append(_post_raw(s, body))

        threads = [threading.Thread(target=fire) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 8
        assert all(code == 200 for code, _ in results), results
        assert all(out["found"] for _, out in results)
    finally:
        s.close()


def test_inflight_depth_cap_rejects_immediately():
    """Past max_inflight concurrent requests, /v1/plan 503s IMMEDIATELY —
    before reading the body — so a burst of oversize-adjacent requests
    holds at most max_inflight bodies in memory (the busy timeout alone
    capped queue time, not depth)."""
    import threading
    import time

    s = PlannerSidecar(
        ReschedulerConfig(solver="numpy"), "127.0.0.1:0",
        busy_timeout_s=30.0, max_inflight=2,
    )
    release = threading.Event()
    real_host = s.service._solve_host

    def gated_solve(stacked, reqs):
        release.wait(timeout=30)
        return real_host(stacked)

    s.service.solve_hook = gated_solve
    s.start_background()
    try:
        body = json.dumps({
            "nodes": [_node("od-1", "worker"), _node("spot-1", "spot-worker")],
            "pods": [_pod("a", "od-1", cpu="100m")],
        }).encode()
        slow_results = []

        def fire_slow():
            slow_results.append(_post_raw(s, body))

        # fill both inflight slots: one solving (gated), one lock-waiting
        occupants = [threading.Thread(target=fire_slow) for _ in range(2)]
        for t in occupants:
            t.start()
            time.sleep(0.2)

        # burst past the cap: each must reject fast (well under the 30 s
        # busy timeout) while the gate still holds both slots
        t0 = time.monotonic()
        burst = [_post_raw(s, body) for _ in range(4)]
        burst_s = time.monotonic() - t0
        assert all(code == 503 for code, _ in burst), burst
        assert all("overloaded" in out["error"] for _, out in burst), burst
        assert burst_s < 5.0, f"depth rejection waited: {burst_s:.1f}s"

        release.set()
        for t in occupants:
            t.join()
        assert sorted(c for c, _ in slow_results) == [200, 200], slow_results
        # slots drain: a fresh request is admitted again
        code, out = _post_raw(s, body)
        assert code == 200 and out["found"]
    finally:
        release.set()
        s.close()


def _post_raw_headers(s, data, headers=None):
    """(status, body, response headers) — Retry-After assertions need
    the header surface, which _post_raw drops."""
    req = urllib.request.Request(
        f"http://{s.address}/v1/plan",
        data=data,
        headers=headers or {"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read()), dict(err.headers)


def test_retry_after_derives_from_measured_batch_cadence():
    """Regression (multi-tenant promotion): the 503 Retry-After value is
    the MEASURED batch cadence — how long until a batch slot actually
    frees — not the static busy timeout. Two layers: the cadence EMA
    itself under a virtual clock, and the HTTP header carrying it."""
    import threading
    import time

    import numpy as np

    from k8s_spot_rescheduler_tpu.service.server import PlannerService
    from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
    from tests.test_service import tiny_packed

    # --- cadence measurement, virtual clock, no threads ---
    clock = FakeClock()
    svc = PlannerService(
        ReschedulerConfig(solver="numpy"), clock=clock, batch_window_s=0
    )
    svc.solve_hook = lambda stacked, reqs: np.zeros(
        (stacked.slot_req.shape[0], 3 + stacked.slot_req.shape[2]), np.int32
    )
    assert svc.retry_after() == 1  # no batch yet: the floor, not 30
    for _ in range(4):  # batches complete 7 s apart
        svc.submit_nowait("a", tiny_packed())
        assert svc.drain_once()
        clock.advance(7.0)
    assert svc._cadence_s == pytest.approx(7.0)
    assert svc.retry_after() == 7  # ceil of the EMA, not busy_timeout

    # --- the header: a depth-cap 503 carries the measured cadence ---
    s = PlannerSidecar(
        ReschedulerConfig(solver="numpy"), "127.0.0.1:0",
        busy_timeout_s=30.0, max_inflight=1,
    )
    release = threading.Event()
    real_host = s.service._solve_host
    s.service.solve_hook = lambda stacked, reqs: (
        release.wait(timeout=30), real_host(stacked)
    )[1]
    s.service._cadence_s = 7.0  # as measured above
    s.start_background()
    try:
        body = json.dumps({
            "nodes": [_node("od-1", "worker"), _node("spot-1", "spot-worker")],
            "pods": [_pod("a", "od-1", cpu="100m")],
        }).encode()
        occupant = threading.Thread(
            target=lambda: _post_raw(s, body)
        )
        occupant.start()
        time.sleep(0.3)  # the lone inflight slot is held
        code, out, headers = _post_raw_headers(s, body)
        assert code == 503
        assert headers.get("Retry-After") == "7", headers
        release.set()
        occupant.join()
    finally:
        release.set()
        s.close()


def test_inprocess_plan_without_server_is_synchronous():
    """The documented in-process entry — PlannerSidecar.plan() with no
    HTTP server or scheduler thread started — solves on the caller's
    thread (the historical synchronous contract), not a 30 s timeout
    against a scheduler nobody started."""
    s = PlannerSidecar(ReschedulerConfig(solver="numpy"), "127.0.0.1:0")
    try:
        out = s.plan({
            "nodes": [_node("od-1", "worker"), _node("spot-1", "spot-worker")],
            "pods": [_pod("a", "od-1", cpu="300m")],
        })
        assert out["found"] is True and out["node"] == "od-1"
    finally:
        s.close()


def test_healthz_reports_tenant_ages_after_plans(sidecar):
    """After a plan, /healthz shows the tenant's last-plan age and the
    measured cadence — the per-tenant starvation surface."""
    body = {
        "nodes": [_node("od-1", "worker"), _node("spot-1", "spot-worker")],
        "pods": [_pod("a", "od-1", cpu="300m")],
    }
    out = _post(sidecar, body)
    assert out["found"] is True
    with urllib.request.urlopen(
        f"http://{sidecar.address}/healthz", timeout=10
    ) as resp:
        health = json.loads(resp.read())
    ages = health["tenant_last_plan_age_s"]
    assert "default" in ages and ages["default"] >= 0.0


def test_negative_content_length_rejected():
    """A negative Content-Length must not reach rfile.read(-1) (which
    would buffer until EOF, bypassing the size cap)."""
    import http.client

    s = PlannerSidecar(
        ReschedulerConfig(solver="numpy"), "127.0.0.1:0", max_body_bytes=1024
    )
    s.start_background()
    try:
        host, _, port = s.address.rpartition(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=10)
        conn.putrequest("POST", "/v1/plan", skip_accept_encoding=True)
        conn.putheader("Content-Length", "-1")
        conn.endheaders()
        resp = conn.getresponse()
        assert resp.status == 400
        conn.close()
    finally:
        s.close()
