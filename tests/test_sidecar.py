"""Planner-sidecar tests: the solver behind its JSON/HTTP boundary."""

import json
import urllib.request

import pytest

from k8s_spot_rescheduler_tpu.sidecar.server import PlannerSidecar
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.test_kube import _node, _pod


@pytest.fixture()
def sidecar():
    s = PlannerSidecar(ReschedulerConfig(), "127.0.0.1:0")
    s.start_background()
    yield s
    s.close()


def _post(sidecar, body):
    req = urllib.request.Request(
        f"http://{sidecar.address}/v1/plan",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def test_healthz(sidecar):
    with urllib.request.urlopen(
        f"http://{sidecar.address}/healthz", timeout=10
    ) as resp:
        assert json.loads(resp.read())["ok"] is True


def test_plan_over_http(sidecar):
    body = {
        "nodes": [_node("od-1", "worker"), _node("spot-1", "spot-worker")],
        "pods": [_pod("a", "od-1", cpu="300m"), _pod("b", "od-1", cpu="200m")],
        "pdbs": [],
    }
    out = _post(sidecar, body)
    assert out["found"] is True
    assert out["node"] == "od-1"
    assert out["assignments"] == {"default/a": "spot-1", "default/b": "spot-1"}
    assert out["nCandidates"] == 1 and out["nFeasible"] == 1


def test_plan_infeasible(sidecar):
    body = {
        "nodes": [_node("od-1", "worker"), _node("spot-1", "spot-worker", cpu="100m")],
        "pods": [_pod("a", "od-1", cpu="1900m")],
    }
    out = _post(sidecar, body)
    assert out["found"] is False
    assert out["nFeasible"] == 0


def test_bad_request(sidecar):
    req = urllib.request.Request(
        f"http://{sidecar.address}/v1/plan",
        data=b"not json",
        method="POST",
    )
    try:
        urllib.request.urlopen(req, timeout=10)
        raised = False
    except urllib.error.HTTPError as err:
        raised = True
        assert err.code == 400
    assert raised
