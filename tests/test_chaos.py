"""Chaos-hardening tests (docs/ROBUSTNESS.md): the seeded fault-injection
client, the retrying kube read path, planner crash containment, crash-safe
drain recovery, the observe-error circuit breaker — and the headline
seeded soak: hundreds of ticks under a FaultPlan with zero loop crashes,
zero orphaned ToBeDeleted taints at end-state, and drains resuming once
faults clear."""

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from k8s_spot_rescheduler_tpu.io.chaos import (
    ChaosClusterClient,
    ChaosError,
    ChaosInterrupt,
    FaultPlan,
)
from k8s_spot_rescheduler_tpu.io.cluster import EvictionError
from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
from k8s_spot_rescheduler_tpu.io.kube import (
    KubeClusterClient,
    transient_http_error,
)
from k8s_spot_rescheduler_tpu.loop import health
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.metrics.registry import robustness_snapshot
from k8s_spot_rescheduler_tpu.models.cluster import (
    TO_BE_DELETED_TAINT,
    Taint,
    parse_rescheduler_taint_value,
    rescheduler_taint_identity,
    rescheduler_taint_value,
)
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.fixtures import ON_DEMAND_LABELS, SPOT_LABELS, make_node, make_pod


@pytest.fixture(autouse=True)
def _reset_health():
    health.STATE.reset()
    yield
    health.STATE.reset()


def _setup(plan=None, solver="numpy", reschedule=True, **cfg_overrides):
    clock = FakeClock()
    fc = FakeCluster(clock, reschedule_evicted=reschedule)
    client = fc if plan is None else ChaosClusterClient(fc, plan, clock=clock)
    config = ReschedulerConfig(solver=solver, **cfg_overrides)
    planner = SolverPlanner(config)
    r = Rescheduler(client, planner, config, clock=clock, recorder=client)
    return fc, client, clock, r


def _drainable_cluster(fc):
    fc.add_node(make_node("od-small", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_node(make_node("spot-2", SPOT_LABELS))
    for i, cpu in enumerate([300, 200, 100]):
        fc.add_pod(make_pod(f"small-{i}", cpu, "od-small"))


def _has_orphan_taint(fc, name="od-small"):
    return any(t.key == TO_BE_DELETED_TAINT for t in fc.nodes[name].taints)


def _owned_taint(r, clock):
    """A ToBeDeleted taint exactly as ``r``'s own drain path writes it —
    the residue an interrupted drain of this replica leaves behind."""
    return Taint(
        TO_BE_DELETED_TAINT,
        rescheduler_taint_value(r.identity, clock.wall()),
        "NoSchedule",
    )


# --- the fault-injection client itself ---


def test_fault_plan_deterministic():
    """Same seed + same call sequence => identical injected faults."""

    def run(seed):
        fc = FakeCluster(FakeClock())
        fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
        chaos = ChaosClusterClient(
            fc, FaultPlan(seed=seed, error_rates={"list_ready_nodes": 0.3})
        )
        outcomes = []
        for _ in range(60):
            try:
                chaos.list_ready_nodes()
                outcomes.append("ok")
            except ChaosError:
                outcomes.append("err")
        return outcomes, dict(chaos.stats)

    a_out, a_stats = run(11)
    b_out, b_stats = run(11)
    c_out, _ = run(12)
    assert a_out == b_out and a_stats == b_stats
    assert "err" in a_out and "ok" in a_out  # both branches exercised
    assert a_out != c_out  # different seed, different stream


def test_scripted_fail_n_then_succeed():
    fc = FakeCluster(FakeClock())
    chaos = ChaosClusterClient(
        fc, FaultPlan(fail_n={"list_unschedulable_pods": 2})
    )
    for _ in range(2):
        with pytest.raises(ChaosError):
            chaos.list_unschedulable_pods()
    assert chaos.list_unschedulable_pods() == []


def test_scripted_429_evictions_then_success():
    clock = FakeClock()
    fc = FakeCluster(clock)
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    pod = make_pod("p", 100, "od-1")
    fc.add_pod(pod)
    chaos = ChaosClusterClient(
        fc, FaultPlan(evict_429={pod.uid: 2}), clock=clock
    )
    for _ in range(2):
        with pytest.raises(EvictionError, match="429"):
            chaos.evict_pod(pod, 30)
    chaos.evict_pod(pod, 30)
    assert fc.evictions == [pod.uid]


def test_quiesce_disables_faults():
    fc = FakeCluster(FakeClock())
    chaos = ChaosClusterClient(
        fc, FaultPlan(error_rates={"list_pdbs": 1.0})
    )
    with pytest.raises(ChaosError):
        chaos.list_pdbs()
    chaos.enabled = False
    assert chaos.list_pdbs() == []


def test_chaos_blocks_columnar_shortcut():
    """The wrapper must force the object observe path — the columnar
    store reads cluster state directly, bypassing every faulted verb."""
    fc = FakeCluster(FakeClock())
    chaos = ChaosClusterClient(fc, FaultPlan())
    assert getattr(chaos, "columnar_store", None) is None
    assert chaos.clock is None or True  # other attrs still delegate
    assert chaos.list_ready_nodes() == []


def test_watch_stream_drop_injection():
    """The _stream hook (wired under the watch cache by cli/main.py)
    drops a healthy stream mid-flight with a connection reset."""

    class StreamStub:
        def _stream(self, path, read_timeout=330.0):
            for i in range(10_000):
                yield {"n": i}

    chaos = ChaosClusterClient(
        StreamStub(), FaultPlan(seed=1, watch_drop_rate=0.2)
    )
    seen = 0
    with pytest.raises(ConnectionResetError):
        for _ in chaos._stream("/api/v1/pods?watch=1"):
            seen += 1
    assert 0 < seen < 10_000  # some events delivered, then the drop
    assert chaos.stats["watch_drop"] == 1
    # quiesced stream runs clean
    chaos.enabled = False
    assert sum(1 for _ in chaos._stream("/x")) == 10_000


# --- retrying kube reads ---


class _RetryStub:
    """Stub apiserver whose LIST fails a scripted number of times."""

    def __init__(self, fail_codes, retry_after="1"):
        self.fail_codes = list(fail_codes)  # consumed per GET
        self.calls = 0
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                stub.calls += 1
                if stub.fail_codes:
                    code = stub.fail_codes.pop(0)
                    body = b"{}"
                    self.send_response(code)
                    if retry_after is not None:
                        self.send_header("Retry-After", retry_after)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                body = json.dumps({"items": []}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.server = HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    @property
    def url(self):
        host, port = self.server.server_address
        return f"http://{host}:{port}"

    def close(self):
        self.server.shutdown()


def test_read_retry_two_429s_then_success():
    """Acceptance: two 429s then 200 => exactly one successful LIST,
    kube_request_retries_total == 2, and each backoff sleep observes the
    server's Retry-After."""
    stub = _RetryStub([429, 429], retry_after="1")
    sleeps = []
    try:
        client = KubeClusterClient(
            stub.url, retry_base=0.001, retry_sleep=sleeps.append
        )
        before = robustness_snapshot()
        assert client.list_pdbs() == []
        diff = {
            k: robustness_snapshot()[k] - before[k]
            for k in ("kube_request_retries", "kube_request_failures")
        }
        assert stub.calls == 3  # 2 rejected + 1 served
        assert diff == {"kube_request_retries": 2, "kube_request_failures": 0}
        # tiny base backoff (1ms) must be floored by Retry-After: 1
        assert len(sleeps) == 2 and all(s >= 1.0 for s in sleeps)
    finally:
        stub.close()


def test_read_retry_honors_retry_after_but_caps_it():
    """Flow control is deferred to, but one bad header (a degraded LB
    saying 'Retry-After: 3600') must not stall the tick for an hour
    inside a single read."""
    stub = _RetryStub([503], retry_after="3600")
    sleeps = []
    try:
        client = KubeClusterClient(
            stub.url, retry_base=0.001, retry_sleep=sleeps.append
        )
        assert client.list_pdbs() == []
        assert len(sleeps) == 1 and sleeps[0] <= 30.0
    finally:
        stub.close()


def test_read_retry_5xx_and_exhaustion():
    stub = _RetryStub([503, 503, 503, 503], retry_after=None)
    sleeps = []
    try:
        client = KubeClusterClient(
            stub.url, retry_max=2, retry_base=0.001,
            retry_sleep=sleeps.append,
        )
        before = robustness_snapshot()
        with pytest.raises(urllib.error.HTTPError):
            client.list_pdbs()
        after = robustness_snapshot()
        assert after["kube_request_retries"] - before["kube_request_retries"] == 2
        assert (
            after["kube_request_failures"] - before["kube_request_failures"]
            == 1
        )
        assert stub.calls == 3  # initial + retry_max attempts
    finally:
        stub.close()


def test_read_404_not_retried():
    stub = _RetryStub([404, 404, 404], retry_after=None)
    try:
        client = KubeClusterClient(stub.url, retry_base=0.001)
        before = robustness_snapshot()
        assert client.get_pod("default", "ghost") is None
        assert stub.calls == 1  # a real answer, not a flake
        after = robustness_snapshot()
        assert after["kube_request_retries"] == before["kube_request_retries"]
    finally:
        stub.close()


def test_write_verbs_single_attempt():
    """Evictions stay single-attempt even on 429 — the actuator owns
    their retry cadence (scaler.go:47-62)."""
    stub = _RetryStub([429, 429, 429], retry_after="1")
    try:
        client = KubeClusterClient(stub.url, retry_base=0.001)
        before = robustness_snapshot()
        with pytest.raises(EvictionError):
            client.evict_pod(make_pod("p", 100, "od-1"), 30)
        # the stub rejects the POST's GET-agnostic handler? no GETs ran:
        assert robustness_snapshot()["kube_request_retries"] == (
            before["kube_request_retries"]
        )
    finally:
        stub.close()


def test_transient_classification():
    err_429 = urllib.error.HTTPError("u", 429, "Too Many", {}, None)
    assert transient_http_error(err_429)[0] is True
    assert transient_http_error(
        urllib.error.HTTPError("u", 500, "ISE", {}, None)
    ) == (True, None)
    assert transient_http_error(
        urllib.error.HTTPError("u", 404, "NF", {}, None)
    ) == (False, None)
    assert transient_http_error(ConnectionResetError("rst")) == (True, None)
    assert transient_http_error(TimeoutError()) == (True, None)
    assert transient_http_error(ValueError("bad json")) == (False, None)
    # certificate verification can never succeed on retry — a
    # misconfigured CA bundle/hostname must surface immediately, not
    # burn the backoff budget on every read
    import ssl

    cert_err = ssl.SSLCertVerificationError(
        "certificate verify failed: unable to get local issuer certificate"
    )
    assert transient_http_error(cert_err) == (False, None)
    assert transient_http_error(urllib.error.URLError(cert_err)) == (
        False,
        None,
    )
    # a non-cert TLS hiccup (handshake reset) stays retryable
    assert transient_http_error(
        urllib.error.URLError(ConnectionResetError("tls reset"))
    ) == (True, None)


# --- skip-tick-on-error policy ---


def test_unschedulable_list_failure_skips_tick():
    """An unknown unschedulable-pods state must SKIP the tick, not be
    treated as 'zero pods' — that would defeat the don't-make-things-
    worse gate exactly when the apiserver is flaky."""
    fc, chaos, clock, r = _setup(
        plan=FaultPlan(fail_n={"list_unschedulable_pods": 1})
    )
    _drainable_cluster(fc)
    result = r.tick()
    assert result.skipped == "error"
    assert fc.evictions == []
    # fault consumed: the next tick proceeds and drains
    assert r.tick().drained == ["od-small"]


# --- planner crash containment ---


class _PoisonedPlanner:
    """Raises from every dispatch shape the controller knows."""

    accepts_columnar = False

    def __init__(self, async_mode=None):
        self.async_mode = async_mode  # None | "dispatch" | "fetch"
        if async_mode is not None:
            self.plan_async = self._plan_async

    def plan(self, observation, pdbs):
        raise RuntimeError("solver exploded (poisoned)")

    def _plan_async(self, observation, pdbs):
        if self.async_mode == "dispatch":
            raise RuntimeError("solver exploded at dispatch (poisoned)")

        def finish():
            raise RuntimeError("solver exploded at fetch (poisoned)")

        return finish


@pytest.mark.parametrize("async_mode", [None, "dispatch", "fetch"])
def test_planner_exception_degrades_to_fallback(async_mode):
    fc, _, clock, r = _setup()
    _drainable_cluster(fc)
    r.planner = _PoisonedPlanner(async_mode)
    before = robustness_snapshot()
    result = r.tick()
    # the tick completed on the numpy-oracle fallback — and still drained
    assert result.skipped == ""
    assert result.planner_fallback is True
    assert result.drained == ["od-small"]
    after = robustness_snapshot()
    assert after["planner_fallback"] - before["planner_fallback"] == 1
    snap = health.snapshot()
    assert snap["degraded"] is True
    assert snap["planner_fallback_total"] == 1
    assert snap["last_successful_tick_age_s"] is not None


def test_degraded_clears_on_clean_primary_tick():
    fc, _, clock, r = _setup()
    _drainable_cluster(fc)
    r.planner = _PoisonedPlanner()
    assert r.tick().planner_fallback is True
    assert health.snapshot()["degraded"] is True
    # planner healed (e.g. device back); next completed tick clears it
    r.planner = SolverPlanner(r.config)
    clock.advance(700.0)
    result = r.tick()
    assert result.skipped == "" and result.planner_fallback is False
    assert health.snapshot()["degraded"] is False


def test_both_planners_failing_skips_tick():
    fc, _, clock, r = _setup()
    _drainable_cluster(fc)
    r.planner = _PoisonedPlanner()
    r._fallback_planner = _PoisonedPlanner()
    result = r.tick()
    assert result.skipped == "error"
    assert fc.evictions == []


def test_planner_fallback_counters_agree():
    """/healthz's planner_fallback_total and the Prometheus counter are
    driven by the same event (one per contained planner exception) —
    including ticks where the fallback failed too — so the two surfaces
    never diverge."""
    fc, _, clock, r = _setup()
    _drainable_cluster(fc)
    r.planner = _PoisonedPlanner()
    before = robustness_snapshot()["planner_fallback"]
    r._fallback_planner = _PoisonedPlanner()
    assert r.tick().skipped == "error"  # primary raised AND fallback died
    r._fallback_planner = None  # lazily rebuilt as the real numpy oracle
    assert r.tick().planner_fallback is True  # primary raised, fallback ran
    prom = robustness_snapshot()["planner_fallback"] - before
    assert prom == 2
    assert health.snapshot()["planner_fallback_total"] == prom


# --- circuit breaker ---


def test_breaker_widens_interval_and_resets():
    fc, chaos, clock, r = _setup(
        plan=FaultPlan(fail_n={"list_unschedulable_pods": 5}),
        breaker_threshold=2,
        housekeeping_interval=10.0,
        breaker_max_interval=80.0,
    )
    assert r.effective_interval() == 10.0
    expected = [10.0, 20.0, 40.0, 80.0, 80.0]  # after error #1..#5 (capped)
    for want in expected:
        assert r.tick().skipped == "error"
        assert r.effective_interval() == want
    assert health.snapshot()["breaker_interval_s"] == 80.0
    assert health.snapshot()["degraded"] is True
    # faults exhausted: the next tick completes, breaker + degraded reset
    assert r.tick().skipped == ""
    assert r.effective_interval() == 10.0
    assert health.snapshot()["degraded"] is False
    assert health.snapshot()["breaker_interval_s"] is None


def test_breaker_resets_on_healthy_unschedulable_skip():
    """An unschedulable-gate skip PROVES the observe path is healthy —
    it must reset the breaker even though the tick never completes."""
    fc, chaos, clock, r = _setup(
        plan=FaultPlan(fail_n={"list_unschedulable_pods": 4}),
        breaker_threshold=2,
        housekeeping_interval=10.0,
        breaker_max_interval=80.0,
    )
    for _ in range(4):
        assert r.tick().skipped == "error"
    assert r.effective_interval() == 80.0  # breaker engaged (capped)
    assert health.snapshot()["degraded"] is True
    # apiserver heals, but a perpetually-Pending pod holds the gate
    fc.pending.append(make_pod("homeless", 100))
    assert r.tick().skipped == "unschedulable"
    assert r.effective_interval() == 10.0  # breaker reset
    assert health.snapshot()["degraded"] is False
    assert health.snapshot()["breaker_interval_s"] is None


def test_unschedulable_skip_keeps_fallback_degradation():
    """The same gate skip must NOT clear fallback-planner degradation —
    only a completed tick proves the planner healthy again."""
    fc, _, clock, r = _setup(node_drain_delay=0.0)
    _drainable_cluster(fc)
    r.planner = _PoisonedPlanner()
    assert r.tick().planner_fallback is True
    assert health.snapshot()["degraded"] is True
    fc.pending.append(make_pod("homeless", 100))
    assert r.tick().skipped == "unschedulable"
    assert health.snapshot()["degraded"] is True  # planner still suspect


def test_taint_ownership_value_is_legal_and_collision_free():
    """The ownership value must be valid k8s label-value syntax (<=63
    chars, ends alphanumeric — an illegal value would 422 every
    add_taint), and two replicas whose pod names differ only in the
    TRAILING hash must never embed the same identity (a shared 'own'
    identity would let one sweep the other's live drain)."""
    import re as _re

    label_value = _re.compile(r"^[A-Za-z0-9]([A-Za-z0-9_.\-]*[A-Za-z0-9])?$")
    long_a = "k8s-spot-rescheduler-tpu-controller-7d9f8b6c4-xk2lp"
    long_b = "k8s-spot-rescheduler-tpu-controller-7d9f8b6c4-ab9qz"
    assert rescheduler_taint_identity(long_a) != rescheduler_taint_identity(
        long_b
    )
    for ident in (long_a, long_b, "", "host_", "a" * 33 + "-" + "b" * 30,
                  "plain-host"):
        value = rescheduler_taint_value(ident, 1722772800.0)
        assert len(value) <= 63
        assert label_value.match(value), value
        holder, ts = parse_rescheduler_taint_value(value)
        assert holder == rescheduler_taint_identity(ident)
        assert ts == 1722772800.0
    # non-marker values (CA's bare timestamp) never parse as ours
    assert parse_rescheduler_taint_value("1722772800") is None
    assert parse_rescheduler_taint_value("") is None


def test_retaint_replaces_own_value_keeps_foreign_heals_unparsable():
    """Re-tainting refreshes OUR ownership stamp (a kept stale stamp
    would age past the grace horizon under a live drain) but never
    steals a FOREIGN same-key entry (CA's scale-down marker — stealing
    it would let the sweep later strip it and abort CA's deletion);
    and a marked taint whose stamp doesn't parse sweeps as infinitely
    old rather than surviving forever."""

    def tbd_values(fc, name):
        return [t.value for t in fc.nodes[name].taints
                if t.key == TO_BE_DELETED_TAINT]

    fc, _, clock, r = _setup()
    fc.add_node(make_node("od-small", ON_DEMAND_LABELS))
    # own marker, re-tainted: REPLACED (one entry, newest stamp)
    fc.add_taint("od-small", Taint(
        TO_BE_DELETED_TAINT, rescheduler_taint_value("me", 100.0),
        "NoSchedule"))
    fc.add_taint("od-small", Taint(
        TO_BE_DELETED_TAINT, rescheduler_taint_value("me", 200.0),
        "NoSchedule"))
    assert tbd_values(fc, "od-small") == [
        rescheduler_taint_value("me", 200.0)
    ]
    # foreign bare-timestamp value already present: OUR add keeps theirs
    fc.remove_taint("od-small", TO_BE_DELETED_TAINT)
    fc.add_taint("od-small", Taint(
        TO_BE_DELETED_TAINT, "1722772800", "NoSchedule"))
    fc.add_taint("od-small", Taint(
        TO_BE_DELETED_TAINT, rescheduler_taint_value("me", 300.0),
        "NoSchedule"))
    assert tbd_values(fc, "od-small") == ["1722772800"]
    # marked value with a mangled timestamp segment: swept immediately
    fc.remove_taint("od-small", TO_BE_DELETED_TAINT)
    fc.add_taint("od-small", Taint(
        TO_BE_DELETED_TAINT, "spot-rescheduler_mangled_other",
        "NoSchedule"))
    assert r.tick().recovered_taints == ["od-small"]
    assert not _has_orphan_taint(fc)


def test_sweep_leaves_foreign_nodes_alone():
    """ToBeDeleted taints on non-on-demand nodes belong to the cluster
    autoscaler's own scale-downs — the sweep must not fight CA."""
    fc, _, clock, r = _setup()
    _drainable_cluster(fc)
    fc.add_taint("spot-1", Taint(TO_BE_DELETED_TAINT, "", "NoSchedule"))
    result = r.tick()
    assert result.recovered_taints == []
    assert any(
        t.key == TO_BE_DELETED_TAINT for t in fc.nodes["spot-1"].taints
    )


# --- crash-safe drain recovery ---


def test_mid_drain_crash_recovers_on_restart():
    """Satellite: interrupt a drain right after add_taint (simulated
    process death), restart the controller against the same cluster —
    the startup sweep untaints, emits ReschedulerRecovered, and the node
    drains cleanly on a later tick."""
    clock = FakeClock()
    fc = FakeCluster(clock, reschedule_evicted=True)
    _drainable_cluster(fc)
    config = ReschedulerConfig(solver="numpy")
    chaos = ChaosClusterClient(
        fc, FaultPlan(interrupt_on_taint=1), clock=clock
    )
    r = Rescheduler(
        chaos, SolverPlanner(config), config, clock=clock, recorder=chaos
    )
    with pytest.raises(ChaosInterrupt):
        r.tick()
    # the crash left the ToBeDeleted residue and evicted nothing
    assert _has_orphan_taint(fc)
    assert fc.evictions == []
    assert r._active_drains == set()

    before = robustness_snapshot()
    # "restart": a fresh controller against the same cluster
    r2 = Rescheduler(
        fc, SolverPlanner(config), config, clock=clock, recorder=fc
    )
    assert not _has_orphan_taint(fc)  # startup sweep healed it
    assert any(e.reason == "ReschedulerRecovered" for e in fc.events)
    after = robustness_snapshot()
    assert (
        after["orphaned_taints_recovered"]
        - before["orphaned_taints_recovered"]
        == 1
    )
    # and the interrupted drain completes on a later tick
    result = r2.tick()
    assert result.drained == ["od-small"]
    assert not _has_orphan_taint(fc)


def test_per_tick_sweep_heals_even_during_cooldown():
    fc, _, clock, r = _setup()
    _drainable_cluster(fc)
    fc.add_taint("od-small", _owned_taint(r, clock))
    r.next_drain_time = clock.now() + 600.0  # cooldown armed
    refreshes = []
    fc.refresh = lambda: refreshes.append(1)
    result = r.tick()
    assert result.skipped == "cooldown"
    assert result.recovered_taints == ["od-small"]
    assert not _has_orphan_taint(fc)
    # a recovery drops the client's cached node view, so a polling
    # client's later cooldown sweeps (which never reach the gate's
    # per-tick refresh) don't re-recover the same orphan
    assert refreshes == [1]
    assert r.tick().recovered_taints == []


def test_sweep_leaves_ca_taint_on_on_demand_node():
    """The REAL cluster autoscaler taints on-demand nodes too — its value
    is a bare unix timestamp, not the rescheduler marker. A drained-empty
    on-demand node mid CA scale-down must keep CA's taint, or the sweep
    would re-mark it schedulable and abort the very scale-down the
    rescheduler exists to cause."""
    fc, _, clock, r = _setup()
    _drainable_cluster(fc)
    # od-empty: drained earlier, now empty, tainted by CA (bare timestamp)
    fc.add_node(make_node("od-empty", ON_DEMAND_LABELS))
    fc.add_taint("od-empty", Taint(TO_BE_DELETED_TAINT, "1722772800", "NoSchedule"))
    for _ in range(3):
        result = r.tick()
        assert result.recovered_taints == []
    assert _has_orphan_taint(fc, "od-empty")  # CA's scale-down unobstructed


def test_sweep_foreign_replica_taint_waits_out_drain_horizon():
    """HA: a marked taint held by ANOTHER identity may be a demoted
    leader's still-running drain — swept only once older than any drain
    could run (taint_sweep_grace), never from under a live drain."""
    fc, _, clock, r = _setup()
    fc.add_node(make_node("od-small", ON_DEMAND_LABELS))
    fc.add_taint(
        "od-small",
        Taint(
            TO_BE_DELETED_TAINT,
            rescheduler_taint_value("other-replica", clock.wall()),
            "NoSchedule",
        ),
    )
    assert r.tick().recovered_taints == []  # fresh: possibly a live drain
    assert _has_orphan_taint(fc)
    clock.advance(r.taint_sweep_grace() + 1.0)
    assert r.tick().recovered_taints == ["od-small"]  # stale: orphan
    assert not _has_orphan_taint(fc)


def test_sweep_disabled_by_config():
    clock = FakeClock()
    fc = FakeCluster(clock)
    # no spot capacity: the node cannot drain, so only the sweep could
    # ever remove the orphaned taint — and it is configured off
    import socket

    fc.add_node(make_node("od-small", ON_DEMAND_LABELS))
    fc.add_pod(make_pod("stuck", 100, "od-small"))
    # an orphan THIS replica's own drain path left (default identity)
    fc.add_taint(
        "od-small",
        Taint(
            TO_BE_DELETED_TAINT,
            rescheduler_taint_value(socket.gethostname(), clock.wall()),
            "NoSchedule",
        ),
    )
    config = ReschedulerConfig(
        solver="numpy", reconcile_orphaned_taints=False
    )
    r = Rescheduler(fc, SolverPlanner(config), config, clock=clock)
    assert _has_orphan_taint(fc)  # startup sweep did not run
    r.tick()
    assert _has_orphan_taint(fc)  # nor the per-tick sweep


# --- drain verify-poll resilience ---


def test_verify_poll_survives_flaky_get():
    """Satellite: one flaky GET must not burn the round for all pods —
    the remaining pods are still checked and the drain succeeds."""
    from k8s_spot_rescheduler_tpu.actuator.drain import drain_node

    clock = FakeClock()
    fc = FakeCluster(clock)
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    pods = [make_pod(f"p{i}", 100, "od-1") for i in range(3)]
    for p in pods:
        fc.add_pod(p)
    checked = []
    original = fc.get_pod

    def spy(ns, name):
        checked.append(name)
        return original(ns, name)

    fc.get_pod = spy
    chaos = ChaosClusterClient(
        fc, FaultPlan(fail_n={"get_pod": 1}), clock=clock
    )
    drain_node(
        chaos, fc, fc.nodes["od-1"], pods,
        clock=clock, max_graceful_termination=30,
        pod_eviction_timeout=120.0, eviction_retry_time=10.0,
    )
    assert fc.list_pods_on_node("od-1") == []
    # round 1: p0's GET was chaos-failed BEFORE reaching the cluster, yet
    # p1 and p2 were still checked that same round
    assert checked[:2] == ["p1", "p2"]


def test_verify_poll_memoizes_confirmed_gone_pods():
    """A pod confirmed off the node is not re-GET-ed in later rounds —
    only the stragglers are."""
    from k8s_spot_rescheduler_tpu.actuator.drain import drain_node

    clock = FakeClock()
    fc = FakeCluster(clock)
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    pods = [make_pod(f"p{i}", 100, "od-1") for i in range(3)]
    for p in pods:
        fc.add_pod(p)
    # p0's eviction fails once -> evicted one retry round (10 s) later
    # than p1/p2, so the first verify round sees p1/p2 gone, p0 present
    fc.eviction_failures[pods[0].uid] = 1
    counts = {}
    original = fc.get_pod

    def spy(ns, name):
        counts[name] = counts.get(name, 0) + 1
        return original(ns, name)

    fc.get_pod = spy
    drain_node(
        fc, fc, fc.nodes["od-1"], pods,
        clock=clock, max_graceful_termination=30,
        pod_eviction_timeout=120.0, eviction_retry_time=10.0,
    )
    assert counts["p0"] == 2  # present in round 1, gone in round 2
    # p1/p2: observed gone in round 1, memoized (not re-polled per
    # round), then ONE fresh confirming read in the success round —
    # never 3+ however many rounds the stragglers take
    assert counts["p1"] == 2 and counts["p2"] == 2


def test_verify_confirm_round_rejects_anomalous_gone_verdict():
    """A single anomalous GET (e.g. a stale-serving client layer
    returning None for a live pod) must not let the drain declare the
    node empty: the success round re-confirms memoized verdicts, finds
    the pod back, and the drain keeps polling (failing honestly at the
    deadline here, since the pod never leaves)."""
    from k8s_spot_rescheduler_tpu.actuator.drain import DrainError, drain_node

    clock = FakeClock()
    fc = FakeCluster(clock)
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    pods = [make_pod(f"p{i}", 100, "od-1") for i in range(2)]
    for p in pods:
        fc.add_pod(p)
    original = fc.get_pod
    calls = {"p1": 0}

    def lying(ns, name):
        if name == "p1":
            calls["p1"] += 1
            if calls["p1"] == 1:
                return None  # the one anomalous "gone" observation
            # thereafter: honestly still on the node, forever
            return pods[1]
        return original(ns, name)

    fc.get_pod = lying
    with pytest.raises(DrainError, match="pods remaining"):
        drain_node(
            fc, fc, fc.nodes["od-1"], pods,
            clock=clock, max_graceful_termination=30,
            pod_eviction_timeout=30.0, eviction_retry_time=10.0,
        )
    # deferred cleanup still untainted the node
    assert fc.nodes["od-1"].taints == []


# --- /healthz surface ---


def test_sidecar_healthz_reports_degraded():
    from k8s_spot_rescheduler_tpu.sidecar.server import PlannerSidecar

    fc, _, clock, r = _setup()
    _drainable_cluster(fc)
    r.planner = _PoisonedPlanner()
    assert r.tick().planner_fallback is True

    sidecar = PlannerSidecar(ReschedulerConfig(solver="numpy"), "127.0.0.1:0")
    sidecar.start_background()
    try:
        with urllib.request.urlopen(
            f"http://{sidecar.address}/healthz", timeout=10
        ) as resp:
            payload = json.loads(resp.read())
    finally:
        sidecar.close()
    assert payload["ok"] is True
    assert payload["degraded"] is True
    assert payload["planner_fallback_total"] == 1
    assert payload["last_successful_tick_age_s"] is not None


# --- the headline chaos soak ---


def test_chaos_soak_300_ticks():
    """>=300 ticks under a seeded FaultPlan (>=10% error rates on
    list/get, scripted eviction 429s, one mid-drain interrupt): the loop
    never crashes, no ToBeDeleted taint survives at end-state, no node
    is drained twice without re-observation, and drains resume after the
    faults clear."""
    clock = FakeClock()
    fc = FakeCluster(clock, reschedule_evicted=True)
    for i in range(4):
        fc.add_node(make_node(f"od-{i}", ON_DEMAND_LABELS))
        fc.add_node(make_node(f"spot-{i}", SPOT_LABELS, cpu_millis=4000))
    seeds = []
    for i in range(4):
        for j in range(3):
            pod = make_pod(f"p{i}-{j}", 100, f"od-{i}")
            fc.add_pod(pod)
            seeds.append(pod.uid)
    plan = FaultPlan(
        seed=7,
        error_rates={
            "list_ready_nodes": 0.12,
            "list_unready_nodes": 0.05,
            "list_pods_on_node": 0.10,
            "list_unschedulable_pods": 0.12,
            "list_pdbs": 0.10,
            "get_pod": 0.10,
            "evict_pod": 0.05,
            "add_taint": 0.03,
            "remove_taint": 0.03,
        },
        evict_429={seeds[0]: 2, seeds[5]: 1, "default/churn-1": 2},
        stale_read_rate=0.05,
        interrupt_on_taint=3,  # the third drain attempt dies mid-taint
    )
    chaos = ChaosClusterClient(fc, plan, clock=clock)
    config = ReschedulerConfig(
        solver="numpy",
        housekeeping_interval=10.0,
        node_drain_delay=30.0,
        pod_eviction_timeout=60.0,
        eviction_retry_time=5.0,
    )
    planner = SolverPlanner(config)

    def make_controller():
        return Rescheduler(
            chaos, planner, config, clock=clock, recorder=chaos
        )

    r = make_controller()
    n_ticks, quiesce_at = 380, 330
    interrupts, completed = 0, 0
    drains = []  # (tick index, node)
    churn = 0
    for i in range(n_ticks):
        clock.sleep(config.housekeeping_interval)
        if i == quiesce_at:
            # pre-tick, so a ChaosInterrupt on this very tick cannot
            # `continue` past the quiesce and leave faults on forever
            chaos.enabled = False  # faults clear
        if i % 15 == 0:
            # cluster churn: new work lands on an on-demand node, so
            # there is always eventually something to drain
            target = f"od-{churn % 4}"
            fc.add_pod(make_pod(f"churn-{churn}", 100, target))
            churn += 1
        occupied = {
            name
            for name in fc.nodes
            if name.startswith("od-") and fc.list_pods_on_node(name)
        }
        try:
            result = r.tick()
        except ChaosInterrupt:
            interrupts += 1
            r = make_controller()  # process "restart" against same cluster
            continue
        completed += 1
        # the no-double-drain-without-re-observation invariant: every
        # drained node was observed WITH PODS at this tick's start (a
        # node drained off a stale/duplicated view would be empty here)
        assert set(result.drained) <= occupied
        drains.extend((i, n) for n in result.drained)
    assert completed >= 300
    assert interrupts == 1  # the scripted mid-drain crash fired exactly once
    assert chaos.stats["evict_429"] >= 1  # scripted 429s were exercised
    # drains resumed after the faults cleared
    assert any(i >= quiesce_at for i, _ in drains)
    assert len(drains) >= 3
    # end-state: zero orphaned ToBeDeleted taints anywhere
    for node in fc.nodes.values():
        assert not any(t.key == TO_BE_DELETED_TAINT for t in node.taints), (
            f"orphaned taint survived on {node.name}"
        )
    # nothing stranded: the closed loop kept re-placing evicted pods
    assert fc.pending == []
