"""NodeSelector and unmodeled-constraint predicates.

The kube-scheduler's NodeSelector predicate (part of the reference's
CheckPredicates surface, reference README.md:103-114) is encoded as
pseudo-taints in the interned constraint table (predicates/masks.py
``SelectorBit``/``UnplaceableBit``) — these tests pin the semantics across
the numpy oracle, the object packer, the columnar packer, and the full
control loop, plus the safe-direction conservatism for constraints the
framework does not model (required affinity, PVCs).
"""

from __future__ import annotations


import numpy as np

from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.models.cluster import build_node_map
from k8s_spot_rescheduler_tpu.models.tensors import pack_cluster
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.fixtures import (
    ON_DEMAND_LABEL,
    ON_DEMAND_LABELS,
    SPOT_LABEL,
    SPOT_LABELS,
    make_node,
    make_pod,
)


def _cluster():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(
        make_node("spot-plain", SPOT_LABELS)
    )
    gpu_labels = dict(SPOT_LABELS, **{"accelerator": "gpu"})
    fc.add_node(make_node("spot-gpu", gpu_labels))
    return fc


def _pack(fc, **kw):
    nodes = fc.list_ready_nodes()
    node_map = build_node_map(
        nodes,
        {n.name: fc.list_pods_on_node(n.name) for n in nodes},
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    return pack_cluster(node_map, fc.pdbs, resources=("cpu", "memory"), **kw)


def test_selector_restricts_placement_to_matching_spot():
    fc = _cluster()
    fc.add_pod(
        make_pod("gpu-pod", 300, "od-1",
                 node_selector={"accelerator": "gpu"})
    )
    packed, meta = _pack(fc)
    result = plan_oracle(packed)
    assert bool(result.feasible[0])
    # spot order: both empty -> insertion order (spot-plain first); the
    # pod must land on spot-gpu, not the first-probed plain node
    target = meta.spot[int(result.assignment[0, 0])].node.name
    assert target == "spot-gpu"


def test_selector_with_no_matching_spot_blocks_drain():
    fc = _cluster()
    fc.add_pod(
        make_pod("picky", 100, "od-1",
                 node_selector={"zone": "nowhere"})
    )
    packed, _ = _pack(fc)
    result = plan_oracle(packed)
    assert not result.feasible[:1].any()


def test_unmodeled_constraints_block_drain_conservatively():
    fc = _cluster()
    fc.add_pod(make_pod("pvc-pod", 100, "od-1", unmodeled_constraints=True))
    fc.add_pod(make_pod("free", 100, "od-1"))
    packed, _ = _pack(fc)
    result = plan_oracle(packed)
    # ample capacity everywhere, but the PVC pod is unplaceable -> the
    # node must NOT be provably drainable (safe direction)
    assert not result.feasible[:1].any()


def test_columnar_parity_with_selectors():
    fc = _cluster()
    fc.add_pod(make_pod("gpu-pod", 300, "od-1",
                        node_selector={"accelerator": "gpu"}))
    fc.add_pod(make_pod("plain", 200, "od-1"))
    fc.add_pod(make_pod("pvc", 100, "od-1", unmodeled_constraints=True))
    store = fc.columnar_store(
        ("cpu", "memory"),
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    obj, _ = _pack(fc)
    col, _ = store.pack(fc.pdbs)
    for field in obj._fields:
        np.testing.assert_array_equal(
            getattr(obj, field), getattr(col, field), err_msg=field
        )


def test_loop_drains_selector_pod_to_matching_node():
    clock = FakeClock()
    fc = FakeCluster(clock, reschedule_evicted=True)
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-plain", SPOT_LABELS))
    fc.add_node(make_node("spot-gpu", dict(SPOT_LABELS, accelerator="gpu")))
    fc.add_pod(make_pod("gpu-pod", 300, "od-1",
                        node_selector={"accelerator": "gpu"}))
    config = ReschedulerConfig(solver="numpy")
    r = Rescheduler(fc, SolverPlanner(config), config, clock=clock, recorder=fc)
    result = r.tick()
    assert result.drained == ["od-1"]
    # the fake scheduler honors the selector too: the pod landed on spot-gpu
    assert [p.name for p in fc.list_pods_on_node("spot-gpu")] == ["gpu-pod"]
    assert fc.list_pods_on_node("spot-plain") == []


def test_loop_never_drains_node_with_unmodeled_pod():
    clock = FakeClock()
    fc = FakeCluster(clock, reschedule_evicted=True)
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS, cpu_millis=8000))
    fc.add_pod(make_pod("pvc-pod", 100, "od-1", unmodeled_constraints=True))
    config = ReschedulerConfig(solver="numpy")
    r = Rescheduler(fc, SolverPlanner(config), config, clock=clock, recorder=fc)
    result = r.tick()
    assert result.drained == []
    assert result.report.n_feasible == 0
    assert fc.evictions == []


def test_native_decode_of_selector_affinity_pvc():
    import json
    import subprocess

    import pytest

    ROOT = __file__.rsplit("/tests/", 1)[0]
    proc = subprocess.run(["make", "native"], cwd=ROOT, capture_output=True)
    if proc.returncode != 0:
        pytest.skip("native build unavailable")
    from k8s_spot_rescheduler_tpu.io import native_ingest
    from k8s_spot_rescheduler_tpu.io.kube import decode_pod

    native_ingest._lib.cache_clear()
    if not native_ingest.available():
        pytest.skip("native library failed to load")

    objs = [
        {"metadata": {"name": "sel", "uid": "u1"},
         "spec": {"nodeName": "n1",
                  "nodeSelector": {"accelerator": "gpu", "zone": "a"},
                  "containers": []},
         "status": {"phase": "Running"}},
        {"metadata": {"name": "aff", "uid": "u2"},
         "spec": {"nodeName": "n1", "containers": [],
                  "affinity": {"nodeAffinity": {
                      "requiredDuringSchedulingIgnoredDuringExecution": {
                          "nodeSelectorTerms": [{"matchExpressions": []}]
                      }}}},
         "status": {"phase": "Running"}},
        {"metadata": {"name": "pvc", "uid": "u3"},
         "spec": {"nodeName": "n1", "containers": [],
                  "volumes": [{"name": "v",
                               "persistentVolumeClaim": {"claimName": "c"}}]},
         "status": {"phase": "Running"}},
        {"metadata": {"name": "prefaff", "uid": "u4"},
         "spec": {"nodeName": "n1", "containers": [],
                  "affinity": {"nodeAffinity": {
                      "preferredDuringSchedulingIgnoredDuringExecution": [
                          {"weight": 1}
                      ]}},
                  "volumes": [{"name": "v", "emptyDir": {}}]},
         "status": {"phase": "Running"}},
    ]
    batch = native_ingest.parse_pod_list(
        json.dumps({"items": objs}).encode()
    )
    for i, obj in enumerate(objs):
        want = decode_pod(obj)
        got = batch.view(i)
        assert got.node_selector == want.node_selector, i
        assert got.unmodeled_constraints == want.unmodeled_constraints, i
