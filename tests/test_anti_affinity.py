"""Selector-based hostname anti-affinity (the k8s spread pattern).

A required podAntiAffinity with topologyKey=hostname and a matchLabels
selector is modeled exactly (predicates/masks.py ``match_affinity_mask``):
the pod refuses nodes hosting matched pods, and — symmetrically, like the
real scheduler's check against existing pods' required anti-affinity —
matched pods refuse nodes hosting it. These tests pin the semantics in
the oracle, the packer parity, the native decoder, and the closed loop.
"""

from __future__ import annotations

import json
import subprocess

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.models.cluster import build_node_map
from k8s_spot_rescheduler_tpu.models.tensors import pack_cluster
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.fixtures import (
    ON_DEMAND_LABEL,
    ON_DEMAND_LABELS,
    SPOT_LABEL,
    SPOT_LABELS,
    make_node,
    make_pod,
    own_terms,
)


def _pack(fc):
    nodes = fc.list_ready_nodes()
    node_map = build_node_map(
        nodes,
        {n.name: fc.list_pods_on_node(n.name) for n in nodes},
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    return pack_cluster(node_map, fc.pdbs, resources=("cpu", "memory"))


def spread_pod(name, cpu, node, app="db"):
    return make_pod(
        name, cpu, node,
        labels={"app": app},
        anti_affinity_match={"app": app},
    )


def test_spread_pair_lands_on_distinct_nodes():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_node(make_node("spot-2", SPOT_LABELS))
    fc.add_pod(spread_pod("db-0", 300, "od-1"))
    fc.add_pod(spread_pod("db-1", 200, "od-1"))
    packed, meta = _pack(fc)
    result = plan_oracle(packed)
    assert bool(result.feasible[0])
    targets = {
        meta.spot[int(result.assignment[0, k])].node.name for k in range(2)
    }
    assert len(targets) == 2  # spread across both spot nodes


def test_spread_infeasible_with_single_spot_node():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS, cpu_millis=8000))
    fc.add_pod(spread_pod("db-0", 100, "od-1"))
    fc.add_pod(spread_pod("db-1", 100, "od-1"))
    packed, _ = _pack(fc)
    assert not plan_oracle(packed).feasible[:1].any()


def test_incoming_spread_pod_repelled_by_plain_resident():
    """Directional: the resident matched pod has NO affinity of its own,
    but the incoming spread pod must still avoid its node."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_node(make_node("spot-2", SPOT_LABELS))
    # plain app=db pod already on spot-1 (most-requested -> probed first)
    fc.add_pod(make_pod("resident", 500, "spot-1", labels={"app": "db"}))
    fc.add_pod(spread_pod("db-new", 300, "od-1"))
    packed, meta = _pack(fc)
    result = plan_oracle(packed)
    assert bool(result.feasible[0])
    target = meta.spot[int(result.assignment[0, 0])].node.name
    assert target == "spot-2"


def test_incoming_matched_pod_repelled_by_resident_spread_pod():
    """Symmetric: a plain pod that MATCHES a resident pod's required
    anti-affinity selector must avoid that node (the real scheduler
    enforces existing pods' required anti-affinity)."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_node(make_node("spot-2", SPOT_LABELS))
    fc.add_pod(spread_pod("guard", 500, "spot-1"))
    fc.add_pod(make_pod("plain-db", 300, "od-1", labels={"app": "db"}))
    packed, meta = _pack(fc)
    result = plan_oracle(packed)
    assert bool(result.feasible[0])
    target = meta.spot[int(result.assignment[0, 0])].node.name
    assert target == "spot-2"


def test_unrelated_pods_unaffected():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_pod(spread_pod("guard", 500, "spot-1"))
    fc.add_pod(make_pod("web", 300, "od-1", labels={"app": "web"}))
    packed, _ = _pack(fc)
    assert bool(plan_oracle(packed).feasible[0])


def test_columnar_parity_with_match_selectors():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("od-2", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_node(make_node("spot-2", SPOT_LABELS))
    fc.add_pod(spread_pod("db-0", 300, "od-1"))
    fc.add_pod(spread_pod("db-1", 250, "od-2"))
    fc.add_pod(make_pod("plain-db", 150, "od-1", labels={"app": "db"}))
    fc.add_pod(spread_pod("cache", 100, "spot-1", app="cache"))
    store = fc.columnar_store(
        ("cpu", "memory"),
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    obj, _ = _pack(fc)
    col, _ = store.pack(fc.pdbs)
    for field in obj._fields:
        np.testing.assert_array_equal(
            getattr(obj, field), getattr(col, field), err_msg=field
        )


def test_loop_spreads_drained_pods():
    clock = FakeClock()
    fc = FakeCluster(clock, reschedule_evicted=True)
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_node(make_node("spot-2", SPOT_LABELS))
    fc.add_pod(spread_pod("db-0", 300, "od-1"))
    fc.add_pod(spread_pod("db-1", 200, "od-1"))
    config = ReschedulerConfig(solver="numpy")
    r = Rescheduler(fc, SolverPlanner(config), config, clock=clock, recorder=fc)
    result = r.tick()
    assert result.drained == ["od-1"]
    placed = {
        n: [p.name for p in fc.list_pods_on_node(n)]
        for n in ("spot-1", "spot-2")
    }
    assert sorted(len(v) for v in placed.values()) == [1, 1]
    assert fc.pending == []


def test_native_decode_of_anti_affinity_shapes():
    ROOT = __file__.rsplit("/tests/", 1)[0]
    proc = subprocess.run(["make", "native"], cwd=ROOT, capture_output=True)
    if proc.returncode != 0:
        pytest.skip("native build unavailable")
    from k8s_spot_rescheduler_tpu.io import native_ingest
    from k8s_spot_rescheduler_tpu.io.kube import decode_pod

    native_ingest._lib.cache_clear()
    if not native_ingest.available():
        pytest.skip("native library failed to load")

    def anti(term):
        return {"podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": term}}

    shapes = [
        # the modeled spread shape
        anti([{"topologyKey": "kubernetes.io/hostname",
               "labelSelector": {"matchLabels": {"app": "db"}}}]),
        # zone topology -> unmodeled
        anti([{"topologyKey": "topology.kubernetes.io/zone",
               "labelSelector": {"matchLabels": {"app": "db"}}}]),
        # matchExpressions -> modeled
        anti([{"topologyKey": "kubernetes.io/hostname",
               "labelSelector": {"matchExpressions": [
                   {"key": "app", "operator": "In", "values": ["db"]}]}}]),
        # two hostname terms -> modeled (round 5: multi-term)
        anti([{"topologyKey": "kubernetes.io/hostname",
               "labelSelector": {"matchLabels": {"app": "a"}}},
              {"topologyKey": "kubernetes.io/hostname",
               "labelSelector": {"matchLabels": {"app": "b"}}}]),
        # empty selector -> unmodeled
        anti([{"topologyKey": "kubernetes.io/hostname",
               "labelSelector": {"matchLabels": {}}}]),
        # cross-namespace -> modeled (round 5: explicit ns lists)
        anti([{"topologyKey": "kubernetes.io/hostname",
               "namespaces": ["other"],
               "labelSelector": {"matchLabels": {"app": "db"}}}]),
        # namespaceSelector {} selects EVERY namespace -> modeled as
        # the "*" wildcard scope (round 5)
        anti([{"topologyKey": "kubernetes.io/hostname",
               "namespaceSelector": {},
               "labelSelector": {"matchLabels": {"app": "db"}}}]),
        anti([{"topologyKey": "kubernetes.io/hostname",
               "namespaceSelector": {"matchLabels": {"team": "x"}},
               "labelSelector": {"matchLabels": {"app": "db"}}}]),
        # required present but not an array (malformed) -> unmodeled
        anti({"topologyKey": "kubernetes.io/hostname"}),
        # required falsy non-array (malformed) -> treated as absent
        anti({}),
        # null / non-object element inside required -> unmodeled
        anti([None]),
        anti(["x"]),
        # truthy non-array namespaces (malformed) -> unmodeled
        anti([{"topologyKey": "kubernetes.io/hostname",
               "namespaces": "other",
               "labelSelector": {"matchLabels": {"app": "db"}}}]),
        # preferred only -> no constraint at all
        {"podAntiAffinity": {
            "preferredDuringSchedulingIgnoredDuringExecution": [
                {"weight": 1}]}},
        # --- round-4 widened shapes ---
        # hostname + zone two-term pair -> BOTH families modeled
        anti([{"topologyKey": "kubernetes.io/hostname",
               "labelSelector": {"matchLabels": {"app": "db"}}},
              {"topologyKey": "topology.kubernetes.io/zone",
               "labelSelector": {"matchLabels": {"app": "db"}}}]),
        # single-value In expressions fold into the selector
        anti([{"topologyKey": "kubernetes.io/hostname",
               "labelSelector": {
                   "matchLabels": {"tier": "be"},
                   "matchExpressions": [
                       {"key": "app", "operator": "In",
                        "values": ["db"]}]}}]),
        # namespaces naming only the pod's own namespace (default here)
        anti([{"topologyKey": "kubernetes.io/hostname",
               "namespaces": ["default"],
               "labelSelector": {"matchLabels": {"app": "db"}}}]),
        # conflicting folded key: term matches nothing -> dropped exactly
        anti([{"topologyKey": "kubernetes.io/hostname",
               "labelSelector": {
                   "matchLabels": {"app": "db"},
                   "matchExpressions": [
                       {"key": "app", "operator": "In",
                        "values": ["web"]}]}}]),
        # two terms of ONE family -> modeled (round 5: multi-term)
        anti([{"topologyKey": "kubernetes.io/hostname",
               "labelSelector": {"matchLabels": {"a": "1"}}},
              {"topologyKey": "kubernetes.io/hostname",
               "labelSelector": {"matchLabels": {"b": "2"}}}]),
        # three terms -> modeled (round 5)
        anti([{"topologyKey": "kubernetes.io/hostname",
               "labelSelector": {"matchLabels": {"a": "1"}}},
              {"topologyKey": "topology.kubernetes.io/zone",
               "labelSelector": {"matchLabels": {"b": "2"}}},
              {"topologyKey": "topology.kubernetes.io/zone",
               "labelSelector": {"matchLabels": {"c": "3"}}}]),
        # multi-value In -> modeled (round 5)
        anti([{"topologyKey": "kubernetes.io/hostname",
               "labelSelector": {"matchExpressions": [
                   {"key": "app", "operator": "In",
                    "values": ["db", "cache"]}]}}]),
        # non-string matchLabels value + key conflict: the TYPE error
        # must win (unmodeled) on both paths — the native engine
        # rejects it at collection time, before the conflict check
        anti([{"topologyKey": "kubernetes.io/hostname",
               "labelSelector": {
                   "matchLabels": {"app": 5},
                   "matchExpressions": [
                       {"key": "app", "operator": "In",
                        "values": ["web"]}]}}]),
        # a VALID first term followed by an unmodeled one: the pod is
        # unmodeled AND the valid term's selector must not leak (its
        # symmetric presence would over-constrain other pods on one
        # ingest path only — round-4 review finding)
        anti([{"topologyKey": "kubernetes.io/hostname",
               "labelSelector": {"matchLabels": {"app": "x"}}},
              {"topologyKey": "topology.kubernetes.io/rack",
               "labelSelector": {"matchLabels": {"app": "x"}}}]),
        anti([{"topologyKey": "kubernetes.io/hostname",
               "labelSelector": {"matchLabels": {"app": "x"}}},
              None]),
    ]
    objs = [
        {"metadata": {"name": f"p{i}", "uid": f"u{i}"},
         "spec": {"nodeName": "n1", "containers": [], "affinity": aff},
         "status": {"phase": "Running"}}
        for i, aff in enumerate(shapes)
    ]
    batch = native_ingest.parse_pod_list(json.dumps({"items": objs}).encode())
    for i, obj in enumerate(objs):
        want = decode_pod(obj)
        got = batch.view(i)
        assert got.anti_affinity_match == want.anti_affinity_match, i
        assert (
            got.anti_affinity_zone_match == want.anti_affinity_zone_match
        ), i
        assert got.unmodeled_constraints == want.unmodeled_constraints, i
    DB = own_terms({"app": "db"})
    assert batch.view(0).anti_affinity_match == DB
    assert not batch.view(0).unmodeled_constraints
    # round-5 widened: single-value In expression ≡ matchLabels
    assert batch.view(2).anti_affinity_match == DB
    # round-5 widened: two hostname terms both modeled
    assert batch.view(3).anti_affinity_match == own_terms(
        {"app": "a"}
    ) + own_terms({"app": "b"})
    # round-5 widened: explicit cross-namespace scope
    assert batch.view(5).anti_affinity_match == (
        (("other",), (("app", "In", ("db",)),)),
    )
    assert not batch.view(5).unmodeled_constraints
    # round 5: {} namespaceSelector = all-namespaces wildcard scope
    assert batch.view(6).anti_affinity_match == (
        (("*",), (("app", "In", ("db",)),)),
    )
    assert not batch.view(6).unmodeled_constraints
    assert batch.view(7).unmodeled_constraints  # namespaceSelector set
    assert batch.view(8).unmodeled_constraints  # non-array required
    assert not batch.view(9).unmodeled_constraints  # falsy required
    assert batch.view(10).unmodeled_constraints  # [null] element
    assert batch.view(11).unmodeled_constraints  # ["x"] element
    assert batch.view(12).unmodeled_constraints  # namespaces: "other" str
    assert not batch.view(13).unmodeled_constraints  # preferred only
    pair = batch.view(14)  # hostname + zone pair: both families
    assert pair.anti_affinity_match == DB
    assert pair.anti_affinity_zone_match == DB
    assert not pair.unmodeled_constraints
    fold = batch.view(15)  # matchLabels + expression in one selector
    assert fold.anti_affinity_match == (
        (("default",), (("app", "In", ("db",)), ("tier", "In", ("be",)))),
    )
    assert not fold.unmodeled_constraints
    ownns = batch.view(16)
    assert ownns.anti_affinity_match == DB
    assert not ownns.unmodeled_constraints
    nothing = batch.view(17)  # conflicting key: dropped, no constraint
    assert nothing.anti_affinity_match == ()
    assert not nothing.unmodeled_constraints
    # round-5 widened: multi-term single family, three terms, multi-In
    assert batch.view(18).anti_affinity_match == own_terms(
        {"a": "1"}
    ) + own_terms({"b": "2"})
    assert not batch.view(18).unmodeled_constraints
    assert batch.view(19).anti_affinity_match == own_terms({"a": "1"})
    assert batch.view(19).anti_affinity_zone_match == own_terms(
        {"b": "2"}
    ) + own_terms({"c": "3"})
    assert not batch.view(19).unmodeled_constraints
    assert batch.view(20).anti_affinity_match == (
        (("default",), (("app", "In", ("cache", "db")),)),
    )
    assert not batch.view(20).unmodeled_constraints
    assert batch.view(21).unmodeled_constraints  # non-str value + conflict
    for i in (22, 23):  # valid term + unmodeled term: nothing leaks
        assert batch.view(i).unmodeled_constraints, i
        assert batch.view(i).anti_affinity_match == (), i


def test_null_namespace_own_ns_list_lockstep():
    """A pod with namespace null/"" normalizes to "default" on BOTH
    decode paths, so an own-namespace list naming "default" stays
    modeled (round-4 review finding)."""
    import json as _json

    from k8s_spot_rescheduler_tpu.io import native_ingest
    from k8s_spot_rescheduler_tpu.io.kube import decode_pod

    if not native_ingest.available():
        pytest.skip("native library unavailable")
    objs = [
        {"metadata": {"name": "p", "namespace": ns_val, "uid": "u"},
         "spec": {"nodeName": "n1", "containers": [], "affinity": {
             "podAntiAffinity": {
                 "requiredDuringSchedulingIgnoredDuringExecution": [
                     {"topologyKey": "kubernetes.io/hostname",
                      "namespaces": ["default"],
                      "labelSelector": {"matchLabels": {"app": "db"}}}]}}},
         "status": {"phase": "Running"}}
        for ns_val in (None, "", "default", "other")
    ]
    batch = native_ingest.parse_pod_list(
        _json.dumps({"items": objs}).encode()
    )
    for i, obj in enumerate(objs):
        want = decode_pod(obj)
        got = batch.view(i)
        assert got.namespace == want.namespace, i
        assert got.anti_affinity_match == want.anti_affinity_match, i
        assert got.unmodeled_constraints == want.unmodeled_constraints, i
    # null/""/default namespaces normalize to the same own-ns scope;
    # a pod in "other" naming ["default"] is a cross-namespace term
    # (round 5: modeled) with the SAME identity as the own-ns form
    for i in (0, 1, 2):
        assert batch.view(i).anti_affinity_match == own_terms(
            {"app": "db"}
        ), i
    assert batch.view(3).anti_affinity_match == own_terms({"app": "db"})
    assert not batch.view(3).unmodeled_constraints
