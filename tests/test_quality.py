"""Quality-oracle and replay-harness tests (BASELINE.md configs 4/5
machinery at test scale)."""


from k8s_spot_rescheduler_tpu.bench.quality import (
    drain_to_exhaustion,
    ilp_max_drains,
)
from k8s_spot_rescheduler_tpu.bench.replay import run_replay
from k8s_spot_rescheduler_tpu.io.synthetic import (
    CONFIGS,
    SyntheticSpec,
    generate_cluster,
    generate_replay,
)
from k8s_spot_rescheduler_tpu.models.cluster import NodeMap, build_node_map
from k8s_spot_rescheduler_tpu.models.tensors import pack_cluster
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig


def _pack(client, cfg):
    nodes = client.list_ready_nodes()
    nm = build_node_map(
        nodes,
        {n.name: client.list_pods_on_node(n.name) for n in nodes},
        on_demand_label=cfg.on_demand_node_label,
        spot_label=cfg.spot_node_label,
        priority_threshold=cfg.priority_threshold,
    )
    return pack_cluster(nm, client.list_pdbs(), resources=cfg.resources)


SMALL = SyntheticSpec("quality-test", 8, 8, 80)


def test_ilp_upper_bounds_greedy():
    cfg = ReschedulerConfig()
    for seed in range(3):
        client = generate_cluster(SMALL, seed)
        packed, _ = _pack(client, cfg)
        ilp = ilp_max_drains(packed)
        assert ilp is not None

        live = generate_cluster(SMALL, seed, reschedule_evicted=True)
        greedy = drain_to_exhaustion(live, cfg)
        # greedy's achieved set is ILP-feasible, so ILP is an upper bound
        assert greedy <= ilp
        # quality target: >= 95% of oracle (BASELINE.md)
        if ilp > 0:
            assert greedy / ilp >= 0.95


def test_ilp_respects_capacity():
    # a candidate whose pods cannot fit must not count
    from tests.fixtures import ON_DEMAND_LABELS, SPOT_LABELS, make_node, make_pod
    from k8s_spot_rescheduler_tpu.models.cluster import NodeInfo

    od = NodeInfo.build(
        make_node("od", ON_DEMAND_LABELS),
        [make_pod("big", 1900, "od")],
    )
    spot = NodeInfo.build(
        make_node("spot", SPOT_LABELS, cpu_millis=1000), []
    )
    packed, _ = pack_cluster(NodeMap(on_demand=[od], spot=[spot]))
    assert ilp_max_drains(packed) == 0


def test_replay_small():
    stats = run_replay(
        ReschedulerConfig(), config_id=5, n_events=20, seed=1
    )
    assert stats["ticks"] > 0
    assert stats["interruptions"] + stats["events"] > 0
    assert stats["replan_ms_p50"] >= 0.0
    assert stats["stranded_by_drain"] == 0


def test_replay_constrained_never_strands():
    """Config-5 churn with the full predicate surface (taints, affinity
    groups, round-5 widened selector terms — operator-based spread
    selectors, NotIn anti-affinity, cross-namespace scopes — PDBs,
    sparse hard spread): every drain the planner approves
    must land its pods — a drain-evicted pod pending at tick end would
    be a stranding, the invariant the whole conservatism design exists
    to uphold. The conservatism gauges ride along in the stats."""
    stats = run_replay(
        ReschedulerConfig(solver="numpy"), n_events=60, seed=0,
        constrained=True,
    )
    assert stats["ticks"] > 0
    assert stats["stranded_by_drain"] == 0
    assert stats["drained_nodes"] > 0, "constrained replay never drained"
    assert "unplaceable_pods_gauge" in stats
    assert "blocked_unmodeled_gauge" in stats


def test_generate_replay_events_ordered():
    _, events = generate_replay(CONFIGS[5], n_events=50, seed=0)
    times = [e.at for e in events]
    assert times == sorted(times)
    kinds = {e.kind for e in events}
    assert kinds <= {"add_spot", "remove_spot"}
