"""End-to-end control-loop tests on the fake cluster: the minimum
observe→plan→actuate slice of SURVEY.md §7 step 4, driven tick by tick on a
virtual clock. Gates, one-drain-per-tick, cooldown, and the closed loop
(evicted pods land on spot nodes) are all exercised."""

import pytest

from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.fixtures import ON_DEMAND_LABELS, SPOT_LABELS, make_node, make_pod


def _setup(solver="jax", reschedule=True, **cfg_overrides):
    clock = FakeClock()
    fc = FakeCluster(clock, reschedule_evicted=reschedule)
    config = ReschedulerConfig(solver=solver, **cfg_overrides)
    planner = SolverPlanner(config)
    r = Rescheduler(fc, planner, config, clock=clock, recorder=fc)
    return fc, clock, r


def _drainable_cluster(fc):
    """One on-demand node whose 3 pods (600m total) fit onto two spot
    nodes; a second on-demand node too big to drain."""
    fc.add_node(make_node("od-small", ON_DEMAND_LABELS))
    fc.add_node(make_node("od-big", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_node(make_node("spot-2", SPOT_LABELS))
    for i, cpu in enumerate([300, 200, 100]):
        fc.add_pod(make_pod(f"small-{i}", cpu, "od-small"))
    for i in range(4):
        fc.add_pod(make_pod(f"big-{i}", 1900, "od-big"))
    fc.add_pod(make_pod("s1", 500, "spot-1"))


@pytest.mark.parametrize("solver", ["numpy", "jax"])
def test_end_to_end_drain(solver):
    fc, clock, r = _setup(solver=solver)
    _drainable_cluster(fc)
    result = r.tick()
    assert result.drained == ["od-small"]
    # evicted pods terminated and were re-placed onto spot capacity
    assert fc.list_pods_on_node("od-small") == []
    moved = {p.uid for n in ("spot-1", "spot-2") for p in fc.list_pods_on_node(n)}
    assert {"default/small-0", "default/small-1", "default/small-2"} <= moved
    assert fc.pending == []
    # the infeasible node was judged but not drained
    assert result.report.n_candidates == 2
    assert result.report.n_feasible == 1


def test_cooldown_gate_after_drain():
    fc, clock, r = _setup()
    _drainable_cluster(fc)
    assert r.tick().drained == ["od-small"]
    # next tick is inside node_drain_delay (10 min default) -> skipped
    clock.advance(10.0)
    assert r.tick().skipped == "cooldown"
    # after the delay, processing resumes
    clock.advance(700.0)
    assert r.tick().skipped == ""


def test_unschedulable_gate():
    fc, clock, r = _setup()
    _drainable_cluster(fc)
    fc.pending.append(make_pod("homeless", 100))
    assert r.tick().skipped == "unschedulable"
    assert fc.evictions == []


def test_one_drain_per_tick():
    fc, clock, r = _setup()
    # two small drainable on-demand nodes, ample spot capacity
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("od-2", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS, cpu_millis=8000))
    fc.add_pod(make_pod("a", 100, "od-1"))
    fc.add_pod(make_pod("b", 100, "od-2"))
    result = r.tick()
    assert len(result.drained) == 1  # rescheduler.go:286 break
    assert result.report.n_feasible == 2


def test_empty_on_demand_node_not_drained():
    # reference rescheduler.go:260-265: no pods -> wait for autoscaler.
    fc, clock, r = _setup()
    fc.add_node(make_node("od-empty", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    result = r.tick()
    assert result.drained == []


def test_infeasible_cluster_drains_nothing():
    fc, clock, r = _setup()
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS, cpu_millis=500))
    fc.add_pod(make_pod("a", 1800, "od-1"))
    result = r.tick()
    assert result.drained == []
    assert result.report.n_feasible == 0


def test_blocked_node_skipped_non_replicated():
    # a bare pod (no controller) blocks its node (rescheduler.go:232-239)
    fc, clock, r = _setup()
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_pod(make_pod("bare", 100, "od-1", replicated=False))
    assert r.tick().drained == []

    # with the flag, it drains (reference --delete-non-replicated-pods)
    fc2, clock2, r2 = _setup(delete_non_replicated_pods=True)
    fc2.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc2.add_node(make_node("spot-1", SPOT_LABELS))
    fc2.add_pod(make_pod("bare", 100, "od-1", replicated=False))
    assert r2.tick().drained == ["od-1"]


def test_drained_order_prefers_emptiest():
    # od nodes judged least-requested-first (nodes/nodes.go:99-101)
    fc, clock, r = _setup()
    fc.add_node(make_node("od-full", ON_DEMAND_LABELS))
    fc.add_node(make_node("od-light", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS, cpu_millis=8000))
    fc.add_pod(make_pod("h1", 900, "od-full"))
    fc.add_pod(make_pod("h2", 900, "od-full"))
    fc.add_pod(make_pod("l1", 100, "od-light"))
    assert r.tick().drained == ["od-light"]


def test_run_forever_cadence():
    fc, clock, r = _setup()
    _drainable_cluster(fc)
    # simulate 3 intervals by hand (run_forever loops sleep+tick)
    for _ in range(3):
        clock.sleep(r.config.housekeeping_interval)
        r.tick()
    assert fc.list_pods_on_node("od-small") == []


def test_tainted_spot_pool_closed_loop():
    """Regression: evicted pods carrying tolerations must land on tainted
    spot nodes in the fake scheduler, not pile up as unschedulable."""
    from k8s_spot_rescheduler_tpu.models.cluster import Taint, Toleration

    taint = Taint("cloud.provider/spot", "true", "NoSchedule")
    tol = Toleration("cloud.provider/spot", "true", "Equal", "NoSchedule")
    fc, clock, r = _setup()
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    spot = make_node("spot-1", SPOT_LABELS)
    spot.taints.append(taint)
    fc.add_node(spot)
    p = make_pod("a", 100, "od-1")
    p.tolerations = [tol]
    fc.add_pod(p)
    assert r.tick().drained == ["od-1"]
    assert fc.pending == []
    assert [q.name for q in fc.list_pods_on_node("spot-1")] == ["a"]


def test_multi_drain_replans_between_drains():
    """max_drains_per_tick > 1 must not overcommit the spot pool: spot-1
    fits either od node's pod but not both."""
    fc, clock, r = _setup(max_drains_per_tick=2, node_drain_delay=0.0)
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("od-2", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS, cpu_millis=2000))
    fc.add_pod(make_pod("a", 1200, "od-1"))
    fc.add_pod(make_pod("b", 1200, "od-2"))
    result = r.tick()
    # first drain moves 1200m onto spot-1; the re-plan sees only 800m
    # left and refuses the second drain
    assert len(result.drained) == 1
    assert fc.pending == []


def test_anti_affinity_respected_end_to_end():
    """A pod whose anti-affinity group already occupies the only roomy
    spot node must not be planned onto it — and the drain is refused when
    no alternative exists."""
    fc, clock, r = _setup()
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    blocker = make_pod("existing", 100, "spot-1")
    blocker.anti_affinity_group = "db"
    fc.add_pod(blocker)
    mover = make_pod("mover", 100, "od-1")
    mover.anti_affinity_group = "db"
    fc.add_pod(mover)
    result = r.tick()
    assert result.drained == []
    assert result.report.n_feasible == 0

    # a second spot node unblocks it
    fc.add_node(make_node("spot-2", SPOT_LABELS))
    clock.advance(700.0)
    result = r.tick()
    assert result.drained == ["od-1"]
    assert [p.name for p in fc.list_pods_on_node("spot-2")] == ["mover"]
