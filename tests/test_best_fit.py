"""Best-fit fallback mode: cross-solver parity and the quality win it
exists for (a drain first-fit cannot prove, best-fit can)."""

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.models.cluster import NodeInfo, NodeMap
from k8s_spot_rescheduler_tpu.models.tensors import pack_cluster
from k8s_spot_rescheduler_tpu.ops.pallas_ffd import plan_ffd_pallas
from k8s_spot_rescheduler_tpu.parallel.mesh import make_mesh
from k8s_spot_rescheduler_tpu.parallel.sharded_ffd import plan_ffd_sharded
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd_jit
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.fixtures import ON_DEMAND_LABELS, SPOT_LABELS, make_node, make_pod
from tests.test_solver import _pack_drain_case, _random_packed, _test_spot_pool


@pytest.mark.parametrize("seed", range(10))
def test_best_fit_parity_all_solvers(seed):
    packed = _random_packed(np.random.default_rng(seed))
    want = plan_oracle(packed, best_fit=True)
    mesh = make_mesh((2, 2))
    for got in (
        plan_ffd_jit(packed, best_fit=True),
        plan_ffd_pallas(packed, best_fit=True),
        plan_ffd_sharded(mesh, packed, best_fit=True),
    ):
        np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
        np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)


def _ff_fails_bf_wins_case():
    """Pods 900, 600, 500 onto spot free capacities [1100, 900].

    First-fit: 900→node_a (free 200), 600→node_b (free 300), 500 strands.
    Best-fit:  900→node_b (exact), 600→node_a (free 500), 500→node_a. ✓
    """
    spot = [
        NodeInfo.build(make_node("node-a", SPOT_LABELS, cpu_millis=1100), []),
        NodeInfo.build(make_node("node-b", SPOT_LABELS, cpu_millis=900), []),
    ]
    od = NodeInfo.build(
        make_node("od-1", ON_DEMAND_LABELS, cpu_millis=4000),
        [make_pod(f"p{i}", c, "od-1") for i, c in enumerate([900, 600, 500])],
    )
    return pack_cluster(NodeMap(on_demand=[od], spot=spot))


def test_best_fit_proves_what_first_fit_cannot():
    packed, _ = _ff_fails_bf_wins_case()
    assert not bool(plan_oracle(packed).feasible[0])
    assert bool(plan_oracle(packed, best_fit=True).feasible[0])


@pytest.mark.parametrize("solver", ["numpy", "jax", "pallas"])
def test_planner_fallback_drains_the_hard_case(solver):
    """With fallback on (default), the planner proves the drain the
    reference's first-fit would have missed; with it off, it must not."""
    spot = [
        NodeInfo.build(make_node("node-a", SPOT_LABELS, cpu_millis=1100), []),
        NodeInfo.build(make_node("node-b", SPOT_LABELS, cpu_millis=900), []),
    ]
    od = NodeInfo.build(
        make_node("od-1", ON_DEMAND_LABELS, cpu_millis=4000),
        [make_pod(f"p{i}", c, "od-1") for i, c in enumerate([900, 600, 500])],
    )
    nm = NodeMap(on_demand=[od], spot=spot)

    planner = SolverPlanner(ReschedulerConfig(solver=solver))
    report = planner.plan(nm, [])
    assert report.plan is not None and report.plan.node.node.name == "od-1"
    # the fallback's placements are the best-fit ones
    assert report.plan.assignments["default/p0"] == "node-b"

    strict = SolverPlanner(
        ReschedulerConfig(solver=solver, fallback_best_fit=False)
    )
    assert strict.plan(nm, []).plan is None


def test_first_fit_assignment_preferred_when_both_feasible():
    """When first-fit already proves the drain, the fallback must not
    change the reference's placements."""
    packed, meta = _pack_drain_case(_test_spot_pool(), [500, 300, 100, 100, 100])
    from k8s_spot_rescheduler_tpu.solver.fallback import with_best_fit_fallback
    from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd

    combined = with_best_fit_fallback(plan_ffd)(packed)
    want = plan_oracle(packed)
    np.testing.assert_array_equal(np.asarray(combined.assignment), want.assignment)
