"""Property-based tests (hypothesis): the safety invariant behind every
solver — an approved drain plan must place every evictable pod within
real remaining capacity, under every predicate. SURVEY.md §7 hard part
(e): conservative over-approximation only in the safe direction."""

import numpy as np
import pytest

# collection must stay clean on images without hypothesis (the whole
# module is skipped there; it runs wherever hypothesis exists)
pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd_jit
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from tests.test_solver import _random_packed


@st.composite
def packed_clusters(draw):
    seed = draw(st.integers(0, 2**31 - 1))
    return _random_packed(np.random.default_rng(seed))


def _check_plan_is_executable(packed, result):
    """Replay the assignments against the initial pool: no capacity,
    count, taint or affinity violation; every valid slot of a feasible
    lane placed; infeasible lanes fully reverted."""
    C, K, R = packed.slot_req.shape
    for c in range(C):
        if not result.feasible[c]:
            assert (result.assignment[c] == -1).all()
            continue
        free = packed.spot_free.copy()
        count = packed.spot_count.copy()
        aff = packed.spot_aff.copy()
        for k in range(K):
            s = result.assignment[c, k]
            if not packed.slot_valid[c, k]:
                assert s == -1
                continue
            assert s >= 0, "feasible lane left a valid slot unplaced"
            assert packed.spot_ok[s]
            free[s] -= packed.slot_req[c, k]
            assert (free[s] >= 0).all(), "capacity oversubscribed"
            count[s] += 1
            assert count[s] <= packed.spot_max_pods[s]
            assert (packed.spot_taints[s] & ~packed.slot_tol[c, k]).sum() == 0
            assert (aff[s] & packed.slot_aff[c, k]).sum() == 0
            aff[s] |= packed.slot_aff[c, k]


@given(packed_clusters())
@settings(max_examples=40, deadline=None)
def test_plans_are_always_executable(packed):
    for best_fit in (False, True):
        result = plan_oracle(packed, best_fit=best_fit)
        _check_plan_is_executable(packed, result)


@given(packed_clusters())
@settings(max_examples=25, deadline=None)
def test_jax_oracle_parity_property(packed):
    want = plan_oracle(packed)
    got = plan_ffd_jit(packed)
    np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
    np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)
