"""Test configuration.

Force JAX onto a virtual 8-device CPU platform — multi-chip sharding tests
run on this mesh, per the build environment (no multi-chip TPU hardware).
The env vars alone are not enough here: the machine's site customization
registers the TPU plugin and snapshots JAX_PLATFORMS at interpreter start,
so the config override after import is what actually takes effect.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
