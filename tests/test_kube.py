"""Kube client shim tests against a stateful stub apiserver (stdlib
http.server) — decode paths, eviction subresource, taint patches, and a
full control-loop tick over real HTTP."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from k8s_spot_rescheduler_tpu.io.kube import (
    KubeClusterClient,
    decode_node,
    decode_pod,
)
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig


def _node(name, role, cpu="2", ready=True, taints=None):
    return {
        "metadata": {"name": name, "labels": {"kubernetes.io/role": role}},
        "spec": {"taints": taints or []},
        "status": {
            "allocatable": {"cpu": cpu, "memory": "4Gi", "pods": "110"},
            "conditions": [{"type": "Ready", "status": "True" if ready else "False"}],
        },
    }


def _pod(name, node, cpu="100m", ns="default"):
    return {
        "metadata": {
            "name": name,
            "namespace": ns,
            "labels": {"app": name},
            "ownerReferences": [
                {"kind": "ReplicaSet", "name": f"{name}-rs", "controller": True}
            ],
        },
        "spec": {
            "nodeName": node,
            "priority": 0,
            "containers": [
                {"resources": {"requests": {"cpu": cpu, "memory": "64Mi"}}}
            ],
        },
        "status": {"phase": "Running"},
    }


class StubApiserver:
    """Just enough apiserver: lists, pod get/evict, node taint patch."""

    def __init__(self):
        self.nodes = {}
        self.pods = {}
        self.pdbs = {}
        self.pvcs = {}
        self.pvs = {}
        self.patches = []
        self.evictions = []
        self.events = []
        self.auths = []
        self.gets = []  # GET paths, for request-count regressions

        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, obj, code=200):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                stub.auths.append(self.headers.get("Authorization", ""))
                path = self.path.split("?")[0]
                stub.gets.append(path)
                if path == "/api/v1/nodes":
                    return self._send({"items": list(stub.nodes.values())})
                if path == "/api/v1/pods":
                    return self._send({"items": list(stub.pods.values())})
                if path == "/apis/policy/v1/poddisruptionbudgets":
                    return self._send({"items": list(stub.pdbs.values())})
                if path == "/api/v1/persistentvolumeclaims":
                    return self._send({"items": list(stub.pvcs.values())})
                if path == "/api/v1/persistentvolumes":
                    return self._send({"items": list(stub.pvs.values())})
                if path.startswith("/api/v1/namespaces/") and "/pods/" in path:
                    name = path.rsplit("/", 1)[1]
                    for key, pod in stub.pods.items():
                        if pod["metadata"]["name"] == name:
                            return self._send(pod)
                    return self._send({"kind": "Status"}, 404)
                if path.startswith("/api/v1/nodes/"):
                    name = path.rsplit("/", 1)[1]
                    if name in stub.nodes:
                        return self._send(stub.nodes[name])
                    return self._send({}, 404)
                return self._send({}, 404)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                if self.path.endswith("/eviction"):
                    name = self.path.split("/pods/")[1].split("/")[0]
                    stub.evictions.append(name)
                    stub.pods = {
                        k: v
                        for k, v in stub.pods.items()
                        if v["metadata"]["name"] != name
                    }
                    return self._send({"kind": "Status", "status": "Success"})
                if "/events" in self.path:
                    stub.events.append(body)
                    return self._send(body, 201)
                return self._send({}, 404)

            def do_PATCH(self):
                # a real apiserver applies strategic-merge semantics (keyed
                # list entries survive omission); this stub only honors
                # merge-patch, where the client's taint list replaces
                # wholesale — reject anything else.
                if self.headers.get("Content-Type") != "application/merge-patch+json":
                    return self._send({"kind": "Status"}, 415)
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                name = self.path.rsplit("/", 1)[1]
                stub.patches.append((name, body))
                if name in stub.nodes:
                    stub.nodes[name]["spec"]["taints"] = body["spec"]["taints"]
                return self._send(stub.nodes.get(name, {}))

        self.server = HTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self.thread.start()

    @property
    def url(self):
        host, port = self.server.server_address
        return f"http://{host}:{port}"

    def close(self):
        self.server.shutdown()


@pytest.fixture()
def stub():
    s = StubApiserver()
    yield s
    s.close()


def test_decode_pod_quantities():
    pod = decode_pod(_pod("web", "n1", cpu="1500m"))
    assert pod.requests["cpu"] == 1500
    assert pod.requests["memory"] == 64 * 1024**2
    assert pod.controller_ref().kind == "ReplicaSet"


def test_decode_node():
    node = decode_node(_node("n1", "worker", cpu="2"))
    assert node.allocatable["cpu"] == 2000
    assert node.allocatable["pods"] == 110
    assert node.ready


def test_list_and_partition(stub):
    stub.nodes["od-1"] = _node("od-1", "worker")
    stub.nodes["spot-1"] = _node("spot-1", "spot-worker")
    stub.nodes["dead"] = _node("dead", "worker", ready=False)
    stub.pods["a"] = _pod("a", "od-1")
    stub.pods["b"] = _pod("b", "spot-1")
    client = KubeClusterClient(stub.url)
    nodes = client.list_ready_nodes()
    assert sorted(n.name for n in nodes) == ["od-1", "spot-1"]  # dead filtered
    assert [p.name for p in client.list_pods_on_node("od-1")] == ["a"]
    assert client.get_pod("default", "a").name == "a"
    assert client.get_pod("default", "zz") is None


def test_full_tick_over_http(stub):
    """observe -> plan (TPU solver) -> drain, every hop over real HTTP."""
    stub.nodes["od-1"] = _node("od-1", "worker")
    stub.nodes["spot-1"] = _node("spot-1", "spot-worker")
    stub.pods["a"] = _pod("a", "od-1", cpu="300m")
    stub.pods["b"] = _pod("b", "od-1", cpu="200m")

    client = KubeClusterClient(stub.url)
    config = ReschedulerConfig(pod_eviction_timeout=5.0, eviction_retry_time=1.0)
    r = Rescheduler(
        client, SolverPlanner(config), config, clock=FakeClock(), recorder=client
    )
    result = r.tick()
    assert result.drained == ["od-1"]
    assert sorted(stub.evictions) == ["a", "b"]
    # taint added then removed (MarkToBeDeleted / CleanToBeDeleted)
    assert len(stub.patches) == 2
    keys_seq = [[t["key"] for t in body["spec"]["taints"]] for _, body in stub.patches]
    assert keys_seq[0] == ["ToBeDeletedByClusterAutoscaler"]
    assert keys_seq[1] == []
    assert any(e["reason"] == "Rescheduler" for e in stub.events)


def test_unschedulable_gate_sees_fresh_state(stub):
    """Regression: the safety gate must not read a stale pod cache — a
    pod that just became pending has to be visible on the next call."""
    stub.nodes["od-1"] = _node("od-1", "worker")
    client = KubeClusterClient(stub.url)
    assert client.list_unschedulable_pods() == []
    pending = _pod("homeless", "", cpu="100m")
    pending["spec"]["nodeName"] = ""
    pending["status"]["phase"] = "Pending"
    stub.pods["homeless"] = pending
    assert [p.name for p in client.list_unschedulable_pods()] == ["homeless"]


def test_token_file_rotation(stub, tmp_path):
    """Regression: projected SA tokens rotate on disk; every request must
    read the current token (client-go behavior)."""
    tok = tmp_path / "token"
    tok.write_text("first")
    client = KubeClusterClient(stub.url, token_file=str(tok))
    client.list_ready_nodes()
    tok.write_text("second")
    client.refresh()  # next tick: the node LIST is cached per tick
    client.list_ready_nodes()
    assert stub.auths[-2:] == ["Bearer first", "Bearer second"]


def test_single_node_list_per_tick(stub):
    """Regression (advisor r4): the ready and unready node views must
    come from ONE GET /api/v1/nodes snapshot per tick — two separate
    LISTs could miss a node flipping unready->ready between them, and
    the heaviest LIST would be paid twice on the 5k-node hot path."""
    stub.nodes["od-1"] = _node("od-1", "worker")
    dead = _node("dead-1", "spot-worker")
    dead["status"]["conditions"] = [{"type": "Ready", "status": "False"}]
    stub.nodes["dead-1"] = dead
    client = KubeClusterClient(stub.url)
    before = len([g for g in stub.gets if g == "/api/v1/nodes"])
    ready = [n.name for n in client.list_ready_nodes()]
    unready = [n.name for n in client.list_unready_nodes()]
    after = len([g for g in stub.gets if g == "/api/v1/nodes"])
    assert ready == ["od-1"] and unready == ["dead-1"]
    assert after - before == 1
    # the next tick re-fetches
    client.refresh()
    client.list_ready_nodes()
    assert len([g for g in stub.gets if g == "/api/v1/nodes"]) == after + 1


def test_taint_patch_uses_merge_patch(stub):
    """Regression: taint removal must use merge-patch semantics (lists
    replace wholesale) — strategic merge cannot delete keyed entries."""
    stub.nodes["od-1"] = _node("od-1", "worker")
    client = KubeClusterClient(stub.url)
    from k8s_spot_rescheduler_tpu.models.cluster import Taint

    client.add_taint("od-1", Taint("ToBeDeletedByClusterAutoscaler", "", "NoSchedule"))
    client.remove_taint("od-1", "ToBeDeletedByClusterAutoscaler")
    assert stub.nodes["od-1"]["spec"]["taints"] == []


def test_volume_affinity_resolved_over_http(stub):
    """A PVC pod bound to a zonal PV resolves through the polling
    client's same-tick PVC/PV LISTs and drains into the volume's zone
    (models/volumes.py); an unresolvable claim stays unplaceable."""
    stub.nodes["od-1"] = _node("od-1", "worker")
    spot_a = _node("spot-a", "spot-worker")
    spot_a["metadata"]["labels"]["zone"] = "a"
    spot_b = _node("spot-b", "spot-worker")
    spot_b["metadata"]["labels"]["zone"] = "b"
    stub.nodes["spot-a"] = spot_a
    stub.nodes["spot-b"] = spot_b
    pod = _pod("web", "od-1", cpu="300m")
    pod["spec"]["volumes"] = [
        {"persistentVolumeClaim": {"claimName": "data"}}
    ]
    stub.pods["web"] = pod
    stub.pvcs["data"] = {
        "metadata": {"name": "data", "namespace": "default"},
        "spec": {"volumeName": "pv-1"},
        "status": {"phase": "Bound"},
    }
    stub.pvs["pv-1"] = {
        "metadata": {"name": "pv-1"},
        "spec": {"nodeAffinity": {"required": {"nodeSelectorTerms": [
            {"matchExpressions": [
                {"key": "zone", "operator": "In", "values": ["a"]}]}]}}},
    }

    client = KubeClusterClient(stub.url)
    config = ReschedulerConfig(pod_eviction_timeout=5.0, eviction_retry_time=1.0)
    r = Rescheduler(
        client, SolverPlanner(config), config, clock=FakeClock(), recorder=client
    )
    result = r.tick()
    assert result.drained == ["od-1"]
    assert stub.evictions == ["web"]

    # now break the binding: the pod must become unplaceable again
    stub.evictions.clear()
    stub.pvcs["data"]["spec"]["volumeName"] = ""
    client.refresh()
    client._pods_cache = None
    r2 = Rescheduler(
        client, SolverPlanner(config), config, clock=FakeClock(), recorder=client
    )
    result = r2.tick()
    assert result.drained == []
    assert stub.evictions == []
