"""Hard topologySpreadConstraints, modeled (round 4).

The k8s-default DoNotSchedule spread constraint previously collapsed to
the unplaceable bit — a spread-constrained pod pinned its node
undrainable forever. It is now modeled in the canonical shape
(topologyKey hostname/zone, matchLabels selector, integer maxSkew,
no counting modifiers): per carrier, a static refused-domain verdict
computed from this tick's per-domain match counts
(predicates/masks.compute_spread_bit), interned as a SpreadBit
pseudo-taint. The reference gets this via the PodTopologySpread plugin
inside CheckPredicates (reference rescheduler.go:344; README.md:103-114).
"""

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
from k8s_spot_rescheduler_tpu.io.kube import decode_pod, decode_topology_spread
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.predicates.masks import (
    ZONE_LABEL,
    SpreadBit,
    compute_spread_bit,
    spread_lane_guard,
)
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.fixtures import (
    pack_fake,
    ON_DEMAND_LABEL,
    ON_DEMAND_LABELS,
    SPOT_LABEL,
    SPOT_LABELS,
    make_node,
    make_pod,
)

HOSTNAME = "kubernetes.io/hostname"


def _host_labels(base, name):
    return dict(base, **{HOSTNAME: name})


def _zone_labels(base, zone):
    return dict(base, **{ZONE_LABEL: zone})


# --- decode ----------------------------------------------------------------

def _spread_pod(spread):
    return {
        "metadata": {"name": "p", "namespace": "ns1",
                     "labels": {"app": "web"}},
        "spec": {"nodeName": "n1", "containers": [],
                 "topologySpreadConstraints": spread},
        "status": {"phase": "Running"},
    }


_CANON = {
    "maxSkew": 1,
    "topologyKey": ZONE_LABEL,
    "whenUnsatisfiable": "DoNotSchedule",
    "labelSelector": {"matchLabels": {"app": "web"}},
}


def test_decode_canonical_hard_spread_modeled():
    pod = decode_pod(_spread_pod([_CANON]))
    assert pod.spread_constraints == (
        (ZONE_LABEL, 1, (("app", "In", ("web",)),)),
    )
    assert not pod.unmodeled_constraints


def test_decode_default_when_unsatisfiable_is_hard():
    entry = {k: v for k, v in _CANON.items() if k != "whenUnsatisfiable"}
    pod = decode_pod(_spread_pod([entry]))
    assert pod.spread_constraints and not pod.unmodeled_constraints


def test_decode_hostname_and_pair():
    host = dict(_CANON, topologyKey=HOSTNAME)
    pod = decode_pod(_spread_pod([host, _CANON]))
    assert pod.spread_constraints == (
        (HOSTNAME, 1, (("app", "In", ("web",)),)),
        (ZONE_LABEL, 1, (("app", "In", ("web",)),)),
    )
    assert not pod.unmodeled_constraints


def test_decode_soft_entries_ignored():
    soft = dict(_CANON, whenUnsatisfiable="ScheduleAnyway")
    pod = decode_pod(_spread_pod([soft]))
    assert pod.spread_constraints == ()
    assert not pod.unmodeled_constraints


@pytest.mark.parametrize("mutate", [
    {"topologyKey": ""},                          # empty topology key
    {"maxSkew": 0},                               # k8s-invalid skew
    {"maxSkew": "1"},                             # non-int skew
    {"maxSkew": True},                            # bool is not an int here
    {"labelSelector": {}},                        # no matchLabels
    {"labelSelector": {"matchLabels": {}}},       # empty selector
    {"labelSelector": {"matchLabels": {"a": "b"},
                       "matchExpressions": [{}]}},  # malformed expression
    {"minDomains": 2},                            # counting modifier
    {"matchLabelKeys": ["rev"]},
    # round 5: explicit DEFAULT modifier values are modeled; only
    # non-default values stay conservative
    {"nodeAffinityPolicy": "Ignore"},
    {"nodeTaintsPolicy": "Honor"},
])
def test_decode_beyond_canonical_unmodeled(mutate):
    entry = dict(_CANON)
    entry.update(mutate)
    pod = decode_pod(_spread_pod([entry]))
    assert pod.spread_constraints == ()
    assert pod.unmodeled_constraints


def test_decode_malformed_list_unmodeled():
    for spread in ("garbage", [None], [[]]):
        constraints, unmodeled = decode_topology_spread(spread)
        assert constraints == () and unmodeled


# --- the verdict math (compute_spread_bit) --------------------------------

def test_verdict_basic_skew():
    # domains a:2 b:0 c:1, maxSkew 1, self-matching carrier from a
    # keyless node: placing adds 1; min=0 -> allowed count <= 0
    bit = compute_spread_bit(
        ZONE_LABEL, 1, None, {"a": 2, "c": 1}, ["a", "b", "c"], True
    )
    assert bit.refused == ("a", "c")


def test_verdict_own_domain_offset():
    # carrier currently in a (count includes it): after departure a:1.
    # min over (a:1, b:0) = 0 -> allowed <= 0 -> a (1) refused, b ok
    bit = compute_spread_bit(
        ZONE_LABEL, 1, "a", {"a": 2}, ["a", "b"], True
    )
    assert bit.refused == ("a",)


def test_verdict_departure_lowers_min():
    # all domains hold exactly 1 and the carrier is one of them: after
    # departure its domain has 0, so min drops to 0 — placements into
    # the OTHER domains (still 1) must now be refused at maxSkew 1
    bit = compute_spread_bit(
        ZONE_LABEL, 1, "a", {"a": 1, "b": 1, "c": 1}, ["a", "b", "c"], True
    )
    assert bit.refused == ("b", "c")


def test_verdict_non_self_match_carrier():
    # carrier doesn't match its own selector: arrival adds nothing,
    # departure shifts nothing — counts a:1 b:0, maxSkew 1: a allowed
    # (1 - 0 <= 1), b allowed
    bit = compute_spread_bit(
        ZONE_LABEL, 1, "a", {"a": 1}, ["a", "b"], False
    )
    assert bit.refused == ()


def test_verdict_max_skew_widens():
    # a:2 b:0, min 0: at maxSkew 2, placing in a gives 2+1-0 = 3 > 2 —
    # still refused; at maxSkew 3 it is allowed
    assert compute_spread_bit(
        ZONE_LABEL, 2, None, {"a": 2}, ["a", "b"], True
    ).refused == ("a",)
    assert compute_spread_bit(
        ZONE_LABEL, 3, None, {"a": 2}, ["a", "b"], True
    ).refused == ()


def test_verdict_no_domains():
    bit = compute_spread_bit(ZONE_LABEL, 1, None, {}, [], True)
    assert bit == SpreadBit(topology_key=ZONE_LABEL, refused=())


def test_lane_guard_two_carriers():
    a = make_pod("a", 100, labels={"app": "web"},
                 spread_constraints=((ZONE_LABEL, 1, (("app", "web"),)),))
    b = make_pod("b", 100, labels={"app": "web"},
                 spread_constraints=((ZONE_LABEL, 1, (("app", "web"),)),))
    plain = make_pod("c", 100)
    assert spread_lane_guard([a, b, plain]) == {0, 1}


def test_lane_guard_carrier_plus_matched_mover():
    a = make_pod("a", 100, labels={"tier": "x"},
                 spread_constraints=((HOSTNAME, 1, (("app", "web"),)),))
    b = make_pod("b", 100, labels={"app": "web"})
    assert spread_lane_guard([a, b]) == {0, 1}


def test_lane_guard_single_carrier_ok():
    a = make_pod("a", 100, labels={"app": "web"},
                 spread_constraints=((HOSTNAME, 1, (("app", "web"),)),))
    plain = make_pod("b", 100)
    assert spread_lane_guard([a, plain]) == set()


# --- oracle / packer (object path) ----------------------------------------

def _placement(fc, pod_name):
    packed, meta = pack_fake(fc)
    result = plan_oracle(packed)
    for c, pods in enumerate(meta.cand_pods):
        for k, p in enumerate(pods):
            if p.name == pod_name:
                if not result.feasible[c]:
                    return None
                return meta.spot[int(result.assignment[c, k])].node.name
    raise AssertionError(f"{pod_name} not in any lane")


def _zone_cluster():
    """Zone a: spot-a1 holds one app=web match. Zone b: spot-b1 empty.
    Candidate od-1 (zone a) holds the mover."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", _zone_labels(ON_DEMAND_LABELS, "a")))
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("web-0", 100, "spot-a1", labels={"app": "web"}))
    return fc


def test_zone_spread_prefers_empty_zone():
    """Mover web-1 (app=web, zone spread maxSkew 1) currently in zone a;
    after departure zone counts are a:1 b:0 — zone a (1+1-0=2>1)
    refused, zone b allowed."""
    fc = _zone_cluster()
    fc.add_pod(make_pod(
        "web-1", 300, "od-1", labels={"app": "web"},
        spread_constraints=((ZONE_LABEL, 1, (("app", "web"),)),),
    ))
    assert _placement(fc, "web-1") == "spot-b1"


def test_zone_spread_blocked_when_all_zones_full():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", _zone_labels(ON_DEMAND_LABELS, "c")))
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("web-a", 100, "spot-a1", labels={"app": "web"}))
    fc.add_pod(make_pod("web-b", 100, "spot-b1", labels={"app": "web"}))
    # mover from zone c (its departure empties c -> min 0): both spot
    # zones at 1, 1+1-0 = 2 > 1 -> nothing admits it
    fc.add_pod(make_pod(
        "web-1", 300, "od-1", labels={"app": "web"},
        spread_constraints=((ZONE_LABEL, 1, (("app", "web"),)),),
    ))
    packed, _ = pack_fake(fc)
    assert not plan_oracle(packed).feasible[:1].any()


def test_zone_spread_max_skew_2_allows():
    fc = _zone_cluster()
    fc.add_pod(make_pod(
        "web-1", 300, "od-1", labels={"app": "web"},
        spread_constraints=((ZONE_LABEL, 2, (("app", "web"),)),),
    ))
    # a:1 b:0 after departure; placing in a: 1+1-0 = 2 <= 2 — first-fit
    # takes the first admitting spot in probe order
    assert _placement(fc, "web-1") in ("spot-a1", "spot-b1")


def test_hostname_spread_one_per_node():
    """The classic one-replica-per-node pattern: maxSkew 1 hostname
    spread with an empty node available — must pick the empty one."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", _host_labels(ON_DEMAND_LABELS, "od-1")))
    fc.add_node(make_node("spot-1", _host_labels(SPOT_LABELS, "spot-1")))
    fc.add_node(make_node("spot-2", _host_labels(SPOT_LABELS, "spot-2")))
    fc.add_pod(make_pod("web-0", 500, "spot-1", labels={"app": "web"}))
    fc.add_pod(make_pod(
        "web-1", 300, "od-1", labels={"app": "web"},
        spread_constraints=((HOSTNAME, 1, (("app", "web"),)),),
    ))
    assert _placement(fc, "web-1") == "spot-2"


def test_keyless_nodes_refuse_spread_carrier():
    """PodTopologySpread filters nodes lacking the topology key: a spot
    node without the zone label cannot take the carrier even though it
    is otherwise empty."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", _zone_labels(ON_DEMAND_LABELS, "a")))
    fc.add_node(make_node("spot-nz", SPOT_LABELS))  # keyless
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod(
        "web-1", 300, "od-1", labels={"app": "web"},
        spread_constraints=((ZONE_LABEL, 1, (("app", "web"),)),),
    ))
    assert _placement(fc, "web-1") == "spot-b1"


def test_spread_counts_span_unclassified_nodes():
    """A match resident on an unclassified (e.g. control-plane) node in
    zone b raises zone b's count — with a:0 b:1 and maxSkew 1 the
    carrier must go to zone a."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", _zone_labels(ON_DEMAND_LABELS, "c")))
    fc.add_node(make_node("cp-1", _zone_labels({}, "b")))
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("web-0", 100, "cp-1", labels={"app": "web"}))
    fc.add_pod(make_pod(
        "web-1", 300, "od-1", labels={"app": "web"},
        spread_constraints=((ZONE_LABEL, 1, (("app", "web"),)),),
    ))
    # a:0, b:1 (cp-1 resident), c:0 after departure; placing in b:
    # 1+1-0 = 2 > 1 refused; a allowed
    assert _placement(fc, "web-1") == "spot-a1"


def test_unready_node_lowers_the_domain_min():
    """Regression (round-4 review): kube-scheduler counts domains on
    NotReady nodes (default nodeTaintsPolicy=Ignore ignores the
    not-ready taints). A dead empty node in zone c drops the true min
    to 0, so with every ready zone at count 1 the carrier must be
    refused EVERYWHERE — missing the unready domain would overstate the
    min and approve a stranding drain."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", _zone_labels(ON_DEMAND_LABELS, "d")))
    dead = make_node("cp-1", _zone_labels({}, "c"))
    dead.ready = False
    fc.add_node(dead)
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("web-a", 100, "spot-a1", labels={"app": "web"}))
    fc.add_pod(make_pod("web-b", 100, "spot-b1", labels={"app": "web"}))
    fc.add_pod(make_pod(
        "web-1", 300, "od-1", labels={"app": "web"},
        spread_constraints=((ZONE_LABEL, 1, (("app", "web"),)),),
    ))
    # ready-only view: min over {a:1, b:1, d:0}... d is od-1's own zone
    # (count 0 after departure) — add a match there so the unready
    # domain is the ONLY zero: without cp-1's zone the model would
    # approve zone a or b
    fc.add_pod(make_pod("web-d", 100, "od-1", labels={"app": "web"}))
    packed, _ = pack_fake(fc)
    assert not plan_oracle(packed).feasible[:1].any()
    _parity(fc)


def test_unready_node_pods_count_in_target_domain():
    """Matched pods on a not-ready node in the TARGET zone raise its
    count — missing them would understate the target and approve what
    the scheduler refuses."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", _zone_labels(ON_DEMAND_LABELS, "c")))
    dead = make_node("cp-1", _zone_labels({}, "a"))
    dead.ready = False
    fc.add_node(dead)
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("web-0", 100, "cp-1", labels={"app": "web"}))
    fc.add_pod(make_pod(
        "web-1", 300, "od-1", labels={"app": "web"},
        spread_constraints=((ZONE_LABEL, 1, (("app", "web"),)),),
    ))
    # a:1 (on the dead node!), b:0, c:0 -> zone a refused (1+1-0 > 1)
    assert _placement(fc, "web-1") == "spot-b1"
    _parity(fc)


def test_two_involved_movers_fail_lane():
    fc = _zone_cluster()
    for i in (1, 2):
        fc.add_pod(make_pod(
            f"web-{i}", 200, "od-1", labels={"app": "web"},
            spread_constraints=((ZONE_LABEL, 1, (("app", "web"),)),),
        ))
    packed, _ = pack_fake(fc)
    assert not plan_oracle(packed).feasible[:1].any()


def test_hostname_and_zone_pair_constraint():
    """The common Deployment shape: hostname + zone spread together.
    spot-a2 is in the already-loaded zone a -> zone constraint refuses
    it; spot-b1 hosts a match -> hostname refuses it; spot-b2 clean."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node(
        "od-1", _host_labels(_zone_labels(ON_DEMAND_LABELS, "a"), "od-1")))
    fc.add_node(make_node(
        "spot-a2", _host_labels(_zone_labels(SPOT_LABELS, "a"), "spot-a2")))
    fc.add_node(make_node(
        "spot-b1", _host_labels(_zone_labels(SPOT_LABELS, "b"), "spot-b1")))
    fc.add_node(make_node(
        "spot-b2", _host_labels(_zone_labels(SPOT_LABELS, "b"), "spot-b2")))
    fc.add_pod(make_pod("web-a", 100, "spot-a2", labels={"app": "web"}))
    fc.add_pod(make_pod("web-b", 100, "spot-b1", labels={"app": "web"}))
    fc.add_pod(make_pod(
        "web-1", 300, "od-1", labels={"app": "web"},
        spread_constraints=(
            (ZONE_LABEL, 2, (("app", "web"),)),
            (HOSTNAME, 1, (("app", "web"),)),
        ),
    ))
    assert _placement(fc, "web-1") == "spot-b2"


def test_plain_peers_unaffected_by_carrier():
    fc = _zone_cluster()
    fc.add_pod(make_pod(
        "web-1", 200, "od-1", labels={"app": "web"},
        spread_constraints=((ZONE_LABEL, 1, (("app", "web"),)),),
    ))
    fc.add_pod(make_pod("plain", 200, "od-1"))
    packed, meta = pack_fake(fc)
    result = plan_oracle(packed)
    assert bool(result.feasible[0])
    pods = meta.cand_pods[0]
    k = next(i for i, p in enumerate(pods) if p.name == "web-1")
    assert meta.spot[int(result.assignment[0, k])].node.name == "spot-b1"


# --- columnar parity -------------------------------------------------------

def _parity(fc):
    store = fc.columnar_store(
        ("cpu", "memory"),
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    obj, _ = pack_fake(fc)
    col, _ = store.pack(fc.pdbs)
    for field in obj._fields:
        np.testing.assert_array_equal(
            getattr(obj, field), getattr(col, field), err_msg=field
        )
    return store


def test_columnar_parity_zone_spread():
    fc = _zone_cluster()
    fc.add_pod(make_pod(
        "web-1", 300, "od-1", labels={"app": "web"},
        spread_constraints=((ZONE_LABEL, 1, (("app", "web"),)),),
    ))
    fc.add_pod(make_pod("plain", 100, "od-1"))
    _parity(fc)


def test_columnar_parity_hostname_zone_pair():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node(
        "od-1", _host_labels(_zone_labels(ON_DEMAND_LABELS, "a"), "od-1")))
    fc.add_node(make_node(
        "spot-a2", _host_labels(_zone_labels(SPOT_LABELS, "a"), "spot-a2")))
    fc.add_node(make_node(
        "spot-b1", _host_labels(_zone_labels(SPOT_LABELS, "b"), "spot-b1")))
    fc.add_pod(make_pod("web-a", 100, "spot-a2", labels={"app": "web"}))
    fc.add_pod(make_pod(
        "web-1", 300, "od-1", labels={"app": "web"},
        spread_constraints=(
            (ZONE_LABEL, 2, (("app", "web"),)),
            (HOSTNAME, 1, (("app", "web"),)),
        ),
    ))
    _parity(fc)


def test_columnar_parity_lane_guard():
    fc = _zone_cluster()
    for i in (1, 2):
        fc.add_pod(make_pod(
            f"web-{i}", 200, "od-1", labels={"app": "web"},
            spread_constraints=((ZONE_LABEL, 1, (("app", "web"),)),),
        ))
    fc.add_pod(make_pod("plain", 100, "od-1"))
    _parity(fc)


def test_columnar_parity_counts_span_unclassified():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", _zone_labels(ON_DEMAND_LABELS, "c")))
    fc.add_node(make_node("cp-1", _zone_labels({}, "b")))
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("web-0", 100, "cp-1", labels={"app": "web"}))
    fc.add_pod(make_pod(
        "web-1", 300, "od-1", labels={"app": "web"},
        spread_constraints=((ZONE_LABEL, 1, (("app", "web"),)),),
    ))
    _parity(fc)


def test_columnar_parity_tracks_match_departure():
    """Churn: the zone-a match leaves; next tick's verdicts must open
    zone a on both paths identically (counts are per tick)."""
    fc = _zone_cluster()
    fc.add_pod(make_pod(
        "web-1", 300, "od-1", labels={"app": "web"},
        spread_constraints=((ZONE_LABEL, 1, (("app", "web"),)),),
    ))
    store = _parity(fc)
    fc.evict_pod(fc.pods["default/web-0"], 0)
    fc.clock.advance(5.0)
    obj, _ = pack_fake(fc)
    col, _ = store.pack(fc.pdbs)
    for field in obj._fields:
        np.testing.assert_array_equal(
            getattr(obj, field), getattr(col, field), err_msg=field
        )


# --- end to end ------------------------------------------------------------

def test_drain_respects_spread_end_to_end():
    fc = FakeCluster(FakeClock(), reschedule_evicted=True)
    fc.add_node(make_node("od-1", _zone_labels(ON_DEMAND_LABELS, "a")))
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("web-0", 100, "spot-a1", labels={"app": "web"}))
    fc.add_pod(make_pod(
        "web-1", 300, "od-1", labels={"app": "web"},
        spread_constraints=((ZONE_LABEL, 1, (("app", "web"),)),),
    ))
    cfg = ReschedulerConfig(solver="numpy", node_drain_delay=0.0)
    r = Rescheduler(fc, SolverPlanner(cfg), cfg, clock=fc.clock, recorder=fc)
    result = r.tick()
    assert result.drained == ["od-1"]
    fc.clock.advance(10.0)
    assert fc.pods["default/web-1"].node_name == "spot-b1"


def test_decode_explicit_default_modifiers_modeled():
    """Round 5: counting-modifier fields carrying their DEFAULT values
    are semantically identical to absence and stay modeled — manifests
    that spell out defaults must not collapse to unplaceable. Both
    decode paths agree."""
    import json

    from k8s_spot_rescheduler_tpu.io import native_ingest

    entry = dict(
        _CANON,
        minDomains=None,
        matchLabelKeys=[],
        nodeAffinityPolicy="Honor",
        nodeTaintsPolicy="Ignore",
    )
    pod = decode_pod(_spread_pod([entry]))
    assert pod.spread_constraints == (
        (ZONE_LABEL, 1, (("app", "In", ("web",)),)),
    )
    assert not pod.unmodeled_constraints
    one = dict(_CANON, minDomains=1)  # nil behaves as 1 (KEP-3022)
    pod = decode_pod(_spread_pod([one]))
    assert pod.spread_constraints and not pod.unmodeled_constraints

    # NON-default values still conservative
    for mutate in (
        {"minDomains": 2},
        {"minDomains": True},
        {"matchLabelKeys": ["rev"]},
        {"nodeAffinityPolicy": "Ignore"},
        {"nodeTaintsPolicy": "Honor"},
    ):
        bad = dict(_CANON, **mutate)
        pod = decode_pod(_spread_pod([bad]))
        assert pod.unmodeled_constraints, mutate

    if not native_ingest.available():
        pytest.skip("native library unavailable")
    shapes = [entry, one,
              dict(_CANON, minDomains=2),
              dict(_CANON, nodeTaintsPolicy="Honor")]
    objs = [_spread_pod([c]) for c in shapes]
    for i, o in enumerate(objs):
        o["metadata"] = dict(o["metadata"], name=f"p{i}", uid=f"u{i}")
    batch = native_ingest.parse_pod_list(
        json.dumps({"items": objs}).encode()
    )
    for i, o in enumerate(objs):
        want = decode_pod(o)
        got = batch.view(i)
        assert got.spread_constraints == want.spread_constraints, i
        assert got.unmodeled_constraints == want.unmodeled_constraints, i


def test_arbitrary_topology_key_spread_modeled_end_to_end():
    """Round 5: spread over ANY topology key (region here) — the
    SpreadBit machinery is generic over the constraint's own key; only
    the decoders used to restrict it. Verdict proven in the oracle with
    packer parity and against the independent fake scheduler."""
    import numpy as np

    from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
    from k8s_spot_rescheduler_tpu.models.cluster import build_node_map
    from k8s_spot_rescheduler_tpu.models.tensors import pack_cluster
    from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
    from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
    from tests.fixtures import (
        ON_DEMAND_LABEL,
        ON_DEMAND_LABELS,
        SPOT_LABEL,
        SPOT_LABELS,
        make_node,
        make_pod,
    )

    REGION = "topology.kubernetes.io/region"
    pod = decode_pod(_spread_pod([dict(_CANON, topologyKey=REGION)]))
    assert pod.spread_constraints == (
        (REGION, 1, (("app", "In", ("web",)),)),
    )
    assert not pod.unmodeled_constraints

    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-east", dict(SPOT_LABELS, **{REGION: "east"})))
    fc.add_node(make_node("spot-west", dict(SPOT_LABELS, **{REGION: "west"})))
    # two matches already in east; none in west -> maxSkew 1 refuses east
    fc.add_pod(make_pod("m1", 400, "spot-east", labels={"app": "web"}))
    fc.add_pod(make_pod("m2", 300, "spot-east", labels={"app": "web"}))
    fc.add_pod(make_pod(
        "mover", 200, "od-1", labels={"app": "web"},
        spread_constraints=((REGION, 1, (("app", "web"),)),),
    ))
    nodes = fc.list_ready_nodes()
    node_map = build_node_map(
        nodes,
        {n.name: fc.list_pods_on_node(n.name) for n in nodes},
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    packed, meta = pack_cluster(node_map, [], resources=("cpu", "memory"))
    result = plan_oracle(packed)
    assert bool(result.feasible[0])
    target = meta.spot[int(result.assignment[0, 0])].node.name
    assert target == "spot-west"
    store = fc.columnar_store(
        ("cpu", "memory"),
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    col, _ = store.pack([])
    for field in packed._fields:
        np.testing.assert_array_equal(
            getattr(packed, field), getattr(col, field), err_msg=field
        )
