"""Actuator state-machine tests (the reference's scaler/ is untested —
SURVEY.md §4 — so these are new coverage, driven on a virtual clock)."""

import pytest

from k8s_spot_rescheduler_tpu.actuator.drain import DrainError, drain_node
from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
from k8s_spot_rescheduler_tpu.models.cluster import TO_BE_DELETED_TAINT
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from tests.fixtures import ON_DEMAND_LABELS, make_node, make_pod


def _cluster_with_node(n_pods=3, **kwargs):
    clock = FakeClock()
    fc = FakeCluster(clock, **kwargs)
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    pods = [make_pod(f"p{i}", 100, "od-1") for i in range(n_pods)]
    for p in pods:
        fc.add_pod(p)
    return fc, clock, pods


def _drain(fc, clock, pods, **overrides):
    kw = dict(
        clock=clock,
        max_graceful_termination=120,
        pod_eviction_timeout=120.0,
        eviction_retry_time=10.0,
    )
    kw.update(overrides)
    drain_node(fc, fc, fc.nodes["od-1"], pods, **kw)


def test_successful_drain_evicts_all_and_untaints():
    fc, clock, pods = _cluster_with_node()
    _drain(fc, clock, pods)
    assert sorted(fc.evictions) == sorted(p.uid for p in pods)
    assert fc.list_pods_on_node("od-1") == []
    # node left schedulable as spare capacity (scaler.go:138-141)
    assert fc.nodes["od-1"].taints == []
    reasons = [e.reason for e in fc.events]
    assert "ReschedulerFailed" not in reasons


def test_taint_present_during_drain():
    fc, clock, pods = _cluster_with_node(n_pods=1)
    seen = []
    original = fc.evict_pod

    def spy(pod, grace):
        seen.append([t.key for t in fc.nodes["od-1"].taints])
        return original(pod, grace)

    fc.evict_pod = spy
    _drain(fc, clock, pods)
    assert seen == [[TO_BE_DELETED_TAINT]]


def test_eviction_retries_until_success():
    fc, clock, pods = _cluster_with_node(n_pods=2)
    fc.eviction_failures[pods[0].uid] = 3  # fails 3 times, then succeeds
    _drain(fc, clock, pods)
    assert pods[0].uid in fc.evictions
    assert fc.list_pods_on_node("od-1") == []


def test_eviction_timeout_fails_drain_and_cleans_taint():
    fc, clock, pods = _cluster_with_node(n_pods=1)
    fc.eviction_failures[pods[0].uid] = 10**6  # never succeeds
    with pytest.raises(DrainError):
        _drain(fc, clock, pods, pod_eviction_timeout=30.0)
    # deferred cleanup ran (scaler.go:83-88)
    assert fc.nodes["od-1"].taints == []
    assert any(e.reason == "ReschedulerFailed" for e in fc.events)


def test_pod_stuck_on_node_fails_verification():
    fc, clock, pods = _cluster_with_node(n_pods=1, termination_latency=10_000.0)
    # eviction succeeds but the pod never actually terminates in time
    with pytest.raises(DrainError, match="pods remaining"):
        _drain(fc, clock, pods, pod_eviction_timeout=30.0)
    assert fc.nodes["od-1"].taints == []


def test_per_pod_normal_event_emitted():
    """Reference scaler.go:44: each pod gets a Normal 'deleting pod from
    on-demand node' event before its eviction is attempted."""
    fc, clock, pods = _cluster_with_node(n_pods=3)
    _drain(fc, clock, pods)
    deleting = [
        e for e in fc.events
        if e.kind == "Pod" and e.event_type == "Normal"
        and "deleting pod from on-demand node" in e.message
    ]
    assert sorted(e.name for e in deleting) == sorted(p.uid for p in pods)
    # announced once per pod per drain, even though retries may loop
    assert len(deleting) == len(pods)


def test_eviction_fanout_parallelizes_slow_evictions():
    """50 slow evictions complete a round in ~a pod-latency, not 50 of
    them (reference fans out one goroutine per pod, scaler.go:93-113)."""
    import time as _time

    fc, clock, pods = _cluster_with_node(n_pods=50)
    original = fc.evict_pod
    PER_POD = 0.05

    def slow(pod, grace):
        _time.sleep(PER_POD)  # wall latency: the apiserver round trip
        return original(pod, grace)

    fc.evict_pod = slow
    t0 = _time.perf_counter()
    _drain(fc, clock, pods)
    wall = _time.perf_counter() - t0
    assert sorted(fc.evictions) == sorted(p.uid for p in pods)
    # serial would be >= 50 * PER_POD = 2.5 s; the bounded pool (32) needs
    # ceil(50/32)=2 waves plus overhead — assert well under serial time
    assert wall < 25 * PER_POD, f"eviction round took {wall:.2f}s (serial?)"


def test_fanout_retry_failures_still_respected():
    """Parallel rounds preserve the retry cadence: pods with injected
    failures get retried next round and eventually succeed."""
    fc, clock, pods = _cluster_with_node(n_pods=8)
    for p in pods[::2]:
        fc.eviction_failures[p.uid] = 2  # fail twice, succeed third round
    _drain(fc, clock, pods)
    assert sorted(set(fc.evictions)) == sorted(p.uid for p in pods)
    assert fc.list_pods_on_node("od-1") == []
