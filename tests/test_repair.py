"""Local-search repair tests (solver/repair.py + solver/validate.py).

The repair phase is the "+ local-search" half of the north-star kernel
(SURVEY.md §7 step 5): when greedy packing (first-fit / best-fit, the
reference's rescheduler.go:334-370 semantics and its strengthening)
cannot prove a candidate drain, a bounded eject-and-reinsert search may.
Safety invariant: repaired placements are re-proven from scratch, so a
feasible verdict is ALWAYS executable — checked here by independent
serial replay, not by the validator that produced it.
"""

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster
from k8s_spot_rescheduler_tpu.solver.fallback import with_repair
from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from k8s_spot_rescheduler_tpu.solver.repair import (
    plan_repair_jit,
    plan_repair_oracle,
)
from k8s_spot_rescheduler_tpu.solver.validate import validate_assignment
from tests.test_properties import _check_plan_is_executable
from tests.test_solver import _random_packed


def _swap_case() -> PackedCluster:
    """Greedy fails, one relocation fixes it.

    Spot pool: n0 free=11, n1 free=5 (n1 carries taint bit0). Candidate
    pods decreasing: a=6 (tolerates), b=5 (tolerates), c=5 (does NOT
    tolerate bit0 — selector-bound to n0). Greedy (first- and best-fit):
    a->n0 (5 left), b->n0 (ties break to probe order; 0 left), c fits
    only n0 -> stuck. Repair: eject b (free 0+5 >= 5), b re-places on
    n1, c takes n0.
    """
    W, A = 1, 2
    return PackedCluster(
        slot_req=np.array([[[6.0], [5.0], [5.0]]], np.float32),
        slot_valid=np.ones((1, 3), bool),
        slot_tol=np.array([[[1], [1], [0]]], np.uint32),
        slot_aff=np.zeros((1, 3, A), np.uint32),
        cand_valid=np.ones((1,), bool),
        spot_free=np.array([[11.0], [5.0]], np.float32),
        spot_count=np.zeros((2,), np.int32),
        spot_max_pods=np.full((2,), 10, np.int32),
        spot_taints=np.array([[0], [1]], np.uint32),
        spot_ok=np.ones((2,), bool),
        spot_aff=np.zeros((2, A), np.uint32),
    )


def test_repair_fixes_greedy_failure():
    packed = _swap_case()
    assert not plan_oracle(packed).feasible[0]
    assert not plan_oracle(packed, best_fit=True).feasible[0]
    res = plan_repair_oracle(packed)
    assert bool(res.feasible[0])
    # c -> n0, b -> n1, a -> n0
    assert list(res.assignment[0]) == [0, 1, 0]
    _check_plan_is_executable(packed, res)


def test_repair_device_matches_fixture():
    packed = _swap_case()
    got = plan_repair_jit(packed)
    assert bool(np.asarray(got.feasible)[0])
    assert list(np.asarray(got.assignment)[0]) == [0, 1, 0]


@pytest.mark.parametrize("seed", range(40))
def test_repair_oracle_jax_parity_randomized(seed):
    """Device repair is bit-identical to the serial mirror: same partial
    pass, rotation, conservative affinity handling, validation."""
    packed = _random_packed(np.random.default_rng(seed))
    want = plan_repair_oracle(packed)
    got = plan_repair_jit(packed)
    np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
    np.testing.assert_array_equal(
        np.asarray(got.assignment), want.assignment
    )


@pytest.mark.parametrize("seed", range(30))
def test_repair_plans_always_executable(seed):
    """Safety: every feasible repair verdict replays cleanly against the
    ORIGINAL spot pool under the serial predicate semantics — the search
    can lose a drain but can never approve an invalid one."""
    packed = _random_packed(np.random.default_rng(1000 + seed))
    res = plan_repair_oracle(packed)
    _check_plan_is_executable(packed, res)


@pytest.mark.parametrize("seed", range(20))
def test_union_never_loses_greedy_feasibility(seed):
    """with_repair >= first-fit and >= best-fit on every lane, and keeps
    the reference's assignment whenever first-fit proves the lane."""
    packed = _random_packed(np.random.default_rng(2000 + seed))
    ff = plan_oracle(packed)
    bf = plan_oracle(packed, best_fit=True)
    union = with_repair(plan_ffd, rounds=8)(packed)
    u_f = np.asarray(union.feasible)
    assert (u_f | ~ff.feasible).all()
    assert (u_f | ~bf.feasible).all()
    np.testing.assert_array_equal(
        np.asarray(union.assignment)[ff.feasible],
        ff.assignment[ff.feasible],
    )
    _check_plan_is_executable(packed, union)


def test_repair_deterministic():
    packed = _random_packed(np.random.default_rng(77))
    a = plan_repair_jit(packed)
    b = plan_repair_jit(packed)
    np.testing.assert_array_equal(np.asarray(a.feasible), np.asarray(b.feasible))
    np.testing.assert_array_equal(
        np.asarray(a.assignment), np.asarray(b.assignment)
    )


@pytest.mark.parametrize("seed", range(15))
def test_validator_agrees_with_serial_replay(seed):
    """validate_assignment(np) must accept exactly the assignments the
    serial replay accepts: run it on greedy plans (known-valid) and on
    deliberately corrupted ones (must reject)."""
    packed = _random_packed(np.random.default_rng(3000 + seed))
    res = plan_oracle(packed)
    ok = np.asarray(validate_assignment(np, packed, res.assignment))
    # greedy-feasible lanes are valid by construction
    np.testing.assert_array_equal(ok[res.feasible], True)
    # corrupt a feasible lane that actually placed something: dropping a
    # placement must invalidate it
    placed_lanes = res.feasible & packed.slot_valid.any(axis=1)
    if placed_lanes.any():
        c = int(np.argmax(placed_lanes))
        bad = res.assignment.copy()
        k = int(np.argmax(packed.slot_valid[c]))
        bad[c, k] = -1  # incomplete placement
        assert not validate_assignment(np, packed, bad)[c]


def test_validator_rejects_oversubscription():
    packed = _swap_case()
    # all three pods on n1 (free 5 < 16, and c doesn't tolerate bit0)
    bad = np.array([[1, 1, 1]], np.int32)
    assert not validate_assignment(np, packed, bad)[0]
    good = np.array([[0, 1, 0]], np.int32)
    assert validate_assignment(np, packed, good)[0]
