"""Local-search repair tests (solver/repair.py + solver/validate.py).

The repair phase is the "+ local-search" half of the north-star kernel
(SURVEY.md §7 step 5): when greedy packing (first-fit / best-fit, the
reference's rescheduler.go:334-370 semantics and its strengthening)
cannot prove a candidate drain, a bounded eject-and-reinsert search may.
Safety invariant: repaired placements are re-proven from scratch, so a
feasible verdict is ALWAYS executable — checked here by independent
serial replay, not by the validator that produced it.
"""

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster
from k8s_spot_rescheduler_tpu.solver.fallback import with_repair
from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from k8s_spot_rescheduler_tpu.solver.repair import (
    plan_repair_jit,
    plan_repair_oracle,
)
from k8s_spot_rescheduler_tpu.solver.validate import validate_assignment

# tests.test_properties needs hypothesis; collection must stay clean on
# images without it (this module skips there, runs wherever it exists)
pytest.importorskip("hypothesis")
from tests.test_properties import _check_plan_is_executable  # noqa: E402
from tests.test_solver import _random_packed  # noqa: E402


def _swap_case() -> PackedCluster:
    """Greedy fails, one relocation fixes it.

    Spot pool: n0 free=11, n1 free=5 (n1 carries taint bit0). Candidate
    pods decreasing: a=6 (tolerates), b=5 (tolerates), c=5 (does NOT
    tolerate bit0 — selector-bound to n0). Greedy (first- and best-fit):
    a->n0 (5 left), b->n0 (ties break to probe order; 0 left), c fits
    only n0 -> stuck. Repair: eject b (free 0+5 >= 5), b re-places on
    n1, c takes n0.
    """
    W, A = 1, 2
    return PackedCluster(
        slot_req=np.array([[[6.0], [5.0], [5.0]]], np.float32),
        slot_valid=np.ones((1, 3), bool),
        slot_tol=np.array([[[1], [1], [0]]], np.uint32),
        slot_aff=np.zeros((1, 3, A), np.uint32),
        cand_valid=np.ones((1,), bool),
        spot_free=np.array([[11.0], [5.0]], np.float32),
        spot_count=np.zeros((2,), np.int32),
        spot_max_pods=np.full((2,), 10, np.int32),
        spot_taints=np.array([[0], [1]], np.uint32),
        spot_ok=np.ones((2,), bool),
        spot_aff=np.zeros((2, A), np.uint32),
    )


def test_repair_fixes_greedy_failure():
    packed = _swap_case()
    assert not plan_oracle(packed).feasible[0]
    assert not plan_oracle(packed, best_fit=True).feasible[0]
    res = plan_repair_oracle(packed)
    assert bool(res.feasible[0])
    # c -> n0, b -> n1, a -> n0
    assert list(res.assignment[0]) == [0, 1, 0]
    _check_plan_is_executable(packed, res)


def test_repair_device_matches_fixture():
    packed = _swap_case()
    got = plan_repair_jit(packed)
    assert bool(np.asarray(got.feasible)[0])
    assert list(np.asarray(got.assignment)[0]) == [0, 1, 0]


def _affinity_swap_case() -> PackedCluster:
    """Greedy fails BECAUSE of anti-affinity; only an affinity-driven
    ejection fixes it (round 4: exact ejection — the old monotone
    accumulation skipped this unlock, leaving the lane infeasible).

    Spot pool: n0 free=9 (clean — the TIGHTER fit for T, so first-fit
    AND best-fit both burn it), n1 free=10 (taint bit0). Pods
    decreasing: T=8 (group bit1, tolerates the taint), I=7 (group bit1,
    intolerant). Greedy: T->n0; I: n1 refused (taint), n0 refused
    (group-mate T) -> stuck. Repair must eject T (clearing its group bit
    from n0 — impossible under monotone accumulation), re-place T on n1
    and land I on n0."""
    W, A = 1, 2
    group = np.array([2, 0], np.uint32)  # bit1 in word 0 of the aff words
    return PackedCluster(
        slot_req=np.array([[[8.0], [7.0]]], np.float32),
        slot_valid=np.ones((1, 2), bool),
        slot_tol=np.array([[[1], [0]]], np.uint32),
        slot_aff=np.array([[group, group]], np.uint32),
        cand_valid=np.ones((1,), bool),
        spot_free=np.array([[9.0], [10.0]], np.float32),
        spot_count=np.zeros((2,), np.int32),
        spot_max_pods=np.full((2,), 10, np.int32),
        spot_taints=np.array([[0], [1]], np.uint32),
        spot_ok=np.ones((2,), bool),
        spot_aff=np.zeros((2, A), np.uint32),
    )


def test_exact_ejection_recovers_affinity_blocked_lane():
    packed = _affinity_swap_case()
    assert not plan_oracle(packed).feasible[0]
    assert not plan_oracle(packed, best_fit=True).feasible[0]
    res = plan_repair_oracle(packed)
    assert bool(res.feasible[0]), "affinity ejection unlock not found"
    assert list(res.assignment[0]) == [1, 0]  # T -> n1, I -> n0
    _check_plan_is_executable(packed, res)
    got = plan_repair_jit(packed)
    np.testing.assert_array_equal(np.asarray(got.feasible), res.feasible)
    np.testing.assert_array_equal(np.asarray(got.assignment), res.assignment)


def test_exact_ejection_respects_remaining_group_mate():
    """Ejecting q clears ONLY q's bits: if another group-mate remains on
    the node (placed there by the partial pass), the recompute keeps its
    bits and the unlock must still be refused."""
    W, A = 1, 2
    group = np.array([2, 0], np.uint32)
    packed = PackedCluster(
        # X=6 (plain, group-bit carrier? no — X carries the group TOO but
        # lands on n0 first; T=5 group; I=4 group). After the partial
        # pass n0 holds X and... two group-mates cannot colocate, so
        # instead: X carries a DIFFERENT overlap — X and I share bit1,
        # X and T do not (T uses bit2). Ejecting T from n0 leaves X's
        # bit1 -> I still refused on n0.
        slot_req=np.array(
            [[[6.0], [5.0], [4.0]]], np.float32
        ),  # X, T, I decreasing
        slot_valid=np.ones((1, 3), bool),
        slot_tol=np.array([[[1], [1], [0]]], np.uint32),
        slot_aff=np.array(
            [[[2, 0], [4, 0], [2, 0]]], np.uint32
        ),  # X:bit1, T:bit2, I:bit1
        cand_valid=np.ones((1,), bool),
        spot_free=np.array([[11.0], [5.0]], np.float32),
        spot_count=np.zeros((2,), np.int32),
        spot_max_pods=np.full((2,), 10, np.int32),
        spot_taints=np.array([[0], [1]], np.uint32),
        spot_ok=np.ones((2,), bool),
        spot_aff=np.zeros((2, A), np.uint32),
    )
    # partial pass: X->n0 (11-6=5), T->n0 (5-5=0), I: n1 taint-refused,
    # n0 has bit1 (X) -> gap. Eject T: n0 free 5 >= 4 but X's bit1
    # remains -> refused. Eject X: (rotation) n0 free 0+6-4 >= 0 ok,
    # X re-places... n1 free 5 < 6: no. Lane must stay infeasible, and
    # CRUCIALLY never place I next to X.
    res = plan_repair_oracle(packed)
    assert not res.feasible[0]
    got = plan_repair_jit(packed)
    np.testing.assert_array_equal(np.asarray(got.feasible), res.feasible)


def _rotation_coverage_case() -> PackedCluster:
    """2 unlockers x 2 chain targets where the ONLY viable chain is the
    off-diagonal pairing (q0, r1) — under the old lockstep rotation
    (both keyed to round_idx) pairings with q ≢ r (mod 2) were
    unreachable at any round count (round-4 review finding).

    Nodes: n0 free=10 (clean, holds q0), n1 free=10 (taint C, holds
    q1), n2/n3 free=10 (taint A, hold r0/r1), n4 free=20 (taint B).
    Tolerations: q0={A}, q1={C}, r0={A}, r1={A,B}, p={C}. Unlockers for
    p: q0, q1 (p tolerates C). q0's chain targets: r0, r1 (A). r0 can
    re-place nowhere; r1 re-places on n4. q1 has no chain targets.
    The solution needs round 2's (q0, r1) pairing: p->n0, q0->n3,
    r1->n4."""
    W, A = 1, 2
    TA, TB, TC = 1, 2, 4
    return PackedCluster(
        slot_req=np.array(
            [[[10.0], [10.0], [10.0], [10.0], [6.0]]], np.float32
        ),  # q0, q1, r0, r1, p
        slot_valid=np.ones((1, 5), bool),
        slot_tol=np.array(
            [[[TA], [TC], [TA], [TA | TB], [TC]]], np.uint32
        ),
        slot_aff=np.zeros((1, 5, A), np.uint32),
        cand_valid=np.ones((1,), bool),
        spot_free=np.array(
            [[10.0], [10.0], [10.0], [10.0], [20.0]], np.float32
        ),
        spot_count=np.zeros((5,), np.int32),
        spot_max_pods=np.full((5,), 10, np.int32),
        spot_taints=np.array([[0], [TC], [TA], [TA], [TB]], np.uint32),
        spot_ok=np.ones((5,), bool),
        spot_aff=np.zeros((5, A), np.uint32),
    )


def test_chain_rotation_reaches_off_diagonal_pairings():
    packed = _rotation_coverage_case()
    assert not plan_oracle(packed).feasible[0]
    assert not plan_oracle(packed, best_fit=True).feasible[0]
    res = plan_repair_oracle(packed)
    assert bool(res.feasible[0]), "off-diagonal (q0, r1) chain not found"
    # p -> n0 (q0's node), q0 -> n3 (r1's node), r1 -> n4
    assert list(res.assignment[0]) == [3, 1, 2, 4, 0]
    _check_plan_is_executable(packed, res)
    got = plan_repair_jit(packed)
    np.testing.assert_array_equal(np.asarray(got.feasible), res.feasible)
    np.testing.assert_array_equal(np.asarray(got.assignment), res.assignment)


def test_repair_parity_at_config2_scale():
    """Config-2-scale repair parity pin (VERDICT r3 weak #6): now that
    repair participates in quality-critical paths, the device/oracle
    lockstep is pinned at real columnar-packed scale (C=256 lanes), not
    just randomized small shapes."""
    from k8s_spot_rescheduler_tpu.bench.quality import pack_quality
    from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS

    packed = pack_quality(CONFIGS[2], 0)
    want = plan_repair_oracle(packed)
    got = plan_repair_jit(packed)
    np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
    np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)


def test_repair_parity_on_affinity_quality_pack():
    """Device/oracle bit parity over the round-4 affinity quality config
    (real packed shapes with group bits, selectors, taints)."""
    from k8s_spot_rescheduler_tpu.bench.quality import pack_quality
    from k8s_spot_rescheduler_tpu.io.synthetic import AffinitySpec

    packed = pack_quality(AffinitySpec("aff-parity", n_groups=4), 0)
    want = plan_repair_oracle(packed)
    got = plan_repair_jit(packed)
    np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
    np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)
    _check_plan_is_executable(packed, want)


@pytest.mark.parametrize("seed", range(40))
def test_repair_oracle_jax_parity_randomized(seed):
    """Device repair is bit-identical to the serial mirror: same partial
    pass, rotation, conservative affinity handling, validation."""
    packed = _random_packed(np.random.default_rng(seed))
    want = plan_repair_oracle(packed)
    got = plan_repair_jit(packed)
    np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
    np.testing.assert_array_equal(
        np.asarray(got.assignment), want.assignment
    )


@pytest.mark.parametrize("seed", range(30))
def test_repair_plans_always_executable(seed):
    """Safety: every feasible repair verdict replays cleanly against the
    ORIGINAL spot pool under the serial predicate semantics — the search
    can lose a drain but can never approve an invalid one."""
    packed = _random_packed(np.random.default_rng(1000 + seed))
    res = plan_repair_oracle(packed)
    _check_plan_is_executable(packed, res)


@pytest.mark.parametrize("seed", range(20))
def test_union_never_loses_greedy_feasibility(seed):
    """with_repair >= first-fit and >= best-fit on every lane, and keeps
    the reference's assignment whenever first-fit proves the lane."""
    packed = _random_packed(np.random.default_rng(2000 + seed))
    ff = plan_oracle(packed)
    bf = plan_oracle(packed, best_fit=True)
    union = with_repair(plan_ffd, rounds=8)(packed)
    u_f = np.asarray(union.feasible)
    assert (u_f | ~ff.feasible).all()
    assert (u_f | ~bf.feasible).all()
    np.testing.assert_array_equal(
        np.asarray(union.assignment)[ff.feasible],
        ff.assignment[ff.feasible],
    )
    _check_plan_is_executable(packed, union)


def test_repair_deterministic():
    packed = _random_packed(np.random.default_rng(77))
    a = plan_repair_jit(packed)
    b = plan_repair_jit(packed)
    np.testing.assert_array_equal(np.asarray(a.feasible), np.asarray(b.feasible))
    np.testing.assert_array_equal(
        np.asarray(a.assignment), np.asarray(b.assignment)
    )


@pytest.mark.parametrize("seed", range(15))
def test_validator_agrees_with_serial_replay(seed):
    """validate_assignment(np) must accept exactly the assignments the
    serial replay accepts: run it on greedy plans (known-valid) and on
    deliberately corrupted ones (must reject)."""
    packed = _random_packed(np.random.default_rng(3000 + seed))
    res = plan_oracle(packed)
    ok = np.asarray(validate_assignment(np, packed, res.assignment))
    # greedy-feasible lanes are valid by construction
    np.testing.assert_array_equal(ok[res.feasible], True)
    # corrupt a feasible lane that actually placed something: dropping a
    # placement must invalidate it
    placed_lanes = res.feasible & packed.slot_valid.any(axis=1)
    if placed_lanes.any():
        c = int(np.argmax(placed_lanes))
        bad = res.assignment.copy()
        k = int(np.argmax(packed.slot_valid[c]))
        bad[c, k] = -1  # incomplete placement
        assert not validate_assignment(np, packed, bad)[c]


def test_validator_rejects_oversubscription():
    packed = _swap_case()
    # all three pods on n1 (free 5 < 16, and c doesn't tolerate bit0)
    bad = np.array([[1, 1, 1]], np.int32)
    assert not validate_assignment(np, packed, bad)[0]
    good = np.array([[0, 1, 0]], np.int32)
    assert validate_assignment(np, packed, good)[0]
