"""The static-analysis suite (tools/analysis) must actually gate.

Mirror of tests/test_lint.py for the vet half of the chain, both tiers:
every AST pass is proven by a seeded violation (a fixture tree the pass
must fail), every jaxpr pass by a seeded manifest (a planted violation
in a traced program), the real tree must be clean on BOTH tiers (`make
analyze` + `make audit-jaxpr` then enforce that forever), the shared
typed-suppression grammar is pinned for both tiers, and the watchdogs
keep each stage inside the `make check` latency budget (10 s ast, 30 s
jaxpr). Fixture machinery lives in tests/analysis_fixtures.py, shared
with the lint gate.
"""

import json

from tests.analysis_fixtures import (
    analyze_tree as _analyze_tree,
    run_analysis as _run,
    seed_jaxpr_manifest,
    seed_tree as _seed,
)

# --- the gate itself ------------------------------------------------------


def test_tree_is_clean():
    """The unified default (--tier all): both tiers, one invocation."""
    r = _run()
    assert r.returncode == 0, f"analysis gate is red:\n{r.stdout}{r.stderr}"


def test_tree_is_clean_within_watchdog():
    """The ast stage (`make analyze`) must stay under 10 s."""
    r = _run("--tier", "ast", "--max-seconds", "10")
    assert r.returncode == 0, f"watchdog tripped:\n{r.stdout}{r.stderr}"


def test_jaxpr_tier_clean_within_watchdog():
    """`make audit-jaxpr` acceptance: the full jaxpr tier — every
    HOT_PROGRAMS entry traced (index-width at the declared 1M-pod /
    100k-node max shapes included) — runs CLEAN on an empty baseline
    and inside the 30 s CPU budget."""
    r = _run("--tier", "jaxpr", "--max-seconds", "30")
    assert r.returncode == 0, f"jaxpr gate is red:\n{r.stdout}{r.stderr}"


def test_noqa_trailing_prose_still_suppresses(tmp_path):
    """Prose after a code must not merge into the code token."""
    _seed(tmp_path, "solver/prose.py", """\
        import jax

        @jax.jit
        def solve(x):
            return x.item()  # noqa: jax-host-sync - fetched once, on purpose
    """)
    r = _analyze_tree(tmp_path)
    assert "jax-host-sync" not in r.stdout
    assert "unknown-suppression" not in r.stdout


def test_donation_unresolvable_spec_skipped(tmp_path):
    """A statically-unresolvable donate_argnums spec must cost recall,
    never produce a false error; tuple(range(N)) IS resolvable."""
    _seed(tmp_path, "planner/spec_donate.py", """\
        import jax

        _SPEC = (0,)

        def f(a, b):
            return a + b

        g = jax.jit(f, donate_argnums=_SPEC)  # unresolvable: skip
        h = jax.jit(f, donate_argnums=tuple(range(1)))  # resolves to {0}

        def use_g(a, b):
            out = g(a, b)
            return b + out  # b not provably donated: no finding

        def use_h(a, b):
            out = h(a, b)
            return a + out  # a donated at position 0: finding
    """)
    r = _analyze_tree(tmp_path)
    hits = [
        l for l in r.stdout.splitlines() if "donation-discipline" in l
    ]
    assert len(hits) == 1, r.stdout
    assert "use_h" in hits[0]


def test_subset_roots_do_not_report_stale_baseline(tmp_path):
    _seed(tmp_path, "solver/bad.py", """\
        import jax

        @jax.jit
        def solve(x):
            return x.item()
    """)
    parity = tmp_path / "PARITY.md"
    parity.write_text("")
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(
        "some/other/file.py::lock-discipline::Foo.bar.attr  # elsewhere\n"
    )
    r = _run(
        tmp_path, "--tier", "ast", "--baseline", baseline,
        "--parity", parity,
    )
    # the seeded host-sync finding fires, but the unrelated entry is NOT
    # called stale — this is a subset-roots run
    assert "jax-host-sync" in r.stdout
    assert "stale-baseline" not in r.stdout


def test_single_tier_does_not_stale_other_tiers_baseline(tmp_path):
    """An ast-only run must not call a jaxpr-tier baseline entry stale
    (and vice versa): the entry's pass never ran."""
    _seed(tmp_path, "solver/bad.py", """\
        import jax

        @jax.jit
        def solve(x):
            return x.item()
    """)
    parity = tmp_path / "PARITY.md"
    parity.write_text("")
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(
        "solver/bad.py::index-width::prog.check  # jaxpr-tier debt\n"
    )
    r = _run(
        tmp_path, "--tier", "ast", "--baseline", baseline,
        "--parity", parity,
    )
    assert "stale-baseline" not in r.stdout


def test_unknown_pass_name_errors():
    """A --pass typo must error, not report a vacuously clean tree."""
    r = _run("--pass", "jax-hostsync-typo")
    assert r.returncode != 0
    assert "invalid choice" in r.stderr


def test_pass_tier_mismatch_errors():
    """Naming a jaxpr pass under --tier ast (or vice versa) must error,
    not silently run nothing."""
    r = _run("--tier", "ast", "--pass", "index-width")
    assert r.returncode != 0
    assert "jaxpr-tier pass" in r.stderr
    r = _run("--tier", "jaxpr", "--pass", "lock-discipline")
    assert r.returncode != 0
    assert "ast-tier pass" in r.stderr


def test_watchdog_fires_on_tiny_budget():
    r = _run("--tier", "ast", "--max-seconds", "0.000001")
    assert r.returncode == 2
    assert "watchdog" in r.stderr


# --- jax-host-sync --------------------------------------------------------


def test_seeded_host_sync_direct(tmp_path):
    _seed(tmp_path, "solver/bad.py", """\
        import jax
        import numpy as np

        @jax.jit
        def solve(x):
            print(x)
            y = np.asarray(x)
            return y.item()
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert r.stdout.count("jax-host-sync") >= 3
    for needle in ("print()", "np.asarray()", ".item()"):
        assert needle in r.stdout


def test_seeded_host_sync_via_call_graph(tmp_path):
    """A sync inside a helper only *reachable* from a jitted root must
    fire — this is what a per-file linter cannot see."""
    _seed(tmp_path, "solver/indirect.py", """\
        import jax

        @jax.jit
        def root(x):
            return _helper(x)

        def _helper(x):
            return x.item()
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "jax-host-sync" in r.stdout and "_helper" in r.stdout


def test_host_sync_not_flagged_outside_jit(tmp_path):
    _seed(tmp_path, "solver/hostside.py", """\
        import numpy as np

        def decode(vec):
            return np.asarray(vec).item()
    """)
    r = _analyze_tree(tmp_path)
    assert "jax-host-sync" not in r.stdout


def test_host_sync_nested_branch_fires(tmp_path):
    """A sync inside a nested def (the lax.cond branch shape) fires
    exactly once — nested bodies are each their own entry, and visiting
    the parent must neither skip nor double-report them."""
    _seed(tmp_path, "solver/nested.py", """\
        import jax

        @jax.jit
        def outer(pred, x):
            def branch(y):
                return y.item()

            def other(y):
                return y

            return jax.lax.cond(pred, branch, other, x)
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1, r.stdout
    hits = [l for l in r.stdout.splitlines() if ".item()" in l]
    assert len(hits) == 1, r.stdout


def test_host_sync_static_argnames_direct_decorator(tmp_path):
    """The @jax.jit(static_argnames=...) decorator form exempts its
    static params just like the jax.jit(f, ...) call form."""
    _seed(tmp_path, "solver/dec_static.py", """\
        import jax

        @jax.jit(static_argnames=("n",))
        def scale(x, n=2):
            return x * int(n)
    """)
    r = _analyze_tree(tmp_path)
    assert "jax-host-sync" not in r.stdout


def test_host_sync_static_argnames_exempt(tmp_path):
    """int() on a static_argnames parameter is trace-time Python, not a
    device sync (solver/repair.py's spot_chunks pattern)."""
    _seed(tmp_path, "solver/static_ok.py", """\
        import jax

        def solve(x, chunks=2):
            n = int(chunks)
            return x * n

        solve_jit = jax.jit(solve, static_argnames=("chunks",))
    """)
    r = _analyze_tree(tmp_path)
    assert "jax-host-sync" not in r.stdout


# --- donation-discipline --------------------------------------------------


def test_seeded_donation_read_after_donate(tmp_path):
    _seed(tmp_path, "planner/bad_donate.py", """\
        import jax

        def f(a, b):
            return a + b

        g = jax.jit(f, donate_argnums=(0,))

        def use(a, b):
            out = g(a, b)
            return a + out
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "donation-discipline" in r.stdout


def test_donation_multiline_call_is_clean(tmp_path):
    """The donated argument's own Load inside a reflowed multi-line call
    must not count as a read-after-donate."""
    _seed(tmp_path, "planner/wrapped_donate.py", """\
        import jax

        def f(a, b):
            return a + b

        g = jax.jit(f, donate_argnums=(0,))

        def use(a, b):
            out = g(
                a,
                b,
            )
            return out
    """)
    r = _analyze_tree(tmp_path)
    assert "donation-discipline" not in r.stdout


def test_donation_rebind_is_clean(tmp_path):
    _seed(tmp_path, "planner/good_donate.py", """\
        import jax

        def f(a, b):
            return a + b

        g = jax.jit(f, donate_argnums=(0,))

        def use(a, b):
            a = g(a, b)
            return a + b
    """)
    r = _analyze_tree(tmp_path)
    assert "donation-discipline" not in r.stdout


def test_donation_shadowed_nested_param_is_clean(tmp_path):
    """A donating call on a nested function's OWN parameter must not be
    attributed to the enclosing function's same-named binding."""
    _seed(tmp_path, "planner/shadow_donate.py", """\
        import jax

        def f(a):
            return a

        step = jax.jit(f, donate_argnums=(0,))

        def outer(a):
            def inner(a):
                return step(a)

            y = inner(a)
            return a + y
    """)
    r = _analyze_tree(tmp_path)
    hits = [
        l for l in r.stdout.splitlines() if "donation-discipline" in l
    ]
    assert not any("'outer'" in h for h in hits), r.stdout


# --- recompile-trigger ----------------------------------------------------


def test_seeded_recompile_triggers(tmp_path):
    _seed(tmp_path, "ops/bad_jit.py", """\
        import jax
        import time

        def tick(x):
            return jax.jit(lambda y: y + 1)(x)

        def build(fns):
            out = []
            for f in fns:
                out.append(jax.jit(f))
            return out

        def g(x):
            return x

        g_jit = jax.jit(g)

        def stamp(x):
            return g_jit(x * time.time())
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "recompiles" in r.stdout  # jit-per-call
    assert "inside a loop" in r.stdout
    assert "per-call-varying" in r.stdout


def test_recompile_no_double_report_in_loop(tmp_path):
    """jax.jit(f)(x) inside a loop is ONE finding (per-call), not also
    an in-loop construction finding for the same call."""
    _seed(tmp_path, "ops/loop_jit.py", """\
        import jax

        def drain(xs):
            out = []
            for x in xs:
                out.append(jax.jit(lambda a: a + 1)(x))
            return out
    """)
    r = _analyze_tree(tmp_path)
    hits = [l for l in r.stdout.splitlines() if "recompile-trigger" in l]
    assert len(hits) == 1, r.stdout
    assert "recompiles" in hits[0]


# --- metrics-contract -----------------------------------------------------


def test_seeded_metrics_contract(tmp_path):
    _seed(tmp_path, "pkg/metrics/registry.py", """\
        from prometheus_client import Counter, Gauge

        dead_gauge = Gauge("dead", "declared but never mutated")
        live = Counter("live", "mutated below")

        def bump():
            live.inc()
    """)
    _seed(tmp_path, "pkg/loop/ctrl.py", """\
        from pkg.metrics import registry as metrics

        def f():
            metrics.ghost.inc()
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "dead_gauge" in r.stdout  # declared, never mutated
    assert "ghost" in r.stdout  # mutated, never declared
    assert "live" not in r.stdout.replace("live.inc", "")


# --- config-contract ------------------------------------------------------


def test_seeded_config_contract(tmp_path):
    _seed(tmp_path, "pkg/utils/config.py", """\
        import dataclasses

        @dataclasses.dataclass(frozen=True)
        class ReschedulerConfig:
            knob_without_flag: int = 3
            unwired: bool = True
            wired: bool = True
    """)
    _seed(tmp_path, "pkg/cli/main.py", """\
        import argparse

        def build_parser():
            p = argparse.ArgumentParser()
            p.add_argument("--wired", default=True)
            p.add_argument("--unwired", default=True)
            p.add_argument("--mystery-flag", default=1)
            return p

        def config_from_args(args):
            from pkg.utils.config import ReschedulerConfig

            return ReschedulerConfig(wired=args.wired)
    """)
    (tmp_path / "PARITY.md").write_text(
        "`wired`, `unwired`, and `knob_without_flag` are documented.\n"
    )
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "knob_without_flag" in r.stdout  # field without flag
    assert "silently does nothing" in r.stdout  # parsed but unwired
    assert "--mystery-flag" in r.stdout  # flag without field (warn)


def test_config_doc_mention_required(tmp_path):
    _seed(tmp_path, "pkg/utils/config.py", """\
        import dataclasses

        @dataclasses.dataclass(frozen=True)
        class ReschedulerConfig:
            documented: int = 1
            undocumented: int = 2
    """)
    _seed(tmp_path, "pkg/cli/main.py", """\
        import argparse

        def build_parser():
            p = argparse.ArgumentParser()
            p.add_argument("--documented", default=1)
            p.add_argument("--undocumented", default=2)
            return p

        def config_from_args(args):
            return ReschedulerConfig(
                documented=args.documented,
                undocumented=args.undocumented,
            )

        def ReschedulerConfig(**kw):
            return kw
    """)
    (tmp_path / "PARITY.md").write_text("only `documented` is here\n")
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "undocumented" in r.stdout and "PARITY.md" in r.stdout


# --- trace-contract -------------------------------------------------------


def test_seeded_trace_contract(tmp_path):
    """Both directions: an emitted-but-undeclared span name and a
    declared-but-never-emitted registry entry each turn the gate red;
    a name emitted AND declared is clean."""
    _seed(tmp_path, "pkg/utils/tracing.py", """\
        SPAN_NAMES = {
            "good": "emitted below",
            "dead": "declared here, emitted nowhere",
        }

        def span(name, **attrs):
            pass

        def phase(name):
            pass

        def make_span(name, t0_ms, dur_ms):
            return (name, t0_ms, dur_ms)
    """)
    _seed(tmp_path, "pkg/loop/ctrl.py", """\
        from pkg.utils import tracing

        def tick():
            with tracing.phase("good"):
                pass
            with tracing.span("rogue"):
                pass
            return tracing.make_span("good", 0.0, 1.0)
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "rogue" in r.stdout  # emitted, never declared
    assert "dead" in r.stdout  # declared, never emitted
    hits = [l for l in r.stdout.splitlines() if "trace-contract" in l]
    assert len(hits) == 2, r.stdout  # 'good' is clean in both directions


def test_trace_contract_inert_without_registry(tmp_path):
    """A tree with no utils/tracing.py SPAN_NAMES (every other fixture
    tree in this file) must not be forced to carry one."""
    _seed(tmp_path, "pkg/loop/ctrl.py", """\
        from pkg.utils import tracing

        def tick():
            with tracing.span("anything"):
                pass
    """)
    r = _analyze_tree(tmp_path)
    assert "trace-contract" not in r.stdout


# --- kube-write-retry -----------------------------------------------------


def test_seeded_kube_write_retry(tmp_path):
    _seed(tmp_path, "io/kube.py", """\
        class Client:
            def _read_retrying(self, method, path, timeout=30.0):
                return b""

            def _request(self, method, path):
                return self._read_retrying("GET", path, timeout=30)

            def evict_pod(self, path):
                # a retried write double-fires its side effect
                return self._read_retrying("POST", path, timeout=30)
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "kube-write-retry" in r.stdout
    assert "non-'GET'" in r.stdout
    assert "evict_pod" in r.stdout


# --- manifest-contract ----------------------------------------------------


def test_seeded_manifest_uncovered_root(tmp_path):
    """Adding a jit root without registering it in HOT_PROGRAMS turns
    the gate red (acceptance: coverage cannot silently shrink)."""
    _seed(tmp_path, "solver/prog.py", """\
        import jax


        @jax.jit
        def covered(x):
            return x + 1


        @jax.jit
        def uncovered(x):
            return x - 1


        def hot_program(**kw):
            return kw


        HOT_PROGRAMS = {
            "prog.covered": hot_program(
                covers=("solver.prog:covered",),
            ),
        }
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    hits = [l for l in r.stdout.splitlines() if "manifest-contract" in l]
    assert len(hits) == 1, r.stdout
    assert "uncovered" in hits[0]


def test_seeded_manifest_deleted_entry(tmp_path):
    """Deleting the manifest entry that covered a root turns the gate
    red from the OTHER side: the root is now uncovered. A covers string
    naming a removed root is equally red."""
    _seed(tmp_path, "solver/prog.py", """\
        import jax


        @jax.jit
        def orphaned(x):
            return x + 1


        def hot_program(**kw):
            return kw


        HOT_PROGRAMS = {
            "prog.stale": hot_program(
                covers=("solver.prog:deleted_root",),
            ),
        }
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "orphaned" in r.stdout  # the root lost its coverage
    assert "no such jit root" in r.stdout  # the dangling covers entry


def test_manifest_exemption_honored_and_staleness_warned(tmp_path):
    _seed(tmp_path, "solver/prog.py", """\
        import jax


        @jax.jit
        def hardware_only(x):
            return x + 1


        EXEMPT_JIT_ROOTS = {
            "solver.prog:hardware_only": "needs a TPU lowering",
            "solver.prog:long_gone": "stale pattern",
        }
    """)
    r = _analyze_tree(tmp_path)
    hits = [l for l in r.stdout.splitlines() if "manifest-contract" in l]
    assert len(hits) == 1, r.stdout  # only the stale exemption, warn tier
    assert "long_gone" in hits[0] and "[warn]" in hits[0]


def test_manifest_contract_inert_without_manifest_infra(tmp_path):
    """Fixture trees with jit roots but NO manifest infrastructure stay
    silent — the contract gates trees that opted into the jaxpr tier
    (the real package always has hot_programs.py in the walk)."""
    _seed(tmp_path, "solver/plain.py", """\
        import jax


        @jax.jit
        def solve(x):
            return x + 1
    """)
    r = _analyze_tree(tmp_path)
    assert "manifest-contract" not in r.stdout


# --- lock-discipline ------------------------------------------------------


def test_seeded_lock_discipline(tmp_path):
    _seed(tmp_path, "state/shared.py", """\
        import threading

        class Shared:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def good(self):
                with self._lock:
                    self.count += 1

            def outer(self):
                with self._lock:
                    self._bump()

            def _bump(self):  # every call site holds the lock
                self.count += 2

            def apply_locked(self):  # caller-holds-lock convention
                self.count += 3

            def bad(self):
                self.count = 5
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    hits = [l for l in r.stdout.splitlines() if "lock-discipline" in l]
    assert len(hits) == 1, r.stdout
    assert "Shared.bad" in hits[0]


# --- exception-discipline -------------------------------------------------


def test_seeded_exception_discipline():
    """Blind excepts on the service/io/loop planes must re-raise,
    record (flight/metrics/health), or carry the typed noqa — one
    finding per undisciplined handler, none for the compliant forms."""
    import tempfile
    from pathlib import Path

    with tempfile.TemporaryDirectory() as tmp:
        tmp_path = Path(tmp)
        _seed(tmp_path, "service/handler.py", """\
            from k8s_spot_rescheduler_tpu.loop import flight, health
            from k8s_spot_rescheduler_tpu.metrics import registry as metrics

            def bad_swallow():
                try:
                    work()
                except Exception as err:
                    log(err)

            def bad_tuple():
                try:
                    work()
                except (ValueError, Exception):
                    pass

            def ok_reraise():
                try:
                    work()
                except Exception:
                    raise

            def ok_flight():
                try:
                    work()
                except Exception as err:
                    flight.note_event("service-shed", cause=str(err))

            def ok_metrics():
                try:
                    work()
                except Exception:
                    metrics.update_service_request("error")

            def ok_health():
                try:
                    work()
                except BaseException:
                    health.STATE.note_startup_degraded()

            def ok_justified():
                try:
                    work()
                except Exception:  # noqa: exception-discipline
                    pass

            def ok_specific():
                try:
                    work()
                except ValueError:
                    pass
        """)
        # out-of-scope plane: the same swallow in solver/ is NOT flagged
        _seed(tmp_path, "solver/kernel.py", """\
            def swallow():
                try:
                    work()
                except Exception:
                    pass
        """)
        r = _analyze_tree(tmp_path)
        assert r.returncode == 1
        hits = [
            l for l in r.stdout.splitlines() if "exception-discipline" in l
        ]
        assert len(hits) == 2, r.stdout
        assert any("bad_swallow" in h for h in hits)
        assert any("bad_tuple" in h for h in hits)
        assert not any("solver/kernel.py" in h for h in hits)


def test_seeded_exception_discipline_bare_except_in_loop():
    import tempfile
    from pathlib import Path

    with tempfile.TemporaryDirectory() as tmp:
        tmp_path = Path(tmp)
        _seed(tmp_path, "loop/runner.py", """\
            def swallow():
                try:
                    work()
                except:  # noqa: bare-except
                    pass
        """)
        r = _analyze_tree(tmp_path)
        assert r.returncode == 1
        hits = [
            l for l in r.stdout.splitlines() if "exception-discipline" in l
        ]
        assert len(hits) == 1 and "bare except" in hits[0], r.stdout


# --- jaxpr tier: dtype-promotion ------------------------------------------

_MANIFEST_PRELUDE = """\
    import jax
    import jax.numpy as jnp

    from k8s_spot_rescheduler_tpu.hot_programs import (
        HotProgram,
        packed_struct,
    )

"""


def test_jaxpr_seeded_float64_literal(tmp_path):
    """A planted float64 literal in a traced fn leaves no jaxpr residue
    under x64-off (JAX truncates it) — the pass must catch it from the
    trace-time warning."""
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(packed):
        scale = jnp.array(1.5, dtype=jnp.float64)
        return (jnp.asarray(packed.spot_free) * scale).sum()


    HOT_PROGRAMS = {
        "fix.f64": HotProgram(
            build=lambda s: (_solve, (packed_struct(s),)),
        ),
    }
    """)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "dtype-promotion" in r.stdout
    assert "64-bit" in r.stdout


def test_jaxpr_seeded_carry_mismatch(tmp_path):
    """A scan whose carry changes dtype mid-loop (the exact bug class of
    the ROADMAP-5 narrow-int carry refactor) fails at trace time; the
    pass owns the resulting finding."""
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(packed):
        def step(c, _):
            return c.astype(jnp.int32), None

        out, _ = jax.lax.scan(
            step, jnp.asarray(packed.spot_free), None, length=3
        )
        return out


    HOT_PROGRAMS = {
        "fix.carry": HotProgram(
            build=lambda s: (_solve, (packed_struct(s),)),
        ),
    }
    """)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "dtype-promotion" in r.stdout
    assert "carry" in r.stdout


def test_jaxpr_seeded_narrow_carry_promotion_mismatch(tmp_path):
    """The LANDED narrow-carry layout's one-keystroke regression: an
    int16 delta carry whose update forgets the ``.astype`` narrow-back
    silently promotes (int16 + weak int32 delta -> int32) and the scan
    carry types no longer match. The dtype pass must CLASSIFY it as a
    carry mismatch finding — never surface the raw trace TypeError."""
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(packed):
        used0 = jnp.zeros(packed.spot_free.shape, jnp.int16)

        def step(used, _):
            # BUG: delta computed in i32, narrow-back astype forgotten
            delta = jnp.ones(used.shape, jnp.int32)
            return used + delta, None

        out, _ = jax.lax.scan(step, used0, None, length=3)
        return out


    HOT_PROGRAMS = {
        "fix.narrow_carry": HotProgram(
            build=lambda s: (_solve, (packed_struct(s),)),
        ),
    }
    """)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "dtype-promotion" in r.stdout
    assert "carry" in r.stdout
    assert "Traceback" not in r.stdout  # classified, not a raw TypeError


# --- jaxpr tier: index-width ----------------------------------------------


def test_jaxpr_seeded_index_overflow_at_max_shapes(tmp_path):
    """An int32 flattened C*S offset overflows at the declared 20x max
    shapes (1M pods / 100k nodes: C*S = 2.6e9 > 2^31) — the gate that
    makes narrow-int packing safe to attempt."""
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(packed):
        C = packed.slot_req.shape[0]
        S = packed.spot_free.shape[0]
        lane = jnp.arange(C, dtype=jnp.int32)
        spot = jnp.arange(S, dtype=jnp.int32)
        flat = lane[:, None] * jnp.int32(S) + spot[None, :]
        return flat


    HOT_PROGRAMS = {
        "fix.overflow": HotProgram(
            build=lambda s: (_solve, (packed_struct(s),)),
        ),
    }
    """)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "index-width" in r.stdout
    assert "int32" in r.stdout and "wraparound" in r.stdout


def test_jaxpr_clean_index_math_stays_clean(tmp_path):
    """Negative fixture: per-axis int32 index math (the real kernels'
    shape) is in range at max shapes — no finding."""
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(packed):
        S = packed.spot_free.shape[0]
        fits = jnp.asarray(packed.spot_ok)
        first = jnp.argmax(fits)  # [0, S-1]: fits i32 at any S here
        onehot = jnp.arange(S) == first
        return onehot.sum()


    HOT_PROGRAMS = {
        "fix.clean": HotProgram(
            build=lambda s: (_solve, (packed_struct(s),)),
        ),
    }
    """)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "index-width" not in r.stdout


# --- jaxpr tier: transfer-audit -------------------------------------------


def test_jaxpr_seeded_donation_without_alias(tmp_path):
    """A donated arg with no aliasable output silently copies — the
    declaration must be proven, not trusted."""
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(a, b):
        return (a + b).sum()  # scalar out: 'a' cannot alias


    HOT_PROGRAMS = {
        "fix.donate": HotProgram(
            build=lambda s: (
                _solve,
                (
                    jax.ShapeDtypeStruct((64, 64), "float32"),
                    jax.ShapeDtypeStruct((64, 64), "float32"),
                ),
            ),
            donate_argnums=(0,),
        ),
    }
    """)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "transfer-audit" in r.stdout
    assert "NO output matches" in r.stdout


def test_jaxpr_seeded_const_capture_and_device_put(tmp_path):
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    _TABLE = jnp.zeros((512, 512), jnp.float32)  # 1 MiB by value


    def _solve(packed):
        x = jax.device_put(jnp.asarray(packed.spot_free))
        return x.sum() + _TABLE.sum()


    HOT_PROGRAMS = {
        "fix.transfer": HotProgram(
            build=lambda s: (_solve, (packed_struct(s),)),
        ),
    }
    """)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "device_put" in r.stdout
    assert "captures a" in r.stdout and "constant by value" in r.stdout


# --- jaxpr tier: memory-reconcile -----------------------------------------


def test_jaxpr_seeded_estimator_drift_names_component(tmp_path):
    """A drifted estimator fails memory-reconcile and the finding names
    WHICH component drifted (per-component reporting acceptance)."""
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(packed):
        def step(c, _):
            return c + 1.0, None

        out, _ = jax.lax.scan(
            step, jnp.asarray(packed.spot_free), None, length=4
        )
        return out


    def _estimator(shapes):
        # carries claimed 100x what the traced scan holds
        plane = shapes.S * shapes.R * 4
        return {
            "carries": 200 * plane,
            "slots": 1,
            "spot_static": 1,
            "outputs": 1,
            "temporaries": 1,
            "repair": 1,
        }


    HOT_PROGRAMS = {
        "fix.memdrift": HotProgram(
            build=lambda s: (_solve, (packed_struct(s),)),
            reconcile={"estimator": _estimator},
        ),
    }
    """)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "memory-reconcile" in r.stdout
    assert "'carries' drifted" in r.stdout
    # the per-component table rides the finding
    assert "estimator[" in r.stdout and "traced[" in r.stdout


# --- jaxpr tier: trace failures, suppression, baseline --------------------


def test_jaxpr_trace_failure_is_red(tmp_path):
    """A manifest entry that cannot trace is lost audit coverage — an
    error, never a silent skip."""
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(packed):
        raise RuntimeError("builder broke")


    HOT_PROGRAMS = {
        "fix.broken": HotProgram(
            build=lambda s: (_solve, (packed_struct(s),)),
        ),
    }
    """)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "trace-failure" in r.stdout


def test_jaxpr_noqa_suppresses_on_manifest_line(tmp_path):
    """Jaxpr findings anchor to the manifest entry line, so the shared
    typed-noqa grammar applies to them unchanged."""
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(packed):
        C = packed.slot_req.shape[0]
        S = packed.spot_free.shape[0]
        lane = jnp.arange(C, dtype=jnp.int32)
        return lane[:, None] * jnp.int32(S)


    HOT_PROGRAMS = {
        "fix.overflow": HotProgram(  # noqa: index-width
            build=lambda s: (_solve, (packed_struct(s),)),
        ),
    }
    """)
    assert "index-width" not in r.stdout, r.stdout
    assert r.returncode == 0, r.stdout + r.stderr


def test_jaxpr_baseline_grandfathers(tmp_path):
    """Jaxpr-tier findings flow through the same baseline file."""
    manifest, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(packed):
        C = packed.slot_req.shape[0]
        S = packed.spot_free.shape[0]
        lane = jnp.arange(C, dtype=jnp.int32)
        return lane[:, None] * jnp.int32(S)


    HOT_PROGRAMS = {
        "fix.overflow": HotProgram(
            build=lambda s: (_solve, (packed_struct(s),)),
        ),
    }
    """)
    assert r.returncode == 1
    r = _run(
        tmp_path, "--tier", "jaxpr", "--manifest", manifest,
        "--no-baseline", "--json",
    )
    found = json.loads(r.stdout)["findings"]
    assert found and all(f["tier"] == "jaxpr" for f in found)
    baseline = tmp_path / "baseline.txt"
    baseline.write_text("".join(
        f"{f['path']}::{f['code']}::{f['anchor']}  # grandfathered\n"
        for f in found
    ))
    r = _run(
        tmp_path, "--tier", "jaxpr", "--manifest", manifest,
        "--baseline", baseline,
    )
    assert r.returncode == 0, r.stdout
    assert "baselined" in r.stderr


# --- suppressions / noqa grammar ------------------------------------------


def test_bare_noqa_is_a_finding(tmp_path):
    _seed(tmp_path, "mod.py", "x = 1  # noqa\n")
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "bare-noqa" in r.stdout


def test_typed_noqa_suppresses_only_named_code(tmp_path):
    _seed(tmp_path, "solver/suppressed.py", """\
        import jax

        @jax.jit
        def solve(x):
            return x.item()  # noqa: jax-host-sync
    """)
    r = _analyze_tree(tmp_path)
    assert "jax-host-sync" not in r.stdout
    _seed(tmp_path, "solver/wrong_code.py", """\
        import jax

        @jax.jit
        def solve2(x):
            return x.item()  # noqa: lock-discipline
    """)
    r = _analyze_tree(tmp_path)
    assert "jax-host-sync" in r.stdout  # wrong code suppresses nothing


def test_unknown_suppression_code_warns(tmp_path):
    _seed(tmp_path, "mod.py", "x = 1  # noqa: TOTALLY-MADE-UP\n")
    r = _analyze_tree(tmp_path)
    assert "unknown-suppression" in r.stdout
    assert r.returncode == 0  # warn tier
    assert _analyze_tree(tmp_path, "--strict").returncode == 1


def test_no_bare_noqa_in_tree():
    """Satellite guarantee: every suppression in the repo names a code."""
    r = _run("--tier", "ast")
    assert "bare-noqa" not in r.stdout


# --- baseline -------------------------------------------------------------


def test_baseline_grandfathers_and_goes_stale(tmp_path):
    _seed(tmp_path, "solver/bad.py", """\
        import jax

        @jax.jit
        def solve(x):
            return x.item()
    """)
    parity = tmp_path / "PARITY.md"
    parity.write_text("")
    # find the finding's key via --json, grandfather it, rerun
    r = _run(
        tmp_path, "--tier", "ast", "--no-baseline", "--parity", parity,
        "--json",
    )
    found = json.loads(r.stdout)["findings"]
    assert found, r.stdout
    key = (
        f"{found[0]['path']}::{found[0]['code']}::{found[0]['anchor']}"
    )
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(f"{key}  # grandfathered for the test\n")
    r = _run(
        tmp_path, "--tier", "ast", "--baseline", baseline,
        "--parity", parity,
    )
    assert r.returncode == 0, r.stdout
    assert "1 baselined" in r.stderr
    # paid debt: entry no longer matches -> stale-baseline warning
    (tmp_path / "solver" / "bad.py").write_text("x = 1\n")
    r = _run(
        tmp_path, "--tier", "ast", "--baseline", baseline,
        "--parity", parity,
    )
    assert "stale-baseline" in r.stdout
    assert r.returncode == 0  # warn tier


# --- --json schema --------------------------------------------------------


def test_json_output_schema(tmp_path):
    _seed(tmp_path, "solver/bad.py", """\
        import jax

        @jax.jit
        def solve(x):
            return x.item()
    """)
    r = _analyze_tree(tmp_path, "--json")
    out = json.loads(r.stdout)
    assert out["version"] == 1
    assert out["tier"] == "ast"
    assert set(out["counts"]) == {"error", "warn", "baselined"}
    f = out["findings"][0]
    assert set(f) == {
        "path", "line", "code", "severity", "message", "anchor", "tier",
    }
    assert f["code"] == "jax-host-sync"
    assert f["severity"] == "error"
    assert f["tier"] == "ast"
