"""The static-analysis suite (tools/analysis) must actually gate.

Mirror of tests/test_lint.py for the vet half of the chain: every pass
is proven by a seeded violation (a fixture tree the pass must fail), the
real tree must be clean (`make analyze` then enforces that forever), the
shared typed-suppression grammar is pinned, and the watchdog keeps the
run inside the `make check` latency budget.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _run(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.analysis", *map(str, args)],
        capture_output=True,
        text=True,
        cwd=REPO,
    )


def _seed(tmp_path, rel, source):
    f = tmp_path / rel
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return f


def _analyze_tree(tmp_path, *extra):
    # fixture runs: no baseline, and the doc check reads the fixture's
    # parity file (or skips when the fixture ships none)
    parity = tmp_path / "PARITY.md"
    if not parity.exists():
        parity.write_text("")
    return _run(tmp_path, "--no-baseline", "--parity", parity, *extra)


# --- the gate itself ------------------------------------------------------


def test_tree_is_clean():
    r = _run()
    assert r.returncode == 0, f"analysis gate is red:\n{r.stdout}{r.stderr}"


def test_tree_is_clean_within_watchdog():
    """The full run must stay under 10 s so `make check` stays fast."""
    r = _run("--max-seconds", "10")
    assert r.returncode == 0, f"watchdog tripped:\n{r.stdout}{r.stderr}"


def test_noqa_trailing_prose_still_suppresses(tmp_path):
    """Prose after a code must not merge into the code token."""
    _seed(tmp_path, "solver/prose.py", """\
        import jax

        @jax.jit
        def solve(x):
            return x.item()  # noqa: jax-host-sync - fetched once, on purpose
    """)
    r = _analyze_tree(tmp_path)
    assert "jax-host-sync" not in r.stdout
    assert "unknown-suppression" not in r.stdout


def test_donation_unresolvable_spec_skipped(tmp_path):
    """A statically-unresolvable donate_argnums spec must cost recall,
    never produce a false error; tuple(range(N)) IS resolvable."""
    _seed(tmp_path, "planner/spec_donate.py", """\
        import jax

        _SPEC = (0,)

        def f(a, b):
            return a + b

        g = jax.jit(f, donate_argnums=_SPEC)  # unresolvable: skip
        h = jax.jit(f, donate_argnums=tuple(range(1)))  # resolves to {0}

        def use_g(a, b):
            out = g(a, b)
            return b + out  # b not provably donated: no finding

        def use_h(a, b):
            out = h(a, b)
            return a + out  # a donated at position 0: finding
    """)
    r = _analyze_tree(tmp_path)
    hits = [
        l for l in r.stdout.splitlines() if "donation-discipline" in l
    ]
    assert len(hits) == 1, r.stdout
    assert "use_h" in hits[0]


def test_subset_roots_do_not_report_stale_baseline(tmp_path):
    _seed(tmp_path, "solver/bad.py", """\
        import jax

        @jax.jit
        def solve(x):
            return x.item()
    """)
    parity = tmp_path / "PARITY.md"
    parity.write_text("")
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(
        "some/other/file.py::lock-discipline::Foo.bar.attr  # elsewhere\n"
    )
    r = _run(tmp_path, "--baseline", baseline, "--parity", parity)
    # the seeded host-sync finding fires, but the unrelated entry is NOT
    # called stale — this is a subset-roots run
    assert "jax-host-sync" in r.stdout
    assert "stale-baseline" not in r.stdout


def test_unknown_pass_name_errors():
    """A --pass typo must error, not report a vacuously clean tree."""
    r = _run("--pass", "jax-hostsync-typo")
    assert r.returncode != 0
    assert "invalid choice" in r.stderr


def test_watchdog_fires_on_tiny_budget():
    r = _run("--max-seconds", "0.000001")
    assert r.returncode == 2
    assert "watchdog" in r.stderr


# --- jax-host-sync --------------------------------------------------------


def test_seeded_host_sync_direct(tmp_path):
    _seed(tmp_path, "solver/bad.py", """\
        import jax
        import numpy as np

        @jax.jit
        def solve(x):
            print(x)
            y = np.asarray(x)
            return y.item()
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert r.stdout.count("jax-host-sync") >= 3
    for needle in ("print()", "np.asarray()", ".item()"):
        assert needle in r.stdout


def test_seeded_host_sync_via_call_graph(tmp_path):
    """A sync inside a helper only *reachable* from a jitted root must
    fire — this is what a per-file linter cannot see."""
    _seed(tmp_path, "solver/indirect.py", """\
        import jax

        @jax.jit
        def root(x):
            return _helper(x)

        def _helper(x):
            return x.item()
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "jax-host-sync" in r.stdout and "_helper" in r.stdout


def test_host_sync_not_flagged_outside_jit(tmp_path):
    _seed(tmp_path, "solver/hostside.py", """\
        import numpy as np

        def decode(vec):
            return np.asarray(vec).item()
    """)
    r = _analyze_tree(tmp_path)
    assert "jax-host-sync" not in r.stdout


def test_host_sync_nested_branch_fires(tmp_path):
    """A sync inside a nested def (the lax.cond branch shape) fires
    exactly once — nested bodies are each their own entry, and visiting
    the parent must neither skip nor double-report them."""
    _seed(tmp_path, "solver/nested.py", """\
        import jax

        @jax.jit
        def outer(pred, x):
            def branch(y):
                return y.item()

            def other(y):
                return y

            return jax.lax.cond(pred, branch, other, x)
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1, r.stdout
    hits = [l for l in r.stdout.splitlines() if ".item()" in l]
    assert len(hits) == 1, r.stdout


def test_host_sync_static_argnames_direct_decorator(tmp_path):
    """The @jax.jit(static_argnames=...) decorator form exempts its
    static params just like the jax.jit(f, ...) call form."""
    _seed(tmp_path, "solver/dec_static.py", """\
        import jax

        @jax.jit(static_argnames=("n",))
        def scale(x, n=2):
            return x * int(n)
    """)
    r = _analyze_tree(tmp_path)
    assert "jax-host-sync" not in r.stdout


def test_host_sync_static_argnames_exempt(tmp_path):
    """int() on a static_argnames parameter is trace-time Python, not a
    device sync (solver/repair.py's spot_chunks pattern)."""
    _seed(tmp_path, "solver/static_ok.py", """\
        import jax

        def solve(x, chunks=2):
            n = int(chunks)
            return x * n

        solve_jit = jax.jit(solve, static_argnames=("chunks",))
    """)
    r = _analyze_tree(tmp_path)
    assert "jax-host-sync" not in r.stdout


# --- donation-discipline --------------------------------------------------


def test_seeded_donation_read_after_donate(tmp_path):
    _seed(tmp_path, "planner/bad_donate.py", """\
        import jax

        def f(a, b):
            return a + b

        g = jax.jit(f, donate_argnums=(0,))

        def use(a, b):
            out = g(a, b)
            return a + out
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "donation-discipline" in r.stdout


def test_donation_multiline_call_is_clean(tmp_path):
    """The donated argument's own Load inside a reflowed multi-line call
    must not count as a read-after-donate."""
    _seed(tmp_path, "planner/wrapped_donate.py", """\
        import jax

        def f(a, b):
            return a + b

        g = jax.jit(f, donate_argnums=(0,))

        def use(a, b):
            out = g(
                a,
                b,
            )
            return out
    """)
    r = _analyze_tree(tmp_path)
    assert "donation-discipline" not in r.stdout


def test_donation_rebind_is_clean(tmp_path):
    _seed(tmp_path, "planner/good_donate.py", """\
        import jax

        def f(a, b):
            return a + b

        g = jax.jit(f, donate_argnums=(0,))

        def use(a, b):
            a = g(a, b)
            return a + b
    """)
    r = _analyze_tree(tmp_path)
    assert "donation-discipline" not in r.stdout


def test_donation_shadowed_nested_param_is_clean(tmp_path):
    """A donating call on a nested function's OWN parameter must not be
    attributed to the enclosing function's same-named binding."""
    _seed(tmp_path, "planner/shadow_donate.py", """\
        import jax

        def f(a):
            return a

        step = jax.jit(f, donate_argnums=(0,))

        def outer(a):
            def inner(a):
                return step(a)

            y = inner(a)
            return a + y
    """)
    r = _analyze_tree(tmp_path)
    hits = [
        l for l in r.stdout.splitlines() if "donation-discipline" in l
    ]
    assert not any("'outer'" in h for h in hits), r.stdout


# --- recompile-trigger ----------------------------------------------------


def test_seeded_recompile_triggers(tmp_path):
    _seed(tmp_path, "ops/bad_jit.py", """\
        import jax
        import time

        def tick(x):
            return jax.jit(lambda y: y + 1)(x)

        def build(fns):
            out = []
            for f in fns:
                out.append(jax.jit(f))
            return out

        def g(x):
            return x

        g_jit = jax.jit(g)

        def stamp(x):
            return g_jit(x * time.time())
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "recompiles" in r.stdout  # jit-per-call
    assert "inside a loop" in r.stdout
    assert "per-call-varying" in r.stdout


def test_recompile_no_double_report_in_loop(tmp_path):
    """jax.jit(f)(x) inside a loop is ONE finding (per-call), not also
    an in-loop construction finding for the same call."""
    _seed(tmp_path, "ops/loop_jit.py", """\
        import jax

        def drain(xs):
            out = []
            for x in xs:
                out.append(jax.jit(lambda a: a + 1)(x))
            return out
    """)
    r = _analyze_tree(tmp_path)
    hits = [l for l in r.stdout.splitlines() if "recompile-trigger" in l]
    assert len(hits) == 1, r.stdout
    assert "recompiles" in hits[0]


# --- metrics-contract -----------------------------------------------------


def test_seeded_metrics_contract(tmp_path):
    _seed(tmp_path, "pkg/metrics/registry.py", """\
        from prometheus_client import Counter, Gauge

        dead_gauge = Gauge("dead", "declared but never mutated")
        live = Counter("live", "mutated below")

        def bump():
            live.inc()
    """)
    _seed(tmp_path, "pkg/loop/ctrl.py", """\
        from pkg.metrics import registry as metrics

        def f():
            metrics.ghost.inc()
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "dead_gauge" in r.stdout  # declared, never mutated
    assert "ghost" in r.stdout  # mutated, never declared
    assert "live" not in r.stdout.replace("live.inc", "")


# --- config-contract ------------------------------------------------------


def test_seeded_config_contract(tmp_path):
    _seed(tmp_path, "pkg/utils/config.py", """\
        import dataclasses

        @dataclasses.dataclass(frozen=True)
        class ReschedulerConfig:
            knob_without_flag: int = 3
            unwired: bool = True
            wired: bool = True
    """)
    _seed(tmp_path, "pkg/cli/main.py", """\
        import argparse

        def build_parser():
            p = argparse.ArgumentParser()
            p.add_argument("--wired", default=True)
            p.add_argument("--unwired", default=True)
            p.add_argument("--mystery-flag", default=1)
            return p

        def config_from_args(args):
            from pkg.utils.config import ReschedulerConfig

            return ReschedulerConfig(wired=args.wired)
    """)
    (tmp_path / "PARITY.md").write_text(
        "`wired`, `unwired`, and `knob_without_flag` are documented.\n"
    )
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "knob_without_flag" in r.stdout  # field without flag
    assert "silently does nothing" in r.stdout  # parsed but unwired
    assert "--mystery-flag" in r.stdout  # flag without field (warn)


def test_config_doc_mention_required(tmp_path):
    _seed(tmp_path, "pkg/utils/config.py", """\
        import dataclasses

        @dataclasses.dataclass(frozen=True)
        class ReschedulerConfig:
            documented: int = 1
            undocumented: int = 2
    """)
    _seed(tmp_path, "pkg/cli/main.py", """\
        import argparse

        def build_parser():
            p = argparse.ArgumentParser()
            p.add_argument("--documented", default=1)
            p.add_argument("--undocumented", default=2)
            return p

        def config_from_args(args):
            return ReschedulerConfig(
                documented=args.documented,
                undocumented=args.undocumented,
            )

        def ReschedulerConfig(**kw):
            return kw
    """)
    (tmp_path / "PARITY.md").write_text("only `documented` is here\n")
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "undocumented" in r.stdout and "PARITY.md" in r.stdout


# --- kube-write-retry -----------------------------------------------------


def test_seeded_kube_write_retry(tmp_path):
    _seed(tmp_path, "io/kube.py", """\
        class Client:
            def _read_retrying(self, method, path, timeout=30.0):
                return b""

            def _request(self, method, path):
                return self._read_retrying("GET", path, timeout=30)

            def evict_pod(self, path):
                # a retried write double-fires its side effect
                return self._read_retrying("POST", path, timeout=30)
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "kube-write-retry" in r.stdout
    assert "non-'GET'" in r.stdout
    assert "evict_pod" in r.stdout


# --- lock-discipline ------------------------------------------------------


def test_seeded_lock_discipline(tmp_path):
    _seed(tmp_path, "state/shared.py", """\
        import threading

        class Shared:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def good(self):
                with self._lock:
                    self.count += 1

            def outer(self):
                with self._lock:
                    self._bump()

            def _bump(self):  # every call site holds the lock
                self.count += 2

            def apply_locked(self):  # caller-holds-lock convention
                self.count += 3

            def bad(self):
                self.count = 5
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    hits = [l for l in r.stdout.splitlines() if "lock-discipline" in l]
    assert len(hits) == 1, r.stdout
    assert "Shared.bad" in hits[0]


# --- suppressions / noqa grammar ------------------------------------------


def test_bare_noqa_is_a_finding(tmp_path):
    _seed(tmp_path, "mod.py", "x = 1  # noqa\n")
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "bare-noqa" in r.stdout


def test_typed_noqa_suppresses_only_named_code(tmp_path):
    _seed(tmp_path, "solver/suppressed.py", """\
        import jax

        @jax.jit
        def solve(x):
            return x.item()  # noqa: jax-host-sync
    """)
    r = _analyze_tree(tmp_path)
    assert "jax-host-sync" not in r.stdout
    _seed(tmp_path, "solver/wrong_code.py", """\
        import jax

        @jax.jit
        def solve2(x):
            return x.item()  # noqa: lock-discipline
    """)
    r = _analyze_tree(tmp_path)
    assert "jax-host-sync" in r.stdout  # wrong code suppresses nothing


def test_unknown_suppression_code_warns(tmp_path):
    _seed(tmp_path, "mod.py", "x = 1  # noqa: TOTALLY-MADE-UP\n")
    r = _analyze_tree(tmp_path)
    assert "unknown-suppression" in r.stdout
    assert r.returncode == 0  # warn tier
    assert _analyze_tree(tmp_path, "--strict").returncode == 1


def test_no_bare_noqa_in_tree():
    """Satellite guarantee: every suppression in the repo names a code."""
    r = _run()
    assert "bare-noqa" not in r.stdout


# --- baseline -------------------------------------------------------------


def test_baseline_grandfathers_and_goes_stale(tmp_path):
    _seed(tmp_path, "solver/bad.py", """\
        import jax

        @jax.jit
        def solve(x):
            return x.item()
    """)
    parity = tmp_path / "PARITY.md"
    parity.write_text("")
    # find the finding's key via --json, grandfather it, rerun
    r = _run(tmp_path, "--no-baseline", "--parity", parity, "--json")
    found = json.loads(r.stdout)["findings"]
    assert found, r.stdout
    key = (
        f"{found[0]['path']}::{found[0]['code']}::{found[0]['anchor']}"
    )
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(f"{key}  # grandfathered for the test\n")
    r = _run(tmp_path, "--baseline", baseline, "--parity", parity)
    assert r.returncode == 0, r.stdout
    assert "1 baselined" in r.stderr
    # paid debt: entry no longer matches -> stale-baseline warning
    (tmp_path / "solver" / "bad.py").write_text("x = 1\n")
    r = _run(tmp_path, "--baseline", baseline, "--parity", parity)
    assert "stale-baseline" in r.stdout
    assert r.returncode == 0  # warn tier


# --- --json schema --------------------------------------------------------


def test_json_output_schema(tmp_path):
    _seed(tmp_path, "solver/bad.py", """\
        import jax

        @jax.jit
        def solve(x):
            return x.item()
    """)
    r = _analyze_tree(tmp_path, "--json")
    out = json.loads(r.stdout)
    assert out["version"] == 1
    assert set(out["counts"]) == {"error", "warn", "baselined"}
    f = out["findings"][0]
    assert set(f) == {
        "path", "line", "code", "severity", "message", "anchor",
    }
    assert f["code"] == "jax-host-sync"
    assert f["severity"] == "error"
