"""The static-analysis suite (tools/analysis) must actually gate.

Mirror of tests/test_lint.py for the vet half of the chain, both tiers:
every AST pass is proven by a seeded violation (a fixture tree the pass
must fail), every jaxpr pass by a seeded manifest (a planted violation
in a traced program), the real tree must be clean on BOTH tiers (`make
analyze` + `make audit-jaxpr` then enforce that forever), the shared
typed-suppression grammar is pinned for both tiers, and the watchdogs
keep each stage inside the `make check` latency budget (10 s ast, 30 s
jaxpr). Fixture machinery lives in tests/analysis_fixtures.py, shared
with the lint gate.
"""

import json

from tests.analysis_fixtures import (
    analyze_tree as _analyze_tree,
    run_analysis as _run,
    seed_jaxpr_manifest,
    seed_tree as _seed,
)

# --- the gate itself ------------------------------------------------------


def test_tree_is_clean():
    """The unified default (--tier all): both tiers, one invocation."""
    r = _run()
    assert r.returncode == 0, f"analysis gate is red:\n{r.stdout}{r.stderr}"


def test_tree_is_clean_within_watchdog():
    """The ast stage (`make analyze`) must stay under 10 s."""
    r = _run("--tier", "ast", "--max-seconds", "10")
    assert r.returncode == 0, f"watchdog tripped:\n{r.stdout}{r.stderr}"


def test_jaxpr_tier_clean_within_watchdog():
    """`make audit-jaxpr` acceptance: the full jaxpr tier — every
    HOT_PROGRAMS entry traced (index-width at the declared 1M-pod /
    100k-node max shapes included) — runs CLEAN on an empty baseline
    and inside the 30 s CPU budget."""
    r = _run("--tier", "jaxpr", "--max-seconds", "30")
    assert r.returncode == 0, f"jaxpr gate is red:\n{r.stdout}{r.stderr}"


def test_proto_tier_clean_within_watchdog():
    """`make verify-protocol` acceptance: the full proto tier — both
    declared product automata exhaustively explored (safety + deadlock
    + storm-drain liveness) plus the model<->implementation contract —
    runs CLEAN on an empty baseline and inside the 60 s budget."""
    r = _run("--tier", "proto", "--max-seconds", "60")
    assert r.returncode == 0, f"proto gate is red:\n{r.stdout}{r.stderr}"


def test_noqa_trailing_prose_still_suppresses(tmp_path):
    """Prose after a code must not merge into the code token."""
    _seed(tmp_path, "solver/prose.py", """\
        import jax

        @jax.jit
        def solve(x):
            return x.item()  # noqa: jax-host-sync - fetched once, on purpose
    """)
    r = _analyze_tree(tmp_path)
    assert "jax-host-sync" not in r.stdout
    assert "unknown-suppression" not in r.stdout


def test_donation_unresolvable_spec_skipped(tmp_path):
    """A statically-unresolvable donate_argnums spec must cost recall,
    never produce a false error; tuple(range(N)) IS resolvable."""
    _seed(tmp_path, "planner/spec_donate.py", """\
        import jax

        _SPEC = (0,)

        def f(a, b):
            return a + b

        g = jax.jit(f, donate_argnums=_SPEC)  # unresolvable: skip
        h = jax.jit(f, donate_argnums=tuple(range(1)))  # resolves to {0}

        def use_g(a, b):
            out = g(a, b)
            return b + out  # b not provably donated: no finding

        def use_h(a, b):
            out = h(a, b)
            return a + out  # a donated at position 0: finding
    """)
    r = _analyze_tree(tmp_path)
    hits = [
        l for l in r.stdout.splitlines() if "donation-discipline" in l
    ]
    assert len(hits) == 1, r.stdout
    assert "use_h" in hits[0]


def test_subset_roots_do_not_report_stale_baseline(tmp_path):
    _seed(tmp_path, "solver/bad.py", """\
        import jax

        @jax.jit
        def solve(x):
            return x.item()
    """)
    parity = tmp_path / "PARITY.md"
    parity.write_text("")
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(
        "some/other/file.py::lock-discipline::Foo.bar.attr  # elsewhere\n"
    )
    r = _run(
        tmp_path, "--tier", "ast", "--baseline", baseline,
        "--parity", parity,
    )
    # the seeded host-sync finding fires, but the unrelated entry is NOT
    # called stale — this is a subset-roots run
    assert "jax-host-sync" in r.stdout
    assert "stale-baseline" not in r.stdout


def test_single_tier_does_not_stale_other_tiers_baseline(tmp_path):
    """An ast-only run must not call a jaxpr-tier baseline entry stale
    (and vice versa): the entry's pass never ran."""
    _seed(tmp_path, "solver/bad.py", """\
        import jax

        @jax.jit
        def solve(x):
            return x.item()
    """)
    parity = tmp_path / "PARITY.md"
    parity.write_text("")
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(
        "solver/bad.py::index-width::prog.check  # jaxpr-tier debt\n"
    )
    r = _run(
        tmp_path, "--tier", "ast", "--baseline", baseline,
        "--parity", parity,
    )
    assert "stale-baseline" not in r.stdout


def test_proto_tier_does_not_stale_other_tiers_baseline(tmp_path):
    """A proto-only run must not call ast/jaxpr baseline entries stale:
    their passes never ran (tier-qualified staleness, third tier)."""
    _seed(tmp_path, "solver/bad.py", """\
        import jax

        @jax.jit
        def solve(x):
            return x.item()
    """)
    parity = tmp_path / "PARITY.md"
    parity.write_text("")
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(
        "solver/bad.py::jax-host-sync::42  # ast-tier debt\n"
        "solver/bad.py::index-width::prog.check  # jaxpr-tier debt\n"
    )
    r = _run(
        tmp_path, "--tier", "proto", "--baseline", baseline,
        "--parity", parity,
    )
    assert "stale-baseline" not in r.stdout


def test_unknown_pass_name_errors():
    """A --pass typo must error, not report a vacuously clean tree."""
    r = _run("--pass", "jax-hostsync-typo")
    assert r.returncode != 0
    assert "invalid choice" in r.stderr


def test_pass_tier_mismatch_errors():
    """Naming a jaxpr pass under --tier ast (or vice versa) must error,
    not silently run nothing."""
    r = _run("--tier", "ast", "--pass", "index-width")
    assert r.returncode != 0
    assert "jaxpr-tier pass" in r.stderr
    r = _run("--tier", "jaxpr", "--pass", "lock-discipline")
    assert r.returncode != 0
    assert "ast-tier pass" in r.stderr


def test_pass_tier_mismatch_errors_proto():
    """The same tier/pass coherence holds for the proto tier: a proto
    pass under another tier (and an ast pass under --tier proto) is an
    argparse error, never a vacuously clean run."""
    r = _run("--tier", "ast", "--pass", "protocol-model")
    assert r.returncode != 0
    assert "proto-tier pass" in r.stderr
    r = _run("--tier", "jaxpr", "--pass", "protocol-contract")
    assert r.returncode != 0
    assert "proto-tier pass" in r.stderr
    r = _run("--tier", "proto", "--pass", "lock-graph")
    assert r.returncode != 0
    assert "ast-tier pass" in r.stderr


def test_watchdog_fires_on_tiny_budget():
    r = _run("--tier", "ast", "--max-seconds", "0.000001")
    assert r.returncode == 2
    assert "watchdog" in r.stderr


# --- jax-host-sync --------------------------------------------------------


def test_seeded_host_sync_direct(tmp_path):
    _seed(tmp_path, "solver/bad.py", """\
        import jax
        import numpy as np

        @jax.jit
        def solve(x):
            print(x)
            y = np.asarray(x)
            return y.item()
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert r.stdout.count("jax-host-sync") >= 3
    for needle in ("print()", "np.asarray()", ".item()"):
        assert needle in r.stdout


def test_seeded_host_sync_via_call_graph(tmp_path):
    """A sync inside a helper only *reachable* from a jitted root must
    fire — this is what a per-file linter cannot see."""
    _seed(tmp_path, "solver/indirect.py", """\
        import jax

        @jax.jit
        def root(x):
            return _helper(x)

        def _helper(x):
            return x.item()
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "jax-host-sync" in r.stdout and "_helper" in r.stdout


def test_host_sync_not_flagged_outside_jit(tmp_path):
    _seed(tmp_path, "solver/hostside.py", """\
        import numpy as np

        def decode(vec):
            return np.asarray(vec).item()
    """)
    r = _analyze_tree(tmp_path)
    assert "jax-host-sync" not in r.stdout


def test_host_sync_nested_branch_fires(tmp_path):
    """A sync inside a nested def (the lax.cond branch shape) fires
    exactly once — nested bodies are each their own entry, and visiting
    the parent must neither skip nor double-report them."""
    _seed(tmp_path, "solver/nested.py", """\
        import jax

        @jax.jit
        def outer(pred, x):
            def branch(y):
                return y.item()

            def other(y):
                return y

            return jax.lax.cond(pred, branch, other, x)
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1, r.stdout
    hits = [l for l in r.stdout.splitlines() if ".item()" in l]
    assert len(hits) == 1, r.stdout


def test_host_sync_static_argnames_direct_decorator(tmp_path):
    """The @jax.jit(static_argnames=...) decorator form exempts its
    static params just like the jax.jit(f, ...) call form."""
    _seed(tmp_path, "solver/dec_static.py", """\
        import jax

        @jax.jit(static_argnames=("n",))
        def scale(x, n=2):
            return x * int(n)
    """)
    r = _analyze_tree(tmp_path)
    assert "jax-host-sync" not in r.stdout


def test_host_sync_static_argnames_exempt(tmp_path):
    """int() on a static_argnames parameter is trace-time Python, not a
    device sync (solver/repair.py's spot_chunks pattern)."""
    _seed(tmp_path, "solver/static_ok.py", """\
        import jax

        def solve(x, chunks=2):
            n = int(chunks)
            return x * n

        solve_jit = jax.jit(solve, static_argnames=("chunks",))
    """)
    r = _analyze_tree(tmp_path)
    assert "jax-host-sync" not in r.stdout


# --- donation-discipline --------------------------------------------------


def test_seeded_donation_read_after_donate(tmp_path):
    _seed(tmp_path, "planner/bad_donate.py", """\
        import jax

        def f(a, b):
            return a + b

        g = jax.jit(f, donate_argnums=(0,))

        def use(a, b):
            out = g(a, b)
            return a + out
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "donation-discipline" in r.stdout


def test_donation_multiline_call_is_clean(tmp_path):
    """The donated argument's own Load inside a reflowed multi-line call
    must not count as a read-after-donate."""
    _seed(tmp_path, "planner/wrapped_donate.py", """\
        import jax

        def f(a, b):
            return a + b

        g = jax.jit(f, donate_argnums=(0,))

        def use(a, b):
            out = g(
                a,
                b,
            )
            return out
    """)
    r = _analyze_tree(tmp_path)
    assert "donation-discipline" not in r.stdout


def test_donation_rebind_is_clean(tmp_path):
    _seed(tmp_path, "planner/good_donate.py", """\
        import jax

        def f(a, b):
            return a + b

        g = jax.jit(f, donate_argnums=(0,))

        def use(a, b):
            a = g(a, b)
            return a + b
    """)
    r = _analyze_tree(tmp_path)
    assert "donation-discipline" not in r.stdout


def test_donation_shadowed_nested_param_is_clean(tmp_path):
    """A donating call on a nested function's OWN parameter must not be
    attributed to the enclosing function's same-named binding."""
    _seed(tmp_path, "planner/shadow_donate.py", """\
        import jax

        def f(a):
            return a

        step = jax.jit(f, donate_argnums=(0,))

        def outer(a):
            def inner(a):
                return step(a)

            y = inner(a)
            return a + y
    """)
    r = _analyze_tree(tmp_path)
    hits = [
        l for l in r.stdout.splitlines() if "donation-discipline" in l
    ]
    assert not any("'outer'" in h for h in hits), r.stdout


# --- recompile-trigger ----------------------------------------------------


def test_seeded_recompile_triggers(tmp_path):
    _seed(tmp_path, "ops/bad_jit.py", """\
        import jax
        import time

        def tick(x):
            return jax.jit(lambda y: y + 1)(x)

        def build(fns):
            out = []
            for f in fns:
                out.append(jax.jit(f))
            return out

        def g(x):
            return x

        g_jit = jax.jit(g)

        def stamp(x):
            return g_jit(x * time.time())
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "recompiles" in r.stdout  # jit-per-call
    assert "inside a loop" in r.stdout
    assert "per-call-varying" in r.stdout


def test_recompile_no_double_report_in_loop(tmp_path):
    """jax.jit(f)(x) inside a loop is ONE finding (per-call), not also
    an in-loop construction finding for the same call."""
    _seed(tmp_path, "ops/loop_jit.py", """\
        import jax

        def drain(xs):
            out = []
            for x in xs:
                out.append(jax.jit(lambda a: a + 1)(x))
            return out
    """)
    r = _analyze_tree(tmp_path)
    hits = [l for l in r.stdout.splitlines() if "recompile-trigger" in l]
    assert len(hits) == 1, r.stdout
    assert "recompiles" in hits[0]


# --- metrics-contract -----------------------------------------------------


def test_seeded_metrics_contract(tmp_path):
    _seed(tmp_path, "pkg/metrics/registry.py", """\
        from prometheus_client import Counter, Gauge

        dead_gauge = Gauge("dead", "declared but never mutated")
        live = Counter("live", "mutated below")

        def bump():
            live.inc()
    """)
    _seed(tmp_path, "pkg/loop/ctrl.py", """\
        from pkg.metrics import registry as metrics

        def f():
            metrics.ghost.inc()
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "dead_gauge" in r.stdout  # declared, never mutated
    assert "ghost" in r.stdout  # mutated, never declared
    assert "live" not in r.stdout.replace("live.inc", "")


# --- config-contract ------------------------------------------------------


def test_seeded_config_contract(tmp_path):
    _seed(tmp_path, "pkg/utils/config.py", """\
        import dataclasses

        @dataclasses.dataclass(frozen=True)
        class ReschedulerConfig:
            knob_without_flag: int = 3
            unwired: bool = True
            wired: bool = True
    """)
    _seed(tmp_path, "pkg/cli/main.py", """\
        import argparse

        def build_parser():
            p = argparse.ArgumentParser()
            p.add_argument("--wired", default=True)
            p.add_argument("--unwired", default=True)
            p.add_argument("--mystery-flag", default=1)
            return p

        def config_from_args(args):
            from pkg.utils.config import ReschedulerConfig

            return ReschedulerConfig(wired=args.wired)
    """)
    (tmp_path / "PARITY.md").write_text(
        "`wired`, `unwired`, and `knob_without_flag` are documented.\n"
    )
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "knob_without_flag" in r.stdout  # field without flag
    assert "silently does nothing" in r.stdout  # parsed but unwired
    assert "--mystery-flag" in r.stdout  # flag without field (warn)


def test_config_doc_mention_required(tmp_path):
    _seed(tmp_path, "pkg/utils/config.py", """\
        import dataclasses

        @dataclasses.dataclass(frozen=True)
        class ReschedulerConfig:
            documented: int = 1
            undocumented: int = 2
    """)
    _seed(tmp_path, "pkg/cli/main.py", """\
        import argparse

        def build_parser():
            p = argparse.ArgumentParser()
            p.add_argument("--documented", default=1)
            p.add_argument("--undocumented", default=2)
            return p

        def config_from_args(args):
            return ReschedulerConfig(
                documented=args.documented,
                undocumented=args.undocumented,
            )

        def ReschedulerConfig(**kw):
            return kw
    """)
    (tmp_path / "PARITY.md").write_text("only `documented` is here\n")
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "undocumented" in r.stdout and "PARITY.md" in r.stdout


# --- trace-contract -------------------------------------------------------


def test_seeded_trace_contract(tmp_path):
    """Both directions: an emitted-but-undeclared span name and a
    declared-but-never-emitted registry entry each turn the gate red;
    a name emitted AND declared is clean."""
    _seed(tmp_path, "pkg/utils/tracing.py", """\
        SPAN_NAMES = {
            "good": "emitted below",
            "dead": "declared here, emitted nowhere",
        }

        def span(name, **attrs):
            pass

        def phase(name):
            pass

        def make_span(name, t0_ms, dur_ms):
            return (name, t0_ms, dur_ms)
    """)
    _seed(tmp_path, "pkg/loop/ctrl.py", """\
        from pkg.utils import tracing

        def tick():
            with tracing.phase("good"):
                pass
            with tracing.span("rogue"):
                pass
            return tracing.make_span("good", 0.0, 1.0)
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "rogue" in r.stdout  # emitted, never declared
    assert "dead" in r.stdout  # declared, never emitted
    hits = [l for l in r.stdout.splitlines() if "trace-contract" in l]
    assert len(hits) == 2, r.stdout  # 'good' is clean in both directions


def test_trace_contract_inert_without_registry(tmp_path):
    """A tree with no utils/tracing.py SPAN_NAMES (every other fixture
    tree in this file) must not be forced to carry one."""
    _seed(tmp_path, "pkg/loop/ctrl.py", """\
        from pkg.utils import tracing

        def tick():
            with tracing.span("anything"):
                pass
    """)
    r = _analyze_tree(tmp_path)
    assert "trace-contract" not in r.stdout


# --- kube-write-retry -----------------------------------------------------


def test_seeded_kube_write_retry(tmp_path):
    _seed(tmp_path, "io/kube.py", """\
        class Client:
            def _read_retrying(self, method, path, timeout=30.0):
                return b""

            def _request(self, method, path):
                return self._read_retrying("GET", path, timeout=30)

            def evict_pod(self, path):
                # a retried write double-fires its side effect
                return self._read_retrying("POST", path, timeout=30)
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "kube-write-retry" in r.stdout
    assert "non-'GET'" in r.stdout
    assert "evict_pod" in r.stdout


# --- manifest-contract ----------------------------------------------------


def test_seeded_manifest_uncovered_root(tmp_path):
    """Adding a jit root without registering it in HOT_PROGRAMS turns
    the gate red (acceptance: coverage cannot silently shrink)."""
    _seed(tmp_path, "solver/prog.py", """\
        import jax


        @jax.jit
        def covered(x):
            return x + 1


        @jax.jit
        def uncovered(x):
            return x - 1


        def hot_program(**kw):
            return kw


        HOT_PROGRAMS = {
            "prog.covered": hot_program(
                covers=("solver.prog:covered",),
            ),
        }
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    hits = [l for l in r.stdout.splitlines() if "manifest-contract" in l]
    assert len(hits) == 1, r.stdout
    assert "uncovered" in hits[0]


def test_seeded_manifest_deleted_entry(tmp_path):
    """Deleting the manifest entry that covered a root turns the gate
    red from the OTHER side: the root is now uncovered. A covers string
    naming a removed root is equally red."""
    _seed(tmp_path, "solver/prog.py", """\
        import jax


        @jax.jit
        def orphaned(x):
            return x + 1


        def hot_program(**kw):
            return kw


        HOT_PROGRAMS = {
            "prog.stale": hot_program(
                covers=("solver.prog:deleted_root",),
            ),
        }
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "orphaned" in r.stdout  # the root lost its coverage
    assert "no such jit root" in r.stdout  # the dangling covers entry


def test_manifest_exemption_honored_and_staleness_warned(tmp_path):
    _seed(tmp_path, "solver/prog.py", """\
        import jax


        @jax.jit
        def hardware_only(x):
            return x + 1


        EXEMPT_JIT_ROOTS = {
            "solver.prog:hardware_only": "needs a TPU lowering",
            "solver.prog:long_gone": "stale pattern",
        }
    """)
    r = _analyze_tree(tmp_path)
    hits = [l for l in r.stdout.splitlines() if "manifest-contract" in l]
    assert len(hits) == 1, r.stdout  # only the stale exemption, warn tier
    assert "long_gone" in hits[0] and "[warn]" in hits[0]


def test_manifest_contract_inert_without_manifest_infra(tmp_path):
    """Fixture trees with jit roots but NO manifest infrastructure stay
    silent — the contract gates trees that opted into the jaxpr tier
    (the real package always has hot_programs.py in the walk)."""
    _seed(tmp_path, "solver/plain.py", """\
        import jax


        @jax.jit
        def solve(x):
            return x + 1
    """)
    r = _analyze_tree(tmp_path)
    assert "manifest-contract" not in r.stdout


# --- lock-discipline ------------------------------------------------------


def test_seeded_lock_discipline(tmp_path):
    _seed(tmp_path, "state/shared.py", """\
        import threading

        class Shared:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def good(self):
                with self._lock:
                    self.count += 1

            def outer(self):
                with self._lock:
                    self._bump()

            def _bump(self):  # every call site holds the lock
                self.count += 2

            def apply_locked(self):  # caller-holds-lock convention
                self.count += 3

            def bad(self):
                self.count = 5
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    hits = [l for l in r.stdout.splitlines() if "lock-discipline" in l]
    assert len(hits) == 1, r.stdout
    assert "Shared.bad" in hits[0]


# --- exception-discipline -------------------------------------------------


def test_seeded_exception_discipline():
    """Blind excepts on the service/io/loop planes must re-raise,
    record (flight/metrics/health), or carry the typed noqa — one
    finding per undisciplined handler, none for the compliant forms."""
    import tempfile
    from pathlib import Path

    with tempfile.TemporaryDirectory() as tmp:
        tmp_path = Path(tmp)
        _seed(tmp_path, "service/handler.py", """\
            from k8s_spot_rescheduler_tpu.loop import flight, health
            from k8s_spot_rescheduler_tpu.metrics import registry as metrics

            def bad_swallow():
                try:
                    work()
                except Exception as err:
                    log(err)

            def bad_tuple():
                try:
                    work()
                except (ValueError, Exception):
                    pass

            def ok_reraise():
                try:
                    work()
                except Exception:
                    raise

            def ok_flight():
                try:
                    work()
                except Exception as err:
                    flight.note_event("service-shed", cause=str(err))

            def ok_metrics():
                try:
                    work()
                except Exception:
                    metrics.update_service_request("error")

            def ok_health():
                try:
                    work()
                except BaseException:
                    health.STATE.note_startup_degraded()

            def ok_justified():
                try:
                    work()
                except Exception:  # noqa: exception-discipline
                    pass

            def ok_specific():
                try:
                    work()
                except ValueError:
                    pass
        """)
        # out-of-scope plane: the same swallow in solver/ is NOT flagged
        _seed(tmp_path, "solver/kernel.py", """\
            def swallow():
                try:
                    work()
                except Exception:
                    pass
        """)
        r = _analyze_tree(tmp_path)
        assert r.returncode == 1
        hits = [
            l for l in r.stdout.splitlines() if "exception-discipline" in l
        ]
        assert len(hits) == 2, r.stdout
        assert any("bad_swallow" in h for h in hits)
        assert any("bad_tuple" in h for h in hits)
        assert not any("solver/kernel.py" in h for h in hits)


def test_seeded_exception_discipline_bare_except_in_loop():
    import tempfile
    from pathlib import Path

    with tempfile.TemporaryDirectory() as tmp:
        tmp_path = Path(tmp)
        _seed(tmp_path, "loop/runner.py", """\
            def swallow():
                try:
                    work()
                except:  # noqa: bare-except
                    pass
        """)
        r = _analyze_tree(tmp_path)
        assert r.returncode == 1
        hits = [
            l for l in r.stdout.splitlines() if "exception-discipline" in l
        ]
        assert len(hits) == 1 and "bare except" in hits[0], r.stdout


# --- jaxpr tier: dtype-promotion ------------------------------------------

_MANIFEST_PRELUDE = """\
    import jax
    import jax.numpy as jnp

    from k8s_spot_rescheduler_tpu.hot_programs import (
        HotProgram,
        packed_struct,
    )

"""


def test_jaxpr_seeded_float64_literal(tmp_path):
    """A planted float64 literal in a traced fn leaves no jaxpr residue
    under x64-off (JAX truncates it) — the pass must catch it from the
    trace-time warning."""
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(packed):
        scale = jnp.array(1.5, dtype=jnp.float64)
        return (jnp.asarray(packed.spot_free) * scale).sum()


    HOT_PROGRAMS = {
        "fix.f64": HotProgram(
            build=lambda s: (_solve, (packed_struct(s),)),
        ),
    }
    """)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "dtype-promotion" in r.stdout
    assert "64-bit" in r.stdout


def test_jaxpr_seeded_carry_mismatch(tmp_path):
    """A scan whose carry changes dtype mid-loop (the exact bug class of
    the ROADMAP-5 narrow-int carry refactor) fails at trace time; the
    pass owns the resulting finding."""
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(packed):
        def step(c, _):
            return c.astype(jnp.int32), None

        out, _ = jax.lax.scan(
            step, jnp.asarray(packed.spot_free), None, length=3
        )
        return out


    HOT_PROGRAMS = {
        "fix.carry": HotProgram(
            build=lambda s: (_solve, (packed_struct(s),)),
        ),
    }
    """)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "dtype-promotion" in r.stdout
    assert "carry" in r.stdout


def test_jaxpr_seeded_narrow_carry_promotion_mismatch(tmp_path):
    """The LANDED narrow-carry layout's one-keystroke regression: an
    int16 delta carry whose update forgets the ``.astype`` narrow-back
    silently promotes (int16 + weak int32 delta -> int32) and the scan
    carry types no longer match. The dtype pass must CLASSIFY it as a
    carry mismatch finding — never surface the raw trace TypeError."""
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(packed):
        used0 = jnp.zeros(packed.spot_free.shape, jnp.int16)

        def step(used, _):
            # BUG: delta computed in i32, narrow-back astype forgotten
            delta = jnp.ones(used.shape, jnp.int32)
            return used + delta, None

        out, _ = jax.lax.scan(step, used0, None, length=3)
        return out


    HOT_PROGRAMS = {
        "fix.narrow_carry": HotProgram(
            build=lambda s: (_solve, (packed_struct(s),)),
        ),
    }
    """)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "dtype-promotion" in r.stdout
    assert "carry" in r.stdout
    assert "Traceback" not in r.stdout  # classified, not a raw TypeError


# --- jaxpr tier: index-width ----------------------------------------------


def test_jaxpr_seeded_index_overflow_at_max_shapes(tmp_path):
    """An int32 flattened C*S offset overflows at the declared 20x max
    shapes (1M pods / 100k nodes: C*S = 2.6e9 > 2^31) — the gate that
    makes narrow-int packing safe to attempt."""
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(packed):
        C = packed.slot_req.shape[0]
        S = packed.spot_free.shape[0]
        lane = jnp.arange(C, dtype=jnp.int32)
        spot = jnp.arange(S, dtype=jnp.int32)
        flat = lane[:, None] * jnp.int32(S) + spot[None, :]
        return flat


    HOT_PROGRAMS = {
        "fix.overflow": HotProgram(
            build=lambda s: (_solve, (packed_struct(s),)),
        ),
    }
    """)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "index-width" in r.stdout
    assert "int32" in r.stdout and "wraparound" in r.stdout


def test_jaxpr_clean_index_math_stays_clean(tmp_path):
    """Negative fixture: per-axis int32 index math (the real kernels'
    shape) is in range at max shapes — no finding."""
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(packed):
        S = packed.spot_free.shape[0]
        fits = jnp.asarray(packed.spot_ok)
        first = jnp.argmax(fits)  # [0, S-1]: fits i32 at any S here
        onehot = jnp.arange(S) == first
        return onehot.sum()


    HOT_PROGRAMS = {
        "fix.clean": HotProgram(
            build=lambda s: (_solve, (packed_struct(s),)),
        ),
    }
    """)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "index-width" not in r.stdout


# --- jaxpr tier: transfer-audit -------------------------------------------


def test_jaxpr_seeded_donation_without_alias(tmp_path):
    """A donated arg with no aliasable output silently copies — the
    declaration must be proven, not trusted."""
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(a, b):
        return (a + b).sum()  # scalar out: 'a' cannot alias


    HOT_PROGRAMS = {
        "fix.donate": HotProgram(
            build=lambda s: (
                _solve,
                (
                    jax.ShapeDtypeStruct((64, 64), "float32"),
                    jax.ShapeDtypeStruct((64, 64), "float32"),
                ),
            ),
            donate_argnums=(0,),
        ),
    }
    """)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "transfer-audit" in r.stdout
    assert "NO output matches" in r.stdout


def test_jaxpr_seeded_const_capture_and_device_put(tmp_path):
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    _TABLE = jnp.zeros((512, 512), jnp.float32)  # 1 MiB by value


    def _solve(packed):
        x = jax.device_put(jnp.asarray(packed.spot_free))
        return x.sum() + _TABLE.sum()


    HOT_PROGRAMS = {
        "fix.transfer": HotProgram(
            build=lambda s: (_solve, (packed_struct(s),)),
        ),
    }
    """)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "device_put" in r.stdout
    assert "captures a" in r.stdout and "constant by value" in r.stdout


# --- jaxpr tier: memory-reconcile -----------------------------------------


def test_jaxpr_seeded_estimator_drift_names_component(tmp_path):
    """A drifted estimator fails memory-reconcile and the finding names
    WHICH component drifted (per-component reporting acceptance)."""
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(packed):
        def step(c, _):
            return c + 1.0, None

        out, _ = jax.lax.scan(
            step, jnp.asarray(packed.spot_free), None, length=4
        )
        return out


    def _estimator(shapes):
        # carries claimed 100x what the traced scan holds
        plane = shapes.S * shapes.R * 4
        return {
            "carries": 200 * plane,
            "slots": 1,
            "spot_static": 1,
            "outputs": 1,
            "temporaries": 1,
            "repair": 1,
        }


    HOT_PROGRAMS = {
        "fix.memdrift": HotProgram(
            build=lambda s: (_solve, (packed_struct(s),)),
            reconcile={"estimator": _estimator},
        ),
    }
    """)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "memory-reconcile" in r.stdout
    assert "'carries' drifted" in r.stdout
    # the per-component table rides the finding
    assert "estimator[" in r.stdout and "traced[" in r.stdout


# --- jaxpr tier: trace failures, suppression, baseline --------------------


def test_jaxpr_trace_failure_is_red(tmp_path):
    """A manifest entry that cannot trace is lost audit coverage — an
    error, never a silent skip."""
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(packed):
        raise RuntimeError("builder broke")


    HOT_PROGRAMS = {
        "fix.broken": HotProgram(
            build=lambda s: (_solve, (packed_struct(s),)),
        ),
    }
    """)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "trace-failure" in r.stdout


def test_jaxpr_noqa_suppresses_on_manifest_line(tmp_path):
    """Jaxpr findings anchor to the manifest entry line, so the shared
    typed-noqa grammar applies to them unchanged."""
    _, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(packed):
        C = packed.slot_req.shape[0]
        S = packed.spot_free.shape[0]
        lane = jnp.arange(C, dtype=jnp.int32)
        return lane[:, None] * jnp.int32(S)


    HOT_PROGRAMS = {
        "fix.overflow": HotProgram(  # noqa: index-width
            build=lambda s: (_solve, (packed_struct(s),)),
        ),
    }
    """)
    assert "index-width" not in r.stdout, r.stdout
    assert r.returncode == 0, r.stdout + r.stderr


def test_jaxpr_baseline_grandfathers(tmp_path):
    """Jaxpr-tier findings flow through the same baseline file."""
    manifest, r = seed_jaxpr_manifest(tmp_path, _MANIFEST_PRELUDE + """\

    def _solve(packed):
        C = packed.slot_req.shape[0]
        S = packed.spot_free.shape[0]
        lane = jnp.arange(C, dtype=jnp.int32)
        return lane[:, None] * jnp.int32(S)


    HOT_PROGRAMS = {
        "fix.overflow": HotProgram(
            build=lambda s: (_solve, (packed_struct(s),)),
        ),
    }
    """)
    assert r.returncode == 1
    r = _run(
        tmp_path, "--tier", "jaxpr", "--manifest", manifest,
        "--no-baseline", "--json",
    )
    found = json.loads(r.stdout)["findings"]
    assert found and all(f["tier"] == "jaxpr" for f in found)
    baseline = tmp_path / "baseline.txt"
    baseline.write_text("".join(
        f"{f['path']}::{f['code']}::{f['anchor']}  # grandfathered\n"
        for f in found
    ))
    r = _run(
        tmp_path, "--tier", "jaxpr", "--manifest", manifest,
        "--baseline", baseline,
    )
    assert r.returncode == 0, r.stdout
    assert "baselined" in r.stderr


# --- suppressions / noqa grammar ------------------------------------------


def test_bare_noqa_is_a_finding(tmp_path):
    _seed(tmp_path, "mod.py", "x = 1  # noqa\n")
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    assert "bare-noqa" in r.stdout


def test_typed_noqa_suppresses_only_named_code(tmp_path):
    _seed(tmp_path, "solver/suppressed.py", """\
        import jax

        @jax.jit
        def solve(x):
            return x.item()  # noqa: jax-host-sync
    """)
    r = _analyze_tree(tmp_path)
    assert "jax-host-sync" not in r.stdout
    _seed(tmp_path, "solver/wrong_code.py", """\
        import jax

        @jax.jit
        def solve2(x):
            return x.item()  # noqa: lock-discipline
    """)
    r = _analyze_tree(tmp_path)
    assert "jax-host-sync" in r.stdout  # wrong code suppresses nothing


def test_unknown_suppression_code_warns(tmp_path):
    _seed(tmp_path, "mod.py", "x = 1  # noqa: TOTALLY-MADE-UP\n")
    r = _analyze_tree(tmp_path)
    assert "unknown-suppression" in r.stdout
    assert r.returncode == 0  # warn tier
    assert _analyze_tree(tmp_path, "--strict").returncode == 1


def test_no_bare_noqa_in_tree():
    """Satellite guarantee: every suppression in the repo names a code."""
    r = _run("--tier", "ast")
    assert "bare-noqa" not in r.stdout


# --- baseline -------------------------------------------------------------


def test_baseline_grandfathers_and_goes_stale(tmp_path):
    _seed(tmp_path, "solver/bad.py", """\
        import jax

        @jax.jit
        def solve(x):
            return x.item()
    """)
    parity = tmp_path / "PARITY.md"
    parity.write_text("")
    # find the finding's key via --json, grandfather it, rerun
    r = _run(
        tmp_path, "--tier", "ast", "--no-baseline", "--parity", parity,
        "--json",
    )
    found = json.loads(r.stdout)["findings"]
    assert found, r.stdout
    key = (
        f"{found[0]['path']}::{found[0]['code']}::{found[0]['anchor']}"
    )
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(f"{key}  # grandfathered for the test\n")
    r = _run(
        tmp_path, "--tier", "ast", "--baseline", baseline,
        "--parity", parity,
    )
    assert r.returncode == 0, r.stdout
    assert "1 baselined" in r.stderr
    # paid debt: entry no longer matches -> stale-baseline warning
    (tmp_path / "solver" / "bad.py").write_text("x = 1\n")
    r = _run(
        tmp_path, "--tier", "ast", "--baseline", baseline,
        "--parity", parity,
    )
    assert "stale-baseline" in r.stdout
    assert r.returncode == 0  # warn tier


# --- --json schema --------------------------------------------------------


def test_json_output_schema(tmp_path):
    _seed(tmp_path, "solver/bad.py", """\
        import jax

        @jax.jit
        def solve(x):
            return x.item()
    """)
    r = _analyze_tree(tmp_path, "--json")
    out = json.loads(r.stdout)
    assert out["version"] == 1
    assert out["tier"] == "ast"
    assert set(out["counts"]) == {"error", "warn", "baselined"}
    f = out["findings"][0]
    assert set(f) == {
        "path", "line", "code", "severity", "message", "anchor", "tier",
    }
    assert f["code"] == "jax-host-sync"
    assert f["severity"] == "error"
    assert f["tier"] == "ast"


def test_json_tier_runtimes(tmp_path):
    """--json carries a tier_runtimes_ms block: one entry per tier
    that actually ran (the trajectory the smoke line samples)."""
    _seed(tmp_path, "solver/ok.py", "x = 1\n")
    r = _analyze_tree(tmp_path, "--json")
    out = json.loads(r.stdout)
    rt = out["tier_runtimes_ms"]
    assert set(rt) == {"ast"}
    assert rt["ast"] >= 0
    r = _analyze_tree(tmp_path, "--json", tier="proto")
    rt = json.loads(r.stdout)["tier_runtimes_ms"]
    assert set(rt) == {"proto"}


# --- flight-contract ------------------------------------------------------


def test_seeded_flight_contract_all_three_directions(tmp_path):
    """One fixture, all three drift directions red at once: a kind
    emitted but undeclared, a kind declared but never emitted, and a
    declared+emitted kind missing from the operator doc — while the
    fully-wired kind stays clean."""
    _seed(tmp_path, "pkg/loop/flight.py", """\
        DEGRADATION_KINDS = frozenset({
            "good",
            "dead",
        })
        CONTEXT_KINDS = frozenset({
            "undoc",
        })

        def note_event(kind, **attrs):
            pass
    """)
    _seed(tmp_path, "pkg/loop/ctrl.py", """\
        from pkg.loop import flight

        def tick():
            flight.note_event("good", phase="x")
            flight.note_event("rogue", phase="y")
            flight.note_event("undoc")
    """)
    (tmp_path / "OBSERVABILITY.md").write_text(
        "| `good` | yes | ... |\n| `dead` | yes | ... |\n"
    )
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    hits = [l for l in r.stdout.splitlines() if "flight-contract" in l]
    assert len(hits) == 3, r.stdout
    assert any("'rogue'" in h and "absent from" in h for h in hits)
    assert any("'dead'" in h and "no call site ever emits" in h
               for h in hits)
    assert any("'undoc'" in h and "not documented" in h for h in hits)


def test_flight_contract_funnel_kinds_count_as_emissions(tmp_path):
    """A funnel (a ``kind``-parameter function forwarding into
    note_event) emits its callers' literal ``kind=`` kwargs AND its own
    literal default — the server's ``_note_shed`` shape stays green."""
    _seed(tmp_path, "pkg/loop/flight.py", """\
        DEGRADATION_KINDS = frozenset({
            "service-shed",
            "resync-shed",
        })

        def note_event(kind, **attrs):
            pass
    """)
    _seed(tmp_path, "pkg/service/server.py", """\
        from pkg.loop import flight

        class Handler:
            def _note_shed(self, reason, kind="service-shed"):
                flight.note_event(kind, reason=reason)

            def reject(self):
                self._note_shed("queue-timeout")

            def storm(self):
                self._note_shed("resync-storm", kind="resync-shed")
    """)
    (tmp_path / "OBSERVABILITY.md").write_text(
        "`service-shed` and `resync-shed`\n"
    )
    r = _analyze_tree(tmp_path)
    assert "flight-contract" not in r.stdout, r.stdout
    assert r.returncode == 0


def test_flight_contract_inert_without_flight_module(tmp_path):
    """Fixture trees without a flight vocabulary are not forced to
    carry one."""
    _seed(tmp_path, "pkg/loop/ctrl.py", """\
        from pkg.loop import flight

        def tick():
            flight.note_event("anything")
    """)
    r = _analyze_tree(tmp_path)
    assert "flight-contract" not in r.stdout


# --- lock-graph -----------------------------------------------------------


def test_seeded_lock_graph_cycle(tmp_path):
    """The planted two-lock ordering cycle: one path takes A then B,
    another takes B then A through a helper call — the finding names
    the full cycle path."""
    _seed(tmp_path, "state/cycle.py", """\
        import threading

        class Store:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def forward(self):
                with self._a:
                    with self._b:
                        pass

            def backward(self):
                with self._b:
                    self._grab_a()

            def _grab_a(self):
                with self._a:
                    pass
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    hits = [l for l in r.stdout.splitlines() if "lock-graph" in l]
    assert any("lock acquisition cycle" in h for h in hits), r.stdout
    cycle = next(h for h in hits if "lock acquisition cycle" in h)
    assert "_a" in cycle and "_b" in cycle and "->" in cycle


def test_lock_graph_consistent_order_is_clean(tmp_path):
    """Negative: the same two locks always taken in the same order —
    no cycle, no finding."""
    _seed(tmp_path, "state/ordered.py", """\
        import threading

        class Store:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    self._inner()

            def _inner(self):
                with self._b:
                    pass
    """)
    r = _analyze_tree(tmp_path)
    assert "lock-graph" not in r.stdout, r.stdout


def test_lock_graph_self_deadlock_and_rlock_exempt(tmp_path):
    """Re-acquiring a plain Lock down the call graph is a certain
    self-deadlock (error); the same shape on an RLock is the reentrant
    contract working as designed (clean)."""
    _seed(tmp_path, "state/reent.py", """\
        import threading

        class Plain:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass

        class Reent:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    hits = [l for l in r.stdout.splitlines() if "lock-graph" in l]
    assert len(hits) == 1, r.stdout
    assert "Plain" in hits[0] and "self-deadlock" in hits[0]


def test_lock_graph_held_across_blocking_warns(tmp_path):
    """Holding a lock across a known-blocking call is a warn (latency
    hazard, not a proven deadlock): rc 0 without --strict."""
    _seed(tmp_path, "state/slow.py", """\
        import threading
        import time

        class Store:
            def __init__(self):
                self._lock = threading.Lock()

            def nap(self):
                with self._lock:
                    time.sleep(1.0)
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 0, r.stdout
    hits = [l for l in r.stdout.splitlines() if "lock-graph" in l]
    assert len(hits) == 1 and "[warn]" in hits[0], r.stdout
    assert "blocking" in hits[0]


def test_lock_graph_condition_wait_holding_other_lock(tmp_path):
    """cond.wait() releases ONLY the condition's own lock — waiting
    while holding a second lock starves every path that needs it."""
    _seed(tmp_path, "state/cond.py", """\
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition()

            def bad_wait(self):
                with self._lock:
                    with self._cond:
                        self._cond.wait()

            def ok_wait(self):
                with self._cond:
                    self._cond.wait()
    """)
    r = _analyze_tree(tmp_path)
    assert r.returncode == 1
    hits = [l for l in r.stdout.splitlines() if "lock-graph" in l]
    errors = [h for h in hits if "[warn]" not in h]
    assert len(errors) == 1, r.stdout
    assert "bad_wait" in errors[0] and "wait" in errors[0]


# --- proto tier: protocol-contract ----------------------------------------

# A minimal contract-clean protocol model + wire module pair. The
# fixture tree carries no agent.py/server.py, so those contract
# sections stay inert — the wire/site checks are what these tests
# exercise. Entries are plain dicts (the pass reads dataclasses and
# dicts alike); sites of None are unbound by design.
_PROTO_MODEL_FIXTURE = """\
    VERSIONS = (1, 2)
    WIRE_VERSION = 2
    KINDS = {
        "KIND_PING": {
            "value": 1,
            "min_version": 1,
            "site": "service/wire.py::encode_ping",
        },
    }
    SHED_REASONS = {}
    BREAKER_STATES = ("closed", "open")
    BREAKER_TABLE = (
        {"src": "closed", "dst": "open", "event": "trip", "site": None},
        {"src": "open", "dst": "closed", "event": "heal", "site": None},
    )
    BREAKER_CONSTANTS = {}
    ENDPOINT_FIELDS = ("url",)
    ADMISSION_COUNTERS = ()
    ADMISSION_LOCK_ATTR = "_lock"
    ADMISSION_CAP_ATTR = "_cap"
    ADMISSION_SITES = {}
    LADDER_TABLE = ()
"""

_PROTO_WIRE_FIXTURE = """\
    WIRE_VERSION = 2
    SUPPORTED_VERSIONS = (1, 2)
    KIND_PING = 1

    def encode_ping(payload):
        return payload
"""


def test_proto_contract_clean_fixture(tmp_path):
    """Negative: a model whose tables mirror the live wire surface and
    whose sites all resolve is green."""
    _seed(tmp_path, "service/protocol_model.py", _PROTO_MODEL_FIXTURE)
    _seed(tmp_path, "service/wire.py", _PROTO_WIRE_FIXTURE)
    r = _analyze_tree(tmp_path, "--pass", "protocol-contract",
                      tier="proto")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "protocol-contract" not in r.stdout


def test_proto_contract_live_kind_missing_from_model(tmp_path):
    """Adding a wire frame kind without teaching the model turns the
    gate red at the live constant: the checker would be blind to it."""
    _seed(tmp_path, "service/protocol_model.py", _PROTO_MODEL_FIXTURE)
    _seed(tmp_path, "service/wire.py",
          _PROTO_WIRE_FIXTURE + "    KIND_ROGUE = 7\n")
    r = _analyze_tree(tmp_path, "--pass", "protocol-contract",
                      tier="proto")
    assert r.returncode == 1
    assert "KIND_ROGUE" in r.stdout
    assert "absent from the protocol model" in r.stdout
    assert "service/wire.py" in r.stdout  # anchored at the LIVE side


def test_proto_contract_model_site_must_exist(tmp_path):
    """A model site string naming a function that does not exist turns
    the gate red at the model: events must describe live code."""
    _seed(tmp_path, "service/protocol_model.py",
          _PROTO_MODEL_FIXTURE.replace("encode_ping", "encode_gone"))
    _seed(tmp_path, "service/wire.py", _PROTO_WIRE_FIXTURE)
    r = _analyze_tree(tmp_path, "--pass", "protocol-contract",
                      tier="proto")
    assert r.returncode == 1
    assert "maps to no live function" in r.stdout
    assert "service/protocol_model.py" in r.stdout


def test_proto_contract_value_and_version_drift(tmp_path):
    """A renumbered frame constant and a bumped WIRE_VERSION each turn
    the gate red with both values named."""
    _seed(tmp_path, "service/protocol_model.py", _PROTO_MODEL_FIXTURE)
    _seed(tmp_path, "service/wire.py",
          _PROTO_WIRE_FIXTURE.replace("KIND_PING = 1", "KIND_PING = 9")
          .replace("WIRE_VERSION = 2", "WIRE_VERSION = 3"))
    r = _analyze_tree(tmp_path, "--pass", "protocol-contract",
                      tier="proto")
    assert r.returncode == 1
    assert "KIND_PING is 9 on the wire but 1" in r.stdout
    assert "WIRE_VERSION is 3 live but 2" in r.stdout


def test_proto_tier_inert_without_model(tmp_path):
    """A tree that declares no protocol model gets no proto findings —
    the tier gates trees that opted in (the real package always has
    service/protocol_model.py in the walk)."""
    _seed(tmp_path, "service/wire.py", _PROTO_WIRE_FIXTURE)
    r = _analyze_tree(tmp_path, tier="proto")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "protocol" not in r.stdout


# --- proto tier: protocol-model -------------------------------------------

# Toy systems for --proto-model: tiny hand-built automata that exercise
# the checker's verdicts without the real model's state-space cost.
_TOY_CLEAN_MODEL = """\
    class _Toy:
        name = "toy"

        def initial(self):
            return 0

        def successors(self, state):
            if state == 0:
                yield ("step", None, 1)

        def check(self, state, label, info, nxt):
            return ()

        def is_goal(self, state):
            return state == 1


    def build_systems():
        return [_Toy()]
"""

# state 1 self-loops forever and is_goal only at 0: every path out of
# the initial state enters a live cycle that can never drain
_TOY_UNDRAINABLE_MODEL = """\
    class _Stuck:
        name = "stuck-storm"

        def initial(self):
            return 0

        def successors(self, state):
            if state == 0:
                yield ("enter-storm", None, 1)
            else:
                yield ("spin", None, 1)

        def check(self, state, label, info, nxt):
            return ()

        def is_goal(self, state):
            return state == 0


    def build_systems():
        return [_Stuck()]
"""


def test_proto_model_toy_clean(tmp_path):
    """Negative: a reachable-goal toy automaton passes the checker."""
    model = _seed(tmp_path, "toy_model.py", _TOY_CLEAN_MODEL)
    r = _analyze_tree(tmp_path, "--proto-model", model, tier="proto")
    assert r.returncode == 0, r.stdout + r.stderr


def test_proto_model_planted_unreachable_drain_is_red(tmp_path):
    """The planted unreachable-storm-drain model turns the run red: a
    state from which no path reaches the drained goal is a liveness
    violation carrying the event trail."""
    model = _seed(tmp_path, "stuck_model.py", _TOY_UNDRAINABLE_MODEL)
    r = _analyze_tree(tmp_path, "--proto-model", model, tier="proto")
    assert r.returncode == 1
    assert "liveness violation" in r.stdout
    assert "cannot drain" in r.stdout
    assert "enter-storm" in r.stdout  # the trail names the bad path


def test_proto_model_safety_violation_carries_trail(tmp_path):
    """A transition the invariant rejects is a safety finding whose
    trail replays the exact event sequence from the initial state."""
    _seed(tmp_path, "bad_model.py", """\
        class _Bad:
            name = "double-pack"

            def initial(self):
                return 0

            def successors(self, state):
                if state < 2:
                    yield ("full-pack", None, state + 1)

            def check(self, state, label, info, nxt):
                if nxt == 2:
                    return ("second full pack in one epoch",)
                return ()

            def is_goal(self, state):
                return state >= 1


        def build_systems():
            return [_Bad()]
    """)
    r = _analyze_tree(tmp_path, "--proto-model",
                      tmp_path / "bad_model.py", tier="proto")
    assert r.returncode == 1
    assert "safety violation" in r.stdout
    assert "second full pack" in r.stdout
    assert "full-pack -> full-pack" in r.stdout


def test_proto_model_broken_model_is_red_not_silent(tmp_path):
    """A model that cannot load, and one whose build_systems returns
    nothing, are each errors — lost verification coverage must never
    read as a pass."""
    broken = _seed(tmp_path, "broken.py", "raise RuntimeError('boom')\n")
    r = _analyze_tree(tmp_path, "--proto-model", broken, tier="proto")
    assert r.returncode == 1
    assert "failed to load" in r.stdout
    empty = _seed(tmp_path, "empty.py", "def build_systems():\n"
                  "    return []\n")
    r = _analyze_tree(tmp_path, "--proto-model", empty, tier="proto")
    assert r.returncode == 1
    assert "vacuously" in r.stdout
