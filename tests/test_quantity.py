"""k8s quantity parsing."""

import pytest

from k8s_spot_rescheduler_tpu.utils.quantity import (
    parse_cpu_millis,
    parse_memory_bytes,
    parse_quantity,
)


def test_cpu_millis():
    assert parse_cpu_millis("500m") == 500
    assert parse_cpu_millis("2") == 2000
    assert parse_cpu_millis("0.1") == 100
    assert parse_cpu_millis("1500m") == 1500
    assert parse_cpu_millis(2) == 2000


def test_cpu_sub_milli_rounds_up():
    assert parse_cpu_millis("1n") == 1  # like k8s MilliValue ceil


def test_memory_bytes():
    assert parse_memory_bytes("2Gi") == 2 * 1024**3
    assert parse_memory_bytes("512Mi") == 512 * 1024**2
    assert parse_memory_bytes("1000") == 1000
    assert parse_memory_bytes("1k") == 1000
    assert parse_memory_bytes("1M") == 10**6


def test_exponent_form():
    assert parse_quantity("1e3") == 1000
    assert parse_quantity("1E3") == 1000


def test_bad_quantity():
    with pytest.raises(ValueError):
        parse_quantity("")
    with pytest.raises(ValueError):
        parse_quantity("abc")
