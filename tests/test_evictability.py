"""Evictability filter tests (reference rescheduler.go:231-256 semantics)."""

from k8s_spot_rescheduler_tpu.models.cluster import (
    MIRROR_POD_ANNOTATION,
    OwnerRef,
    PDBSpec,
)
from k8s_spot_rescheduler_tpu.models.evictability import get_pods_for_deletion
from tests.fixtures import make_pod


def test_replicated_pods_pass():
    pods = [make_pod("a", 100), make_pod("b", 100)]
    out, blocking = get_pods_for_deletion(pods, [])
    assert [p.name for p in out] == ["a", "b"]
    assert blocking is None


def test_daemonset_pods_skipped():
    ds = make_pod("ds", 100)
    ds.owner_refs = [OwnerRef("DaemonSet", "ds-owner")]
    out, blocking = get_pods_for_deletion([ds, make_pod("a", 100)], [])
    assert [p.name for p in out] == ["a"]
    assert blocking is None


def test_non_controller_daemonset_ref_not_skipped():
    # reference rescheduler.go:245 checks *owner.Controller
    p = make_pod("p", 100, replicated=False)
    p.owner_refs = [OwnerRef("DaemonSet", "x", controller=False)]
    out, blocking = get_pods_for_deletion([p], [])
    assert blocking is not None  # falls through to non-replicated check


def test_mirror_pods_skipped():
    m = make_pod("m", 100, replicated=False)
    m.annotations = {MIRROR_POD_ANNOTATION: "true"}
    out, blocking = get_pods_for_deletion([m], [])
    assert out == [] and blocking is None


def test_finished_pods_skipped():
    p = make_pod("done", 100)
    p.phase = "Succeeded"
    out, blocking = get_pods_for_deletion([p], [])
    assert out == [] and blocking is None


def test_non_replicated_blocks_unless_flag():
    bare = make_pod("bare", 100, replicated=False)
    out, blocking = get_pods_for_deletion([bare], [])
    assert blocking is not None and blocking.pod.name == "bare"

    out, blocking = get_pods_for_deletion([bare], [], delete_non_replicated=True)
    assert [p.name for p in out] == ["bare"] and blocking is None


def test_pdb_blocks_when_budget_exhausted():
    pod = make_pod("web", 100)
    pod.labels = {"app": "web"}
    pdb = PDBSpec("web-pdb", match_labels={"app": "web"}, disruptions_allowed=0)
    out, blocking = get_pods_for_deletion([pod], [pdb])
    assert blocking is not None and "budget" in blocking.reason

    pdb_ok = PDBSpec("web-pdb", match_labels={"app": "web"}, disruptions_allowed=1)
    out, blocking = get_pods_for_deletion([pod], [pdb_ok])
    assert [p.name for p in out] == ["web"] and blocking is None


def test_pdb_in_other_namespace_ignored():
    pod = make_pod("web", 100, namespace="prod")
    pod.labels = {"app": "web"}
    pdb = PDBSpec("web-pdb", namespace="dev", match_labels={"app": "web"})
    out, blocking = get_pods_for_deletion([pod], [pdb])
    assert blocking is None


def test_hard_topology_spread_decode():
    """whenUnsatisfiable=DoNotSchedule spread constraints are predicates
    the reference's CheckPredicates enforces (PodTopologySpread). Since
    round 4 the CANONICAL shape is modeled (spread_constraints +
    SpreadBit verdicts, tests/test_spread.py); non-canonical hard shapes
    must still collapse to unplaceable, never to unconstrained."""
    from k8s_spot_rescheduler_tpu.io.kube import decode_pod

    def pod(spread):
        return decode_pod({
            "metadata": {"name": "p"},
            "spec": {"nodeName": "n", "containers": [],
                     "topologySpreadConstraints": spread},
            "status": {"phase": "Running"},
        })

    hard = {"maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "x"}}}
    soft = dict(hard, whenUnsatisfiable="ScheduleAnyway")
    default = {k: v for k, v in hard.items() if k != "whenUnsatisfiable"}
    beyond = dict(hard, minDomains=2)  # counting modifier: not modeled

    assert not pod([hard]).unmodeled_constraints  # canonical: modeled
    assert pod([hard]).spread_constraints
    assert not pod([default]).unmodeled_constraints  # k8s default is hard
    assert pod([default]).spread_constraints
    assert not pod([soft]).unmodeled_constraints
    assert not pod([soft]).spread_constraints  # soft: dropped
    assert not pod([]).unmodeled_constraints
    assert pod([beyond]).unmodeled_constraints
    assert not pod([beyond]).spread_constraints
    assert pod("garbage").unmodeled_constraints  # malformed: conservative
