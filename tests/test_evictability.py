"""Evictability filter tests (reference rescheduler.go:231-256 semantics)."""

from k8s_spot_rescheduler_tpu.models.cluster import (
    MIRROR_POD_ANNOTATION,
    OwnerRef,
    PDBSpec,
)
from k8s_spot_rescheduler_tpu.models.evictability import get_pods_for_deletion
from tests.fixtures import make_pod


def test_replicated_pods_pass():
    pods = [make_pod("a", 100), make_pod("b", 100)]
    out, blocking = get_pods_for_deletion(pods, [])
    assert [p.name for p in out] == ["a", "b"]
    assert blocking is None


def test_daemonset_pods_skipped():
    ds = make_pod("ds", 100)
    ds.owner_refs = [OwnerRef("DaemonSet", "ds-owner")]
    out, blocking = get_pods_for_deletion([ds, make_pod("a", 100)], [])
    assert [p.name for p in out] == ["a"]
    assert blocking is None


def test_non_controller_daemonset_ref_not_skipped():
    # reference rescheduler.go:245 checks *owner.Controller
    p = make_pod("p", 100, replicated=False)
    p.owner_refs = [OwnerRef("DaemonSet", "x", controller=False)]
    out, blocking = get_pods_for_deletion([p], [])
    assert blocking is not None  # falls through to non-replicated check


def test_mirror_pods_skipped():
    m = make_pod("m", 100, replicated=False)
    m.annotations = {MIRROR_POD_ANNOTATION: "true"}
    out, blocking = get_pods_for_deletion([m], [])
    assert out == [] and blocking is None


def test_finished_pods_skipped():
    p = make_pod("done", 100)
    p.phase = "Succeeded"
    out, blocking = get_pods_for_deletion([p], [])
    assert out == [] and blocking is None


def test_non_replicated_blocks_unless_flag():
    bare = make_pod("bare", 100, replicated=False)
    out, blocking = get_pods_for_deletion([bare], [])
    assert blocking is not None and blocking.pod.name == "bare"

    out, blocking = get_pods_for_deletion([bare], [], delete_non_replicated=True)
    assert [p.name for p in out] == ["bare"] and blocking is None


def test_pdb_blocks_when_budget_exhausted():
    pod = make_pod("web", 100)
    pod.labels = {"app": "web"}
    pdb = PDBSpec("web-pdb", match_labels={"app": "web"}, disruptions_allowed=0)
    out, blocking = get_pods_for_deletion([pod], [pdb])
    assert blocking is not None and "budget" in blocking.reason

    pdb_ok = PDBSpec("web-pdb", match_labels={"app": "web"}, disruptions_allowed=1)
    out, blocking = get_pods_for_deletion([pod], [pdb_ok])
    assert [p.name for p in out] == ["web"] and blocking is None


def test_pdb_in_other_namespace_ignored():
    pod = make_pod("web", 100, namespace="prod")
    pod.labels = {"app": "web"}
    pdb = PDBSpec("web-pdb", namespace="dev", match_labels={"app": "web"})
    out, blocking = get_pods_for_deletion([pod], [pdb])
    assert blocking is None


def test_hard_topology_spread_decode():
    """whenUnsatisfiable=DoNotSchedule spread constraints are predicates
    the reference's CheckPredicates enforces (PodTopologySpread). Since
    round 4 the CANONICAL shape is modeled (spread_constraints +
    SpreadBit verdicts, tests/test_spread.py); non-canonical hard shapes
    must still collapse to unplaceable, never to unconstrained."""
    from k8s_spot_rescheduler_tpu.io.kube import decode_pod

    def pod(spread):
        return decode_pod({
            "metadata": {"name": "p"},
            "spec": {"nodeName": "n", "containers": [],
                     "topologySpreadConstraints": spread},
            "status": {"phase": "Running"},
        })

    hard = {"maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "x"}}}
    soft = dict(hard, whenUnsatisfiable="ScheduleAnyway")
    default = {k: v for k, v in hard.items() if k != "whenUnsatisfiable"}
    beyond = dict(hard, minDomains=2)  # counting modifier: not modeled

    assert not pod([hard]).unmodeled_constraints  # canonical: modeled
    assert pod([hard]).spread_constraints
    assert not pod([default]).unmodeled_constraints  # k8s default is hard
    assert pod([default]).spread_constraints
    assert not pod([soft]).unmodeled_constraints
    assert not pod([soft]).spread_constraints  # soft: dropped
    assert not pod([]).unmodeled_constraints
    assert pod([beyond]).unmodeled_constraints
    assert not pod([beyond]).spread_constraints
    assert pod("garbage").unmodeled_constraints  # malformed: conservative


def test_pdb_selector_operators_widened():
    """Round 5: PDB selectors parse the full matchExpressions operator
    surface; shapes beyond it select EVERY pod in the namespace (the
    conservative direction — an unparseable PDB blocks, never
    under-protects)."""
    from k8s_spot_rescheduler_tpu.io.kube import decode_pdb
    from k8s_spot_rescheduler_tpu.models.cluster import PodSpec

    def pdb_obj(selector):
        return {
            "metadata": {"name": "pdb", "namespace": "shop"},
            "spec": {"selector": selector},
            "status": {"disruptionsAllowed": 0},
        }

    pdb = decode_pdb(pdb_obj({"matchExpressions": [
        {"key": "app", "operator": "In", "values": ["web", "api"]},
        {"key": "canary", "operator": "DoesNotExist"},
    ]}))
    web = PodSpec(name="w", namespace="shop", labels={"app": "web"})
    canary = PodSpec(name="c", namespace="shop",
                     labels={"app": "web", "canary": "1"})
    other = PodSpec(name="o", namespace="shop", labels={"app": "db"})
    foreign = PodSpec(name="f", namespace="other", labels={"app": "web"})
    assert pdb.selects(web)
    assert not pdb.selects(canary)
    assert not pdb.selects(other)
    assert not pdb.selects(foreign)

    # beyond the surface (unknown operator): select-all in namespace
    weird = decode_pdb(pdb_obj({"matchExpressions": [
        {"key": "app", "operator": "Gt", "values": ["1"]}]}))
    assert weird.match_labels == ()
    assert weird.selects(other) and weird.selects(web)
    assert not weird.selects(foreign)

    # empty selector: k8s PDB semantics select every pod in namespace
    empty = decode_pdb(pdb_obj({}))
    assert empty.selects(other)

    # NIL selector (field absent): policy/v1 selects ZERO pods
    nil = decode_pdb({
        "metadata": {"name": "pdb", "namespace": "shop"},
        "spec": {},
        "status": {"disruptionsAllowed": 0},
    })
    assert not nil.selects(other) and not nil.selects(web)


def test_pdb_expression_selector_blocks_drain_end_to_end():
    """An exhausted PDB whose selector is pure matchExpressions must
    block its node's drain on BOTH pack paths (the round-4 model
    ignored matchExpressions entirely — the under-protecting
    direction)."""
    import numpy as np

    from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
    from k8s_spot_rescheduler_tpu.io.kube import decode_pdb
    from k8s_spot_rescheduler_tpu.models.cluster import build_node_map
    from k8s_spot_rescheduler_tpu.models.tensors import pack_cluster
    from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
    from tests.fixtures import (
        ON_DEMAND_LABEL,
        ON_DEMAND_LABELS,
        SPOT_LABEL,
        SPOT_LABELS,
        make_node,
        make_pod,
    )

    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_pod(make_pod("mover", 300, "od-1", labels={"tier": "be"}))
    fc.pdbs.append(decode_pdb({
        "metadata": {"name": "be-pdb", "namespace": "default"},
        "spec": {"selector": {"matchExpressions": [
            {"key": "tier", "operator": "Exists"}]}},
        "status": {"disruptionsAllowed": 0},
    }))
    nodes = fc.list_ready_nodes()
    node_map = build_node_map(
        nodes,
        {n.name: fc.list_pods_on_node(n.name) for n in nodes},
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    packed, meta = pack_cluster(node_map, fc.pdbs,
                                resources=("cpu", "memory"))
    assert not packed.cand_valid[:1].any()  # blocked, not drainable
    assert meta.blocking_pods()[0].pod.name == "mover"
    store = fc.columnar_store(
        ("cpu", "memory"),
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    col, cmeta = store.pack(fc.pdbs)
    for field in packed._fields:
        np.testing.assert_array_equal(
            getattr(packed, field), getattr(col, field), err_msg=field
        )
    assert cmeta.blocking_pods()[0].pod.name == "mover"
