"""The tick tracer + flight recorder (utils/tracing.py, loop/flight.py).

Covers the satellite fix (phase() records its duration — with an
error attribute — even when the body raises), the span-tree mechanics
the controller/planner/agent thread their spans through, the wire
round trip of trace IDs and server spans (one tree, one ID — the
end-to-end acceptance), the flight ring's capture/dump/redaction
behavior, and the gated /debug endpoints.
"""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.loop import flight
from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.utils import tracing


@pytest.fixture(autouse=True)
def _clean_recorder():
    flight.RECORDER.reset()
    flight.RECORDER.configure(ring_size=64, dump_dir="")
    yield
    flight.RECORDER.reset()
    flight.RECORDER.configure(ring_size=64, dump_dir="")


def _phase_count(phase_name: str) -> float:
    """Observation count of the tick_phase_duration histogram for one
    phase label, via the public collect() API."""
    for sample in metrics.tick_phase_duration.collect()[0].samples:
        if (
            sample.name.endswith("_count")
            and sample.labels.get("phase") == phase_name
        ):
            return sample.value
    return 0.0


# --- phase(): the satellite fix -------------------------------------------


def test_phase_records_duration_profiler_off():
    """No profiler dir configured (the default): phase() still times
    into the histogram and spans onto the ambient trace."""
    tracing.disable_profiler()
    before = _phase_count("observe")
    with tracing.tick_trace() as trace:
        with tracing.phase("observe"):
            pass
    assert _phase_count("observe") == before + 1
    (span,) = trace.find("observe")
    assert span.dur_ms >= 0.0


def test_phase_records_duration_on_exception():
    """The satellite: a body that raises must still observe the phase
    duration, and the span carries error=true."""
    before = _phase_count("actuate")
    with tracing.tick_trace() as trace:
        with pytest.raises(ValueError):
            with tracing.phase("actuate"):
                raise ValueError("boom")
    assert _phase_count("actuate") == before + 1  # was skipped pre-fix
    (span,) = trace.find("actuate")
    assert span.attrs.get("error") is True


def test_phase_profiler_path_is_best_effort(tmp_path):
    """With a trace dir configured the jax.profiler annotation wraps
    the phase; metrics and spans behave identically."""
    tracing.enable_profiler(str(tmp_path))
    try:
        before = _phase_count("observe")
        with tracing.tick_trace() as trace:
            with tracing.phase("observe"):
                pass
        assert _phase_count("observe") == before + 1
        assert trace.find("observe")
    finally:
        tracing.disable_profiler()


def test_phase_without_trace_is_metric_only():
    before = _phase_count("plan")
    with tracing.phase("plan"):
        pass
    assert _phase_count("plan") == before + 1
    assert tracing.current_trace() is None


# --- Trace mechanics ------------------------------------------------------


def test_spans_nest_and_attrs_survive():
    with tracing.tick_trace() as trace:
        with tracing.phase("observe"):
            with tracing.span("kube.get", path="/api/v1/pods") as sp:
                assert sp is not None
                sp.attrs["attempts"] = 2
    d = trace.to_dict()
    assert d["trace_id"] == trace.trace_id and len(d["trace_id"]) == 16
    (observe,) = d["spans"]
    assert observe["name"] == "observe"
    (kube,) = observe["spans"]
    assert kube["name"] == "kube.get"
    assert kube["attrs"] == {"path": "/api/v1/pods", "attempts": 2}


def test_span_outside_trace_is_free():
    with tracing.span("kube.get", path="/x") as sp:
        assert sp is None  # no ambient trace: nothing recorded


def test_span_cap_counts_drops():
    trace = tracing.Trace()
    for _ in range(tracing.MAX_SPANS + 7):
        with trace.span("kube.get"):
            pass
    d = trace.to_dict()
    assert len(d["spans"]) == tracing.MAX_SPANS
    assert d["dropped_spans"] == 7


def test_graft_server_spans():
    trace = tracing.Trace()
    parent = tracing.make_span("wire.request", 0.0, 70.0)
    children = (
        tracing.make_span("service.queue-wait", 0.0, 3.0),
        tracing.make_span("service.solve", 3.0, 1.2),
    )
    trace.graft(parent, children, attrs={"batch_tenants": 4})
    (wire_sp,) = trace.find("wire.request")
    assert [c.name for c in wire_sp.children] == [
        "service.queue-wait", "service.solve",
    ]
    assert wire_sp.attrs["batch_tenants"] == 4


def test_span_overhead_supports_always_on():
    """The ≤2% steady-tick claim (docs/OBSERVABILITY.md): one full span
    enter/exit must cost well under 50 µs — a real tick carries ~10-20
    spans against a ~341 ms steady tick, so this bound leaves two
    orders of magnitude of headroom."""
    trace = tracing.Trace()
    n = 1000
    t0 = time.perf_counter()
    for _ in range(n):
        with trace.span("kube.get"):
            pass
    per_span_us = (time.perf_counter() - t0) / n * 1e6
    assert per_span_us < 50.0, f"span enter/exit costs {per_span_us:.1f} µs"


# --- flight recorder ------------------------------------------------------


def test_flight_ring_and_counts():
    flight.note_event(
        "planner-fallback", cause="RuntimeError: x", trace_id="a" * 16
    )
    trace = tracing.Trace()
    flight.record_tick(trace.to_dict())
    assert flight.RECORDER.counts() == {"planner-fallback": 1}
    last = flight.last_tick()
    assert last["trace"]["trace_id"] == trace.trace_id
    (ev,) = last["events"]
    assert ev["kind"] == "planner-fallback"
    assert ev["cause"] == "RuntimeError: x"
    assert ev["trace_id"] == "a" * 16


def test_flight_ring_is_bounded():
    flight.RECORDER.configure(ring_size=4)
    try:
        for i in range(10):
            t = tracing.Trace()
            t.set_attr("i", i)
            flight.record_tick(t.to_dict())
        snap = flight.RECORDER.snapshot()
        assert snap["ring_ticks"] == 4
        assert flight.last_tick()["trace"]["attrs"]["i"] == 9
    finally:
        flight.RECORDER.configure(ring_size=64)


def test_clean_ticks_never_dump(tmp_path):
    """The acceptance's negative half: with a dump dir configured,
    clean ticks and non-degradation events write nothing."""
    flight.RECORDER.configure(dump_dir=str(tmp_path))
    for _ in range(5):
        flight.record_tick(tracing.Trace().to_dict())
    flight.note_event("orphan-taint-recovered", cause="sweep", node="od-1")
    assert list(tmp_path.iterdir()) == []
    assert flight.RECORDER.dump_count() == 0


def test_degradation_edge_dumps_redacted(tmp_path):
    flight.RECORDER.configure(dump_dir=str(tmp_path))
    with tracing.tick_trace() as trace:
        with tracing.span("kube.get", path="/api/v1/namespaces/x/pods"):
            pass
    flight.record_tick(trace.to_dict())
    flight.note_event(
        "planner-fallback", cause="RuntimeError: boom",
        trace_id=trace.trace_id, solver="jax", node="od-secret-1",
    )
    files = list(tmp_path.iterdir())
    assert len(files) == 1
    payload = json.loads(files[0].read_text())
    assert payload["reason"] == "planner-fallback"
    (ev,) = payload["events"]
    # cause survives (it IS the postmortem); identifier attrs are hashed,
    # structural attrs pass through
    assert ev["cause"] == "RuntimeError: boom"
    assert ev["attrs"]["solver"] == "jax"
    assert ev["attrs"]["node"].startswith("sha1:")
    (entry,) = payload["ring"]
    (kube,) = entry["trace"]["spans"]
    assert kube["attrs"]["path"].startswith("sha1:")
    # debounce: an immediate second event of the same kind records in
    # the ring but does not write a second file
    flight.note_event("planner-fallback", cause="again")
    assert len(list(tmp_path.iterdir())) == 1
    assert flight.RECORDER.counts()["planner-fallback"] == 2


def test_manual_dump_without_dir_is_none():
    assert flight.dump("debug-endpoint") is None


# --- end-to-end: one agent tick through a real ServiceServer --------------


def _tiny_packed():
    from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster

    C, K, S, R, W, A = 2, 3, 2, 2, 1, 2
    return PackedCluster(
        slot_req=np.zeros((C, K, R), np.float32),
        slot_valid=np.zeros((C, K), bool),
        slot_tol=np.zeros((C, K, W), np.uint32),
        slot_aff=np.zeros((C, K, A), np.uint32),
        cand_valid=np.ones(C, bool),
        spot_free=np.ones((S, R), np.float32),
        spot_count=np.zeros(S, np.int32),
        spot_max_pods=np.full(S, 10, np.int32),
        spot_taints=np.zeros((S, W), np.uint32),
        spot_ok=np.ones(S, bool),
        spot_aff=np.zeros((S, A), np.uint32),
    )


def _service(config=None, **kw):
    from k8s_spot_rescheduler_tpu.service.server import ServiceServer
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    cfg = config or ReschedulerConfig(solver="numpy")
    srv = ServiceServer(cfg, "127.0.0.1:0", batch_window_s=0.0, **kw)
    srv.start_background()
    return srv


def test_trace_id_round_trips_the_wire():
    """The tentpole acceptance at unit scale: the request's trace ID
    keys the server-side spans, and the reply returns them so one tree
    answers queue-or-solve-or-wire."""
    from k8s_spot_rescheduler_tpu.service import wire

    srv = _service()
    try:
        with tracing.tick_trace() as trace:
            body = wire.encode_plan_request(
                "t-0", _tiny_packed(), trace_id=trace.trace_id
            )
            req = urllib.request.Request(
                f"http://{srv.address}/v2/plan", data=body, method="POST",
                headers={"Content-Type": "application/octet-stream"},
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                reply = wire.decode_plan_reply(resp.read())
        names = [s[0] for s in reply.spans]
        assert names == [
            "service.admit", "service.decode", "service.queue-wait",
            "service.batch", "service.solve", "service.encode",
        ]
        # keyed server-side by the agent's trace id
        recent = srv.recent_request_traces()
        assert recent[-1]["trace_id"] == trace.trace_id
    finally:
        srv.close()


def test_debug_endpoints_gated_off_by_default():
    srv = _service()
    try:
        for path in ("/debug/trace", "/debug/flight"):
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://{srv.address}{path}", timeout=10
                )
            assert exc.value.code == 404
    finally:
        srv.close()


def test_debug_endpoints_serve_when_enabled(tmp_path):
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    cfg = ReschedulerConfig(
        solver="numpy", debug_endpoints=True,
        flight_dump_dir=str(tmp_path),
    )
    srv = _service(config=cfg)
    try:
        with tracing.tick_trace() as trace:
            with tracing.span("observe"):
                pass
        flight.record_tick(trace.to_dict())
        with urllib.request.urlopen(
            f"http://{srv.address}/debug/trace", timeout=10
        ) as resp:
            out = json.loads(resp.read())
        assert out["last_tick"]["trace"]["trace_id"] == trace.trace_id
        with urllib.request.urlopen(
            f"http://{srv.address}/debug/flight?dump=1", timeout=10
        ) as resp:
            out = json.loads(resp.read())
        assert out["ring_ticks"] == 1
        assert out["dumped"] and json.loads(
            open(out["dumped"]).read()
        )["reason"] == "debug-endpoint"
    finally:
        srv.close()
