"""Adversarial quality suite (VERDICT round-2 task 2).

The north-star quality claim (BASELINE.md: free >=95% as many on-demand
nodes as an ILP oracle) must survive contention: high spot utilization,
taints, selector-pinned pools — the regime where one-pass greedy
(first-fit, the reference's rescheduler.go:334-370 semantics, or
best-fit) demonstrably loses drains. These tests pin:

- the contended configs DO discriminate: pure first-fit achieves < 0.95
  of the oracle;
- the shipped solver stack (first-fit ∪ best-fit ∪ local-search repair,
  solver/repair.py) recovers to >= 0.95 on the same clusters;
- the LP/Hall relaxation (bench/quality.lp_upper_bound) is a true upper
  bound on the ILP at small scale (where both are computable) and scales
  to config-2-size packs;
- planner placement hints route evicted pods by the drain plan's proof.
"""

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.bench.quality import (
    drain_to_exhaustion,
    ilp_max_drains,
    lp_upper_bound,
    pack_quality,
)
from k8s_spot_rescheduler_tpu.io.synthetic import (
    QUALITY_CONFIGS,
    AffinitySpec,
    ContendedSpec,
    SyntheticSpec,
    generate_quality_cluster,
)
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

SMALL = ContendedSpec("quality-contended-test", n_groups=6)


def _exhaust(spec, seed, **cfg_kwargs):
    cfg = ReschedulerConfig(
        solver="numpy", resources=spec.resources, **cfg_kwargs
    )
    client = generate_quality_cluster(spec, seed, reschedule_evicted=True)
    return drain_to_exhaustion(client, cfg)


@pytest.mark.parametrize("seed", [0, 1])
def test_contended_discriminates_and_repair_recovers(seed):
    packed = pack_quality(SMALL, seed)
    ilp = ilp_max_drains(packed)
    assert ilp and ilp > 0
    ffd = _exhaust(SMALL, seed, fallback_best_fit=False, repair_rounds=0)
    shipped = _exhaust(SMALL, seed)
    assert ffd / ilp < 0.95, "config no longer stresses pure first-fit"
    assert shipped / ilp >= 0.95, "shipped solver lost the contended regime"


def test_best_fit_alone_insufficient_on_contended():
    # the swap pools are built so best-fit misroutes exactly like
    # first-fit — only the repair phase recovers them
    packed = pack_quality(SMALL, 0)
    ilp = ilp_max_drains(packed)
    bf_only = _exhaust(SMALL, 0, repair_rounds=0)
    assert bf_only / ilp < 0.95


@pytest.mark.parametrize(
    "spec,seed",
    [(SMALL, 0), (SMALL, 3), (SyntheticSpec("q", 8, 8, 120), 0)],
)
def test_lp_bound_dominates_ilp_small_scale(spec, seed):
    packed = pack_quality(spec, seed)
    ilp = ilp_max_drains(packed)
    lp = lp_upper_bound(packed)
    assert lp is not None and ilp is not None
    assert lp >= ilp


def test_lp_bound_scales_to_config2():
    from bench import build_problem

    packed = build_problem(2, 0)[0]
    lp = lp_upper_bound(packed)
    assert lp is not None
    assert 0 <= lp <= int(np.asarray(packed.cand_valid).sum())


def test_shipped_configs_registered():
    assert {
        "balanced", "contended", "contended-zipf", "affinity", "interlock"
    } <= set(QUALITY_CONFIGS)


# --- anti-affinity contention (round 4, VERDICT r3 #3) ---------------------

AFF_SMALL = AffinitySpec("quality-affinity-test", n_groups=6)
ILK_SMALL = AffinitySpec("quality-interlock-test", n_groups=6,
                         aswap_frac=0.0, interlock_frac=1 / 3)
CH3_SMALL = AffinitySpec("quality-chain3-test", n_groups=6,
                         aswap_frac=0.0, chain3_frac=1 / 3)


@pytest.mark.parametrize("seed", [0, 1])
def test_affinity_discriminates_and_shipped_recovers(seed):
    """The aswap pools: greedy loses BECAUSE of required anti-affinity
    (the group-mate burns the only eligible node); exact affinity
    ejection (solver/repair.py round 4) relocates it and recovers every
    drain the affinity-aware ILP finds."""
    packed = pack_quality(AFF_SMALL, seed)
    ilp = ilp_max_drains(packed)
    assert ilp and ilp > 0
    ffd = _exhaust(AFF_SMALL, seed, fallback_best_fit=False, repair_rounds=0)
    shipped = _exhaust(AFF_SMALL, seed)
    assert ffd / ilp < 0.95, "config no longer stresses greedy via affinity"
    assert shipped / ilp >= 0.95, "affinity contention regressed"


@pytest.mark.parametrize("seed", [0, 1])
def test_interlock_closed_by_depth2_chain(seed):
    """The two-pod interlock — depth-1's published boundary in early
    round 4 (shipped 0.750) — is CLOSED by the depth-2 chained
    relocation (p→s_q, q→s_r, r→s3): shipped now matches the ILP, and
    the config graduated into the headline quality metric."""
    packed = pack_quality(ILK_SMALL, seed)
    ilp = ilp_max_drains(packed)
    assert ilp and ilp > 0
    ffd = _exhaust(ILK_SMALL, seed, fallback_best_fit=False, repair_rounds=0)
    shipped = _exhaust(ILK_SMALL, seed)
    assert ffd < ilp, "config no longer stresses greedy"
    assert shipped == ilp, "depth-2 chain regressed on the interlock"


def _spread_small():
    from k8s_spot_rescheduler_tpu.io.synthetic import SpreadQualitySpec

    return SpreadQualitySpec("quality-spread-test", n_groups=6)


@pytest.mark.parametrize("seed", [0, 1])
def test_spread_discriminates_and_shipped_recovers(seed):
    """Round 5 (VERDICT r4 #3): the spread pools — greedy loses a drain
    BECAUSE of maxSkew (the filler burns the only skew-admissible node;
    both first-fit and best-fit tie into it), and the repair phase
    recovers every drain the spread-aware ILP finds via a spread-driven
    relocation."""
    spec = _spread_small()
    packed = pack_quality(spec, seed)
    ilp = ilp_max_drains(packed)
    assert ilp and ilp > 0
    ffd = _exhaust(spec, seed, fallback_best_fit=False, repair_rounds=0)
    shipped = _exhaust(spec, seed)
    assert ffd / ilp < 0.95, "config no longer stresses greedy via spread"
    assert shipped / ilp >= 0.95, "spread contention regressed"


def test_spread_loss_is_caused_by_the_constraint():
    """Ablation: strip the carriers' spread constraints and pure greedy
    drains the whole config — proving the quality loss above is caused
    by maxSkew, not by capacity shapes."""
    import dataclasses as _dc

    from k8s_spot_rescheduler_tpu.bench.quality import drain_to_exhaustion

    spec = _spread_small()
    client = generate_quality_cluster(spec, 0, reschedule_evicted=True)
    for pod in list(client.pods.values()):
        if pod.spread_constraints:
            # re-add through the public API (upsert keeps every index
            # consistent)
            client.add_pod(_dc.replace(pod, spread_constraints=()))
    cfg = ReschedulerConfig(
        solver="numpy", fallback_best_fit=False, repair_rounds=0,
        resources=spec.resources,
    )
    assert drain_to_exhaustion(client, cfg) == 6


@pytest.mark.parametrize("seed", [0, 1])
def test_chain3_is_repairs_published_boundary(seed):
    """Three-link chains: the only unlocker's re-placement needs TWO
    chained ejections — beyond the depth-2 search at ANY round count.
    The ILP (simultaneous) drains them; shipped < 1.000 by
    construction. Published in docs/RESULTS.md; each added depth
    multiplies the per-round election cost, and no organic config has
    produced one — so the boundary is published, not chased."""
    packed = pack_quality(CH3_SMALL, seed)
    ilp = ilp_max_drains(packed)
    assert ilp and ilp > 0
    shipped = _exhaust(CH3_SMALL, seed)
    more_rounds = _exhaust(CH3_SMALL, seed, repair_rounds=64)
    assert shipped < ilp, "chain3 no longer defeats depth-2 repair"
    assert more_rounds == shipped, "extra rounds cannot close a depth-3 gap"
    # every non-chain pool still drains
    n_chain = sum(
        1 for p in generate_quality_cluster(CH3_SMALL, seed).pods.values()
        if p.name.startswith("ch-c-")
    )
    assert shipped == ilp - n_chain


def test_ilp_pairwise_affinity_constraint():
    """Two moved group-mates may not share a spot node: with ONE spot
    node (room for both), the affinity-aware ILP must report 0 drains;
    dropping the members' affinity makes it 1."""
    from tests.fixtures import (
        ON_DEMAND_LABELS,
        SPOT_LABELS,
        make_node,
        make_pod,
        pack_fake,
    )
    from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
    from k8s_spot_rescheduler_tpu.utils.clock import FakeClock

    def cluster(with_affinity):
        fc = FakeCluster(FakeClock())
        fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
        fc.add_node(make_node("spot-1", SPOT_LABELS))
        kw = (
            dict(labels={"app": "web"}, anti_affinity_match={"app": "web"})
            if with_affinity
            else {}
        )
        fc.add_pod(make_pod("m1", 300, "od-1", **kw))
        fc.add_pod(make_pod("m2", 200, "od-1", **kw))
        return pack_fake(fc)[0]

    assert ilp_max_drains(cluster(with_affinity=False)) == 1
    assert ilp_max_drains(cluster(with_affinity=True)) == 0


def test_ilp_static_resident_affinity():
    """A group-mate RESIDENT on the only spot node statically excludes
    the mover in the ILP."""
    from tests.fixtures import (
        ON_DEMAND_LABELS,
        SPOT_LABELS,
        make_node,
        make_pod,
        pack_fake,
    )
    from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
    from k8s_spot_rescheduler_tpu.utils.clock import FakeClock

    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_pod(make_pod("res", 100, "spot-1", labels={"app": "web"}))
    fc.add_pod(make_pod("mover", 300, "od-1", labels={"app": "web"},
                        anti_affinity_match={"app": "web"}))
    assert ilp_max_drains(pack_fake(fc)[0]) == 0


def test_placement_hints_route_by_plan():
    """A hinted eviction lands on the plan's node even when first-fit
    dict order would strand a later pod."""
    client = generate_quality_cluster(SMALL, 0, reschedule_evicted=True)
    swap_pods = [p for p in client.pods.values() if p.name.startswith("tol-")]
    assert swap_pods
    pod = swap_pods[0]
    g = pod.node_selector["pool"]
    target = f"spot-z-{g[1:]}"
    client.placement_hints[pod.uid] = target
    client.evict_pod(pod, 0)
    client.clock.advance(5.0)
    moved = client.pods[pod.uid]
    assert moved.node_name == target


def test_hint_ignored_when_inadmissible():
    """A stale/invalid hint falls back to the scheduler scan."""
    client = generate_quality_cluster(SMALL, 0, reschedule_evicted=True)
    intol = [p for p in client.pods.values() if p.name.startswith("intol-")][0]
    g = intol.node_selector["pool"]
    client.placement_hints[intol.uid] = f"spot-z-{g[1:]}"  # tainted: refused
    client.evict_pod(intol, 0)
    client.clock.advance(5.0)
    live = client.pods.get(intol.uid)
    if live is not None:
        assert live.node_name != f"spot-z-{g[1:]}"
